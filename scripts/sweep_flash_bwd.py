"""Sweep pallas flash-attention BACKWARD block sizes on the real chip
(VERDICT r3 item 1 / r4 item 2: the forward was swept in round 3; the
backward keeps the forward's blocks until this records a winner). Times
jax.grad through the kernel with K iterations inside one jitted scan so
tunnel dispatch amortises.

Wedge-tolerant (the axon endpoint can hang indefinitely): every config runs
in a fresh subprocess with a hard timeout, and results stream to
scripts/flash_bwd_sweep_results.json after each config — a wedge mid-sweep
keeps everything measured so far.

Usage: python scripts/sweep_flash_bwd.py
"""

import itertools
import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH, SEQ, HEADS, HD = 4, 2048, 32, 128
K = 8
RESULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "flash_bwd_sweep_results.json")
CONFIG_TIMEOUT_S = 240.0


def bwd_time(block_overrides):
    """fwd+bwd time per call with the given dkv/dq block sizes (ms)."""
    import jax
    import jax.numpy as jnp

    from galvatron_tpu.ops import attention as A
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    orig = A._flash_block_sizes

    def patched(sq, sk):
        bq = A._flash_divisor(sq, 1024)
        bk = A._flash_divisor(sk, 512)
        kw = dict(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
        )
        kw.update({k: A._flash_divisor(sq if "q" in k.split("_")[1] else sk, v)
                   for k, v in block_overrides.items()})
        return BlockSizes(**kw)

    A._flash_block_sizes = patched
    try:
        q = jax.random.normal(jax.random.PRNGKey(2), (BATCH, SEQ, HEADS, HD), jnp.bfloat16)

        def attn_loss(c):
            return jnp.mean(A.core_attention(c, c, c, causal=True).astype(jnp.float32) ** 2)

        @jax.jit
        def run(c):
            def body(cc, _):
                return cc - 1e-6 * jax.grad(attn_loss)(cc), ()

            out, _ = jax.lax.scan(body, c, None, length=K)
            return out

        def sync(x):
            return float(jnp.sum(x.astype(jnp.float32)))

        sync(run(q))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            sync(run(q))
            ts.append(time.perf_counter() - t0)
        return float(np.min(ts)) / K * 1e3
    finally:
        A._flash_block_sizes = orig


def _grid():
    configs = [("base_1024_512", {})]
    for bq, bk in itertools.product([256, 512, 1024], [256, 512, 1024]):
        if bq == 1024 and bk == 512:
            continue
        configs.append(("q%d_k%d" % (bq, bk), {
            "block_q_major_dkv": bq, "block_q_dkv": bq,
            "block_k_major_dkv": bk, "block_k_dkv": bk,
            "block_q_dq": bq, "block_k_major_dq": bk, "block_k_dq": bk,
        }))
    return configs


def main():
    if os.environ.get("GALVATRON_SWEEP_CONFIG"):
        name = os.environ["GALVATRON_SWEEP_CONFIG"]
        overrides = dict(_grid())[name]
        print(json.dumps({"name": name, "ms": bwd_time(overrides)}))
        return

    results = {}
    if os.path.exists(RESULTS_PATH):
        try:
            results = json.load(open(RESULTS_PATH)).get("results", {})
            print("resuming; already have %d results" % len(results), flush=True)
        except (json.JSONDecodeError, OSError) as e:
            print("results file unreadable (%s); starting fresh" % e, flush=True)
    for name, _ in _grid():
        if name in results:
            continue
        env = dict(os.environ, GALVATRON_SWEEP_CONFIG=name)
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=CONFIG_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            print("%s: TIMEOUT (tunnel wedge?)" % name, flush=True)
            continue
        line = next((ln for ln in reversed(p.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        if p.returncode != 0 or line is None:
            print("%s: FAIL rc=%d %s" % (name, p.returncode,
                                         (p.stderr or "").strip()[-120:]), flush=True)
            continue
        results[name] = json.loads(line)["ms"]
        print("%s: %.2f ms" % (name, results[name]), flush=True)
        best = min(results, key=results.get)
        # atomic write: a kill mid-dump must not corrupt the resume file
        tmp = RESULTS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"shapes": dict(batch=BATCH, seq=SEQ, heads=HEADS, hd=HD),
                       "steps_per_call": K, "results": results, "best": best},
                      f, indent=1)
        os.replace(tmp, RESULTS_PATH)
    if results:
        best = min(results, key=results.get)
        print("BEST: %s = %.2f ms (baseline %s)"
              % (best, results[best], results.get("base_1024_512")))
    else:
        print("no results — tunnel down for every config?")


if __name__ == "__main__":
    main()
