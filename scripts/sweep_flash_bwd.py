"""Sweep pallas flash-attention BACKWARD block sizes on the real chip
(VERDICT r3 item 1: the forward was swept in round 3; the backward kept the
forward's blocks untuned). Times jax.grad through the kernel with K
iterations inside one jitted scan so tunnel dispatch amortises.

Usage: python scripts/sweep_flash_bwd.py
"""

import itertools
import time

import numpy as np

import jax
import jax.numpy as jnp

from galvatron_tpu.ops import attention as A

BATCH, SEQ, HEADS, HD = 4, 2048, 32, 128
K = 8


def timed(fn, *args, iters=3):
    def sync(x):
        return float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))

    sync(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.min(ts))


def bwd_time(block_overrides):
    """fwd+bwd time per call with the given dkv/dq block sizes (ms)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    orig = A._flash_block_sizes

    def patched(sq, sk):
        bq = A._flash_divisor(sq, 1024)
        bk = A._flash_divisor(sk, 512)
        kw = dict(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
        )
        kw.update({k: A._flash_divisor(sq if "q" in k.split("_")[1] else sk, v)
                   for k, v in block_overrides.items()})
        return BlockSizes(**kw)

    A._flash_block_sizes = patched
    try:
        q = jax.random.normal(jax.random.PRNGKey(2), (BATCH, SEQ, HEADS, HD), jnp.bfloat16)

        def attn_loss(c):
            return jnp.mean(A.core_attention(c, c, c, causal=True).astype(jnp.float32) ** 2)

        @jax.jit
        def run(c):
            def body(cc, _):
                return cc - 1e-6 * jax.grad(attn_loss)(cc), ()
            out, _ = jax.lax.scan(body, c, None, length=K)
            return out

        return timed(run, q) / K * 1e3
    finally:
        A._flash_block_sizes = orig


def main():
    print("device:", jax.devices()[0].device_kind, flush=True)
    base = bwd_time({})
    print("baseline (dkv/dq = fwd 1024q/512k): %.2f ms" % base, flush=True)
    results = {"base_1024_512": base}
    grid_q = [256, 512, 1024]
    grid_k = [256, 512, 1024]
    for bq, bk in itertools.product(grid_q, grid_k):
        if bq == 1024 and bk == 512:
            continue
        ov = {
            "block_q_major_dkv": bq, "block_q_dkv": bq,
            "block_k_major_dkv": bk, "block_k_dkv": bk,
            "block_q_dq": bq, "block_k_major_dq": bk, "block_k_dq": bk,
        }
        try:
            t = bwd_time(ov)
        except Exception as e:
            print("dkv/dq q%d k%d: FAIL %s" % (bq, bk, str(e)[:80]), flush=True)
            continue
        results["q%d_k%d" % (bq, bk)] = t
        print("dkv/dq q%d k%d: %.2f ms" % (bq, bk, t), flush=True)
    best = min(results, key=results.get)
    print("BEST: %s = %.2f ms (baseline %.2f)" % (best, results[best], base))


if __name__ == "__main__":
    main()
