"""Sweep pallas flash-attention BACKWARD block sizes on the real chip
(VERDICT r3 item 1 / r4 item 2: the forward was swept in round 3; the
backward keeps the forward's blocks until this records a winner). Times
jax.grad through the kernel with K iterations inside one jitted scan so
tunnel dispatch amortises.

Wedge-tolerant (the axon endpoint can hang indefinitely): every config runs
in a fresh subprocess with a hard timeout, and results stream to
scripts/flash_bwd_sweep_results.json after each config — a wedge mid-sweep
keeps everything measured so far.

Usage: python scripts/sweep_flash_bwd.py
"""

import itertools
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from _bench_util import (  # noqa: E402
    apply_jax_platforms_override,
    child_pythonpath,
    interpret_ctx_factory,
    run_isolated,
)

SMOKE = bool(os.environ.get("GALVATRON_SWEEP_SMOKE"))
BATCH, SEQ, HEADS, HD = (1, 256, 2, 128) if SMOKE else (4, 2048, 32, 128)
K = 1 if SMOKE else 8
RESULTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "flash_bwd_sweep_results%s.json" % ("_smoke" if SMOKE else ""),
)
CONFIG_TIMEOUT_S = 240.0


def bwd_time(block_overrides):
    """fwd+bwd time per call with the given dkv/dq block sizes (ms)."""
    import jax
    import jax.numpy as jnp

    from galvatron_tpu.ops import attention as A
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    orig = A._flash_block_sizes

    def patched(sq, sk):
        bq = A._flash_divisor(sq, 1024)
        bk = A._flash_divisor(sk, 512)
        kw = dict(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
        )
        kw.update({k: A._flash_divisor(sq if "q" in k.split("_")[1] else sk, v)
                   for k, v in block_overrides.items()})
        return BlockSizes(**kw)

    # native on TPU; interpret mode for the off-chip smoke path
    ctx = interpret_ctx_factory()()

    A._flash_block_sizes = patched
    try:
        q = jax.random.normal(jax.random.PRNGKey(2), (BATCH, SEQ, HEADS, HD), jnp.bfloat16)

        def attn_loss(c):
            return jnp.mean(A.core_attention(c, c, c, causal=True).astype(jnp.float32) ** 2)

        @jax.jit
        def run(c):
            def body(cc, _):
                return cc - 1e-6 * jax.grad(attn_loss)(cc), ()

            out, _ = jax.lax.scan(body, c, None, length=K)
            return out

        def sync(x):
            return float(jnp.sum(x.astype(jnp.float32)))

        with ctx:
            sync(run(q))
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                sync(run(q))
                ts.append(time.perf_counter() - t0)
        return float(np.min(ts)) / K * 1e3
    finally:
        A._flash_block_sizes = orig


def _grid():
    def ov(bq, bk):
        return {
            "block_q_major_dkv": bq, "block_q_dkv": bq,
            "block_k_major_dkv": bk, "block_k_dkv": bk,
            "block_q_dq": bq, "block_k_major_dq": bk, "block_k_dq": bk,
        }

    configs = [("base_1024_512", {})]
    if SMOKE:
        # machinery check only: one override config (interpret mode is slow)
        return configs + [("q256_k256", ov(256, 256))]
    for bq, bk in itertools.product([256, 512, 1024], [256, 512, 1024]):
        if bq == 1024 and bk == 512:
            continue
        configs.append(("q%d_k%d" % (bq, bk), ov(bq, bk)))
    return configs


def main():
    if os.environ.get("GALVATRON_SWEEP_CONFIG"):
        apply_jax_platforms_override()
        name = os.environ["GALVATRON_SWEEP_CONFIG"]
        overrides = dict(_grid())[name]
        ms = bwd_time(overrides)
        import jax

        print(json.dumps({"name": name, "ms": ms,
                          "device": jax.devices()[0].device_kind}))
        return

    context = {"shapes": dict(batch=BATCH, seq=SEQ, heads=HEADS, hd=HD),
               "steps_per_call": K}
    results = {}
    if os.path.exists(RESULTS_PATH):
        try:
            prev = json.load(open(RESULTS_PATH))
            # only resume measurements taken under the SAME shapes/K: stale
            # entries from other conditions must not compete for "best"
            if all(prev.get(k) == v for k, v in context.items()):
                results = prev.get("results", {})
                print("resuming; already have %d results" % len(results), flush=True)
            else:
                print("results file is from different shapes/K; starting fresh",
                      flush=True)
        except (json.JSONDecodeError, OSError) as e:
            print("results file unreadable (%s); starting fresh" % e, flush=True)
    for name, _ in _grid():
        if name in results:
            continue
        env = dict(os.environ, GALVATRON_SWEEP_CONFIG=name)
        env["PYTHONPATH"] = child_pythonpath(env, _REPO)
        # shared wedge-tolerant harness: own process group (killed as a
        # unit on timeout), JSON kept even if the child died in teardown
        payload, rc, err_tail = run_isolated(
            [sys.executable, os.path.abspath(__file__)], env, CONFIG_TIMEOUT_S,
        )
        if payload is None:
            if rc is None:
                print("%s: TIMEOUT (tunnel wedge?)" % name, flush=True)
            else:
                print("%s: FAIL rc=%s %s" % (name, rc, err_tail[-120:]), flush=True)
            continue
        results[name] = payload["ms"]
        print("%s: %.2f ms (device %s)" % (name, results[name],
                                           payload.get("device", "?")), flush=True)
        best = min(results, key=results.get)
        # atomic write: a kill mid-dump must not corrupt the resume file
        tmp = RESULTS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dict(context, device=payload.get("device"),
                           results=results, best=best), f, indent=1)
        os.replace(tmp, RESULTS_PATH)
    if results:
        best = min(results, key=results.get)
        print("BEST: %s = %.2f ms (baseline %s)"
              % (best, results[best], results.get("base_1024_512")))
    else:
        print("no results — tunnel down for every config?")


if __name__ == "__main__":
    main()
