"""Measure padded-mask flash vs unmasked flash vs the old XLA fallback on the
real chip (VERDICT r4 item 3 acceptance: masked seq-2048 within ~1.2x of
unmasked flash). Run on TPU: python scripts/bench_masked_flash.py"""

import time

import numpy as np

import jax
import jax.numpy as jnp

from galvatron_tpu.ops.attention import (
    _pallas_flash,
    _xla_attention,
    padding_bias_to_segment_ids,
)

B, S, NH, HD = 8, 2048, 32, 128  # bench.py layer shapes


def timed(fn, *args, iters=10):
    out = fn(*args)
    float(jnp.sum(out[0] if isinstance(out, tuple) else out).astype(jnp.float32))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        float(jnp.sum(out[0] if isinstance(out, tuple) else out).astype(jnp.float32))
        ts.append(time.perf_counter() - t0)
    return float(np.min(ts)) * 1e3


def main():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, NH, HD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, NH, HD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, NH, HD), jnp.bfloat16)
    mask = np.ones((B, S), np.float32)
    mask[:, -S // 4:] = 0.0  # 25% padding, BERT-style suffix
    bias = jnp.asarray((1.0 - mask)[:, None, None, :] * -1e9)
    seg = padding_bias_to_segment_ids(bias)
    sc = HD ** -0.5

    flash = jax.jit(lambda q, k, v: _pallas_flash(q, k, v, causal=False, sm_scale=sc))
    flash_seg = jax.jit(lambda q, k, v: _pallas_flash(
        q, k, v, causal=False, sm_scale=sc, segment_ids=seg))
    xla = jax.jit(lambda q, k, v: _xla_attention(
        q, k, v, causal=False, sm_scale=sc, bias=bias))

    t_flash = timed(flash, q, k, v)
    t_seg = timed(flash_seg, q, k, v)
    t_xla = timed(xla, q, k, v)
    print("unmasked flash     %.3f ms" % t_flash)
    print("masked seg flash   %.3f ms (%.2fx unmasked)" % (t_seg, t_seg / t_flash))
    print("masked XLA (old)   %.3f ms (%.2fx unmasked)" % (t_xla, t_xla / t_flash))


if __name__ == "__main__":
    main()
