#!/usr/bin/env bash
# CI gate: every static analyzer over the shipped package and the shipped
# strategy corpus — source AST (GLC), strategy JSON (GLS), checkpoint audit,
# traced-program lint (GLT: the tiny CPU gpt's train step abstract-traced
# under every valid strategy fixture, no compiles) and the jax-workaround
# inventory (WA: a retirable workaround surfaces as a warning here first).
# Machine-readable output, non-zero exit on any error diagnostic. Run from
# anywhere; well under a minute on a laptop CPU.
#
#   scripts/lint.sh              # human output
#   scripts/lint.sh --json       # one JSON report (schema: analysis/diagnostics)
#
# ALLOWLIST: accepted exceptions go here as extra --rules filters or
# `# galv-lint: ignore[CODE]` pragmas at the offending line (grep for the
# pragma to audit them). Currently the package and corpus are fully clean:
# no exceptions are allowed.
set -euo pipefail
cd "$(dirname "$0")/.."

# Telemetry schema gate: the report CLI must analyze the golden event stream
# cleanly (exit-code contract shared with the GLS/GLC framework: 0 clean,
# 1 schema violations, 2 usage/IO). --json keeps the output machine-checked.
env JAX_PLATFORMS=cpu python -m galvatron_tpu.cli report --json \
    tests/obs/fixtures/golden_telemetry.jsonl > /dev/null

exec env JAX_PLATFORMS=cpu python -m galvatron_tpu.cli lint \
    --code \
    --world_size 8 \
    --ckpt tests/analysis/fixtures/ckpt_valid \
    --trace --compat \
    --model_type gpt --hidden_size 64 --num_heads 4 \
    --seq_length 64 --vocab_size 128 \
    tests/analysis/fixtures/valid/*.json \
    "$@"
