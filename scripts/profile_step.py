"""Step-time breakdown on the real TPU chip (VERDICT r3 item 1).

Times the bench.py train-step's components so the MFU work targets the real
bottleneck. The axon tunnel adds ~70 ms dispatch latency to EVERY synced
call, so each measurement runs the op K times inside one jit (lax.scan) and
DIFFERENCES two iteration counts (K2 - K1): the dispatch cancels and the
per-iteration device time remains (same differencing idea as bench.py's
layer-count differencing; reference model_profiler.py:328-372).

Usage: python scripts/profile_step.py [--quick]
"""

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

from galvatron_tpu.models import base as M

HIDDEN, FFN, HEADS, SEQ = 4096, 11008, 32, 2048
LAYERS, BATCH = 2, 4
K1, K2 = 4, 8


def cfg_():
    return M.TransformerConfig(
        hidden_size=HIDDEN, num_heads=HEADS, num_layers=LAYERS,
        ffn_hidden=FFN, vocab_size=256, max_seq_len=SEQ,
        norm_type="rmsnorm", activation="swiglu", position_type="rope",
        qkv_bias=False, mlp_bias=False, out_bias=False,
        compute_dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )


def sync(x):
    return float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))


def timeit(fn, *args, iters=4, warmup=2):
    for _ in range(warmup):
        sync(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.min(ts))


def scanned(body, carry_init, k):
    """jit a K-iteration scan of body so dispatch amortises; body must return
    a same-shaped carry that DEPENDS on the previous one (no dead-code elim)."""

    @jax.jit
    def run(c):
        out, _ = jax.lax.scan(lambda cc, _: (body(cc), ()), c, None, length=k)
        return out

    return lambda: run(carry_init)


def diffed(body, carry_init, iters=4, label=""):
    """Difference K2 vs K1 iteration scans; print the result immediately so a
    tunnel transport failure later in the run does not lose earlier numbers."""
    try:
        t1 = timeit(scanned(body, carry_init, K1), iters=iters)
        t2 = timeit(scanned(body, carry_init, K2), iters=iters)
    except Exception as e:  # axon remote_compile can drop the connection
        print("MEASURE-FAIL %-10s: %s" % (label, str(e)[:120]), flush=True)
        return float("nan")
    t = (t2 - t1) / (K2 - K1)
    if label:
        print("measured %-10s: %8.2f ms" % (label, t * 1e3), flush=True)
    return t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    iters = 2 if args.quick else 4

    cfg = cfg_()
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, LAYERS)]
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SEQ, HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(SEQ), (BATCH, SEQ))
    tx = optax.adam(1e-4)
    opt_state = tx.init(layers)

    def loss_fn(layers, x):
        y = x
        for lp in layers:
            y = M.layer_forward(lp, y, positions, cfg)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    # ---- full step, K iterations inside one jit (params/opt as scan carry)
    def step_body(carry):
        layers, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(layers, x)
        updates, opt_state = tx.update(grads, opt_state, layers)
        return optax.apply_updates(layers, updates), opt_state

    t_step = diffed(step_body, (layers, opt_state), iters=iters, label="step")

    # ---- forward only (carry = x so iterations chain)
    def fwd_body(xx):
        y = xx
        for lp in layers:
            y = M.layer_forward(lp, y, positions, cfg)
        return 0.5 * xx + 0.5 * y

    t_fwd = diffed(fwd_body, x, iters=iters, label="fwd")

    # ---- forward + backward (carry = params, nudged by grads)
    def fb_body(ls):
        g = jax.grad(loss_fn)(ls, x)
        return jax.tree.map(lambda p, gg: p - 1e-6 * gg, ls, g)

    t_fb = diffed(fb_body, layers, iters=iters, label="fwd+bwd")

    # ---- adam update only
    grads = jax.jit(jax.grad(loss_fn))(layers, x)
    sync(grads)

    def adam_body(carry):
        ls, st = carry
        updates, st = tx.update(grads, st, ls)
        return optax.apply_updates(ls, updates), st

    t_adam = diffed(adam_body, (layers, opt_state), iters=iters, label="adam")

    # ---- attention isolated
    from galvatron_tpu.ops.attention import core_attention

    q = jax.random.normal(jax.random.PRNGKey(2), (BATCH, SEQ, HEADS, 128), jnp.bfloat16)

    def attn_f_body(c):
        return 0.5 * c + 0.5 * core_attention(c, c, c, causal=True)

    def attn_loss(c):
        return jnp.mean(core_attention(c, c, c, causal=True).astype(jnp.float32) ** 2)

    def attn_fb_body(c):
        return c - 1e-6 * jax.grad(attn_loss)(c)

    t_attn_f = diffed(attn_f_body, q, iters=iters, label="attn-fwd")
    t_attn_fb = diffed(attn_fb_body, q, iters=iters, label="attn-f+b")

    # ---- big matmul ceiling
    w1 = jax.random.normal(jax.random.PRNGKey(3), (HIDDEN, FFN), jnp.bfloat16)
    a = x.reshape(-1, HIDDEN)

    def mm_body(c):
        return 0.99 * c + 1e-6 * ((c @ w1) @ w1.T)

    t_mm = diffed(mm_body, a, iters=iters, label="mm-pair")
    mm_flops = 2 * 2 * a.shape[0] * HIDDEN * FFN

    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(layers))
    tokens = BATCH * SEQ
    flops_step = 6.0 * n_params * tokens + 12 * LAYERS * SEQ * HIDDEN * tokens * 0.5
    peak = 197e12
    attn_flops = 4 * BATCH * HEADS * SEQ * SEQ * 128 * 0.5
    print("device:", jax.devices()[0].device_kind)
    print("params: %.1fM  tokens/step: %d  (all times dispatch-free)" % (n_params / 1e6, tokens))
    print("full step : %7.2f ms   (MFU %.3f)" % (t_step * 1e3, flops_step / t_step / peak))
    print("fwd only  : %7.2f ms   (MFU %.3f)" % (t_fwd * 1e3, flops_step / 3 / t_fwd / peak))
    print("fwd+bwd   : %7.2f ms   (MFU %.3f)" % (t_fb * 1e3, flops_step / t_fb / peak))
    print("bwd alone : %7.2f ms   (ideal %.2f)" % ((t_fb - t_fwd) * 1e3, flops_step * 2 / 3 / peak * 1e3))
    print("adam only : %7.2f ms" % (t_adam * 1e3))
    print("attn fwd  : %7.2f ms   (%.0f%% of kernel peak)" % (t_attn_f * 1e3, 100 * attn_flops / t_attn_f / peak))
    print("attn f+b  : %7.2f ms   (%.0f%% of kernel peak)" % (t_attn_fb * 1e3, 100 * 3 * attn_flops / t_attn_fb / peak))
    print("attn bwd  : %7.2f ms   (ideal %.2f)" % ((t_attn_fb - t_attn_f) * 1e3, 2 * attn_flops / peak * 1e3))
    print("mm pair   : %7.2f ms   (%.0f%% peak)" % (t_mm * 1e3, 100 * mm_flops / t_mm / peak))


if __name__ == "__main__":
    main()
