"""Step-time breakdown on the real TPU chip (VERDICT r3 item 1).

Times the bench.py train-step's components separately so the MFU work targets
the real bottleneck. Methodology matches bench.py: differenced / min-of-round
timings; every measured call iterates the op K times inside one jit (lax.scan)
so the ~70 ms axon-tunnel dispatch latency amortises away.

Usage: python scripts/profile_step.py [--quick]
"""

import argparse
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
import optax

from galvatron_tpu.models import base as M

HIDDEN, FFN, HEADS, SEQ = 4096, 11008, 32, 2048
LAYERS, BATCH = 2, 4


def cfg_():
    return M.TransformerConfig(
        hidden_size=HIDDEN, num_heads=HEADS, num_layers=LAYERS,
        ffn_hidden=FFN, vocab_size=256, max_seq_len=SEQ,
        norm_type="rmsnorm", activation="swiglu", position_type="rope",
        qkv_bias=False, mlp_bias=False, out_bias=False,
        compute_dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )


def sync(x):
    return float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))


def timeit(fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        sync(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.min(ts))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    iters = 3 if args.quick else 6

    cfg = cfg_()
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, LAYERS)]
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SEQ, HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(SEQ), (BATCH, SEQ))
    tx = optax.adam(1e-4)
    opt_state = tx.init(layers)

    def loss_fn(layers, x):
        y = x
        for lp in layers:
            y = M.layer_forward(lp, y, positions, cfg)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    # ---- full step (donated) — the bench metric
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(layers, opt_state, x):
        loss, grads = jax.value_and_grad(loss_fn)(layers, x)
        updates, opt_state = tx.update(grads, opt_state, layers)
        layers = optax.apply_updates(layers, updates)
        return layers, opt_state, loss

    # time the full step WITHOUT donation-safe reuse issues: run pairs
    def run_step():
        nonlocal layers, opt_state
        layers, opt_state, loss = step(layers, opt_state, x)
        return loss

    for _ in range(2):
        sync(run_step())
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(run_step())
        ts.append(time.perf_counter() - t0)
    t_step = float(np.min(ts))

    # ---- forward only
    fwd = jax.jit(loss_fn)
    t_fwd = timeit(fwd, layers, x, iters=iters)

    # ---- forward + backward (no optimizer)
    grad = jax.jit(jax.value_and_grad(loss_fn))
    t_grad = timeit(lambda l, xx: grad(l, xx)[1], layers, x, iters=iters)

    # ---- optimizer only (fixed grads)
    grads = jax.jit(jax.grad(loss_fn))(layers, x)
    sync(grads)

    @jax.jit
    def adam_only(grads, opt_state, layers):
        updates, new_state = tx.update(grads, opt_state, layers)
        return optax.apply_updates(layers, updates), new_state

    t_adam = timeit(lambda g, s, l: adam_only(g, s, l)[0], grads, opt_state, layers, iters=iters)

    # ---- attention fwd+bwd isolated (scan K inner iters to amortise dispatch)
    K = 8
    q = jax.random.normal(jax.random.PRNGKey(2), (BATCH, SEQ, HEADS, 128), jnp.bfloat16)

    from galvatron_tpu.ops.attention import core_attention

    def attn_loss(q):
        return jnp.mean(core_attention(q, q, q, causal=True).astype(jnp.float32) ** 2)

    attn_grad = jax.grad(attn_loss)

    @jax.jit
    def attn_bwd_k(q):
        def body(c, _):
            g = attn_grad(c)
            return c + 1e-6 * g, ()
        out, _ = jax.lax.scan(body, q, None, length=K)
        return out

    @jax.jit
    def attn_fwd_k(q):
        def body(c, _):
            o = core_attention(c, c, c, causal=True)
            return c + 1e-6 * o, ()
        out, _ = jax.lax.scan(body, q, None, length=K)
        return out

    t_attn_f = timeit(attn_fwd_k, q, iters=iters) / K
    t_attn_fb = timeit(attn_bwd_k, q, iters=iters) / K

    # ---- big matmul ceiling: one (B*S, H) x (H, FFN) matmul chain, K iters
    w1 = jax.random.normal(jax.random.PRNGKey(3), (HIDDEN, FFN), jnp.bfloat16)

    @jax.jit
    def mm_k(a, w):
        def body(c, _):
            y = c @ w
            return c + 1e-6 * (y @ w.T), ()
        out, _ = jax.lax.scan(body, a, None, length=K)
        return out

    a = x.reshape(-1, HIDDEN)
    t_mm = timeit(mm_k, a, w1, iters=iters) / K
    mm_flops = 2 * 2 * a.shape[0] * HIDDEN * FFN  # fwd+transpose matmuls

    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(layers))
    tokens = BATCH * SEQ
    flops_step = 6.0 * n_params * tokens + 12 * LAYERS * SEQ * HIDDEN * tokens * 0.5
    peak = 197e12
    print("device:", jax.devices()[0].device_kind)
    print("params: %.1fM  tokens/step: %d" % (n_params / 1e6, tokens))
    print("full step : %7.2f ms   (MFU %.3f)" % (t_step * 1e3, flops_step / t_step / peak))
    print("fwd only  : %7.2f ms   (MFU %.3f)" % (t_fwd * 1e3, flops_step / 3 / t_fwd / peak))
    print("fwd+bwd   : %7.2f ms   (MFU %.3f)" % (t_grad * 1e3, flops_step / t_grad / peak))
    print("adam only : %7.2f ms" % (t_adam * 1e3))
    print("residual (step - fwdbwd - adam): %7.2f ms" % ((t_step - t_grad - t_adam) * 1e3))
    attn_flops = 4 * BATCH * HEADS * SEQ * SEQ * 128 * 0.5  # causal qk+pv
    print("attn fwd  : %7.2f ms   (%.0f%% of kernel peak)" % (
        t_attn_f * 1e3, 100 * attn_flops / t_attn_f / peak))
    print("attn f+b  : %7.2f ms   (%.0f%% of kernel peak)" % (
        t_attn_fb * 1e3, 100 * 3 * attn_flops / t_attn_fb / peak))
    print("mm pair   : %7.2f ms   (%.0f%% peak)" % (t_mm * 1e3, 100 * mm_flops / t_mm / peak))


if __name__ == "__main__":
    main()
