"""Shared wedge-tolerant subprocess harness for the standalone benchmark
orchestrators (bench.py, scripts/sweep_flash_bwd.py).

The axon remote-compile endpoint can hang a child process indefinitely
(BENCH_r04 rc=124), so both orchestrators run every measurement in a fresh
subprocess and must agree on the recovery rules:

  - children run in their OWN process group and are SIGKILLed as a unit on
    timeout, so wedged tunnel helpers cannot squat the chip;
  - a child that printed its result JSON but died in tunnel teardown still
    counts as success;
  - an explicit non-axon JAX_PLATFORMS is honored via jax.config.update
    (the axon plugin pins jax_platforms at registration; the env var alone
    does not win);
  - off-TPU smoke runs execute pallas kernels in interpret mode.

stdlib-only on the orchestrator side: importing this module must never touch
jax (the whole point is that the parent cannot wedge)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from typing import Optional, Tuple


def extract_json(stdout: Optional[str]) -> Optional[dict]:
    """Last parseable {...} line of a child's stdout, else None."""
    for line in reversed((stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def run_isolated(argv, env, timeout_s: float,
                 on_spawn=None) -> Tuple[Optional[dict], Optional[int], str]:
    """Run `argv` in its own process group with a hard timeout.

    Returns (payload, returncode, stderr_tail): payload is the child's last
    JSON stdout line (accepted EVEN IF the child exited non-zero — flaky
    tunnel destructors must not discard a finished measurement); returncode
    is None on timeout (the whole process group is SIGKILLed). `on_spawn`
    receives the live Popen so a caller's watchdog can kill_group() it from
    a signal handler."""
    p = subprocess.Popen(
        argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True,
    )
    if on_spawn is not None:
        on_spawn(p)
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        kill_group(p)
        try:
            out, err = p.communicate(timeout=10.0)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        return extract_json(out), None, (err or "").strip()[-200:]
    return extract_json(out), p.returncode, (err or "").strip()[-200:]


def kill_group(p: subprocess.Popen) -> None:
    """SIGKILL a child and its whole process group (tunnel helpers included)."""
    if p.poll() is None:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            p.kill()


def _ancestor_pids() -> set:
    """This process's full ancestor pid chain via /proc (linux). The bench
    is routinely launched through wrapper shells/timeout whose own command
    lines contain the word "bench" — excluding only pid/ppid still flags
    the grandparent shell as a concurrent bench. Falls back to {self,
    parent} where /proc is unavailable."""
    pids = {str(os.getpid()), str(os.getppid())}
    pid = os.getpid()
    for _ in range(64):
        try:
            with open("/proc/%d/stat" % pid) as f:
                # field 4 (after the parenthesised, space-tolerant comm)
                pid = int(f.read().rsplit(")", 1)[-1].split()[1])
        except (OSError, ValueError, IndexError):
            break
        pids.add(str(pid))
        if pid <= 1:
            break
    return pids


def concurrent_bench_processes():
    """`pgrep -af bench` minus this process's ancestor chain: the timing
    discipline run before any section is measured. Another bench round (or
    a stray wedged measurement child) sharing the host corrupts every
    number, so the orchestrator records what it saw and the payload carries
    the hazard instead of shipping silently-noisy timings. Best-effort: no
    pgrep (or a hung one) yields an empty list, never an exception."""
    try:
        p = subprocess.run(["pgrep", "-af", "bench"], capture_output=True,
                           text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return []
    own = _ancestor_pids()
    hits = []
    for line in (p.stdout or "").strip().splitlines():
        parts = line.strip().split(None, 1)
        if not parts or parts[0] in own:
            continue
        hits.append(line.strip()[:200])
    return hits


def apply_jax_platforms_override() -> None:
    """In a measurement CHILD: honor an explicit non-axon JAX_PLATFORMS.
    Only jax.config.update outranks the axon plugin's pinned platforms."""
    jp = os.environ.get("JAX_PLATFORMS")
    if jp and "axon" not in jp:
        import jax

        jax.config.update("jax_platforms", jp)


def interpret_ctx_factory():
    """Context-manager factory for pallas kernels: native on TPU, interpret
    mode elsewhere (CPU smoke runs — timings meaningless, path exercised).
    Call once per timed region; generator-based contexts are single-use."""
    import contextlib

    import jax

    if jax.default_backend() in ("tpu", "axon"):
        return contextlib.nullcontext
    import jax.experimental.pallas.tpu as pltpu

    return pltpu.force_tpu_interpret_mode


def child_pythonpath(env: dict, repo_root: str) -> str:
    """PYTHONPATH for measurement children: the repo (so galvatron_tpu
    imports) plus /root/.axon_site (or the axon backend fails to register —
    see .claude/skills/verify/SKILL.md)."""
    extra = [repo_root, "/root/.axon_site", env.get("PYTHONPATH", "")]
    return ":".join(p for p in extra if p)


if sys.version_info < (3, 9):  # pragma: no cover
    raise RuntimeError("python >= 3.9 required")


# ===================================================================== gate
# MFU-regression gate (ROADMAP item 1): compare a bench payload against the
# most recent non-empty BENCH_r*.json so the perf trajectory cannot silently
# decay again (BENCH_r04/r05 shipped zero numbers and nobody noticed until
# re-anchor). stdlib-only: runs in the orchestrator.

def _get_path(d, dotted):
    for part in dotted.split("."):
        if not isinstance(d, dict) or part not in d:
            return None
        d = d[part]
    return d


# (dotted path under the payload, higher_is_better). The headline value is
# keyed by its metric name so a SMOKE payload never compares against a
# full-shape baseline.
GATE_METRICS = (
    ("extra.train_step.mfu", True),
    ("extra.train_step.tokens_per_sec_per_chip", True),
    ("extra.train_loop.dispatch_ahead.steps_per_s", True),
    # TP execution paths (ISSUE 8): the regression gate covers both the
    # GSPMD baseline and the decomposed overlapped path
    ("extra.tp_overlap.gspmd.step_ms", False),
    ("extra.tp_overlap.overlap.step_ms", False),
    # Quantized collectives (ISSUE 9): the gate pins both the fp32 baseline
    # and the int8 grad-sync step so neither path silently decays — and the
    # loss delta so quantization error cannot silently grow either
    ("extra.quant_comm.fp32.step_ms", False),
    ("extra.quant_comm.int8.step_ms", False),
    ("extra.quant_comm.loss_delta_int8", False),
    # Serving (ISSUE 11): the gate pins warm-path throughput for both the
    # gspmd baseline and the searched layout, plus the searched layout's
    # decode step and TTFT tail, so the inference engine cannot silently
    # decay between rounds
    ("extra.serve.gspmd.tokens_per_s_per_chip", True),
    ("extra.serve.searched.tokens_per_s_per_chip", True),
    ("extra.serve.searched.decode_step_ms", False),
    ("extra.serve.searched.ttft_ms_p99", False),
    # Silent-corruption sentinel (ISSUE 13): the gate pins all three
    # sentinel modes' step time — digest must stay within its <= 2%
    # budget and the vote's shard_map digest cannot silently bloat
    ("extra.sdc_overhead.off.step_ms", False),
    ("extra.sdc_overhead.digest.step_ms", False),
    ("extra.sdc_overhead.vote.step_ms", False),
    # Per-layer remat search (ISSUE 15): the gate pins all three remat
    # plans' step time — the searched-mixed plan must keep beating the
    # all-full plan it exists to improve on — and the searched plan's
    # compiled memory footprint so the mix cannot silently drift toward
    # holding everything resident
    ("extra.remat.none.step_ms", False),
    ("extra.remat.full.step_ms", False),
    ("extra.remat.searched.step_ms", False),
    ("extra.remat.searched.peak_mb", False),
    # Online autotuner (ISSUE 14): the gate pins throughput on both sides
    # of the mid-run hot-swap — the mis-specified start (detector + planner
    # riding along) and the converged post-swap strategy — so neither the
    # tuner's overhead nor the swapped-to layout can silently decay
    ("extra.autotune.misspecified.steps_per_s", True),
    ("extra.autotune.converged.steps_per_s", True),
)


def perf_metrics(payload):
    """name -> (value, higher_is_better) for every comparable number the
    payload carries. Absent/None entries are simply not in the dict, so
    absent-numbers rounds contribute nothing."""
    out = {}
    if isinstance(payload.get("value"), (int, float)) and payload.get("metric"):
        out["value[%s]" % payload["metric"]] = (float(payload["value"]), False)
    for path, higher in GATE_METRICS:
        v = _get_path(payload, path)
        if isinstance(v, (int, float)):
            out[path] = (float(v), higher)
    return out


def perf_regressions(current_payload, baseline_payload, tolerance=0.1):
    """Regression report lines, empty when every shared metric is within
    `tolerance` of the baseline (relative decay for higher-is-better
    metrics, relative growth for lower-is-better)."""
    cur = perf_metrics(current_payload or {})
    base = perf_metrics(baseline_payload or {})
    out = []
    for name in sorted(set(cur) & set(base)):
        c, higher = cur[name]
        b, _ = base[name]
        if b <= 0:
            continue
        if higher and c < b * (1.0 - tolerance):
            out.append("%s: %.6g -> %.6g (-%.1f%%, tolerance %.0f%%)"
                       % (name, b, c, (1.0 - c / b) * 100.0, tolerance * 100.0))
        elif not higher and c > b * (1.0 + tolerance):
            out.append("%s: %.6g -> %.6g (+%.1f%%, tolerance %.0f%%)"
                       % (name, b, c, (c / b - 1.0) * 100.0, tolerance * 100.0))
    return out


def load_latest_baseline(glob_pattern):
    """(path, payload) of the newest baseline round that actually carries
    numbers, else None. Accepts both the raw bench JSON-line shape and the
    perf driver's wrapper ({"n": round, "parsed": {...}}); rounds whose
    parsed payload is null or number-free (the wedged-tunnel rounds) are
    tolerated and skipped."""
    import glob as _glob

    candidates = []
    for path in _glob.glob(glob_pattern):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        payload = doc.get("parsed", doc) if isinstance(doc, dict) else None
        if not isinstance(payload, dict) or not perf_metrics(payload):
            continue
        order = doc.get("n") if isinstance(doc.get("n"), (int, float)) else None
        candidates.append(((order is None, order if order is not None else path), path, payload))
    if not candidates:
        return None
    candidates.sort(key=lambda t: t[0])
    _, path, payload = candidates[-1]
    return path, payload
