"""Shared wedge-tolerant subprocess harness for the standalone benchmark
orchestrators (bench.py, scripts/sweep_flash_bwd.py).

The axon remote-compile endpoint can hang a child process indefinitely
(BENCH_r04 rc=124), so both orchestrators run every measurement in a fresh
subprocess and must agree on the recovery rules:

  - children run in their OWN process group and are SIGKILLed as a unit on
    timeout, so wedged tunnel helpers cannot squat the chip;
  - a child that printed its result JSON but died in tunnel teardown still
    counts as success;
  - an explicit non-axon JAX_PLATFORMS is honored via jax.config.update
    (the axon plugin pins jax_platforms at registration; the env var alone
    does not win);
  - off-TPU smoke runs execute pallas kernels in interpret mode.

stdlib-only on the orchestrator side: importing this module must never touch
jax (the whole point is that the parent cannot wedge)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from typing import Optional, Tuple


def extract_json(stdout: Optional[str]) -> Optional[dict]:
    """Last parseable {...} line of a child's stdout, else None."""
    for line in reversed((stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def run_isolated(argv, env, timeout_s: float,
                 on_spawn=None) -> Tuple[Optional[dict], Optional[int], str]:
    """Run `argv` in its own process group with a hard timeout.

    Returns (payload, returncode, stderr_tail): payload is the child's last
    JSON stdout line (accepted EVEN IF the child exited non-zero — flaky
    tunnel destructors must not discard a finished measurement); returncode
    is None on timeout (the whole process group is SIGKILLed). `on_spawn`
    receives the live Popen so a caller's watchdog can kill_group() it from
    a signal handler."""
    p = subprocess.Popen(
        argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True,
    )
    if on_spawn is not None:
        on_spawn(p)
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        kill_group(p)
        try:
            out, err = p.communicate(timeout=10.0)
        except subprocess.TimeoutExpired:
            out, err = "", ""
        return extract_json(out), None, (err or "").strip()[-200:]
    return extract_json(out), p.returncode, (err or "").strip()[-200:]


def kill_group(p: subprocess.Popen) -> None:
    """SIGKILL a child and its whole process group (tunnel helpers included)."""
    if p.poll() is None:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            p.kill()


def apply_jax_platforms_override() -> None:
    """In a measurement CHILD: honor an explicit non-axon JAX_PLATFORMS.
    Only jax.config.update outranks the axon plugin's pinned platforms."""
    jp = os.environ.get("JAX_PLATFORMS")
    if jp and "axon" not in jp:
        import jax

        jax.config.update("jax_platforms", jp)


def interpret_ctx_factory():
    """Context-manager factory for pallas kernels: native on TPU, interpret
    mode elsewhere (CPU smoke runs — timings meaningless, path exercised).
    Call once per timed region; generator-based contexts are single-use."""
    import contextlib

    import jax

    if jax.default_backend() in ("tpu", "axon"):
        return contextlib.nullcontext
    import jax.experimental.pallas.tpu as pltpu

    return pltpu.force_tpu_interpret_mode


def child_pythonpath(env: dict, repo_root: str) -> str:
    """PYTHONPATH for measurement children: the repo (so galvatron_tpu
    imports) plus /root/.axon_site (or the axon backend fails to register —
    see .claude/skills/verify/SKILL.md)."""
    extra = [repo_root, "/root/.axon_site", env.get("PYTHONPATH", "")]
    return ":".join(p for p in extra if p)


if sys.version_info < (3, 9):  # pragma: no cover
    raise RuntimeError("python >= 3.9 required")
