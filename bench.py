"""Benchmark on the real TPU chip: reference layer-forward parity + the
project's north-star training-throughput metrics.

Primary metric (vs_baseline) matches the one concrete number the reference
ships (BASELINE.md): GPT layer (hidden=4096, heads=32, seq=2048, bf16)
forward time per layer per sample = 5.331 ms on the authors' GPU
(reference: models/gpt_hf/configs/computation_profiling_bf16_hidden4096_head32_seqlen2048.json).
Methodology mirrors the reference profiler's layer differencing
(model_profiler.py:328-372). Robustness: ROUNDS independent measurement
rounds, each a median of ITERS timed calls; the reported value is the MIN
round (timing noise is strictly additive — the min is the best estimate of
the kernel's true cost, cf. python timeit) and the cross-round spread is
reported so a noisy host is visible instead of silently flipping
vs_baseline.

North-star extras (BASELINE.json): a FULL train step — forward + backward +
adam — on LLaMA-7B layer shapes (hidden 4096, ffn 11008, 32 heads, seq 2048,
bf16 compute / fp32 adam), reported as tokens/sec/chip and MFU against the
chip's peak bf16 matmul throughput.

Wedge-proofing (round 5): the axon remote-compile endpoint has been observed
to wedge mid-run (BENCH_r04 rc=124 lost every already-measured number). This
process is therefore a pure ORCHESTRATOR that never imports jax; each metric
section runs in a fresh subprocess (fresh tunnel connection) with its own
timeout and one retry, a global deadline caps total runtime, and the final
JSON line is always printed with whatever was measured — exit code 0 even if
every section fails.

Compile-cost accounting (ISSUE 3): each section AOT-lowers and compiles its
jitted program with explicit timing, so `trace_ms` / `compile_ms` (one-off
program build — depth-constant under the scan-over-layer-runs runtime) and
`step_ms` (steady state) are separate fields in the JSON; per-phase deadline
floors keep one wedged compile from starving the later phases; and
GALVATRON_BENCH_COMPILE_CACHE=1 (or =<dir>) turns on jax's persistent
compilation cache in the measurement children.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

import json
import os
import signal
import sys
import time

from _bench_util import (
    apply_jax_platforms_override,
    concurrent_bench_processes,
    interpret_ctx_factory,
    kill_group,
    load_latest_baseline,
    perf_regressions,
    run_isolated,
)

REFERENCE_MS_PER_LAYER_PER_SAMPLE = 5.331

SMOKE = bool(os.environ.get("GALVATRON_BENCH_SMOKE"))
SECTION = os.environ.get("GALVATRON_BENCH_SECTION")

# GPT layer-forward parity config (the reference's measured layer)
HIDDEN, HEADS, SEQ = (512, 8, 256) if SMOKE else (4096, 32, 2048)
BATCH = 2 if SMOKE else 8
N_LO, N_HI = 1, 3
WARMUP, ITERS, ROUNDS = (1, 3, 2) if SMOKE else (3, 10, 5)

# LLaMA-7B layer shapes for the train-step metric
L7B_HIDDEN, L7B_FFN, L7B_HEADS, L7B_SEQ = (512, 1376, 8, 256) if SMOKE else (4096, 11008, 32, 2048)
# 2 layers (~405M params): fp32 master+adam states ~4.9GB + grads + activations
# fits the single (possibly shared) chip; per-token metrics are depth-invariant
L7B_LAYERS = 2
L7B_BATCH = 1 if SMOKE else 4

# steps executed back-to-back inside one jitted scan per timed call: the
# ~70 ms axon-tunnel dispatch latency amortises away and the measurement is
# the DEVICE step time, as in real training where dispatch runs ahead of the
# device (same differencing rationale as the layer-fwd metric)
STEPS_PER_CALL = 1 if SMOKE else 8

# peak FLOP/s per chip: the obs/flops.py registry is the single source of
# truth now (sections import it lazily — this orchestrator never imports
# galvatron_tpu, whose package init pulls in jax)


# =========================================================================
# Section implementations — run in a fresh child process each; jax is only
# imported here, never in the orchestrator.
# =========================================================================


def _sync(x):
    # NB: block_until_ready does not reliably block on the experimental axon
    # tunnel backend; a host transfer of a scalar does.
    import jax
    import jax.numpy as jnp

    return float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))


def _aot(fn, *args):
    """AOT-lower and compile a jitted fn with explicit timing, so sections
    report trace/compile cost separately from steady-state step time.
    Returns (compiled, trace_ms, compile_ms)."""
    t0 = time.perf_counter()
    lowered = fn.lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    return compiled, (t1 - t0) * 1e3, (t2 - t1) * 1e3


def _build_stack(n_layers):
    import jax
    import jax.numpy as jnp

    from galvatron_tpu.models import base as M

    cfg = M.TransformerConfig(
        hidden_size=HIDDEN, num_heads=HEADS, num_layers=n_layers, vocab_size=256,
        max_seq_len=SEQ, norm_type="layernorm", activation="gelu",
        position_type="learned", compute_dtype=jnp.bfloat16,
        param_dtype=jnp.bfloat16,
    )
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, n_layers)]
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SEQ, HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(SEQ), (BATCH, SEQ))

    def fwd(layers, x):
        # the scan-over-layer-runs path (models/base.py run_layers): one
        # traced+compiled layer body regardless of stack depth
        y = M.run_layers({"layers": layers}, x, positions, cfg)
        # reduce to a scalar so the timing sync transfers O(1) bytes
        return jnp.sum(y.astype(jnp.float32))

    return jax.jit(fwd), layers, x


def _time_stack(fwd, layers, x):
    import numpy as np

    for _ in range(WARMUP):
        float(fwd(layers, x))
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        float(fwd(layers, x))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def section_layer_fwd():
    import numpy as np

    f_lo, l_lo, x_lo = _build_stack(N_LO)
    f_hi, l_hi, x_hi = _build_stack(N_HI)
    # compile both stacks up-front with explicit timing: the program-build
    # cost (the thing scan-over-layer-runs bounds) is reported separately
    # from the steady-state step time instead of hiding in the first warmup
    f_lo, tr_lo, co_lo = _aot(f_lo, l_lo, x_lo)
    f_hi, tr_hi, co_hi = _aot(f_hi, l_hi, x_hi)
    per_round = []
    t_hi = 0.0
    for _ in range(ROUNDS):
        t_lo = _time_stack(f_lo, l_lo, x_lo)
        t_hi = _time_stack(f_hi, l_hi, x_hi)
        per_round.append((t_hi - t_lo) / (N_HI - N_LO) / BATCH * 1e3)
    med = float(np.median(per_round))
    out = {
        "layer_fwd_ms": float(np.min(per_round)),
        "layer_fwd_ms_median": round(med, 4),
        "layer_fwd_round_spread": round(
            float((np.max(per_round) - np.min(per_round)) / max(med, 1e-9)), 4
        ),
        "rounds": ROUNDS,
        "trace_ms": round(tr_lo + tr_hi, 1),
        "compile_ms": round(co_lo + co_hi, 1),
        "step_ms": round(t_hi * 1e3, 3),  # steady-state, N_HI-layer stack
    }
    # forward-only MFU of the N_HI stack (obs/flops.py accounting)
    from galvatron_tpu.obs import flops as F

    fwd_flops = N_HI * F.layer_fwd_flops(
        hidden=HIDDEN, num_heads=HEADS, seq_len=SEQ, tokens=BATCH * SEQ,
        causal=True, swiglu=False,
    )
    peak, _kind = _peak_flops()
    fps = F.flops_per_s(fwd_flops, t_hi * 1e3)
    if fps:
        out["model_flops_per_s"] = round(fps, 1)
    util = F.mfu(fwd_flops, t_hi * 1e3, peak)
    if util is not None:
        out["mfu_fwd"] = round(util, 4)
    return out


def _l7b_setup():
    import jax
    import jax.numpy as jnp
    import optax

    from galvatron_tpu.models import base as M

    cfg = M.TransformerConfig(
        hidden_size=L7B_HIDDEN, num_heads=L7B_HEADS, num_layers=L7B_LAYERS,
        ffn_hidden=L7B_FFN, vocab_size=256, max_seq_len=L7B_SEQ,
        norm_type="rmsnorm", activation="swiglu", position_type="rope",
        qkv_bias=False, mlp_bias=False, out_bias=False,
        compute_dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, L7B_LAYERS)]
    x = jax.random.normal(jax.random.PRNGKey(1), (L7B_BATCH, L7B_SEQ, L7B_HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(L7B_SEQ), (L7B_BATCH, L7B_SEQ))
    tx = optax.adam(1e-4)
    opt_state = tx.init(layers)
    return M, cfg, layers, x, positions, tx, opt_state


def _l7b_flops_tokens(layers):
    import jax
    import numpy as np

    from galvatron_tpu.obs import flops as F

    tokens = L7B_BATCH * L7B_SEQ
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(layers))
    # model FLOPs (PaLM appendix-B convention), via the shared accounting
    flops = F.train_flops_from_params(
        n_params, tokens, L7B_LAYERS, L7B_SEQ, L7B_HIDDEN, causal=True)
    return flops, tokens, n_params


def _peak_flops():
    import jax

    from galvatron_tpu.obs import flops as F

    kind = jax.devices()[0].device_kind
    return F.peak_flops_for(kind), kind


def section_train_step():
    import numpy as np

    import jax
    import optax
    from functools import partial

    M, cfg, layers, x, positions, tx, opt_state = _l7b_setup()
    import jax.numpy as jnp

    def loss_fn(layers, x):
        y = x
        for lp in layers:
            y = M.layer_forward(lp, y, positions, cfg)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    def one_step(carry, _):
        layers, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(layers, x)
        updates, opt_state = tx.update(grads, opt_state, layers)
        layers = optax.apply_updates(layers, updates)
        return (layers, opt_state), loss

    # donate params + opt state: without donation the updated copies double
    # the resident model states and OOM the chip
    @partial(jax.jit, donate_argnums=(0,))
    def run_steps(carry):
        carry, losses = jax.lax.scan(one_step, carry, None, length=STEPS_PER_CALL)
        return carry, losses[-1]

    carry = (layers, opt_state)
    # explicit AOT compile: trace/compile cost reported as separate fields
    run_steps, trace_ms, compile_ms = _aot(run_steps, carry)
    carry, loss = run_steps(carry)  # warmup (first device run)
    _sync(loss)
    rounds = []
    for _ in range(ROUNDS):
        times = []
        for _ in range(max(ITERS // 2, 2)):
            t0 = time.perf_counter()
            carry, loss = run_steps(carry)
            _sync(loss)
            times.append(time.perf_counter() - t0)
        rounds.append(float(np.median(times)) / STEPS_PER_CALL)
    step_s = float(np.min(rounds))

    flops, tokens, n_params = _l7b_flops_tokens(carry[0])
    peak, kind = _peak_flops()
    return {
        "config": "llama7b_layer_stack%d_seq%d_bf16_adam" % (L7B_LAYERS, L7B_SEQ),
        "step_ms": round(step_s * 1e3, 3),
        "trace_ms": round(trace_ms, 1),
        "compile_ms": round(compile_ms, 1),
        "steps_per_call": STEPS_PER_CALL,
        "tokens_per_sec_per_chip": round(tokens / step_s, 1),
        "model_flops_per_s": round(flops / step_s, 1),
        "mfu": round(flops / step_s / peak, 4) if peak else None,
        "device_kind": kind,
        "params": n_params,
    }


def section_breakdown():
    """fwd / adam component timings; bwd is the step-time remainder (the
    parent passes the measured step_ms via GALVATRON_BENCH_STEP_MS)."""
    import numpy as np

    import jax
    import optax

    M, cfg, layers, x, positions, tx, opt_state = _l7b_setup()
    K = STEPS_PER_CALL

    @jax.jit
    def fwd_k(xx):
        def body(c, _):
            y = c
            for lp in layers:
                y = M.layer_forward(lp, y, positions, cfg)
            return 0.5 * c + 0.5 * y, ()

        out, _ = jax.lax.scan(body, xx, None, length=K)
        return out

    # grads are a jit ARGUMENT filled with random data: a closed-over zeros
    # tree would let XLA constant-fold the zero-multiply chains and
    # under-report the real optimizer cost (ADVICE r4)
    grads = jax.tree.map(
        lambda k, l: 1e-3 * jax.random.normal(k, l.shape, l.dtype),
        jax.tree.unflatten(
            jax.tree.structure(layers),
            list(jax.random.split(jax.random.PRNGKey(2), len(jax.tree.leaves(layers)))),
        ),
        layers,
    )

    @jax.jit
    def adam_k(carry, grads):
        def body(c, _):
            ls, st = c
            updates, st = tx.update(grads, st, ls)
            return (optax.apply_updates(ls, updates), st), ()

        out, _ = jax.lax.scan(body, carry, None, length=K)
        return out

    def _time(fn, *a):
        _sync(fn(*a))
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            _sync(fn(*a))
            ts.append(time.perf_counter() - t0)
        return float(np.min(ts)) / K

    t_fwd = _time(fwd_k, x)
    t_adam = _time(adam_k, (layers, opt_state), grads)
    out = {"fwd_ms": round(t_fwd * 1e3, 2), "adam_ms": round(t_adam * 1e3, 2)}
    step_ms = os.environ.get("GALVATRON_BENCH_STEP_MS")
    if step_ms:
        out["bwd_plus_overhead_ms"] = round(float(step_ms) - out["fwd_ms"] - out["adam_ms"], 2)
    # forward-slot MFU: fwd model flops are exactly 1/3 of the train-step
    # convention (fwd + 2x bwd)
    from galvatron_tpu.obs import flops as F

    flops, _tokens, _n = _l7b_flops_tokens(layers)
    peak, _kind = _peak_flops()
    fps = F.flops_per_s(flops / 3.0, t_fwd * 1e3)
    if fps:
        out["fwd_model_flops_per_s"] = round(fps, 1)
    util = F.mfu(flops / 3.0, t_fwd * 1e3, peak)
    if util is not None:
        out["mfu_fwd"] = round(util, 4)
    return out


def section_masked_flash():
    """Padded-mask flash evidence (VERDICT r4 item 3 acceptance): masked
    (segment-id) flash vs unmasked flash vs the old XLA-with-bias fallback at
    the bench layer shapes, 25% suffix padding."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from galvatron_tpu.ops.attention import (
        _pallas_flash,
        _xla_attention,
        padding_bias_to_segment_ids,
    )

    B_, S_, NH_, HD_ = (2, 256, 2, 128) if SMOKE else (8, 2048, 32, 128)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B_, S_, NH_, HD_), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B_, S_, NH_, HD_), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B_, S_, NH_, HD_), jnp.bfloat16)
    mask = np.ones((B_, S_), np.float32)
    mask[:, -S_ // 4:] = 0.0
    bias = jnp.asarray((1.0 - mask)[:, None, None, :] * -1e9)
    seg = padding_bias_to_segment_ids(bias)
    sc = HD_ ** -0.5
    K = STEPS_PER_CALL

    def k_steps(attn):
        # chain outputs through q so the scan body can't be DCE'd; K calls
        # per timed sync amortise the tunnel dispatch latency
        @jax.jit
        def run(q):
            def body(c, _):
                return 0.5 * c + 0.5 * attn(c), ()

            out, _ = jax.lax.scan(body, q, None, length=K)
            return out

        return run

    f_plain = k_steps(lambda c: _pallas_flash(c, k, v, causal=False, sm_scale=sc))
    f_seg = k_steps(lambda c: _pallas_flash(c, k, v, causal=False, sm_scale=sc,
                                            segment_ids=seg))
    f_xla = k_steps(lambda c: _xla_attention(c, k, v, causal=False, sm_scale=sc,
                                             bias=bias))

    make_ctx = interpret_ctx_factory()

    def t(fn):
        with make_ctx():
            _sync(fn(q))
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                _sync(fn(q))
                ts.append(time.perf_counter() - t0)
        return float(np.min(ts)) / K * 1e3

    t_plain, t_seg, t_xla = t(f_plain), t(f_seg), t(f_xla)
    out = {
        "seq": S_,
        "unmasked_flash_ms": round(t_plain, 3),
        "masked_seg_flash_ms": round(t_seg, 3),
        "masked_xla_ms": round(t_xla, 3),
        "masked_vs_unmasked": round(t_seg / max(t_plain, 1e-9), 3),
    }
    # attention arithmetic throughput (scores + weighted sum, non-causal)
    from galvatron_tpu.obs import flops as F

    attn_flops = 4.0 * B_ * NH_ * S_ * S_ * HD_
    peak, _kind = _peak_flops()
    fps = F.flops_per_s(attn_flops, t_plain)
    if fps:
        out["model_flops_per_s"] = round(fps, 1)
    util = F.mfu(attn_flops, t_plain, peak)
    if util is not None:
        out["mfu_fwd"] = round(util, 4)
    return out


def section_train_loop():
    """Host-serialized vs dispatch-ahead training loop (ISSUE 4): steps/s and
    host_blocked_ms for both modes of cli/train.py on a CPU-sized config with
    emulated per-batch input latency — the storage/tokenization wait the
    prefetcher exists to hide (injected through the production FaultHooks
    data-iterator seam, so the measured loop is the shipped loop). Runs with
    --donate_step 0: XLA:CPU executes a call with donated in-flight inputs
    synchronously, which would serialize BOTH loops and mask the contrast
    (TPU runtimes dispatch donated futures asynchronously, so production
    training keeps donation on)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from galvatron_tpu.cli.arguments import initialize_galvatron
    from galvatron_tpu.cli.train import train
    from galvatron_tpu.runtime.resilience import FaultHooks

    iters = 6 if SMOKE else 16

    def latency_hooks(ms):
        def wrap(data_iter, start_step):
            for b in data_iter:
                time.sleep(ms / 1e3)  # emulated input I/O wait
                yield b

        return FaultHooks(wrap_data_iter=wrap)

    argv = [
        "--model_type", "gpt", "--set_model_config_manually", "1",
        "--hidden_size", "64", "--num_attention_heads", "4", "--num_layers", "2",
        "--vocab_size", "256", "--seq_length", "64", "--mixed_precision", "fp32",
        "--global_train_batch_size", "8", "--train_iters", str(iters),
        "--world_size", "1", "--log_interval", "1000", "--lr", "1e-3",
        "--donate_step", "0",
    ]
    # calibration run: the emulated input wait must dominate the machine's
    # actual step time, or the comparison degenerates to compute-bound noise
    probe = train(initialize_galvatron(mode="train_dist", argv=argv + ["--no_async_loop"]))
    latency_ms = round(max(2.0 * probe.get("steady_step_ms", 25.0), 25.0), 1)
    out = {"train_iters": iters, "input_latency_ms_emulated": latency_ms,
           "probe_steady_step_ms": round(probe.get("steady_step_ms", 0.0), 2)}
    # third mode: the dispatch-ahead loop with the telemetry sink enabled —
    # pins the observability overhead (acceptance: <= 2% steps_per_s)
    import tempfile

    tele_path = os.path.join(tempfile.mkdtemp(prefix="galv_bench_tele_"), "t.jsonl")
    modes = (
        ("sync", ["--no_async_loop"]),
        ("dispatch_ahead", []),
        ("dispatch_ahead_telemetry", ["--telemetry", tele_path]),
    )
    for key, extra in modes:
        args = initialize_galvatron(mode="train_dist", argv=argv + extra)
        args.fault_hooks = latency_hooks(latency_ms)
        s = train(args)
        out[key] = {
            "steps_per_s": round(s.get("steps_per_s", 0.0), 3),
            "host_blocked_ms": round(s.get("host_blocked_ms", 0.0), 3),
            "host_blocked_ms_total": round(s.get("host_blocked_ms_total", 0.0), 1),
            "dispatch_ms": round(s.get("dispatch_ms", 0.0), 3),
            "wall_ms_per_iter": round(s.get("wall_ms_per_iter", 0.0), 2),
        }
        if s.get("model_flops_per_s"):
            out[key]["model_flops_per_s"] = round(s["model_flops_per_s"], 1)
        if s.get("mfu") is not None:
            out[key]["mfu"] = round(s["mfu"], 6)
    sync_b = out["sync"]["host_blocked_ms"]
    ahead_b = out["dispatch_ahead"]["host_blocked_ms"]
    if sync_b > 0:
        out["host_blocked_reduction"] = round(1.0 - ahead_b / sync_b, 4)
    if out["sync"]["steps_per_s"] > 0:
        out["throughput_speedup"] = round(
            out["dispatch_ahead"]["steps_per_s"] / out["sync"]["steps_per_s"], 3
        )
    if out["dispatch_ahead"]["steps_per_s"] > 0:
        out["telemetry_overhead"] = round(
            1.0 - out["dispatch_ahead_telemetry"]["steps_per_s"]
            / out["dispatch_ahead"]["steps_per_s"], 4
        )
    return out


def section_tp_overlap():
    """TP-collective execution paths (ISSUE 8): gspmd (compiler-inferred,
    collectives serialize with the matmuls) vs shard_map (manual,
    undecomposed) vs overlap (ppermute-pipelined chunked matmuls) on the
    multi-device-host CPU config — loss+grad through run_layers, which is
    where the collectives live. Reports step_ms/trace_ms/compile_ms/mfu per
    mode plus comm_hidden_ms: the step-level (serialized - overlapped) delta
    and the per-LayerRun measurement from
    parallel/tp_shard_map.measure_comm_hidden (the same helper the train
    driver records under --profile)."""
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from galvatron_tpu.config.strategy import HybridParallelConfig
    from galvatron_tpu.models import base as M
    from galvatron_tpu.obs import flops as F
    from galvatron_tpu.parallel import tp_shard_map as tp_sm
    from galvatron_tpu.parallel.mesh import build_mesh

    B_, S_, H_, NL = (4, 64, 64, 2) if SMOKE else (8, 128, 128, 2)
    cfg = M.TransformerConfig(
        hidden_size=H_, num_heads=4, num_layers=NL, vocab_size=256,
        max_seq_len=S_, compute_dtype=jnp.float32, param_dtype=jnp.float32,
    )
    params = {"layers": [
        M.init_layer_params(k, cfg)
        for k in jax.random.split(jax.random.PRNGKey(0), NL)
    ]}
    x = 0.05 * jax.random.normal(jax.random.PRNGKey(1), (B_, S_, H_), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S_), (B_, S_))
    flops = 3.0 * NL * F.layer_fwd_flops(
        hidden=H_, num_heads=4, seq_len=S_, tokens=B_ * S_, causal=True,
        swiglu=False,
    )
    peak, kind = _peak_flops()

    out = {"world": 4, "tp": 2, "layers": NL, "seq": S_, "device_kind": kind}
    step_ms = {}
    for mode in ("gspmd", "shard_map", "overlap"):
        hp = HybridParallelConfig.uniform(4, NL, tp=2, global_bsz=B_,
                                          tp_comm_mode=mode)
        mesh = build_mesh(hp)

        def loss(p):
            y = M.run_layers(p, x, positions, cfg, hp, mesh)
            return jnp.mean(y.astype(jnp.float32) ** 2)

        fn, trace_ms, compile_ms = _aot(jax.jit(jax.value_and_grad(loss)), params)
        jax.block_until_ready(fn(params))  # first device run
        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(params))
            times.append(time.perf_counter() - t0)
        step_ms[mode] = float(np.median(times)) * 1e3
        entry = {
            "step_ms": round(step_ms[mode], 3),
            "trace_ms": round(trace_ms, 1),
            "compile_ms": round(compile_ms, 1),
        }
        util = F.mfu(flops, step_ms[mode], peak)
        if util is not None:
            entry["mfu"] = round(util, 6)
        fps = F.flops_per_s(flops, step_ms[mode])
        if fps:
            entry["model_flops_per_s"] = round(fps, 1)
        out[mode] = entry
    # comm hidden by the decomposed schedule: step-level delta plus the
    # per-run helper measurement the driver/report use
    out["comm_hidden_ms"] = round(max(step_ms["shard_map"] - step_ms["overlap"], 0.0), 3)
    out["overlap_vs_gspmd"] = round(step_ms["overlap"] / max(step_ms["gspmd"], 1e-9), 3)
    hp_overlap = HybridParallelConfig.uniform(4, NL, tp=2, global_bsz=B_,
                                              tp_comm_mode="overlap")
    out["runs"] = tp_sm.measure_comm_hidden(
        cfg, hp_overlap, build_mesh(hp_overlap), batch_size=B_)
    return out


def section_quant_comm():
    """Quantized collectives (ISSUE 9): fp32 vs int8 gradient sync (ddp) and
    fp32 vs int8 ZeRO-3 gather+sync on the multi-virtual-device CPU config —
    the full train step through make_train_step, which is where the explicit
    shard_map grad ring lives (parallel/quant_collectives.py). Reports per
    mode step_ms/trace_ms/compile_ms + the final short-run loss, plus the
    bytes-on-wire estimate and the fp32-vs-int8 loss delta. On CPU the ring
    is python-unrolled scalar work, so int8 showing no speedup is expected —
    the numbers exist so the regression gate pins them and the first
    real-silicon round has a baseline shape to fill in."""
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from galvatron_tpu.config.strategy import HybridParallelConfig
    from galvatron_tpu.models import base as M
    from galvatron_tpu.parallel import quant_collectives as QC
    from galvatron_tpu.runtime.dataloader import get_train_iterator
    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    S_, H_, NL, BSZ = (32, 32, 2, 8) if SMOKE else (64, 64, 2, 8)
    steps = 4 if SMOKE else 8
    cfg = M.TransformerConfig(
        hidden_size=H_, num_heads=4, num_layers=NL, vocab_size=256,
        max_seq_len=S_, compute_dtype=jnp.float32, param_dtype=jnp.float32,
    )
    modes = {
        "fp32": dict(sdp=0, grad_comm_dtype="none", param_comm_dtype="none"),
        "int8": dict(sdp=0, grad_comm_dtype="int8", param_comm_dtype="none"),
        "zero3_fp32": dict(sdp=1, grad_comm_dtype="none", param_comm_dtype="none"),
        "zero3_int8": dict(sdp=1, grad_comm_dtype="int8", param_comm_dtype="int8"),
    }
    out = {"world": 4, "layers": NL, "seq": S_, "global_bsz": BSZ,
           "train_steps": steps}
    finals = {}
    for name, kw in modes.items():
        hp = HybridParallelConfig.uniform(
            4, NL, tp=1, global_bsz=BSZ, mixed_precision="fp32", **kw)
        model = construct_hybrid_parallel_model(cfg, hp)
        tx = optax.adam(1e-3)
        params = model.init_params(jax.random.PRNGKey(0))
        opt_state = model.init_opt_state(tx, params)
        step = model.make_train_step(tx, donate=False)
        it = get_train_iterator(hp, cfg.vocab_size, cfg.max_seq_len, seed=1)
        batches = [model.shard_batch(next(it)) for _ in range(steps)]
        t0 = time.perf_counter()
        params, opt_state, m = step(params, opt_state, batches[0])
        jax.block_until_ready(m["loss"])
        build_ms = (time.perf_counter() - t0) * 1e3  # trace+compile+1st step
        losses, times = [float(m["loss"])], []
        for b in batches[1:]:
            t0 = time.perf_counter()
            params, opt_state, m = step(params, opt_state, b)
            jax.block_until_ready(m["loss"])
            times.append(time.perf_counter() - t0)
            losses.append(float(m["loss"]))
        finals[name] = losses[-1]
        entry = {
            "step_ms": round(float(np.median(times)) * 1e3, 3),
            "build_ms": round(build_ms, 1),
            "final_loss": round(losses[-1], 6),
        }
        from galvatron_tpu.analysis.strategy_lint import _analytic_parameter_mb

        pmb = _analytic_parameter_mb(cfg)
        if pmb:
            entry["wire_mb"] = QC.bytes_on_wire_mb(hp, pmb)["configured"]
        out[name] = entry
    out["loss_delta_int8"] = round(abs(finals["int8"] - finals["fp32"]), 6)
    out["loss_delta_zero3_int8"] = round(
        abs(finals["zero3_int8"] - finals["zero3_fp32"]), 6)
    out["int8_vs_fp32"] = round(
        out["int8"]["step_ms"] / max(out["fp32"]["step_ms"], 1e-9), 3)
    out["quant_overhead_ms_64k"] = round(
        QC.measure_quant_overhead_ms((1 << 16,), dtype="int8"), 3)
    return out


def section_serve():
    """Searched-strategy serving (ISSUE 11): the shipped cli/serve driver on
    the multi-virtual-device CPU config — the gspmd baseline layout (tp=1:
    weights replicated per chip, decode slots sharded over dp) vs the
    serve-objective winner shape for this geometry (tp=2: weight and KV
    reads split across chips, the layout `search --objective serve` picks
    once decode is weight-read-bound). Each mode runs the synthetic load
    twice in-process: the first (cold) pass pays trace+compile for every
    bucket executable, the second rides the in-process AOT memo and is the
    steady-state measurement — tokens/s(/chip), TTFT/TPOT percentiles, and
    the median decode step from the decode_batch telemetry stream. CPU
    numbers are host noise in absolute terms; the regression gate pins them
    so the serving path cannot silently decay and the first real-silicon
    round has a baseline shape to fill in."""
    import statistics
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from galvatron_tpu.cli.arguments import initialize_galvatron
    from galvatron_tpu.cli.serve import serve

    n_req = 4 if SMOKE else 8
    n_new = 4 if SMOKE else 8
    out = {"world": 4, "requests": n_req, "max_new_tokens": n_new,
           "max_concurrency": 4}
    tdir = tempfile.mkdtemp(prefix="galv_bench_serve_")
    tps = {}
    for name, tp in (("gspmd", 1), ("searched", 2)):
        tele = os.path.join(tdir, name + ".jsonl")
        argv = [
            "--model_type", "gpt", "--set_model_config_manually", "1",
            "--hidden_size", "64", "--num_attention_heads", "4",
            "--num_layers", "2", "--vocab_size", "256", "--seq_length", "128",
            "--mixed_precision", "fp32", "--global_train_batch_size", "8",
            "--world_size", "4", "--global_tp_deg", str(tp),
            "--serve_max_concurrency", "4", "--serve_page_size", "16",
            "--num_requests", str(n_req), "--rate_rps", "0",
            "--prompt_len_min", "4", "--prompt_len_max", "12",
            "--max_new_tokens", str(n_new),
        ]
        t0 = time.perf_counter()
        serve(initialize_galvatron(mode="serve", argv=argv))
        cold_ms = (time.perf_counter() - t0) * 1e3
        # telemetry only on the warm pass: the cold pass's per-bucket compile
        # ticks would pollute the decode step_ms median
        t0 = time.perf_counter()
        s = serve(initialize_galvatron(
            mode="serve", argv=argv + ["--telemetry", tele]))
        warm_ms = (time.perf_counter() - t0) * 1e3
        steps = []
        with open(tele) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("type") == "decode_batch" and ev.get("step_ms") is not None:
                    steps.append(float(ev["step_ms"]))
        tps[name] = s["tokens_per_s"]
        out[name] = {
            "tokens_per_s": round(s["tokens_per_s"], 2),
            "tokens_per_s_per_chip": round(s["tokens_per_s_per_chip"], 3),
            "ttft_ms_p50": round(s["ttft_ms"]["p50"], 2),
            "ttft_ms_p99": round(s["ttft_ms"]["p99"], 2),
            "tpot_ms_p50": round(s["tpot_ms"]["p50"], 2),
            "tpot_ms_p99": round(s["tpot_ms"]["p99"], 2),
            "decode_step_ms": round(statistics.median(steps), 3) if steps else None,
            "decode_steps": s.get("decode_steps"),
            "build_plus_load_ms": round(cold_ms, 1),
            "warm_load_ms": round(warm_ms, 1),
        }
    if tps["gspmd"] > 0:
        out["searched_vs_gspmd"] = round(tps["searched"] / tps["gspmd"], 3)
    return out


def section_serve_degraded():
    """Serving resilience (ISSUE 12): the shipped cli/serve driver on the
    4-virtual-device CPU config losing half its mesh mid-load. The mesh
    probe sees 2 of 4 devices at decode step 2, the engine re-searches a
    serve strategy for the survivors, relayouts params in memory, rebuilds
    the KV cache, and journal-replays the in-flight requests — the numbers
    are the migration cost (serve_migrate duration) and the tokens/s /
    decode-tick recovery on the shrunken world, measured from the same
    telemetry stream the report CLI consumes. Absolute CPU numbers are host
    noise; the gate pins the shape (migration happens, zero requests lost,
    decode resumes) so the resilience path cannot silently decay."""
    import statistics
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from galvatron_tpu.cli.arguments import initialize_galvatron
    from galvatron_tpu.cli.serve import serve
    from galvatron_tpu.runtime.resilience import FaultHooks

    # NOT smoke-scaled: the load must outlive the probe interval with a
    # queue still pending, or the loss lands after the last decode tick and
    # there is no migration to measure (2 slots x 8 requests x 8 tokens
    # leaves ~24 post-loss ticks; the whole section runs in seconds)
    n_req, n_new = 8, 8
    tele = os.path.join(
        tempfile.mkdtemp(prefix="galv_bench_serve_degraded_"), "t.jsonl")
    argv = [
        "--model_type", "gpt", "--set_model_config_manually", "1",
        "--hidden_size", "64", "--num_attention_heads", "4",
        "--num_layers", "2", "--vocab_size", "256", "--seq_length", "128",
        "--mixed_precision", "fp32", "--global_train_batch_size", "8",
        "--world_size", "4", "--global_tp_deg", "2",
        "--serve_max_concurrency", "2", "--serve_page_size", "16",
        "--num_requests", str(n_req), "--rate_rps", "0",
        "--prompt_len_min", "4", "--prompt_len_max", "12",
        "--max_new_tokens", str(n_new),
        "--mesh_probe_interval", "0.02", "--migrate_on_degrade", "1",
        "--telemetry", tele,
    ]
    args = initialize_galvatron(mode="serve", argv=argv)
    lost = {"v": False}

    def on_step(it):
        if it >= 2:
            lost["v"] = True

    args.fault_hooks = FaultHooks(on_step=on_step)
    args.probe_devices_fn = (
        lambda: jax.devices()[:2] if lost["v"] else jax.devices())
    t0 = time.perf_counter()
    s = serve(args)
    wall_ms = (time.perf_counter() - t0) * 1e3
    with open(tele) as f:
        events = [json.loads(line) for line in f]
    [mig] = [e for e in events if e["type"] == "serve_migrate"]
    pre = [e["step_ms"] for e in events
           if e["type"] == "decode_batch" and e["seq"] < mig["seq"]]
    post = [e["step_ms"] for e in events
            if e["type"] == "decode_batch" and e["seq"] > mig["seq"]]
    return {
        "world": 4, "live_world": mig["to_world"], "requests": n_req,
        "completed": s["requests"], "shed": s["shed"],
        "migrations": s["migrations"],
        "replayed": mig["replayed"],
        "migrate_ms": round(mig["duration_ms"], 1),
        "tokens_per_s": round(s["tokens_per_s"], 2),
        "decode_step_ms_pre": (
            round(statistics.median(pre), 3) if pre else None),
        "decode_step_ms_post": (
            round(statistics.median(post), 3) if post else None),
        "post_migration_decode_steps": len(post),
        "wall_ms": round(wall_ms, 1),
    }


def section_sdc_overhead():
    """Silent-corruption sentinel cost (ISSUE 13): steady step time of the
    shipped cli/train loop on the 4-virtual-device CPU config with the
    sentinel off, with the in-jit integrity digests (--sdc_check digest),
    and with the cross-replica vote (--sdc_check vote) on the pure-dp
    layout where the vote envelope holds. Digest mode fuses two scalar
    side-outputs into the already-jitted step, so its budget is <= 2%
    step-time overhead; vote adds a shard_map digest of the input params
    per step and is allowed to cost more. The section also re-checks the
    transparency contract: digest-mode losses must be bitwise identical to
    the sentinel-off run (vote legally shifts GSPMD partitioning, so it
    carries no such guarantee). The <= 2% digest budget is a real-silicon
    acceptance: on this toy CPU config the per-leaf bitcast+fold dispatch
    is comparable to the toy matmuls it rides beside, so the measured pct
    is a loose upper bound and run-to-run host noise exceeds the budget
    itself. The binding CPU checks are the bitwise-transparency bit and
    the regression gate pinning all three step times so sentinel cost
    cannot silently grow between rounds."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from galvatron_tpu.cli.arguments import initialize_galvatron
    from galvatron_tpu.cli.train import train

    iters = 6 if SMOKE else 24
    argv = [
        "--model_type", "gpt", "--set_model_config_manually", "1",
        "--hidden_size", "64", "--num_attention_heads", "4", "--num_layers", "2",
        "--vocab_size", "256", "--seq_length", "64", "--mixed_precision", "fp32",
        "--global_train_batch_size", "8", "--train_iters", str(iters),
        "--world_size", "4", "--log_interval", "1000", "--lr", "1e-3",
    ]
    out = {"world": 4, "train_iters": iters,
           "digest_overhead_target_pct": 2.0}
    losses = {}
    for mode in ("off", "digest", "vote"):
        extra = [] if mode == "off" else [
            "--sdc_check", mode, "--sdc_interval", "1"]
        s = train(initialize_galvatron(mode="train_dist", argv=argv + extra))
        losses[mode] = list(s.get("losses", ()))
        out[mode] = {
            "step_ms": round(s.get("steady_step_ms", 0.0), 3),
            "sdc_checks": s.get("resilience", {}).get("sdc_checks", 0),
        }
    if out["off"]["step_ms"] > 0:
        out["digest_overhead_pct"] = round(
            100.0 * (out["digest"]["step_ms"] / out["off"]["step_ms"] - 1.0), 2)
        out["vote_overhead_pct"] = round(
            100.0 * (out["vote"]["step_ms"] / out["off"]["step_ms"] - 1.0), 2)
    # the digest legs read the same buffers the update consumes and write
    # only side-outputs — the trajectory must not move by one ulp
    out["digest_bitwise_identical"] = bool(losses["digest"] == losses["off"])
    return out


def section_remat():
    """Per-layer rematerialization search (ISSUE 15): all-none vs all-full
    vs searched-mixed remat plans on the 4-virtual-device CPU config. The
    searched leg is the real pipeline end to end — the DP with
    remat_search=True over mock profiles, swept down from a roomy budget to
    the first one that emits a MIXED per-layer plan (some layers
    checkpointed under dots_saveable, some not), saved to the on-disk JSON
    schema and loaded back through from_json — then that plan's per-layer
    policies drive the measured train step layer-for-layer. Layers are
    UNROLLED (scan_layers=False): under scan, XLA:CPU prices the
    non-checkpointed path's stacked activation storage above the recompute
    it saves (the autotune section's inversion), which would invert the
    ordering this section exists to measure. Reports per-leg step_ms plus
    the compiled executable's temp+output memory (the XLA:CPU analogue of
    peak device memory) — expected ordering: full < searched < none on
    memory, searched < full on step time."""
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import tempfile

    import jax.numpy as jnp
    import optax

    from galvatron_tpu.config.strategy import HybridParallelConfig
    from galvatron_tpu.models import base as M
    from galvatron_tpu.runtime.dataloader import get_train_iterator
    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model
    from galvatron_tpu.search.engine import GalvatronSearchEngine, SearchArgs

    S_, H_, NL, BSZ = (32, 32, 4, 8) if SMOKE else (64, 64, 4, 8)
    steps = 4 if SMOKE else 14
    cfg = M.TransformerConfig(
        hidden_size=H_, num_heads=4, num_layers=NL, vocab_size=256,
        max_seq_len=S_, compute_dtype=jnp.float32, param_dtype=jnp.float32,
    )

    # mock profiles (tests/search_engine shapes): the DP is pure python over
    # these numbers, so the search itself costs milliseconds here
    allreduce_bw = {"allreduce_size_4_consec_1": 155.0,
                    "allreduce_size_4_consec_0": 150.0,
                    "allreduce_size_2_consec_1": 130.0,
                    "allreduce_size_2_consec_0": 145.0}
    p2p_bw = {"pp_size_2": 160.0, "pp_size_4": 140.0}
    time_config = {"layertype_0": 5.3, "other_time": 2.0}
    memory_config = {
        "layertype_0": {
            "parameter_size": 96.0,
            "tp_activation_per_bsz_dict": {
                1: 500.0, 2: 260.0, 4: 140.0, "checkpoint": 30.0}},
        "other_memory_pp_off": {
            "model_states": {1: 3000.0, 2: 1500.0, 4: 750.0},
            "activation": {1: 80.0, 2: 42.0, 4: 22.0}},
        "other_memory_pp_on": {
            "first_stage": {
                "model_states": {1: 2000.0, 2: 1000.0, 4: 500.0},
                "activation": {1: 50.0, 2: 26.0, 4: 14.0}},
            "last_stage": {
                "model_states": {1: 1500.0, 2: 750.0, 4: 375.0},
                "activation": {1: 30.0, 2: 16.0, 4: 8.0}}},
    }

    def search(mem_gb):
        args = SearchArgs(memory_constraint=mem_gb, settle_bsz=BSZ,
                          settle_chunk=1, max_tp_deg=1, disable_pp=True,
                          remat_search=True)
        eng = GalvatronSearchEngine(
            args, 4,
            [{"hidden_size": 4096, "seq_len": 2048, "layer_num": NL}],
            model_name="bench_remat")
        eng.set_model_profiles(time_config, memory_config)
        eng.set_hardware_profiles(allreduce_bw, p2p_bw, {"overlap_coe": 1.12})
        eng.initialize_search_engine()
        return eng, eng.parallelism_optimization()

    tmp = tempfile.mkdtemp(prefix="galv_bench_remat_")
    searched_hp, plan_desc, search_gb = None, None, None
    for gb in (5.5, 5.0, 4.5, 4.0, 3.0):
        eng, r = search(gb)
        if r is None:
            continue
        cpts = [s[3].get("cpt", s[3].get("ckpt", 0)) for s in r["strategies"]]
        rps = [s[3].get("rp", "full") for s in r["strategies"]]
        if 0 < sum(cpts) < len(cpts):  # a genuinely mixed plan
            path = eng.save_results(r, os.path.join(tmp, "mixed.json"))
            searched_hp = HybridParallelConfig.from_json(
                path, world_size=4, scan_layers=False,
                mixed_precision="fp32")
            plan_desc = ["%s" % (rp if c else "none")
                         for c, rp in zip(cpts, rps)]
            search_gb = gb
            break

    def leg(hp):
        model = construct_hybrid_parallel_model(cfg, hp)
        tx = optax.adam(1e-3)
        params = model.init_params(jax.random.PRNGKey(0))
        opt_state = model.init_opt_state(tx, params)
        step = model.make_train_step(tx, donate=False)
        it = get_train_iterator(hp, cfg.vocab_size, cfg.max_seq_len, seed=1)
        batches = [model.shard_batch(next(it)) for _ in range(steps)]
        entry = {}
        try:
            # XLA:CPU supports compiled memory accounting: temp+output is
            # the executable's transient high-water analogue of peak HBM
            ma = step.lower(params, opt_state, batches[0]).compile() \
                     .memory_analysis()
            entry["peak_mb"] = round(
                (ma.temp_size_in_bytes + ma.output_size_in_bytes) / 2**20, 3)
        except Exception:
            pass  # accounting is backend-best-effort; step_ms still gates
        t0 = time.perf_counter()
        params, opt_state, m = step(params, opt_state, batches[0])
        jax.block_until_ready(m["loss"])
        entry["build_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        times = []
        for b in batches[1:]:
            t0 = time.perf_counter()
            params, opt_state, m = step(params, opt_state, b)
            jax.block_until_ready(m["loss"])
            times.append(time.perf_counter() - t0)
        entry["step_ms"] = round(float(np.median(times)) * 1e3, 3)
        entry["final_loss"] = round(float(m["loss"]), 6)
        return entry

    out = {"world": 4, "layers": NL, "seq": S_, "global_bsz": BSZ,
           "train_steps": steps}
    out["none"] = leg(HybridParallelConfig.uniform(
        4, NL, tp=1, global_bsz=BSZ, mixed_precision="fp32",
        scan_layers=False))
    out["full"] = leg(HybridParallelConfig.uniform(
        4, NL, tp=1, checkpoint=1, global_bsz=BSZ, mixed_precision="fp32",
        scan_layers=False))
    if searched_hp is not None:
        out["searched"] = leg(searched_hp)
        out["searched_plan"] = plan_desc
        out["searched_budget_gb"] = search_gb
        out["searched_vs_full"] = round(
            out["searched"]["step_ms"] / max(out["full"]["step_ms"], 1e-9), 3)
        # rematerialization recomputes the SAME forward — the trajectory
        # must not move by one ulp across any of the three plans
        out["losses_match"] = (
            out["none"]["final_loss"] == out["full"]["final_loss"]
            == out["searched"]["final_loss"])
    else:
        out["error"] = "no budget in the sweep produced a mixed plan"
    return out


def section_autotune():
    """Online autotuner (ISSUE 14): the shipped cli/train loop on the
    4-virtual-device CPU config started from a deliberately mis-specified
    strategy — needless activation checkpointing on a model that fits
    without it. The autotuner detects steady state, calibrates the cost
    model on the measured step time, re-searches under the original memory
    budget, and hot-swaps to the checkpoint-off winner mid-run. heads=1
    caps the searched tp at 1, so the winner differs from the start only
    by dropping the recompute — a change that is faster in wall clock on
    this host too, which makes steps/s before vs after the swap a
    meaningful number here (unlike layout-only swaps, whose CPU timing is
    virtual-device noise). Layers are unrolled (--no_scan_layers): under
    scan, XLA:CPU prices the non-checkpointed path's stacked activation
    storage above the recompute it saves, inverting the tradeoff the
    tuner is being measured on. The no-op leg re-runs FROM the winner: the
    planner must fire and refuse to swap (hysteresis), pinning the
    convergence contract alongside the two gated steps/s numbers."""
    import statistics
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    from galvatron_tpu.cli.arguments import initialize_galvatron
    from galvatron_tpu.cli.train import train
    from galvatron_tpu.config.strategy import HybridParallelConfig

    tmp = tempfile.mkdtemp(prefix="galv_bench_autotune_")
    start = os.path.join(tmp, "ckpt_on.json")
    HybridParallelConfig.uniform(
        world_size=4, num_layers=2, pp=1, tp=1, checkpoint=1, global_bsz=8,
    ).save(start)

    def run(tag, iters, config_path):
        tele = os.path.join(tmp, tag + ".jsonl")
        argv = [
            "--model_type", "gpt", "--set_model_config_manually", "1",
            "--hidden_size", "64", "--num_attention_heads", "1",
            "--num_layers", "2", "--vocab_size", "256", "--seq_length", "64",
            "--mixed_precision", "fp32", "--global_train_batch_size", "8",
            "--train_iters", str(iters), "--world_size", "4",
            "--log_interval", "1000", "--lr", "1e-3", "--no_scan_layers",
            "--autotune", "apply", "--galvatron_config_path", config_path,
            "--telemetry", tele,
        ]
        args = initialize_galvatron(mode="train_dist", argv=argv)
        args.autotune_window = 3  # settle inside the short bench run
        s = train(args)
        with open(tele) as f:
            events = [json.loads(line) for line in f]
        return s, events

    iters = 8 if SMOKE else 16
    s, events = run("misspec", iters, start)
    plans = [e for e in events
             if e["type"] == "autotune" and e.get("action") == "plan"]
    swapped = [e for e in plans if e.get("swapped")]
    steps = {e["iter"]: e["iter_ms"] for e in events
             if e["type"] == "step" and e.get("iter_ms") is not None}
    out = {"world": 4, "train_iters": iters,
           "plans": len(plans), "swaps": len(swapped)}
    if swapped:
        sw = swapped[0]
        si = sw.get("iter") or 0
        out["swap_iter"] = si
        out["predicted_saving_ms"] = round(
            sw.get("predicted_saving_ms") or 0.0, 3)
        out["winner_checkpoint"] = (sw.get("to_strategy") or {}).get("checkpoint")
        # iters 0-1 are warmup/compile; swap_iter+1 funds the winner's
        # recompile — both excluded, same split the tuner itself uses
        pre = [ms for it, ms in steps.items() if 2 <= it < si]
        post = [ms for it, ms in steps.items() if it > si + 1]
        if pre:
            m = statistics.median(pre)
            out["misspecified"] = {
                "step_ms": round(m, 3), "steps_per_s": round(1000.0 / m, 3)}
        if post:
            m = statistics.median(post)
            out["converged"] = {
                "step_ms": round(m, 3), "steps_per_s": round(1000.0 / m, 3)}
        realized = [e for e in events
                    if e["type"] == "autotune" and e.get("action") == "realized"]
        if realized:
            out["realized_saving_ms"] = round(
                realized[-1].get("realized_saving_ms") or 0.0, 3)
        # no-op leg: restart from the searched winner — the planner must
        # refuse to swap (zero plans would mean the detector never settled;
        # a swap would mean the hysteresis contract broke)
        winner = os.path.join(tmp, "winner.json")
        with open(winner, "w") as f:
            json.dump(sw["to_strategy"], f)
        s2, ev2 = run("noop", 6 if SMOKE else 10, winner)
        noop_plans = [e for e in ev2
                      if e["type"] == "autotune" and e.get("action") == "plan"]
        out["noop"] = {
            "plans": len(noop_plans),
            "swaps": sum(1 for e in noop_plans if e.get("swapped")),
        }
    return out


SECTIONS = {
    "layer_fwd": section_layer_fwd,
    "train_step": section_train_step,
    "breakdown": section_breakdown,
    "masked_flash": section_masked_flash,
    "train_loop": section_train_loop,
    "tp_overlap": section_tp_overlap,
    "quant_comm": section_quant_comm,
    "serve": section_serve,
    "serve_degraded": section_serve_degraded,
    "sdc_overhead": section_sdc_overhead,
    "remat": section_remat,
    "autotune": section_autotune,
}


# =========================================================================
# Orchestrator — never imports jax, so it cannot wedge on the tunnel.
# =========================================================================

# The external driver killed round 4's bench at its own timeout (rc=124);
# common budgets are 900s, so the normal-path emit must land by ~780s and the
# last-resort watchdog by ~800s — comfortably inside.
DEADLINE_S = float(os.environ.get("GALVATRON_BENCH_DEADLINE", "200" if SMOKE else "780"))
# masked_flash compiles three attention programs through the tunnel
# (~20-40s each), so it gets headroom; the deadline still caps the total
SECTION_BUDGETS = {"layer_fwd": 300.0, "train_step": 360.0, "breakdown": 200.0,
                   "masked_flash": 180.0, "train_loop": 200.0,
                   "tp_overlap": 200.0, "quant_comm": 200.0, "serve": 200.0,
                   "serve_degraded": 200.0, "sdc_overhead": 200.0,
                   "remat": 200.0, "autotune": 200.0}
_START = time.time()
_ACTIVE_CHILD = None  # Popen of the in-flight section, for watchdog cleanup


def _remaining():
    return DEADLINE_S - (time.time() - _START)


def _kill_active_child():
    if _ACTIVE_CHILD is not None:
        kill_group(_ACTIVE_CHILD)


def _run_section(name, errors, extra_env=None, reserve_s=0.0):
    """Run one section via the shared wedge-tolerant harness (_bench_util):
    fresh subprocess in its own process group, one retry; None on failure.
    A child that printed its JSON but died in teardown still counts.

    Per-phase deadline split (BENCH_r05: one wedged compile starved
    masked_flash out of the budget entirely): the section's budget is a cap
    on BOTH attempts combined — a first attempt that wedges for the full
    budget forfeits its retry instead of eating another budget's worth — and
    `reserve_s` seconds of the global deadline are kept back for the phases
    still to run, so every phase gets floor time even after a wedge."""
    global _ACTIVE_CHILD

    def on_spawn(p):
        global _ACTIVE_CHILD
        _ACTIVE_CHILD = p

    budget = SECTION_BUDGETS[name]
    section_t0 = time.time()
    for attempt in (1, 2):
        b = min(budget - (time.time() - section_t0), _remaining() - 10.0 - reserve_s)
        if b < 45.0:
            errors.setdefault(name, "skipped: phase deadline exhausted")
            return None
        env = dict(os.environ)
        env["GALVATRON_BENCH_SECTION"] = name
        env.update(extra_env or {})
        result, rc, err_tail = run_isolated(
            [sys.executable, os.path.abspath(__file__)], env, b, on_spawn=on_spawn,
        )
        _ACTIVE_CHILD = None
        if result is not None:
            errors.pop(name, None)
            return result
        if rc is None:
            errors[name] = "attempt %d: timeout after %.0fs (tunnel wedge?)" % (attempt, b)
        elif rc == 0:
            errors[name] = "attempt %d: no JSON in section output" % attempt
        else:
            errors[name] = "attempt %d: rc=%d %s" % (attempt, rc, err_tail)
    return None


def main():
    results, errors = {}, {}
    timing_hazards = []

    def emit_and_exit(signum=None, frame=None):
        layer = results.get("layer_fwd") or {}
        best = layer.get("layer_fwd_ms")
        extra = {k: v for k, v in layer.items() if k != "layer_fwd_ms"}
        train = results.get("train_step")
        if train is not None:
            if results.get("breakdown"):
                train = dict(train, breakdown=results["breakdown"])
            extra["train_step"] = train
        elif "train_step" in errors:
            extra["train_step"] = {"error": errors["train_step"]}
        if results.get("masked_flash"):
            extra["masked_flash"] = results["masked_flash"]
        if results.get("train_loop"):
            extra["train_loop"] = results["train_loop"]
        if results.get("tp_overlap"):
            extra["tp_overlap"] = results["tp_overlap"]
        if results.get("quant_comm"):
            extra["quant_comm"] = results["quant_comm"]
        if results.get("serve"):
            extra["serve"] = results["serve"]
        if results.get("serve_degraded"):
            extra["serve_degraded"] = results["serve_degraded"]
        if results.get("sdc_overhead"):
            extra["sdc_overhead"] = results["sdc_overhead"]
        if results.get("remat"):
            extra["remat"] = results["remat"]
        if results.get("autotune"):
            extra["autotune"] = results["autotune"]
        if timing_hazards:
            extra["timing_hazard"] = timing_hazards
        if errors:
            extra["errors"] = errors
        _kill_active_child()  # don't leave a wedged child squatting the chip
        metric = (
            "SMOKE_gpt_layer_fwd_ms_h%d_s%d" % (HIDDEN, SEQ)
            if SMOKE else "gpt_layer_fwd_ms_per_layer_per_sample_h4096_s2048_bf16"
        )
        payload = {
            "metric": metric,
            "value": round(best, 4) if best is not None else None,
            "unit": "ms",
            # the baseline is the full-shape reference number; a smoke run
            # measures different shapes and must not claim a ratio
            "vs_baseline": None if (SMOKE or best is None) else round(
                REFERENCE_MS_PER_LAYER_PER_SAMPLE / best, 4
            ),
            "extra": extra,
        }
        print(json.dumps(payload))
        sys.stdout.flush()
        # MFU-regression gate (opt-in, ROADMAP item 1): compare against the
        # newest non-empty BENCH_r*.json and FAIL the process on decay beyond
        # tolerance. Off by default — the wedge-proofing contract ("a partial
        # bench is a result, not a failure", exit 0) stays the default; the
        # perf driver enables the gate explicitly.
        rc = 0
        if os.environ.get("GALVATRON_BENCH_GATE", "") not in ("", "0", "false", "no"):
            tol = float(os.environ.get("GALVATRON_BENCH_GATE_TOL", "0.1"))
            pattern = os.environ.get(
                "GALVATRON_BENCH_BASELINE_GLOB",
                os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json"),
            )
            baseline = load_latest_baseline(pattern)
            if baseline is None:
                # absent baselines / number-free rounds are tolerated
                print("MFU-GATE: no usable baseline under %s — pass" % pattern)
            else:
                regressions = perf_regressions(payload, baseline[1], tol)
                for line in regressions:
                    print("MFU-REGRESSION [vs %s]: %s" % (baseline[0], line))
                if regressions:
                    rc = 1
                else:
                    print("MFU-GATE: no regression vs %s (tolerance %.0f%%)"
                          % (baseline[0], tol * 100.0))
        sys.stdout.flush()
        os._exit(rc)

    # gate-test seam: canned section results (no measurement children) let
    # the regression gate's exit-code contract be tested without a chip
    fake = os.environ.get("GALVATRON_BENCH_FAKE_RESULTS")
    if fake:
        with open(fake) as f:
            canned = json.load(f)
        results.update(canned.get("results", {}))
        errors.update(canned.get("errors", {}))
        emit_and_exit()

    # timing discipline: a concurrent bench (another round, a stray wedged
    # child) on the same host corrupts every number — record what
    # `pgrep -af bench` saw BEFORE any section times, so a suspect round is
    # visibly suspect in its own payload instead of silently noisy
    timing_hazards.extend(concurrent_bench_processes())
    for line in timing_hazards:
        print("TIMING-HAZARD: concurrent bench-like process: %s" % line,
              file=sys.stderr)

    # last-resort watchdog: even if the orchestrator itself stalls (e.g. in
    # communicate() on a wedged child), the JSON line with whatever was
    # measured still goes out, and the child is killed so it can't keep
    # squatting the shared chip
    signal.signal(signal.SIGALRM, emit_and_exit)
    signal.alarm(int(DEADLINE_S + 20))

    # each phase keeps a floor reserved for every phase still to run, so a
    # wedged early compile cannot starve the later phases ("deadline
    # exhausted" masked_flash, BENCH_r05)
    floor = min(60.0, DEADLINE_S / (2 * len(SECTIONS)))
    results["layer_fwd"] = _run_section("layer_fwd", errors, reserve_s=4 * floor)
    results["train_step"] = _run_section("train_step", errors, reserve_s=3 * floor)
    if results["train_step"] is not None:
        results["breakdown"] = _run_section(
            "breakdown", errors,
            extra_env={"GALVATRON_BENCH_STEP_MS": str(results["train_step"]["step_ms"])},
            reserve_s=2 * floor,
        )
    results["masked_flash"] = _run_section("masked_flash", errors, reserve_s=2 * floor)
    # pure-CPU sections (host overlap and the multi-virtual-device TP paths
    # are host/compiler properties; never need the chip)
    results["train_loop"] = _run_section(
        "train_loop", errors, extra_env={"JAX_PLATFORMS": "cpu"},
        reserve_s=floor)
    results["tp_overlap"] = _run_section(
        "tp_overlap", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        }, reserve_s=floor)
    results["quant_comm"] = _run_section(
        "quant_comm", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        }, reserve_s=floor)
    results["serve"] = _run_section(
        "serve", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        }, reserve_s=floor)
    results["serve_degraded"] = _run_section(
        "serve_degraded", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        }, reserve_s=floor)
    results["sdc_overhead"] = _run_section(
        "sdc_overhead", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        }, reserve_s=floor)
    results["remat"] = _run_section(
        "remat", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        }, reserve_s=floor)
    results["autotune"] = _run_section(
        "autotune", errors, extra_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=4").strip(),
        })
    emit_and_exit()


if __name__ == "__main__":
    if SECTION:
        apply_jax_platforms_override()
        # opt-in persistent compile cache: identical section HLO across bench
        # runs (and across the lo/hi stacks' shared programs) loads from disk
        # instead of re-invoking XLA. Per-host cache — see
        # galvatron_tpu/utils/compile_cache.py for the shared-dir hazard.
        _cache = os.environ.get("GALVATRON_BENCH_COMPILE_CACHE")
        if _cache:
            from galvatron_tpu.utils.compile_cache import enable_persistent_cache

            enable_persistent_cache(None if _cache in ("1", "true", "yes") else _cache)
        print(json.dumps(SECTIONS[SECTION]()))
    else:
        main()
