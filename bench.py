"""Benchmark: transformer-layer forward time on the real TPU chip.

Metric matches the one concrete number the reference ships (BASELINE.md):
GPT layer (hidden=4096, heads=32, seq=2048, bf16) forward time per layer per
sample = 5.331 ms on the authors' GPU
(reference: models/gpt_hf/configs/computation_profiling_bf16_hidden4096_head32_seqlen2048.json).

Methodology mirrors the reference profiler's layer differencing
(model_profiler.py:328-372): time N_hi and N_lo layer stacks, per-layer time
= (T_hi - T_lo) / (N_hi - N_lo) / batch_size.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = reference_ms / measured_ms (>1 = faster than the reference's
GPU measurement).
"""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

REFERENCE_MS_PER_LAYER_PER_SAMPLE = 5.331

HIDDEN, HEADS, SEQ = 4096, 32, 2048
BATCH = 8
N_LO, N_HI = 1, 3
WARMUP, ITERS = 3, 10


def build_stack(n_layers):
    from galvatron_tpu.models import base as M

    cfg = M.TransformerConfig(
        hidden_size=HIDDEN, num_heads=HEADS, num_layers=n_layers, vocab_size=256,
        max_seq_len=SEQ, norm_type="layernorm", activation="gelu",
        position_type="learned", compute_dtype=jnp.bfloat16,
        param_dtype=jnp.bfloat16,
    )
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, n_layers)]
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SEQ, HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(SEQ), (BATCH, SEQ))

    def fwd(layers, x):
        for lp in layers:
            x = M.layer_forward(lp, x, positions, cfg)
        # reduce to a scalar so the timing sync transfers O(1) bytes
        return jnp.sum(x.astype(jnp.float32))

    return jax.jit(fwd), layers, x


def time_stack(n_layers):
    fwd, layers, x = build_stack(n_layers)
    # NB: block_until_ready does not reliably block on the experimental axon
    # tunnel backend; a host transfer of the scalar result does.
    for _ in range(WARMUP):
        float(fwd(layers, x))
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        float(fwd(layers, x))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main():
    t_lo = time_stack(N_LO)
    t_hi = time_stack(N_HI)
    per_layer_per_sample_ms = (t_hi - t_lo) / (N_HI - N_LO) / BATCH * 1e3
    print(
        json.dumps(
            {
                "metric": "gpt_layer_fwd_ms_per_layer_per_sample_h4096_s2048_bf16",
                "value": round(per_layer_per_sample_ms, 4),
                "unit": "ms",
                "vs_baseline": round(REFERENCE_MS_PER_LAYER_PER_SAMPLE / per_layer_per_sample_ms, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
