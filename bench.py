"""Benchmark on the real TPU chip: reference layer-forward parity + the
project's north-star training-throughput metrics.

Primary metric (vs_baseline) matches the one concrete number the reference
ships (BASELINE.md): GPT layer (hidden=4096, heads=32, seq=2048, bf16)
forward time per layer per sample = 5.331 ms on the authors' GPU
(reference: models/gpt_hf/configs/computation_profiling_bf16_hidden4096_head32_seqlen2048.json).
Methodology mirrors the reference profiler's layer differencing
(model_profiler.py:328-372). Robustness: ROUNDS independent measurement
rounds, each a median of ITERS timed calls; the reported value is the MIN
round (timing noise is strictly additive — the min is the best estimate of
the kernel's true cost, cf. python timeit) and the cross-round spread is
reported so a noisy host is visible instead of silently flipping
vs_baseline.

North-star extras (BASELINE.json): a FULL train step — forward + backward +
adam — on LLaMA-7B layer shapes (hidden 4096, ffn 11008, 32 heads, seq 2048,
bf16 compute / fp32 adam), reported as tokens/sec/chip and MFU against the
chip's peak bf16 matmul throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

import json
import os
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

REFERENCE_MS_PER_LAYER_PER_SAMPLE = 5.331

SMOKE = bool(os.environ.get("GALVATRON_BENCH_SMOKE"))

# GPT layer-forward parity config (the reference's measured layer)
HIDDEN, HEADS, SEQ = (512, 8, 256) if SMOKE else (4096, 32, 2048)
BATCH = 2 if SMOKE else 8
N_LO, N_HI = 1, 3
WARMUP, ITERS, ROUNDS = (1, 3, 2) if SMOKE else (3, 10, 5)

# LLaMA-7B layer shapes for the train-step metric
L7B_HIDDEN, L7B_FFN, L7B_HEADS, L7B_SEQ = (512, 1376, 8, 256) if SMOKE else (4096, 11008, 32, 2048)
# 2 layers (~405M params): fp32 master+adam states ~4.9GB + grads + activations
# fits the single (possibly shared) chip; per-token metrics are depth-invariant
L7B_LAYERS = 2
L7B_BATCH = 1 if SMOKE else 4

# peak dense bf16 matmul throughput per chip, FLOP/s
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


def _peak_flops():
    kind = jax.devices()[0].device_kind
    for k, v in PEAK_FLOPS_BY_KIND.items():
        if kind.lower().startswith(k.lower()):
            return v, kind
    return None, kind


def _sync(x):
    # NB: block_until_ready does not reliably block on the experimental axon
    # tunnel backend; a host transfer of a scalar does.
    return float(jnp.sum(jax.tree.leaves(x)[0].astype(jnp.float32)))


# ------------------------------------------------------- layer-forward parity
def build_stack(n_layers):
    from galvatron_tpu.models import base as M

    cfg = M.TransformerConfig(
        hidden_size=HIDDEN, num_heads=HEADS, num_layers=n_layers, vocab_size=256,
        max_seq_len=SEQ, norm_type="layernorm", activation="gelu",
        position_type="learned", compute_dtype=jnp.bfloat16,
        param_dtype=jnp.bfloat16,
    )
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, n_layers)]
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, SEQ, HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(SEQ), (BATCH, SEQ))

    def fwd(layers, x):
        for lp in layers:
            x = M.layer_forward(lp, x, positions, cfg)
        # reduce to a scalar so the timing sync transfers O(1) bytes
        return jnp.sum(x.astype(jnp.float32))

    return jax.jit(fwd), layers, x


def time_stack(fwd, layers, x):
    for _ in range(WARMUP):
        float(fwd(layers, x))
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        float(fwd(layers, x))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def layer_fwd_metric():
    f_lo, l_lo, x_lo = build_stack(N_LO)
    f_hi, l_hi, x_hi = build_stack(N_HI)
    per_round = []
    for _ in range(ROUNDS):
        t_lo = time_stack(f_lo, l_lo, x_lo)
        t_hi = time_stack(f_hi, l_hi, x_hi)
        per_round.append((t_hi - t_lo) / (N_HI - N_LO) / BATCH * 1e3)
    best = float(np.min(per_round))
    med = float(np.median(per_round))
    spread = float((np.max(per_round) - np.min(per_round)) / max(med, 1e-9))
    return best, med, spread


# ------------------------------------------------- LLaMA-7B-layer train step
# steps executed back-to-back inside one jitted scan per timed call: the
# ~70 ms axon-tunnel dispatch latency amortises away and the measurement is
# the DEVICE step time, as in real training where dispatch runs ahead of the
# device (same differencing rationale as layer_fwd_metric; round 3 measured
# single synced calls and under-reported MFU 0.38 vs the true ~0.6)
STEPS_PER_CALL = 1 if SMOKE else 8


def train_step_metric():
    import optax

    from galvatron_tpu.models import base as M

    cfg = M.TransformerConfig(
        hidden_size=L7B_HIDDEN, num_heads=L7B_HEADS, num_layers=L7B_LAYERS,
        ffn_hidden=L7B_FFN, vocab_size=256, max_seq_len=L7B_SEQ,
        norm_type="rmsnorm", activation="swiglu", position_type="rope",
        qkv_bias=False, mlp_bias=False, out_bias=False,
        compute_dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    key = jax.random.PRNGKey(0)
    layers = [M.init_layer_params(k, cfg) for k in jax.random.split(key, L7B_LAYERS)]
    x = jax.random.normal(jax.random.PRNGKey(1), (L7B_BATCH, L7B_SEQ, L7B_HIDDEN), jnp.bfloat16)
    positions = jnp.broadcast_to(jnp.arange(L7B_SEQ), (L7B_BATCH, L7B_SEQ))
    tx = optax.adam(1e-4)
    opt_state = tx.init(layers)

    def loss_fn(layers, x):
        y = x
        for lp in layers:
            y = M.layer_forward(lp, y, positions, cfg)
        return jnp.mean(y.astype(jnp.float32) ** 2)

    def one_step(carry, _):
        layers, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(layers, x)
        updates, opt_state = tx.update(grads, opt_state, layers)
        layers = optax.apply_updates(layers, updates)
        return (layers, opt_state), loss

    # donate params + opt state: without donation the updated copies double
    # the resident model states and OOM the chip
    @partial(jax.jit, donate_argnums=(0,))
    def run_steps(carry):
        carry, losses = jax.lax.scan(one_step, carry, None, length=STEPS_PER_CALL)
        return carry, losses[-1]

    carry = (layers, opt_state)
    # warmup (compile + first run)
    carry, loss = run_steps(carry)
    _sync(loss)
    rounds = []
    for _ in range(ROUNDS):
        times = []
        for _ in range(max(ITERS // 2, 2)):
            t0 = time.perf_counter()
            carry, loss = run_steps(carry)
            _sync(loss)
            times.append(time.perf_counter() - t0)
        rounds.append(float(np.median(times)) / STEPS_PER_CALL)
    step_s = float(np.min(rounds))
    layers = carry[0]

    # component breakdown (VERDICT r3: record where the step time goes);
    # guarded — a tunnel compile failure OR HANG must not lose the headline
    # metric (the axon remote-compile endpoint has been observed to wedge)
    breakdown = {}
    import signal

    def _alarm(signum, frame):
        raise TimeoutError("breakdown compile/run exceeded budget")

    old_handler = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(180)
    try:
        K = STEPS_PER_CALL

        @jax.jit
        def fwd_k(xx):
            def body(c, _):
                y = c
                for lp in layers:
                    y = M.layer_forward(lp, y, positions, cfg)
                return 0.5 * c + 0.5 * y, ()
            out, _ = jax.lax.scan(body, xx, None, length=K)
            return out

        grads = jax.tree.map(jnp.zeros_like, layers)

        @jax.jit
        def adam_k(carry):
            def body(c, _):
                ls, st = c
                updates, st = tx.update(grads, st, ls)
                return (optax.apply_updates(ls, updates), st), ()
            out, _ = jax.lax.scan(body, carry, None, length=K)
            return out

        def _time(fn, *a):
            _sync(fn(*a))
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                _sync(fn(*a))
                ts.append(time.perf_counter() - t0)
            return float(np.min(ts)) / K

        t_fwd = _time(fwd_k, x)
        t_adam = _time(adam_k, (layers, opt_state))
        breakdown = {
            "fwd_ms": round(t_fwd * 1e3, 2),
            "adam_ms": round(t_adam * 1e3, 2),
            "bwd_plus_overhead_ms": round((step_s - t_fwd - t_adam) * 1e3, 2),
        }
    except Exception as e:  # pragma: no cover - tunnel flakiness
        breakdown = {"error": str(e)[:120]}
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old_handler)

    tokens = L7B_BATCH * L7B_SEQ
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(layers))
    # model FLOPs: 6 * params * tokens (fwd 2x + bwd 4x) + causal attention
    # 12 * L * S * H * tokens * 0.5 (PaLM appendix-B convention)
    flops = 6.0 * n_params * tokens + 12 * L7B_LAYERS * L7B_SEQ * L7B_HIDDEN * tokens * 0.5
    peak, kind = _peak_flops()
    tokens_per_sec = tokens / step_s
    mfu = (flops / step_s / peak) if peak else None
    return {
        "config": "llama7b_layer_stack%d_seq%d_bf16_adam" % (L7B_LAYERS, L7B_SEQ),
        "step_ms": round(step_s * 1e3, 3),
        "steps_per_call": STEPS_PER_CALL,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "device_kind": kind,
        "params": n_params,
        "breakdown": breakdown,
    }


def main():
    best, med, spread = layer_fwd_metric()
    extra = {
        "layer_fwd_ms_median": round(med, 4),
        "layer_fwd_round_spread": round(spread, 4),
        "rounds": ROUNDS,
        "train_step": train_step_metric(),
    }
    metric = (
        "SMOKE_gpt_layer_fwd_ms_h%d_s%d" % (HIDDEN, SEQ)
        if SMOKE else "gpt_layer_fwd_ms_per_layer_per_sample_h4096_s2048_bf16"
    )
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(best, 4),
                "unit": "ms",
                # the baseline is the full-shape reference number; a smoke run
                # measures different shapes and must not claim a ratio
                "vs_baseline": None if SMOKE else round(
                    REFERENCE_MS_PER_LAYER_PER_SAMPLE / best, 4
                ),
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
