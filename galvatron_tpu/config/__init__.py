from galvatron_tpu.config.strategy import HybridParallelConfig, LayerStrategy

__all__ = ["HybridParallelConfig", "LayerStrategy"]
