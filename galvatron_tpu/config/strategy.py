"""Layer-wise hybrid-parallel strategy schema.

TPU-native re-design of the reference's hybrid-parallel config layer
(reference: galvatron/core/runtime/hybrid_parallel_config.py:17-158 and
galvatron/utils/config_utils.py:22-57). The on-disk JSON format is
load/save-compatible with the reference (`pp_deg`, `tp_sizes_enc`,
`tp_consecutive_flags`, `dp_types_enc`, `use_sp`, `checkpoint`, `pp_division`,
`vtp`/`vsp`/`vcp`, `global_bsz`, `chunks`, `pipeline_type`, `default_dp_type`,
`embed_sdp`), so searched configs are interchangeable — but the in-memory
representation targets a `jax.sharding.Mesh`, not NCCL rank lists.

Semantics (mirroring the reference):
- ``tp``       per-layer tensor-parallel degree (Megatron-style).
- ``sp``       per-layer flag: 1 => the tp axis is repurposed as a
               DeepSpeed-Ulysses sequence axis (all-to-all attention) for this
               layer (reference hybrid_parallel_config.py:261-266).
- ``cp``       per-layer context-parallel (ring attention) degree.
- ``fsdp``     per-layer flag: 1 => ZeRO-3 (parameter sharding) for this layer;
               0 => ``default_dp_type`` (ddp / zero2 / zero3)
               (reference runtime/parallel.py:61-62,107-111).
- ``checkpoint`` per-layer activation-rematerialisation flag.
- ``tp_consec``  rank-layout choice; on TPU this selects whether the tp role is
               assigned to the *minor* (fast, contiguous-ICI) or *major* mesh
               sub-axes (reference comm_groups.py:71-143; see parallel/mesh.py).
- ``vocab_tp/vocab_sp/vocab_cp`` separate degrees for embedding/cls layers.
- ``embed_sdp``  ZeRO-3 for embedding/cls (reference arguments.py `--embed_sdp`).

The per-layer data-parallel degree is derived:
``dp = world_size // pp // tp // cp`` (sp shares the tp sub-axes).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from galvatron_tpu.utils.jsonio import read_json_config, write_json_config
from galvatron_tpu.utils.strategy_utils import array2str, str2array

DP_TYPES = ("ddp", "zero2", "zero3")
PIPELINE_TYPES = ("gpipe", "pipedream_flush")


@dataclass(frozen=True)
class LayerStrategy:
    """Parallel strategy for a single transformer layer."""

    tp: int = 1
    cp: int = 1
    sp: int = 0
    fsdp: int = 0
    checkpoint: int = 0
    tp_consec: int = 1

    def __post_init__(self):
        if self.tp < 1 or self.cp < 1:
            raise ValueError("tp/cp degrees must be >= 1, got tp=%d cp=%d" % (self.tp, self.cp))
        if self.sp not in (0, 1) or self.fsdp not in (0, 1):
            raise ValueError("sp/fsdp must be 0/1")

    @property
    def seq_shard_degree(self) -> int:
        """How many ways the sequence dim is sharded inside this layer's
        attention: cp always shards the sequence; ulysses-sp shards it by tp."""
        return self.cp * (self.tp if self.sp else 1)


def even_pp_division(total_layers: int, pp: int) -> List[int]:
    """Default layer division across pipeline stages (reference
    hybrid_parallel_config.py:86-89: equal with remainder on last stage)."""
    avg = total_layers // pp
    return [avg] * (pp - 1) + [total_layers - avg * (pp - 1)]


def pp_stage_of_layer(pp_division: Sequence[int]) -> List[int]:
    """`pp_ranks_enc` in the reference (hybrid_parallel_config.py:9-14)."""
    out: List[int] = []
    for stage, n in enumerate(pp_division):
        out += [stage] * n
    return out


@dataclass
class HybridParallelConfig:
    """Whole-model layer-wise hybrid-parallel configuration."""

    world_size: int
    pp: int
    layers: List[LayerStrategy]
    global_bsz: int = 8
    chunks: int = 1
    pp_division: Optional[List[int]] = None
    pipeline_type: str = "gpipe"
    default_dp_type: str = "ddp"
    vocab_tp: int = 1
    vocab_sp: int = 0
    vocab_cp: int = 1
    embed_sdp: int = 0
    mixed_precision: str = "bf16"
    sequence_parallel: bool = True  # Megatron-SP activation sharding when tp>1
    cp_mode: str = "zigzag"  # ring | zigzag — zigzag applies the balanced data
    # layout as a global sequence permutation in the input pipeline
    # (reference --cp_mode, runtime/arguments.py; redistribute.py:8-44)

    def __post_init__(self):
        if self.pp_division is None:
            self.pp_division = even_pp_division(len(self.layers), self.pp)
        self.validate()

    # ------------------------------------------------------------------ checks
    def validate(self):
        if self.default_dp_type not in DP_TYPES:
            raise ValueError("default_dp_type must be one of %s" % (DP_TYPES,))
        if self.pipeline_type not in PIPELINE_TYPES:
            raise ValueError("pipeline_type must be one of %s" % (PIPELINE_TYPES,))
        if self.world_size % self.pp != 0:
            raise ValueError("world_size %d not divisible by pp %d" % (self.world_size, self.pp))
        if len(self.pp_division) != self.pp or sum(self.pp_division) != len(self.layers):
            raise ValueError(
                "pp_division %s inconsistent with pp=%d, %d layers"
                % (self.pp_division, self.pp, len(self.layers))
            )
        per_stage = self.world_size // self.pp
        for i, s in enumerate(self.layers):
            if per_stage % (s.tp * s.cp) != 0:
                raise ValueError(
                    "layer %d: tp*cp=%d does not divide per-stage devices %d"
                    % (i, s.tp * s.cp, per_stage)
                )
        if per_stage % (self.vocab_tp * self.vocab_cp) != 0:
            raise ValueError("vocab_tp*vocab_cp must divide per-stage devices")
        # batch must divide every layer's dp degree (incl. the vocab layers):
        # the batch dim is sharded over each layer's dp axes (cf. reference
        # assert at hybrid_parallel_config.py:93-96, done there via min_tp)
        max_dp = max(
            [per_stage // (s.tp * s.cp) for s in self.layers]
            + [per_stage // (self.vocab_tp * self.vocab_cp)]
        )
        if self.global_bsz % max_dp != 0:
            raise ValueError(
                "global_bsz %d must be a multiple of the largest layer dp degree %d"
                % (self.global_bsz, max_dp)
            )
        # Under the 1F1B schedule the sharded unit is the MICROBATCH, and it
        # must shard EVENLY over every LAYER's dp degree: an uneven batch
        # shard makes GSPMD pad and reshard with collective-permutes, which
        # the schedule's stage-divergent branches cannot host (see
        # parallel/pipeline_1f1b.py divergence-safety invariant). The vocab
        # layers are exempt — embed/head run in the schedule's uniform
        # (non-branch) region, where padding reshards are safe — as are pp=1
        # and the gpipe scan (uniform code throughout).
        if self.pp > 1 and self.pipeline_type == "pipedream_flush":
            if self.global_bsz % self.chunks != 0:
                raise ValueError(
                    "global_bsz %d must divide into %d chunks" % (self.global_bsz, self.chunks)
                )
            mb = self.global_bsz // self.chunks
            max_layer_dp = max(per_stage // (s.tp * s.cp) for s in self.layers)
            if mb % max_layer_dp != 0:
                raise ValueError(
                    "1F1B microbatch size %d (global_bsz %d / chunks %d) must be "
                    "a multiple of the largest layer dp degree %d"
                    % (mb, self.global_bsz, self.chunks, max_layer_dp)
                )
        if self.cp_mode not in ("ring", "zigzag"):
            raise ValueError("cp_mode must be 'ring' or 'zigzag', got %r" % (self.cp_mode,))

    # -------------------------------------------------------------- properties
    @property
    def num_layers(self) -> int:
        return len(self.layers)

    @property
    def per_stage_devices(self) -> int:
        return self.world_size // self.pp

    def dp(self, layer_idx: int) -> int:
        s = self.layers[layer_idx]
        return self.per_stage_devices // (s.tp * s.cp)

    @property
    def stage_of_layer(self) -> List[int]:
        return pp_stage_of_layer(self.pp_division)

    def layers_of_stage(self, stage: int) -> List[int]:
        lo = sum(self.pp_division[:stage])
        return list(range(lo, lo + self.pp_division[stage]))

    def dp_type(self, layer_idx: int) -> str:
        return "zero3" if self.layers[layer_idx].fsdp else self.default_dp_type

    @property
    def max_cp(self) -> int:
        return max([s.cp for s in self.layers] + [self.vocab_cp])

    @property
    def microbatch_size(self) -> int:
        if self.global_bsz % self.chunks != 0:
            raise ValueError("global_bsz must divide evenly into chunks (pad upstream)")
        return self.global_bsz // self.chunks

    # ------------------------------------------------------------ constructors
    @classmethod
    def uniform(
        cls,
        world_size: int,
        num_layers: int,
        pp: int = 1,
        tp: int = 1,
        cp: int = 1,
        sp: int = 0,
        sdp: int = 0,
        checkpoint: int = 0,
        **kw,
    ) -> "HybridParallelConfig":
        """GLOBAL-mode config: one strategy for every layer (reference
        hybrid_parallel_config.py:27-42)."""
        layer = LayerStrategy(tp=tp, cp=cp, sp=sp, fsdp=sdp, checkpoint=checkpoint)
        return cls(world_size=world_size, pp=pp, layers=[layer] * num_layers, **kw)

    @classmethod
    def from_json(cls, path_or_dict, world_size: int, **overrides) -> "HybridParallelConfig":
        """Load a searched strategy JSON in the reference's on-disk format
        (reference utils/config_utils.py:22-46)."""
        cfg = path_or_dict if isinstance(path_or_dict, dict) else read_json_config(path_or_dict)
        tp_sizes = str2array(cfg["tp_sizes_enc"])
        n = len(tp_sizes)
        cp_sizes = str2array(cfg.get("cp_sizes_enc", array2str([1] * n)))
        consec = str2array(cfg.get("tp_consecutive_flags", array2str([1] * n)))
        dp_types = str2array(cfg["dp_types_enc"])
        use_sp = str2array(cfg.get("use_sp", array2str([0] * n)))
        ckpt = str2array(cfg.get("checkpoint", array2str([0] * n)))
        layers = [
            LayerStrategy(
                tp=tp_sizes[i], cp=cp_sizes[i], sp=use_sp[i], fsdp=dp_types[i],
                checkpoint=ckpt[i], tp_consec=consec[i],
            )
            for i in range(n)
        ]
        kw = dict(
            world_size=world_size,
            pp=cfg["pp_deg"],
            layers=layers,
            global_bsz=cfg.get("global_bsz", 8),
            chunks=cfg.get("chunks", 1),
            pp_division=str2array(cfg["pp_division"]) if "pp_division" in cfg else None,
            pipeline_type=cfg.get("pipeline_type", "gpipe"),
            default_dp_type=cfg.get("default_dp_type", "ddp"),
            vocab_tp=cfg.get("vtp", 1),
            vocab_sp=cfg.get("vsp", 0),
            vocab_cp=cfg.get("vcp", 1),
            embed_sdp=cfg.get("embed_sdp", 0),
            cp_mode=cfg.get("cp_mode", "zigzag"),
        )
        kw.update(overrides)
        return cls(**kw)

    # ----------------------------------------------------------- serialization
    def to_json_dict(self) -> dict:
        """Reference-compatible JSON dict (utils/config_utils.py:48-57 plus the
        extra keys train_dist reads back)."""
        return {
            "pp_deg": self.pp,
            "tp_sizes_enc": array2str([s.tp for s in self.layers]),
            "tp_consecutive_flags": array2str([s.tp_consec for s in self.layers]),
            "cp_sizes_enc": array2str([s.cp for s in self.layers]),
            "dp_types_enc": array2str([s.fsdp for s in self.layers]),
            "use_sp": array2str([s.sp for s in self.layers]),
            "checkpoint": array2str([s.checkpoint for s in self.layers]),
            "global_bsz": self.global_bsz,
            "chunks": self.chunks,
            "pp_division": array2str(self.pp_division),
            "pipeline_type": self.pipeline_type,
            "default_dp_type": self.default_dp_type,
            "vtp": self.vocab_tp,
            "vsp": self.vocab_sp,
            "vcp": self.vocab_cp,
            "embed_sdp": self.embed_sdp,
            "cp_mode": self.cp_mode,
        }

    def save(self, path: str):
        write_json_config(self.to_json_dict(), path)

    # For checkpoint-resume strategy equality assertion (reference
    # hybrid_parallel_config.py:112-124).
    def assert_equal(self, other: "HybridParallelConfig"):
        a, b = self.to_json_dict(), other.to_json_dict()
        if a != b:
            diff = {k: (a[k], b[k]) for k in a if a.get(k) != b.get(k)}
            raise AssertionError("Hybrid parallel configs are not equal: %s" % diff)

    def describe(self) -> str:
        lines = ["pp=%d world=%d bsz=%d chunks=%d pipeline=%s default_dp=%s" % (
            self.pp, self.world_size, self.global_bsz, self.chunks,
            self.pipeline_type, self.default_dp_type)]
        for i, s in enumerate(self.layers):
            lines.append(
                "  layer %2d: stage %d tp=%d%s cp=%d dp=%d(%s)%s%s"
                % (
                    i, self.stage_of_layer[i], s.tp,
                    "(ulysses-sp)" if s.sp else "",
                    s.cp, self.dp(i), self.dp_type(i),
                    " ckpt" if s.checkpoint else "",
                    "" if s.tp_consec else " nonconsec",
                )
            )
        lines.append(
            "  vocab: tp=%d sp=%d cp=%d embed_sdp=%d" % (self.vocab_tp, self.vocab_sp, self.vocab_cp, self.embed_sdp)
        )
        return "\n".join(lines)
