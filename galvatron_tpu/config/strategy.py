"""Layer-wise hybrid-parallel strategy schema.

TPU-native re-design of the reference's hybrid-parallel config layer
(reference: galvatron/core/runtime/hybrid_parallel_config.py:17-158 and
galvatron/utils/config_utils.py:22-57). The on-disk JSON format is
load/save-compatible with the reference (`pp_deg`, `tp_sizes_enc`,
`tp_consecutive_flags`, `dp_types_enc`, `use_sp`, `checkpoint`, `pp_division`,
`vtp`/`vsp`/`vcp`, `global_bsz`, `chunks`, `pipeline_type`, `default_dp_type`,
`embed_sdp`), so searched configs are interchangeable — but the in-memory
representation targets a `jax.sharding.Mesh`, not NCCL rank lists.

Semantics (mirroring the reference):
- ``tp``       per-layer tensor-parallel degree (Megatron-style).
- ``sp``       per-layer flag: 1 => the tp axis is repurposed as a
               DeepSpeed-Ulysses sequence axis (all-to-all attention) for this
               layer (reference hybrid_parallel_config.py:261-266).
- ``cp``       per-layer context-parallel (ring attention) degree.
- ``fsdp``     per-layer flag: 1 => ZeRO-3 (parameter sharding) for this layer;
               0 => ``default_dp_type`` (ddp / zero2 / zero3)
               (reference runtime/parallel.py:61-62,107-111).
- ``checkpoint`` per-layer activation-rematerialisation flag.
- ``tp_consec``  rank-layout choice; on TPU this selects whether the tp role is
               assigned to the *minor* (fast, contiguous-ICI) or *major* mesh
               sub-axes (reference comm_groups.py:71-143; see parallel/mesh.py).
- ``vocab_tp/vocab_sp/vocab_cp`` separate degrees for embedding/cls layers.
- ``embed_sdp``  ZeRO-3 for embedding/cls (reference arguments.py `--embed_sdp`).

The per-layer data-parallel degree is derived:
``dp = world_size // pp // tp // cp`` (sp shares the tp sub-axes).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from galvatron_tpu.utils.jsonio import read_json_config, write_json_config
from galvatron_tpu.utils.strategy_utils import array2str, str2array

DP_TYPES = ("ddp", "zero2", "zero3")
PIPELINE_TYPES = ("gpipe", "pipedream_flush")
CP_MODES = ("ring", "zigzag")
# jax.checkpoint policy applied to layers with checkpoint=1 (models/base.py
# _remat): "full" is jax.checkpoint's default (save nothing, remat
# everything — the reference's --checkpoint semantics), "none" disables the
# layer's checkpoint flag entirely, the *_saveable names select the
# matching jax.checkpoint_policies member (dots_saveable keeps matmul
# outputs resident and remats only the cheap elementwise chains).
# A SERIALIZED per-layer strategy field since the remat search dimension
# (LayerStrategy.remat_policy; on-disk key "remat_policy"): the search
# engine chooses the policy per layer under the memory budget, exactly like
# grad_comm_dtype. The global --remat_policy CLI flag survives only as a
# default-override (HybridParallelConfig.remat_policy): it fills layers
# whose JSON does not serialize the key; serialized per-layer values always
# win, and a non-default flag shadowed by them warns GLS103.
REMAT_POLICIES = ("none", "full", "dots_saveable", "nothing_saveable")
# TP-collective execution path for layer runs (models/base.run_layers —
# parallel/tp_shard_map.py): "gspmd" leaves the collectives to the
# compiler (they serialize with the matmuls), "shard_map" hand-writes them
# (visible/schedulable, undecomposed), "overlap" decomposes them into
# ppermute-pipelined chunked matmuls (ring all-gather / reduce-scatter
# overlapped with compute, the ring_attention idiom on the dense kernels).
# A runtime knob: NOT serialized into the strategy JSON.
TP_COMM_MODES = ("gspmd", "shard_map", "overlap")
# Wire precision of a collective's payload (parallel/quant_collectives.py):
# "none" keeps the exact full-precision collective, "bf16" is a passthrough
# cast, int8/fp8_e4m3 are blockwise-quantized (per-block absmax scales,
# block size = comm_quant_block). grad_comm_dtype (DP/ZeRO gradient sync)
# and param_comm_dtype (ZeRO-3 weight all-gather) are SERIALIZED per-layer
# strategy fields — the search engine chooses them per layer (ROADMAP item
# 2) — unlike tp_comm_quant, which quantizes the PR-8 TP ring payloads and
# stays a runtime knob like tp_comm_mode.
COMM_DTYPES = ("none", "bf16", "int8", "fp8_e4m3")

# The reference-compatible on-disk schema (from_json/to_json_dict). Split by
# shape so the schema linter can check lengths/types uniformly.
PER_LAYER_KEYS = (
    "tp_sizes_enc", "tp_consecutive_flags", "cp_sizes_enc", "dp_types_enc",
    "use_sp", "checkpoint",
)
# per-layer comma-separated STRING enums, not int lists; each key validates
# against its own allowed-value set (schema_diagnostics)
PER_LAYER_STR_ENUMS = {
    "grad_comm_dtype": COMM_DTYPES,
    "param_comm_dtype": COMM_DTYPES,
    "remat_policy": REMAT_POLICIES,
}
PER_LAYER_STR_KEYS = tuple(PER_LAYER_STR_ENUMS)
SCALAR_KEYS = (
    "pp_deg", "global_bsz", "chunks", "pp_division", "pipeline_type",
    "default_dp_type", "vtp", "vsp", "vcp", "embed_sdp", "cp_mode",
    "comm_quant_block", "serve_max_concurrency", "serve_page_size",
    "serve_p99_ttft_ms", "serve_max_pending",
)
KNOWN_STRATEGY_KEYS = frozenset(PER_LAYER_KEYS + PER_LAYER_STR_KEYS + SCALAR_KEYS)
REQUIRED_STRATEGY_KEYS = ("pp_deg", "tp_sizes_enc", "dp_types_enc")


def str2strlist(v) -> List[str]:
    """'none,int8,int8' -> ['none', 'int8', 'int8'] (the string-enum
    analogue of utils.strategy_utils.str2array)."""
    if isinstance(v, (list, tuple)):
        return [str(x).strip() for x in v]
    return [s.strip() for s in str(v).split(",") if s.strip()]


def strlist2str(vals: Sequence[str]) -> str:
    return ",".join(str(v) for v in vals)


def schema_diagnostics(cfg: dict) -> list:
    """Raw strategy-dict checks shared by `from_json` (which raises on any
    error) and the strategy linter (which reports them all): unknown keys
    with did-you-mean hints (GLS001), missing required keys (GLS005),
    per-layer array length disagreements (GLS006), out-of-range enum values
    and flags (GLS005). Returns a list of Diagnostics."""
    from galvatron_tpu.analysis import diagnostics as D

    out = []
    for k in sorted(cfg):
        if k not in KNOWN_STRATEGY_KEYS:
            out.append(D.make(
                "GLS001", "unknown strategy key %r" % k, key=k,
                hint=D.did_you_mean(k, KNOWN_STRATEGY_KEYS),
            ))
    for k in REQUIRED_STRATEGY_KEYS:
        if k not in cfg:
            out.append(D.make("GLS005", "missing required key %r" % k, key=k))
    arrays = {}
    for k in PER_LAYER_KEYS:
        if k in cfg:
            try:
                arrays[k] = str2array(cfg[k])
            except ValueError:
                out.append(D.make(
                    "GLS005", "key %r is not a comma-separated int list: %r"
                    % (k, cfg[k]), key=k,
                ))
    str_arrays = {}
    for k, allowed in PER_LAYER_STR_ENUMS.items():
        if k in cfg:
            str_arrays[k] = str2strlist(cfg[k])
            for i, v in enumerate(str_arrays[k]):
                if v not in allowed:
                    out.append(D.make(
                        "GLS005", "%s[%d]=%r must be one of %s"
                        % (k, i, v, allowed), key=k, layer=i,
                        hint=D.did_you_mean(v, allowed),
                    ))
    # a serialized remat_policy of all-"full" carries no information: "full"
    # is what checkpoint=1 already means (and the from_json default), so the
    # key only earns its place when some layer deviates
    rp_vals = str_arrays.get("remat_policy")
    if rp_vals and all(v == "full" for v in rp_vals):
        out.append(D.make(
            "GLS103", "serialized remat_policy is 'full' on every layer — it "
            "duplicates the checkpoint flag (checkpoint=1 already remats "
            "fully); drop the key", key="remat_policy",
        ))
    if "tp_sizes_enc" in arrays:
        n = len(arrays["tp_sizes_enc"])
        for k, arr in list(arrays.items()) + list(str_arrays.items()):
            if len(arr) != n:
                out.append(D.make(
                    "GLS006", "%r has %d entries but 'tp_sizes_enc' has %d"
                    % (k, len(arr), n), key=k,
                ))
    cqb = cfg.get("comm_quant_block")
    if cqb is not None and (not isinstance(cqb, int) or cqb < 1):
        out.append(D.make(
            "GLS005", "comm_quant_block must be a positive int, got %r" % (cqb,),
            key="comm_quant_block",
        ))
    for k in ("serve_max_concurrency", "serve_page_size", "serve_max_pending"):
        sv = cfg.get(k)
        if sv is not None and (not isinstance(sv, int) or sv < 0):
            out.append(D.make(
                "GLS005", "%s must be a non-negative int, got %r" % (k, sv),
                key=k,
            ))
    ttft = cfg.get("serve_p99_ttft_ms")
    if ttft is not None and (not isinstance(ttft, (int, float))
                             or isinstance(ttft, bool) or ttft < 0):
        out.append(D.make(
            "GLS005", "serve_p99_ttft_ms must be a non-negative number, "
            "got %r" % (ttft,), key="serve_p99_ttft_ms",
        ))
    for k, lo in (("tp_sizes_enc", 1), ("cp_sizes_enc", 1)):
        for i, v in enumerate(arrays.get(k, [])):
            if v < lo:
                out.append(D.make(
                    "GLS005", "%s[%d]=%d must be >= %d" % (k, i, v, lo),
                    key=k, layer=i,
                ))
    for k in ("dp_types_enc", "use_sp", "checkpoint", "tp_consecutive_flags"):
        for i, v in enumerate(arrays.get(k, [])):
            if v not in (0, 1):
                out.append(D.make(
                    "GLS005", "%s[%d]=%d must be 0 or 1" % (k, i, v),
                    key=k, layer=i,
                ))
    for k, allowed in (
        ("pipeline_type", PIPELINE_TYPES),
        ("default_dp_type", DP_TYPES),
        ("cp_mode", CP_MODES),
    ):
        v = cfg.get(k)
        if v is not None and v not in allowed:
            out.append(D.make(
                "GLS005", "%s must be one of %s, got %r" % (k, allowed, v),
                key=k, hint=D.did_you_mean(str(v), allowed),
            ))
    return out


@dataclass(frozen=True)
class LayerStrategy:
    """Parallel strategy for a single transformer layer."""

    tp: int = 1
    cp: int = 1
    sp: int = 0
    fsdp: int = 0
    checkpoint: int = 0
    tp_consec: int = 1
    # wire precision of this layer's collectives (COMM_DTYPES; serialized —
    # the search engine's comm-precision axis chooses these per layer):
    grad_comm_dtype: str = "none"   # DP/ZeRO gradient sync payload
    param_comm_dtype: str = "none"  # ZeRO-3 weight all-gather payload
    # jax.checkpoint policy this layer remats under when checkpoint=1
    # (REMAT_POLICIES; serialized — the search engine's remat axis chooses
    # the recompute-vs-memory point per layer). Inert on checkpoint=0
    # layers; "none" disables remat for this layer even with checkpoint=1.
    remat_policy: str = "full"

    def __post_init__(self):
        if self.tp < 1 or self.cp < 1:
            raise ValueError("tp/cp degrees must be >= 1, got tp=%d cp=%d" % (self.tp, self.cp))
        if self.sp not in (0, 1) or self.fsdp not in (0, 1):
            raise ValueError("sp/fsdp must be 0/1")
        for k in ("grad_comm_dtype", "param_comm_dtype"):
            if getattr(self, k) not in COMM_DTYPES:
                raise ValueError("%s must be one of %s, got %r"
                                 % (k, COMM_DTYPES, getattr(self, k)))
        if self.remat_policy not in REMAT_POLICIES:
            raise ValueError("remat_policy must be one of %s, got %r"
                             % (REMAT_POLICIES, self.remat_policy))

    @property
    def effective_remat_policy(self) -> str:
        """The jax.checkpoint policy this layer actually executes under:
        checkpoint=0 layers never wrap (their serialized policy is inert),
        and checkpoint=1 with remat_policy='none' opts the layer out. The
        runtime (models/base.run_layers), the run splitter (layer_runs) and
        the cost models all key on THIS, so inert differences never split a
        scan run or fork a cost-model cache entry."""
        return self.remat_policy if self.checkpoint else "none"

    @property
    def seq_shard_degree(self) -> int:
        """How many ways the sequence dim is sharded inside this layer's
        attention: cp always shards the sequence; ulysses-sp shards it by tp."""
        return self.cp * (self.tp if self.sp else 1)


@dataclass(frozen=True)
class LayerRun:
    """A maximal run of consecutive layers that compile to ONE program: every
    layer in [start, stop) has the same mesh-axis assignment (LayerAxes),
    the same effective rematerialization policy (checkpoint flag + per-layer
    remat_policy), and lives on the same pipeline stage. The runtime
    executes a run of length >= 2 as a single `jax.lax.scan` over
    weight-stacked params (models/base.py run_layers), so trace/compile
    cost is per-RUN, not per-layer."""

    start: int
    stop: int  # exclusive
    strategy: LayerStrategy  # the run's shared strategy (first layer's)

    @property
    def length(self) -> int:
        return self.stop - self.start

    @property
    def layer_indices(self) -> range:
        return range(self.start, self.stop)


def layer_runs(config: "HybridParallelConfig") -> List[LayerRun]:
    """Partition ``config.layers`` into maximal scannable runs.

    Layers are grouped by the *realised* strategy — the LayerAxes their
    LayerStrategy maps to on this mesh — not by raw LayerStrategy equality,
    so inert flag differences (e.g. ``sp`` or ``tp_consec`` at tp=1, or a
    remat_policy on a checkpoint=0 layer) do not split a run. The effective
    remat policy partitions (checkpoint flag + remat_policy — each policy
    wraps the scanned body in a different jax.checkpoint program) and runs
    never span a pipeline-stage boundary. Searched strategies are
    piecewise-uniform in practice (PAPER.md), so this typically yields a
    handful of runs regardless of depth."""
    # lazy: parallel.mesh imports this module at top level
    from galvatron_tpu.parallel.mesh import layer_axes

    stage_of = config.stage_of_layer
    out: List[LayerRun] = []
    prev_key = None
    for i in range(config.num_layers):
        key = (layer_axes(config, i),
               config.layers[i].effective_remat_policy, stage_of[i])
        if out and key == prev_key:
            out[-1] = dataclasses.replace(out[-1], stop=i + 1)
        else:
            out.append(LayerRun(start=i, stop=i + 1, strategy=config.layers[i]))
        prev_key = key
    return out


def even_pp_division(total_layers: int, pp: int) -> List[int]:
    """Default layer division across pipeline stages (reference
    hybrid_parallel_config.py:86-89: equal with remainder on last stage)."""
    avg = total_layers // pp
    return [avg] * (pp - 1) + [total_layers - avg * (pp - 1)]


def pp_stage_of_layer(pp_division: Sequence[int]) -> List[int]:
    """`pp_ranks_enc` in the reference (hybrid_parallel_config.py:9-14)."""
    out: List[int] = []
    for stage, n in enumerate(pp_division):
        out += [stage] * n
    return out


@dataclass
class HybridParallelConfig:
    """Whole-model layer-wise hybrid-parallel configuration."""

    world_size: int
    pp: int
    layers: List[LayerStrategy]
    global_bsz: int = 8
    chunks: int = 1
    pp_division: Optional[List[int]] = None
    pipeline_type: str = "gpipe"
    default_dp_type: str = "ddp"
    vocab_tp: int = 1
    vocab_sp: int = 0
    vocab_cp: int = 1
    embed_sdp: int = 0
    mixed_precision: str = "bf16"
    sequence_parallel: bool = True  # Megatron-SP activation sharding when tp>1
    cp_mode: str = "zigzag"  # ring | zigzag — zigzag applies the balanced data
    # layout as a global sequence permutation in the input pipeline
    # (reference --cp_mode, runtime/arguments.py; redistribute.py:8-44)
    # Runtime execution knobs (like mixed_precision/sequence_parallel, these
    # are NOT part of the searched on-disk strategy schema):
    scan_layers: bool = True  # stack same-strategy layer runs into lax.scan
    # (depth-constant trace/compile cost); False = unroll every layer
    # Global remat default-override (REMAT_POLICIES). PRECEDENCE RULE: the
    # per-layer LayerStrategy.remat_policy is authoritative at runtime; this
    # field only FILLS layers at construction — uniform() stamps it on every
    # layer, from_json uses it for JSONs that do not serialize the
    # "remat_policy" key. A non-default value shadowed by serialized
    # per-layer policies is inert and warns GLS103 (strategy_lint).
    remat_policy: str = "full"
    tp_comm_mode: str = "gspmd"  # TP_COMM_MODES: TP-collective execution path
    tp_comm_quant: str = "none"  # COMM_DTYPES: wire precision of the manual
    # TP ring payloads (parallel/tp_shard_map.py); requires a manual
    # tp_comm_mode — the compiler owns the gspmd collectives (GLS013).
    # Runtime knob like tp_comm_mode: NOT serialized.
    # Block size of the blockwise quantization (elements per absmax scale)
    # for every quantized collective. Serialized (the cost models price the
    # scale overhead through it).
    comm_quant_block: int = 64
    # Serving knobs (serve/): a serve-objective search records the KV-cache
    # geometry its memory/latency pricing assumed — max concurrent request
    # slots and the context-bucket page size. 0 = not a serve strategy;
    # serialized only when set so train-objective JSONs are unchanged. In
    # train mode these knobs are inert (GLS103).
    serve_max_concurrency: int = 0
    serve_page_size: int = 0
    # Shedding knobs (serve/engine.ContinuousBatcher admission control): the
    # p99 TTFT bound the predicted-TTFT shedder enforces and the pending-
    # queue depth bound. 0 = unset; like the geometry knobs, serialized only
    # when set and inert (GLS103) in train mode.
    serve_p99_ttft_ms: float = 0.0
    serve_max_pending: int = 0

    def __post_init__(self):
        if self.pp_division is None:
            self.pp_division = even_pp_division(len(self.layers), self.pp)
        self.validate()

    # ------------------------------------------------------------------ checks
    def structural_diagnostics(self) -> list:
        """Every structural check as a Diagnostic list (GLS002-GLS005,
        GLS010), so the CLI linter and the constructing `validate()` report
        identically. Checks degrade gracefully: a failed prerequisite (e.g.
        world % pp) skips the checks whose arithmetic it would poison rather
        than raising mid-collection."""
        from galvatron_tpu.analysis import diagnostics as D

        out = []
        if self.default_dp_type not in DP_TYPES:
            out.append(D.make(
                "GLS005", "default_dp_type must be one of %s, got %r"
                % (DP_TYPES, self.default_dp_type), key="default_dp_type",
            ))
        if self.pipeline_type not in PIPELINE_TYPES:
            out.append(D.make(
                "GLS005", "pipeline_type must be one of %s, got %r"
                % (PIPELINE_TYPES, self.pipeline_type), key="pipeline_type",
            ))
        if self.cp_mode not in CP_MODES:
            out.append(D.make(
                "GLS005", "cp_mode must be one of %s, got %r"
                % (CP_MODES, self.cp_mode), key="cp_mode",
            ))
        if self.remat_policy not in REMAT_POLICIES:
            out.append(D.make(
                "GLS005", "remat_policy must be one of %s, got %r"
                % (REMAT_POLICIES, self.remat_policy), key="remat_policy",
                hint=D.did_you_mean(str(self.remat_policy), REMAT_POLICIES),
            ))
        if self.tp_comm_mode not in TP_COMM_MODES:
            out.append(D.make(
                "GLS005", "tp_comm_mode must be one of %s, got %r"
                % (TP_COMM_MODES, self.tp_comm_mode), key="tp_comm_mode",
                hint=D.did_you_mean(str(self.tp_comm_mode), TP_COMM_MODES),
            ))
        if self.tp_comm_quant not in COMM_DTYPES:
            out.append(D.make(
                "GLS005", "tp_comm_quant must be one of %s, got %r"
                % (COMM_DTYPES, self.tp_comm_quant), key="tp_comm_quant",
                hint=D.did_you_mean(str(self.tp_comm_quant), COMM_DTYPES),
            ))
        elif self.tp_comm_quant != "none" and self.tp_comm_mode == "gspmd":
            # the compiler owns the gspmd collectives: there is no ring
            # payload to quantize, and silently ignoring the knob would
            # break the never-silently-differ contract
            out.append(D.make(
                "GLS013", "tp_comm_quant=%r requires a manual tp_comm_mode "
                "(shard_map or overlap); gspmd collectives are compiler-"
                "derived and cannot carry a quantized ring payload"
                % self.tp_comm_quant, key="tp_comm_quant",
            ))
        if not isinstance(self.comm_quant_block, int) or self.comm_quant_block < 1:
            out.append(D.make(
                "GLS005", "comm_quant_block must be a positive int, got %r"
                % (self.comm_quant_block,), key="comm_quant_block",
            ))
        for k in ("serve_max_concurrency", "serve_page_size", "serve_max_pending"):
            sv = getattr(self, k)
            if not isinstance(sv, int) or sv < 0:
                out.append(D.make(
                    "GLS005", "%s must be a non-negative int, got %r" % (k, sv),
                    key=k,
                ))
        if (not isinstance(self.serve_p99_ttft_ms, (int, float))
                or isinstance(self.serve_p99_ttft_ms, bool)
                or self.serve_p99_ttft_ms < 0):
            out.append(D.make(
                "GLS005", "serve_p99_ttft_ms must be a non-negative number, "
                "got %r" % (self.serve_p99_ttft_ms,), key="serve_p99_ttft_ms",
            ))
        if self.pp < 1 or self.world_size % self.pp != 0:
            out.append(D.make(
                "GLS002", "world_size %d not divisible by pp %d"
                % (self.world_size, self.pp), key="pp_deg",
            ))
            return out  # per-stage arithmetic below would be meaningless
        if len(self.pp_division) != self.pp or sum(self.pp_division) != len(self.layers):
            out.append(D.make(
                "GLS003", "pp_division %s inconsistent with pp=%d, %d layers"
                % (self.pp_division, self.pp, len(self.layers)), key="pp_division",
            ))
        elif any(n < 1 for n in self.pp_division):
            out.append(D.make(
                "GLS003", "every pipeline stage needs >= 1 layer, got %s"
                % (self.pp_division,), key="pp_division",
            ))
        per_stage = self.world_size // self.pp
        dps = []
        for i, s in enumerate(self.layers):
            if per_stage % (s.tp * s.cp) != 0:
                out.append(D.make(
                    "GLS002", "layer %d: tp*cp=%d does not divide per-stage devices %d"
                    % (i, s.tp * s.cp, per_stage), layer=i,
                ))
            else:
                dps.append(per_stage // (s.tp * s.cp))
        if per_stage % (self.vocab_tp * self.vocab_cp) != 0:
            out.append(D.make(
                "GLS002", "vocab_tp*vocab_cp=%d must divide per-stage devices %d"
                % (self.vocab_tp * self.vocab_cp, per_stage), key="vtp",
            ))
        else:
            dps.append(per_stage // (self.vocab_tp * self.vocab_cp))
        # batch must divide every layer's dp degree (incl. the vocab layers):
        # the batch dim is sharded over each layer's dp axes (cf. reference
        # assert at hybrid_parallel_config.py:93-96, done there via min_tp)
        max_dp = max(dps) if dps else 1
        if self.global_bsz % max_dp != 0:
            out.append(D.make(
                "GLS004", "global_bsz %d must be a multiple of the largest "
                "layer dp degree %d" % (self.global_bsz, max_dp),
                key="global_bsz",
            ))
        # Under the 1F1B schedule the sharded unit is the MICROBATCH, and it
        # must shard EVENLY over every LAYER's dp degree: an uneven batch
        # shard makes GSPMD pad and reshard with collective-permutes, which
        # the schedule's stage-divergent branches cannot host (see
        # parallel/pipeline_1f1b.py divergence-safety invariant). The vocab
        # layers are exempt — embed/head run in the schedule's uniform
        # (non-branch) region, where padding reshards are safe — as are pp=1
        # and the gpipe scan (uniform code throughout).
        if self.pp > 1 and self.pipeline_type == "pipedream_flush":
            if self.global_bsz % self.chunks != 0:
                out.append(D.make(
                    "GLS004", "global_bsz %d must divide into %d chunks"
                    % (self.global_bsz, self.chunks), key="chunks",
                ))
            else:
                mb = self.global_bsz // self.chunks
                layer_dps = [
                    per_stage // (s.tp * s.cp) for s in self.layers
                    if per_stage % (s.tp * s.cp) == 0
                ]
                max_layer_dp = max(layer_dps) if layer_dps else 1
                if mb % max_layer_dp != 0:
                    out.append(D.make(
                        "GLS004", "1F1B microbatch size %d (global_bsz %d / "
                        "chunks %d) must be a multiple of the largest layer "
                        "dp degree %d"
                        % (mb, self.global_bsz, self.chunks, max_layer_dp),
                        key="chunks",
                    ))
        return out

    def pipeline_engine_diagnostics(self) -> list:
        """Cross-layer mesh-axis consistency within/across pipeline stages
        (GLS010) and checkpoint legality (GLS011), mirroring the engine-side
        validators (parallel/pipeline.py asserts, pipeline_1f1b.py
        validate_1f1b_config) so a bad searched config is refused before any
        tracing. NOT part of `validate()` — configs destined for pp=1 slicing
        or custom engines construct fine; the linter (and the engines
        themselves) enforce these."""
        from galvatron_tpu.analysis import diagnostics as D

        out = []
        if self.pp <= 1:
            return out
        div = self.pp_division
        if len(div) != self.pp or sum(div) != len(self.layers) or any(n < 1 for n in div):
            return out  # GLS003 already reported; stage slicing is undefined
        stage_sigs = []
        for st in range(self.pp):
            stage_sigs.append(tuple(self.layers[i] for i in self.layers_of_stage(st)))
        if self.pipeline_type == "gpipe":
            # the vmapped scan body is ONE program: equal stages, identical
            # within-stage strategies everywhere, no ring cp
            if len(set(div)) != 1:
                out.append(D.make(
                    "GLS010", "gpipe scan requires equal layers per stage, "
                    "got pp_division %s (use pipeline_type="
                    "'pipedream_flush' for uneven divisions)" % (div,),
                    key="pp_division",
                ))
            elif len(set(stage_sigs)) != 1:
                # report remat-only divergence (checkpoint flag OR per-layer
                # remat_policy — both change the scanned program, nothing
                # else) as GLS011, anything else as GLS010
                ckpt_only = len({
                    tuple(dataclasses.replace(s, checkpoint=0,
                                              remat_policy="full")
                          for s in sig)
                    for sig in stage_sigs
                }) == 1
                code = "GLS011" if ckpt_only else "GLS010"
                what = ("activation-checkpoint flags" if ckpt_only
                        else "layer strategies")
                out.append(D.make(
                    code, "gpipe scan requires within-stage %s to match on "
                    "every stage (the vmapped body is one program); use "
                    "pipeline_type='pipedream_flush' for per-stage "
                    "heterogeneous strategies" % what,
                ))
            for i, s in enumerate(self.layers):
                if s.cp > 1:
                    out.append(D.make(
                        "GLS010", "layer %d: cp>1 with pp>1 must run through "
                        "the 1F1B engine (pipeline_type='pipedream_flush'); "
                        "the scan pipeline computes attention without the "
                        "ring shard_map" % i, layer=i,
                    ))
                    break
        else:  # pipedream_flush
            if any(s.cp > 1 for s in self.layers) and len(set(stage_sigs)) != 1:
                out.append(D.make(
                    "GLS010", "ring-attention cp>1 inside the 1F1B schedule "
                    "requires stage-uniform strategies (equal divisions "
                    "included): the ring's collective-permutes must execute "
                    "identically on every stage every tick",
                ))
        return out

    def validate(self):
        from galvatron_tpu.analysis import diagnostics as D

        errors = [d for d in self.structural_diagnostics() if d.severity == D.ERROR]
        if errors:
            raise D.DiagnosticError(errors)

    # -------------------------------------------------------------- properties
    @property
    def num_layers(self) -> int:
        return len(self.layers)

    @property
    def per_stage_devices(self) -> int:
        return self.world_size // self.pp

    def dp(self, layer_idx: int) -> int:
        s = self.layers[layer_idx]
        return self.per_stage_devices // (s.tp * s.cp)

    @property
    def stage_of_layer(self) -> List[int]:
        return pp_stage_of_layer(self.pp_division)

    def layers_of_stage(self, stage: int) -> List[int]:
        lo = sum(self.pp_division[:stage])
        return list(range(lo, lo + self.pp_division[stage]))

    def dp_type(self, layer_idx: int) -> str:
        return "zero3" if self.layers[layer_idx].fsdp else self.default_dp_type

    @property
    def max_cp(self) -> int:
        return max([s.cp for s in self.layers] + [self.vocab_cp])

    @property
    def microbatch_size(self) -> int:
        if self.global_bsz % self.chunks != 0:
            raise ValueError("global_bsz must divide evenly into chunks (pad upstream)")
        return self.global_bsz // self.chunks

    # ------------------------------------------------------------ constructors
    @classmethod
    def uniform(
        cls,
        world_size: int,
        num_layers: int,
        pp: int = 1,
        tp: int = 1,
        cp: int = 1,
        sp: int = 0,
        sdp: int = 0,
        checkpoint: int = 0,
        grad_comm_dtype: str = "none",
        param_comm_dtype: str = "none",
        remat_policy: str = "full",
        **kw,
    ) -> "HybridParallelConfig":
        """GLOBAL-mode config: one strategy for every layer (reference
        hybrid_parallel_config.py:27-42). The global ``remat_policy``
        default-override is stamped onto every layer here (there are no
        serialized per-layer values to defer to in GLOBAL mode)."""
        layer = LayerStrategy(tp=tp, cp=cp, sp=sp, fsdp=sdp, checkpoint=checkpoint,
                              grad_comm_dtype=grad_comm_dtype,
                              param_comm_dtype=param_comm_dtype,
                              remat_policy=remat_policy)
        return cls(world_size=world_size, pp=pp, layers=[layer] * num_layers,
                   remat_policy=remat_policy, **kw)

    @classmethod
    def from_json(cls, path_or_dict, world_size: int, **overrides) -> "HybridParallelConfig":
        """Load a searched strategy JSON in the reference's on-disk format
        (reference utils/config_utils.py:22-46). Rejects unknown/typo'd keys
        and malformed per-layer arrays with structured diagnostics (GLS001/
        GLS005/GLS006 via DiagnosticError) instead of silently ignoring them
        — a misspelled key would otherwise fall back to its default and
        surface minutes later as an OOM or a wrong-parallelism run."""
        from galvatron_tpu.analysis import diagnostics as D

        cfg = path_or_dict if isinstance(path_or_dict, dict) else read_json_config(path_or_dict)
        schema_errors = [d for d in schema_diagnostics(cfg) if d.severity == D.ERROR]
        if schema_errors:
            raise D.DiagnosticError(schema_errors)
        tp_sizes = str2array(cfg["tp_sizes_enc"])
        n = len(tp_sizes)
        cp_sizes = str2array(cfg.get("cp_sizes_enc", array2str([1] * n)))
        consec = str2array(cfg.get("tp_consecutive_flags", array2str([1] * n)))
        dp_types = str2array(cfg["dp_types_enc"])
        use_sp = str2array(cfg.get("use_sp", array2str([0] * n)))
        ckpt = str2array(cfg.get("checkpoint", array2str([0] * n)))
        gcd = str2strlist(cfg["grad_comm_dtype"]) if "grad_comm_dtype" in cfg \
            else ["none"] * n
        pcd = str2strlist(cfg["param_comm_dtype"]) if "param_comm_dtype" in cfg \
            else ["none"] * n
        # precedence rule: serialized per-layer remat policies win; the
        # global --remat_policy flag (arriving as the remat_policy override)
        # only fills layers when the JSON does not carry the key
        rp_default = overrides.get("remat_policy", "full")
        rp = str2strlist(cfg["remat_policy"]) if "remat_policy" in cfg \
            else [rp_default] * n
        layers = [
            LayerStrategy(
                tp=tp_sizes[i], cp=cp_sizes[i], sp=use_sp[i], fsdp=dp_types[i],
                checkpoint=ckpt[i], tp_consec=consec[i],
                grad_comm_dtype=gcd[i], param_comm_dtype=pcd[i],
                remat_policy=rp[i],
            )
            for i in range(n)
        ]
        kw = dict(
            world_size=world_size,
            pp=cfg["pp_deg"],
            layers=layers,
            global_bsz=cfg.get("global_bsz", 8),
            chunks=cfg.get("chunks", 1),
            pp_division=str2array(cfg["pp_division"]) if "pp_division" in cfg else None,
            pipeline_type=cfg.get("pipeline_type", "gpipe"),
            default_dp_type=cfg.get("default_dp_type", "ddp"),
            vocab_tp=cfg.get("vtp", 1),
            vocab_sp=cfg.get("vsp", 0),
            vocab_cp=cfg.get("vcp", 1),
            embed_sdp=cfg.get("embed_sdp", 0),
            cp_mode=cfg.get("cp_mode", "zigzag"),
            comm_quant_block=cfg.get("comm_quant_block", 64),
            serve_max_concurrency=cfg.get("serve_max_concurrency", 0),
            serve_page_size=cfg.get("serve_page_size", 0),
            serve_p99_ttft_ms=cfg.get("serve_p99_ttft_ms", 0.0),
            serve_max_pending=cfg.get("serve_max_pending", 0),
        )
        kw.update(overrides)
        return cls(**kw)

    # ----------------------------------------------------------- serialization
    def to_json_dict(self) -> dict:
        """Reference-compatible JSON dict (utils/config_utils.py:48-57 plus the
        extra keys train_dist reads back)."""
        return {
            "pp_deg": self.pp,
            "tp_sizes_enc": array2str([s.tp for s in self.layers]),
            "tp_consecutive_flags": array2str([s.tp_consec for s in self.layers]),
            "cp_sizes_enc": array2str([s.cp for s in self.layers]),
            "dp_types_enc": array2str([s.fsdp for s in self.layers]),
            "use_sp": array2str([s.sp for s in self.layers]),
            "checkpoint": array2str([s.checkpoint for s in self.layers]),
            "global_bsz": self.global_bsz,
            "chunks": self.chunks,
            "pp_division": array2str(self.pp_division),
            "pipeline_type": self.pipeline_type,
            "default_dp_type": self.default_dp_type,
            "vtp": self.vocab_tp,
            "vsp": self.vocab_sp,
            "vcp": self.vocab_cp,
            "embed_sdp": self.embed_sdp,
            "cp_mode": self.cp_mode,
            "grad_comm_dtype": strlist2str([s.grad_comm_dtype for s in self.layers]),
            "param_comm_dtype": strlist2str([s.param_comm_dtype for s in self.layers]),
            "comm_quant_block": self.comm_quant_block,
        } | ({
            # serialized only when some layer deviates from "full": an
            # all-"full" key duplicates the checkpoint flag (GLS103) and
            # from_json default-fills it anyway, so round-trips stay clean
            "remat_policy": strlist2str([s.remat_policy for s in self.layers]),
        } if any(s.remat_policy != "full" for s in self.layers) else {}) | ({
            "serve_max_concurrency": self.serve_max_concurrency,
            "serve_page_size": self.serve_page_size,
        } if self.serve_max_concurrency or self.serve_page_size else {}) | ({
            "serve_p99_ttft_ms": self.serve_p99_ttft_ms,
        } if self.serve_p99_ttft_ms else {}) | ({
            "serve_max_pending": self.serve_max_pending,
        } if self.serve_max_pending else {})

    def save(self, path: str):
        write_json_config(self.to_json_dict(), path)

    # For checkpoint-resume strategy equality assertion (reference
    # hybrid_parallel_config.py:112-124).
    def assert_equal(self, other: "HybridParallelConfig"):
        a, b = self.to_json_dict(), other.to_json_dict()
        if a != b:
            diff = {k: (a[k], b[k]) for k in a if a.get(k) != b.get(k)}
            raise AssertionError("Hybrid parallel configs are not equal: %s" % diff)

    def describe(self) -> str:
        lines = ["pp=%d world=%d bsz=%d chunks=%d pipeline=%s default_dp=%s" % (
            self.pp, self.world_size, self.global_bsz, self.chunks,
            self.pipeline_type, self.default_dp_type)]
        for i, s in enumerate(self.layers):
            lines.append(
                "  layer %2d: stage %d tp=%d%s cp=%d dp=%d(%s)%s%s%s%s"
                % (
                    i, self.stage_of_layer[i], s.tp,
                    "(ulysses-sp)" if s.sp else "",
                    s.cp, self.dp(i), self.dp_type(i),
                    (" ckpt" if s.remat_policy == "full"
                     else " ckpt[%s]" % s.remat_policy) if s.checkpoint else "",
                    "" if s.tp_consec else " nonconsec",
                    " gcomm=%s" % s.grad_comm_dtype
                    if s.grad_comm_dtype != "none" else "",
                    " pcomm=%s" % s.param_comm_dtype
                    if s.param_comm_dtype != "none" else "",
                )
            )
        lines.append(
            "  vocab: tp=%d sp=%d cp=%d embed_sdp=%d" % (self.vocab_tp, self.vocab_sp, self.vocab_cp, self.embed_sdp)
        )
        return "\n".join(lines)
