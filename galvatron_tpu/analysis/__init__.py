"""Static analysis for galvatron_tpu: catch bad strategies and broken code
before any device time is spent.

- `diagnostics`: the shared finding/report framework (codes, severities,
  JSON output, exit-code contract).
- `strategy_lint`: validates a searched strategy JSON against a model config
  and world size with no device or tracing work (GLS*** codes).
- `code_lint`: AST pass over the package flagging jax-API drift and
  jit-safety hazards (GLC*** codes).
- `ckpt_lint`: offline checkpoint-directory audit (GLS21x codes).
- `trace_lint`: abstract-evals the train step to a ClosedJaxpr (no compile)
  and walks it for the pinned GSPMD miscompile classes, donation waste,
  manual-region hazards and predicted-vs-traced collective drift
  (GLT*** codes; the WA*** workaround inventory lives in
  `utils/jax_compat.py`).

The package __init__ stays import-light (the config layer imports
`analysis.diagnostics` from inside `HybridParallelConfig.validate`); the
linters are loaded lazily on attribute access.
"""

from galvatron_tpu.analysis.diagnostics import (  # noqa: F401
    CODES,
    Diagnostic,
    DiagnosticError,
    DiagnosticReport,
    did_you_mean,
    make,
    registry_table,
)

_LAZY = {"strategy_lint", "code_lint", "ckpt_lint", "trace_lint"}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return importlib.import_module("galvatron_tpu.analysis." + name)
    raise AttributeError(name)
