"""Offline checkpoint auditor (``GLS21x`` diagnostics).

``python -m galvatron_tpu.cli lint --ckpt <dir>`` checks a checkpoint
directory WITHOUT restoring any arrays (host-only, seconds even for
multi-TB checkpoints): per-iteration manifest/digest-record integrity,
provenance presence and internal consistency, and a full strategy lint of
the provenance's embedded strategy JSON — so CI can tell "this directory
can be resumed (elastically, if needed)" before a multi-day job bets on it.

Checks:
- every on-disk step has a committed, well-formed manifest (GLS210 torn /
  GLS212 malformed) whose item records carry the digest/spec_digest/
  num_leaves triple the restore-time verifier needs;
- orphan manifests and stray non-step entries are flagged (GLS211);
- manifests carry provenance (GLS213 when missing — resumable only on the
  identical mesh), whose strategy JSON lints clean against its own recorded
  world size (the GLS0xx pipeline) and whose mesh/device bookkeeping is
  self-consistent (GLS212).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from galvatron_tpu.analysis import diagnostics as D

# directory entries that belong to the checkpoint layout besides the
# integer-named step dirs
_KNOWN_ENTRIES = ("manifests", "hybrid_parallel_config.json", "meta.json")
_REQUIRED_ITEM_KEYS = ("spec_digest", "num_leaves")


def _provenance_diagnostics(step: int, prov: Dict[str, Any]) -> List[D.Diagnostic]:
    out: List[D.Diagnostic] = []
    strategy = prov.get("strategy")
    world = prov.get("world_size")
    if not isinstance(strategy, dict) or not isinstance(world, int):
        out.append(D.make(
            "GLS212", "step %d provenance lacks a strategy dict / integer "
            "world_size — not elastically resumable" % step,
        ))
        return out
    mesh_shape = prov.get("mesh_shape")
    if isinstance(mesh_shape, dict):
        n = 1
        for v in mesh_shape.values():
            n *= int(v)
        if n != world:
            out.append(D.make(
                "GLS212", "step %d provenance mesh_shape %s covers %d "
                "devices but world_size says %d" % (step, mesh_shape, n, world),
            ))
    if not prov.get("model_digest"):
        out.append(D.make(
            "GLS212", "step %d provenance has no model_digest; an elastic "
            "resume could silently restore into a different model" % step,
        ))
    from galvatron_tpu.analysis import strategy_lint as S

    for d in S.lint_strategy_dict(dict(strategy), world).diagnostics:
        out.append(D.Diagnostic(**{
            **d.__dict__,
            "message": "step %d provenance strategy: %s" % (step, d.message),
        }))
    return out


def audit_checkpoint_dir(path: str) -> D.DiagnosticReport:
    """Audit one checkpoint directory."""
    from galvatron_tpu.runtime import checkpoint as ck

    report = D.DiagnosticReport()

    def add(code, msg, **kw):
        kw.setdefault("file", path)
        report.add(D.make(code, msg, **kw))

    if not os.path.isdir(path):
        add("GLS212", "not a directory")
        return report
    with ck._manager(path) as mgr:
        steps = sorted(mgr.all_steps())
    manifest_steps = set()
    mdir = os.path.join(path, ck.MANIFEST_DIRNAME)
    if os.path.isdir(mdir):
        for name in sorted(os.listdir(mdir)):
            stem = name.split(".")[0]
            if name.endswith(".json") and stem.isdigit():
                manifest_steps.add(int(stem))
            elif not name.endswith(".json"):
                add("GLS211", "stray entry %r in %s/" % (name, ck.MANIFEST_DIRNAME))
    has_discipline = bool(manifest_steps) or os.path.isdir(mdir)
    # stray entries in the top-level dir (a torn orbax tmp dir, editor
    # droppings): tolerated by every runtime path, but worth surfacing
    for name in sorted(os.listdir(path)):
        if name in _KNOWN_ENTRIES or name.isdigit():
            continue
        add("GLS211", "stray entry %r in the checkpoint dir" % name)
    if not steps:
        add("GLS211", "no checkpoint steps on disk")
    for step in steps:
        if not has_discipline:
            add("GLS213", "step %d predates the manifest discipline (no "
                "integrity verification possible)" % step)
            continue
        manifest = ck.read_manifest(path, step)
        if manifest is None:
            add("GLS210", "step %d has no committed manifest (torn or "
                "interrupted save)" % step)
            continue
        if manifest.get("iteration") != step:
            add("GLS212", "step %d manifest records iteration %r"
                % (step, manifest.get("iteration")))
        items = manifest.get("items")
        if not isinstance(items, dict) or "params" not in items:
            add("GLS212", "step %d manifest has no 'params' item record" % step)
        else:
            for name, rec in sorted(items.items()):
                missing = [k for k in _REQUIRED_ITEM_KEYS if not rec.get(k)]
                if missing:
                    add("GLS212", "step %d item %r record lacks %s"
                        % (step, name, ", ".join(missing)))
        prov = manifest.get("provenance")
        if prov is None:
            add("GLS213", "step %d manifest has no provenance (resumable "
                "only on the identical mesh/strategy)" % step)
        else:
            for d in _provenance_diagnostics(step, prov):
                report.add(D.Diagnostic(**{**d.__dict__, "file": d.file or path}))
    for orphan in sorted(manifest_steps - set(steps)):
        add("GLS211", "manifest for step %d has no step directory (GC race "
            "leftover?)" % orphan)
    return report
