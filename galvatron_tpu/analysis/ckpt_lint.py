"""Offline checkpoint auditor (``GLS21x`` diagnostics).

``python -m galvatron_tpu.cli lint --ckpt <dir>`` checks a checkpoint
directory WITHOUT restoring any arrays (host-only, seconds even for
multi-TB checkpoints): per-iteration manifest/digest-record integrity,
provenance presence and internal consistency, and a full strategy lint of
the provenance's embedded strategy JSON — so CI can tell "this directory
can be resumed (elastically, if needed)" before a multi-day job bets on it.

Checks:
- every on-disk step has a committed, well-formed manifest (GLS210 torn /
  GLS212 malformed) whose item records carry the digest/spec_digest/
  num_leaves triple the restore-time verifier needs;
- orphan manifests and stray non-step entries are flagged (GLS211);
- manifests carry provenance (GLS213 when missing — resumable only on the
  identical mesh), whose strategy JSON lints clean against its own recorded
  world size (the GLS0xx pipeline) and whose mesh/device bookkeeping is
  self-consistent (GLS212);
- with ``--deep`` (the one opt-out of the host-only contract), each step's
  arrays are actually restored host-side and their layout-invariant
  integrity fold (runtime/sdc.py) recomputed against the manifest's
  recorded one (GLS214) — catches bit rot *between* save and resume, which
  the torn-write sha256 only catches at restore time.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from galvatron_tpu.analysis import diagnostics as D

# directory entries that belong to the checkpoint layout besides the
# integer-named step dirs
_KNOWN_ENTRIES = ("manifests", "hybrid_parallel_config.json", "meta.json")
_REQUIRED_ITEM_KEYS = ("spec_digest", "num_leaves")


def _provenance_diagnostics(step: int, prov: Dict[str, Any]) -> List[D.Diagnostic]:
    out: List[D.Diagnostic] = []
    strategy = prov.get("strategy")
    world = prov.get("world_size")
    if not isinstance(strategy, dict) or not isinstance(world, int):
        out.append(D.make(
            "GLS212", "step %d provenance lacks a strategy dict / integer "
            "world_size — not elastically resumable" % step,
        ))
        return out
    mesh_shape = prov.get("mesh_shape")
    if isinstance(mesh_shape, dict):
        n = 1
        for v in mesh_shape.values():
            n *= int(v)
        if n != world:
            out.append(D.make(
                "GLS212", "step %d provenance mesh_shape %s covers %d "
                "devices but world_size says %d" % (step, mesh_shape, n, world),
            ))
    if not prov.get("model_digest"):
        out.append(D.make(
            "GLS212", "step %d provenance has no model_digest; an elastic "
            "resume could silently restore into a different model" % step,
        ))
    from galvatron_tpu.analysis import strategy_lint as S

    for d in S.lint_strategy_dict(dict(strategy), world).diagnostics:
        out.append(D.Diagnostic(**{
            **d.__dict__,
            "message": "step %d provenance strategy: %s" % (step, d.message),
        }))
    return out


def _deep_item_diagnostics(path: str, step: int, items: Dict[str, Any], add) -> None:
    """``--deep``: restore each array item host-side and recompute the
    layout-invariant integrity fold against the manifest's record. A
    mismatch is GLS214 — the bytes changed between save and now (bit rot,
    a partial overwrite, tampering), which the restore-time sha256 would
    also catch but only once a resume already bet on the directory."""
    import jax
    import orbax.checkpoint as ocp

    from galvatron_tpu.runtime import checkpoint as ck
    from galvatron_tpu.runtime import sdc

    with ck._manager(path) as mgr:
        try:
            metas = dict(mgr.item_metadata(step).items())
        except Exception as e:
            add("GLS212", "step %d: cannot enumerate item metadata for the "
                "deep audit (%s)" % (step, e))
            return
        for name, rec in sorted(items.items()):
            if name == "train_meta" or name not in metas:
                continue
            want = rec.get("fold")
            if want is None:
                add("GLS213", "step %d item %r predates the integrity fold; "
                    "the deep audit cannot verify its values" % (step, name))
                continue
            try:
                abstract = jax.tree.map(
                    lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
                    metas[name])
                restored = mgr.restore(step, args=ocp.args.Composite(
                    **{name: ocp.args.StandardRestore(abstract)}))[name]
            except Exception as e:
                add("GLS212", "step %d item %r failed to restore for the "
                    "deep audit (%s)" % (step, name, e))
                continue
            got = sdc.host_tree_fold(restored)
            if got != int(want) & 0xFFFFFFFF:
                add("GLS214", "step %d item %r: recomputed integrity fold "
                    "0x%08x != manifest 0x%08x — the checkpoint bytes "
                    "changed since save" % (step, name, got, int(want)))


def audit_checkpoint_dir(path: str, deep: bool = False) -> D.DiagnosticReport:
    """Audit one checkpoint directory. `deep` additionally restores every
    array item and verifies its integrity fold (GLS214) — no longer
    host-metadata-only, so it costs a full read of the checkpoint."""
    from galvatron_tpu.runtime import checkpoint as ck

    report = D.DiagnosticReport()

    def add(code, msg, **kw):
        kw.setdefault("file", path)
        report.add(D.make(code, msg, **kw))

    if not os.path.isdir(path):
        add("GLS212", "not a directory")
        return report
    with ck._manager(path) as mgr:
        steps = sorted(mgr.all_steps())
    manifest_steps = set()
    mdir = os.path.join(path, ck.MANIFEST_DIRNAME)
    if os.path.isdir(mdir):
        for name in sorted(os.listdir(mdir)):
            stem = name.split(".")[0]
            if name.endswith(".json") and stem.isdigit():
                manifest_steps.add(int(stem))
            elif not name.endswith(".json"):
                add("GLS211", "stray entry %r in %s/" % (name, ck.MANIFEST_DIRNAME))
    has_discipline = bool(manifest_steps) or os.path.isdir(mdir)
    # stray entries in the top-level dir (a torn orbax tmp dir, editor
    # droppings): tolerated by every runtime path, but worth surfacing
    for name in sorted(os.listdir(path)):
        if name in _KNOWN_ENTRIES or name.isdigit():
            continue
        add("GLS211", "stray entry %r in the checkpoint dir" % name)
    if not steps:
        add("GLS211", "no checkpoint steps on disk")
    for step in steps:
        if not has_discipline:
            add("GLS213", "step %d predates the manifest discipline (no "
                "integrity verification possible)" % step)
            continue
        manifest = ck.read_manifest(path, step)
        if manifest is None:
            add("GLS210", "step %d has no committed manifest (torn or "
                "interrupted save)" % step)
            continue
        if manifest.get("iteration") != step:
            add("GLS212", "step %d manifest records iteration %r"
                % (step, manifest.get("iteration")))
        items = manifest.get("items")
        if not isinstance(items, dict) or "params" not in items:
            add("GLS212", "step %d manifest has no 'params' item record" % step)
        else:
            for name, rec in sorted(items.items()):
                missing = [k for k in _REQUIRED_ITEM_KEYS if not rec.get(k)]
                if missing:
                    add("GLS212", "step %d item %r record lacks %s"
                        % (step, name, ", ".join(missing)))
            if deep:
                _deep_item_diagnostics(path, step, items, add)
        prov = manifest.get("provenance")
        if prov is None:
            add("GLS213", "step %d manifest has no provenance (resumable "
                "only on the identical mesh/strategy)" % step)
        else:
            for d in _provenance_diagnostics(step, prov):
                report.add(D.Diagnostic(**{**d.__dict__, "file": d.file or path}))
    for orphan in sorted(manifest_steps - set(steps)):
        add("GLS211", "manifest for step %d has no step directory (GC race "
            "leftover?)" % orphan)
    return report
