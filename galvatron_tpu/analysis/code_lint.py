"""Static code linter for jax-API drift and jit-safety hazards (``GLC***``).

A pure-AST pass (no execution of the linted code) over Python sources:

- **GLC001 — missing jax API**: every dotted attribute chain rooted at a
  jax import alias (``jax.shard_map``, ``jnp.einsum``, ``lax.scan`` ...) and
  every ``from jax.x import y`` is resolved against the jax actually
  *installed in this environment* — introspected, not hard-coded — so an
  upgrade/downgrade that removes an API is caught at lint time instead of at
  import/trace time on a TPU pod. (This is exactly the
  ``jax.shard_map``/``get_abstract_mesh`` class of breakage that took out
  ring attention, both 1F1B engines and the hardware profiler on jax
  0.4.37.) Because `galvatron_tpu.utils.jax_compat` installs its shims at
  package import, chains the shim provides resolve — the linter validates
  the *effective* API surface.
- **GLC002 — host numpy inside jit**: calls to a ``numpy`` alias inside a
  jit-compiled function. `np.asarray(x)` on a tracer either fails or silently
  constant-folds; dtype/constant accesses (``np.float32``, ``np.pi``) are
  trace-time constants and allowed.
- **GLC003 — Python control flow on traced values**: ``if``/``while`` whose
  condition reads a (non-static) parameter of a jit-compiled function.
  Shape/dtype/None tests are static and exempt.
- **GLC004 — donated buffer reuse**: an argument passed at a donated
  position of a ``donate_argnums`` jit is read again afterwards without
  rebinding — the buffer backing it may already be aliased to the output
  (the PR-1 anomaly-guard lesson: donated step inputs cannot be "kept" on
  the host side).
- **GLC006 — ad-hoc logging in runtime library code**: bare ``print(...)``
  calls and append-mode ``open(..., "a")`` file logging inside
  ``galvatron_tpu/runtime/`` and ``galvatron_tpu/obs/`` (the rule is
  path-scoped; CLI drivers and tests may print). Library-layer output must
  go through the telemetry stream (``obs.telemetry.runtime_log`` / a
  ``TelemetrySink``) or an injectable ``print_fn``/``log_fn`` parameter
  (``RuntimeProfiler.log_iteration(print_fn=)``): bare prints are invisible
  to the structured event stream the report/autotuner layers consume, and
  per-call append-opens cost a filesystem round trip on hot paths (the
  ``log_iteration`` reopen bug this rule pins).
- **GLC005 — blocking host sync in a loop**: driver-side loops that force a
  host<->device round trip every iteration (``float(...)``/``.item()``/
  ``np.asarray(...)`` on values produced by a jitted callable, or any
  ``block_until_ready``) kill JAX's async dispatch: the device idles while
  the host books keep, exactly the serialization the dispatch-ahead train
  loop removes (cli/train.py ISSUE 4). Dispatch all iterations first and
  drain once — or mark a deliberate sync point (profilers measure by
  syncing) with the pragma. The value-producer taint is tracked through
  names assigned from ``jax.jit(...)``-wrapped callables and
  ``jax.device_put``, so plain host-numpy ``float()`` loops don't trip it;
  ``block_until_ready`` is a sync by definition and is flagged untainted.
- **GLC007 — custom_vjp closing over a traced axis_index**: a custom_vjp
  primal or ``defvjp`` rule that reads, as a free variable, a name bound
  from ``jax.lax.axis_index`` in an enclosing scope. Inside a shard_map
  region the index is a per-shard traced value; baked into the rule's
  closure, the legacy shard_map transpose replays it with the wrong
  shard's value (the tp ring cotangent hazard ``parallel/tp_shard_map.py``
  documents) — recompute ``axis_index`` inside the rule instead. The
  traced-program linter's GLT005 catches the same bug at jaxpr level.

Jit contexts are found both as decorators (``@jax.jit``,
``@partial(jax.jit, ...)``) and as wrappings of a locally-defined function
(``step = jax.jit(train_step, donate_argnums=(0, 1))``).

Suppressions: a line comment ``# galv-lint: ignore[GLC002]`` (comma-
separated codes) suppresses findings reported for that line.
"""

from __future__ import annotations

import ast
import importlib
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from galvatron_tpu.analysis import diagnostics as D

_PRAGMA_RE = re.compile(r"#\s*galv-lint:\s*ignore\[([A-Za-z0-9_, ]+)\]")

# numpy attributes that are trace-time constants / types, fine inside jit
_NUMPY_STATIC_OK = {
    "pi", "e", "inf", "nan", "newaxis", "ndarray", "dtype", "generic",
    "integer", "floating", "bool_", "float16", "float32", "float64",
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "complex64", "complex128", "iinfo", "finfo",
}

# test-expression contexts that are static even on a traced name
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
_STATIC_CALLS = {"isinstance", "len", "hasattr", "getattr", "callable", "int", "bool"}


# --------------------------------------------------------------- resolution
class JaxResolver:
    """Resolve dotted chains against the installed jax, importing submodules
    on demand. Memoised per (chain) so a package-wide lint is one getattr
    walk per distinct chain."""

    def __init__(self, roots: Sequence[str] = ("jax",)):
        self.roots = tuple(roots)
        self._cache: Dict[Tuple[str, ...], Optional[str]] = {}

    def missing_prefix(self, parts: Sequence[str]) -> Optional[str]:
        """None if the chain resolves; else the shortest unresolvable
        prefix (e.g. 'jax.shard_mapp')."""
        parts = tuple(parts)
        if parts in self._cache:
            return self._cache[parts]
        result: Optional[str] = None
        try:
            obj = importlib.import_module(parts[0])
        except ImportError:
            result = parts[0]
        else:
            for i, name in enumerate(parts[1:], start=1):
                try:
                    obj = getattr(obj, name)
                except AttributeError:
                    dotted = ".".join(parts[: i + 1])
                    try:
                        obj = importlib.import_module(dotted)
                    except ImportError:
                        result = dotted
                        break
        self._cache[parts] = result
        return result


# ------------------------------------------------------------- file linting
class _Aliases:
    """Import-alias tables for one module."""

    def __init__(self):
        self.jax: Dict[str, Tuple[str, ...]] = {}    # alias -> dotted chain
        self.numpy: Set[str] = set()                 # aliases of host numpy

    def visit_import(self, node: ast.Import):
        for a in node.names:
            parts = tuple(a.name.split("."))
            bound = a.asname or parts[0]
            if parts[0] == "jax":
                self.jax[bound] = parts if a.asname else (parts[0],)
            elif parts[0] == "numpy":
                self.numpy.add(bound)

    def visit_import_from(self, node: ast.ImportFrom) -> List[Tuple[Tuple[str, ...], int]]:
        """Returns jax-rooted (chain, lineno) pairs to resolve (GLC001)."""
        out = []
        if node.level or not node.module:
            return out
        mparts = tuple(node.module.split("."))
        for a in node.names:
            if a.name == "*":
                continue
            bound = a.asname or a.name
            if mparts[0] == "jax":
                chain = mparts + (a.name,)
                self.jax[bound] = chain
                out.append((chain, node.lineno))
            elif mparts[0] == "numpy":
                self.numpy.add(bound)
        return out


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['jnp', 'linalg', 'norm'] for a pure Name.Attr.Attr chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(v, int):
        return (v,)
    if isinstance(v, (tuple, list)) and all(isinstance(x, int) for x in v):
        return tuple(v)
    return None


def _is_jax_jit(node: ast.AST, aliases: _Aliases) -> bool:
    chain = _attr_chain(node)
    if chain is None:
        return False
    root = aliases.jax.get(chain[0])
    if root is None:
        return False
    return (root + tuple(chain[1:]))[-1] == "jit"


class _JitInfo:
    def __init__(self, static_names: Set[str], donated: Tuple[int, ...] = ()):
        self.static_names = static_names
        self.donated = donated


def _jit_call_info(call: ast.Call, aliases: _Aliases) -> Optional[Tuple[Optional[str], _JitInfo]]:
    """(wrapped function name | None, info) when `call` is jax.jit(...)."""
    if not _is_jax_jit(call.func, aliases):
        return None
    static: Set[str] = set()
    donated: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            try:
                lv = ast.literal_eval(v)
                static |= {lv} if isinstance(lv, str) else set(lv)
            except (ValueError, SyntaxError):
                pass
        elif kw.arg == "donate_argnums":
            donated = _literal_int_tuple(kw.value) or ()
    fname = None
    if call.args and isinstance(call.args[0], ast.Name):
        fname = call.args[0].id
    return fname, _JitInfo(static, donated)


class _ModuleLint:
    def __init__(self, src: str, filename: str, resolver: JaxResolver,
                 rules: Set[str]):
        self.filename = filename
        self.resolver = resolver
        self.rules = rules
        self.diags: List[D.Diagnostic] = []
        self.tree = ast.parse(src, filename=filename)
        self.lines = src.splitlines()
        self.aliases = _Aliases()
        # function-def name -> _JitInfo for functions that get jit-wrapped
        self.jit_wrapped: Dict[str, _JitInfo] = {}
        # donated-jit callable name -> donated positions
        self.donated_callables: Dict[str, Tuple[int, ...]] = {}
        # names bound to a jax.jit(...) result (device-value producers)
        self.jit_callables: Set[str] = set()

    # ---- pass 1: imports, jit registry --------------------------------
    def scan_module(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                self.aliases.visit_import(node)
            elif isinstance(node, ast.ImportFrom):
                for chain, lineno in self.aliases.visit_import_from(node):
                    self._check_chain(chain, lineno)
            elif isinstance(node, ast.Call):
                info = _jit_call_info(node, self.aliases)
                if info is not None:
                    fname, ji = info
                    if fname:
                        self.jit_wrapped[fname] = ji
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value, self.aliases)
                if info is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.jit_callables.add(t.id)
                            if info[1].donated:
                                self.donated_callables[t.id] = info[1].donated

    # ---- GLC001 --------------------------------------------------------
    def _check_chain(self, chain: Sequence[str], lineno: int):
        if "GLC001" not in self.rules:
            return
        missing = self.resolver.missing_prefix(chain)
        if missing is not None:
            self.diags.append(D.make(
                "GLC001", "%r does not exist in the installed jax (%s)"
                % (".".join(chain), missing),
                file=self.filename, line=lineno, key=".".join(chain),
            ))

    def check_attribute_chains(self):
        # flag only maximal chains: collect the set of inner Attribute nodes
        inner: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Attribute):
                inner.add(id(node.value))
        seen: Set[Tuple[Tuple[str, ...], int]] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Attribute) or id(node) in inner:
                continue
            if not isinstance(node.ctx, ast.Load):
                continue  # `jax.shard_map = shim` in jax_compat is a Store
            chain = _attr_chain(node)
            if chain is None:
                continue
            rooted = self.aliases.jax.get(chain[0])
            if rooted is None:
                continue
            full = rooted + tuple(chain[1:])
            key = (full, node.lineno)
            if key not in seen:
                seen.add(key)
                self._check_chain(full, node.lineno)

    # ---- jit-body rules ------------------------------------------------
    def _jit_functions(self) -> List[Tuple[ast.AST, _JitInfo]]:
        out = []
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            info: Optional[_JitInfo] = None
            for dec in node.decorator_list:
                if _is_jax_jit(dec, self.aliases):
                    info = _JitInfo(set())
                elif isinstance(dec, ast.Call):
                    # @partial(jax.jit, ...) / @jax.jit(...) with options
                    if _is_jax_jit(dec.func, self.aliases):
                        info = _jit_call_info(dec, self.aliases)[1]
                    elif (isinstance(dec.func, ast.Name) and dec.func.id == "partial"
                          and dec.args and _is_jax_jit(dec.args[0], self.aliases)):
                        info = _jit_call_info(
                            ast.Call(func=dec.args[0], args=dec.args[1:],
                                     keywords=dec.keywords), self.aliases)[1]
            if info is None and node.name in self.jit_wrapped:
                info = self.jit_wrapped[node.name]
            if info is not None:
                out.append((node, info))
        return out

    @staticmethod
    def _param_names(fn) -> List[str]:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def check_jit_bodies(self):
        for fn, info in self._jit_functions():
            params = [p for p in self._param_names(fn) if p not in info.static_names]
            traced = set(params)
            if "GLC002" in self.rules:
                self._check_host_numpy(fn)
            if "GLC003" in self.rules:
                self._check_traced_branches(fn, traced)

    def _check_host_numpy(self, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[0] not in self.aliases.numpy:
                continue
            if len(chain) == 2 and chain[1] in _NUMPY_STATIC_OK:
                continue
            self.diags.append(D.make(
                "GLC002", "host-side numpy call %r inside jit-compiled "
                "%r: numpy cannot consume tracers; use jax.numpy (or move "
                "the computation out of the jitted function)"
                % (".".join(chain), fn.name),
                file=self.filename, line=node.lineno, key=".".join(chain),
            ))

    def _check_traced_branches(self, fn, traced: Set[str]):
        class TestVisitor(ast.NodeVisitor):
            """Finds Names of traced params used non-statically in a
            condition expression."""

            def __init__(self, outer):
                self.outer = outer
                self.offending: List[ast.Name] = []

            def visit_Attribute(self, node):
                if node.attr in _STATIC_ATTRS:
                    return  # x.shape/... and anything under it is static
                self.generic_visit(node)

            def visit_Call(self, node):
                if isinstance(node.func, ast.Name) and node.func.id in _STATIC_CALLS:
                    return
                self.generic_visit(node)

            def visit_Compare(self, node):
                # `x is None` / `x is not None` are static identity tests
                if (len(node.ops) == 1 and isinstance(node.ops[0], (ast.Is, ast.IsNot))
                        and isinstance(node.comparators[0], ast.Constant)):
                    return
                # `"key" in batch`: dict-key membership is pytree structure,
                # static under jit (unlike `x in array`)
                if (len(node.ops) == 1 and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and isinstance(node.left, ast.Constant)
                        and isinstance(node.left.value, str)):
                    return
                self.generic_visit(node)

            def visit_Name(self, node):
                if node.id in traced:
                    self.offending.append(node)

        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            tv = TestVisitor(self)
            tv.visit(node.test)
            for name in tv.offending:
                self.diags.append(D.make(
                    "GLC003", "Python %s on traced value %r inside "
                    "jit-compiled %r: the branch is taken at trace time, not "
                    "per-step; use jax.lax.cond/jnp.where (or mark the "
                    "argument static)"
                    % ("while" if isinstance(node, ast.While) else "if",
                       name.id, fn.name),
                    file=self.filename, line=node.lineno, key=name.id,
                ))
                break  # one finding per statement

    # ---- GLC004 --------------------------------------------------------
    def check_donated_reuse(self):
        if "GLC004" not in self.rules or not self.donated_callables:
            return
        for fn in ast.walk(self.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                self._check_donated_in_scope(fn)

    @staticmethod
    def _walk_scope(scope) -> Iterable[ast.AST]:
        """All nodes of this scope only — nested function/class bodies are
        their own scope and are not entered."""
        stack = list(scope.body)
        while stack:
            node = stack.pop(0)
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _check_donated_in_scope(self, scope):
        nodes = list(self._walk_scope(scope))
        # (donated arg name, call lineno) events, in order
        events: List[Tuple[str, int]] = []
        for node in nodes:
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                donated = self.donated_callables.get(node.func.id)
                if not donated:
                    continue
                for pos in donated:
                    if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                        events.append((node.args[pos].id, node.lineno))
        if not events:
            return
        # per donated name: flag Loads after the donating call and before the
        # next Store to that name
        stores: Dict[str, List[int]] = {}
        loads: Dict[str, List[Tuple[int, ast.Name]]] = {}
        for node in nodes:
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Store):
                    stores.setdefault(node.id, []).append(node.lineno)
                elif isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append((node.lineno, node))
        for name, call_line in events:
            rebind = min((ln for ln in stores.get(name, []) if ln >= call_line),
                         default=None)
            for ln, node in loads.get(name, []):
                if ln <= call_line:
                    continue
                if rebind is not None and ln >= rebind:
                    continue
                self.diags.append(D.make(
                    "GLC004", "%r was donated to the jit call on line %d "
                    "(donate_argnums) and is read again here: its buffer "
                    "may already alias the output; copy it before the call "
                    "or stop donating it" % (name, call_line),
                    file=self.filename, line=ln, key=name,
                ))
                break  # one finding per (name, call)

    # ---- GLC005 --------------------------------------------------------
    def _device_tainted_names(self) -> Set[str]:
        """Names assigned (incl. tuple-unpacked) from a call to a known
        jit-wrapped callable or from jax.device_put — conservative taint for
        'this is (a tree of) device array(s)'."""
        producers = set(self.jit_callables) | set(self.jit_wrapped)
        tainted: Set[str] = set()
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            fn = node.value.func
            is_device = isinstance(fn, ast.Name) and fn.id in producers
            if not is_device:
                chain = _attr_chain(fn)
                is_device = bool(
                    chain and chain[0] in self.aliases.jax
                    and chain[-1] in ("device_put", "device_put_sharded",
                                      "device_put_replicated")
                )
            if is_device:
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        return tainted

    def _device_expr(self, expr: ast.AST, tainted: Set[str]) -> bool:
        """expr references a tainted name or calls a jit callable."""
        producers = set(self.jit_callables) | set(self.jit_wrapped)
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in producers):
                return True
        return False

    def _blocking_sync(self, call: ast.Call, tainted: Set[str]) -> Optional[str]:
        """The offending sync's key when `call` is a per-iteration blocking
        host sync, else None."""
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "block_until_ready":
            chain = _attr_chain(func)
            return ".".join(chain) if chain else "block_until_ready"
        if (isinstance(func, ast.Attribute) and func.attr == "item"
                and not call.args and self._device_expr(func.value, tainted)):
            return "item"
        if (isinstance(func, ast.Name) and func.id == "float"
                and len(call.args) == 1
                and self._device_expr(call.args[0], tainted)):
            return "float"
        chain = _attr_chain(func)
        if (chain and chain[0] in self.aliases.numpy
                and chain[-1] in ("asarray", "array") and call.args
                and self._device_expr(call.args[0], tainted)):
            return ".".join(chain)
        return None

    def check_host_syncs_in_loops(self):
        if "GLC005" not in self.rules:
            return
        # loops inside jitted functions are traced, not executed per-step:
        # a float() there is a different bug (GLC002/tracer error), not a sync
        jit_nodes: Set[int] = set()
        for fn, _ in self._jit_functions():
            jit_nodes.update(id(n) for n in ast.walk(fn))
        tainted = self._device_tainted_names()
        seen: Set[Tuple[int, str]] = set()
        for loop in ast.walk(self.tree):
            if not isinstance(loop, (ast.For, ast.While)) or id(loop) in jit_nodes:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                key = self._blocking_sync(node, tainted)
                if key is None or (node.lineno, key) in seen:
                    continue
                seen.add((node.lineno, key))
                self.diags.append(D.make(
                    "GLC005", "blocking host sync %r inside a loop: every "
                    "iteration stalls the host on the device (and the device "
                    "on the host), killing async dispatch; dispatch all "
                    "iterations first and drain once, or mark a deliberate "
                    "sync point with the pragma" % key,
                    file=self.filename, line=node.lineno, key=key,
                ))

    # ---- GLC006 --------------------------------------------------------
    def check_runtime_logging(self):
        """Path-scoped: only library code under galvatron_tpu/runtime/ or
        galvatron_tpu/obs/ is held to the no-ad-hoc-logging contract."""
        if "GLC006" not in self.rules or not _GLC006_PATH_RE.search(self.filename):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
                continue
            if node.func.id == "print":
                self.diags.append(D.make(
                    "GLC006", "bare print() in runtime library code: route "
                    "output through obs.telemetry (runtime_log / a "
                    "TelemetrySink event) or an injectable print_fn/log_fn "
                    "parameter so it reaches the structured event stream",
                    file=self.filename, line=node.lineno, key="print",
                ))
            elif node.func.id == "open":
                mode = None
                if (len(node.args) >= 2 and isinstance(node.args[1], ast.Constant)
                        and isinstance(node.args[1].value, str)):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if (kw.arg == "mode" and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        mode = kw.value.value
                if mode and mode.startswith("a"):
                    self.diags.append(D.make(
                        "GLC006", "append-mode open(..., %r) logging in "
                        "runtime library code: emit through the telemetry "
                        "sink (or hold ONE appending handle for the run, "
                        "like RuntimeProfiler.log_iteration)" % mode,
                        file=self.filename, line=node.lineno, key="open",
                    ))

    # ---- GLC007 --------------------------------------------------------
    def _axis_index_names(self, scope) -> Set[str]:
        """Names bound in `scope`'s own body (nested functions excluded)
        from a call to jax.lax.axis_index."""
        out: Set[str] = set()
        for node in self._walk_scope(scope):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            chain = _attr_chain(node.value.func)
            if not chain:
                continue
            rooted = self.aliases.jax.get(chain[0])
            if rooted is None:
                continue
            if (rooted + tuple(chain[1:]))[-1] == "axis_index":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    @staticmethod
    def _locally_bound(fn) -> Set[str]:
        a = fn.args
        bound = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if isinstance(fn, ast.Lambda):
            return bound
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                bound.add(n.id)
        return bound

    def check_custom_vjp_closures(self):
        """GLC007: a custom_vjp primal or vjp rule reads, as a free
        variable, a name its enclosing scope bound from jax.lax.axis_index.
        Inside a shard_map region that index is a per-shard traced value;
        closing over it bakes it into the rule's closure, where the legacy
        shard_map transpose replays it wrong (the PR-8 tp ring hazard).
        Recompute axis_index inside the rule instead."""
        if "GLC007" not in self.rules:
            return
        # vjp-rule surface: f.defvjp(fwd, bwd) args, f = jax.custom_vjp(g)
        # operands, and @jax.custom_vjp-decorated primals
        vjp_names: Set[str] = set()
        vjp_lambdas: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and node.func.attr == "defvjp":
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            vjp_names.add(a.id)
                        elif isinstance(a, ast.Lambda):
                            vjp_lambdas.add(id(a))
                else:
                    chain = _attr_chain(node.func)
                    if (chain and chain[-1] == "custom_vjp"
                            and self.aliases.jax.get(chain[0])
                            and node.args and isinstance(node.args[0], ast.Name)):
                        vjp_names.add(node.args[0].id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    chain = _attr_chain(target)
                    if (chain and chain[-1] == "custom_vjp"
                            and self.aliases.jax.get(chain[0])):
                        vjp_names.add(node.name)
        if not vjp_names and not vjp_lambdas:
            return
        for scope in ast.walk(self.tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            idx_names = self._axis_index_names(scope)
            if not idx_names:
                continue
            for nested in ast.walk(scope):
                if nested is scope:
                    continue
                is_vjp = (
                    isinstance(nested, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and nested.name in vjp_names
                ) or (isinstance(nested, ast.Lambda) and id(nested) in vjp_lambdas)
                if not is_vjp:
                    continue
                local = self._locally_bound(nested)
                for n in ast.walk(nested):
                    if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                            and n.id in idx_names and n.id not in local):
                        fname = getattr(nested, "name", "<lambda>")
                        self.diags.append(D.make(
                            "GLC007", "custom_vjp rule %r closes over %r, "
                            "bound from jax.lax.axis_index in the enclosing "
                            "scope: inside a shard_map region that index is "
                            "a per-shard traced value and the legacy "
                            "shard_map transpose replays the closure with "
                            "the wrong shard's value; recompute "
                            "jax.lax.axis_index inside the rule"
                            % (fname, n.id),
                            file=self.filename, line=n.lineno, key=n.id,
                        ))
                        break  # one finding per rule function

    # ---- pragmas -------------------------------------------------------
    def apply_pragmas(self) -> List[D.Diagnostic]:
        out = []
        for d in self.diags:
            if d.line is not None and 1 <= d.line <= len(self.lines):
                m = _PRAGMA_RE.search(self.lines[d.line - 1])
                if m and d.code in {c.strip() for c in m.group(1).split(",")}:
                    continue
            out.append(d)
        return out


ALL_RULES = frozenset(
    {"GLC001", "GLC002", "GLC003", "GLC004", "GLC005", "GLC006", "GLC007"})

# GLC006 scope: the runtime/observability library layers (posix or windows
# separators); CLI drivers, analysis tools and tests are exempt by path
_GLC006_PATH_RE = re.compile(r"(^|[/\\])galvatron_tpu[/\\](runtime|obs)[/\\]")


def lint_source(
    src: str,
    filename: str = "<string>",
    resolver: Optional[JaxResolver] = None,
    rules: Optional[Iterable[str]] = None,
) -> List[D.Diagnostic]:
    resolver = resolver or JaxResolver()
    rules = set(rules) if rules is not None else set(ALL_RULES)
    try:
        ml = _ModuleLint(src, filename, resolver, rules)
    except SyntaxError as e:
        return [D.make("GLC001", "file does not parse: %s" % e,
                       file=filename, line=e.lineno, severity=D.ERROR)]
    ml.scan_module()
    ml.check_attribute_chains()
    ml.check_jit_bodies()
    ml.check_donated_reuse()
    ml.check_host_syncs_in_loops()
    ml.check_runtime_logging()
    ml.check_custom_vjp_closures()
    return ml.apply_pragmas()


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                out.extend(
                    os.path.join(dirpath, f) for f in filenames if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_paths(
    paths: Iterable[str],
    rules: Optional[Iterable[str]] = None,
) -> D.DiagnosticReport:
    report = D.DiagnosticReport()
    resolver = JaxResolver()
    for f in iter_python_files(paths):
        with open(f, "r", encoding="utf-8") as fp:
            src = fp.read()
        report.extend(lint_source(src, filename=f, resolver=resolver, rules=rules))
    return report
