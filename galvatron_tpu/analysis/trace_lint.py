"""Traced-program lint: jaxpr-level sharding/collective auditor (``GLT***``).

The strategy linter (GLS, analysis/strategy_lint.py) sees the plan and the
code linter (GLC, analysis/code_lint.py) sees the source AST — but every
miscompile in this repo's history lived in the *traced* program, between the
two: the jax-0.4.37 GSPMD partitioner silently corrupting a reshape of a
sharded dim inside a scan (models/base.stack_layer_run), the unconstrained
microbatch split feeding the pipeline tick scan (parallel/pipeline.
make_pipelined_loss), and the fused stacked init under pp ``out_shardings``
(runtime/model_api.HybridParallelModel.init_params). This module abstract-
evals the SAME train-step the driver jits (no compile, no device transfers —
`jax.make_jaxpr` over ShapeDtypeStructs) and walks the ClosedJaxpr with a
sharding-propagation pass:

- a per-variable partition spec environment is seeded from every
  ``sharding_constraint`` eqn and from pjit ``in_/out_shardings``, and
  propagated through shape-preserving ops, transposes, broadcasts and 1:1
  reshapes;
- **GLT001** fires on a reshape that splits or merges an explicitly sharded
  dim inside a `scan` (or `while`) body — the stack_layer_run miscompile
  class;
- **GLT002** taints the output of any sharded-dim-splitting reshape and
  fires when the tainted value reaches a `scan` without an intervening
  ``sharding_constraint`` — the make_pipelined_loss class (the shipped
  ``split()`` constrains immediately, clearing the taint);
- **GLT003** fires on a pjit whose ``out_shardings`` shard dim *d* of an
  output produced by a stack (concatenate of size-1-along-*d* pieces) along
  that same dim — the init_params class;
- **GLT004** warns when a donated input has no same-shape/dtype output to
  alias (donation cannot buy anything and the caller may still hold the
  buffer);
- **GLT005** fires on the PR-8 hazard shape: a shard_map body containing a
  ``custom_vjp`` whose closure captured a traced ``axis_index`` from the
  enclosing scope — under `jax.grad` the capture surfaces as a *dangling*
  ``axis_index`` eqn (all outputs DropVars) next to the
  ``custom_vjp_call_jaxpr``;
- **GLT006** warns on psum-of-psum over the same axis inside a manual region
  (the cotangent double-count shape — the legacy shard_map transpose already
  psums over unmentioned manual axes, see parallel/tp_shard_map.py).

The collective audit (GLT101/GLT102) extracts every explicit collective
(psum/ppermute/all_gather/reduce_scatter/all_to_all) with its wire bytes
(from avals, multiplied by enclosing scan trip counts) and cross-checks the
result against ``TimeCostModel``'s per-LayerRun predicted comm
(obs/attribution.predict_layer_runs): a strategy that prices manual TP
collectives whose trace contains none is drift the online autotuner would
otherwise only discover after burning steps. GSPMD-mode collectives are
compiler-inserted *after* partitioning and are invisible at trace level;
the audit says so (GLT102) instead of pretending coverage.

Eqn ``source_info`` is mapped to file:line via the user-frame filter, so
findings point at model code, not jax internals. Everything here is
CPU-only and allocation-free: `jax.make_jaxpr` + `jax.eval_shape` over the
same path ``cli/train.py`` traces.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax

from galvatron_tpu.analysis import diagnostics as D

# Per-variable sharding knowledge: a tuple with one entry per array dim —
# `()` = known replicated on that dim, `("m0", ...)` = known sharded over
# those mesh axes, `None` = unknown. A variable absent from the environment
# is wholly unknown (treated as safe: the detectors only ever fire on
# *explicitly constrained* shardings, never on guesses).
DimSpec = Optional[Tuple[str, ...]]
Spec = Tuple[DimSpec, ...]

_COLLECTIVES = ("psum", "ppermute", "all_gather", "reduce_scatter",
                "all_to_all", "pmax", "pmin")

# single-output ops through which a value keeps its shape and layout intent
_SHAPE_PRESERVING = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "atan2",
    "and", "or", "xor", "not", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "nextafter",
    "exp", "exp2", "log", "log1p", "expm1", "tanh", "logistic", "sin",
    "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "sqrt", "rsqrt",
    "cbrt", "neg", "sign", "abs", "floor", "ceil", "round", "erf",
    "erfc", "erf_inv", "square", "integer_pow", "is_finite", "real",
    "imag", "conj", "clamp", "select_n", "convert_element_type",
    "stop_gradient", "copy", "reduce_precision", "eq", "ne", "lt", "le",
    "gt", "ge",
})


def _is_literal(v) -> bool:
    return hasattr(v, "val")


def _is_dropvar(v) -> bool:
    return type(v).__name__ == "DropVar"


def _is_jaxpr_like(v) -> bool:
    return hasattr(v, "eqns") or hasattr(v, "jaxpr")


def _open(j):
    """Jaxpr from a Jaxpr-or-ClosedJaxpr param value."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _src(eqn) -> Tuple[Optional[str], Optional[int]]:
    """eqn source_info -> (user file, line), skipping jax-internal frames."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, frame.start_line
    except Exception:
        pass
    return None, None


def _spec_of_sharding(sh, ndim: int) -> Optional[Spec]:
    """NamedSharding -> Spec; UnspecifiedValue/AUTO/None -> None (unknown).
    A constraint makes EVERY dim known: unmentioned dims are `()`."""
    pspec = getattr(sh, "spec", None)
    if pspec is None:
        return None
    entries = tuple(pspec)
    out: List[DimSpec] = []
    for i in range(ndim):
        e = entries[i] if i < len(entries) else None
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append((str(e),))
    return tuple(out)


def _sharded_axes(spec: Optional[Spec], dim: int) -> Tuple[str, ...]:
    if spec is None or dim >= len(spec) or spec[dim] is None:
        return ()
    return spec[dim]


def _reshape_blocks(in_shape, out_shape):
    """Greedy minimal equal-product blocks mapping input dims to output dims.
    Returns [(in_dims, out_dims), ...] or None when the shapes contain a zero
    (degenerate; nothing to check)."""
    if 0 in in_shape or 0 in out_shape:
        return None
    blocks = []
    i = j = 0
    while i < len(in_shape) or j < len(out_shape):
        ig, og = [], []
        pi = pj = 1
        if i < len(in_shape):
            pi *= in_shape[i]
            ig.append(i)
            i += 1
        if j < len(out_shape):
            pj *= out_shape[j]
            og.append(j)
            j += 1
        while pi != pj:
            if pi < pj and i < len(in_shape):
                pi *= in_shape[i]
                ig.append(i)
                i += 1
            elif pj < pi and j < len(out_shape):
                pj *= out_shape[j]
                og.append(j)
                j += 1
            else:  # ragged tail (cannot happen for equal-size reshapes)
                return None
        blocks.append((ig, og))
    return blocks


@dataclass
class _Taint:
    """A sharded-dim-splitting reshape whose output has not been re-
    constrained yet (the GLT002 precondition)."""

    file: Optional[str]
    line: Optional[int]
    axes: Tuple[str, ...]


@dataclass
class _Ctx:
    in_loop: int = 0  # scan/while body nesting depth
    trip: int = 1  # product of enclosing known scan lengths
    in_shard_map: bool = False
    manual_axes: Tuple[str, ...] = ()


class _State:
    def __init__(self):
        self.report = D.DiagnosticReport()
        self.collectives: List[Dict[str, Any]] = []
        self._seen = set()

    def emit(self, code: str, message: str, eqn, **kw) -> None:
        f, line = _src(eqn)
        key = (code, f, line)
        if key in self._seen:  # fwd + transposed bwd trace the same site
            return
        self._seen.add(key)
        self.report.add(D.make(code, message, file=f, line=line, **kw))


def _axes_of_collective(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name")
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list, frozenset, set)):
        return tuple(str(a) for a in axes)
    return (str(axes),)


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * getattr(dtype, "itemsize", 1)


def _map_env(outer_env, outer_taint, outer_vars, inner_vars):
    env: Dict[Any, Spec] = {}
    tnt: Dict[Any, _Taint] = {}
    for o, iv in zip(outer_vars, inner_vars):
        if _is_literal(o):
            continue
        if o in outer_env:
            env[iv] = outer_env[o]
        if o in outer_taint:
            tnt[iv] = outer_taint[o]
    return env, tnt


def _map_back(env, taint, inner_env, inner_taint, inner_outs, outer_outs):
    for bv, ov in zip(inner_outs, outer_outs):
        if _is_dropvar(ov) or _is_literal(bv):
            continue
        if bv in inner_env:
            env[ov] = inner_env[bv]
        if bv in inner_taint:
            taint[ov] = inner_taint[bv]


# --------------------------------------------------------------- the walker
def _walk(jaxpr, env, taint, ctx: _Ctx, st: _State) -> None:
    produced = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            if not _is_dropvar(ov):
                produced[ov] = eqn

    if ctx.in_shard_map:
        _check_dangling_axis_index(jaxpr, st)

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "sharding_constraint":
            _do_constraint(eqn, env, taint)
        elif prim == "reshape":
            _do_reshape(eqn, env, taint, ctx, st)
        elif prim == "transpose":
            _do_transpose(eqn, env, taint)
        elif prim == "squeeze":
            _do_squeeze(eqn, env, taint)
        elif prim == "broadcast_in_dim":
            _do_broadcast(eqn, env)
        elif prim == "optimization_barrier":
            for iv, ov in zip(eqn.invars, eqn.outvars):
                if _is_literal(iv) or _is_dropvar(ov):
                    continue
                if iv in env:
                    env[ov] = env[iv]
                if iv in taint:
                    taint[ov] = taint[iv]
        elif prim == "pjit":
            _do_pjit(eqn, env, taint, ctx, st)
        elif prim == "scan":
            _do_scan(eqn, env, taint, ctx, st)
        elif prim == "while":
            _do_while(eqn, ctx, st)
        elif prim == "cond":
            _do_cond(eqn, env, taint, ctx, st)
        elif prim == "shard_map":
            _do_shard_map(eqn, ctx, st)
        elif prim in ("custom_vjp_call_jaxpr", "custom_vjp_call",
                      "custom_jvp_call", "custom_jvp_call_jaxpr"):
            _do_custom_call(eqn, env, taint, ctx, st)
        elif prim in ("remat", "remat2", "checkpoint", "closed_call",
                      "core_call", "xla_call"):
            _do_inline_call(eqn, env, taint, ctx, st)
        elif prim in _COLLECTIVES:
            _do_collective(eqn, produced, ctx, st)
        elif prim in _SHAPE_PRESERVING:
            _do_elementwise(eqn, env, taint)
        else:
            # unknown container primitives still get walked (collectives and
            # constraint seeds inside must not go dark), with a fresh env
            for val in eqn.params.values():
                for j in (val if isinstance(val, (tuple, list)) else (val,)):
                    if _is_jaxpr_like(j):
                        _walk(_open(j), {}, {}, ctx, st)


def _do_constraint(eqn, env, taint) -> None:
    ov = eqn.outvars[0]
    spec = _spec_of_sharding(eqn.params.get("sharding"), len(ov.aval.shape))
    if spec is not None:
        env[ov] = spec
    # the constrained RESULT is clean; other consumers of the unconstrained
    # input stay tainted
    taint.pop(ov, None)


def _do_reshape(eqn, env, taint, ctx: _Ctx, st: _State) -> None:
    iv, ov = eqn.invars[0], eqn.outvars[0]
    in_shape = tuple(iv.aval.shape)
    out_shape = tuple(ov.aval.shape)
    spec = None if _is_literal(iv) else env.get(iv)
    if eqn.params.get("dimensions") is not None:
        # reshape fused with a permutation: too rare to model — spec unknown
        return
    blocks = _reshape_blocks(in_shape, out_shape)
    if blocks is None:
        return
    out_spec: List[DimSpec] = [None] * len(out_shape)
    hazard_axes: Tuple[str, ...] = ()
    for ig, og in blocks:
        nt_in = [d for d in ig if in_shape[d] != 1]
        nt_out = [d for d in og if out_shape[d] != 1]
        if len(nt_in) <= 1 and len(nt_out) <= 1:
            # 1:1 modulo size-1 dims: carry the spec across
            carried: DimSpec = ()
            if nt_in and spec is not None and nt_in[0] < len(spec):
                carried = spec[nt_in[0]]
            for d in og:
                out_spec[d] = () if out_shape[d] == 1 else carried
        else:
            # genuine split/merge block: hazardous iff an input dim in the
            # block is EXPLICITLY sharded
            for d in nt_in:
                ax = _sharded_axes(spec, d)
                if ax:
                    hazard_axes = hazard_axes + ax
    if hazard_axes:
        f, line = _src(eqn)
        if ctx.in_loop > 0:
            st.emit(
                "GLT001",
                "reshape %s -> %s splits/merges a dim sharded over %s inside "
                "a scan body — the jax-0.4.37 GSPMD partitioner miscompiles "
                "this shape (the stack_layer_run class); stack with jnp.stack "
                "or constrain to a replicated layout first"
                % (in_shape, out_shape, sorted(set(hazard_axes))),
                eqn,
            )
        else:
            taint[ov] = _Taint(file=f, line=line,
                               axes=tuple(sorted(set(hazard_axes))))
        return
    if not _is_literal(iv) and iv in taint:
        taint[ov] = taint[iv]
    if all(e is not None for e in out_spec):
        env[ov] = tuple(out_spec)


def _do_transpose(eqn, env, taint) -> None:
    iv, ov = eqn.invars[0], eqn.outvars[0]
    if _is_literal(iv):
        return
    if iv in taint:
        taint[ov] = taint[iv]
    spec = env.get(iv)
    if spec is None:
        return
    perm = eqn.params.get("permutation")
    if perm is None or len(perm) != len(spec):
        return
    env[ov] = tuple(spec[p] for p in perm)


def _do_squeeze(eqn, env, taint) -> None:
    iv, ov = eqn.invars[0], eqn.outvars[0]
    if _is_literal(iv):
        return
    if iv in taint:
        taint[ov] = taint[iv]
    spec = env.get(iv)
    if spec is None:
        return
    dims = set(eqn.params.get("dimensions") or ())
    env[ov] = tuple(s for d, s in enumerate(spec) if d not in dims)


def _do_broadcast(eqn, env) -> None:
    iv, ov = eqn.invars[0], eqn.outvars[0]
    if _is_literal(iv):
        return
    spec = env.get(iv)
    if spec is None:
        return
    bdims = eqn.params.get("broadcast_dimensions") or ()
    out_spec: List[DimSpec] = [()] * len(ov.aval.shape)
    for pos, bd in enumerate(bdims):
        if pos < len(iv.aval.shape) and iv.aval.shape[pos] == ov.aval.shape[bd]:
            out_spec[bd] = spec[pos] if pos < len(spec) else None
    if all(e is not None for e in out_spec):
        env[ov] = tuple(out_spec)


def _do_elementwise(eqn, env, taint) -> None:
    if len(eqn.outvars) != 1:
        return
    ov = eqn.outvars[0]
    if _is_dropvar(ov):
        return
    shape = tuple(getattr(ov.aval, "shape", ()))
    for iv in eqn.invars:
        if _is_literal(iv) or tuple(getattr(iv.aval, "shape", ())) != shape:
            continue
        if ov not in env and iv in env:
            env[ov] = env[iv]
        if ov not in taint and iv in taint:
            taint[ov] = taint[iv]


def _do_pjit(eqn, env, taint, ctx: _Ctx, st: _State) -> None:
    closed = eqn.params["jaxpr"]
    body = _open(closed)
    _check_stacked_init(eqn, body, st)
    _check_donation(eqn, st)
    env2, tnt2 = _map_env(env, taint, eqn.invars, body.invars)
    for sh, iv in zip(eqn.params.get("in_shardings") or (), body.invars):
        spec = _spec_of_sharding(sh, len(getattr(iv.aval, "shape", ())))
        if spec is not None:
            env2[iv] = spec
    _walk(body, env2, tnt2, ctx, st)
    _map_back(env, taint, env2, tnt2, body.outvars, eqn.outvars)
    for sh, ov in zip(eqn.params.get("out_shardings") or (), eqn.outvars):
        if _is_dropvar(ov):
            continue
        spec = _spec_of_sharding(sh, len(getattr(ov.aval, "shape", ())))
        if spec is not None:
            env[ov] = spec
            taint.pop(ov, None)  # an output constraint IS a constraint


def _check_stacked_init(eqn, body, st: _State) -> None:
    """GLT003: pjit output = stack (concatenate of size-1 pieces) along a dim
    its out_shardings shard — the init_params miscompile class."""
    out_sh = eqn.params.get("out_shardings") or ()
    if not out_sh:
        return
    produced = {}
    for e in body.eqns:
        for ov in e.outvars:
            if not _is_dropvar(ov):
                produced[ov] = e
    for sh, bv in zip(out_sh, body.outvars):
        if _is_literal(bv):
            continue
        spec = _spec_of_sharding(sh, len(getattr(bv.aval, "shape", ())))
        if spec is None:
            continue
        src_eqn = produced.get(bv)
        hops = 0
        while (src_eqn is not None and hops < 8
               and src_eqn.primitive.name in ("convert_element_type", "copy",
                                              "sharding_constraint")):
            nxt = src_eqn.invars[0]
            src_eqn = None if _is_literal(nxt) else produced.get(nxt)
            hops += 1
        if src_eqn is None or src_eqn.primitive.name != "concatenate":
            continue
        d = src_eqn.params.get("dimension", 0)
        if not _sharded_axes(spec, d):
            continue
        piece_sizes = [getattr(iv.aval, "shape", (0,))[d]
                       for iv in src_eqn.invars]
        out_size = bv.aval.shape[d]
        if len(piece_sizes) >= 2 and all(p == 1 for p in piece_sizes) \
                and len(piece_sizes) == out_size:
            st.emit(
                "GLT003",
                "jit output stacks %d pieces along dim %d while out_shardings "
                "shard that dim over %s — the jax-0.4.37 GSPMD partitioner "
                "produces silently wrong stacked entries (the init_params "
                "class); stack outside jit and device_put onto the shardings"
                % (len(piece_sizes), d, sorted(set(spec[d]))),
                src_eqn,
            )


def _check_donation(eqn, st: _State) -> None:
    """GLT004: a donated input whose aval matches no output aval cannot be
    aliased — XLA holds the buffer anyway and the caller loses access."""
    donated = eqn.params.get("donated_invars") or ()
    if not any(donated):
        return
    avail = Counter(
        (tuple(getattr(ov.aval, "shape", ())), str(getattr(ov.aval, "dtype", "")))
        for ov in eqn.outvars if not _is_dropvar(ov)
    )
    for don, iv in zip(donated, eqn.invars):
        if not don:
            continue
        key = (tuple(getattr(iv.aval, "shape", ())),
               str(getattr(iv.aval, "dtype", "")))
        if avail.get(key, 0) > 0:
            avail[key] -= 1
        else:
            st.emit(
                "GLT004",
                "donated input %s%s has no same-shape/dtype output to alias; "
                "the donation buys nothing and the caller's buffer is dead"
                % (key[1], list(key[0])),
                eqn,
            )


def _do_scan(eqn, env, taint, ctx: _Ctx, st: _State) -> None:
    closed = eqn.params["jaxpr"]
    body = _open(closed)
    num_consts = eqn.params.get("num_consts", 0)
    num_carry = eqn.params.get("num_carry", 0)
    length = int(eqn.params.get("length", 1) or 1)
    for iv in eqn.invars:
        if not _is_literal(iv) and iv in taint:
            rec = taint[iv]
            origin = ""
            if rec.file:
                origin = " (reshape at %s:%s)" % (rec.file, rec.line)
            st.emit(
                "GLT002",
                "a reshape that split/merged a dim sharded over %s%s feeds "
                "this scan with no sharding_constraint in between — the "
                "jax-0.4.37 GSPMD partitioner miscompiles the unconstrained "
                "split under the scan (the make_pipelined_loss class); "
                "constrain the reshaped value to an explicit layout first"
                % (list(rec.axes), origin),
                eqn,
            )
    env2: Dict[Any, Spec] = {}
    for k, (o, bv) in enumerate(zip(eqn.invars, body.invars)):
        if _is_literal(o):
            continue
        sp = env.get(o)
        if sp is None:
            continue
        if k >= num_consts + num_carry:
            sp = sp[1:] if len(sp) >= 1 else sp  # xs lose the scan dim
        env2[bv] = sp
    ctx2 = _Ctx(in_loop=ctx.in_loop + 1, trip=ctx.trip * max(length, 1),
                in_shard_map=ctx.in_shard_map, manual_axes=ctx.manual_axes)
    tnt2: Dict[Any, _Taint] = {}
    _walk(body, env2, tnt2, ctx2, st)
    for i in range(min(num_carry, len(eqn.outvars))):
        bv = body.outvars[i]
        ov = eqn.outvars[i]
        if not _is_literal(bv) and not _is_dropvar(ov) and bv in env2:
            env[ov] = env2[bv]


def _do_while(eqn, ctx: _Ctx, st: _State) -> None:
    for key in ("cond_jaxpr", "body_jaxpr"):
        j = eqn.params.get(key)
        if _is_jaxpr_like(j):
            ctx2 = _Ctx(in_loop=ctx.in_loop + 1, trip=ctx.trip,
                        in_shard_map=ctx.in_shard_map,
                        manual_axes=ctx.manual_axes)
            _walk(_open(j), {}, {}, ctx2, st)


def _do_cond(eqn, env, taint, ctx: _Ctx, st: _State) -> None:
    for br in eqn.params.get("branches") or ():
        body = _open(br)
        # operands follow the predicate
        env2, tnt2 = _map_env(env, taint, eqn.invars[1:], body.invars)
        _walk(body, env2, tnt2, ctx, st)


def _do_shard_map(eqn, ctx: _Ctx, st: _State) -> None:
    mesh = eqn.params.get("mesh")
    axis_names = tuple(getattr(mesh, "axis_names", ()) or ())
    auto = eqn.params.get("auto") or frozenset()
    manual = tuple(a for a in axis_names if a not in auto)
    body = _open(eqn.params["jaxpr"])
    ctx2 = _Ctx(in_loop=ctx.in_loop, trip=ctx.trip,
                in_shard_map=True, manual_axes=manual)
    # per-shard block shapes: the outer spec environment does not transfer
    _walk(body, {}, {}, ctx2, st)


def _do_custom_call(eqn, env, taint, ctx: _Ctx, st: _State) -> None:
    body = None
    for key in ("fun_jaxpr", "call_jaxpr", "jaxpr"):
        if _is_jaxpr_like(eqn.params.get(key)):
            body = _open(eqn.params[key])
            break
    if body is None:
        return
    if len(body.invars) == len(eqn.invars):
        env2, tnt2 = _map_env(env, taint, eqn.invars, body.invars)
    else:
        env2, tnt2 = {}, {}
    _walk(body, env2, tnt2, ctx, st)
    _map_back(env, taint, env2, tnt2, body.outvars, eqn.outvars)


def _do_inline_call(eqn, env, taint, ctx: _Ctx, st: _State) -> None:
    body = None
    for key in ("jaxpr", "call_jaxpr"):
        if _is_jaxpr_like(eqn.params.get(key)):
            body = _open(eqn.params[key])
            break
    if body is None:
        return
    if len(body.invars) == len(eqn.invars):
        env2, tnt2 = _map_env(env, taint, eqn.invars, body.invars)
    else:
        env2, tnt2 = {}, {}
    _walk(body, env2, tnt2, ctx, st)
    _map_back(env, taint, env2, tnt2, body.outvars, eqn.outvars)


def _do_collective(eqn, produced, ctx: _Ctx, st: _State) -> None:
    axes = _axes_of_collective(eqn)
    nbytes = sum(_aval_bytes(iv) for iv in eqn.invars)
    f, line = _src(eqn)
    st.collectives.append({
        "prim": eqn.primitive.name,
        "axes": axes,
        "bytes": nbytes * ctx.trip,
        "trip": ctx.trip,
        "manual_axes": ctx.manual_axes,
        "file": f,
        "line": line,
    })
    if eqn.primitive.name == "psum":
        for iv in eqn.invars:
            if _is_literal(iv):
                continue
            src_eqn = produced.get(iv)
            if src_eqn is not None and src_eqn.primitive.name == "psum":
                inner_axes = set(_axes_of_collective(src_eqn))
                if inner_axes & set(axes):
                    st.emit(
                        "GLT006",
                        "psum over %s consumes the result of another psum "
                        "over the same axis in one manual region — with the "
                        "legacy shard_map's automatic cotangent psum over "
                        "unmentioned manual axes this is the gradient "
                        "double-count shape (see parallel/tp_shard_map.py "
                        "autodiff note)" % (sorted(inner_axes & set(axes)),),
                        eqn,
                    )


def _check_dangling_axis_index(jaxpr, st: _State) -> None:
    """GLT005: inside a shard_map body, an ``axis_index`` whose every output
    is dropped, next to a custom_vjp call. This is exactly how a custom_vjp
    closure over an enclosing-scope traced axis_index surfaces under grad:
    the captured value rides the closure, the eqn that produced it dangles."""
    has_custom_vjp = any(
        e.primitive.name in ("custom_vjp_call_jaxpr", "custom_vjp_call")
        for e in jaxpr.eqns
    )
    if not has_custom_vjp:
        return
    for e in jaxpr.eqns:
        if e.primitive.name == "axis_index" \
                and e.outvars and all(_is_dropvar(v) for v in e.outvars):
            st.emit(
                "GLT005",
                "custom_vjp in this shard_map body closes over a traced "
                "axis_index computed in the enclosing scope (the dangling "
                "axis_index eqn is the capture); jax 0.4.37 miscompiles the "
                "transposed region — compute axis_index INSIDE the fwd/bwd "
                "functions instead (the tp_shard_map pattern)",
                e,
            )


# ---------------------------------------------------------------- entry API
@dataclass
class TraceLintResult:
    report: D.DiagnosticReport
    collectives: List[Dict[str, Any]] = field(default_factory=list)
    predicted: Optional[List[Dict[str, Any]]] = None

    def render_audit(self) -> str:
        """Human-readable collective-audit table (never printed in --json
        mode: stdout stays one JSON document)."""
        lines = ["traced collectives (bytes include scan trip counts):"]
        if not self.collectives:
            lines.append("  (none — gspmd collectives are compiler-inserted "
                         "after partitioning)")
        grouped: Dict[Tuple, Dict[str, Any]] = {}
        for c in self.collectives:
            key = (c["prim"], c["axes"], c["file"], c["line"])
            g = grouped.setdefault(key, {"count": 0, "bytes": 0})
            g["count"] += 1
            g["bytes"] += c["bytes"]
        for (prim, axes, f, line), g in sorted(
                grouped.items(), key=lambda kv: -kv[1]["bytes"]):
            loc = "%s:%s" % (f, line) if f else "<unknown>"
            lines.append("  %-14s axes=%-12s x%-3d %10d B  %s"
                         % (prim, ",".join(axes) or "-", g["count"],
                            g["bytes"], loc))
        if self.predicted:
            lines.append("cost-model predicted comm per LayerRun:")
            for row in self.predicted:
                if row.get("predicted_comm_ms") is None:
                    continue
                lines.append(
                    "  run %-4s layers %s-%s  %-22s comm %.4g ms"
                    % (row["run"], row.get("start"), row.get("stop"),
                       row.get("strategy"), row["predicted_comm_ms"]))
        return "\n".join(lines)


def abstract_batch(cfg, hp, data_kind: str = "lm") -> Dict[str, Any]:
    """ShapeDtypeStruct batch matching cli/train.py's input pipeline for the
    given family data kind. Only token-stream families are traceable here;
    callers turn the ValueError into a GLT102 skip."""
    import numpy as np

    if data_kind != "lm":
        raise ValueError(
            "trace lint supports token-stream (lm) families only; "
            "data_kind=%r has no abstract batch builder yet" % data_kind)
    bsz = hp.global_bsz
    seq = getattr(cfg, "max_seq_len", 64)
    tok = jax.ShapeDtypeStruct((bsz, seq), np.dtype("int32"))
    return {"tokens": tok, "positions": tok, "labels": tok}


def trace_train_step(model, tx=None, data_kind: str = "lm"):
    """ClosedJaxpr of the exact jitted train step cli/train.py dispatches —
    abstract tracing only: no compile, no buffers."""
    import optax

    tx = tx or optax.adam(1e-3)
    step = model.make_train_step(tx, donate=True)
    params = model.abstract_params()
    opt_state = jax.eval_shape(tx.init, params)
    batch = abstract_batch(model.cfg, model.hp, data_kind)
    return jax.make_jaxpr(step)(params, opt_state, batch)


def trace_init(model):
    """ClosedJaxpr of the init program init_params would run, mirroring its
    branch structure (the pp>1 path stacks OUTSIDE jit — that host-side stack
    is exactly the WA006 workaround, so only the jitted part is traced)."""
    import numpy as np

    rng = jax.ShapeDtypeStruct((2,), np.dtype("uint32"))
    if model.init_fn is None and model.hp.pp > 1:
        from galvatron_tpu.models import base as M

        return jax.make_jaxpr(
            jax.jit(lambda r: M.init_model_params(r, model.cfg)))(rng)
    return jax.make_jaxpr(
        jax.jit(model._init_fn, out_shardings=model.shardings()))(rng)


def _tp_axes(hp) -> set:
    from galvatron_tpu.parallel.mesh import layer_axes

    axes: set = set()
    for i in range(hp.num_layers):
        ax = layer_axes(hp, i)
        if getattr(ax, "tp", None) and not getattr(ax, "ulysses", False):
            axes.update(ax.tp)
    return axes


def _audit(model, result: TraceLintResult, st: _State) -> None:
    """GLT101/GLT102: cross-check traced collectives against the cost
    model's predicted comm. Conservative by design — only clear
    contradictions fire; gspmd-implicit comm is reported as invisible."""
    hp = model.hp
    try:
        from galvatron_tpu.obs.attribution import predict_layer_runs

        result.predicted = predict_layer_runs(model.cfg, hp)
    except Exception as e:  # analytic tables cannot price this family
        result.predicted = None
        st.report.add(D.make(
            "GLT102",
            "collective audit skipped: cost model cannot price this "
            "config (%s)" % e))
        return
    if result.predicted is None:
        st.report.add(D.make(
            "GLT102",
            "collective audit skipped: no analytic/profiled cost tables "
            "for this model family"))
        return
    tp_comm_mode = getattr(hp, "tp_comm_mode", "gspmd")
    tp_axes = _tp_axes(hp)
    traced_tp = [c for c in st.collectives if set(c["axes"]) & tp_axes]
    prices_manual_tp = tp_comm_mode in ("shard_map", "overlap") and any(
        row.get("predicted_comm_ms") for row in result.predicted)
    if prices_manual_tp and not traced_tp:
        st.report.add(D.make(
            "GLT101",
            "cost model prices manual TP collectives (tp_comm_mode=%s, "
            "predicted_comm_ms > 0) but the traced program contains no "
            "collective over the tp mesh axes %s — predicted-vs-traced "
            "drift; the plan and the program disagree"
            % (tp_comm_mode, sorted(tp_axes))))
    wants_quant = any(
        s.grad_comm_dtype != "none" or s.param_comm_dtype != "none"
        for s in hp.layers)
    if wants_quant and model.grad_fn is None and not st.collectives:
        st.report.add(D.make(
            "GLT101",
            "strategy requests quantized grad sync (an explicit shard_map "
            "collective ring) but the traced program contains no "
            "collectives at all — the quantized path was not taken"))
    max_tp = max([s.tp for s in hp.layers] + [1])
    if tp_comm_mode == "gspmd" and max_tp > 1 and not traced_tp:
        st.report.add(D.make(
            "GLT102",
            "tp_comm_mode=gspmd with tp>1: TP collectives are compiler-"
            "inserted after partitioning and invisible at trace level; "
            "the per-run comm audit covers manual regions only"))


def lint_hybrid_model(model, *, data_kind: str = "lm", audit: bool = True,
                      tx=None) -> TraceLintResult:
    """Trace-lint an already-constructed HybridParallelModel: train step +
    init program + (optionally) the collective audit."""
    st = _State()
    result = TraceLintResult(report=st.report)
    try:
        closed = trace_train_step(model, tx=tx, data_kind=data_kind)
    except ValueError as e:
        st.report.add(D.make(
            "GLT102", "train-step trace skipped: %s" % e))
        return result
    _walk(closed.jaxpr, {}, {}, _Ctx(), st)
    try:
        init_closed = trace_init(model)
    except Exception as e:
        st.report.add(D.make(
            "GLT102", "init trace skipped: %s" % e))
    else:
        _walk(init_closed.jaxpr, {}, {}, _Ctx(), st)
    result.collectives = st.collectives
    if audit:
        _audit(model, result, st)
    return result


def lint_model(cfg, hp, devices=None, *, data_kind: str = "lm",
               audit: bool = True, tx=None) -> TraceLintResult:
    """Construct the hybrid-parallel model for (cfg, hp) and trace-lint it —
    the same construction path cli/train.py runs before compiling."""
    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    model = construct_hybrid_parallel_model(cfg, hp, devices)
    return lint_hybrid_model(model, data_kind=data_kind, audit=audit, tx=tx)


def lint_closed_jaxpr(closed) -> TraceLintResult:
    """Walk an arbitrary ClosedJaxpr (the golden-repro tests' entry point)."""
    st = _State()
    _walk(closed.jaxpr, {}, {}, _Ctx(), st)
    result = TraceLintResult(report=st.report)
    result.collectives = st.collectives
    return result
