"""Static strategy-JSON linter (``GLS***`` diagnostics).

Validates a searched/hand-written hybrid-parallel strategy against a model
config and world size with *no device or tracing work*: a bad config is
refused in milliseconds on the host instead of minutes later as an opaque XLA
compile error or an OOM on real TPUs.

Check layers (each gated on the previous one succeeding):

1. raw-dict schema (shared with ``HybridParallelConfig.from_json``):
   unknown/typo'd keys with did-you-mean hints, missing required keys, array
   length mismatches, out-of-range flags — GLS001/GLS005/GLS006.
2. structural (shared with ``HybridParallelConfig.validate``): device-grid and
   batch divisibility — GLS002/GLS003/GLS004.
3. pipeline-engine consistency (``pipeline_engine_diagnostics``): gpipe
   stage-uniformity, ring-cp stage-uniformity under 1F1B — GLS010/GLS011.
4. model-aware divisibility (needs a model config): heads vs tp, sequence vs
   cp/sp shard degrees, vocab vs vocab-tp — GLS007/GLS008/GLS009.
5. cost-model-backed warnings: per-stage memory estimated through the search
   engine's own ``MemoryCostModel`` (profiled activation tables when
   available, an analytic Megatron-style estimate otherwise) vs the HBM
   budget — GLS101; adjacent-layer resharding — GLS102; runnable-but-odd
   configs — GLS103.

Entry points: `lint_strategy_dict`, `lint_strategy_file`, `lint_hp` (for an
already-constructed config — the train driver and search engine hook).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from galvatron_tpu.analysis import diagnostics as D
from galvatron_tpu.config.strategy import (
    HybridParallelConfig,
    schema_diagnostics,
)
from galvatron_tpu.utils.jsonio import read_json_config

# ------------------------------------------------------- model-aware checks


def _model_aware_diagnostics(hp: HybridParallelConfig, model_cfg: Any) -> List[D.Diagnostic]:
    """GLS007/GLS008/GLS009: divisibility of the model's head/sequence/vocab
    dimensions by the per-layer shard degrees. `model_cfg` is duck-typed
    (TransformerConfig or anything exposing the same fields); checks whose
    field is absent (e.g. swin configs have no flat ``num_heads``) are
    skipped rather than guessed."""
    out: List[D.Diagnostic] = []
    num_heads = getattr(model_cfg, "num_heads", None)
    num_kv = getattr(model_cfg, "num_kv_heads", None) or num_heads
    seq_len = getattr(model_cfg, "max_seq_len", None)
    vocab = getattr(model_cfg, "vocab_size", None)
    for i, s in enumerate(hp.layers):
        if num_heads is not None and s.tp > 1:
            # megatron-tp shards the head dim; ulysses all-to-all also
            # re-buckets by head — both need heads % tp == 0
            if num_heads % s.tp != 0:
                out.append(D.make(
                    "GLS007", "layer %d: num_heads=%d not divisible by tp=%d"
                    % (i, num_heads, s.tp), layer=i,
                ))
            elif num_kv is not None and num_kv % s.tp != 0 and s.tp % num_kv != 0:
                out.append(D.make(
                    "GLS007", "layer %d: num_kv_heads=%d neither divides nor "
                    "is divided by tp=%d; GQA heads will pad/replicate "
                    "unevenly" % (i, num_kv, s.tp), layer=i,
                    severity=D.WARNING,
                ))
        if seq_len is not None:
            if s.cp > 1 and seq_len % (2 * s.cp) != 0:
                # the zigzag ring layout splits each rank's shard in two
                # (ops/ring_attention.py asserts seq_len % (2*cp) == 0)
                out.append(D.make(
                    "GLS008", "layer %d: seq_len=%d not divisible by 2*cp=%d "
                    "(ring attention's zigzag layout needs two blocks per "
                    "rank)" % (i, seq_len, 2 * s.cp), layer=i,
                ))
            shard = s.seq_shard_degree * (
                s.tp if (not s.sp and hp.sequence_parallel) else 1
            )
            if shard > 1 and seq_len % shard != 0:
                out.append(D.make(
                    "GLS008", "layer %d: seq_len=%d not divisible by its "
                    "sequence shard degree %d (cp=%d, %s)"
                    % (i, seq_len, shard, s.cp,
                       "ulysses tp=%d" % s.tp if s.sp else "megatron-sp tp=%d" % s.tp),
                    layer=i,
                ))
    if vocab is not None and hp.vocab_tp > 1 and vocab % hp.vocab_tp != 0:
        out.append(D.make(
            "GLS009", "vocab_size=%d not divisible by vocab_tp=%d; pad the "
            "vocab (e.g. to %d) or lower vtp"
            % (vocab, hp.vocab_tp,
               (vocab + hp.vocab_tp - 1) // hp.vocab_tp * hp.vocab_tp),
            key="vtp",
        ))
    if seq_len is not None and hp.vocab_cp > 1 and seq_len % hp.vocab_cp != 0:
        out.append(D.make(
            "GLS008", "seq_len=%d not divisible by vocab_cp=%d (embed/head "
            "sequence sharding)" % (seq_len, hp.vocab_cp), key="vcp",
        ))
    return out


def _comm_quant_diagnostics(
    hp: HybridParallelConfig, model_cfg: Any,
    anomaly_guard: Optional[bool] = None,
) -> List[D.Diagnostic]:
    """GLS013/GLS103 for the comm-precision axis
    (parallel/quant_collectives.py). The quantized grad-sync path refuses —
    with the same reason string the trace-time assert raises — layouts it
    cannot express: non-pure-dp layers, vocab parallelism, zero2 grad
    accumulators, fp8 without runtime support, and composition with the
    anomaly guard (whose spike/rollback contract expects the bitwise GSPMD
    loss — `anomaly_guard` is driver state, so the check only fires when
    the caller passes it). Runnable-but-inert knobs warn GLS103: quantized
    comm with no dp group, param_comm_dtype with no ZeRO-3 leaf, and
    tp_comm_quant with nothing routed through the manual TP rings."""
    from galvatron_tpu.parallel import quant_collectives as QC

    out: List[D.Diagnostic] = []
    asks = any(
        s.grad_comm_dtype != "none" or s.param_comm_dtype != "none"
        for s in hp.layers
    )
    if asks:
        try:
            inert = all(hp.dp(i) <= 1 for i in range(hp.num_layers))
        except Exception:
            inert = False  # broken grids already reported by GLS002
        if inert:
            out.append(D.make(
                "GLS103", "grad/param comm dtypes are set but every layer "
                "has dp=1: there is no gradient sync to quantize",
                key="grad_comm_dtype",
            ))
        else:
            reason = QC.quant_comm_reason(model_cfg, hp,
                                          anomaly_guard=anomaly_guard)
            if reason is not None:
                out.append(D.make(
                    "GLS013", "quantized collectives: %s" % reason,
                    key="grad_comm_dtype",
                ))
        if any(s.param_comm_dtype != "none" and not s.fsdp for s in hp.layers):
            out.append(D.make(
                "GLS103", "param_comm_dtype set on a non-ZeRO-3 layer is "
                "inert: only fsdp=1 layers all-gather parameters",
                key="param_comm_dtype",
            ))
    if hp.tp_comm_quant != "none":
        # the gspmd combination is refused at construction (GLS013 in
        # structural_diagnostics); here the runnable-but-odd rest
        if hp.tp_comm_quant == "fp8_e4m3" and not QC.fp8_supported() \
                and hp.tp_comm_mode != "gspmd":
            out.append(D.make(
                "GLS013", "tp_comm_quant='fp8_e4m3' needs "
                "jax.numpy.float8_e4m3fn, which this jax does not provide",
                key="tp_comm_quant",
            ))
        elif hp.tp_comm_mode != "gspmd" and (
                all(s.tp <= 1 for s in hp.layers) or hp.pp > 1):
            out.append(D.make(
                "GLS103", "tp_comm_quant=%r is inert: no layer routes "
                "through the manual TP rings (%s)" % (
                    hp.tp_comm_quant,
                    "pp>1 keeps the GSPMD path" if hp.pp > 1
                    else "every layer has tp=1"),
                key="tp_comm_quant",
            ))
    return out


def _tp_comm_mode_diagnostics(hp: HybridParallelConfig, model_cfg: Any) -> List[D.Diagnostic]:
    """GLS012: the manual shard_map TP path (tp_comm_mode != gspmd) refuses
    configs it cannot express — report the refusal here, before any tracing,
    with the same reason run_layers would raise with. Deduplicated by
    reason; pp>1 is inert (GLS103), not refused, since the pipeline engines
    keep the GSPMD path."""
    out: List[D.Diagnostic] = []
    if hp.tp_comm_mode == "gspmd" or hp.pp > 1:
        return out
    from galvatron_tpu.parallel.tp_shard_map import manual_tp_reason

    seen = set()
    for i, s in enumerate(hp.layers):
        if s.tp <= 1:
            continue
        reason = manual_tp_reason(model_cfg, hp, s)
        if reason and reason not in seen:
            seen.add(reason)
            out.append(D.make(
                "GLS012", "layer %d: tp_comm_mode=%r refused: %s"
                % (i, hp.tp_comm_mode, reason), layer=i, key="tp_comm_mode",
            ))
    return out


# ----------------------------------------------------- cost-model warnings


def _analytic_parameter_mb(model_cfg: Any) -> Optional[float]:
    """fp32 MB of one transformer layer's parameters, from the model config
    alone (used when no profiled memory table is supplied)."""
    h = getattr(model_cfg, "hidden_size", None)
    nh = getattr(model_cfg, "num_heads", None)
    if h is None or nh is None:
        return None
    nkv = getattr(model_cfg, "num_kv_heads", None) or nh
    ffn = getattr(model_cfg, "ffn_hidden", None) or 4 * h
    attn = h * h * (2.0 + 2.0 * nkv / nh)  # q,o full; k,v scaled by GQA
    mlp_mats = 3 if getattr(model_cfg, "activation", "gelu") == "swiglu" else 2
    mlp = mlp_mats * h * ffn
    return (attn + mlp) * 4.0 / 2**20


def _analytic_activation_dict(model_cfg: Any, max_tp: int) -> Optional[Dict[Any, float]]:
    """Megatron-style per-sample live-activation MB per layer, keyed by tp
    degree (+ 'checkpoint' = the layer input only). bf16 residual stream:
    ~34*s*h bytes of intermediates + 5*a*s^2 of attention scores."""
    h = getattr(model_cfg, "hidden_size", None)
    nh = getattr(model_cfg, "num_heads", None)
    s = getattr(model_cfg, "max_seq_len", None)
    if h is None or nh is None or s is None:
        return None
    base = (34.0 * s * h + 5.0 * nh * s * s) / 2**20
    d: Dict[Any, float] = {"checkpoint": 2.0 * s * h / 2**20}
    t = 1
    while t <= max_tp:
        d[t] = base / t
        t *= 2
    return d


def estimate_stage_memory_mb(
    hp: HybridParallelConfig,
    model_cfg: Any = None,
    memory_profile: Optional[dict] = None,
) -> Optional[List[float]]:
    """Per-pipeline-stage estimated device memory (MB), priced through the
    search engine's MemoryCostModel so the linter and the search agree on
    what fits. `memory_profile` is the profiler's memory JSON
    (``layertype_0`` schema); without it, analytic tables derived from the
    model config are used. Returns None when neither source has enough
    information."""
    from galvatron_tpu.search.cost_model import MemoryCostModel
    from galvatron_tpu.search.cost_model_args import (
        ModelArgs,
        ParallelArgs,
        ProfileModelArgs,
        TrainArgs,
    )

    per_stage = hp.per_stage_devices
    if memory_profile is not None and "layertype_0" in memory_profile:
        lt = memory_profile["layertype_0"]
        param_mb = float(lt["parameter_size"])
        act_dict = dict(lt["tp_activation_per_bsz_dict"])
    else:
        param_mb = _analytic_parameter_mb(model_cfg) if model_cfg is not None else None
        act_dict = (
            _analytic_activation_dict(model_cfg, per_stage)
            if model_cfg is not None else None
        )
    if param_mb is None or not act_dict:
        return None
    seq_len = getattr(model_cfg, "max_seq_len", 2048) if model_cfg is not None else 2048
    hidden = getattr(model_cfg, "hidden_size", 1024) if model_cfg is not None else 1024
    ma = ModelArgs(parameter_size=param_mb, seq_length=seq_len,
                   hidden_size=hidden, layer_num=hp.num_layers)
    ta = TrainArgs(mixed_precision=hp.mixed_precision == "bf16")
    pa = ParallelArgs(
        use_zero2_for_dp=hp.default_dp_type == "zero2",
        sequence_parallel=hp.sequence_parallel,
        chunks=hp.chunks,
        pipeline_type=hp.pipeline_type,
        disable_vtp=True,  # embed/head priced analytically below
    )
    stage_mb = [0.0] * hp.pp
    for i, s in enumerate(hp.layers):
        info: Dict[str, int] = {}
        if s.sp:
            info["sp"] = 1
        if s.cp > 1:
            info["cp"] = s.cp
        if s.fsdp:
            info["fsdp"] = 1
        if s.checkpoint:
            info["cpt"] = 1
            if s.remat_policy != "full":
                info["rp"] = s.remat_policy
        strategy = [hp.pp, s.tp, hp.dp(i), info]
        cost = MemoryCostModel(
            strategy, global_batch_size=hp.global_bsz,
            mbsz=max(1, hp.global_bsz // max(1, hp.chunks)),
            min_tp=1, max_tp=per_stage, model_args=ma, train_args=ta,
            parallel_args=pa,
            profile_model_args=ProfileModelArgs(tp_activation_per_bsz_dict=act_dict),
        ).get_memory_cost()
        stage_mb[hp.stage_of_layer[i]] += cost["enc_total"]
    # embed/head states: vocab-parallel table(s), Adam fp32 states (~4x),
    # sharded over vocab_tp (and over pp for the 1F1B storage layout)
    vocab = getattr(model_cfg, "vocab_size", None) if model_cfg is not None else None
    if vocab is not None:
        tables = 1 if getattr(model_cfg, "tie_embeddings", True) else 2
        vmb = tables * vocab * hidden * 4.0 * 4.0 / 2**20 / hp.vocab_tp
        if hp.pp == 1:
            stage_mb[0] += vmb
        elif hp.pipeline_type == "pipedream_flush":
            for st in range(hp.pp):
                stage_mb[st] += vmb / hp.pp
        else:
            stage_mb[0] += vmb / tables
            stage_mb[-1] += vmb / tables
    return stage_mb


def _warning_diagnostics(
    hp: HybridParallelConfig,
    model_cfg: Any = None,
    memory_budget_gb: Optional[float] = None,
    memory_profile: Optional[dict] = None,
) -> List[D.Diagnostic]:
    out: List[D.Diagnostic] = []
    # GLS102: adjacent layers whose activations live on different mesh axes
    # force a resharding collective between them on every microbatch
    for i in range(1, hp.num_layers):
        a, b = hp.layers[i - 1], hp.layers[i]
        if hp.stage_of_layer[i - 1] != hp.stage_of_layer[i]:
            continue  # stage boundary: the p2p transfer reshards anyway
        moves = []
        if a.tp != b.tp or a.sp != b.sp:
            moves.append("tp%s%d->tp%s%d" % ("/sp" if a.sp else "", a.tp,
                                             "/sp" if b.sp else "", b.tp))
        if a.cp != b.cp:
            moves.append("cp%d->cp%d" % (a.cp, b.cp))
        if a.tp == b.tp and a.tp > 1 and a.tp_consec != b.tp_consec:
            moves.append("tp placement consec%d->consec%d" % (a.tp_consec, b.tp_consec))
        if moves:
            out.append(D.make(
                "GLS102", "layers %d->%d reshard activations within a stage "
                "(%s): an allgather/all-to-all per microbatch; consider "
                "aligning the run of layers" % (i - 1, i, ", ".join(moves)),
                layer=i,
            ))
    # GLS103: runnable but almost certainly not what was meant
    if hp.pp == 1 and hp.pipeline_type == "pipedream_flush":
        out.append(D.make(
            "GLS103", "pipeline_type='pipedream_flush' with pp=1 runs the "
            "plain single-stage path; the flag is inert", key="pipeline_type",
        ))
    for i, s in enumerate(hp.layers):
        if s.sp and s.tp == 1:
            out.append(D.make(
                "GLS103", "layer %d: use_sp=1 with tp=1 is a no-op (ulysses "
                "repurposes the tp axis)" % i, layer=i,
            ))
            break
    if hp.tp_comm_mode != "gspmd":
        if all(s.tp <= 1 for s in hp.layers):
            out.append(D.make(
                "GLS103", "tp_comm_mode=%r with tp=1 on every layer is "
                "inert: there are no TP collectives to make visible or "
                "overlap" % hp.tp_comm_mode, key="tp_comm_mode",
            ))
        elif hp.pp > 1:
            out.append(D.make(
                "GLS103", "tp_comm_mode=%r with pp=%d is inert: the "
                "pipeline engines drive layer_forward directly and keep "
                "the GSPMD TP path" % (hp.tp_comm_mode, hp.pp),
                key="tp_comm_mode",
            ))
    # remat precedence rule (config/strategy.py): the per-layer serialized
    # remat_policy is authoritative at runtime; a non-default global flag
    # that disagrees with any layer was shadowed, not applied
    if hp.remat_policy != "full" and any(
            s.remat_policy != hp.remat_policy for s in hp.layers):
        out.append(D.make(
            "GLS103", "global remat_policy=%r is shadowed by serialized "
            "per-layer policies (%d of %d layers differ): the per-layer "
            "field is authoritative; drop the flag or edit the JSON"
            % (hp.remat_policy,
               sum(1 for s in hp.layers if s.remat_policy != hp.remat_policy),
               hp.num_layers),
            key="remat_policy",
        ))
    # GLS101: estimated memory vs budget
    if memory_budget_gb:
        stage_mb = estimate_stage_memory_mb(hp, model_cfg, memory_profile)
        if stage_mb is not None:
            budget_mb = memory_budget_gb * 1024.0
            for st, mb in enumerate(stage_mb):
                if mb > budget_mb:
                    out.append(D.make(
                        "GLS101", "stage %d estimated %.2f GB exceeds the "
                        "%.1f GB budget (%s estimate via MemoryCostModel)"
                        % (st, mb / 1024.0, memory_budget_gb,
                           "profiled" if memory_profile else "analytic"),
                    ))
    return out


def serve_kv_mb_per_device(
    hp: HybridParallelConfig,
    model_cfg: Any,
    max_concurrency: int,
    page_size: int,
    dtype_bytes: int = 2,
) -> Optional[float]:
    """Per-device MB the decode KV cache pins: `max_concurrency` slots, each
    holding a full-context (k, v) pair per layer, sharded the way
    serve/kv_cache.layer_kv_spec shards it (slots over dp, kv heads over tp
    when divisible). The serve search and the GLS014 budget check price KV
    through this one function so they agree on what fits."""
    nh = getattr(model_cfg, "num_heads", None)
    hd = getattr(model_cfg, "head_dim", None)
    seq = getattr(model_cfg, "max_seq_len", None)
    if nh is None or seq is None:
        return None
    nkv = getattr(model_cfg, "num_kv_heads", None) or nh
    hd = hd or getattr(model_cfg, "hidden_size") // nh
    page = max(int(page_size), 1)
    max_ctx = -(-seq // page) * page  # bucket-quantised full context
    total = 0.0
    for i, s in enumerate(hp.layers):
        slots_per_dev = max_concurrency / max(hp.dp(i), 1)
        heads_per_dev = nkv / s.tp if (s.tp > 1 and nkv % s.tp == 0) else nkv
        total += 2.0 * slots_per_dev * max_ctx * heads_per_dev * hd * dtype_bytes
    return total / 2**20


def _serve_diagnostics(
    hp: HybridParallelConfig,
    model_cfg: Any,
    memory_budget_gb: Optional[float],
) -> List[D.Diagnostic]:
    """GLS014: layouts and budgets a decode engine cannot realise
    (serve/kv_cache.py raises the same refusals at construction; the lint
    fires them pre-trace with the layer named). Latency-bound infeasibility
    is the search engine's half of GLS014 — it needs the time cost models."""
    out: List[D.Diagnostic] = []
    if hp.pp > 1:
        out.append(D.make(
            "GLS014", "pp=%d: the decode engine drives single-token steps "
            "over one stage; pipeline parallelism is unsupported in serve "
            "mode" % hp.pp, key="pp_deg",
        ))
    for i, s in enumerate(hp.layers):
        if s.cp > 1:
            out.append(D.make(
                "GLS014", "layer %d: cp=%d — ring context parallelism never "
                "materialises the full per-layer k/v, so a decode cache "
                "cannot be filled; serve layouts require cp=1" % (i, s.cp),
                layer=i,
            ))
            break
    for i, s in enumerate(hp.layers):
        if s.sp:
            out.append(D.make(
                "GLS014", "layer %d: use_sp=1 (Ulysses) repurposes the tp "
                "axes for sequence all-to-alls a length-1 decode query "
                "cannot use; serve layouts require sp=0" % i, layer=i,
            ))
            break
    conc = hp.serve_max_concurrency
    if conc > 0 and model_cfg is not None and memory_budget_gb:
        kv_mb = serve_kv_mb_per_device(
            hp, model_cfg, conc, hp.serve_page_size or 16)
        layer_mb = _analytic_parameter_mb(model_cfg)
        if kv_mb is not None and layer_mb is not None:
            # bf16 inference weights, sharded over tp (and dp when fsdp)
            param_mb = sum(
                layer_mb / 2.0 / s.tp / (hp.dp(i) if s.fsdp else 1)
                for i, s in enumerate(hp.layers)
            )
            budget_mb = memory_budget_gb * 1024.0
            if kv_mb + param_mb > budget_mb:
                out.append(D.make(
                    "GLS014", "KV cache for %d concurrent slots needs %.1f MB"
                    "/device on top of %.1f MB of weights — over the %.1f GB "
                    "budget; lower concurrency, context, or raise tp/dp"
                    % (conc, kv_mb, param_mb, memory_budget_gb),
                    key="serve_max_concurrency",
                ))
    return out


# ------------------------------------------------------------- entry points


def lint_hp(
    hp: HybridParallelConfig,
    model_cfg: Any = None,
    memory_budget_gb: Optional[float] = None,
    memory_profile: Optional[dict] = None,
    file: Optional[str] = None,
    anomaly_guard: Optional[bool] = None,
    mode: Optional[str] = None,
    sdc_check: Optional[str] = None,
    sdc_interval: Optional[int] = None,
    autotune: Optional[str] = None,
    autotune_margin: Optional[float] = None,
    elastic_strategy: Optional[str] = None,
) -> D.DiagnosticReport:
    """Lint an already-constructed config (the train-driver / search-engine
    hook): engine-consistency + model-aware checks + cost warnings. The
    construction itself already enforced schema + structure.
    ``anomaly_guard`` is driver state (not part of the strategy): the train
    driver passes it so the quantized-comm x guard refusal (GLS013) fires
    pre-trace; file-level lints leave it None and skip that check.
    ``mode`` is likewise driver state: "serve" turns on the GLS014
    serve-feasibility layer (cli/serve and the serve-objective search),
    "train" warns GLS103 on inert serve knobs; None (file-level lint
    without --serve) runs neither. ``sdc_check``/``sdc_interval`` are the
    silent-corruption sentinel flags: voting on a layout with no per-device
    replica (runtime/sdc.vote_reason) silently downgrades at runtime, and
    an interval with the sentinel off is inert — both warned GLS103 here so
    the operator learns it before a multi-day run does.
    ``autotune``/``autotune_margin``/``elastic_strategy`` are the online-
    autotuner flags: `apply` composed with a pinned --elastic_strategy is
    refused outright (GLS017 — every swap the tuner performs would be undone
    by the next migration resolving back to the pinned JSON), and knobs that
    silently degrade or disable the tuner warn GLS103."""
    report = D.DiagnosticReport()
    report.extend(hp.structural_diagnostics())
    report.extend(hp.pipeline_engine_diagnostics())
    if model_cfg is not None:
        report.extend(_model_aware_diagnostics(hp, model_cfg))
    report.extend(_tp_comm_mode_diagnostics(hp, model_cfg))
    report.extend(_comm_quant_diagnostics(hp, model_cfg, anomaly_guard))
    report.extend(_warning_diagnostics(hp, model_cfg, memory_budget_gb, memory_profile))
    if mode == "serve":
        report.extend(_serve_diagnostics(hp, model_cfg, memory_budget_gb))
    elif mode == "train" and (hp.serve_max_concurrency or hp.serve_page_size):
        report.add(D.make(
            "GLS103", "serve_max_concurrency/serve_page_size are inert in "
            "train mode: only the serve engine allocates a KV cache",
            key="serve_max_concurrency",
        ))
    if mode == "train" and (hp.serve_p99_ttft_ms or hp.serve_max_pending):
        report.add(D.make(
            "GLS103", "serve_p99_ttft_ms/serve_max_pending are inert in "
            "train mode: admission control and overload shedding live in "
            "the serve batcher, not the training loop",
            key="serve_p99_ttft_ms",
        ))
    if sdc_check == "vote":
        from galvatron_tpu.runtime.sdc import vote_reason

        reason = vote_reason(hp)
        if reason is not None:
            report.add(D.make(
                "GLS103", "sdc_check=vote downgrades to digest on this "
                "layout (%s): cross-replica voting needs a full per-device "
                "parameter replica" % reason,
                key="sdc_check",
            ))
    if sdc_interval and (sdc_check or "off") == "off":
        report.add(D.make(
            "GLS103", "sdc_interval is inert with sdc_check off: there is "
            "no integrity digest to emit",
            key="sdc_interval",
        ))
    autotune_mode = autotune or "off"
    if autotune_mode == "apply" and elastic_strategy:
        report.add(D.make(
            "GLS017", "--autotune apply with a pinned --elastic_strategy: "
            "any strategy the autotuner swaps to would be reverted by the "
            "next migration resolving back to the pinned JSON; drop one of "
            "the two (observe mode composes fine)",
            key="autotune",
        ))
    if autotune_mode != "off":
        if not hp.scan_layers:
            report.add(D.make(
                "GLS103", "autotune with scan_layers off: every hot-swap "
                "recompiles a program whose build time grows with layer "
                "count, inflating the swap cost the amortization check "
                "must recover",
                key="autotune",
            ))
        if hp.pp > 1:
            report.add(D.make(
                "GLS103", "autotune with pp=%d: the pipeline engines bypass "
                "the per-LayerRun path, so the calibrator falls back to "
                "whole-step scaling and the measured tables are coarser"
                % hp.pp,
                key="autotune",
            ))
    if autotune_margin is not None and autotune_mode == "off":
        report.add(D.make(
            "GLS103", "autotune_margin is inert with autotune off: there "
            "is no re-search decision to apply the hysteresis to",
            key="autotune_margin",
        ))
    if file:
        report.diagnostics = [
            D.Diagnostic(**{**d.__dict__, "file": d.file or file})
            for d in report.diagnostics
        ]
    return report


def lint_strategy_dict(
    cfg_dict: dict,
    world_size: int,
    model_cfg: Any = None,
    memory_budget_gb: Optional[float] = None,
    memory_profile: Optional[dict] = None,
    file: Optional[str] = None,
    mode: Optional[str] = None,
    **overrides,
) -> D.DiagnosticReport:
    """Lint a raw strategy dict (the on-disk JSON schema) bottom-up. Stops
    after the schema layer if the dict cannot construct at all."""
    report = D.DiagnosticReport()
    schema = schema_diagnostics(cfg_dict)
    report.extend(schema)
    if any(d.severity == D.ERROR for d in schema):
        return _with_file(report, file)
    try:
        hp = HybridParallelConfig.from_json(cfg_dict, world_size=world_size, **overrides)
    except D.DiagnosticError as e:
        report.extend(e.diagnostics)
        return _with_file(report, file)
    except (KeyError, ValueError, TypeError) as e:
        report.add(D.make("GLS005", "config failed to construct: %s" % e))
        return _with_file(report, file)
    report.extend(lint_hp(
        hp, model_cfg=model_cfg, memory_budget_gb=memory_budget_gb,
        memory_profile=memory_profile, mode=mode,
    ).diagnostics)
    return _with_file(report, file)


def lint_strategy_file(
    path: str,
    world_size: int,
    model_cfg: Any = None,
    memory_budget_gb: Optional[float] = None,
    memory_profile: Optional[dict] = None,
    mode: Optional[str] = None,
    **overrides,
) -> D.DiagnosticReport:
    return lint_strategy_dict(
        read_json_config(path), world_size, model_cfg=model_cfg,
        memory_budget_gb=memory_budget_gb, memory_profile=memory_profile,
        file=path, mode=mode, **overrides,
    )


def _with_file(report: D.DiagnosticReport, file: Optional[str]) -> D.DiagnosticReport:
    if file:
        report.diagnostics = [
            D.Diagnostic(**{**d.__dict__, "file": d.file or file})
            for d in report.diagnostics
        ]
    return report
