"""Shared diagnostics framework for the static analyzers.

Both the strategy linter (analysis/strategy_lint.py, ``GLS***`` codes) and the
code linter (analysis/code_lint.py, ``GLC***`` codes) report through this
module so the CLI, the runtime config validator and CI all speak one format:

- `Diagnostic`: one finding — stable code, severity, message, location
  (file/line for code findings, layer/key for strategy findings), optional
  did-you-mean hint.
- `DiagnosticReport`: a collection with machine-readable JSON output
  (`to_json`), human rendering (`render`) and the exit-code contract
  (`exit_code`: 0 = clean or warnings only, 1 = at least one error).

This module is import-light on purpose (stdlib only, no jax, no other
galvatron modules) so `config/strategy.py` can raise structured
`DiagnosticError`s without creating an import cycle with the linters.
"""

from __future__ import annotations

import difflib
import json
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

ERROR = "error"
WARNING = "warning"

# ------------------------------------------------------------- code registry
# code -> (default severity, short title). The README's diagnostic-code table
# is generated from this registry (see `registry_table`), so it cannot drift.
CODES: Dict[str, Tuple[str, str]] = {
    # ---- strategy linter (GLS0xx structural errors) ----
    "GLS001": (ERROR, "unknown or misspelled strategy-JSON key"),
    "GLS002": (ERROR, "device-grid divisibility violation (world/pp/tp/cp/vocab)"),
    "GLS003": (ERROR, "pipeline division inconsistent with pp/layer count"),
    "GLS004": (ERROR, "batch divisibility violation (global_bsz/chunks/dp)"),
    "GLS005": (ERROR, "invalid field value or flag"),
    "GLS006": (ERROR, "per-layer arrays disagree in length"),
    "GLS007": (ERROR, "attention heads not divisible by tensor-parallel degree"),
    "GLS008": (ERROR, "sequence length not divisible by its shard degree"),
    "GLS009": (ERROR, "vocab size not divisible by vocab-parallel degree"),
    "GLS010": (ERROR, "cross-layer mesh-axis inconsistency within a pipeline stage"),
    "GLS011": (ERROR, "illegal activation-checkpoint placement"),
    "GLS012": (ERROR, "config unsupported by the manual shard_map TP path"),
    "GLS013": (ERROR, "unsupported comm-precision (quantized collectives) configuration"),
    "GLS014": (ERROR, "serve-infeasible configuration (latency bound, KV budget, or layout)"),
    "GLS015": (ERROR, "serve world infeasible after mesh degradation"),
    "GLS016": (ERROR, "state motion changed the layout-invariant integrity digest"),
    "GLS017": (ERROR, "online autotuner fighting a pinned strategy"),
    # ---- strategy linter (GLS1xx cost-model-backed warnings) ----
    "GLS101": (WARNING, "estimated per-device memory exceeds the HBM budget"),
    "GLS102": (WARNING, "expensive cross-layer redistribution between adjacent layers"),
    "GLS103": (WARNING, "suspicious but runnable configuration"),
    # ---- elastic resume / checkpoint portability (GLS20x) ----
    "GLS201": (ERROR, "model-config digest mismatch between checkpoint and run"),
    "GLS202": (ERROR, "optimizer state incompatible with the checkpoint's"),
    "GLS203": (ERROR, "no feasible strategy for the surviving mesh under the memory budget"),
    "GLS204": (ERROR, "checkpoint lacks the provenance elastic resume requires"),
    "GLS205": (ERROR, "world size changed but no replacement strategy was resolved"),
    "GLS206": (ERROR, "cross-strategy relayout unsupported for this model family"),
    "GLS207": (ERROR, "live in-memory strategy migration infeasible for this run"),
    # ---- checkpoint auditor (GLS21x) ----
    "GLS210": (ERROR, "checkpoint step without a committed integrity manifest (torn save)"),
    "GLS211": (WARNING, "stray or orphaned entry in the checkpoint directory"),
    "GLS212": (ERROR, "malformed checkpoint manifest or inconsistent provenance"),
    "GLS213": (WARNING, "checkpoint predates provenance (not elastically resumable)"),
    "GLS214": (ERROR, "checkpoint bytes no longer match the manifest's integrity digest"),
    # ---- code linter (GLC0xx) ----
    "GLC001": (ERROR, "jax attribute chain missing from the installed jax"),
    "GLC002": (WARNING, "host-side numpy call inside a jitted function"),
    "GLC003": (WARNING, "Python control flow on a traced value inside jit"),
    "GLC004": (ERROR, "donated buffer used again after the donating jit call"),
    "GLC005": (WARNING, "blocking host sync inside a loop in driver code"),
    "GLC006": (WARNING, "ad-hoc print/append-file logging in runtime library code"),
    "GLC007": (ERROR, "custom_vjp closes over a traced axis_index from an enclosing scope"),
    # ---- traced-program linter (GLT0xx jaxpr-level hazards) ----
    "GLT001": (ERROR, "reshape splits/merges an explicitly sharded dim inside a scan body"),
    "GLT002": (ERROR, "sharded-dim reshape feeds a scan without a sharding constraint"),
    "GLT003": (ERROR, "stacked init under out_shardings that shard the stacked dim"),
    "GLT004": (WARNING, "donated input has no same-shape/dtype output to alias"),
    "GLT005": (ERROR, "custom_vjp in a shard_map body closes over a dangling axis_index"),
    "GLT006": (WARNING, "psum-of-psum over the same axis in a manual region (double count)"),
    # ---- traced-program linter (GLT1xx collective audit) ----
    "GLT101": (WARNING, "traced collectives contradict the cost model's predicted comm"),
    "GLT102": (WARNING, "traced-program audit skipped or limited"),
    # ---- jax-workaround inventory (WA0xx, utils/jax_compat.py registry) ----
    "WA001": (WARNING, "shard_map modern-signature shim (axis_names/check_vma)"),
    "WA002": (WARNING, "jax.sharding.get_abstract_mesh fallback shim"),
    "WA003": (WARNING, "partial-manual shard_map compile gate (out-of-process probe)"),
    "WA004": (WARNING, "jnp.stack (not concat+reshape) in stack_layer_run scan stacking"),
    "WA005": (WARNING, "explicit sharding constraints on the pipeline microbatch split"),
    "WA006": (WARNING, "host-side per-layer init + stack outside jit under pp shardings"),
    "WA007": (WARNING, "persistent-cache bypass on XLA:CPU (deserialized-executable corruption)"),
    "WA008": (WARNING, "no manual psum of tp cotangents (legacy shard_map auto-psum contract)"),
}


@dataclass(frozen=True)
class Diagnostic:
    code: str
    severity: str
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    layer: Optional[int] = None
    key: Optional[str] = None
    hint: Optional[str] = None

    def format(self) -> str:
        loc = self.file or "<strategy>"
        if self.line is not None:
            loc += ":%d" % self.line
        if self.layer is not None:
            loc += " [layer %d]" % self.layer
        msg = "%s: %s %s: %s" % (loc, self.severity, self.code, self.message)
        if self.hint:
            msg += " (%s)" % self.hint
        return msg


def make(code: str, message: str, **loc) -> Diagnostic:
    """Build a Diagnostic for a registered code (severity from the registry;
    pass ``severity=`` to override, e.g. demoting an error to a warning)."""
    if code not in CODES:
        raise KeyError("unregistered diagnostic code %r" % code)
    severity = loc.pop("severity", CODES[code][0])
    return Diagnostic(code=code, severity=severity, message=message, **loc)


def did_you_mean(name: str, candidates: Iterable[str]) -> Optional[str]:
    """Closest-match hint for typo'd keys, or None when nothing is close."""
    matches = difflib.get_close_matches(name, list(candidates), n=1, cutoff=0.6)
    return "did you mean %r?" % matches[0] if matches else None


class DiagnosticError(ValueError):
    """Structured validation failure: carries the diagnostics that caused it
    (all errors), rendering like the legacy ValueErrors so existing
    ``pytest.raises(ValueError, match=...)`` callers keep working."""

    def __init__(self, diagnostics: List[Diagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__("; ".join("[%s] %s" % (d.code, d.message) for d in self.diagnostics))


@dataclass
class DiagnosticReport:
    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(self, diag: Diagnostic) -> None:
        self.diagnostics.append(diag)

    def extend(self, diags: Iterable[Diagnostic]) -> None:
        self.diagnostics.extend(diags)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        return not self.errors

    def exit_code(self) -> int:
        """The CLI contract: 0 = clean (warnings allowed), 1 = errors."""
        return 0 if self.ok else 1

    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": 1,
                "summary": {
                    "errors": len(self.errors),
                    "warnings": len(self.warnings),
                    "codes": self.codes(),
                },
                "diagnostics": [asdict(d) for d in self.diagnostics],
            },
            indent=2,
        )

    def render(self) -> str:
        lines = [d.format() for d in self.diagnostics]
        lines.append(
            "%d error(s), %d warning(s)" % (len(self.errors), len(self.warnings))
        )
        return "\n".join(lines)


def registry_table() -> str:
    """Markdown table of every registered code (used by the README section
    and by --explain)."""
    lines = ["| code | severity | meaning |", "|------|----------|---------|"]
    for code in sorted(CODES):
        sev, title = CODES[code]
        lines.append("| %s | %s | %s |" % (code, sev, title))
    return "\n".join(lines)
