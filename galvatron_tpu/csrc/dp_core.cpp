// Dynamic-programming core for the strategy search.
//
// Native re-implementation of the reference's pybind11 extension
// (reference: csrc/dp_core.cpp:24-124) with a plain extern "C" interface so
// Python loads it via ctypes (pybind11 is not available in this image).
//
// Contract (mirrors the reference): knapsack-style DP over
// (layer, memory, strategy) with inter-layer transition costs.
//   f[v][s]    = min cost to place layers 0..i with s at layer i, mem <= v
//   candidates = f[v - v_data[i][s]][si] + inter_cost[i][si][s] + intra_cost[i][s]
//   mark[i][v][s] = argmin_si   (for backtracking)
// After the sweep, for each candidate vocab-tp the caller supplies
// other_mem[vtp]; we read the best terminal state at v = max_mem-1-other_mem,
// backtrack the per-layer strategy indices, and report
// total_cost[vtp] (+ other_time[vtp]) and remaining memory.
//
// Arrays are C-contiguous, caller-allocated:
//   v_data      int32  [layer_num][strategy_num]
//   mark        int32  [layer_num][max_mem][strategy_num]
//   f           double [max_mem][strategy_num]      (zero-initialised)
//   inter_cost  double [layer_num][strategy_num][strategy_num]
//   intra_cost  double [layer_num][strategy_num]
//   per vtp:    res    int32  [layer_num]
// Build: make -C galvatron_tpu/csrc   (g++ -O2 -shared -fPIC)

#include <algorithm>
#include <cstdint>
#include <limits>
#include <vector>

extern "C" {

// Runs the full DP sweep. Returns 0 on success.
// Layer i reads layer i-1's table from a separate buffer (not in-place), so
// v_data entries of 0 (sub-MB layers truncated by the caller) cannot alias
// the row being written.
int dp_sweep(int layer_num, int max_mem, int strategy_num,
             const int32_t* v_data, int32_t* mark, double* f,
             const double* inter_cost, const double* intra_cost) {
  const double INF = std::numeric_limits<double>::infinity();
  const int64_t cells = static_cast<int64_t>(max_mem) * strategy_num;
  std::vector<double> prev(f, f + cells);  // layer-(i-1) table
  for (int i = 0; i < layer_num; ++i) {
    for (int v = max_mem - 1; v >= 0; --v) {
      for (int s = 0; s < strategy_num; ++s) {
        const int need = v_data[i * strategy_num + s];
        if (v < need) {
          mark[(static_cast<int64_t>(i) * max_mem + v) * strategy_num + s] = -1;
          f[static_cast<int64_t>(v) * strategy_num + s] = INF;
          continue;
        }
        const double* f_prev = prev.data() + static_cast<int64_t>(v - need) * strategy_num;
        const double* inter = inter_cost + (static_cast<int64_t>(i) * strategy_num) * strategy_num + s;
        double best = INF;
        int best_si = 0;
        for (int si = 0; si < strategy_num; ++si) {
          const double c = f_prev[si] + inter[static_cast<int64_t>(si) * strategy_num];
          if (c < best) {
            best = c;
            best_si = si;
          }
        }
        mark[(static_cast<int64_t>(i) * max_mem + v) * strategy_num + s] = best_si;
        f[static_cast<int64_t>(v) * strategy_num + s] = best + intra_cost[i * strategy_num + s];
      }
    }
    std::copy(f, f + cells, prev.begin());
  }
  return 0;
}

// Backtracks the winning strategy per layer for one memory budget.
// Returns total cost (inf if infeasible); fills res[layer_num] and
// *remaining_mem (-1 if infeasible).
double dp_backtrack(int layer_num, int max_mem, int strategy_num,
                    const int32_t* v_data, const int32_t* mark, const double* f,
                    int other_mem, int32_t* res, int* remaining_mem) {
  const double INF = std::numeric_limits<double>::infinity();
  *remaining_mem = -1;
  const int budget = max_mem - 1 - other_mem;
  if (budget < 0) return INF;
  const double* row = f + static_cast<int64_t>(budget) * strategy_num;
  int next = static_cast<int>(std::min_element(row, row + strategy_num) - row);
  double total = row[next];
  if (!(total < INF)) return INF;
  int v = budget;
  res[layer_num - 1] = next;
  for (int i = layer_num - 1; i > 0; --i) {
    const int cur = next;
    next = mark[(static_cast<int64_t>(i) * max_mem + v) * strategy_num + next];
    v -= v_data[i * strategy_num + cur];
    res[i - 1] = next;
  }
  *remaining_mem = v - v_data[0 * strategy_num + next];
  return total;
}

}  // extern "C"
