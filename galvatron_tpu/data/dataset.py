"""Indexed GPT dataset: memmapped token binaries + native sample-index builder.

TPU-native replacement for the reference's Megatron dataset stack
(site_package/megatron/core/datasets/: IndexedDataset, GPTDataset,
BlendedMegatronDatasetBuilder; glued in core/runtime/dataloader.py:4-20 and
models/gpt_hf/dataloader.py). Same three-index design:

  doc_idx    — document ids repeated per epoch, shuffled (epoch-wise);
  sample_idx — per sample, the (doc_idx position, token offset) where its
               seq_len+1 window starts (NATIVE: data/csrc/index_helpers.cpp,
               the helpers.cpp analogue);
  shuffle_idx— permutation of samples.

All three are pure functions of (corpus, seq_len, seed, epoch count), so a
resumed run rebuilds identical indices and the stream continues byte-for-byte
— the determinism-across-resume property called out in SURVEY.md §7.

On-disk format (our own, simpler than Megatron's .bin/.idx pair):
  <path>.bin — flat int32 token stream
  <path>.idx.npy — int64 document boundary offsets [n_docs + 1]
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.runtime.dataloader import prepare_batch

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libindex_helpers.so")
_lib = None


def _load_helpers():
    """Load (building if needed) the native index helper; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    try:
        subprocess.run(["make", "-C", _CSRC, "-s"], check=True, capture_output=True, timeout=120)
    except Exception:
        if not os.path.exists(_LIB_PATH):
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.build_sample_idx.restype = ctypes.c_int64
    lib.build_sample_idx.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    if hasattr(lib, "build_blending_indices"):
        lib.build_blending_indices.restype = None
        lib.build_blending_indices.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
    _lib = lib
    return _lib


def _build_sample_idx_py(doc_lens, doc_idx, seq_len, n_samples) -> np.ndarray:
    """Numpy fallback, same contract as the C++ helper."""
    out = np.zeros((n_samples + 1, 2), np.int64)
    pos, offset, sample = 0, 0, 0
    n = len(doc_idx)
    while sample < n_samples and pos < n:
        remaining = seq_len
        while remaining > 0 and pos < n:
            doc_left = int(doc_lens[doc_idx[pos]]) - offset
            if doc_left > remaining:
                offset += remaining
                remaining = 0
            else:
                remaining -= doc_left
                pos += 1
                offset = 0
        if remaining > 0:
            break
        sample += 1
        out[sample] = (pos, offset)
    return out[: sample + 1]


def build_sample_idx(doc_lens: np.ndarray, doc_idx: np.ndarray, seq_len: int,
                     n_samples: int) -> np.ndarray:
    """(n_emitted+1, 2) array of (doc_idx position, offset) boundaries."""
    lib = _load_helpers()
    doc_lens = np.ascontiguousarray(doc_lens, np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, np.int32)
    if lib is None:
        return _build_sample_idx_py(doc_lens, doc_idx, seq_len, n_samples)
    out = np.zeros((n_samples + 1, 2), np.int64)
    emitted = lib.build_sample_idx(
        doc_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        doc_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(doc_idx), seq_len, n_samples,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out[: emitted + 1]


# ------------------------------------------------------------------ on disk
def write_indexed_dataset(path: str, documents: Sequence[Sequence[int]]) -> None:
    """Write documents (token id lists) as <path>.bin + <path>.idx.npy."""
    offsets = np.zeros(len(documents) + 1, np.int64)
    for i, d in enumerate(documents):
        offsets[i + 1] = offsets[i] + len(d)
    tokens = np.concatenate([np.asarray(d, np.int32) for d in documents]) if documents else np.zeros(0, np.int32)
    tokens.tofile(path + ".bin")
    np.save(path + ".idx.npy", offsets)


class IndexedDataset:
    """Memmapped flat token stream with document boundaries."""

    def __init__(self, path: str):
        bin_path, idx_path = path + ".bin", path + ".idx.npy"
        if not os.path.exists(bin_path) or not os.path.exists(idx_path):
            raise FileNotFoundError(
                "indexed dataset %r needs %s and %s (write_indexed_dataset builds them)"
                % (path, bin_path, idx_path)
            )
        self.tokens = np.memmap(bin_path, dtype=np.int32, mode="r")
        self.offsets = np.load(idx_path)

    @property
    def n_docs(self) -> int:
        return len(self.offsets) - 1

    @property
    def doc_lens(self) -> np.ndarray:
        return (self.offsets[1:] - self.offsets[:-1]).astype(np.int32)

    def doc(self, i: int) -> np.ndarray:
        return self.tokens[self.offsets[i] : self.offsets[i + 1]]


def split_doc_ids(n_docs: int, split: str) -> Dict[str, np.ndarray]:
    """Contiguous train/valid/test document ranges from a weight string like
    "969,30,1" (Megatron --split semantics: get_train_valid_test_split_,
    consumed by the reference's BlendedMegatronDatasetBuilder). Deterministic —
    a pure function of (n_docs, split) — so a resumed run sees identical
    splits."""
    weights = [float(w) for w in split.split(",")]
    if len(weights) != 3 or any(w < 0 for w in weights) or sum(weights) <= 0:
        raise ValueError("--split needs three non-negative weights, got %r" % split)
    total = sum(weights)
    bounds = np.cumsum([0.0] + [w / total for w in weights])
    edges = np.round(bounds * n_docs).astype(np.int64)
    edges[-1] = n_docs
    names = ("train", "valid", "test")
    return {
        name: np.arange(edges[i], edges[i + 1], dtype=np.int32)
        for i, name in enumerate(names)
    }


class GPTDataset:
    """Sampled LM windows over an IndexedDataset (Megatron GPTDataset
    semantics: epoch-shuffled documents, overlapping seq_len+1 windows,
    sample-level shuffle). `documents` restricts the dataset to a doc-id
    subset (the split ranges from split_doc_ids)."""

    def __init__(self, indexed: IndexedDataset, seq_len: int, n_samples: int,
                 seed: int = 1234, documents: Optional[np.ndarray] = None):
        self.indexed = indexed
        self.seq_len = seq_len
        self.seed = seed
        self.documents = (
            np.arange(indexed.n_docs, dtype=np.int32)
            if documents is None else np.asarray(documents, np.int32)
        )
        if len(self.documents) == 0:
            raise ValueError("empty document subset (check the --split weights)")
        doc_lens = indexed.doc_lens[self.documents]
        total_tokens = int(doc_lens.sum())
        if total_tokens <= seq_len:
            raise ValueError(
                "split has %d tokens; need > seq_len=%d" % (total_tokens, seq_len)
            )
        samples_per_epoch = max((total_tokens - 1) // seq_len, 1)
        n_epochs = (n_samples + samples_per_epoch - 1) // samples_per_epoch + 1
        rng = np.random.RandomState(seed)
        doc_idx = np.concatenate([
            rng.permutation(len(self.documents)).astype(np.int32)
            for _ in range(n_epochs)
        ])
        self.sample_idx = build_sample_idx(doc_lens, doc_idx, seq_len, n_samples)
        self.doc_idx = doc_idx
        n_avail = len(self.sample_idx) - 1
        self.shuffle_idx = np.random.RandomState(seed + 1).permutation(n_avail)
        self.n_samples = n_avail

    def __len__(self) -> int:
        return self.n_samples

    def _doc(self, pos: int) -> np.ndarray:
        return self.indexed.doc(int(self.documents[self.doc_idx[pos]]))

    def __getitem__(self, i: int) -> np.ndarray:
        """seq_len+1 tokens (inputs + shifted target)."""
        i = int(self.shuffle_idx[i % self.n_samples])
        (p0, o0), (p1, o1) = self.sample_idx[i], self.sample_idx[i + 1]
        if p0 == p1:
            parts = [self._doc(p0)[o0 : o1 + 1]]
        else:
            parts = [self._doc(p0)[o0:]]
            for p in range(p0 + 1, p1):
                parts.append(self._doc(p))
            parts.append(self._doc(p1)[: o1 + 1])
        out = np.concatenate(parts)
        # the +1 target token may fall exactly on a boundary the walk did not
        # include (end of corpus walk); pad deterministically if so
        if len(out) < self.seq_len + 1:
            out = np.concatenate([out, np.zeros(self.seq_len + 1 - len(out), np.int32)])
        return out[: self.seq_len + 1]


def gpt_data_iterator(
    data_path: str,
    hp: HybridParallelConfig,
    seq_len: int,
    seed: int = 1234,
    n_samples: Optional[int] = None,
    start_step: int = 0,
    split: str = "train",
    split_weights: str = "969,30,1",
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Deterministic batch stream over one split of the indexed dataset
    (reference core/runtime/dataloader.py:4-20 builds all three splits).
    `data_path` may be a single prefix or a Megatron-style blend
    "W1 PREFIX1 W2 PREFIX2 ..." (BlendedMegatronDatasetBuilder). Batch
    content is a pure function of the step index, so resume passes
    `start_step` (O(1) skip); split ranges and the blend schedule are pure
    functions of the corpora + weights, so resume sees the same streams."""
    ds = _build_lm_dataset(data_path, seq_len, n_samples or 1_000_000,
                           seed, split, split_weights)
    step = start_step
    while True:
        rows = [ds[step * hp.global_bsz + b] for b in range(hp.global_bsz)]
        window = np.stack(rows)
        yield prepare_batch(hp, window[:, :-1], labels=window[:, 1:])
        step += 1


def gpt_train_iterator(data_path, hp, seq_len, seed=1234, n_samples=None,
                       start_step=0):
    """Back-compat alias: a train stream over the FULL corpus (no held-out
    splits — callers wanting splits use gpt_data_iterator)."""
    return gpt_data_iterator(data_path, hp, seq_len, seed=seed,
                             n_samples=n_samples, start_step=start_step,
                             split="train", split_weights="1,0,0")


# ---------------------------------------------------------- corpus blending
def build_blending_indices(weights: Sequence[float], n_samples: int):
    """Greedy blend schedule (reference helpers.cpp build_blending_indices via
    BlendedMegatronDatasetBuilder, models/gpt_hf/dataloader.py:7-8): sample i
    draws from the dataset whose running count lags its weight most, so every
    prefix of the stream tracks the requested proportions. Deterministic —
    a pure function of (weights, n_samples). Returns (dataset_index,
    dataset_sample_index) int arrays."""
    w = np.asarray(weights, np.float64)
    if (w <= 0).any():
        raise ValueError("blend weights must be positive, got %r" % (list(weights),))
    w = np.ascontiguousarray(w / w.sum())
    ds_index = np.zeros(n_samples, np.int32)
    ds_sample = np.zeros(n_samples, np.int64)
    lib = _load_helpers()
    if lib is not None and hasattr(lib, "build_blending_indices"):
        lib.build_blending_indices(
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(w), n_samples,
            ds_index.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ds_sample.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return ds_index, ds_sample
    # The greedy schedule (repeatedly pick argmin_k (count_k+1)/w_k, first
    # index on ties) is exactly a merge of the per-dataset key sequences
    # (j+1)/w_k, each strictly increasing — so it vectorizes to one lexsort
    # instead of an O(n_samples * n_datasets) interpreted loop (ADVICE r4).
    # Keys are the same doubles the native helper computes, so both paths
    # produce identical schedules including tie cases.
    # cap per-dataset keys at its share plus slack: at the n-th smallest key P,
    # n = sum_k floor(P*w_k) >= P - K, so count_k = floor(P*w_k) <= ceil(n*w_k) + K
    caps = np.minimum(
        np.ceil(w * n_samples).astype(np.int64) + len(w) + 2, n_samples
    )
    ks = np.repeat(np.arange(len(w), dtype=np.int32), caps)
    js = np.concatenate([np.arange(c, dtype=np.int64) for c in caps])
    prio = (js + 1).astype(np.float64) / w[ks]
    order = np.lexsort((ks, prio))[:n_samples]
    ds_index[:] = ks[order]
    ds_sample[:] = js[order]
    return ds_index, ds_sample


def parse_blend(data_path: str):
    """Megatron --data-path blend syntax: "W1 PREFIX1 W2 PREFIX2 ..." (or a
    single prefix). Returns (weights, prefixes). A multi-token string whose
    first token is not a number is treated as ONE path containing whitespace,
    not a malformed blend."""
    parts = data_path.split()
    if len(parts) <= 1:
        return [1.0], [data_path.strip() or data_path]
    try:
        float(parts[0])
    except ValueError:
        return [1.0], [data_path]
    if len(parts) % 2 != 0:
        raise ValueError(
            "blended --data_path must alternate WEIGHT PREFIX pairs, got %r" % data_path
        )
    weights = [float(parts[i]) for i in range(0, len(parts), 2)]
    prefixes = [parts[i] for i in range(1, len(parts), 2)]
    if any(not np.isfinite(w) or w <= 0 for w in weights):
        raise ValueError("blend weights must be positive, got %r" % weights)
    return weights, prefixes


def _build_lm_dataset(data_path: str, seq_len: int, total: int, seed: int,
                      split: str, split_weights: str):
    """Single-corpus GPTDataset or weighted blend, per the --data_path form.
    Each blended corpus is sized to roughly its weight share of `total`
    (plus the blend schedule's slack) instead of the full total — the
    sample-index build is the expensive part of construction (ADVICE r4)."""
    weights, prefixes = parse_blend(data_path)
    w = np.asarray(weights, np.float64)
    w = w / w.sum()
    per_corpus = []
    for k, prefix in enumerate(prefixes):
        indexed = IndexedDataset(prefix)
        docs = split_doc_ids(indexed.n_docs, split_weights)[split]
        n_k = total if len(prefixes) == 1 else int(np.ceil(w[k] * total)) + len(w) + 2
        per_corpus.append(GPTDataset(
            indexed, seq_len, n_k, seed=seed + k, documents=docs,
        ))
    return (per_corpus[0] if len(per_corpus) == 1
            else BlendedGPTDataset(per_corpus, weights, total))


class BlendedGPTDataset:
    """Weighted blend of per-corpus GPTDatasets (each already restricted to
    the requested split)."""

    def __init__(self, datasets: List[GPTDataset], weights: Sequence[float],
                 n_samples: int):
        if len(datasets) != len(weights):
            raise ValueError("need one weight per dataset")
        self.datasets = datasets
        self.ds_index, self.ds_sample = build_blending_indices(weights, n_samples)
        self.n_samples = n_samples

    def __len__(self):
        return self.n_samples

    def __getitem__(self, i: int) -> np.ndarray:
        i = i % self.n_samples
        return self.datasets[int(self.ds_index[i])][int(self.ds_sample[i])]


# ------------------------------------------------------- T5 span corruption
def t5_span_corrupt(tokens: np.ndarray, rng: np.random.RandomState, *,
                    vocab_size: int, noise_density: float = 0.15,
                    mean_span_len: float = 3.0, n_sentinels: int = 100):
    """T5 span-corruption of one token window (the reference's
    T5MaskedWordPieceDataset objective, models/T5/dataloader.py:152-200,
    re-derived from the T5 paper's denoising recipe rather than the megatron
    wordpiece masker): contiguous spans covering ~noise_density of the window
    are each replaced by ONE sentinel id in the encoder stream; the decoder
    target is [sentinel_i, span_i...] for every span, closed by a final
    sentinel. Sentinels count down from vocab_size-1 (HF T5 extra_ids).

    Returns (enc_tokens, dec_target) as int32 arrays (variable length)."""
    if not 0.0 < noise_density < 1.0:
        raise ValueError("noise_density must be in (0, 1), got %r" % noise_density)
    if mean_span_len <= 0:
        raise ValueError("mean_span_len must be positive, got %r" % mean_span_len)
    L = len(tokens)
    n_noise = min(max(int(round(L * noise_density)), 1), max(L - 1, 1))
    n_spans = max(int(round(n_noise / mean_span_len)), 1)
    # feasibility: the span-split draws n_spans-1 distinct cut points inside
    # (0, n_noise) and n_spans distinct starts over the L-n_noise+1 gap slots;
    # high noise_density / short windows would otherwise crash rng.choice
    n_spans = min(n_spans, n_noise, L - n_noise + 1)
    # random span lengths summing to n_noise (multinomial split)
    cuts = np.sort(rng.choice(np.arange(1, n_noise), size=n_spans - 1,
                              replace=False)) if n_noise > n_spans else np.arange(1, n_spans)
    span_lens = np.diff(np.concatenate([[0], cuts, [n_noise]]))
    span_lens = span_lens[span_lens > 0]
    # random span starts over the non-noise gaps
    n_gap = L - int(span_lens.sum())
    starts_gap = np.sort(rng.choice(np.arange(n_gap + 1), size=len(span_lens),
                                    replace=False))
    enc_parts, dec_parts = [], []
    pos = 0
    gap_consumed = 0
    for i, (g, sl) in enumerate(zip(starts_gap, span_lens)):
        keep = g - gap_consumed
        sentinel = vocab_size - 1 - (i % n_sentinels)
        enc_parts.append(tokens[pos : pos + keep])
        enc_parts.append(np.asarray([sentinel], np.int32))
        dec_parts.append(np.asarray([sentinel], np.int32))
        dec_parts.append(tokens[pos + keep : pos + keep + sl])
        pos += keep + sl
        gap_consumed = g
    enc_parts.append(tokens[pos:])
    dec_parts.append(np.asarray([vocab_size - 1 - (len(span_lens) % n_sentinels)], np.int32))
    return (np.concatenate(enc_parts).astype(np.int32),
            np.concatenate(dec_parts).astype(np.int32))


def t5_data_iterator(
    data_path: str,
    hp: HybridParallelConfig,
    enc_seq_len: int,
    dec_seq_len: int,
    seed: int = 1234,
    n_samples: Optional[int] = None,
    start_step: int = 0,
    split: str = "train",
    split_weights: str = "969,30,1",
    vocab_size: int = 32128,
    noise_density: float = 0.15,
    mean_span_len: float = 3.0,
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Span-corruption batch stream over one split of an indexed corpus.
    Emits the t5 batch contract (tokens/attn_mask/dec_tokens/labels/
    loss_mask) at STATIC shapes (enc_seq_len, dec_seq_len) — truncate/pad,
    jit sees one shape. `data_path` may be a single prefix or a Megatron
    blend "W1 PREFIX1 W2 PREFIX2 ..." (blending happens on the raw windows,
    before span corruption). Deterministic per (corpus, weights, seed,
    step)."""
    ds = _build_lm_dataset(data_path, enc_seq_len, n_samples or 1_000_000,
                           seed, split, split_weights)
    step = start_step
    while True:
        enc = np.zeros((hp.global_bsz, enc_seq_len), np.int32)
        attn = np.zeros((hp.global_bsz, enc_seq_len), np.float32)
        dec_in = np.zeros((hp.global_bsz, dec_seq_len), np.int32)
        labels = np.zeros((hp.global_bsz, dec_seq_len), np.int32)
        lmask = np.zeros((hp.global_bsz, dec_seq_len), np.float32)
        for b in range(hp.global_bsz):
            i = step * hp.global_bsz + b
            window = ds[i][:enc_seq_len]
            rng = np.random.RandomState((seed * 1_000_003 + i) % (2**31 - 1))
            e, d = t5_span_corrupt(
                window, rng, vocab_size=vocab_size,
                noise_density=noise_density, mean_span_len=mean_span_len,
            )
            e, d = e[:enc_seq_len], d[:dec_seq_len]
            enc[b, : len(e)] = e
            attn[b, : len(e)] = 1.0
            # teacher forcing: decoder input is the target shifted right
            # behind the pad/start id 0 (HF T5 _shift_right)
            dec_in[b, 1 : len(d)] = d[: len(d) - 1]
            labels[b, : len(d)] = d
            lmask[b, : len(d)] = 1.0
        yield {
            "tokens": jnp.asarray(enc),
            "attn_mask": jnp.asarray(attn),
            "dec_tokens": jnp.asarray(dec_in),
            "labels": jnp.asarray(labels),
            "loss_mask": jnp.asarray(lmask),
        }
        step += 1


# ------------------------------------------------------------- vision shards
def write_vision_dataset(path: str, images: np.ndarray, labels: np.ndarray) -> None:
    """Write <path>.images.npy + <path>.labels.npy shards (uint8 or float32
    NHWC images)."""
    if len(images) != len(labels):
        raise ValueError("images/labels length mismatch: %d vs %d" % (len(images), len(labels)))
    np.save(path + ".images.npy", images)
    np.save(path + ".labels.npy", np.asarray(labels, np.int32))


def vision_data_iterator(
    data_path: str,
    hp: HybridParallelConfig,
    image_size: int,
    num_channels: int,
    seed: int = 1234,
    start_step: int = 0,
    split: str = "train",
    split_weights: str = "969,30,1",
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Batch stream over .images.npy/.labels.npy shards (the vision analogue
    of the indexed LM corpus; the reference wires megatron-style datasets for
    swin/vit but trains on largely random pixels). Samples are memmapped;
    sample order is a deterministic per-epoch permutation of the split."""
    _, _prefixes = parse_blend(data_path)
    if len(_prefixes) > 1:
        raise ValueError(
            "corpus blending (\"W1 PREFIX1 W2 PREFIX2 ...\") is not supported "
            "for vision datasets; got --data_path %r" % data_path
        )
    data_path = _prefixes[0]
    img_path, lab_path = data_path + ".images.npy", data_path + ".labels.npy"
    if not os.path.exists(img_path) or not os.path.exists(lab_path):
        raise FileNotFoundError(
            "vision dataset %r needs %s and %s (write_vision_dataset builds them)"
            % (data_path, img_path, lab_path)
        )
    images = np.load(img_path, mmap_mode="r")
    labels = np.load(lab_path)
    if (images.shape[1] != image_size or images.shape[2] != image_size
            or images.shape[3] != num_channels):
        raise ValueError(
            "dataset images are %s; model expects (%d, %d, %d)"
            % (images.shape[1:], image_size, image_size, num_channels)
        )
    ids = split_doc_ids(len(images), split_weights)[split]
    if len(ids) == 0:
        raise ValueError("empty %s split over %d samples" % (split, len(images)))
    n = len(ids)
    step = start_step
    cur_epoch, perm = -1, None
    while True:
        batch_ids = []
        for b in range(hp.global_bsz):
            i = step * hp.global_bsz + b
            epoch, off = divmod(i, n)
            if epoch != cur_epoch:  # pure function of epoch: resume-safe
                perm = np.random.RandomState(seed + epoch).permutation(n)
                cur_epoch = epoch
            batch_ids.append(ids[perm[off]])
        px = np.stack([images[int(j)] for j in batch_ids])
        if px.dtype == np.uint8:
            px = px.astype(np.float32) / 255.0
        yield {
            "pixels": jnp.asarray(px.astype(np.float32)),
            "labels": jnp.asarray(labels[np.asarray(batch_ids)].astype(np.int32)),
        }
        step += 1
