// Native sample-index builder for the indexed GPT dataset.
//
// TPU-era equivalent of the reference's vendored Megatron dataset helper
// (site_package/megatron/core/datasets/helpers.cpp: build_sample_idx), which
// the reference compiles at runtime (core/runtime/dataloader.py:12-20). Same
// contract: walk the (epoch-repeated, shuffled) document order and emit, for
// every training sample, the (document-index position, within-document offset)
// where the sample's seq_len+1 token window starts. The walk is O(tokens) and
// dominates dataset startup for billion-token corpora — the reason both the
// reference and this build keep it native.
//
// Built by the Makefile next to this file into libindex_helpers.so and loaded
// via ctypes (galvatron_tpu/data/dataset.py); a numpy fallback covers
// environments without a toolchain.

#include <cstdint>

extern "C" {

// doc_lens:  token count per document id                      [n_docs]
// doc_idx:   document ids in epoch-shuffled traversal order   [n_doc_idx]
// sample_idx: out, (n_samples+1) rows of (doc_idx_pos, offset) [2*(n_samples+1)]
// Returns the number of samples actually emitted (<= n_samples).
int64_t build_sample_idx(const int32_t* doc_lens,
                         const int32_t* doc_idx,
                         int64_t n_doc_idx,
                         int64_t seq_len,
                         int64_t n_samples,
                         int64_t* sample_idx) {
    int64_t sample = 0;
    int64_t pos = 0;      // position in doc_idx
    int64_t offset = 0;   // token offset within doc_idx[pos]
    sample_idx[0] = pos;
    sample_idx[1] = offset;
    while (sample < n_samples && pos < n_doc_idx) {
        // advance seq_len tokens (sample windows overlap by 1 token: the
        // language-model target shift, matching Megatron's sample walk)
        int64_t remaining = seq_len;
        while (remaining > 0 && pos < n_doc_idx) {
            int64_t doc_left = (int64_t)doc_lens[doc_idx[pos]] - offset;
            if (doc_left > remaining) {
                offset += remaining;
                remaining = 0;
            } else {
                remaining -= doc_left;
                ++pos;
                offset = 0;
            }
        }
        if (remaining > 0) break;  // ran out of tokens
        ++sample;
        sample_idx[2 * sample] = pos;
        sample_idx[2 * sample + 1] = offset;
    }
    return sample;
}

// Greedy corpus-blend schedule (reference helpers.cpp
// build_blending_indices, consumed by BlendedMegatronDatasetBuilder):
// sample i draws from the dataset whose running count lags its normalised
// weight most, so every stream prefix tracks the requested proportions.
//
// weights:    normalised blend weights                [n_datasets]
// ds_index:   out, dataset id per sample              [n_samples]
// ds_sample:  out, within-dataset sample id           [n_samples]
void build_blending_indices(const double* weights,
                            int64_t n_datasets,
                            int64_t n_samples,
                            int32_t* ds_index,
                            int64_t* ds_sample) {
    int64_t* counts = new int64_t[n_datasets]();
    for (int64_t i = 0; i < n_samples; ++i) {
        int64_t best = 0;
        double best_err = 0.0;
        for (int64_t j = 0; j < n_datasets; ++j) {
            // key = (count+1)/w — the per-step common 1/(i+1) factor is
            // dropped so the numpy fallback (a lexsort merge of the same
            // per-dataset key sequences) computes bit-identical doubles
            double err = (double)(counts[j] + 1) / weights[j];
            if (j == 0 || err < best_err) {
                best = j;
                best_err = err;
            }
        }
        ds_index[i] = (int32_t)best;
        ds_sample[i] = counts[best];
        ++counts[best];
    }
    delete[] counts;
}

}  // extern "C"
