from galvatron_tpu.data.dataset import (
    GPTDataset,
    IndexedDataset,
    build_sample_idx,
    gpt_train_iterator,
    write_indexed_dataset,
)

__all__ = [
    "GPTDataset",
    "IndexedDataset",
    "build_sample_idx",
    "gpt_train_iterator",
    "write_indexed_dataset",
]
