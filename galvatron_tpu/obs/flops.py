"""Analytic model-FLOPs accounting and the peak-FLOPs registry behind MFU.

Model FLOPs (not hardware FLOPs): the arithmetic the model semantically
requires — matmul-dominated terms of attention (including the causal 0.5
factor), the MLP, and the embed/head projection — independent of remat
replay or compiler fusions, per the PaLM appendix-B convention. MFU is then
``model_flops / step_time / peak_flops`` on the device kind's peak dense
matmul throughput.

Two validation hooks keep the analytic numbers honest:

- :func:`xla_flops` reads ``cost_analysis()`` off a lowered/compiled XLA
  program where the backend reports flops (XLA:CPU does), and
  tests/obs/test_flops.py pins the analytic forward count against it on a
  tiny model;
- every consumer (RuntimeProfiler.summary, per-step telemetry, bench
  sections) reports model-FLOPs/s alongside MFU, so a wrong peak entry
  shifts MFU but never the throughput trend.

Import-light on purpose: math/os only at module scope — the bench
orchestrator (which must never import jax) reads the registry directly; jax
is touched only inside :func:`xla_flops`, which receives an already-built
jax object.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

# Peak dense matmul throughput per chip, FLOP/s, by device_kind prefix
# (jax Device.device_kind). bf16 for the TPU generations; the "cpu" entry is
# a NOMINAL single-host figure (a few GFLOP/s/core class) so CPU test runs
# still produce a well-defined MFU — treat absolute CPU MFU as a label, not
# a measurement. Extend via GALVATRON_PEAK_FLOPS (overrides everything).
PEAK_FLOPS_BY_KIND: Dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
    "cpu": 5e10,
}


def peak_flops_for(device_kind: Optional[str]) -> Optional[float]:
    """Peak FLOP/s for a device kind (longest-prefix match, case-insensitive);
    None when unknown. $GALVATRON_PEAK_FLOPS overrides the registry — the
    escape hatch for new chips and for declaring an honest CPU peak."""
    override = os.environ.get("GALVATRON_PEAK_FLOPS")
    if override:
        try:
            return float(override)
        except ValueError:
            pass
    if not device_kind:
        return None
    kind = device_kind.lower()
    best: Optional[float] = None
    best_len = -1
    for prefix, peak in PEAK_FLOPS_BY_KIND.items():
        if kind.startswith(prefix.lower()) and len(prefix) > best_len:
            best, best_len = peak, len(prefix)
    return best


# ------------------------------------------------------------ analytic FLOPs
def layer_fwd_flops(
    *,
    hidden: int,
    num_heads: int,
    seq_len: int,
    ffn_hidden: Optional[int] = None,
    head_dim: Optional[int] = None,
    num_kv_heads: Optional[int] = None,
    causal: bool = True,
    swiglu: bool = False,
    tokens: Optional[float] = None,
) -> float:
    """Forward model FLOPs of ONE transformer block over `tokens` tokens
    (default: one sequence). Matmul terms only (2 FLOPs per MAC); norms and
    elementwise activations are O(tokens*hidden) noise next to these."""
    tokens = float(seq_len if tokens is None else tokens)
    ffn = ffn_hidden or 4 * hidden
    hd = head_dim or hidden // num_heads
    nkv = num_kv_heads or num_heads
    q_dim = num_heads * hd
    # per-token projection matmuls: q, fused kv (GQA-scaled), out
    proj = 2.0 * hidden * q_dim + 2.0 * hidden * (2 * nkv * hd) + 2.0 * q_dim * hidden
    # per-token attention arithmetic: scores (q·kᵀ) + weighted sum (p·v),
    # each 2*S*q_dim; causal masks half the score matrix
    attn = 2.0 * (2.0 * seq_len * q_dim) * (0.5 if causal else 1.0)
    # MLP: swiglu projects to 2*ffn (gate+up) then back; gelu/relu ffn both ways
    mlp = (2.0 * hidden * (2 * ffn) + 2.0 * ffn * hidden) if swiglu \
        else (2.0 * hidden * ffn + 2.0 * ffn * hidden)
    return tokens * (proj + attn + mlp)


def layer_fwd_flops_from_config(cfg: Any, tokens: Optional[float] = None,
                                seq_len: Optional[int] = None) -> Optional[float]:
    """Duck-typed entry for TransformerConfig-shaped configs; None when the
    config lacks the transformer fields (custom families)."""
    hidden = getattr(cfg, "hidden_size", None)
    heads = getattr(cfg, "num_heads", None)
    seq = seq_len or getattr(cfg, "max_seq_len", None)
    if not hidden or not heads or not seq:
        return None
    return layer_fwd_flops(
        hidden=hidden,
        num_heads=heads,
        seq_len=seq,
        ffn_hidden=getattr(cfg, "ffn_hidden", None),
        head_dim=getattr(cfg, "head_dim", None),
        num_kv_heads=getattr(cfg, "num_kv_heads", None),
        causal=bool(getattr(cfg, "causal", True)),
        swiglu=getattr(cfg, "activation", "gelu") == "swiglu",
        tokens=tokens,
    )


def head_fwd_flops_from_config(cfg: Any, tokens: Optional[float] = None) -> float:
    """Embed/head projection FLOPs over `tokens` tokens: the vocab matmul for
    lm/mlm heads (embedding lookups are gathers, ~0 FLOPs), the class
    projection for classification heads."""
    hidden = getattr(cfg, "hidden_size", 0) or 0
    tokens = float(tokens if tokens is not None else getattr(cfg, "max_seq_len", 0) or 0)
    head_type = getattr(cfg, "head_type", "lm")
    if head_type in ("lm", "mlm"):
        vocab = getattr(cfg, "vocab_size", 0) or 0
        extra = 2.0 * hidden * hidden if head_type == "mlm" else 0.0  # transform dense
        return tokens * (2.0 * hidden * vocab + extra)
    if head_type == "classification":
        classes = getattr(cfg, "num_classes", 0) or 0
        # one pooled vector per sample; callers pass tokens=batch*seq, the
        # per-sample projection is seq-fold smaller — negligible, price ~0
        return 2.0 * hidden * classes
    return 0.0


def model_fwd_flops(cfg: Any, batch_size: int = 1) -> Optional[float]:
    """Whole-model forward FLOPs for one batch; None for configs the
    analytic model cannot describe."""
    seq = getattr(cfg, "max_seq_len", None)
    layers = getattr(cfg, "num_layers", None)
    if not seq or not layers:
        return None
    tokens = float(batch_size) * seq
    per_layer = layer_fwd_flops_from_config(cfg, tokens=tokens)
    if per_layer is None:
        return None
    return layers * per_layer + head_fwd_flops_from_config(cfg, tokens=tokens)


# backward ~= 2x forward (dL/dx and dL/dW each re-run every matmul)
BWD_FWD_RATIO = 2.0


def train_step_flops(cfg: Any, global_bsz: int) -> Optional[float]:
    """Model FLOPs of one optimizer step at `global_bsz`: forward + backward
    (3x forward). Remat replay is deliberately NOT counted — MFU measures
    useful arithmetic, recompute is overhead it should expose."""
    fwd = model_fwd_flops(cfg, batch_size=global_bsz)
    if fwd is None:
        return None
    return fwd * (1.0 + BWD_FWD_RATIO)


def train_flops_from_params(n_params: float, tokens: float, num_layers: int,
                            seq_len: int, hidden: int, causal: bool = True) -> float:
    """The 6*N*T parameter-count convention (+ attention term), for callers
    that have a live param tree instead of a config (bench.py's layer-stack
    sections)."""
    attn = 12.0 * num_layers * seq_len * hidden * tokens * (0.5 if causal else 1.0)
    return 6.0 * float(n_params) * float(tokens) + attn


def run_fwd_flops(cfg: Any, hp: Any) -> Optional[List[float]]:
    """Per-LayerRun forward FLOPs for one global batch (config/strategy
    layer_runs partitioning); None when the model is not analytically
    describable. The head/embed share is appended as a final pseudo-run so
    shares over the step sum to 1."""
    from galvatron_tpu.config.strategy import layer_runs

    tokens = float(hp.global_bsz) * (getattr(cfg, "max_seq_len", 0) or 0)
    per_layer = layer_fwd_flops_from_config(cfg, tokens=tokens)
    if per_layer is None or not tokens:
        return None
    out = [per_layer * run.length for run in layer_runs(hp)]
    out.append(head_fwd_flops_from_config(cfg, tokens=tokens))
    return out


# -------------------------------------------------------------- inference
def decode_step_flops(cfg: Any, batch_size: int = 1,
                      context_len: Optional[int] = None) -> Optional[float]:
    """Model FLOPs of ONE decode tick: `batch_size` slots each emit one
    token against a KV cache of `context_len` entries. Forward-only — no 3x
    train multiplier — and the attention term prices query-length 1 against
    the CACHE length (causal=False: the cache rows ARE the visible past, so
    no 0.5 triangular discount), which is what layer_fwd_flops computes when
    tokens=batch and seq_len=context. None for non-transformer configs."""
    layers = getattr(cfg, "num_layers", None)
    ctx = context_len or getattr(cfg, "max_seq_len", None)
    if not layers or not ctx:
        return None
    per_layer = layer_fwd_flops_from_config(
        cfg, tokens=float(batch_size), seq_len=int(ctx))
    if per_layer is None:
        return None
    # decode attention is not causal-masked: every cached position is live
    # (layer_fwd_flops_from_config honours cfg.causal, so undo the 0.5)
    if bool(getattr(cfg, "causal", True)):
        hd = getattr(cfg, "head_dim", None) or cfg.hidden_size // cfg.num_heads
        q_dim = cfg.num_heads * hd
        per_layer += float(batch_size) * (2.0 * (2.0 * ctx * q_dim)) * 0.5
    return layers * per_layer + head_fwd_flops_from_config(
        cfg, tokens=float(batch_size))


def model_bytes_per_decode_token(cfg: Any, *, context_len: Optional[int] = None,
                                 dtype_bytes: int = 2,
                                 batch_size: int = 1) -> Optional[float]:
    """HBM bytes one decode tick must stream per generated token: the full
    weight read (amortised over the batch — weights are read once per STEP,
    not per token) plus the token's own KV-cache read at `context_len`.
    This is the bandwidth-roofline denominator serving throughput divides
    by (search/cost_model.ServeTimeCostModel prices the same quantity from
    profiled tables); None for non-transformer configs."""
    hidden = getattr(cfg, "hidden_size", None)
    layers = getattr(cfg, "num_layers", None)
    heads = getattr(cfg, "num_heads", None)
    if not hidden or not layers or not heads:
        return None
    ctx = context_len or getattr(cfg, "max_seq_len", 0) or 0
    ffn = getattr(cfg, "ffn_hidden", None) or 4 * hidden
    hd = getattr(cfg, "head_dim", None) or hidden // heads
    nkv = getattr(cfg, "num_kv_heads", None) or heads
    swiglu = getattr(cfg, "activation", "gelu") == "swiglu"
    # per-layer weight elements: q + kv (GQA) + out projections and the MLP
    q_dim = heads * hd
    proj = hidden * q_dim + hidden * (2 * nkv * hd) + q_dim * hidden
    mlp = hidden * (2 * ffn) + ffn * hidden if swiglu else 2 * hidden * ffn
    weight_bytes = layers * (proj + mlp) * float(dtype_bytes)
    vocab = getattr(cfg, "vocab_size", 0) or 0
    weight_bytes += hidden * vocab * float(dtype_bytes)  # head matmul read
    kv_bytes = layers * 2.0 * ctx * nkv * hd * float(dtype_bytes)
    return weight_bytes / max(int(batch_size), 1) + kv_bytes


# ------------------------------------------------------------------ ratios
def mfu(flops_per_step: Optional[float], step_ms: Optional[float],
        peak_flops: Optional[float]) -> Optional[float]:
    """Model-FLOPs utilization; None when any input is unknown/degenerate."""
    if not flops_per_step or not step_ms or not peak_flops or step_ms <= 0:
        return None
    return flops_per_step / (step_ms / 1e3) / peak_flops


def flops_per_s(flops_per_step: Optional[float], step_ms: Optional[float]) -> Optional[float]:
    if not flops_per_step or not step_ms or step_ms <= 0:
        return None
    return flops_per_step / (step_ms / 1e3)


def xla_flops(lowered_or_compiled: Any) -> Optional[float]:
    """Total flops XLA's cost analysis reports for a lowered/compiled
    program; None when the backend does not report (TPU plugins vary) or the
    API shape differs. The validation hook for the analytic numbers.

    Caveat (pinned by tests/obs/test_flops.py): HloCostAnalysis counts a
    while/scan BODY once, not per trip — under scan-over-layer-runs the
    reported number covers one layer per run, so it under-reports a deep
    scanned model by roughly the run length. Compare against unrolled
    programs (or per-run bodies), and treat the recorded
    ``xla_flops_per_step`` as a lower bound."""
    try:
        analysis = lowered_or_compiled.cost_analysis()
    except Exception:
        return None
    # jax has returned both a dict and a per-device list of dicts here
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    val = analysis.get("flops")
    try:
        val = float(val)
    except (TypeError, ValueError):
        return None
    # XLA reports -1/0 when it cannot count
    return val if val > 0 else None
