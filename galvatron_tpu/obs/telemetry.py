"""Structured runtime telemetry: a buffered, schema-versioned JSONL stream.

The runtime previously emitted aggregate means (`RuntimeProfiler.summary()`)
and a free-text iteration log — no per-step record, nothing machine-readable
for the online autotuner (ROADMAP item 5) or the MFU-regression gate
(ROADMAP item 1) to consume. This module is the event spine:

- :class:`TelemetrySink` — validate-and-record API (``emit(type, **fields)``).
  Every event gets an envelope (schema version, wall time, monotonic
  sequence number) and is checked against :data:`EVENT_SCHEMAS`: unknown
  event types and unknown keys are rejected at emit time AND at read time,
  so a stream that parses is a stream the analysis layer can trust.
- :class:`JsonlSink` — the production backend. Writes happen on a daemon
  writer thread feeding from a bounded queue (the runtime/prefetch.py
  pattern applied to output): ``emit`` costs one validate + one enqueue on
  the critical path; serialization and file I/O run behind it. Ordering is
  exact (single queue, single worker), ``close()`` drains everything, and a
  writer-side exception is re-raised to the producer on the next
  emit/flush/close — a full disk fails the run, it does not silently drop
  the record.
- :class:`MemorySink` — in-memory list backend for tests and in-process
  consumers (the report analyzer accepts its events directly).
- a process-wide *active sink* (:func:`install` / :func:`emit`): deep
  runtime layers (checkpoint save/GC, elastic resume, retry backoff) emit
  lifecycle events without threading a sink handle through every call
  stack; with no sink installed the module-level :func:`emit` is a no-op.
- :func:`runtime_log` — the sanctioned replacement for bare ``print`` in
  library runtime code (lint rule GLC006): prints through an injectable
  ``print_fn`` AND records the same line as a ``log`` event.

stdlib-only on purpose (no jax, no numpy): the bench orchestrator and the
offline report CLI import this module without touching an accelerator stack.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

SCHEMA_VERSION = 1

# Envelope keys stamped onto every event by the sink.
ENVELOPE_KEYS = ("v", "t", "seq", "type")

# type -> (required field names, optional field names). Unknown types and
# unknown keys are rejected; None-valued optional fields are dropped at emit
# so readers never see explicit nulls.
EVENT_SCHEMAS: Dict[str, Tuple[Tuple[str, ...], Tuple[str, ...]]] = {
    # one per run: identity + the constants per-step MFU is computed from
    "run_start": (
        ("model", "world_size"),
        ("strategy", "train_iters", "global_bsz", "start_iter",
         "model_flops_per_step", "peak_flops", "device_kind", "pipeline_type",
         "num_layers", "resumed_from",
         # model-shape identity: enough for the offline calibrator
         # (report --emit_profiles) to rebuild analytic base tables and the
         # profiler's file tag without the live model config
         "model_type", "hidden_size", "num_heads", "num_kv_heads",
         "ffn_hidden", "vocab_size", "seq_len", "mixed_precision",
         "activation"),
    ),
    # one-off program build cost + the compiler-reported working set the
    # MemoryCostModel prediction is checked against
    "compile": (
        (),
        ("trace_ms", "compile_ms", "compiled_memory_mb", "xla_flops_per_step",
         "cache_hit"),
    ),
    # the per-step record (emitted at drain time under the dispatch-ahead
    # loop; iter_ms is dispatch->drain latency, which overlaps across steps)
    "step": (
        ("iter",),
        ("loss", "iter_ms", "dispatch_ms", "host_blocked_ms",
         "hbm_in_use_mb", "hbm_peak_mb", "mfu", "model_flops_per_s",
         "grad_norm"),
    ),
    "eval": (("iter", "split", "loss"), ()),
    # lifecycle: checkpointing
    "checkpoint_save": (("iteration",), ("duration_ms", "emergency", "path")),
    "checkpoint_restore": (
        ("iteration",),
        ("duration_ms", "path", "torn_skipped", "cross_strategy"),
    ),
    "checkpoint_gc": (("deleted",), ("path",)),
    # lifecycle: resilience
    "anomaly_skip": (("iter", "verdict"), ("loss", "strikes")),
    "rollback": (("to_iter",), ("at_iter", "count", "stream_offset")),
    "retry": (("description", "attempt"), ("error", "delay_s")),
    "preemption": (("signal",), ("iter",)),
    # the training watchdog (runtime/health.py): a missed progress deadline
    # ("fire" -> drain-and-retry, "escalate" -> emergency save + exit 3),
    # a stalled prefetch producer, or a degraded/wedged mesh-probe verdict —
    # each with the diagnostic dump the post-mortem needs (in-flight window
    # depth, last drained step, per-thread stacks)
    "watchdog": (
        ("action",),
        ("iter", "phase", "elapsed_s", "deadline_s", "inflight_depth",
         "last_drained", "fires", "stacks", "detail", "status",
         "expected", "live", "missing_ids"),
    ),
    # lifecycle: elastic resume / re-search; action="migrate" is the LIVE
    # in-memory strategy swap (runtime/elastic.migrate) and carries the full
    # before/after strategy JSON
    "elastic": (
        ("action",),
        ("saved_world", "live_world", "reason", "iter", "from_strategy",
         "to_strategy", "duration_ms", "same_layout"),
    ),
    # per-LayerRun prediction record (obs/attribution.py): what the search
    # engine's cost models expect, so the report can lay measured numbers
    # beside it
    "layer_run": (
        ("run", "start", "stop"),
        ("strategy", "predicted_ms", "predicted_memory_mb", "flops",
         "flops_share", "tp_comm_mode", "predicted_comm_ms",
         "predicted_comm_hidden_ms", "grad_comm_dtype",
         "predicted_quant_overhead_ms", "remat_policy",
         "predicted_recompute_ms"),
    ),
    # measured compute/collective overlap of the decomposed TP path
    # (parallel/tp_shard_map.measure_comm_hidden): per TP LayerRun, the
    # wall-clock of the run under the overlapped schedule vs the serialized
    # manual schedule — comm_hidden_ms is the communication the chunked
    # ppermute pipeline moved off the critical path
    "tp_overlap": (
        ("run",),
        ("start", "stop", "mode", "overlap_ms", "serial_ms",
         "comm_hidden_ms"),
    ),
    # comm-precision axis (parallel/quant_collectives.py): the run's wire
    # dtypes (comma list per layer), the measured quantize+dequantize toll,
    # and the bytes-on-wire estimate vs an fp32 sync — `cli report` joins
    # these into the predicted-vs-measured view
    "quant_comm": (
        ("grad_comm_dtype",),
        ("param_comm_dtype", "comm_quant_block", "tp_comm_quant",
         "quant_overhead_ms", "wire_mb_fp32", "wire_mb_configured"),
    ),
    # serving (serve/engine.ContinuousBatcher): one per completed request —
    # the raw timestamps (seconds on the batcher clock) plus the derived
    # latencies, so the report can recompute percentiles from either
    "serve_request": (
        ("id",),
        ("arrival_t", "prefill_start_t", "first_token_t", "done_t",
         "prompt_len", "output_len", "ttft_ms", "tpot_ms"),
    ),
    # one per decode tick: batch occupancy + the bucket it routed to
    "decode_batch": (
        ("step",),
        ("occupancy", "slots", "step_ms", "bucket_pages", "tokens"),
    ),
    # one per shed/failed request (admission control + overload shedding):
    # reason is "oversize" | "deadline" | "predicted_ttft" | "queue_full" |
    # "drain" | "prefill_error" | "decode_error" | "migrate_infeasible" |
    # "migrate_prefill_error"; retryable is 0/1 (oversize is the only
    # non-retryable rejection today)
    "serve_shed": (
        ("id", "reason"),
        ("retryable", "prompt_len", "output_len", "waited_ms",
         "predicted_ttft_ms", "queue_depth", "error"),
    ),
    # one per graceful drain (SIGTERM/SIGINT, watchdog escalation, or an
    # explicit control-plane drain): how the in-flight + pending load was
    # disposed of
    "serve_drain": (
        ("reason",),
        ("completed", "active_completed", "active_shed", "pending_shed",
         "shed", "exit_code"),
    ),
    # one per degraded-mesh serve migration: the world transition plus how
    # many in-flight requests were journal-replayed vs shed
    "serve_migrate": (
        ("from_world", "to_world"),
        ("replayed", "shed", "duration_ms", "reason", "from_strategy",
         "to_strategy", "kv_slots", "kv_pages"),
    ),
    # silent-corruption sentinel (runtime/sdc.py). sdc_check is the
    # high-volume heartbeat — one per digested step (mode="digest"/"vote",
    # gated by --sdc_interval) or per continuity assert (mode="continuity",
    # state motion named by `where`); like serve_shed it stays OFF the
    # report timeline. sdc_mismatch is one vote round that disagreed
    # (suspects = localized device ids, action = reexecute|quarantine);
    # sdc_quarantine is the strike-ladder escalation that feeds the
    # degraded-mesh migration path, naming the lying device ids.
    "sdc_check": (
        ("mode",),
        ("iter", "fold", "sumsq", "where"),
    ),
    "sdc_mismatch": (
        ("iter", "action"),
        ("suspects", "folds", "strikes"),
    ),
    "sdc_quarantine": (
        ("iter", "device_ids"),
        ("strikes", "reason"),
    ),
    # online autotuner (runtime/autotune.py). action="plan" is one
    # measured-cost re-search decision: reason is
    # "swap" | "hysteresis" | "amortization" | "identical" | "infeasible",
    # swapped is 0/1 (observe mode never swaps — a reason of "swap" with
    # swapped=0 is the logged counterfactual); the before/after strategy
    # JSON rides along like the elastic migrate event's. action="realized"
    # follows a swap once the new strategy re-settles, closing the
    # predicted-vs-realized loop.
    "autotune": (
        ("action",),
        ("iter", "mode", "reason", "steady_step_ms", "incumbent_ms",
         "winner_ms", "predicted_saving_ms", "margin", "remaining_steps",
         "swap_cost_ms", "swapped", "from_strategy", "to_strategy",
         "step_ms_before", "step_ms_after", "realized_saving_ms"),
    ),
    # jax.profiler start/stop_trace bracketing (--xla_trace)
    "trace": (("action",), ("dir", "first_step", "last_step", "error")),
    "log": (("message",), ()),
    "run_end": ((), ("summary",)),
}


class TelemetryError(RuntimeError):
    """Schema violation or a failed/closed sink."""


def validate_event(event: Dict[str, Any]) -> None:
    """Raise TelemetryError unless `event` is a schema-valid envelope+payload
    dict (shared by emit and by the offline reader)."""
    if not isinstance(event, dict):
        raise TelemetryError("event must be a dict, got %r" % type(event))
    etype = event.get("type")
    if etype not in EVENT_SCHEMAS:
        raise TelemetryError(
            "unknown telemetry event type %r (knowns: %s)"
            % (etype, ", ".join(sorted(EVENT_SCHEMAS)))
        )
    if event.get("v") != SCHEMA_VERSION:
        raise TelemetryError(
            "telemetry schema version %r != supported %d" % (event.get("v"), SCHEMA_VERSION)
        )
    required, optional = EVENT_SCHEMAS[etype]
    allowed = set(ENVELOPE_KEYS) | set(required) | set(optional)
    unknown = sorted(set(event) - allowed)
    if unknown:
        raise TelemetryError(
            "event %r carries unknown key(s) %s (allowed: %s)"
            % (etype, unknown, sorted(allowed))
        )
    missing = sorted(k for k in required if k not in event)
    if missing:
        raise TelemetryError("event %r missing required key(s) %s" % (etype, missing))


# ------------------------------------------------------------------- sinks
class TelemetrySink:
    """Validate-and-record base: subclasses implement `_write(event_dict)`.

    Thread-safe: emit may be called from the train loop, the prefetch
    worker's retry path, or a signal-adjacent drain; the envelope sequence
    number is the total order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self._closed = False

    def emit(self, etype: str, **fields) -> Dict[str, Any]:
        if self._closed:
            raise TelemetryError("emit() on a closed %s" % type(self).__name__)
        event: Dict[str, Any] = {
            "v": SCHEMA_VERSION,
            "t": time.time(),
            "type": etype,
        }
        event.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            event["seq"] = self._seq
            self._seq += 1
            validate_event(event)
            self._write(event)
        return event

    # -- subclass surface --------------------------------------------------
    def _write(self, event: Dict[str, Any]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False


class MemorySink(TelemetrySink):
    """In-memory backend (tests, in-process analysis)."""

    def __init__(self):
        super().__init__()
        self.events: List[Dict[str, Any]] = []

    def _write(self, event):
        self.events.append(event)


class NullSink(TelemetrySink):
    """Validates and drops (schema checking without storage)."""

    def _write(self, event):
        pass


def _json_default(obj):
    """Serialize numpy scalars/arrays (``.item()``/``.tolist()``) and other
    strays without making the emit sites care about dtypes."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                break
    if isinstance(obj, (set, frozenset, tuple)):
        return list(obj)
    return str(obj)


_FLUSH, _STOP = "flush", "stop"


class JsonlSink(TelemetrySink):
    """JSONL file backend with an off-critical-path writer thread.

    ``emit`` enqueues; the daemon worker serializes and writes. The queue is
    bounded (`depth`) so a stalled filesystem back-pressures the producer
    instead of ballooning host memory — the same containment contract as
    PrefetchIterator. `flush()` blocks until everything emitted so far is on
    disk (fsync not forced); `close()` flushes and joins. A writer exception
    is stored and re-raised on the next emit/flush/close."""

    def __init__(self, path: str, depth: int = 1024):
        super().__init__()
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # open in the producer so a bad path fails at construction, not
        # asynchronously on the first write
        self._fh = open(path, "w", encoding="utf-8")
        self._queue: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._worker, name="galvatron-telemetry", daemon=True
        )
        self._thread.start()

    # -- worker ------------------------------------------------------------
    def _worker(self):
        while True:
            tag, payload = self._queue.get()
            try:
                if tag == _STOP:
                    self._fh.flush()
                    return
                if tag == _FLUSH:
                    self._fh.flush()
                    payload.set()
                    continue
                self._fh.write(json.dumps(payload, default=_json_default) + "\n")
            except BaseException as e:  # noqa: BLE001 — relayed to producer
                self._error = e
                if tag == _FLUSH:
                    payload.set()
                if tag == _STOP:
                    return
            finally:
                self._queue.task_done()

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise TelemetryError(
                "telemetry writer failed for %s: %s" % (self.path, err)
            ) from err

    # -- producer ----------------------------------------------------------
    def _write(self, event):
        self._raise_pending()
        self._queue.put(("event", event))

    def flush(self, timeout: float = 10.0) -> None:
        self._raise_pending()
        if not self._thread.is_alive():
            return
        done = threading.Event()
        self._queue.put((_FLUSH, done))
        done.wait(timeout=timeout)
        self._raise_pending()

    def close(self, timeout: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        if self._thread.is_alive():
            self._queue.put((_STOP, None))
            self._thread.join(timeout=timeout)
        try:
            self._fh.close()
        except OSError as e:
            if self._error is None:
                self._error = e
        self._raise_pending()


# ----------------------------------------------------- process-wide routing
# The innermost installed sink receives module-level emit()s. A stack (not a
# single slot) so nested drivers (search trials calling train()) compose.
_ACTIVE: List[TelemetrySink] = []
_ACTIVE_LOCK = threading.Lock()


def install(sink: TelemetrySink) -> TelemetrySink:
    with _ACTIVE_LOCK:
        _ACTIVE.append(sink)
    return sink


def uninstall(sink: TelemetrySink) -> None:
    with _ACTIVE_LOCK:
        if sink in _ACTIVE:
            _ACTIVE.remove(sink)


def active_sink() -> Optional[TelemetrySink]:
    with _ACTIVE_LOCK:
        return _ACTIVE[-1] if _ACTIVE else None


def emit(etype: str, **fields) -> Optional[Dict[str, Any]]:
    """Emit to the active sink; no-op (returns None) when none is installed.
    Schema violations always propagate — they are bugs at the emit site, not
    runtime conditions."""
    sink = active_sink()
    if sink is None:
        return None
    return sink.emit(etype, **fields)


def runtime_log(message: str, print_fn=print) -> None:
    """Library-code logging: print through the injectable `print_fn` and
    mirror the line into the telemetry stream (the GLC006-sanctioned path
    for runtime/ and obs/ modules)."""
    print_fn(message)
    emit("log", message=message)


# ------------------------------------------------------------------ reading
def read_events(
    path_or_lines, strict: bool = True
) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Load and validate a telemetry JSONL. Returns (events, errors); with
    `strict`, the first malformed line raises TelemetryError instead. Events
    come back in file order (which equals emit order: single writer)."""
    if isinstance(path_or_lines, str):
        with open(path_or_lines, "r", encoding="utf-8") as fh:
            lines: Iterable[str] = fh.readlines()
    else:
        lines = path_or_lines
    events: List[Dict[str, Any]] = []
    errors: List[str] = []
    for n, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
            validate_event(event)
        except (ValueError, TelemetryError) as e:
            msg = "line %d: %s" % (n, e)
            if strict:
                raise TelemetryError(msg) from e
            errors.append(msg)
            continue
        events.append(event)
    return events, errors
