"""Steady-state detection over per-step time series — the one detector
shared by the offline report (`cli report`) and the online autotuner
(`runtime/autotune.py`).

The rule (unchanged from its original home in obs/report.py): the steady
region starts at the first index where the next `window` values have
stdev/mean <= rel_std. A series that never settles still yields a usable
tail — the post-25% median region — but the result says so explicitly:
`SteadyState.settled` is False and `method` is "fallback", so callers that
must not act on an unsettled run (the autotuner) can refuse while callers
that just need a number (the report) can keep printing one.

Two entry points:

- `detect(values)` — batch, for a recorded series (the report path).
- `SteadyStateDetector` — streaming, for the driver's drain loop: push
  each drained step's wall time; the detector settles at the first
  trailing window that meets the tolerance, which is the same index the
  batch scan would find on the series so far.

stdlib-only: this module is imported by the report CLI and the bench
orchestrator's children and must never pull in jax.
"""

from __future__ import annotations

import statistics
from dataclasses import dataclass
from typing import List, Optional, Sequence

__all__ = ["SteadyState", "SteadyStateDetector", "detect"]


@dataclass(frozen=True)
class SteadyState:
    """Where the steady region starts and how much to trust it.

    method is "rolling-window" (a window met the tolerance — `settled` is
    True), "fallback" (never settled; `start_index` is the post-25% tail
    start), or "empty" (`start_index` is None)."""

    start_index: Optional[int]
    method: str
    settled: bool
    window: int
    rel_std: float
    n: int  # samples examined

    def as_tuple(self):
        """(start_index, method) — the legacy report-API shape."""
        return self.start_index, self.method


def _window_settles(win: Sequence[float], rel_std: float) -> bool:
    mean = statistics.fmean(win)
    if mean <= 0:
        return False
    return statistics.pstdev(win) / mean <= rel_std


def detect(
    values: Sequence[float], window: int = 5, rel_std: float = 0.15
) -> SteadyState:
    """Batch steady-state detection over a full series. None entries are
    dropped (a step event without iter_ms contributes nothing)."""
    vals = [float(v) for v in values if v is not None]
    n = len(vals)
    if not vals:
        return SteadyState(None, "empty", False, window, rel_std, 0)
    if n >= max(window, 2):
        for i in range(0, n - window + 1):
            if _window_settles(vals[i:i + window], rel_std):
                return SteadyState(i, "rolling-window", True, window, rel_std, n)
    return SteadyState(
        min(n - 1, n // 4), "fallback", False, window, rel_std, n)


class SteadyStateDetector:
    """Streaming twin of `detect`: push per-step times as they drain.

    Settles at the first push whose trailing `window` values meet the
    tolerance — the minimal settling index, so the decision agrees with
    the batch scan over the same prefix. Once settled the decision is
    final (the autotuner treats a settle as one planning epoch; `reset()`
    starts a new epoch after a strategy swap)."""

    def __init__(self, window: int = 5, rel_std: float = 0.15):
        self.window = int(window)
        self.rel_std = float(rel_std)
        self._values: List[float] = []
        self._decision: Optional[SteadyState] = None

    def push(self, value: Optional[float]) -> Optional[SteadyState]:
        """Record one step time; returns the settled SteadyState (every
        call after settling) or None while still unsettled."""
        if value is not None:
            self._values.append(float(value))
            n = len(self._values)
            if (self._decision is None and n >= max(self.window, 2)
                    and _window_settles(self._values[-self.window:], self.rel_std)):
                self._decision = SteadyState(
                    n - self.window, "rolling-window", True,
                    self.window, self.rel_std, n)
        return self._decision

    @property
    def settled(self) -> bool:
        return self._decision is not None

    def state(self) -> SteadyState:
        """Current decision — the settled window if there is one, else the
        explicit fallback/empty result over everything seen so far."""
        if self._decision is not None:
            return self._decision
        return detect(self._values, window=self.window, rel_std=self.rel_std)

    def steady_tail(self) -> List[float]:
        """Values from the decided start on (settled or fallback)."""
        st = self.state()
        if st.start_index is None:
            return []
        return self._values[st.start_index:]

    def steady_step_ms(self) -> Optional[float]:
        """Median of the steady tail — the measured steady step time."""
        tail = self.steady_tail()
        return float(statistics.median(tail)) if tail else None

    def reset(self) -> None:
        """Forget everything — a new measurement epoch (post-swap)."""
        self._values = []
        self._decision = None
