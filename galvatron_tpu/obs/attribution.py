"""Predicted-vs-measured cost attribution per LayerRun.

The search engine picked the strategy from ``TimeCostModel``/
``MemoryCostModel`` predictions; the runtime measures only whole-step time
and whole-program memory. This module produces the bridge table ROADMAP
item 5's online autotuner re-plans from: for every :class:`LayerRun` (the
unit the runtime actually compiles and scans), the cost models' predicted
per-iteration time and memory next to the run's share of the measured
step.

Measured per-run shares come from FLOPs attribution of the scanned run
bodies (obs/flops.py — validated against XLA cost analysis where the
backend reports flops): the runs of a dense transformer differ by strategy,
not arithmetic, so model-FLOPs shares are exact for compute and the
residual divergence IS the signal — a run whose measured share outruns its
predicted share is paying for communication or remat the model mispriced.

Predictions price through the same cost-model classes the search used, with
the same profiled tables when given and the same analytic fallback tables
otherwise (runtime/elastic.py's ``analytic_*_profiles`` — the linter's
GLS101 estimate), so search, linter, elastic re-search, and this report can
never disagree about what a strategy was expected to cost.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from galvatron_tpu.config.strategy import HybridParallelConfig, LayerStrategy, layer_runs
from galvatron_tpu.obs import flops as F

HEAD_RUN = -1  # pseudo-run index for the embed/head share row


def strategy_as_list(s: LayerStrategy, hp: HybridParallelConfig, layer_idx: int) -> list:
    """A LayerStrategy in the cost models' reference list form
    [pp, tp, dp, info]."""
    info: Dict[str, int] = {}
    if s.sp:
        info["sp"] = 1
    if s.cp > 1:
        info["cp"] = s.cp
    if s.fsdp:
        info["fsdp"] = 1
    if s.checkpoint:
        info["cpt"] = 1
    if not s.tp_consec:
        info["tp"] = 0
    if s.grad_comm_dtype != "none":
        info["gcd"] = s.grad_comm_dtype
    if s.param_comm_dtype != "none":
        info["pcd"] = s.param_comm_dtype
    if s.remat_policy != "full":
        info["rp"] = s.remat_policy
    return [hp.pp, s.tp, hp.dp(layer_idx), info]


def describe_strategy(s: LayerStrategy, hp: HybridParallelConfig, layer_idx: int) -> str:
    return "tp%d%s cp%d dp%d%s%s%s" % (
        s.tp, "(sp)" if s.sp else "", s.cp, hp.dp(layer_idx),
        "(z3)" if s.fsdp else "",
        ((" ckpt" if s.remat_policy == "full" else " ckpt[%s]" % s.remat_policy)
         if s.checkpoint else ""),
        " g%s" % s.grad_comm_dtype if s.grad_comm_dtype != "none" else "",
    )


def predict_layer_runs(
    cfg: Any,
    hp: HybridParallelConfig,
    time_config: Optional[dict] = None,
    memory_config: Optional[dict] = None,
    hardware_configs: Optional[dict] = None,
) -> Optional[List[Dict[str, Any]]]:
    """Cost-model predictions per LayerRun, ready to emit as ``layer_run``
    telemetry events.

    Returns None for model families the analytic tables cannot describe
    (and no profiled tables were given). Each entry:
    ``{run, start, stop, strategy, predicted_ms, predicted_memory_mb,
    flops, flops_share}``; a final ``run == HEAD_RUN`` entry carries the
    embed/head FLOPs share so the shares sum to ~1 over the step."""
    from galvatron_tpu.analysis.strategy_lint import (
        _analytic_activation_dict,
        _analytic_parameter_mb,
    )
    from galvatron_tpu.runtime.elastic import (
        analytic_hardware_profiles,
        analytic_model_profiles,
    )
    from galvatron_tpu.search.cost_model import MemoryCostModel, TimeCostModel
    from galvatron_tpu.search.cost_model_args import (
        ModelArgs,
        ParallelArgs,
        ProfileHardwareArgs,
        ProfileModelArgs,
        TrainArgs,
        parse_hardware_profiles,
    )

    per_stage = hp.per_stage_devices

    # ---- model profile tables (profiled > analytic fallback) -------------
    if memory_config is not None and "layertype_0" in memory_config:
        lt = memory_config["layertype_0"]
        param_mb = float(lt["parameter_size"])
        act_dict = dict(lt["tp_activation_per_bsz_dict"])
    else:
        param_mb = _analytic_parameter_mb(cfg)
        act_dict = _analytic_activation_dict(cfg, per_stage)
    if time_config is not None and "layertype_0" in time_config:
        fwd_time = time_config["layertype_0"]
    else:
        synth = analytic_model_profiles(cfg, max_tp=per_stage)
        fwd_time = synth[0]["layertype_0"] if synth is not None else None
    if param_mb is None or not act_dict or fwd_time is None:
        return None

    # ---- hardware coefficient tables -------------------------------------
    if hardware_configs is None:
        allreduce, p2p, overlap = analytic_hardware_profiles(hp.world_size)
        hardware_configs = parse_hardware_profiles(allreduce, p2p, overlap)
    pha = ProfileHardwareArgs(
        comm_coe_dict=hardware_configs.get("comm_coe_dict", {"1": 0.0}),
        p2p_comm_coe_dict=hardware_configs.get("p2p_coe_dict") or None,
        dp_overlap_coe=hardware_configs.get("overlap_coe", 1.1),
        bct_overlap_coe=hardware_configs.get("overlap_coe", 1.1),
        allreduce_dict=hardware_configs.get("allreduce_dict", {}),
        all2all_dict=hardware_configs.get("all2all_dict", {}),
    )

    seq_len = getattr(cfg, "max_seq_len", 2048)
    ma = ModelArgs(parameter_size=param_mb, seq_length=seq_len,
                   hidden_size=getattr(cfg, "hidden_size", 1024),
                   layer_num=hp.num_layers)
    ta = TrainArgs(mixed_precision=hp.mixed_precision == "bf16")
    pa = ParallelArgs(
        use_zero2_for_dp=hp.default_dp_type == "zero2",
        sequence_parallel=hp.sequence_parallel,
        chunks=hp.chunks,
        pipeline_type=hp.pipeline_type,
        disable_vtp=True,  # embed/head is the HEAD_RUN flops row, not priced here
        comm_quant_block=hp.comm_quant_block,
    )
    pma = ProfileModelArgs(
        forward_computation_time=fwd_time,
        tp_activation_per_bsz_dict=act_dict,
        remat_recompute_frac=(time_config or {}).get("remat_recompute_frac"),
    )

    runs = layer_runs(hp)
    run_flops = F.run_fwd_flops(cfg, hp)  # len(runs)+1 (head), or None
    total_flops = sum(run_flops) if run_flops else None
    tp_comm_mode = getattr(hp, "tp_comm_mode", "gspmd")

    # chunks-aware pricing (ROADMAP item 5 leftover): mirror the engine's
    # pipeline_costmodel — per-MICROBATCH layer costs times the schedule's
    # tick count. A run's step share is length x per-mb cost x ticks/pp
    # (ticks = chunks + pp - 1, the GPipe fill+drain; the /pp spreads the
    # lockstep tick cost over the stages so the rows still sum to ~one
    # step). At chunks=1 this reduces exactly to the old full-batch
    # pricing, so calibrations against chunk-less runs are unchanged.
    chunks = max(1, int(hp.chunks or 1))
    mb_bsz = hp.global_bsz / chunks
    tick_factor = (chunks + hp.pp - 1) / hp.pp

    out: List[Dict[str, Any]] = []
    for idx, run in enumerate(runs):
        strategy = strategy_as_list(run.strategy, hp, run.start)
        tcm = TimeCostModel(
            strategy, global_batch_size=mb_bsz,
            model_args=ma, train_args=ta, parallel_args=pa,
            profile_model_args=pma, profile_hardware_args=pha,
        )
        per_layer_ms = tcm.gen_result() * tick_factor
        # the TP-collective share of the layer, priced on the same scale as
        # gen_result — the term tp_comm_mode=overlap can hide behind the
        # chunked matmul schedule (bounded by the compute it overlaps with,
        # the T3 perfect-overlap model)
        scale = pha.costmodel_coe / tcm.layer_num * tick_factor
        per_layer_comm_ms = tcm.tp_communication_time * scale
        per_layer_hidden_ms = 0.0
        if tp_comm_mode == "overlap" and run.strategy.tp > 1:
            per_layer_hidden_ms = min(per_layer_comm_ms,
                                      (tcm.fct + tcm.bct) * scale)
            per_layer_ms -= per_layer_hidden_ms
        per_layer_mb = MemoryCostModel(
            strategy, global_batch_size=hp.global_bsz,
            mbsz=max(1, hp.global_bsz // max(1, hp.chunks)),
            min_tp=1, max_tp=per_stage, model_args=ma, train_args=ta,
            parallel_args=pa, profile_model_args=pma,
        ).get_memory_cost()["enc_total"]
        entry: Dict[str, Any] = {
            "run": idx,
            "start": run.start,
            "stop": run.stop,
            "strategy": describe_strategy(run.strategy, hp, run.start),
            "predicted_ms": round(per_layer_ms * run.length, 4),
            "predicted_memory_mb": round(per_layer_mb * run.length, 2),
        }
        if run.strategy.tp > 1:
            entry["tp_comm_mode"] = tp_comm_mode
            entry["predicted_comm_ms"] = round(per_layer_comm_ms * run.length, 4)
            if tp_comm_mode == "overlap":
                entry["predicted_comm_hidden_ms"] = round(
                    per_layer_hidden_ms * run.length, 4)
        # comm-precision axis: what the cost model charges for the
        # quantize/dequantize passes rides its own column so the report can
        # lay it beside the measured quant_comm event
        if run.strategy.grad_comm_dtype != "none" \
                or run.strategy.param_comm_dtype != "none":
            entry["grad_comm_dtype"] = run.strategy.grad_comm_dtype
            entry["predicted_quant_overhead_ms"] = round(
                tcm.quant_overhead_ms * scale * run.length, 4)
        # remat axis: the policy-scaled recompute toll the cost model
        # charged into the backward, beside the policy itself, so the
        # report can lay predicted recompute against measured divergence
        eff_rp = run.strategy.effective_remat_policy
        if eff_rp != "none":
            entry["remat_policy"] = eff_rp
            entry["predicted_recompute_ms"] = round(
                tcm.fct * tcm.remat_frac * scale * run.length, 4)
        if run_flops is not None:
            entry["flops"] = run_flops[idx]
            entry["flops_share"] = round(run_flops[idx] / total_flops, 6)
        out.append(entry)
    if run_flops is not None:
        out.append({
            "run": HEAD_RUN,
            "start": hp.num_layers,
            "stop": hp.num_layers,
            "strategy": "embed/head vtp%d" % hp.vocab_tp,
            "flops": run_flops[-1],
            "flops_share": round(run_flops[-1] / total_flops, 6),
        })
    return out


# --------------------------------------------------------------- divergence
def divergence_rows(
    predictions: List[Dict[str, Any]],
    measured_step_ms: Optional[float] = None,
    measured_memory_mb: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Join per-run predictions with the measured step: each run's measured
    time is its FLOPs share of the steady-state step, memory its share of
    the compiled working set. `predictions` accepts both predict_layer_runs
    output and replayed ``layer_run`` telemetry events."""
    rows: List[Dict[str, Any]] = []
    for p in predictions:
        row = {k: p.get(k) for k in (
            "run", "start", "stop", "strategy", "predicted_ms",
            "predicted_memory_mb", "flops_share", "tp_comm_mode",
            "predicted_comm_ms", "predicted_comm_hidden_ms",
            "grad_comm_dtype", "predicted_quant_overhead_ms",
            "remat_policy", "predicted_recompute_ms",
        )}
        share = p.get("flops_share")
        if measured_step_ms is not None and share is not None:
            row["measured_ms"] = round(measured_step_ms * share, 4)
            if p.get("predicted_ms"):
                row["time_ratio"] = p["predicted_ms"] / row["measured_ms"] \
                    if row["measured_ms"] else None
        if measured_memory_mb is not None and share is not None \
                and p.get("predicted_memory_mb") is not None:
            row["measured_memory_mb"] = round(measured_memory_mb * share, 2)
        rows.append(row)
    return rows


def render_divergence_table(rows: List[Dict[str, Any]]) -> str:
    """Fixed-width text table of the divergence rows (the report CLI's
    human rendering)."""
    if not rows:
        return "(no layer-run predictions recorded)"
    # the comm columns only render when some run priced a TP-collective
    # path (tp>1); dp-only tables keep the original width
    has_comm = any(r.get("predicted_comm_ms") is not None for r in rows)
    has_quant = any(r.get("grad_comm_dtype") is not None for r in rows)
    has_remat = any(r.get("remat_policy") is not None for r in rows)
    header = ("run", "layers", "strategy", "pred_ms", "meas_ms", "ratio",
              "pred_mb", "share")
    if has_comm:
        header += ("comm_ms", "hid_ms")
    if has_quant:
        header += ("gcomm", "q_ms")
    if has_remat:
        header += ("remat", "rc_ms")
    body = []
    for r in rows:
        run = r.get("run")
        layers = ("%d-%d" % (r["start"], r["stop"] - 1)
                  if r.get("stop") and r["stop"] > r.get("start", 0) else "-")
        cells = (
            "head" if run == HEAD_RUN else str(run),
            layers,
            str(r.get("strategy") or "-"),
            _fmt(r.get("predicted_ms")),
            _fmt(r.get("measured_ms")),
            _fmt(r.get("time_ratio")),
            _fmt(r.get("predicted_memory_mb")),
            _fmt(r.get("flops_share")),
        )
        if has_comm:
            cells += (_fmt(r.get("predicted_comm_ms")),
                      _fmt(r.get("predicted_comm_hidden_ms")))
        if has_quant:
            cells += (_fmt(r.get("grad_comm_dtype")),
                      _fmt(r.get("predicted_quant_overhead_ms")))
        if has_remat:
            cells += (_fmt(r.get("remat_policy")),
                      _fmt(r.get("predicted_recompute_ms")))
        body.append(cells)
    widths = [max(len(header[i]), *(len(b[i]) for b in body)) for i in range(len(header))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(header, widths))]
    lines.append("  ".join("-" * w for w in widths))
    for b in body:
        lines.append("  ".join(c.ljust(w) for c, w in zip(b, widths)))
    return "\n".join(lines)


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)
