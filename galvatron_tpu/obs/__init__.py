"""Runtime observability: structured telemetry, MFU accounting, attribution.

The paper's premise is a closed profile -> search -> train loop; this package
is the measurement substrate that closes it at runtime:

- ``obs.telemetry``   — a schema-versioned JSONL event stream (per-step and
  lifecycle events), buffered off the critical path like runtime/prefetch.py.
- ``obs.flops``       — analytic model-FLOPs accounting + a per-device-kind
  peak-FLOPs registry, so every timing surface (profiler summary, telemetry,
  bench sections) can report MFU and model-FLOPs/s.
- ``obs.attribution`` — the predicted-vs-measured divergence table: the
  search engine's TimeCostModel/MemoryCostModel prediction per LayerRun next
  to measured steady-state step time and compiled-step memory.
- ``obs.report``      — offline analysis of a telemetry JSONL
  (``python -m galvatron_tpu.cli report``): steady-state detection, MFU,
  lifecycle timeline, divergence table.

Import-light on purpose: ``telemetry``/``flops``/``report`` are stdlib-only
at module scope (jax is touched only inside functions that receive jax
objects), so the offline report path never initialises an accelerator
backend.
"""
