"""Offline analysis of a telemetry JSONL: ``python -m galvatron_tpu.cli report``.

Consumes the event stream obs/telemetry.py wrote during training and
produces the numbers the perf loop runs on:

- **steady-state detection** — the first rolling window of per-step times
  whose relative stdev drops under a tolerance marks the end of warmup/
  compile/cache-population noise; the steady step time is the median from
  there on (falling back to the post-25% median when the run never
  settles, and saying so).
- **MFU / model-FLOPs-per-s** — recomputed from the run's recorded
  ``model_flops_per_step`` + ``peak_flops`` constants at the steady step
  time (not averaged from per-step MFU, which under the dispatch-ahead
  loop measures overlapping dispatch->drain latencies).
- **lifecycle timeline** — anomalies, rollbacks, checkpoint save/restore/GC,
  retries, preemption, elastic decisions, trace captures, in emit order.
- **divergence table** — the per-LayerRun predicted-vs-measured join
  (obs/attribution.py) using the steady step time and the compiled-step
  memory recorded by the ``compile`` event.
- **integrity rollup** — when the silent-corruption sentinel ran
  (``train --sdc_check``): digest heartbeats, cross-replica vote
  mismatches with the suspected device ids, re-executions, quarantines,
  and state-motion continuity checks.
- **serving rollup** — when the stream carries ``serve_request`` /
  ``decode_batch`` events (``cli serve --telemetry``): TTFT/TPOT
  percentiles, decode-step occupancy, and output tokens/s; plus the
  resilience ledger from ``serve_shed`` / ``serve_drain`` /
  ``serve_migrate`` — shed rate by reason, drain outcomes, and live
  degraded-mesh migrations.

Exit-code contract (shared with the GLS/GLC lint framework): 0 = analyzed
clean, 1 = schema violations in the stream, 2 = usage/IO failure.
``--json`` prints the machine-readable analysis dict.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from galvatron_tpu.obs import attribution as A
from galvatron_tpu.obs import flops as F
from galvatron_tpu.obs import steady as S
from galvatron_tpu.obs import telemetry as T

# lifecycle event types surfaced on the timeline, in schema order
TIMELINE_TYPES = (
    "compile", "checkpoint_save", "checkpoint_restore", "checkpoint_gc",
    "anomaly_skip", "rollback", "retry", "preemption", "watchdog", "elastic",
    "autotune", "trace", "eval", "serve_drain", "serve_migrate",
    "sdc_mismatch", "sdc_quarantine",
)
# serve_shed is deliberately NOT on the timeline: a shedding server emits
# one per rejected request, which under overload is most of the load.
# sdc_check is off it for the same reason: it is a per-interval heartbeat,
# not a lifecycle transition — only mismatches and quarantines are.

# timeline rendering: the watchdog's stack dump and a migration's full
# strategy JSON are post-mortem payloads, not one-line timeline material
_TIMELINE_ELIDED_KEYS = ("stacks", "from_strategy", "to_strategy")


# ---------------------------------------------------------- steady state
def detect_steady_state(
    values: Sequence[float], window: int = 5, rel_std: float = 0.15
) -> Tuple[Optional[int], str]:
    """(start index, method) of the steady-state region of a per-step time
    series. The detector itself lives in obs/steady.py (shared with the
    online autotuner, which also needs the streaming form); this wrapper
    keeps the report's historical tuple API."""
    return S.detect(values, window=window, rel_std=rel_std).as_tuple()


def _median(vals: Sequence[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return float(statistics.median(vals)) if vals else None


def _percentile(vals: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (same convention as serve/engine.percentile)."""
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    k = min(len(vals) - 1, max(0, int(round(q / 100.0 * (len(vals) - 1)))))
    return float(vals[k])


def _serving_section(
    reqs: List[Dict[str, Any]],
    batches: List[Dict[str, Any]],
    sheds: List[Dict[str, Any]] = (),
    drains: List[Dict[str, Any]] = (),
    migrates: List[Dict[str, Any]] = (),
) -> Dict[str, Any]:
    """Latency/throughput rollup of serve_request + decode_batch events,
    plus the resilience ledger (serve_shed/serve_drain/serve_migrate)."""
    ttft = [e.get("ttft_ms") for e in reqs]
    tpot = [e.get("tpot_ms") for e in reqs]
    out_tokens = sum(e.get("output_len") or 0 for e in reqs)
    arrivals = [e.get("arrival_t") for e in reqs if e.get("arrival_t") is not None]
    dones = [e.get("done_t") for e in reqs if e.get("done_t") is not None]
    span = (max(dones) - min(arrivals)) if arrivals and dones else None
    occ = [e["occupancy"] for e in batches if e.get("occupancy") is not None]
    by_reason: Dict[str, int] = {}
    for e in sheds:
        r = e.get("reason") or "?"
        by_reason[r] = by_reason.get(r, 0) + 1
    offered = len(reqs) + len(sheds)
    return {
        "requests": len(reqs),
        "output_tokens": out_tokens,
        "tokens_per_s": (out_tokens / span) if span else None,
        "ttft_ms": {q: _percentile(ttft, n) for q, n in
                    (("p50", 50), ("p90", 90), ("p99", 99))},
        "tpot_ms": {q: _percentile(tpot, n) for q, n in
                    (("p50", 50), ("p90", 90), ("p99", 99))},
        "decode_steps": len(batches),
        "median_step_ms": _median([e.get("step_ms") for e in batches]),
        "mean_occupancy": (statistics.fmean(occ) if occ else None),
        "shed": len(sheds),
        "shed_retryable": sum(1 for e in sheds if e.get("retryable")),
        "shed_rate": (len(sheds) / offered) if offered else None,
        "shed_by_reason": dict(sorted(by_reason.items())),
        "drains": [
            {k: e.get(k) for k in ("reason", "completed", "active_completed",
                                   "active_shed", "pending_shed", "exit_code")
             if e.get(k) is not None}
            for e in drains
        ],
        "migrations": len(migrates),
        "migrated_worlds": [
            [e.get("from_world"), e.get("to_world")] for e in migrates],
    }


def _integrity_section(
    checks: List[Dict[str, Any]],
    mismatches: List[Dict[str, Any]],
    quarantines: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Silent-corruption sentinel rollup (sdc_check / sdc_mismatch /
    sdc_quarantine events). Heartbeats carry the step-mode digests; the
    mode=="continuity" checks are the GLS016 asserts around state motion
    (relayout / migrate / cross-layout restore) and are counted apart."""
    heartbeats = [e for e in checks if e.get("mode") != "continuity"]
    continuity = [e for e in checks if e.get("mode") == "continuity"]
    reexecs = sum(1 for e in mismatches if e.get("action") == "reexecute")
    suspects: Dict[str, int] = {}
    for e in mismatches:
        for dev in e.get("suspects") or ():
            suspects[str(dev)] = suspects.get(str(dev), 0) + 1
    return {
        "mode": heartbeats[-1].get("mode") if heartbeats else None,
        "checks": len(heartbeats),
        "continuity_checks": len(continuity),
        "continuity_sites": sorted(
            {e.get("where") for e in continuity if e.get("where")}),
        "mismatches": len(mismatches),
        "mismatch_rate": (len(mismatches) / (len(heartbeats) + len(mismatches))
                          if (heartbeats or mismatches) else None),
        "reexecutions": reexecs,
        "suspect_counts": dict(sorted(suspects.items())),
        "quarantines": len(quarantines),
        "quarantined_devices": sorted(
            {int(d) for e in quarantines for d in (e.get("device_ids") or ())}),
        "last_fold": (("0x%08x" % int(heartbeats[-1]["fold"]))
                      if heartbeats and heartbeats[-1].get("fold") is not None
                      else None),
    }


def _autotune_section(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Online-autotuner rollup (`train --autotune`): planning decisions,
    applied swaps with predicted-vs-realized saving, and — in observe mode
    — the counterfactuals (decisions that WOULD have swapped)."""
    plans = [e for e in events if e.get("action") == "plan"]
    realized = [e for e in events if e.get("action") == "realized"]
    holds: Dict[str, int] = {}
    for e in plans:
        if not e.get("swapped"):
            r = e.get("reason") or "?"
            holds[r] = holds.get(r, 0) + 1
    return {
        "plans": len(plans),
        "swaps": sum(1 for e in plans if e.get("swapped")),
        "counterfactuals": sum(
            1 for e in plans
            if e.get("mode") == "observe" and e.get("reason") == "swap"),
        "holds_by_reason": dict(sorted(holds.items())),
        "predicted_saving_ms": sum(
            e.get("predicted_saving_ms") or 0.0
            for e in plans if e.get("swapped")) or None,
        "counterfactual_saving_ms": sum(
            e.get("predicted_saving_ms") or 0.0
            for e in plans
            if e.get("mode") == "observe" and e.get("reason") == "swap")
            or None,
        "realized_saving_ms": sum(
            e.get("realized_saving_ms") or 0.0 for e in realized)
            if realized else None,
        "swapped_iters": [e.get("iter") for e in plans if e.get("swapped")],
    }


# -------------------------------------------------------------- analysis
def analyze(
    events: List[Dict[str, Any]],
    window: int = 5,
    rel_std: float = 0.15,
) -> Dict[str, Any]:
    """The full analysis dict (the --json payload)."""
    by_type: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        by_type.setdefault(e["type"], []).append(e)

    run_start = (by_type.get("run_start") or [{}])[-1]
    steps = by_type.get("step", [])
    iter_ms = [e.get("iter_ms") for e in steps if e.get("iter_ms") is not None]

    start_idx, method = detect_steady_state(iter_ms, window=window, rel_std=rel_std)
    steady: Dict[str, Any] = {"method": method, "window": window, "rel_std": rel_std}
    if start_idx is not None and iter_ms:
        tail = iter_ms[start_idx:]
        steady_ms = _median(tail)
        steady.update(
            start_step_index=start_idx,
            start_iter=steps[start_idx].get("iter") if start_idx < len(steps) else None,
            step_ms=steady_ms,
            steps_measured=len(tail),
        )
        if steady_ms:
            steady["steps_per_s"] = 1e3 / steady_ms
            fps = run_start.get("model_flops_per_step")
            steady["model_flops_per_s"] = F.flops_per_s(fps, steady_ms)
            steady["mfu"] = F.mfu(fps, steady_ms, run_start.get("peak_flops"))

    compile_ev = (by_type.get("compile") or [{}])[-1]
    predictions = [e for e in by_type.get("layer_run", [])]
    divergence = A.divergence_rows(
        predictions,
        measured_step_ms=steady.get("step_ms"),
        measured_memory_mb=compile_ev.get("compiled_memory_mb"),
    ) if predictions else []
    # measured overlap (tp_shard_map.measure_comm_hidden): lay the measured
    # hidden-comm number beside the prediction's row for the same run
    overlap_events = [
        {k: v for k, v in e.items() if k not in ("v", "t", "seq", "type")}
        for e in by_type.get("tp_overlap", [])
    ]
    # comm-precision axis (quantized collectives): the run-level wire
    # dtypes + measured quant toll sit beside the divergence table, whose
    # per-run gcomm/q_ms columns carry the predictions
    quant_events = [
        {k: v for k, v in e.items() if k not in ("v", "t", "seq", "type")}
        for e in by_type.get("quant_comm", [])
    ]
    if overlap_events and divergence:
        by_run = {e.get("run"): e for e in overlap_events}
        for row in divergence:
            ev = by_run.get(row.get("run"))
            if ev is not None and ev.get("comm_hidden_ms") is not None:
                row["comm_hidden_ms"] = ev["comm_hidden_ms"]

    timeline = [
        {k: v for k, v in e.items() if k not in ("v",) + _TIMELINE_ELIDED_KEYS}
        for e in sorted(
            (e for t in TIMELINE_TYPES for e in by_type.get(t, [])),
            key=lambda e: e["seq"],
        )
    ]

    losses = [e.get("loss") for e in steps if e.get("loss") is not None]
    analysis: Dict[str, Any] = {
        "version": T.SCHEMA_VERSION,
        "run": {k: v for k, v in run_start.items()
                if k not in ("v", "t", "seq", "type")},
        "counts": {t: len(v) for t, v in sorted(by_type.items())},
        "steps": {
            "n": len(steps),
            "first_iter": steps[0].get("iter") if steps else None,
            "last_iter": steps[-1].get("iter") if steps else None,
            "first_loss": losses[0] if losses else None,
            "last_loss": losses[-1] if losses else None,
            "median_iter_ms": _median(iter_ms),
            "median_dispatch_ms": _median([e.get("dispatch_ms") for e in steps]),
            "median_host_blocked_ms": _median(
                [e.get("host_blocked_ms") for e in steps]),
        },
        "steady": steady,
        "compile": {k: v for k, v in compile_ev.items()
                    if k not in ("v", "t", "seq", "type")},
        "anomalies": {
            "skipped": len(by_type.get("anomaly_skip", [])),
            "rollbacks": len(by_type.get("rollback", [])),
            "retries": len(by_type.get("retry", [])),
        },
        "health": {
            "watchdog_fires": sum(
                1 for e in by_type.get("watchdog", []) if e.get("action") == "fire"),
            "watchdog_escalations": sum(
                1 for e in by_type.get("watchdog", [])
                if e.get("action") == "escalate"),
            "migrations": sum(
                1 for e in by_type.get("elastic", [])
                if e.get("action") == "migrate"),
        },
        "divergence": divergence,
        "tp_overlap": overlap_events,
        "quant_comm": quant_events,
        "timeline": timeline,
    }
    sdc_checks = by_type.get("sdc_check", [])
    sdc_mismatches = by_type.get("sdc_mismatch", [])
    sdc_quarantines = by_type.get("sdc_quarantine", [])
    if sdc_checks or sdc_mismatches or sdc_quarantines:
        analysis["integrity"] = _integrity_section(
            sdc_checks, sdc_mismatches, sdc_quarantines)
    serve_reqs = by_type.get("serve_request", [])
    decode_batches = by_type.get("decode_batch", [])
    sheds = by_type.get("serve_shed", [])
    drains = by_type.get("serve_drain", [])
    migrates = by_type.get("serve_migrate", [])
    if serve_reqs or decode_batches or sheds or drains or migrates:
        analysis["serving"] = _serving_section(
            serve_reqs, decode_batches, sheds, drains, migrates)
    autotune_events = by_type.get("autotune", [])
    if autotune_events:
        analysis["autotuning"] = _autotune_section(autotune_events)
    run_end = by_type.get("run_end")
    if run_end and run_end[-1].get("summary") is not None:
        analysis["summary"] = run_end[-1]["summary"]
    return analysis


# ------------------------------------------------------------- rendering
def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.4g" % v
    return str(v)


def render(analysis: Dict[str, Any]) -> str:
    run = analysis["run"]
    steps = analysis["steps"]
    steady = analysis["steady"]
    lines = []
    lines.append("telemetry report (schema v%d)" % analysis["version"])
    if run:
        lines.append(
            "run: model=%s world=%s bsz=%s iters=%s device=%s"
            % (run.get("model", "?"), run.get("world_size", "?"),
               run.get("global_bsz", "?"), run.get("train_iters", "?"),
               run.get("device_kind", "?"))
        )
    lines.append(
        "steps: %d recorded (iter %s..%s), loss %s -> %s"
        % (steps["n"], _fmt(steps["first_iter"]), _fmt(steps["last_iter"]),
           _fmt(steps["first_loss"]), _fmt(steps["last_loss"]))
    )
    lines.append(
        "steady state (%s): step %s ms over %s steps from iter %s "
        "| steps/s %s | model FLOP/s %s | MFU %s"
        % (steady.get("method"), _fmt(steady.get("step_ms")),
           _fmt(steady.get("steps_measured")), _fmt(steady.get("start_iter")),
           _fmt(steady.get("steps_per_s")), _fmt(steady.get("model_flops_per_s")),
           _fmt(steady.get("mfu")))
    )
    comp = analysis["compile"]
    if comp:
        lines.append(
            "compile: trace %s ms, compile %s ms, compiled memory %s MB, "
            "xla flops %s"
            % (_fmt(comp.get("trace_ms")), _fmt(comp.get("compile_ms")),
               _fmt(comp.get("compiled_memory_mb")),
               _fmt(comp.get("xla_flops_per_step")))
        )
    an = analysis["anomalies"]
    lines.append(
        "resilience: %d anomalies skipped, %d rollbacks, %d retries"
        % (an["skipped"], an["rollbacks"], an["retries"])
    )
    lines.append("")
    lines.append("predicted vs measured per layer run:")
    lines.append(A.render_divergence_table(analysis["divergence"]))
    if analysis.get("quant_comm"):
        lines.append("")
        lines.append("quantized collectives:")
        for e in analysis["quant_comm"]:
            lines.append(
                "  grad wire %s | param wire %s | block %s | tp ring %s | "
                "quant toll %s ms | wire MB %s (fp32 %s)"
                % (_fmt(e.get("grad_comm_dtype")),
                   _fmt(e.get("param_comm_dtype")),
                   _fmt(e.get("comm_quant_block")),
                   _fmt(e.get("tp_comm_quant")),
                   _fmt(e.get("quant_overhead_ms")),
                   _fmt(e.get("wire_mb_configured")),
                   _fmt(e.get("wire_mb_fp32")))
            )
    if analysis.get("tp_overlap"):
        lines.append("")
        lines.append("TP overlap (decomposed collectives, measured):")
        for e in analysis["tp_overlap"]:
            lines.append(
                "  run %s (layers %s-%s): overlap %s ms vs serialized %s ms "
                "-> comm hidden %s ms"
                % (_fmt(e.get("run")), _fmt(e.get("start")),
                   _fmt(e.get("stop", 1) - 1 if e.get("stop") is not None else None),
                   _fmt(e.get("overlap_ms")), _fmt(e.get("serial_ms")),
                   _fmt(e.get("comm_hidden_ms")))
            )
    if analysis.get("integrity"):
        iv = analysis["integrity"]
        lines.append("")
        lines.append("integrity (silent-corruption sentinel):")
        lines.append(
            "  mode %s | %s digest checks (last fold %s) | %s continuity "
            "checks%s"
            % (_fmt(iv["mode"]), _fmt(iv["checks"]), _fmt(iv["last_fold"]),
               _fmt(iv["continuity_checks"]),
               (" (%s)" % ", ".join(iv["continuity_sites"])
                if iv["continuity_sites"] else ""))
        )
        if iv["mismatches"]:
            suspects = " ".join(
                "dev%s=%d" % (k, v) for k, v in iv["suspect_counts"].items())
            lines.append(
                "  mismatches: %s (rate %s), %s re-executions%s"
                % (_fmt(iv["mismatches"]), _fmt(iv["mismatch_rate"]),
                   _fmt(iv["reexecutions"]),
                   (" | suspects %s" % suspects) if suspects else "")
            )
        if iv["quarantines"]:
            lines.append(
                "  quarantines: %s, devices %s"
                % (_fmt(iv["quarantines"]),
                   ",".join(str(d) for d in iv["quarantined_devices"]))
            )
    if analysis.get("serving"):
        sv = analysis["serving"]
        lines.append("")
        lines.append("serving:")
        lines.append(
            "  %s requests, %s output tokens, %s tok/s | %s decode steps, "
            "median step %s ms, mean occupancy %s"
            % (_fmt(sv["requests"]), _fmt(sv["output_tokens"]),
               _fmt(sv["tokens_per_s"]), _fmt(sv["decode_steps"]),
               _fmt(sv["median_step_ms"]), _fmt(sv["mean_occupancy"]))
        )
        for name in ("ttft_ms", "tpot_ms"):
            p = sv[name]
            lines.append(
                "  %s p50/p90/p99: %s / %s / %s"
                % (name, _fmt(p["p50"]), _fmt(p["p90"]), _fmt(p["p99"]))
            )
        if sv.get("shed"):
            reasons = " ".join(
                "%s=%d" % (k, v) for k, v in sv["shed_by_reason"].items())
            lines.append(
                "  shed: %s (%s retryable, rate %s) %s"
                % (_fmt(sv["shed"]), _fmt(sv["shed_retryable"]),
                   _fmt(sv["shed_rate"]), reasons)
            )
        for d in sv.get("drains") or ():
            lines.append(
                "  drain %s: completed %s, active completed %s, shed "
                "%s active + %s pending"
                % (_fmt(d.get("reason")), _fmt(d.get("completed")),
                   _fmt(d.get("active_completed")), _fmt(d.get("active_shed")),
                   _fmt(d.get("pending_shed")))
            )
        if sv.get("migrations"):
            lines.append(
                "  migrations: %s (%s)"
                % (_fmt(sv["migrations"]),
                   ", ".join("world %s->%s" % (a, b)
                             for a, b in sv["migrated_worlds"]))
            )
    if analysis.get("autotuning"):
        at = analysis["autotuning"]
        lines.append("")
        lines.append("autotuning:")
        holds = " ".join(
            "%s=%d" % (k, v) for k, v in at["holds_by_reason"].items())
        lines.append(
            "  plans: %s | swaps: %s%s%s"
            % (_fmt(at["plans"]), _fmt(at["swaps"]),
               (" (iters %s)" % ",".join(str(i) for i in at["swapped_iters"])
                if at["swapped_iters"] else ""),
               (" | held: %s" % holds) if holds else "")
        )
        lines.append(
            "  predicted saving %s ms/step | realized %s ms/step | "
            "counterfactual (observe) %s swaps worth %s ms/step"
            % (_fmt(at["predicted_saving_ms"]),
               _fmt(at["realized_saving_ms"]),
               _fmt(at["counterfactuals"]),
               _fmt(at["counterfactual_saving_ms"]))
        )
    if analysis["timeline"]:
        lines.append("")
        lines.append("lifecycle timeline:")
        for e in analysis["timeline"]:
            detail = " ".join(
                "%s=%s" % (k, _fmt(v)) for k, v in e.items()
                if k not in ("t", "seq", "type")
            )
            lines.append("  [seq %4d] %-18s %s" % (e["seq"], e["type"], detail))
    return "\n".join(lines)


# ------------------------------------------------------------------- CLI
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "galvatron_tpu-report",
        description="analyze a telemetry JSONL written by train --telemetry",
        allow_abbrev=False,
    )
    p.add_argument("path", help="telemetry .jsonl file")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="machine-readable analysis output")
    p.add_argument("--steady_window", type=int, default=5,
                   help="rolling-window length for steady-state detection")
    p.add_argument("--steady_tol", type=float, default=0.15,
                   help="relative stdev threshold for the steady window")
    p.add_argument("--emit_profiles", type=str, default=None, metavar="DIR",
                   help="offline calibrator: write measured per-layer "
                        "time/memory tables (profiler JSON schema) from this "
                        "stream into DIR, for search --time_profile_path/"
                        "--memory_profile_path")
    return p


def run(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        events, errors = T.read_events(args.path, strict=False)
    except OSError as e:
        print("cannot read %s: %s" % (args.path, e), file=sys.stderr)  # galv-lint: ignore[GLC006] -- CLI usage error
        return 2
    for err in errors:
        print("schema: %s: %s" % (args.path, err), file=sys.stderr)  # galv-lint: ignore[GLC006] -- CLI diagnostics
    analysis = analyze(events, window=args.steady_window, rel_std=args.steady_tol)
    analysis["schema_errors"] = errors
    if args.emit_profiles:
        # measured-table emission shares the online autotuner's calibrator;
        # paths go to stderr so --json stdout stays machine-parseable
        from galvatron_tpu.runtime import autotune as AT

        try:
            paths = AT.emit_profiles(
                events, args.emit_profiles,
                window=args.steady_window, rel_std=args.steady_tol)
        except ValueError as e:
            print("emit_profiles: %s" % e, file=sys.stderr)  # galv-lint: ignore[GLC006] -- CLI usage error
            return 2
        for kind, path in sorted(paths.items()):
            print("emit_profiles: wrote %s table %s" % (kind, path),  # galv-lint: ignore[GLC006] -- CLI diagnostics
                  file=sys.stderr)
    print(json.dumps(analysis, indent=2) if args.as_json else render(analysis))  # galv-lint: ignore[GLC006] -- CLI output
    return 1 if errors else 0


def main(argv: Optional[List[str]] = None) -> None:
    rc = run(argv)
    if rc:
        sys.exit(rc)
