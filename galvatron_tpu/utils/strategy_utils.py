"""Strategy list helpers (reference: galvatron/utils/strategy_utils.py,
config_utils.py:8-12).

A "search strategy" is the list form used by the search engine:
``[pp, tp, dp, {'fsdp':0/1, 'sp':0/1, 'cp':int, 'ckpt':0/1, 'tp':0/1(consec)}]``.
"""


def str2array(s):
    return list(map(int, str(s).split(",")))


def array2str(a):
    return ",".join(map(str, a))


def form_strategy(strategy):
    """Pretty-print one search strategy, e.g. ``2-4-1-sp-fsdp-ckpt``."""
    pp, tp, dp = strategy[0], strategy[1], strategy[2]
    info = strategy[3] if len(strategy) > 3 else {}
    tag = "%d-%d-%d" % (pp, tp, dp)
    if info.get("cp", 1) > 1:
        tag += "-cp%d" % info["cp"]
    if info.get("sp", 0):
        tag += "-sp"
    elif tp > 1 and not info.get("tp", 1):
        tag += "-nonconsec"
    if info.get("fsdp", 0):
        tag += "-fsdp"
    if info.get("cpt", info.get("ckpt", 0)):
        tag += "-ckpt"
        # remat-policy axis: a non-default policy changes both the memory
        # and the time cost, so it is part of the cache identity too
        if info.get("rp", "full") != "full":
            tag += "[%s]" % info["rp"]
    # comm-precision axis (quantized collectives): part of the identity —
    # the cost-model caches key on this string
    if info.get("gcd", "none") != "none":
        tag += "-g%s" % info["gcd"]
    if info.get("pcd", "none") != "none":
        tag += "-p%s" % info["pcd"]
    return tag


def print_strategies(strategy_list, stream=None):
    import sys

    stream = stream or sys.stdout
    if strategy_list is None:
        print("None", file=stream)
        return
    print(", ".join(form_strategy(s) for s in strategy_list), file=stream)
