"""jax 0.4.x compatibility shims.

The codebase targets the modern jax surface (`jax.shard_map` with
``axis_names=``/``check_vma=``, `jax.sharding.get_abstract_mesh`), but the
pinned environment ships jax 0.4.37 where those live elsewhere or do not
exist:

- ``jax.shard_map``            -> ``jax.experimental.shard_map.shard_map``,
  translating ``axis_names`` (the axes to make Manual) into the old ``auto=``
  complement and ``check_vma`` into ``check_rep``.
- ``jax.sharding.get_abstract_mesh`` -> no thread-local mesh context exists on
  0.4.37 (``jax._src.mesh`` tracks an empty tuple); the fallback returns
  ``None``, which callers treat as "no context mesh" (see
  ops/ring_attention.py).

`install()` is idempotent, patches only the *missing* names, and is invoked
from the package ``__init__`` so every entry point (CLI, tests, notebooks)
sees a uniform API. On a jax that already provides these names the shim is a
no-op. The static code linter (analysis/code_lint.py GLC001) resolves
attribute chains against the *patched* module, so `jax.shard_map` call sites
lint clean exactly when this shim (or a modern jax) provides them.
"""

from __future__ import annotations

from functools import wraps

import jax


def _shard_map_shim():
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @wraps(_legacy_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None,
                  auto=None, **kwargs):
        """Modern-signature `jax.shard_map` on top of the 0.4.x experimental
        API. ``axis_names`` lists the mesh axes the body is *manual* over;
        the legacy API instead takes ``auto`` — the complement."""
        if auto is None:
            if axis_names is not None and mesh is not None:
                auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            else:
                auto = frozenset()
        if check_rep is None:
            check_rep = bool(check_vma) if check_vma is not None else True
        if auto:
            # 0.4.x cannot run the replication checker over partially-auto
            # meshes (it raises); the modern default is equivalent to off.
            check_rep = False
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_rep, auto=frozenset(auto), **kwargs,
        )

    return shard_map


def _get_abstract_mesh_shim():
    def get_abstract_mesh():
        """0.4.x has no use_mesh/abstract-mesh context; report "none" so
        callers fall back to their explicit concrete mesh."""
        return None

    return get_abstract_mesh


def install() -> None:
    """Patch the missing modern APIs into the installed jax. Idempotent."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_shim()
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _get_abstract_mesh_shim()


_PARTIAL_MANUAL: dict = {}


def supports_partial_manual_shard_map() -> bool:
    """Whether this jax can compile a shard_map that is manual over a SUBSET
    of the mesh axes with a collective inside (the 1F1B engines' shape:
    manual over 'pp', GSPMD-auto within the stage). jax 0.4.x's legacy
    ``auto=`` lowering emits a PartitionId op that SPMD partitioning rejects
    at compile time; modern jax handles it. Probed once per process by
    compiling a 4-device toy (device_count permitting), not version-matched,
    so a backport or partial fix flips the answer automatically."""
    if "ok" in _PARTIAL_MANUAL:
        return _PARTIAL_MANUAL["ok"]
    # The probe MUST run out-of-process: on jax 0.4.x some partial-manual
    # lowerings die in a fatal XLA CHECK (spmd_partitioner.cc
    # IsManualSubgroup), which would abort the probing process itself.
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS', '') "
        "+ ' --xla_force_host_platform_device_count=4'\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.experimental.shard_map import shard_map\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ('pp', 'dp'))\n"
        "f = shard_map(lambda x: jax.lax.ppermute(x, 'pp', [(0, 1), (1, 0)]),\n"
        "              mesh=mesh, in_specs=P('pp'), out_specs=P('pp'),\n"
        "              check_rep=False, auto=frozenset({'dp'}))\n"
        "jax.jit(f).lower(jnp.zeros((4, 4))).compile()\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=180,
        )
        _PARTIAL_MANUAL["ok"] = proc.returncode == 0
    except Exception:  # noqa: BLE001 - any probe failure means "no"
        _PARTIAL_MANUAL["ok"] = False
    return _PARTIAL_MANUAL["ok"]


install()
