"""jax 0.4.x compatibility shims.

The codebase targets the modern jax surface (`jax.shard_map` with
``axis_names=``/``check_vma=``, `jax.sharding.get_abstract_mesh`), but the
pinned environment ships jax 0.4.37 where those live elsewhere or do not
exist:

- ``jax.shard_map``            -> ``jax.experimental.shard_map.shard_map``,
  translating ``axis_names`` (the axes to make Manual) into the old ``auto=``
  complement and ``check_vma`` into ``check_rep``.
- ``jax.sharding.get_abstract_mesh`` -> no thread-local mesh context exists on
  0.4.37 (``jax._src.mesh`` tracks an empty tuple); the fallback returns
  ``None``, which callers treat as "no context mesh" (see
  ops/ring_attention.py).

`install()` is idempotent, patches only the *missing* names, and is invoked
from the package ``__init__`` so every entry point (CLI, tests, notebooks)
sees a uniform API. On a jax that already provides these names the shim is a
no-op. The static code linter (analysis/code_lint.py GLC001) resolves
attribute chains against the *patched* module, so `jax.shard_map` call sites
lint clean exactly when this shim (or a modern jax) provides them.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import wraps
from typing import Callable, List, Optional, Tuple

import jax


def _shard_map_shim():
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @wraps(_legacy_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None,
                  auto=None, **kwargs):
        """Modern-signature `jax.shard_map` on top of the 0.4.x experimental
        API. ``axis_names`` lists the mesh axes the body is *manual* over;
        the legacy API instead takes ``auto`` — the complement."""
        if auto is None:
            if axis_names is not None and mesh is not None:
                auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            else:
                auto = frozenset()
        if check_rep is None:
            check_rep = bool(check_vma) if check_vma is not None else True
        if auto:
            # 0.4.x cannot run the replication checker over partially-auto
            # meshes (it raises); the modern default is equivalent to off.
            check_rep = False
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_rep, auto=frozenset(auto), **kwargs,
        )

    shard_map._galvatron_shim = True  # the WA001 inventory probe
    return shard_map


def _get_abstract_mesh_shim():
    def get_abstract_mesh():
        """0.4.x has no use_mesh/abstract-mesh context; report "none" so
        callers fall back to their explicit concrete mesh."""
        return None

    get_abstract_mesh._galvatron_shim = True  # the WA002 inventory probe
    return get_abstract_mesh


def install() -> None:
    """Patch the missing modern APIs into the installed jax. Idempotent."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_shim()
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _get_abstract_mesh_shim()


_PARTIAL_MANUAL: dict = {}


def supports_partial_manual_shard_map() -> bool:
    """Whether this jax can compile a shard_map that is manual over a SUBSET
    of the mesh axes with a collective inside (the 1F1B engines' shape:
    manual over 'pp', GSPMD-auto within the stage). jax 0.4.x's legacy
    ``auto=`` lowering emits a PartitionId op that SPMD partitioning rejects
    at compile time; modern jax handles it. Probed once per process by
    compiling a 4-device toy (device_count permitting), not version-matched,
    so a backport or partial fix flips the answer automatically."""
    if "ok" in _PARTIAL_MANUAL:
        return _PARTIAL_MANUAL["ok"]
    # The probe MUST run out-of-process: on jax 0.4.x some partial-manual
    # lowerings die in a fatal XLA CHECK (spmd_partitioner.cc
    # IsManualSubgroup), which would abort the probing process itself.
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS', '') "
        "+ ' --xla_force_host_platform_device_count=4'\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "from jax.experimental.shard_map import shard_map\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ('pp', 'dp'))\n"
        "f = shard_map(lambda x: jax.lax.ppermute(x, 'pp', [(0, 1), (1, 0)]),\n"
        "              mesh=mesh, in_specs=P('pp'), out_specs=P('pp'),\n"
        "              check_rep=False, auto=frozenset({'dp'}))\n"
        "jax.jit(f).lower(jnp.zeros((4, 4))).compile()\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=180,
        )
        _PARTIAL_MANUAL["ok"] = proc.returncode == 0
    except Exception:  # noqa: BLE001 - any probe failure means "no"
        _PARTIAL_MANUAL["ok"] = False
    return _PARTIAL_MANUAL["ok"]


# --------------------------------------------------------------------------
# Workaround inventory (the `lint --compat` registry, ROADMAP item 5's
# retirement checklist). Every pinned jax-0.4.37 workaround in the codebase
# gets a stable WA*** id, an installed-jax probe and the pytest ids of the
# tests that pin its behaviour, so the upgrade PR is mechanical: bump jax,
# run `lint --compat --deep`, retire whatever reports RETIRABLE, and keep
# whatever the pinning tests still demand. Probes return
# ``(active, detail)`` where active is True (the installed jax still needs
# the workaround), False (retirable) or None (cannot be decided cheaply —
# rerun with deep=True or rerun the pinning tests on the new jax).


@dataclass(frozen=True)
class WorkaroundEntry:
    code: str  # diagnostics.CODES id (WA0xx)
    title: str
    where: str  # the module carrying the workaround
    pinning_tests: Tuple[str, ...]  # pytest ids that pin the behaviour
    probe: Callable[[], Tuple[Optional[bool], str]]
    deep_probe: Optional[Callable[[], Tuple[Optional[bool], str]]] = None


def _jax_version_tuple() -> Tuple[int, ...]:
    out = []
    for part in jax.__version__.split("."):
        digits = "".join(ch for ch in part if ch.isdigit())
        if not digits:
            break
        out.append(int(digits))
    return tuple(out)


def _probe_shim(attr_chain: str):
    def probe() -> Tuple[Optional[bool], str]:
        obj = jax
        for name in attr_chain.split("."):
            obj = getattr(obj, name, None)
            if obj is None:
                return None, "%s missing from the installed jax" % attr_chain
        if getattr(obj, "_galvatron_shim", False):
            return True, "shim installed (jax %s lacks the native API)" % jax.__version__
        return False, "jax %s provides %s natively — shim retirable" % (
            jax.__version__, attr_chain)

    return probe


def _probe_miscompile_range(detail_active: str):
    """The three GSPMD miscompile classes and the XLA:CPU cache corruption
    are pinned on the 0.4.x line; no cheap in-process probe can prove a
    newer jax fixed them, so outside that range the answer is 'unverified —
    rerun the pinning tests' rather than a guess."""

    def probe() -> Tuple[Optional[bool], str]:
        v = _jax_version_tuple()
        if v[:2] <= (0, 4):
            return True, "jax %s is in the pinned 0.4.x hazard range: %s" % (
                jax.__version__, detail_active)
        return None, ("unverified on jax %s — rerun the pinning tests "
                      "before retiring" % jax.__version__)

    return probe


def _probe_partial_manual_cheap() -> Tuple[Optional[bool], str]:
    if "ok" in _PARTIAL_MANUAL:  # a deep run already paid for the answer
        return _probe_partial_manual_deep()
    v = _jax_version_tuple()
    if v[:2] <= (0, 4):
        return True, ("jax %s: legacy auto= lowering emits PartitionId ops "
                      "SPMD partitioning rejects (fatal XLA CHECK); probe "
                      "with --deep to compile the 4-device toy" % jax.__version__)
    return None, "needs the out-of-process compile probe (run with --deep)"


def _probe_partial_manual_deep() -> Tuple[Optional[bool], str]:
    ok = supports_partial_manual_shard_map()
    if ok:
        return False, ("installed jax compiles the partial-manual toy — the "
                       "compile gate is retirable")
    return True, "partial-manual shard_map still fails to compile (probed)"


WORKAROUNDS: Tuple[WorkaroundEntry, ...] = (
    WorkaroundEntry(
        code="WA001",
        title="jax.shard_map modern-signature shim "
              "(axis_names/check_vma -> legacy auto/check_rep)",
        where="utils/jax_compat.py:_shard_map_shim",
        pinning_tests=(
            "tests/analysis/test_jax_compat.py::test_shim_installed_by_package_import",
            "tests/analysis/test_jax_compat.py::test_shard_map_full_manual_runs",
        ),
        probe=_probe_shim("shard_map"),
    ),
    WorkaroundEntry(
        code="WA002",
        title="jax.sharding.get_abstract_mesh fallback (no thread-local "
              "mesh context on 0.4.x)",
        where="utils/jax_compat.py:_get_abstract_mesh_shim",
        pinning_tests=(
            "tests/analysis/test_jax_compat.py::test_get_abstract_mesh_contract",
        ),
        probe=_probe_shim("sharding.get_abstract_mesh"),
    ),
    WorkaroundEntry(
        code="WA003",
        title="partial-manual shard_map compile gate (out-of-process probe; "
              "1F1B engines skip on unsupported jax)",
        where="utils/jax_compat.py:supports_partial_manual_shard_map",
        pinning_tests=(
            "tests/analysis/test_jax_compat.py::test_partial_manual_probe_is_cached_and_boolean",
            "tests/analysis/test_jax_compat.py::test_shard_map_axis_names_accepts_partial_manual_tracing",
        ),
        probe=_probe_partial_manual_cheap,
        deep_probe=_probe_partial_manual_deep,
    ),
    WorkaroundEntry(
        code="WA004",
        title="jnp.stack (never concat+reshape) when stacking layer params "
              "for the scan runs — GSPMD miscompiles a sharded-dim reshape "
              "inside a scan",
        where="models/base.py:stack_layer_run",
        pinning_tests=(
            "tests/models/test_tp_comm_mode.py::test_sharded_paths_match_unsharded_reference",
            "tests/analysis/test_trace_lint.py::test_glt001_sharded_reshape_in_scan_flagged",
        ),
        probe=_probe_miscompile_range(
            "sharded-dim reshape inside scan corrupts the stacked values"),
    ),
    WorkaroundEntry(
        code="WA005",
        title="explicit sharding constraints on the pipeline microbatch "
              "split before the tick scan",
        where="parallel/pipeline.py:make_pipelined_loss",
        pinning_tests=(
            "tests/parallel/test_pipeline.py::test_pipeline_matches_dp",
            "tests/analysis/test_trace_lint.py::test_glt002_unconstrained_microbatch_split_flagged",
        ),
        probe=_probe_miscompile_range(
            "unconstrained dp-sharded split under the tick scan miscompiles"),
    ),
    WorkaroundEntry(
        code="WA006",
        title="pp>1 init: per-layer init jitted, stages stacked OUTSIDE jit, "
              "then device_put — never fused under pp out_shardings",
        where="runtime/model_api.py:HybridParallelModel.init_params",
        pinning_tests=(
            "tests/parallel/test_pipeline.py::test_pipelined_bert_mlm_matches_single_stage",
            "tests/analysis/test_trace_lint.py::test_glt003_stacked_init_under_out_shardings_flagged",
        ),
        probe=_probe_miscompile_range(
            "fused stacked init under pp out_shardings yields wrong entries"),
    ),
    WorkaroundEntry(
        code="WA007",
        title="persistent compilation cache bypassed for the AOT step; "
              "in-process executable memo instead (XLA:CPU deserialized "
              "executables corrupt the allocator heap)",
        where="cli/train.py:_compile_uncached/_STEP_EXECUTABLES",
        pinning_tests=(
            "tests/analysis/test_compat_inventory.py::test_wa007_compile_uncached_bypasses_persistent_cache",
        ),
        # no deep probe on purpose: the failure mode is heap corruption in
        # the probing process (see tests/conftest.py KNOWN HAZARD)
        probe=_probe_miscompile_range(
            "deserialized XLA:CPU executables SIGSEGV on the AOT fast path"),
    ),
    WorkaroundEntry(
        code="WA008",
        title="manual-TP bwd never psums cotangents over the tp axes — the "
              "legacy shard_map transpose auto-psums unmentioned manual "
              "axes at the region boundary",
        where="parallel/tp_shard_map.py (autodiff note)",
        pinning_tests=(
            "tests/models/test_tp_comm_mode.py::test_manual_path_matches_gspmd",
        ),
        probe=_probe_shim("shard_map"),
    ),
)


def workaround_inventory(deep: bool = False) -> List[dict]:
    """Probe every registered workaround against the installed jax.
    Each row: ``{code, title, where, active, detail, pinning_tests}`` with
    ``active`` True/False/None (see module comment). ``deep=True`` runs the
    expensive probes (out-of-process compiles) where one exists."""
    rows = []
    for wa in WORKAROUNDS:
        probe = wa.deep_probe if (deep and wa.deep_probe is not None) else wa.probe
        try:
            active, detail = probe()
        except Exception as e:  # a broken probe must not take down the CLI
            active, detail = None, "probe failed: %s" % e
        rows.append({
            "code": wa.code,
            "title": wa.title,
            "where": wa.where,
            "active": active,
            "detail": detail,
            "pinning_tests": list(wa.pinning_tests),
        })
    return rows


def render_inventory(rows: List[dict]) -> str:
    """Fixed-width human rendering of `workaround_inventory` output."""
    lines = ["jax workaround inventory (installed jax %s):" % jax.__version__]
    for r in rows:
        status = {True: "ACTIVE", False: "RETIRABLE", None: "UNKNOWN"}[r["active"]]
        lines.append("  %s  %-9s %s" % (r["code"], status, r["title"]))
        lines.append("         where: %s" % r["where"])
        lines.append("         probe: %s" % r["detail"])
        lines.append("         pinned by: %s" % ", ".join(r["pinning_tests"]))
    return "\n".join(lines)


install()
