"""Opt-in persistent XLA compilation cache.

Scan-over-layer-runs (models/base.py run_layers) makes compile cost
depth-constant; this module removes it across PROCESS restarts too: with the
cache enabled, a re-launched train/bench run whose step HLO is unchanged
loads the compiled executable from disk instead of re-invoking XLA.

Opt-in (``--compile_cache 1`` on the train CLI,
``GALVATRON_BENCH_COMPILE_CACHE=1`` for bench.py) because the cache is
per-HOST state: XLA:CPU AOT entries embed the writing host's ISA features
(cpu_aot_loader.cc), so a cache dir shared across heterogeneous machines
risks SIGILL on load — keep the default location on local disk and do not
point it at a network share used by different hosts (the same hazard note as
tests/conftest.py's session-fresh cache).
"""

from __future__ import annotations

import os
from typing import Optional

DEFAULT_CACHE_DIR = "~/.cache/galvatron_tpu/xla"


def enable_persistent_cache(cache_dir: Optional[str] = None) -> str:
    """Point jax's persistent compilation cache at `cache_dir` (created if
    missing; default ~/.cache/galvatron_tpu/xla) and lower the min-compile-
    time threshold so the small per-run programs of a scanned model are
    cached too. Returns the resolved path. Call before the first jit
    compilation; safe to call again (last dir wins)."""
    path = os.path.expanduser(cache_dir or DEFAULT_CACHE_DIR)
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except AttributeError:  # jax without the knob: default threshold applies
        pass
    return path
