from galvatron_tpu.utils.jsonio import read_json_config, write_json_config
from galvatron_tpu.utils.strategy_utils import (
    array2str,
    form_strategy,
    print_strategies,
    str2array,
)

__all__ = [
    "read_json_config",
    "write_json_config",
    "str2array",
    "array2str",
    "form_strategy",
    "print_strategies",
]
