"""JSON config IO helpers (reference: galvatron/utils/config_utils.py:14-20)."""

import json
import os


def read_json_config(path):
    with open(path, "r", encoding="utf-8") as fp:
        return json.load(fp)


def write_json_config(config, path):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fp:
        json.dump(config, fp, indent=4)
        fp.write("\n")
