"""HF <-> native checkpoint converters.

Counterpart of the reference's offline converter pair
(tools/checkpoint_convert_h2g.py:6-41 and tools/checkpoint_convert_g2h.py:11-40):
h2g splits an HF checkpoint into the native per-layer tree and writes it as an
orbax checkpoint the train driver resumes from (iteration 0); g2h reads a
native checkpoint back into an HF state dict. Sharding is NOT baked into the
files — orbax/tensorstore reads any slice, so the same converted checkpoint
serves every parallel strategy (the reference instead streams TP-sliced
shards at init, parallel.py:79-89).

CLI:
  python -m galvatron_tpu.tools.convert_checkpoint h2g \
      --model_type gpt --hf_path <dir|file.bin> --output_dir ckpt/
  python -m galvatron_tpu.tools.convert_checkpoint g2h \
      --model_type gpt --checkpoint_dir ckpt/ --output_path out.bin
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Dict, Optional

import numpy as np


def _load_hf_state_dict(hf_path: str) -> Dict[str, Any]:
    """Accepts a transformers model directory, a torch .bin/.pt file, or a
    .safetensors file."""
    if os.path.isdir(hf_path):
        for name in ("pytorch_model.bin", "model.safetensors"):
            cand = os.path.join(hf_path, name)
            if os.path.exists(cand):
                hf_path = cand
                break
        else:
            raise FileNotFoundError("no pytorch_model.bin / model.safetensors in %s" % hf_path)
    if hf_path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return dict(load_file(hf_path))
    import torch

    return torch.load(hf_path, map_location="cpu", weights_only=True)


def hf_to_native(
    model_type: str,
    hf_state_dict: Dict[str, Any],
    hf_config=None,
    model_size: Optional[str] = None,
    **config_overrides,
):
    """Returns (cfg, params). `hf_config` (a transformers config) wins over
    `model_size` presets."""
    from galvatron_tpu.models.registry import get_family

    fam = get_family(model_type)
    if fam.convert_from_hf is None:
        raise NotImplementedError("family %r has no HF converter" % model_type)
    if hf_config is not None:
        if fam.config_from_hf is None:
            raise NotImplementedError("family %r cannot derive config from HF" % model_type)
        cfg = fam.config_from_hf(hf_config, **config_overrides)
    else:
        cfg = fam.config_fn(model_size or fam.default_size, **config_overrides)
    return cfg, fam.convert_from_hf(hf_state_dict, cfg)


def native_to_hf(model_type: str, params, cfg) -> Dict[str, np.ndarray]:
    from galvatron_tpu.models.registry import get_family

    fam = get_family(model_type)
    if fam.export_to_hf is None:
        raise NotImplementedError("family %r has no HF exporter" % model_type)
    return fam.export_to_hf(params, cfg)


def convert_h2g(args) -> str:
    from galvatron_tpu.runtime.checkpoint import save_checkpoint

    sd = _load_hf_state_dict(args.hf_path)
    hf_config = None
    if args.hf_config_path or os.path.isdir(args.hf_path):
        import transformers

        hf_config = transformers.AutoConfig.from_pretrained(
            args.hf_config_path or args.hf_path
        )
    cfg, params = hf_to_native(
        args.model_type, sd, hf_config=hf_config, model_size=args.model_size
    )
    save_checkpoint(args.output_dir, 0, params, train_meta={"iteration": 0,
                    "source": "hf", "model_type": args.model_type})
    return args.output_dir


def convert_g2h(args) -> str:
    import jax

    from galvatron_tpu.models.registry import get_family
    from galvatron_tpu.runtime.checkpoint import load_checkpoint

    fam = get_family(args.model_type)
    if args.hf_config_path:
        import transformers

        cfg = fam.config_from_hf(transformers.AutoConfig.from_pretrained(args.hf_config_path))
    else:
        cfg = fam.config_fn(args.model_size or fam.default_size)
    # abstract restore target from a fresh init (shapes only; no sharding)
    if fam.name == "t5":
        from galvatron_tpu.models.t5 import init_t5_params as init
    elif fam.name == "swin":
        from galvatron_tpu.models.swin import init_swin_params as init
    else:
        from galvatron_tpu.models.base import init_model_params as init
    target = jax.eval_shape(lambda: init(jax.random.PRNGKey(0), cfg))
    params, _, _ = load_checkpoint(
        args.checkpoint_dir, args.iteration, params_target=target, hp=None
    )
    sd = native_to_hf(args.model_type, params, cfg)
    import torch

    torch.save({k: torch.tensor(np.asarray(v)) for k, v in sd.items()}, args.output_path)
    return args.output_path


def main(argv=None):
    p = argparse.ArgumentParser("galvatron_tpu checkpoint converter")
    sub = p.add_subparsers(dest="direction", required=True)
    h2g = sub.add_parser("h2g", help="HuggingFace -> native orbax checkpoint")
    h2g.add_argument("--model_type", required=True)
    h2g.add_argument("--model_size", default=None)
    h2g.add_argument("--hf_path", required=True)
    h2g.add_argument("--hf_config_path", default=None)
    h2g.add_argument("--output_dir", required=True)
    g2h = sub.add_parser("g2h", help="native checkpoint -> HF state dict (.bin)")
    g2h.add_argument("--model_type", required=True)
    g2h.add_argument("--model_size", default=None)
    g2h.add_argument("--hf_config_path", default=None)
    g2h.add_argument("--checkpoint_dir", required=True)
    g2h.add_argument("--iteration", type=int, default=None)
    g2h.add_argument("--output_path", required=True)
    args = p.parse_args(argv)
    out = convert_h2g(args) if args.direction == "h2g" else convert_g2h(args)
    print("wrote %s" % out)
    return out


if __name__ == "__main__":
    main()
