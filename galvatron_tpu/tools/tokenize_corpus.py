"""Offline corpus tokenizer: raw text -> the indexed dataset the train
driver consumes (``<prefix>.bin`` + ``<prefix>.idx.npy``).

Counterpart of the reference's Megatron preprocessing capability
(site_package/megatron/training/tokenizer/ consumed by
tools/preprocess_data.py in upstream Megatron): the reference vendors its
tokenizers so ``--data_path`` can consume raw corpora; here tokenization is
an explicit offline step and the training contract is the pre-tokenized
int32 stream (data/dataset.py on-disk format).

Tokenizers:
  - ``bytes``               UTF-8 byte-level, vocab 256 (+257 with --append-eod:
                            id 256 is EOD). Zero dependencies, deterministic.
  - anything else           passed to ``transformers.AutoTokenizer
                            .from_pretrained`` (a local directory works
                            offline; a hub name needs network).

Document segmentation (``--doc-sep``):
  - ``line``        one document per non-empty input line (default; the jsonl
                    -> one-text-per-line shape Megatron preprocessing uses)
  - ``blank-line``  documents separated by blank lines (paragraph corpora)
  - ``file``        each input file is one document

CLI:
  python -m galvatron_tpu.tools.tokenize_corpus \\
      --input corpus_a.txt corpus_b.txt --output /data/corpus \\
      --tokenizer bytes --append-eod

The resulting prefix feeds ``--data_path /data/corpus``, or a weighted blend
``--data_path "0.7 /data/a 0.3 /data/b"`` (data/dataset.py parse_blend).
"""

from __future__ import annotations

import argparse
from typing import Iterator, List, Sequence


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255, EOD = 256."""

    vocab_size = 256
    eod_id = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers.AutoTokenizer adapter (EOD = its eos token)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self.tok = AutoTokenizer.from_pretrained(name_or_path)
        self.vocab_size = len(self.tok)
        self.eod_id = self.tok.eos_token_id
        if self.eod_id is None:
            self.eod_id = self.tok.pad_token_id

    def encode(self, text: str) -> List[int]:
        return self.tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self.tok.decode(list(ids))


def get_tokenizer(name: str):
    return ByteTokenizer() if name == "bytes" else HFTokenizer(name)


def iter_documents(paths: Sequence[str], doc_sep: str) -> Iterator[str]:
    """Yield document texts from the input files per the segmentation mode."""
    for path in paths:
        with open(path, encoding="utf-8") as f:
            if doc_sep == "file":
                text = f.read().strip()
                if text:
                    yield text
            elif doc_sep == "line":
                for line in f:
                    line = line.strip()
                    if line:
                        yield line
            elif doc_sep == "blank-line":
                buf: List[str] = []
                for line in f:
                    if line.strip():
                        buf.append(line.rstrip("\n"))
                    elif buf:
                        yield "\n".join(buf)
                        buf = []
                if buf:
                    yield "\n".join(buf)
            else:
                raise ValueError("unknown --doc-sep %r" % doc_sep)


def tokenize_corpus(
    inputs: Sequence[str],
    output_prefix: str,
    tokenizer="bytes",
    doc_sep: str = "line",
    append_eod: bool = False,
) -> dict:
    """Tokenize input text files into <output_prefix>.bin/.idx.npy; returns
    {n_docs, n_tokens, vocab_size} (vocab_size includes the EOD id when
    --append-eod grows it past the tokenizer's own table, as the byte
    tokenizer's does)."""
    import numpy as np

    tok = get_tokenizer(tokenizer) if isinstance(tokenizer, str) else tokenizer
    if append_eod and tok.eod_id is None:
        raise ValueError(
            "--append-eod requested but the tokenizer has no EOD id "
            "(no eos or pad token); pick another tokenizer or drop the flag"
        )
    # stream documents straight to the .bin (a pretraining corpus held as
    # Python int lists costs ~28 bytes/token and OOMs; the upstream Megatron
    # preprocessor this mirrors also streams), accumulating only offsets.
    # Stream to a temp file and drop any stale index FIRST: a mid-run failure
    # must never leave a truncated .bin silently pairing with an old .idx.npy
    import os

    idx_path = output_prefix + ".idx.npy"
    if os.path.exists(idx_path):
        os.remove(idx_path)
    tmp_bin = output_prefix + ".bin.tmp"
    offsets = [0]
    try:
        with open(tmp_bin, "wb") as f:
            for text in iter_documents(inputs, doc_sep):
                ids = tok.encode(text)
                if not ids:
                    continue
                if append_eod:
                    ids = list(ids) + [tok.eod_id]
                np.asarray(ids, np.int32).tofile(f)
                offsets.append(offsets[-1] + len(ids))
        if len(offsets) == 1:
            raise ValueError("no non-empty documents found in %r" % list(inputs))
        os.replace(tmp_bin, output_prefix + ".bin")
    finally:
        if os.path.exists(tmp_bin):
            os.remove(tmp_bin)
    np.save(idx_path, np.asarray(offsets, np.int64))
    vocab = max(tok.vocab_size, (tok.eod_id + 1) if append_eod else 0)
    return {"n_docs": len(offsets) - 1, "n_tokens": offsets[-1], "vocab_size": vocab}


def main(argv=None):
    p = argparse.ArgumentParser(
        "galvatron_tpu corpus tokenizer",
        description="raw text -> <prefix>.bin/.idx.npy for --data_path",
    )
    p.add_argument("--input", nargs="+", required=True, help="input text files")
    p.add_argument("--output", required=True, help="output dataset prefix")
    p.add_argument("--tokenizer", default="bytes",
                   help="'bytes' or a transformers AutoTokenizer name/path")
    p.add_argument("--doc-sep", default="line",
                   choices=("line", "blank-line", "file"))
    p.add_argument("--append-eod", action="store_true",
                   help="append the tokenizer's EOD id to every document")
    a = p.parse_args(argv)
    stats = tokenize_corpus(a.input, a.output, a.tokenizer, a.doc_sep, a.append_eod)
    print(
        "wrote %s.bin/.idx.npy: %d docs, %d tokens (vocab %d) — train with "
        "--data_path %s and --vocab_size >= %d"
        % (a.output, stats["n_docs"], stats["n_tokens"], stats["vocab_size"],
           a.output, stats["vocab_size"])
    )


if __name__ == "__main__":
    main()
