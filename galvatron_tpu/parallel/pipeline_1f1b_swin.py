"""1F1B pipeline schedule for hierarchical-resolution models (Swin).

The reference pipelines Swin like any other family — its per-stage layer
lists and per-stage sequence lengths flow through the multi-layer-type DP
(reference model_profiler.py:71-100, dynamic_programming.py:170-189) and the
stage pipeline slices arbitrary `model_ranks` (pipeline.py:110-112). The TPU
schedule (parallel/pipeline_1f1b.py — its divergence-safety invariants all
apply here) requires two things a hierarchical model does not natively give:

- a single static CHANNEL shape between stages: Swin halves the token count
  and doubles the channel dim at each patch merge, so the inter-stage
  activation is carried as a FLAT buffer sized to the largest (stage-0)
  activation, ``(mb, L0 * C0)``; each stage body slices the prefix it needs,
  reshapes to its own (H, W, C), runs its blocks (and any patch merges that
  statically fall inside it), then flattens and zero-pads back. Total
  elements halve at every merge, so the padding never exceeds 2x and the
  buffer is tiny relative to transformer channels;
- uniform per-slot parameter trees for the stacked ``(pp, ...)`` layout:
  block params differ in shape across Swin stages (C, heads, window all
  grow), so each slot holds every leaf padded to the element-wise MAX shape
  over the pipeline stages, and the per-stage body statically slices the
  live region. Sliced-out entries get exactly-zero gradients (the vjp of a
  slice), so any elementwise optimizer leaves the padding at zero. Patch
  merges are slot entries of the block they follow; stages without a merge
  at that slot hold never-referenced zeros.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import PP_AXIS, layer_axes, vocab_axes
from galvatron_tpu.parallel.pipeline_1f1b import build_schedule, use_masked_path

Params = Dict[str, Any]


def validate_swin_config(cfg, hp: HybridParallelConfig) -> None:
    # cp/sp are inapplicable at ANY pp degree (windowed attention has no
    # sequence dimension) — check before the pp early-return
    for s in hp.layers:
        if s.cp > 1 or s.sp:
            raise ValueError(
                "swin windowed attention has no sequence dimension to shard: "
                "cp / ulysses-sp do not apply (strategy %r)" % (s,)
            )
    if hp.pp <= 1:
        return
    div = hp.pp_division
    if len(set(div)) != 1:
        raise ValueError(
            "swin 1F1B requires equal layers per stage, got pp_division=%s" % (div,)
        )


# ------------------------------------------------------------- shape algebra
def _block_dims(cfg, t: int) -> Dict[str, int]:
    c = cfg.stage_dim(t)
    nh = cfg.num_heads[t]
    w = min(cfg.window, cfg.stage_resolution(t))
    return dict(c=c, nh=nh, hd=c // nh, ff=int(c * cfg.mlp_ratio), nb=(2 * w - 1) ** 2)


def _slot_types(cfg, hp: HybridParallelConfig, j: int) -> List[int]:
    """Swin-stage type of slot j's block on each pipeline stage."""
    lps = hp.pp_division[0]
    return [cfg.stage_of_block(s * lps + j) for s in range(hp.pp)]


def _merge_types(cfg, hp: HybridParallelConfig, j: int) -> List[int]:
    """Swin stages whose trailing patch merge falls at slot j (on any stage)."""
    lps = hp.pp_division[0]
    cum = np.cumsum(cfg.depths)
    out = []
    for s in range(hp.pp):
        gi = s * lps + j
        t = cfg.stage_of_block(gi)
        if t < cfg.num_stages - 1 and gi == cum[t] - 1:
            out.append(t)
    return out


def _max_dims(cfg, types) -> Dict[str, int]:
    dims = [_block_dims(cfg, t) for t in types]
    return {k: max(d[k] for d in dims) for k in dims[0]}


def _block_shapes(cfg, d: Dict[str, int]) -> Params:
    c, nh, hd, ff, nb = d["c"], d["nh"], d["hd"], d["ff"], d["nb"]
    shapes: Params = {
        "ln1": {"scale": (c,), "bias": (c,)},
        "ln2": {"scale": (c,), "bias": (c,)},
        "wqkv": {"kernel": (c, 3, nh, hd)},
        "wo": {"kernel": (c, c), "bias": (c,)},
        "wi": {"kernel": (c, ff), "bias": (ff,)},
        "wo_mlp": {"kernel": (ff, c), "bias": (c,)},
        "rel_bias": (nb, nh),
    }
    if cfg.qkv_bias:
        shapes["wqkv"]["bias"] = (3, nh, hd)
    return shapes


def _merge_shapes(cfg, c: int) -> Params:
    return {
        "norm": {"scale": (4 * c,), "bias": (4 * c,)},
        "reduction": {"kernel": (4 * c, 2 * c)},
    }


def _pad_leaf(a: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    return jnp.pad(a, [(0, m - n) for n, m in zip(a.shape, shape)])


def _slice_leaf(a: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    return a[tuple(slice(0, n) for n in shape)]


def _map_shapes(fn, tree: Params, shapes: Params) -> Params:
    return jax.tree.map(fn, tree, shapes, is_leaf=lambda x: isinstance(x, tuple))


# ---------------------------------------------------------- stacking / specs
def stack_swin_layer_specs(cfg, hp: HybridParallelConfig):
    """Per-slot specs for the padded universal trees. Within-stage sharding
    follows slot j's first-stage axes (the stacked-layout convention,
    parallel/pipeline.py stack_layer_specs); padded dims need not divide the
    axis size — GSPMD shards unevenly."""
    from galvatron_tpu.models.swin import block_param_specs

    lps = hp.pp_division[0]
    out = []
    for j in range(lps):
        spec_j = dict(block_param_specs(cfg, 0, layer_axes(hp, j)))
        if _merge_types(cfg, hp, j):
            spec_j["merge"] = {
                "norm": {"scale": P(None), "bias": P(None)},
                "reduction": {"kernel": P(None, None)},
            }
        out.append(jax.tree.map(
            lambda sp: P(PP_AXIS, *sp), spec_j, is_leaf=lambda x: isinstance(x, P)
        ))
    return out


def stack_swin_params(params: Params, cfg, hp: HybridParallelConfig) -> List[Params]:
    """Canonical swin tree (blocks / merges) -> lps padded slot trees with a
    leading pp dim."""
    pp, lps = hp.pp, hp.pp_division[0]
    cum = np.cumsum(cfg.depths)
    stacked = []
    for j in range(lps):
        pad_shapes = _block_shapes(cfg, _max_dims(cfg, _slot_types(cfg, hp, j)))
        mts = _merge_types(cfg, hp, j)
        per_stage = []
        for s in range(pp):
            gi = s * lps + j
            tree = _map_shapes(_pad_leaf, params["blocks"][gi], pad_shapes)
            if mts:
                mshapes = _merge_shapes(cfg, max(cfg.stage_dim(t) for t in mts))
                t = cfg.stage_of_block(gi)
                if t < cfg.num_stages - 1 and gi == cum[t] - 1:
                    tree["merge"] = _map_shapes(_pad_leaf, params["merges"][t], mshapes)
                else:
                    tree["merge"] = jax.tree.map(
                        lambda sh: jnp.zeros(sh, cfg.param_dtype), mshapes,
                        is_leaf=lambda x: isinstance(x, tuple),
                    )
            per_stage.append(tree)
        stacked.append(jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage))
    return stacked


def unstack_swin_params(stacked: List[Params], cfg, hp: HybridParallelConfig) -> Params:
    """Inverse of stack_swin_params (checkpoint export): recover canonical
    blocks and merges at their true shapes."""
    pp, lps = hp.pp, hp.pp_division[0]
    cum = np.cumsum(cfg.depths)
    blocks: List[Params] = [None] * cfg.num_layers  # type: ignore
    merges: List[Params] = [None] * (cfg.num_stages - 1)  # type: ignore
    for j, tree in enumerate(stacked):
        for s in range(pp):
            gi = s * lps + j
            t = cfg.stage_of_block(gi)
            slot = jax.tree.map(lambda a: a[s], tree)
            merge = slot.pop("merge", None)
            blocks[gi] = _map_shapes(_slice_leaf, slot, _block_shapes(cfg, _block_dims(cfg, t)))
            if merge is not None and t < cfg.num_stages - 1 and gi == cum[t] - 1:
                merges[t] = _map_shapes(_slice_leaf, merge, _merge_shapes(cfg, cfg.stage_dim(t)))
    return {"blocks": blocks, "merges": merges}


# ==================================================================== engine
def make_swin_loss_and_grad(cfg, hp: HybridParallelConfig, mesh):
    """``fn(params, batch) -> (loss, grads)`` running Swin through the 1F1B
    schedule. params: {embed, final_norm, head, stages}; batch: pixels
    (B, H, W, C), labels (B,)."""
    from galvatron_tpu.models import swin as SW
    from galvatron_tpu.models.base import patchify, softmax_nll
    from galvatron_tpu.ops.norms import layer_norm

    validate_swin_config(cfg, hp)
    pp, chunks = hp.pp, hp.chunks
    lps = hp.pp_division[0]
    vax = vocab_axes(hp)
    sched = build_schedule(pp, chunks)
    if hp.global_bsz % chunks != 0:
        raise ValueError("global_bsz must divide into chunks")

    ns = cfg.num_stages
    cum = np.cumsum(cfg.depths)
    L0 = cfg.stage_resolution(0) ** 2
    C0 = cfg.embed_dim
    N = L0 * C0  # flat channel width (largest activation; halves per merge)
    ch_spec = P(S._ax(vax.batch_axes), None)

    mask_not_branch = use_masked_path()

    # ------------------------------------------------- per-stage forward body
    def stage_body(s: int):
        lo = s * lps
        t_in = cfg.stage_of_block(lo)
        res_in = cfg.stage_resolution(t_in)
        c_in = cfg.stage_dim(t_in)

        def body(slots: List[Params], ch):
            x = ch[:, : res_in * res_in * c_in].reshape(-1, res_in, res_in, c_in)
            for j in range(lps):
                gi = lo + j
                t = cfg.stage_of_block(gi)
                d = gi - (int(cum[t - 1]) if t else 0)
                ax = layer_axes(hp, gi)
                bp = _map_shapes(
                    _slice_leaf,
                    {k: v for k, v in slots[j].items() if k != "merge"},
                    _block_shapes(cfg, _block_dims(cfg, t)),
                )
                fwd = partial(
                    SW.block_forward, cfg=cfg, stage=t, shift=(d % 2 == 1),
                    mesh=mesh, axes=ax,
                )
                if hp.layers[gi].checkpoint:
                    fwd = jax.checkpoint(fwd)
                x = fwd(bp, x)
                if t < ns - 1 and gi == cum[t] - 1:
                    mp = _map_shapes(
                        _slice_leaf, slots[j]["merge"], _merge_shapes(cfg, cfg.stage_dim(t))
                    )
                    x = SW.patch_merge(mp, x, cfg)
            out = x.reshape(x.shape[0], -1)
            out = jnp.pad(out, ((0, 0), (0, N - out.shape[1])))
            return S.constrain(out, mesh, ch_spec)

        return body

    # ------------------------------------------------------- uniform pieces
    def embed_fwd(vparams, pixels):
        dtype = cfg.compute_dtype
        emb = vparams["embed"]
        x = patchify(pixels.astype(dtype), cfg.patch_size)
        x = x @ emb["patch"]["kernel"].astype(dtype) + emb["patch"]["bias"].astype(dtype)
        x = layer_norm(x, emb["norm"]["scale"], emb["norm"]["bias"], cfg.layernorm_eps)
        return S.constrain(x.reshape(x.shape[0], -1), mesh, ch_spec)

    resL = cfg.stage_resolution(ns - 1)
    cL = cfg.stage_dim(ns - 1)

    def head_loss(vparams, y, labels, weight):
        dtype = cfg.compute_dtype
        h = S.constrain(y, mesh, ch_spec)[:, : resL * resL * cL]
        h = h.reshape(-1, resL * resL, cL)
        h = layer_norm(
            h, vparams["final_norm"]["scale"], vparams["final_norm"]["bias"],
            cfg.layernorm_eps,
        )
        pooled = jnp.mean(h, axis=1)
        logits = pooled @ vparams["head"]["kernel"].astype(dtype) + vparams["head"]["bias"].astype(dtype)
        return softmax_nll(logits, labels) * weight

    def loss_and_grad(params, batch):
        vparams = {k: v for k, v in params.items() if k != "stages"}
        stages = params["stages"]

        B = batch["pixels"].shape[0]
        mb = B // chunks

        def split(x):
            return x.reshape((chunks, mb) + x.shape[1:])

        pixels_mb = split(batch["pixels"])
        labels_mb = split(batch["labels"])

        def rep(t):
            return S.constrain(t, mesh, S.replicated_spec(t.ndim))

        pixels_mb, labels_mb = rep(pixels_mb), rep(labels_mb)
        weights = jnp.full((chunks,), 1.0 / chunks, jnp.float32)
        act_dtype = cfg.compute_dtype
        bodies = [stage_body(s) for s in range(pp)]

        xs = {
            "fwd_mb": jnp.asarray(sched.fwd_mb),
            "fwd_v": jnp.asarray(sched.fwd_valid),
            "arr_mb": jnp.asarray(sched.arr_mb),
            "arr_v": jnp.asarray(sched.arr_valid),
            "bwd_mb": jnp.asarray(sched.bwd_mb),
            "bwd_v": jnp.asarray(sched.bwd_valid),
            "head_mb": jnp.asarray(sched.head_mb),
            "head_v": jnp.asarray(sched.head_valid),
            "emb_mb": jnp.asarray(sched.emb_mb),
            "emb_v": jnp.asarray(sched.emb_valid),
            "inject_mb": jnp.asarray(sched.inject_mb),
        }

        # (see pipeline_1f1b.make_loss_and_grad for the divergence-safety
        # rationale: manual over pp, ONE cross-stage all-gather per tick,
        # mask-not-branch on CPU, branch exits pinned to fixed specs)
        def schedule_body(stages_in, vparams, pixels_mb, labels_mb, weights, xs):
            stage = lax.axis_index(PP_AXIS)
            local = [jax.tree.map(lambda a: a[0], t) for t in stages_in]

            def gather_mb(table, idx):
                return lax.dynamic_index_in_dim(
                    table, jnp.clip(idx, 0, chunks - 1), 0, keepdims=False
                )

            def tick(carry, xt):
                y_prev, dx_prev, dy, stash, loss, sgrads, vgrads = carry

                # gated on stage 0's forward validity (stage-uniform scalar;
                # see pipeline_1f1b.py): skip the patch embedding on dead
                # ticks; both branches pin ch_spec (invariant (b))
                x_inj = lax.cond(
                    xt["fwd_v"][0],
                    lambda: S.constrain(
                        embed_fwd(vparams, gather_mb(pixels_mb, xt["inject_mb"])).astype(act_dtype),
                        mesh, ch_spec,
                    ),
                    lambda: S.constrain(jnp.zeros((mb, N), act_dtype), mesh, ch_spec),
                )

                # THE cross-stage collective
                prev_all = lax.all_gather(jnp.stack([y_prev, dx_prev]), PP_AXIS)
                x_arr = lax.dynamic_index_in_dim(
                    prev_all, jnp.clip(stage - 1, 0, pp - 1), 0, keepdims=False
                )[0]
                x_arr = jnp.where(stage == 0, x_inj, x_arr)
                g_arr = lax.dynamic_index_in_dim(
                    prev_all, jnp.clip(stage + 1, 0, pp - 1), 0, keepdims=False
                )[1]
                y_exit = prev_all[pp - 1, 0]
                dx0 = prev_all[0, 1]

                aslot = xt["arr_mb"][stage] % sched.stash
                old = lax.dynamic_index_in_dim(stash, aslot, 0, keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(xt["arr_v"][stage], x_arr, old), aslot, 0
                )

                fmb = xt["fwd_mb"][stage]
                x_f = lax.dynamic_index_in_dim(stash, fmb % sched.stash, 0, keepdims=False)

                def run_fwd(x):
                    return lax.switch(stage, bodies, local, x)

                if mask_not_branch:
                    y = run_fwd(x_f) * xt["fwd_v"][stage].astype(act_dtype)
                else:
                    y = lax.cond(xt["fwd_v"][stage], run_fwd, jnp.zeros_like, x_f)

                g_in = jnp.where(stage == pp - 1, dy, g_arr)

                bmb = xt["bwd_mb"][stage]
                x_b = lax.dynamic_index_in_dim(stash, bmb % sched.stash, 0, keepdims=False)

                def run_bwd(g):
                    def fb(ps, xx):
                        return lax.switch(stage, bodies, ps, xx)

                    _, vjp = jax.vjp(fb, local, x_b)
                    dps_, dx_ = vjp(g)
                    # pin the branch exit INSIDE the branch (invariant (b),
                    # pipeline_1f1b.py)
                    dps_ = [
                        jax.tree.map(
                            lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), t
                        )
                        for t in dps_
                    ]
                    return dps_, S.constrain(dx_, mesh, ch_spec)

                def zero_bwd(g):
                    return jax.tree.map(jnp.zeros_like, local), jnp.zeros_like(x_b)

                if mask_not_branch:
                    dps, dx = run_bwd(g_in * xt["bwd_v"][stage].astype(act_dtype))
                else:
                    dps, dx = lax.cond(xt["bwd_v"][stage], run_bwd, zero_bwd, g_in)
                sgrads = jax.tree.map(jnp.add, sgrads, dps)

                # [uniform] head + loss on the exiting activation, gated on
                # head_v (stage-uniform; see pipeline_1f1b.py)
                e = xt["head_mb"]
                labels_e = gather_mb(labels_mb, e)
                w_e = weights[jnp.clip(e, 0, chunks - 1)]

                def _pin_tree(t):
                    return jax.tree.map(
                        lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), t
                    )

                def run_head():
                    l_e, head_vjp = jax.vjp(
                        lambda vp, yy: head_loss(vp, yy, labels_e, w_e), vparams, y_exit
                    )
                    dvp, dy_h = head_vjp(jnp.ones((), jnp.float32))
                    return l_e, _pin_tree(dvp), S.constrain(dy_h, mesh, ch_spec)

                l_e, dvp_head, dy_h = lax.cond(
                    xt["head_v"],
                    run_head,
                    lambda: (
                        jnp.zeros((), jnp.float32),
                        _pin_tree(jax.tree.map(jnp.zeros_like, vparams)),
                        S.constrain(jnp.zeros_like(y_exit), mesh, ch_spec),
                    ),
                )
                loss = loss + l_e
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_head)

                # [uniform] patch-embedding backward (stage 0's bwd, lagged)
                pix_b = gather_mb(pixels_mb, xt["emb_mb"])

                def run_emb():
                    _, evjp = jax.vjp(
                        lambda vp: embed_fwd(vp, pix_b).astype(act_dtype), vparams
                    )
                    (d,) = evjp(dx0)
                    return _pin_tree(d)

                dvp_e = lax.cond(
                    xt["emb_v"], run_emb,
                    lambda: _pin_tree(jax.tree.map(jnp.zeros_like, vparams)),
                )
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_e)

                return (
                    y, dx, dy_h.astype(act_dtype), stash, loss, sgrads, vgrads,
                ), None

            deps = jax.tree.leaves(vparams) + jax.tree.leaves(
                (pixels_mb, labels_mb, weights)
            )
            y0 = lax.optimization_barrier(
                tuple([jnp.zeros((mb, N), act_dtype)] + deps)
            )[0]
            carry0 = (
                y0,
                jnp.zeros((mb, N), act_dtype),
                jnp.zeros((mb, N), act_dtype),
                jnp.zeros((sched.stash, mb, N), act_dtype),
                jnp.zeros((), jnp.float32),
                [jax.tree.map(jnp.zeros_like, t) for t in local],
                jax.tree.map(jnp.zeros_like, vparams),
            )
            final, _ = lax.scan(tick, carry0, xs)
            loss, sgrads, vgrads = final[4], final[5], final[6]
            return (
                loss,
                [jax.tree.map(lambda a: a[None], t) for t in sgrads],
                vgrads,
            )

        pp_specs = [jax.tree.map(lambda _: P(PP_AXIS), t) for t in stages]

        def rep_tree(t):
            return jax.tree.map(lambda _: P(), t)

        smap = jax.shard_map(
            schedule_body,
            mesh=mesh,
            in_specs=(pp_specs, rep_tree(vparams), P(), P(), P(), rep_tree(xs)),
            out_specs=(P(), pp_specs, rep_tree(vparams)),
            axis_names={PP_AXIS},
            check_vma=False,
        )
        # Gather slot params from their tp/z3-sharded STORAGE layout to
        # within-stage replicated HERE, in the uniform pre-loop region: the
        # stage bodies statically SLICE the padded universal trees, and a
        # slice of a within-stage-sharded dim lowers to a GSPMD
        # collective-permute — inside the divergent branches that is the
        # deadlock class the engine forbids (pipeline_1f1b.py invariant).
        # State stays sharded (ZeRO semantics: shard for state, gather for
        # compute); window attention parallelises over batch x windows.
        stages_local = [
            jax.tree.map(
                lambda a: S.constrain(a, mesh, P(PP_AXIS, *([None] * (a.ndim - 1)))), t
            )
            for t in stages
        ]
        loss, sgrads, vgrads = smap(
            stages_local, vparams, pixels_mb, labels_mb, weights, xs
        )
        grads = dict(vgrads)
        grads["stages"] = sgrads
        return loss, grads

    return loss_and_grad
