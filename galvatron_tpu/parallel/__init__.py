from galvatron_tpu.parallel.mesh import (
    LayerAxes,
    build_mesh,
    layer_axes,
    subaxis_sizes,
    vocab_axes,
)

__all__ = ["LayerAxes", "build_mesh", "layer_axes", "vocab_axes", "subaxis_sizes"]
