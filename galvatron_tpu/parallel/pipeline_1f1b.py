"""True 1F1B (pipedream-flush) pipeline schedule as one SPMD program.

TPU-native re-design of the reference's 1F1B engine
(galvatron/core/runtime/pipeline/pipeline.py:375-701 — warmup :455-495,
steady one-forward-one-backward :512-631, cooldown :640-691, batched P2P
:1080-1257). The reference runs per-rank Python schedules with NCCL
send/recv; here the whole schedule — embedding, forward ticks, backward
ticks, the bounded activation stash, the hand-written backward, and the
head/loss — is ONE `lax.scan` inside ONE `shard_map` that is *manual* over
the ``pp`` mesh axis and *auto* (GSPMD) over the within-stage axes:

- each device knows its stage via ``lax.axis_index('pp')`` and follows its
  own row of a precomputed (T, pp) schedule table: 1F1B timing
  ``fwd(i, s) = s + i`` during warmup, ``2 i + s`` in steady state,
  ``bwd(j, s) = 2 j + 2 pp - s`` — the steady state alternates one forward
  and one backward per stage and stage s holds at most ``pp - s + 1``
  in-flight microbatches (the 1F1B activation watermark, reference
  cost_model.py:85-97), independent of ``chunks``;
- ALL cross-stage movement rides exactly ONE ``lax.all_gather`` over ``pp``
  per tick, carrying the previous tick's stage outputs (the analogue of the
  reference's ``batch_isend_irecv`` round): each stage slices its arriving
  activation, its arriving cotangent, the exiting activation for the
  head/loss, and stage 0's input cotangent for the embedding backward. One
  collective per tick + the scan's iteration barrier makes the cross-stage
  collective order total BY CONSTRUCTION — see the divergence-safety notes
  in `make_loss_and_grad` for why weaker designs deadlock;
- the backward is hand-written inside the scan: each backward tick pops the
  saved stage *input* from a ``min(pp + 1, chunks)``-deep circular stash and
  calls ``jax.vjp`` on the stage body (stage-granular rematerialisation —
  the same compute budget as the reference's 1F1B with
  ``--checkpoint_activations``), accumulating parameter gradients in a
  carried accumulator. Nothing autodiffs *through* the scan, so no per-tick
  residuals are saved — the live set is the stash plus one transient stage;
- per-stage bodies are selected with ``lax.switch``, so every stage may run
  its own layer strategies (tp/sp/fsdp/ckpt per layer — the reference's
  layer-wise heterogeneity, hybrid_parallel_model.py:263-268), with only
  group-scoped within-stage collectives allowed inside the divergent
  branches;
- the embedding and the head/loss run once per tick on every stage
  (redundantly — the last stage is the critical path either way), computing
  in the within-stage vocab_tp layout; their parameters are STORED with the
  vocab dimension sharded over ``('pp',) + vocab_tp`` (1/(pp*vtp) state per
  device, vs the reference's full replication per pp group,
  GPTModel_sequential.py:201-248) and gathered to the within-stage layout
  once per step at the shard_map boundary.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, List, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import PP_AXIS, layer_axes, vocab_axes

Params = Dict[str, Any]


def _stage_sigs(hp: HybridParallelConfig):
    """Per-stage (strategy, ...) tuples (variable length under uneven
    divisions)."""
    from galvatron_tpu.parallel.pipeline import stage_layer_offsets

    offs = stage_layer_offsets(hp)
    return [
        tuple(hp.layers[offs[s] + j] for j in range(hp.pp_division[s]))
        for s in range(hp.pp)
    ]


def validate_1f1b_config(hp: HybridParallelConfig):
    """Strategies may differ freely across stages, and divisions may be
    UNEVEN (reference slices arbitrary model_ranks, pipeline.py:110-112):
    short stages' trailing slots hold zero padding their `lax.switch` body
    statically skips. Ring cp>1 alone requires equal, stage-uniform stages
    (its collective-permutes must run identically everywhere every tick)."""
    if hp.pp <= 1:
        return
    div = hp.pp_division
    if any(n < 1 for n in div):
        raise ValueError("every pipeline stage needs >= 1 layer, got %s" % (div,))
    if any(s.cp > 1 for s in hp.layers):
        if len(set(_stage_sigs(hp))) != 1:
            raise ValueError(
                "ring-attention cp>1 inside the 1F1B schedule requires stage-"
                "uniform strategies (equal divisions included): the ring's "
                "collective-permutes must be executed identically by every "
                "stage every tick (see the divergence-safety invariant), "
                "which only the single-body schedule guarantees"
            )
    if hp.global_bsz % hp.chunks != 0:
        raise ValueError("global_bsz must divide into chunks")


# ================================================================== schedule
class Schedule(NamedTuple):
    """Precomputed (T, pp) 1F1B timetable (all numpy, trace-time constants)."""

    T: int
    stash: int
    fwd_mb: np.ndarray  # (T, pp) microbatch whose forward runs
    fwd_valid: np.ndarray  # (T, pp) bool
    arr_mb: np.ndarray  # (T, pp) microbatch arriving from the previous stage
    arr_valid: np.ndarray
    bwd_mb: np.ndarray  # (T, pp) microbatch whose backward runs
    bwd_valid: np.ndarray
    head_mb: np.ndarray  # (T,) microbatch whose head/loss runs this tick
    head_valid: np.ndarray
    emb_mb: np.ndarray  # (T,) microbatch whose embedding backward runs
    emb_valid: np.ndarray
    inject_mb: np.ndarray  # (T,) microbatch embedded for stage-0 injection


def use_masked_path(has_cp: bool = False) -> bool:
    """Mask-vs-branch path selection for the 1F1B engines (shared by the
    enc-dec and swin variants). Default: CPU masks (divergent branch
    collectives deadlock the single-process mesh), TPU branches (collectives
    match statically per replica group). cp>1 always masks — the ring's
    collective-permutes need every participant every tick on any backend.
    GALVATRON_1F1B_PATH=branch|masked overrides the backend default — used
    by the AOT tests that compile the TPU branch path for an abstract
    topology from a CPU host (tests/parallel/test_branch_path_aot.py)."""
    if has_cp:
        return True
    force = os.environ.get("GALVATRON_1F1B_PATH", "")
    if force == "branch":
        return False
    if force == "masked":
        return True
    return jax.default_backend() == "cpu"


def build_schedule(pp: int, chunks: int) -> Schedule:
    """1F1B slot equations, generated forward and inverted to tables.

    fwd(i, s) = s + i                     for i < pp - s   (warmup)
                2 i + s                   otherwise        (steady/cooldown)
    bwd(j, s) = 2 j + 2 pp - s

    All cross-stage movement rides ONE all-gather per tick carrying the
    PREVIOUS tick's stage outputs (see schedule_body), so every stage
    boundary costs one tick: forwards chain as fwd(i, s) = fwd(i, s-1) + 1;
    the head/loss runs one tick after the last-stage forward
    (head(i) = fwd(i, pp-1) + 1); the last stage's backward consumes the
    cotangent one tick after that (bwd(i, pp-1) = head(i) + 1); cotangents
    then flow down one stage per tick (bwd(i, s) = bwd(i, s+1) + 1); and the
    embedding backward runs one tick after stage 0's backward. Compared to
    the textbook per-rank 1F1B this costs 2 extra pipeline ticks end-to-end
    and one extra stash slot (min(pp+1, chunks)) — the price of keeping a
    single, trivially-ordered cross-stage collective per tick. A tick may
    host BOTH a forward and a backward on the same stage (the two slot
    equations share parity); the engine runs them as separate branches.
    """
    f = np.zeros((chunks, pp), np.int64)
    b = np.zeros((chunks, pp), np.int64)
    for s in range(pp):
        for i in range(chunks):
            f[i, s] = s + i if i < pp - s else 2 * i + s
            b[i, s] = 2 * i + 2 * pp - s
    # +1 past the last stage-0 backward so its embedding backward still runs
    T = int(b[chunks - 1, 0]) + 2
    stash = min(pp + 1, chunks)

    fwd_mb = np.zeros((T, pp), np.int32)
    fwd_valid = np.zeros((T, pp), bool)
    bwd_mb = np.zeros((T, pp), np.int32)
    bwd_valid = np.zeros((T, pp), bool)
    for s in range(pp):
        for i in range(chunks):
            t = f[i, s]
            assert not fwd_valid[t, s], "duplicate forward slot"
            fwd_mb[t, s], fwd_valid[t, s] = i, True
            t = b[i, s]
            assert not bwd_valid[t, s], "duplicate backward slot"
            bwd_mb[t, s], bwd_valid[t, s] = i, True

    # arrival at stage s (tick after the producer's forward); stage 0's
    # "arrival" is the embedding injection at its own forward tick.
    arr_mb = np.zeros((T, pp), np.int32)
    arr_valid = np.zeros((T, pp), bool)
    arr_mb[:, 0], arr_valid[:, 0] = fwd_mb[:, 0], fwd_valid[:, 0]
    arr_mb[1:, 1:], arr_valid[1:, 1:] = fwd_mb[:-1, :-1], fwd_valid[:-1, :-1]

    # stash-slot safety: an arriving microbatch's circular slot (mb % stash)
    # must be free, i.e. microbatch mb - stash was already popped (strictly
    # earlier: within a tick the arrival write precedes the backward read).
    for s in range(pp):
        for i in range(stash, chunks):
            assert b[i - stash, s] < f[i, s], (
                "stash slot clash at stage %d mb %d" % (s, i)
            )

    # head/loss processes the microbatch whose last-stage forward ran the
    # PREVIOUS tick (its activation arrives via this tick's all-gather)
    head_mb = np.zeros((T,), np.int32)
    head_valid = np.zeros((T,), bool)
    head_mb[1:], head_valid[1:] = fwd_mb[:-1, pp - 1], fwd_valid[:-1, pp - 1]
    # embedding backward: one tick after stage 0's backward
    emb_mb = np.zeros((T,), np.int32)
    emb_valid = np.zeros((T,), bool)
    emb_mb[1:], emb_valid[1:] = bwd_mb[:-1, 0], bwd_valid[:-1, 0]

    return Schedule(
        T=T, stash=stash,
        fwd_mb=fwd_mb, fwd_valid=fwd_valid,
        arr_mb=arr_mb, arr_valid=arr_valid,
        bwd_mb=bwd_mb, bwd_valid=bwd_valid,
        head_mb=head_mb, head_valid=head_valid,
        emb_mb=emb_mb, emb_valid=emb_valid,
        inject_mb=np.clip(fwd_mb[:, 0], 0, chunks - 1),
    )


# ============================================================== vocab sharding
def vocab_param_specs(cfg, hp: HybridParallelConfig) -> Params:
    """Override specs for the vocab layers under the 1f1b pipeline: the vocab
    dim is sharded over ('pp',) + vocab_tp, so embed/head state is split
    across pipeline groups instead of replicated per group."""
    from galvatron_tpu.models import base as M

    vax = vocab_axes(hp)
    specs = M.model_param_specs(cfg, hp)
    z3 = S._ax(vax.dp) if vax.zero3 else None
    vocab_ax = S._ax((PP_AXIS,) + (() if vax.ulysses else tuple(vax.tp)))
    if cfg.input_type != "patches":
        specs["embed"]["wte"] = P(vocab_ax, z3)
    if cfg.head_type in ("lm", "mlm") and not cfg.tie_embeddings:
        specs["lm_head"]["kernel"] = P(None, vocab_ax)
    if cfg.head_type == "mlm":
        specs["head"]["bias"] = P(vocab_ax)
    return specs


# ==================================================================== engine
def make_loss_and_grad(cfg, hp: HybridParallelConfig, mesh: Mesh):
    """Build ``fn(params, batch) -> (loss, grads)`` running the 1F1B schedule.

    The gradients are the token-weighted sum of per-microbatch gradients —
    the same objective as the chunked gradient-accumulation path in
    runtime/model_api.py (verified against it in
    tests/parallel/test_pipeline_1f1b.py)."""
    from galvatron_tpu.models import base as M

    from galvatron_tpu.parallel.pipeline import stage_layer_offsets

    validate_1f1b_config(hp)
    pp, chunks = hp.pp, hp.chunks
    offs = stage_layer_offsets(hp)
    vax = vocab_axes(hp)
    sched = build_schedule(pp, chunks)

    mb_spec = P(S._ax(vax.batch_axes), S._ax(vax.seq_axes), None)  # (mb, S, H)

    # ------------------------------------------------- per-stage forward body
    # Divergence-safety invariant (the round-2 multichip deadlock, reproduced
    # and bisected here): these bodies run inside `lax.cond`/`lax.switch`
    # branches that only SOME stages execute, and XLA:CPU's (and conservatively
    # TPU's) collective-permute rendezvous spans ALL devices — so any
    # GSPMD-inserted collective-permute in a branch deadlocks the step. Only
    # group-scoped collectives (all-reduce / all-gather / reduce-scatter /
    # grouped all-to-all over within-stage axes) may appear in branch code.
    # Enforced by (a) axis-monotone reshards between per-layer specs
    # (S.monotone_constrain), (b) pinning every branch output to a fixed spec
    # before the branch returns, and (c) the compile-time HLO guard
    # `assert_no_divergent_global_collectives`.
    def stage_body(s: int):
        lo = offs[s]

        def body(stage_layers: List[Params], x, pos, bias):
            prev = mb_spec
            # statically runs only this stage's live slots; padded trailing
            # slots (uneven divisions) are never referenced and get
            # exactly-zero grads from the vjp
            for j in range(hp.pp_division[s]):
                gi = lo + j
                ax = layer_axes(hp, gi)
                cur = S.act_spec(ax)
                x = S.monotone_constrain(x, mesh, prev, cur)
                fwd = partial(M.layer_forward, cfg=cfg, mesh=mesh, axes=ax,
                              attn_bias=bias)
                if hp.layers[gi].checkpoint:
                    fwd = jax.checkpoint(fwd)
                x = fwd(stage_layers[j], x, pos)
                prev = cur
            return S.monotone_constrain(x, mesh, prev, mb_spec)

        return body

    bodies = [stage_body(s) for s in range(pp)]
    # When every stage runs the same strategy list (the common case, incl.
    # every stage-uniform searched config), all bodies are identical — skip
    # the lax.switch so the program has NO stage-divergent control flow at
    # all (within-layer heterogeneity lives inside the single body).
    uniform_stages = len(set(_stage_sigs(hp))) == 1

    # XLA:CPU's in-process collective runtime keys rendezvous clique-wide: a
    # grouped collective executed by only the stage whose fwd/bwd slot is
    # valid this tick starves devices of other stages that never visit it,
    # and the schedule deadlocks (bisected live: stage 1 parked in its
    # backward's ZeRO-3 all-gather while stage 0 idles that tick). On CPU we
    # therefore run EVERY stage's forward and backward EVERY tick and mask
    # instead of branching: the cotangent is zeroed for invalid slots (vjp is
    # linear, so the gradients are exactly zero) and the forward result is
    # zeroed after the fact. The garbage compute fills ticks that were idle
    # anyway (fwd and bwd slots share parity per stage), so wall-clock is
    # unchanged; arithmetic doubles, which only matters for energy. On TPU
    # collectives are matched statically per replica group, so the efficient
    # lax.cond path (skip invalid slots) is safe and used — EXCEPT when ring
    # CP runs inside the schedule: the ring's collective-permutes need every
    # participant every tick on any backend, so cp>1 forces the masked path
    # (validate_1f1b_config already required stage-uniform strategies).
    has_cp = any(s.cp > 1 for s in hp.layers)
    mask_not_branch = use_masked_path(has_cp)

    # ------------------------------------------------------- vocab fwd pieces
    def embed_fwd(vparams, inputs, positions, token_types):
        """Vocab-parallel embedding on the within-stage gathered tables (see
        the vparams gather in loss_and_grad): the one-hot einsum partitions
        into masked local lookup + psum over the within-stage vocab_tp group
        (cf. base.py embed_tokens).

        ALL table lookups here are one-hot matmuls, not gathers: the vjp of a
        gather is a scatter-add, which GSPMD partitions with index-operand
        collective-permutes outside any dataflow ordering — the deadlock found
        by driving GPT (learned positions) through the 1F1B schedule. A
        matmul's vjp is a matmul: dense, orderable, and MXU-friendly."""
        emb = vparams["embed"]
        dtype = cfg.compute_dtype
        if cfg.input_type == "patches":
            x = M.embed_patches(emb, inputs, cfg)
            return S.constrain(x, mesh, mb_spec)
        onehot = jax.nn.one_hot(inputs, cfg.vocab_size, dtype=dtype)
        x = jnp.einsum("bsv,vh->bsh", onehot, emb["wte"].astype(dtype))
        if cfg.position_type == "learned":
            pos1h = jax.nn.one_hot(positions, cfg.max_seq_len, dtype=dtype)
            x = x + jnp.einsum("bsp,ph->bsh", pos1h, emb["wpe"].astype(dtype))
        if cfg.type_vocab_size:
            tti = token_types if token_types is not None else jnp.zeros_like(inputs)
            tti1h = jax.nn.one_hot(tti, cfg.type_vocab_size, dtype=dtype)
            x = x + jnp.einsum("bst,th->bsh", tti1h, emb["tte"].astype(dtype))
        if cfg.embed_norm:
            x = M._norm(x, emb["norm"], cfg)
        return S.constrain(x, mesh, mb_spec)

    def head_loss(vparams, y, labels, loss_mask, weight):
        h = S.constrain(y, mesh, mb_spec)
        logits = M.model_head(vparams, h, cfg)
        if cfg.head_type == "classification":
            return M.softmax_nll(logits, labels) * weight
        # within-stage vocab sharding (see the vparams gather in
        # loss_and_grad): the CE psums stay group-scoped inside the scan
        logits = S.constrain(logits, mesh, S.logits_spec(vax))
        return M.vocab_parallel_cross_entropy(logits, labels, loss_mask) * weight

    def loss_and_grad(params, batch):
        vparams_stored = {k: v for k, v in params.items() if k != "stages"}
        stages = params["stages"]  # list of lps stacked (pp, ...) trees

        B = batch[next(iter(batch))].shape[0]
        mb = B // chunks

        def split(x):
            return x.reshape((chunks, mb) + x.shape[1:])

        if cfg.input_type == "patches":
            inputs_mb = split(batch["pixels"])
            Sq = cfg.max_seq_len
            pos_mb = jnp.zeros((chunks, mb, Sq), jnp.int32)
        else:
            inputs_mb = split(batch["tokens"])
            pos_mb = split(batch["positions"])
            Sq = inputs_mb.shape[-1]
        labels_mb = split(batch["labels"])
        has_tti = batch.get("token_type_ids") is not None
        tti_mb = split(batch["token_type_ids"]) if has_tti else jnp.zeros((chunks, 1), jnp.int32)
        has_mask = batch.get("loss_mask") is not None
        mask_mb = split(batch["loss_mask"]) if has_mask else jnp.zeros((chunks, 1), jnp.float32)
        has_bias = batch.get("attn_mask") is not None
        bias_mb = (
            split(M.padding_attn_bias(batch["attn_mask"]))
            if has_bias else jnp.zeros((chunks, 1), jnp.float32)  # unused dummy
        )

        # Pin every per-tick table fully replicated BEFORE the shard_map: the
        # in_spec below only governs the manual pp axis, and a table left
        # auto-sharded over the within-stage axes makes every in-loop
        # gather/take a partitioned gather (one such gather crashes the GSPMD
        # partitioner, spmd_partitioner_util.cc:495, and the rest would emit
        # per-tick collectives for index reads that must stay local).
        def rep(t):
            return S.constrain(t, mesh, S.replicated_spec(t.ndim))

        inputs_mb, pos_mb, labels_mb, tti_mb, mask_mb, bias_mb = (
            rep(t) for t in (inputs_mb, pos_mb, labels_mb, tti_mb, mask_mb, bias_mb)
        )

        # per-microbatch loss weights: keeps the chunked objective identical
        # to chunks=1 (as in model_api.make_train_step)
        if has_mask:
            msums = jnp.sum(mask_mb.astype(jnp.float32), axis=tuple(range(1, mask_mb.ndim)))
            weights = msums / jnp.maximum(jnp.sum(msums), 1.0)
        else:
            weights = jnp.full((chunks,), 1.0 / chunks, jnp.float32)

        H = cfg.hidden_size
        act_dtype = cfg.compute_dtype

        xs = {
            "fwd_mb": jnp.asarray(sched.fwd_mb),
            "fwd_v": jnp.asarray(sched.fwd_valid),
            "arr_mb": jnp.asarray(sched.arr_mb),
            "arr_v": jnp.asarray(sched.arr_valid),
            "bwd_mb": jnp.asarray(sched.bwd_mb),
            "bwd_v": jnp.asarray(sched.bwd_valid),
            "head_mb": jnp.asarray(sched.head_mb),
            "head_v": jnp.asarray(sched.head_valid),
            "emb_mb": jnp.asarray(sched.emb_mb),
            "emb_v": jnp.asarray(sched.emb_valid),
            "inject_mb": jnp.asarray(sched.inject_mb),
        }

        # ------------------------------------------------------------------
        # The ENTIRE schedule runs inside ONE shard_map that is manual over
        # ``pp`` — embed, stage ticks, head/loss, and the embedding backward.
        # Rationale (the round-2/3 multichip deadlocks): XLA:CPU keys each
        # collective's rendezvous by (run_id, op_id) with no iteration or
        # branch context, reuses channel ids across distinct ops, and lets a
        # device park threads in several collectives at once — so once the
        # per-stage divergent branches skew each stage's executor timeline,
        # ANY two cross-stage collectives that are not strictly ordered by
        # dataflow can be entered in opposite orders by different stages and
        # cross-deadlock (or pair mismatched rendezvous). When the loop body
        # is GSPMD auto over the whole mesh the partitioner freely creates
        # such collectives (it re-grids even replicated einsums over the pp
        # axis). Two structural rules eliminate the class:
        #   1. manual over pp: GSPMD never sees the pp axis, so it cannot
        #      invent cross-stage collectives;
        #   2. exactly ONE hand-placed cross-stage collective per tick — a
        #      single all-gather of the previous tick's stage outputs, from
        #      which every stage slices what it needs (activation from below,
        #      cotangent from above, the exiting activation, stage 0's input
        #      cotangent). lax.scan's iteration barrier serialises successive
        #      instances, so the cross-stage order is total by construction.
        # Within-stage collectives stay GSPMD-auto: a stage's devices share
        # identical branch history, so their executor order is consistent and
        # group-scoped rendezvous cannot cross-deadlock.
        # ------------------------------------------------------------------
        def schedule_body(stages_in, vparams, inputs_mb, pos_mb, labels_mb,
                          tti_mb, mask_mb, bias_mb, weights, xs):
            stage = lax.axis_index(PP_AXIS)
            local = [jax.tree.map(lambda a: a[0], t) for t in stages_in]

            def gather_mb(table, idx):
                return lax.dynamic_index_in_dim(
                    table, jnp.clip(idx, 0, chunks - 1), 0, keepdims=False
                )

            def stage_row(table, idxs):
                return gather_mb(table, idxs[stage])

            def tick(carry, xt):
                y_prev, dx_prev, dy, stash, loss, sgrads, vgrads = carry

                # [uniform] embed this tick's injected microbatch — computed
                # redundantly by every stage (within-stage collectives only).
                # Gated on stage 0's forward validity: the predicate is
                # IDENTICAL on every device (a (T,)-table scalar), so the
                # cond is not stage-divergent control flow and the O(V)
                # embedding matmul is skipped on the ~half of ticks whose
                # injection is dead (warmup/cooldown/odd-parity).
                inj = xt["inject_mb"]
                tok = gather_mb(inputs_mb, inj)
                pos_i = gather_mb(pos_mb, inj)
                tti_i = gather_mb(tti_mb, inj) if has_tti else None
                # both branches pin their output to mb_spec (invariant (b):
                # cond branches must return identically-sharded values)
                x_inj = lax.cond(
                    xt["fwd_v"][0],
                    lambda: S.constrain(
                        embed_fwd(vparams, tok, pos_i, tti_i).astype(act_dtype),
                        mesh, mb_spec,
                    ),
                    lambda: S.constrain(
                        jnp.zeros((mb, Sq, H), act_dtype), mesh, mb_spec
                    ),
                )

                # THE cross-stage collective: every stage's previous-tick
                # outputs, everywhere. Slices below serve as activation
                # arrival (stage s-1's forward output), cotangent arrival
                # (stage s+1's backward output), the exiting activation for
                # head/loss (stage pp-1), and the embedding backward's input
                # cotangent (stage 0).
                prev_all = lax.all_gather(jnp.stack([y_prev, dx_prev]), PP_AXIS)
                x_arr = lax.dynamic_index_in_dim(
                    prev_all, jnp.clip(stage - 1, 0, pp - 1), 0, keepdims=False
                )[0]
                x_arr = jnp.where(stage == 0, x_inj, x_arr)
                g_arr = lax.dynamic_index_in_dim(
                    prev_all, jnp.clip(stage + 1, 0, pp - 1), 0, keepdims=False
                )[1]
                y_exit = prev_all[pp - 1, 0]
                dx0 = prev_all[0, 1]

                aslot = xt["arr_mb"][stage] % sched.stash
                old = lax.dynamic_index_in_dim(stash, aslot, 0, keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(xt["arr_v"][stage], x_arr, old), aslot, 0
                )

                # --- forward tick (divergent branch: within-stage collectives
                # only — see the divergence-safety invariant above stage_body)
                fmb = xt["fwd_mb"][stage]
                x_f = lax.dynamic_index_in_dim(stash, fmb % sched.stash, 0, keepdims=False)
                pos_f = stage_row(pos_mb, xt["fwd_mb"])
                bias_f = stage_row(bias_mb, xt["fwd_mb"]) if has_bias else None

                def run_fwd(x):
                    if uniform_stages:
                        return bodies[0](local, x, pos_f, bias_f)
                    return lax.switch(stage, bodies, local, x, pos_f, bias_f)

                if mask_not_branch:
                    y = run_fwd(x_f) * xt["fwd_v"][stage].astype(act_dtype)
                else:
                    # both branches pin the SAME exit sharding: the HLO
                    # verifier rejects conditionals whose branches disagree
                    # (caught by the AOT branch-path compile test — the bare
                    # zeros branch lowered replicated vs the live branch's
                    # mb_spec)
                    y = lax.cond(
                        xt["fwd_v"][stage],
                        lambda x: S.constrain(run_fwd(x), mesh, mb_spec),
                        lambda x: S.constrain(jnp.zeros_like(x), mesh, mb_spec),
                        x_f,
                    )

                g_in = jnp.where(stage == pp - 1, dy, g_arr)

                # --- backward tick (hand-written vjp; stage-granular remat)
                bmb = xt["bwd_mb"][stage]
                x_b = lax.dynamic_index_in_dim(stash, bmb % sched.stash, 0, keepdims=False)
                pos_b = stage_row(pos_mb, xt["bwd_mb"])
                bias_b = stage_row(bias_mb, xt["bwd_mb"]) if has_bias else None

                def run_bwd(g):
                    def fb(ps, xx):
                        if uniform_stages:
                            return bodies[0](ps, xx, pos_b, bias_b)
                        return lax.switch(stage, bodies, ps, xx, pos_b, bias_b)

                    _, vjp = jax.vjp(fb, local, x_b)
                    dps_, dx_ = vjp(g)
                    # Pin the branch exit INSIDE the branch: partial/sharded
                    # kernel grads -> within-stage-replicated. A reshard to
                    # replicated only lowers to all-reduce / all-gather
                    # (group-scoped), never an axis-reassigning
                    # collective-permute; without this pin the ZeRO
                    # grad-accumulator sharding propagates backward through
                    # the scan and GSPMD plants an m_tp -> m_dp permute in
                    # this divergent branch — the round-2 MULTICHIP deadlock.
                    dps_ = [
                        jax.tree.map(
                            lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), t
                        )
                        for t in dps_
                    ]
                    return dps_, S.constrain(dx_, mesh, mb_spec)

                def zero_bwd(g):
                    # mirror run_bwd's exit pins exactly (see fwd cond note)
                    zps = jax.tree.map(
                        lambda a: S.constrain(
                            jnp.zeros_like(a), mesh, S.replicated_spec(a.ndim)
                        ),
                        local,
                    )
                    return zps, S.constrain(jnp.zeros_like(x_b), mesh, mb_spec)

                if mask_not_branch:
                    # masked cotangent -> exactly-zero grads for invalid slots
                    dps, dx = run_bwd(g_in * xt["bwd_v"][stage].astype(act_dtype))
                else:
                    dps, dx = lax.cond(xt["bwd_v"][stage], run_bwd, zero_bwd, g_in)
                sgrads = jax.tree.map(jnp.add, sgrads, dps)

                # [uniform] head + loss for the microbatch whose last-stage
                # forward ran the PREVIOUS tick (every stage runs it
                # redundantly — the last stage is the critical path either
                # way); its cotangent feeds the last stage's backward NEXT
                # tick (bwd(j, pp-1) = head(j) + 1 by the slot equations).
                # head_v / emb_v are stage-uniform (T,)-table scalars, so
                # these conds are not stage-divergent; they skip the O(V)
                # head/embedding matmuls on the ticks whose slot is invalid.
                e = xt["head_mb"]
                labels_e = gather_mb(labels_mb, e)
                mask_e = gather_mb(mask_mb, e) if has_mask else None
                w_e = weights[jnp.clip(e, 0, chunks - 1)]

                def _pin_head(l_e, dvp, dy_h):
                    # invariant (b): identical branch-output shardings
                    return (
                        l_e,
                        jax.tree.map(
                            lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), dvp
                        ),
                        S.constrain(dy_h, mesh, mb_spec),
                    )

                def run_head():
                    l_e, head_vjp = jax.vjp(
                        lambda vp, yy: head_loss(vp, yy, labels_e, mask_e, w_e),
                        vparams, y_exit,
                    )
                    dvp, dy_h = head_vjp(jnp.ones((), jnp.float32))
                    return _pin_head(l_e, dvp, dy_h)

                l_e, dvp_head, dy_new = lax.cond(
                    xt["head_v"],
                    run_head,
                    lambda: _pin_head(
                        jnp.zeros((), jnp.float32),
                        jax.tree.map(jnp.zeros_like, vparams),
                        jnp.zeros_like(y_exit),
                    ),
                )
                loss = loss + l_e
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_head)

                # [uniform] embedding backward for the microbatch whose
                # stage-0 backward ran the PREVIOUS tick (its cotangent
                # arrived via this tick's all-gather)
                b0 = xt["emb_mb"]
                tok_b = gather_mb(inputs_mb, b0)
                pos_bb = gather_mb(pos_mb, b0)
                tti_b = gather_mb(tti_mb, b0) if has_tti else None

                def _pin_tree(t):
                    return jax.tree.map(
                        lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), t
                    )

                def run_emb():
                    _, embed_vjp = jax.vjp(
                        lambda vp: embed_fwd(vp, tok_b, pos_bb, tti_b).astype(act_dtype),
                        vparams,
                    )
                    (d,) = embed_vjp(dx0)
                    return _pin_tree(d)

                dvp_embed = lax.cond(
                    xt["emb_v"], run_emb,
                    lambda: _pin_tree(jax.tree.map(jnp.zeros_like, vparams)),
                )
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_embed)

                return (
                    y, dx, dy_new.astype(act_dtype), stash, loss, sgrads,
                    vgrads,
                ), None

            # Order the scan's FIRST cross-stage all-gather after every
            # shard_map boundary reshard (the vocab-params gather from the
            # pp-sharded storage layout, batch-table replication): those
            # reshards are cross-stage collectives in the uniform pre-loop
            # region, but the first tick's all-gather consumes only zeros and
            # would otherwise race them — the last deadlock shape found while
            # driving this engine (stage-0 parked in the tick gather, the
            # rest in the boundary permute).
            deps = jax.tree.leaves(vparams) + jax.tree.leaves(
                (inputs_mb, pos_mb, labels_mb, tti_mb, mask_mb, bias_mb, weights)
            )
            y0 = lax.optimization_barrier(
                tuple([jnp.zeros((mb, Sq, H), act_dtype)] + deps)
            )[0]
            carry0 = (
                y0,
                jnp.zeros((mb, Sq, H), act_dtype),
                jnp.zeros((mb, Sq, H), act_dtype),
                jnp.zeros((sched.stash, mb, Sq, H), act_dtype),
                jnp.zeros((), jnp.float32),
                [jax.tree.map(jnp.zeros_like, t) for t in local],
                jax.tree.map(jnp.zeros_like, vparams),
            )
            final, _ = lax.scan(tick, carry0, xs)
            loss, sgrads, vgrads = final[4], final[5], final[6]
            return (
                loss,
                [jax.tree.map(lambda a: a[None], t) for t in sgrads],
                vgrads,
            )

        pp_specs = [jax.tree.map(lambda _: P(PP_AXIS), t) for t in stages]

        def rep_tree(t):
            return jax.tree.map(lambda _: P(), t)

        smap = jax.shard_map(
            schedule_body,
            mesh=mesh,
            in_specs=(
                pp_specs,                     # stages: stacked across pp
                rep_tree(vparams_stored),     # vocab layers: within-stage layout
                P(), P(), P(), P(), P(), P(), P(),  # batch tables + weights
                rep_tree(xs),                 # schedule tables
            ),
            out_specs=(P(), pp_specs, rep_tree(vparams_stored)),
            axis_names={PP_AXIS},
            check_vma=False,
        )

        # Gather the vocab layers from their pp-sharded STORAGE layout
        # (vocab_param_specs: vocab over ('pp',) + vocab_tp — state is
        # 1/(pp*vtp) per device) into the within-stage layout the schedule
        # computes in. This one cross-stage all-gather per step happens HERE,
        # before any divergence, where it is safe.
        base_specs = M.model_param_specs(cfg, hp)
        vparams_local = jax.tree.map(
            lambda sp, t: S.constrain(t, mesh, sp),
            {k: base_specs[k] for k in vparams_stored}, vparams_stored,
            is_leaf=lambda x: isinstance(x, P),
        )
        loss, sgrads, vgrads = smap(
            stages, vparams_local, inputs_mb, pos_mb, labels_mb,
            tti_mb, mask_mb, bias_mb, weights, xs,
        )
        grads = dict(vgrads)
        grads["stages"] = sgrads
        return loss, grads

    return loss_and_grad


# ============================================================ divergence guard
def assert_no_divergent_global_collectives(hlo_text: str) -> None:
    """Compile-time deadlock guard for the 1F1B schedule.

    The schedule's per-stage `lax.cond`/`lax.switch` branches (the TPU path;
    the CPU path masks instead of branching) execute on only a subset of
    devices, but XLA's collective-permute rendezvous (rendezvous.cc) spans
    every device in the computation — a GSPMD resharding permute inside a
    branch therefore hangs the step on CPU and is conservatively unsafe on
    TPU. Group-scoped collectives (all-reduce / all-gather / reduce-scatter /
    grouped all-to-all over within-stage axes) are fine on TPU: collectives
    are matched statically per replica group, and branch predicates only vary
    across stages, never within one. This scans *optimized* HLO (GSPMD runs
    at compile time) and fails loudly instead of letting a future config
    deadlock at runtime. The engine's only hand-placed cross-stage collective
    (the per-tick all-gather) is uniform code, not under `/cond/`, and is
    excluded."""
    bad = []
    for line in hlo_text.splitlines():
        if "collective-permute" not in line:
            continue
        if "op_name=" not in line or "/cond/" not in line.split("op_name=", 1)[1]:
            continue
        bad.append(line.strip()[:240])
    if bad:
        raise RuntimeError(
            "collective-permute inside a stage-divergent branch (would deadlock "
            "across pipeline stages):\n" + "\n".join(bad)
        )


def compile_and_check(step_fn, *example_args):
    """Lower + compile a train step and run the divergence guard on the result.
    Returns the compiled executable (so callers pay compilation only once)."""
    compiled = jax.jit(step_fn).lower(*example_args).compile() if not hasattr(
        step_fn, "lower"
    ) else step_fn.lower(*example_args).compile()
    assert_no_divergent_global_collectives(compiled.as_text())
    return compiled
