"""True 1F1B (pipedream-flush) pipeline schedule as one SPMD program.

TPU-native re-design of the reference's 1F1B engine
(galvatron/core/runtime/pipeline/pipeline.py:375-701 — warmup :455-495,
steady one-forward-one-backward :512-631, cooldown :640-691, batched P2P
:1080-1257). The reference runs per-rank Python schedules with NCCL
send/recv; here the whole schedule — forward ticks, backward ticks, the
bounded activation stash, and the hand-written backward — is ONE jitted
`lax.scan` whose body enters a `shard_map` that is *manual* over the ``pp``
mesh axis and *auto* (GSPMD) over the within-stage axes:

- each device knows its stage via ``lax.axis_index('pp')`` and follows its
  own row of a precomputed (T, pp) schedule table: classic 1F1B timing
  ``fwd(i, s) = s + i`` during warmup (depth ``pp - s``), ``2 i + s`` in
  steady state, ``bwd(j, s) = 2 j + 2 pp - s - 1`` — so the steady state
  alternates one forward and one backward per stage and stage s holds at
  most ``pp - s`` in-flight microbatches (the 1F1B activation watermark,
  reference cost_model.py:85-97), independent of ``chunks``;
- stage boundaries are explicit ``lax.ppermute`` sends (the analogue of the
  reference's ``batch_isend_irecv``) — activations up, cotangents down;
- the backward is hand-written inside the scan: each backward tick pops the
  saved stage *input* from a ``min(pp, chunks)``-deep circular stash and
  calls ``jax.vjp`` on the stage body (stage-granular rematerialisation —
  the same compute budget as the reference's 1F1B with
  ``--checkpoint_activations``), accumulating parameter gradients in a
  carried accumulator. Nothing autodiffs *through* the scan, so no per-tick
  residuals are saved — the live set is the stash plus one transient stage;
- per-stage bodies are selected with ``lax.switch``, so every stage may run
  its own layer strategies (tp/sp/fsdp/ckpt per layer — the reference's
  layer-wise heterogeneity, hybrid_parallel_model.py:263-268) with GSPMD
  resharding the activations at stage boundaries;
- the embedding and the head/loss run *outside* the manual region, once per
  microbatch tick, with the vocab dimension of their weights sharded over
  ``('pp',) + vocab_tp`` — vocab-layer state is 1/(pp * vtp) per device
  (the reference instead replicates full embed/head per pp group,
  GPTModel_sequential.py:201-248) and the head matmul is parallelised over
  the whole mesh, which costs the same wall-clock as the reference's
  last-stage placement (the last stage is the critical path either way) and
  strictly less memory.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import PP_AXIS, layer_axes, vocab_axes

Params = Dict[str, Any]


def validate_1f1b_config(hp: HybridParallelConfig):
    """The stacked-parameter layout needs equal layers per stage with the same
    param-tree *shapes* per within-stage slot; strategies may differ freely
    across stages (unlike the gpipe scan's uniformity requirement)."""
    if hp.pp <= 1:
        return
    div = hp.pp_division
    if len(set(div)) != 1:
        raise ValueError(
            "1f1b pipeline requires equal layers per stage, got pp_division=%s" % (div,)
        )
    for s in hp.layers:
        if s.cp > 1:
            raise ValueError("cp>1 with pp>1 is not yet supported in the 1f1b pipeline")
    if hp.global_bsz % hp.chunks != 0:
        raise ValueError("global_bsz must divide into chunks")


# ================================================================== schedule
class Schedule(NamedTuple):
    """Precomputed (T, pp) 1F1B timetable (all numpy, trace-time constants)."""

    T: int
    stash: int
    fwd_mb: np.ndarray  # (T, pp) microbatch whose forward runs
    fwd_valid: np.ndarray  # (T, pp) bool
    arr_mb: np.ndarray  # (T, pp) microbatch arriving from the previous stage
    arr_valid: np.ndarray
    bwd_mb: np.ndarray  # (T, pp) microbatch whose backward runs
    bwd_valid: np.ndarray
    exit_mb: np.ndarray  # (T,) microbatch leaving the last stage this tick
    exit_valid: np.ndarray
    inject_mb: np.ndarray  # (T,) microbatch embedded for stage-0 injection


def build_schedule(pp: int, chunks: int) -> Schedule:
    """Classic 1F1B slot equations, generated forward and inverted to tables.

    fwd(i, s) = s + i                     for i < pp - s   (warmup)
                2 i + s                   otherwise        (steady/cooldown)
    bwd(j, s) = 2 j + 2 pp - s - 1
    """
    f = np.zeros((chunks, pp), np.int64)
    b = np.zeros((chunks, pp), np.int64)
    for s in range(pp):
        for i in range(chunks):
            f[i, s] = s + i if i < pp - s else 2 * i + s
            b[i, s] = 2 * i + 2 * pp - s - 1
    T = int(b[chunks - 1, 0]) + 1
    stash = min(pp, chunks)

    fwd_mb = np.zeros((T, pp), np.int32)
    fwd_valid = np.zeros((T, pp), bool)
    bwd_mb = np.zeros((T, pp), np.int32)
    bwd_valid = np.zeros((T, pp), bool)
    for s in range(pp):
        for i in range(chunks):
            t = f[i, s]
            assert not fwd_valid[t, s] and not bwd_valid[t, s], "schedule slot clash"
            fwd_mb[t, s], fwd_valid[t, s] = i, True
            t = b[i, s]
            assert not fwd_valid[t, s] and not bwd_valid[t, s], "schedule slot clash"
            bwd_mb[t, s], bwd_valid[t, s] = i, True

    # arrival at stage s (tick after the producer's forward); stage 0's
    # "arrival" is the embedding injection at its own forward tick.
    arr_mb = np.zeros((T, pp), np.int32)
    arr_valid = np.zeros((T, pp), bool)
    arr_mb[:, 0], arr_valid[:, 0] = fwd_mb[:, 0], fwd_valid[:, 0]
    arr_mb[1:, 1:], arr_valid[1:, 1:] = fwd_mb[:-1, :-1], fwd_valid[:-1, :-1]

    # stash-slot safety: an arriving microbatch's circular slot (mb % stash)
    # must be free, i.e. microbatch mb - stash was already popped.
    for s in range(pp):
        for i in range(stash, chunks):
            arr = f[i, s - 1] + 1 if s > 0 else f[i, 0]
            assert b[i - stash, s] < arr, "stash slot clash at stage %d mb %d" % (s, i)

    return Schedule(
        T=T, stash=stash,
        fwd_mb=fwd_mb, fwd_valid=fwd_valid,
        arr_mb=arr_mb, arr_valid=arr_valid,
        bwd_mb=bwd_mb, bwd_valid=bwd_valid,
        exit_mb=fwd_mb[:, pp - 1].copy(), exit_valid=fwd_valid[:, pp - 1].copy(),
        inject_mb=np.clip(fwd_mb[:, 0], 0, chunks - 1),
    )


# ============================================================== vocab sharding
def vocab_param_specs(cfg, hp: HybridParallelConfig) -> Params:
    """Override specs for the vocab layers under the 1f1b pipeline: the vocab
    dim is sharded over ('pp',) + vocab_tp, so embed/head state is split
    across pipeline groups instead of replicated per group."""
    from galvatron_tpu.models import base as M

    vax = vocab_axes(hp)
    specs = M.model_param_specs(cfg, hp)
    z3 = S._ax(vax.dp) if vax.zero3 else None
    vocab_ax = S._ax((PP_AXIS,) + (() if vax.ulysses else tuple(vax.tp)))
    if cfg.input_type != "patches":
        specs["embed"]["wte"] = P(vocab_ax, z3)
    if cfg.head_type in ("lm", "mlm") and not cfg.tie_embeddings:
        specs["lm_head"]["kernel"] = P(None, vocab_ax)
    if cfg.head_type == "mlm":
        specs["head"]["bias"] = P(vocab_ax)
    return specs


def _logits_spec_pp(vax) -> P:
    vocab_ax = S._ax((PP_AXIS,) + (() if vax.ulysses else tuple(vax.tp)))
    seq_ax = S._ax(vax.seq_axes) if vax.ulysses else S._ax(vax.cp)
    return P(S._ax(vax.batch_axes), seq_ax, vocab_ax)


# ==================================================================== engine
def make_loss_and_grad(cfg, hp: HybridParallelConfig, mesh: Mesh):
    """Build ``fn(params, batch) -> (loss, grads)`` running the 1F1B schedule.

    The gradients are the token-weighted sum of per-microbatch gradients —
    the same objective as the chunked gradient-accumulation path in
    runtime/model_api.py (verified against it in
    tests/parallel/test_pipeline_1f1b.py)."""
    from galvatron_tpu.models import base as M

    validate_1f1b_config(hp)
    pp, chunks = hp.pp, hp.chunks
    lps = hp.pp_division[0]
    vax = vocab_axes(hp)
    sched = build_schedule(pp, chunks)
    perm_up = [(i, i + 1) for i in range(pp - 1)]
    perm_down = [(i, i - 1) for i in range(1, pp)]

    mb_spec = P(S._ax(vax.batch_axes), S._ax(vax.seq_axes), None)  # (mb, S, H)
    buf_spec = P(PP_AXIS, S._ax(vax.batch_axes), S._ax(vax.seq_axes), None)
    stash_spec = P(PP_AXIS, None, S._ax(vax.batch_axes), S._ax(vax.seq_axes), None)

    # ------------------------------------------------- per-stage forward body
    def stage_body(s: int):
        lo = s * lps

        def body(stage_layers: List[Params], x, pos, bias):
            for j in range(lps):
                gi = lo + j
                ax = layer_axes(hp, gi)
                x = S.constrain(x, mesh, S.act_spec(ax))
                fwd = partial(M.layer_forward, cfg=cfg, mesh=mesh, axes=ax,
                              attn_bias=bias)
                if hp.layers[gi].checkpoint:
                    fwd = jax.checkpoint(fwd)
                x = fwd(stage_layers[j], x, pos)
            return S.constrain(x, mesh, mb_spec)

        return body

    bodies = [stage_body(s) for s in range(pp)]

    # ------------------------------------------------------- vocab fwd pieces
    def embed_fwd(vparams, inputs, positions, token_types):
        """Vocab-parallel embedding with the table's vocab dim sharded over
        (pp, vtp): the one-hot einsum partitions into masked local lookup +
        psum across all pipeline groups (cf. base.py embed_tokens; forced to
        the one-hot path because pp always shards the vocab here)."""
        emb = vparams["embed"]
        dtype = cfg.compute_dtype
        if cfg.input_type == "patches":
            x = M.embed_patches(emb, inputs, cfg)
            return S.constrain(x, mesh, mb_spec)
        onehot = jax.nn.one_hot(inputs, cfg.vocab_size, dtype=dtype)
        x = jnp.einsum("bsv,vh->bsh", onehot, emb["wte"].astype(dtype))
        if cfg.position_type == "learned":
            x = x + emb["wpe"].astype(dtype)[positions]
        if cfg.type_vocab_size:
            tti = token_types if token_types is not None else jnp.zeros_like(inputs)
            x = x + emb["tte"].astype(dtype)[tti]
        if cfg.embed_norm:
            x = M._norm(x, emb["norm"], cfg)
        return S.constrain(x, mesh, mb_spec)

    def head_loss(vparams, y, labels, loss_mask, weight):
        h = S.constrain(y, mesh, mb_spec)
        logits = M.model_head(vparams, h, cfg)
        if cfg.head_type == "classification":
            return M.softmax_nll(logits, labels) * weight
        logits = S.constrain(logits, mesh, _logits_spec_pp(vax))
        return M.vocab_parallel_cross_entropy(logits, labels, loss_mask) * weight

    def loss_and_grad(params, batch):
        vparams = {k: v for k, v in params.items() if k != "stages"}
        stages = params["stages"]  # list of lps stacked (pp, ...) trees
        B = batch[next(iter(batch))].shape[0]
        mb = B // chunks

        def split(x):
            return x.reshape((chunks, mb) + x.shape[1:])

        if cfg.input_type == "patches":
            inputs_mb = split(batch["pixels"])
            Sq = cfg.max_seq_len
            pos_mb = jnp.zeros((chunks, mb, Sq), jnp.int32)
        else:
            inputs_mb = split(batch["tokens"])
            pos_mb = split(batch["positions"])
            Sq = inputs_mb.shape[-1]
        labels_mb = split(batch["labels"])
        tti_mb = (
            split(batch["token_type_ids"])
            if batch.get("token_type_ids") is not None else None
        )
        mask_mb = split(batch["loss_mask"]) if batch.get("loss_mask") is not None else None
        has_bias = batch.get("attn_mask") is not None
        bias_mb = (
            split(M.padding_attn_bias(batch["attn_mask"]))
            if has_bias else jnp.zeros((chunks, 1), jnp.float32)  # unused dummy
        )

        # per-microbatch loss weights: keeps the chunked objective identical
        # to chunks=1 (as in model_api.make_train_step)
        if mask_mb is not None:
            msums = jnp.sum(mask_mb.astype(jnp.float32), axis=tuple(range(1, mask_mb.ndim)))
            weights = msums / jnp.maximum(jnp.sum(msums), 1.0)
        else:
            weights = jnp.full((chunks,), 1.0 / chunks, jnp.float32)

        H = cfg.hidden_size
        act_dtype = cfg.compute_dtype

        def tick_inner(stages_in, sgrads_in, x_out, g_out, stash, x_inj, dy,
                       pos_f_all, pos_b_all, bias_f_all, bias_b_all,
                       fwd_mb_t, fwd_v_t, arr_mb_t, arr_v_t, bwd_mb_t, bwd_v_t):
            stage = lax.axis_index(PP_AXIS)
            local = [jax.tree.map(lambda a: a[0], t) for t in stages_in]
            glocal = [jax.tree.map(lambda a: a[0], t) for t in sgrads_in]

            # --- arrival: previous tick's outputs shift up one stage; the
            # stage-0 arrival is this tick's embedded injection.
            x_arr = lax.ppermute(x_out[0], PP_AXIS, perm_up)
            x_arr = jnp.where(stage == 0, x_inj, x_arr)
            aslot = arr_mb_t[stage] % sched.stash
            old = lax.dynamic_index_in_dim(stash[0], aslot, 0, keepdims=False)
            stash_new = lax.dynamic_update_index_in_dim(
                stash[0], jnp.where(arr_v_t[stage], x_arr, old), aslot, 0
            )

            # --- forward tick
            fmb = fwd_mb_t[stage]
            x_f = lax.dynamic_index_in_dim(stash_new, fmb % sched.stash, 0, keepdims=False)
            pos_f = pos_f_all[0]
            bias_f = bias_f_all[0] if has_bias else None

            def run_fwd(x):
                return lax.switch(stage, bodies, local, x, pos_f, bias_f)

            y = lax.cond(fwd_v_t[stage], run_fwd, jnp.zeros_like, x_f)

            # --- backward tick (hand-written vjp; stage-granular remat)
            g_arr = lax.ppermute(g_out[0], PP_AXIS, perm_down)
            g_in = jnp.where(stage == pp - 1, dy, g_arr)
            bmb = bwd_mb_t[stage]
            x_b = lax.dynamic_index_in_dim(stash_new, bmb % sched.stash, 0, keepdims=False)
            pos_b = pos_b_all[0]
            bias_b = bias_b_all[0] if has_bias else None

            def run_bwd(g):
                def fb(ps, xx):
                    return lax.switch(stage, bodies, ps, xx, pos_b, bias_b)

                _, vjp = jax.vjp(fb, local, x_b)
                return vjp(g)

            def zero_bwd(g):
                return jax.tree.map(jnp.zeros_like, local), jnp.zeros_like(x_b)

            dps, dx = lax.cond(bwd_v_t[stage], run_bwd, zero_bwd, g_in)
            glocal = jax.tree.map(jnp.add, glocal, dps)

            return (
                y[None],
                dx[None],
                stash_new[None],
                [jax.tree.map(lambda a: a[None], t) for t in glocal],
            )

        pp_specs = [jax.tree.map(lambda _: P(PP_AXIS), t) for t in stages]
        smap = jax.shard_map(
            tick_inner,
            mesh=mesh,
            in_specs=(
                pp_specs, pp_specs,                      # stages, sgrads
                P(PP_AXIS), P(PP_AXIS), P(PP_AXIS),      # x_out, g_out, stash
                P(), P(),                                # x_inj, dy
                P(PP_AXIS), P(PP_AXIS), P(PP_AXIS), P(PP_AXIS),  # pos/bias rows
                P(), P(), P(), P(), P(), P(),            # schedule vectors
            ),
            out_specs=(P(PP_AXIS), P(PP_AXIS), P(PP_AXIS), pp_specs),
            axis_names={PP_AXIS},
            check_vma=False,
        )

        def gather_mb(table, idx):
            return lax.dynamic_index_in_dim(
                table, jnp.clip(idx, 0, chunks - 1), 0, keepdims=False
            )

        def tick(carry, xt):
            x_out, g_out, dy, stash, loss, sgrads, vgrads = carry

            # [world] embed the microbatch injected at stage 0 this tick
            inj = xt["inject_mb"]
            tok = gather_mb(inputs_mb, inj)
            pos_i = gather_mb(pos_mb, inj)
            tti_i = gather_mb(tti_mb, inj) if tti_mb is not None else None
            x_inj = embed_fwd(vparams, tok, pos_i, tti_i).astype(act_dtype)

            # per-stage microbatch rows for this tick's fwd/bwd stage work,
            # gathered in the world region ((pp, ...) pp-sharded operands)
            def rows(table, idxs):
                # pp-sharded on dim 0 and REPLICATED elsewhere: any resharding
                # of these small operands must happen here in the world region,
                # never inside the divergent per-stage cond branches (a
                # collective there would rendezvous across stages running
                # different branches and deadlock).
                out = jnp.take(table, jnp.clip(idxs, 0, chunks - 1), axis=0)
                return S.constrain(out, mesh, P(*([PP_AXIS] + [None] * (out.ndim - 1))))

            pos_f_all = rows(pos_mb, xt["fwd_mb"])
            pos_b_all = rows(pos_mb, xt["bwd_mb"])
            bias_f_all = rows(bias_mb, xt["fwd_mb"])
            bias_b_all = rows(bias_mb, xt["bwd_mb"])

            # [manual pp] arrivals + one forward and one backward stage tick
            x_out, g_out, stash, sgrads = smap(
                stages, sgrads, x_out, g_out, stash, x_inj, dy,
                pos_f_all, pos_b_all, bias_f_all, bias_b_all,
                xt["fwd_mb"], xt["fwd_v"], xt["arr_mb"],
                xt["arr_v"], xt["bwd_mb"], xt["bwd_v"],
            )

            # [world] head + loss for the microbatch leaving the last stage;
            # its cotangent feeds the last stage's backward NEXT tick
            # (bwd(j, pp-1) = fwd-exit(j) + 1 by the slot equations).
            e = xt["exit_mb"]
            ev = xt["exit_v"].astype(jnp.float32)
            labels_e = gather_mb(labels_mb, e)
            mask_e = gather_mb(mask_mb, e) if mask_mb is not None else None
            w_e = weights[jnp.clip(e, 0, chunks - 1)]
            y_last = x_out[pp - 1]
            l_e, head_vjp = jax.vjp(
                lambda vp, yy: head_loss(vp, yy, labels_e, mask_e, w_e), vparams, y_last
            )
            dvp_head, dy_new = head_vjp(ev)
            loss = loss + l_e * ev
            vgrads = jax.tree.map(jnp.add, vgrads, dvp_head)

            # [world] embedding backward for the microbatch whose stage-0
            # backward ran this tick (its dx just came out of the manual region)
            b0 = xt["bwd_mb0"]
            b0v = xt["bwd_v0"].astype(act_dtype)
            tok_b = gather_mb(inputs_mb, b0)
            pos_b = gather_mb(pos_mb, b0)
            tti_b = gather_mb(tti_mb, b0) if tti_mb is not None else None
            dx0 = g_out[0]
            _, embed_vjp = jax.vjp(
                lambda vp: embed_fwd(vp, tok_b, pos_b, tti_b).astype(act_dtype), vparams
            )
            (dvp_embed,) = embed_vjp(dx0 * b0v)
            vgrads = jax.tree.map(jnp.add, vgrads, dvp_embed)

            return (x_out, g_out, dy_new.astype(act_dtype), stash, loss, sgrads, vgrads), None

        xs = {
            "fwd_mb": jnp.asarray(sched.fwd_mb),
            "fwd_v": jnp.asarray(sched.fwd_valid),
            "arr_mb": jnp.asarray(sched.arr_mb),
            "arr_v": jnp.asarray(sched.arr_valid),
            "bwd_mb": jnp.asarray(sched.bwd_mb),
            "bwd_v": jnp.asarray(sched.bwd_valid),
            "bwd_mb0": jnp.asarray(sched.bwd_mb[:, 0]),
            "bwd_v0": jnp.asarray(sched.bwd_valid[:, 0]),
            "exit_mb": jnp.asarray(sched.exit_mb),
            "exit_v": jnp.asarray(sched.exit_valid),
            "inject_mb": jnp.asarray(sched.inject_mb),
        }

        carry0 = (
            S.constrain(jnp.zeros((pp, mb, Sq, H), act_dtype), mesh, buf_spec),
            S.constrain(jnp.zeros((pp, mb, Sq, H), act_dtype), mesh, buf_spec),
            jnp.zeros((mb, Sq, H), act_dtype),
            S.constrain(jnp.zeros((pp, sched.stash, mb, Sq, H), act_dtype), mesh, stash_spec),
            jnp.zeros((), jnp.float32),
            jax.tree.map(jnp.zeros_like, stages),
            jax.tree.map(jnp.zeros_like, vparams),
        )
        final, _ = lax.scan(tick, carry0, xs)
        loss, sgrads, vgrads = final[4], final[5], final[6]
        grads = dict(vgrads)
        grads["stages"] = sgrads
        return loss, grads

    return loss_and_grad
