"""1F1B pipeline schedule for encoder-decoder models (T5).

The reference runs T5 through its pipeline as a matter of course — decoder
stages receive multi-tensor sends carrying BOTH the decoder hidden state and
the encoder output for cross-attention (reference pipeline.py:1442-1580
send/recv_forward_multi; multi-layer-type DP, dynamic_programming.py:170-189).
This module is the TPU-native equivalent, built on the same schedule tables
and divergence-safety rules as the generic engine (parallel/pipeline_1f1b.py
— read its docstring first; every invariant there applies here):

- the pipeline CHANNEL is a PAIR ``(h, mem)``: encoder stages produce
  ``(enc_h, enc_h)`` (the last encoder stage seeds ``mem`` with the
  final-normed encoder output); decoder stages consume ``mem`` for
  cross-attention and pass it through unchanged, so ``jax.vjp`` of the stage
  body automatically accumulates every decoder stage's cross-attention
  cotangent down the chain into the encoder backward — the hand-rolled
  d(enc_out) bookkeeping of a rank-based runtime falls out of autodiff;
- there are TWO injection points: encoder token embeddings enter at stage 0,
  decoder token embeddings replace the ``h`` component at the first decoder
  stage ``pe`` (the arriving encoder hidden is dropped there, so the
  cotangent flowing from stage ``pe`` down to ``pe - 1`` zeroes its ``h``
  component), and symmetrically TWO embedding backwards run in the uniform
  region;
- every stage slot carries a UNIVERSAL decoder-shaped parameter tree:
  encoder stages hold zero-initialised, never-referenced cross-attention
  entries so the stacked (pp, ...) layout stays uniform — the price is
  ~1/3 extra parameter state on encoder stages, the payoff is that the
  stacking/ZeRO/spec machinery of the generic engine applies unchanged;
- T5's relative-position tables live INSIDE slot 0 of each stage (they feed
  every layer's attention bias, so their gradient must flow through the
  stage-body vjp); same-type stages hold tied copies, and the tick-invariant
  tie is restored after the scan by summing + re-broadcasting the stacked
  gradient rows over the encoder range and the decoder range.

Sequence lengths: the schedule's static channel requires one sequence length,
so encoder and decoder streams are padded to ``max(Se, Sd)`` by the caller
(`models/t5.py` pads and extends attn/loss masks).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import PP_AXIS, layer_axes, vocab_axes
from galvatron_tpu.parallel.pipeline_1f1b import build_schedule, use_masked_path

Params = Dict[str, Any]


def validate_encdec_config(cfg, hp: HybridParallelConfig) -> int:
    """Returns pe, the number of encoder stages. The enc/dec boundary must
    fall on a stage boundary and every stage must hold the same layer count
    (the universal-slot layout needs equal slots per stage)."""
    if hp.pp <= 1:
        return 0
    div = hp.pp_division
    if len(set(div)) != 1:
        raise ValueError(
            "enc-dec 1F1B requires equal layers per stage, got pp_division=%s" % (div,)
        )
    lps = div[0]
    if cfg.num_enc_layers % lps != 0:
        raise ValueError(
            "the encoder/decoder boundary must align with a stage boundary: "
            "%d encoder layers do not divide into stages of %d layers"
            % (cfg.num_enc_layers, lps)
        )
    for s in hp.layers:
        if s.cp > 1:
            raise ValueError("cp>1 with pp>1 is not yet supported in the 1f1b pipeline")
    return cfg.num_enc_layers // lps


# =========================================================== universal stacking
def stack_t5_layer_specs(cfg, hp: HybridParallelConfig):
    """Per-slot specs for the universal decoder-shaped tree (+ slot-0 extras:
    the rel-bias table and the encoder seed norm)."""
    from galvatron_tpu.models.t5 import dec_layer_specs

    lps = hp.pp_division[0]
    out = []
    for j in range(lps):
        ax = layer_axes(hp, j)
        spec_j = dict(dec_layer_specs(cfg, ax))
        if j == 0:
            spec_j["rel_bias"] = P(None, None)
            spec_j["seed_norm"] = {"scale": P(None)}
        out.append(jax.tree.map(
            lambda sp: P(PP_AXIS, *sp), spec_j, is_leaf=lambda x: isinstance(x, P)
        ))
    return out


def stack_t5_params(params: Params, cfg, hp: HybridParallelConfig) -> List[Params]:
    """Canonical t5 tree (enc_layers / dec_layers / rel tables / norms) ->
    list of lps universal slot trees with a leading pp dim."""
    from galvatron_tpu.models.t5 import init_dec_layer

    pp, lps = hp.pp, hp.pp_division[0]
    pe = cfg.num_enc_layers // lps
    template = jax.tree.map(
        jnp.zeros_like, init_dec_layer(jax.random.PRNGKey(0), cfg)
    )

    def slot_tree(s: int, j: int) -> Params:
        if s < pe:
            src = params["enc_layers"][s * lps + j]
            tree = dict(template)
            tree.update(jax.tree.map(lambda a: a, src))
        else:
            tree = dict(params["dec_layers"][(s - pe) * lps + j])
        if j == 0:
            tree["rel_bias"] = (
                params["enc_rel_bias"] if s < pe else params["dec_rel_bias"]
            )
            tree["seed_norm"] = {
                "scale": params["enc_norm"]["scale"] if s == pe - 1
                else jnp.ones_like(params["enc_norm"]["scale"])
            }
        return tree

    stacked = []
    for j in range(lps):
        per_stage = [slot_tree(s, j) for s in range(pp)]
        stacked.append(jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage))
    return stacked


def unstack_t5_params(stacked: List[Params], cfg, hp: HybridParallelConfig) -> Params:
    """Inverse of stack_t5_params for checkpoint export: recovers the
    canonical tree (encoder slots drop the zero cross-attention entries)."""
    pp, lps = hp.pp, hp.pp_division[0]
    pe = cfg.num_enc_layers // lps
    enc_layers, dec_layers = [], []
    for s in range(pp):
        for j in range(lps):
            tree = jax.tree.map(lambda a: a[s], stacked[j])
            rel = tree.pop("rel_bias", None)
            seed = tree.pop("seed_norm", None)
            if s < pe:
                for k in ("cross", "ln_cross"):
                    tree.pop(k, None)
                enc_layers.append(tree)
            else:
                dec_layers.append(tree)
            if j == 0:
                if s == 0:
                    enc_rel = rel
                if s == pe:
                    dec_rel = rel
                if s == pe - 1:
                    enc_norm = {"scale": seed["scale"]}
    return {
        "enc_layers": enc_layers, "dec_layers": dec_layers,
        "enc_rel_bias": enc_rel, "dec_rel_bias": dec_rel, "enc_norm": enc_norm,
    }


# ==================================================================== engine
def make_encdec_loss_and_grad(cfg, hp: HybridParallelConfig, mesh):
    """``fn(params, batch) -> (loss, grads)`` running T5 through the 1F1B
    schedule. params: {embed, dec_norm, (lm_head), stages}; batch (padded to
    a common seq length by models/t5.py): tokens, dec_tokens, labels,
    loss_mask?, attn_mask?."""
    from galvatron_tpu.models import t5 as T

    pe = validate_encdec_config(cfg, hp)
    pp, chunks = hp.pp, hp.chunks
    lps = hp.pp_division[0]
    vax = vocab_axes(hp)
    sched = build_schedule(pp, chunks)
    if hp.global_bsz % chunks != 0:
        raise ValueError("global_bsz must divide into chunks")

    mb_spec = P(S._ax(vax.batch_axes), S._ax(vax.seq_axes), None)
    # boundary spec of the (h, mem) channel pair
    pair_spec = P(None, S._ax(vax.batch_axes), S._ax(vax.seq_axes), None)

    # encoder and decoder bodies always differ, so the lax.switch can never
    # collapse to a single body the way the generic engine's does
    uniform_stages = False
    mask_not_branch = use_masked_path()

    # ------------------------------------------------- per-stage forward body
    def stage_body(s: int, Sq: int):
        lo = s * lps
        is_enc = s < pe

        def body(stage_layers: List[Params], ch, self_bias, cross_bias):
            rel = stage_layers[0]["rel_bias"]
            h, mem = ch[0], ch[1]
            bias = T.rel_bias(rel, Sq, Sq, cfg, bidirectional=is_enc)
            if is_enc:
                bias = bias + self_bias
            prev = mb_spec
            for j in range(lps):
                gi = lo + j
                ax = layer_axes(hp, gi)
                cur = S.act_spec(ax)
                h = S.monotone_constrain(h, mesh, prev, cur)
                lp = stage_layers[j]
                if is_enc:
                    fwd = lambda p, x: T.enc_layer_forward(p, x, cfg, bias, mesh=mesh, axes=ax)
                else:
                    # mem stays in the boundary layout (it is never rewritten
                    # by a layer), so each transition starts from mb_spec
                    mem_c = S.monotone_constrain(mem, mesh, mb_spec, cur)
                    fwd = lambda p, x: T.dec_layer_forward(
                        p, x, mem_c, cfg, bias, cross_bias=cross_bias, mesh=mesh, axes=ax
                    )
                if hp.layers[gi].checkpoint:
                    fwd = jax.checkpoint(fwd)
                h = fwd(lp, h)
                prev = cur
            h = S.monotone_constrain(h, mesh, prev, mb_spec)
            if is_enc:
                mem_out = h
                if s == pe - 1:
                    mem_out = T._rms(h, stage_layers[0]["seed_norm"], cfg)
            else:
                mem_out = mem
            return jnp.stack([h, mem_out])

        return body

    # ------------------------------------------------------- vocab fwd pieces
    def embed_fwd(vparams, tokens):
        """One-hot wte lookup (see pipeline_1f1b.embed_fwd for why matmul,
        not gather)."""
        dtype = cfg.compute_dtype
        onehot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=dtype)
        x = jnp.einsum("bsv,vh->bsh", onehot, vparams["embed"]["wte"].astype(dtype))
        return S.constrain(x, mesh, mb_spec)

    def head_loss(vparams, y, labels, loss_mask, weight):
        from galvatron_tpu.models.base import vocab_parallel_cross_entropy

        dtype = cfg.compute_dtype
        y = T._rms(S.constrain(y, mesh, mb_spec), vparams["dec_norm"], cfg)
        if cfg.tie_embeddings:
            y = y * (cfg.hidden_size ** -0.5)
            logits = y @ vparams["embed"]["wte"].astype(dtype).T
        else:
            logits = y @ vparams["lm_head"]["kernel"].astype(dtype)
        logits = S.constrain(logits, mesh, S.logits_spec(vax))
        return vocab_parallel_cross_entropy(logits, labels, loss_mask) * weight

    def loss_and_grad(params, batch):
        vparams_stored = {k: v for k, v in params.items() if k != "stages"}
        stages = params["stages"]

        B = batch["tokens"].shape[0]
        mb = B // chunks
        Sq = batch["tokens"].shape[1]
        assert batch["dec_tokens"].shape[1] == Sq, (
            "enc/dec streams must be padded to a common sequence length"
        )

        def split(x):
            return x.reshape((chunks, mb) + x.shape[1:])

        enc_mb = split(batch["tokens"])
        dec_mb = split(batch["dec_tokens"])
        labels_mb = split(batch["labels"])
        has_mask = batch.get("loss_mask") is not None
        mask_mb = split(batch["loss_mask"]) if has_mask else jnp.zeros((chunks, 1), jnp.float32)
        has_bias = batch.get("attn_mask") is not None
        # padded encoder keys mask encoder self-attn and decoder cross-attn
        key_bias_mb = (
            split((1.0 - batch["attn_mask"].astype(jnp.float32))[:, None, None, :] * -1e9)
            if has_bias else jnp.zeros((chunks, 1), jnp.float32)
        )

        def rep(t):
            return S.constrain(t, mesh, S.replicated_spec(t.ndim))

        enc_mb, dec_mb, labels_mb, mask_mb, key_bias_mb = (
            rep(t) for t in (enc_mb, dec_mb, labels_mb, mask_mb, key_bias_mb)
        )

        if has_mask:
            msums = jnp.sum(mask_mb.astype(jnp.float32), axis=tuple(range(1, mask_mb.ndim)))
            weights = msums / jnp.maximum(jnp.sum(msums), 1.0)
        else:
            weights = jnp.full((chunks,), 1.0 / chunks, jnp.float32)

        H = cfg.hidden_size
        act_dtype = cfg.compute_dtype
        bodies_by_stage = [stage_body(s, Sq) for s in range(pp)]

        xs = {
            "fwd_mb": jnp.asarray(sched.fwd_mb),
            "fwd_v": jnp.asarray(sched.fwd_valid),
            "arr_mb": jnp.asarray(sched.arr_mb),
            "arr_v": jnp.asarray(sched.arr_valid),
            "bwd_mb": jnp.asarray(sched.bwd_mb),
            "bwd_v": jnp.asarray(sched.bwd_valid),
            "head_mb": jnp.asarray(sched.head_mb),
            "head_v": jnp.asarray(sched.head_valid),
            "emb_mb": jnp.asarray(sched.emb_mb),
            "emb_v": jnp.asarray(sched.emb_valid),
            # decoder-side tables: stage pe's arrival (dec embedding swap-in)
            # and stage pe's backward, lagged one tick for its embedding bwd
            "arr_pe_mb": jnp.asarray(sched.arr_mb[:, pe] if pe < pp else sched.arr_mb[:, 0]),
            "arr_pe_v": jnp.asarray(
                sched.arr_valid[:, pe] if pe < pp else sched.arr_valid[:, 0]
            ),
            "emb2_mb": jnp.asarray(
                np.concatenate([[0], sched.bwd_mb[:-1, pe]]) if pe < pp else sched.emb_mb
            ),
            "emb2_v": jnp.asarray(
                np.concatenate([[False], sched.bwd_valid[:-1, pe]])
                if pe < pp else np.zeros_like(sched.emb_valid)
            ),
            "inject_mb": jnp.asarray(sched.inject_mb),
        }

        # (see pipeline_1f1b.make_loss_and_grad for the full divergence-safety
        # rationale behind this structure: one shard_map manual over pp, one
        # cross-stage all-gather per tick, mask-not-branch on CPU)
        def schedule_body(stages_in, vparams, enc_mb, dec_mb, labels_mb,
                          mask_mb, key_bias_mb, weights, xs):
            stage = lax.axis_index(PP_AXIS)
            local = [jax.tree.map(lambda a: a[0], t) for t in stages_in]

            def gather_mb(table, idx):
                return lax.dynamic_index_in_dim(
                    table, jnp.clip(idx, 0, chunks - 1), 0, keepdims=False
                )

            def tick(carry, xt):
                y_prev, dx_prev, dy, stash, loss, sgrads, vgrads = carry

                # [uniform] both embeddings for this tick's injections, gated
                # on their (stage-uniform) validity scalars so the O(V)
                # matmuls skip dead ticks; both cond branches pin mb_spec
                # (invariant (b), pipeline_1f1b.py)
                def _embed_or_zero(valid, tokens):
                    return lax.cond(
                        valid,
                        lambda: S.constrain(
                            embed_fwd(vparams, tokens).astype(act_dtype), mesh, mb_spec
                        ),
                        lambda: S.constrain(
                            jnp.zeros((mb, Sq, H), act_dtype), mesh, mb_spec
                        ),
                    )

                x_inj_enc = _embed_or_zero(xt["fwd_v"][0], gather_mb(enc_mb, xt["inject_mb"]))
                x_inj_dec = _embed_or_zero(xt["arr_pe_v"], gather_mb(dec_mb, xt["arr_pe_mb"]))

                # THE cross-stage collective (channel pairs double the width)
                prev_all = lax.all_gather(jnp.stack([y_prev, dx_prev]), PP_AXIS)
                x_arr = lax.dynamic_index_in_dim(
                    prev_all, jnp.clip(stage - 1, 0, pp - 1), 0, keepdims=False
                )[0]
                zero_ch = jnp.zeros((mb, Sq, H), act_dtype)
                x_arr = jnp.where(stage == 0, jnp.stack([x_inj_enc, zero_ch]), x_arr)
                # first decoder stage: decoder embedding replaces h; the
                # arriving mem (seeded encoder output) is kept
                x_arr = jnp.where(
                    stage == pe, jnp.stack([x_inj_dec, x_arr[1]]), x_arr
                )
                g_arr = lax.dynamic_index_in_dim(
                    prev_all, jnp.clip(stage + 1, 0, pp - 1), 0, keepdims=False
                )[1]
                # the h arriving at stage pe was dropped (replaced by the
                # decoder embedding), so no h-cotangent flows to stage pe-1
                g_arr = jnp.where(
                    stage == pe - 1, jnp.stack([jnp.zeros_like(g_arr[0]), g_arr[1]]), g_arr
                )
                y_exit = prev_all[pp - 1, 0, 0]
                dx0 = prev_all[0, 1, 0]
                dx_pe = prev_all[pe if pe < pp else 0, 1, 0]

                aslot = xt["arr_mb"][stage] % sched.stash
                old = lax.dynamic_index_in_dim(stash, aslot, 0, keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(xt["arr_v"][stage], x_arr, old), aslot, 0
                )

                fmb = xt["fwd_mb"][stage]
                x_f = lax.dynamic_index_in_dim(stash, fmb % sched.stash, 0, keepdims=False)
                self_b_f = gather_mb(key_bias_mb, fmb) if has_bias else 0.0
                cross_b_f = self_b_f if has_bias else None

                def run_fwd(x):
                    if uniform_stages:
                        return bodies_by_stage[0](local, x, self_b_f, cross_b_f)
                    return lax.switch(
                        stage, bodies_by_stage, local, x, self_b_f, cross_b_f
                    )

                if mask_not_branch:
                    y = run_fwd(x_f) * xt["fwd_v"][stage].astype(act_dtype)
                else:
                    y = lax.cond(xt["fwd_v"][stage], run_fwd, jnp.zeros_like, x_f)

                g_in = jnp.where(stage == pp - 1, dy, g_arr)

                bmb = xt["bwd_mb"][stage]
                x_b = lax.dynamic_index_in_dim(stash, bmb % sched.stash, 0, keepdims=False)
                self_b_b = gather_mb(key_bias_mb, bmb) if has_bias else 0.0
                cross_b_b = self_b_b if has_bias else None

                def run_bwd(g):
                    def fb(ps, xx):
                        if uniform_stages:
                            return bodies_by_stage[0](ps, xx, self_b_b, cross_b_b)
                        return lax.switch(
                            stage, bodies_by_stage, ps, xx, self_b_b, cross_b_b
                        )

                    _, vjp = jax.vjp(fb, local, x_b)
                    dps_, dx_ = vjp(g)
                    # pin the branch exit INSIDE the branch (divergence-safety
                    # invariant (b), pipeline_1f1b.py)
                    dps_ = [
                        jax.tree.map(
                            lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), t
                        )
                        for t in dps_
                    ]
                    return dps_, S.constrain(dx_, mesh, pair_spec)

                def zero_bwd(g):
                    return jax.tree.map(jnp.zeros_like, local), jnp.zeros_like(x_b)

                if mask_not_branch:
                    dps, dx = run_bwd(g_in * xt["bwd_v"][stage].astype(act_dtype))
                else:
                    dps, dx = lax.cond(xt["bwd_v"][stage], run_bwd, zero_bwd, g_in)
                sgrads = jax.tree.map(jnp.add, sgrads, dps)

                # [uniform] head + loss on the exiting decoder hidden, gated
                # on head_v (stage-uniform; see pipeline_1f1b.py)
                e = xt["head_mb"]
                labels_e = gather_mb(labels_mb, e)
                mask_e = gather_mb(mask_mb, e) if has_mask else None
                w_e = weights[jnp.clip(e, 0, chunks - 1)]

                def _pin_tree(t):
                    return jax.tree.map(
                        lambda a: S.constrain(a, mesh, S.replicated_spec(a.ndim)), t
                    )

                def run_head():
                    l_e, head_vjp = jax.vjp(
                        lambda vp, yy: head_loss(vp, yy, labels_e, mask_e, w_e),
                        vparams, y_exit,
                    )
                    dvp, dy_h = head_vjp(jnp.ones((), jnp.float32))
                    return l_e, _pin_tree(dvp), S.constrain(dy_h, mesh, mb_spec)

                l_e, dvp_head, dy_h = lax.cond(
                    xt["head_v"],
                    run_head,
                    lambda: (
                        jnp.zeros((), jnp.float32),
                        _pin_tree(jax.tree.map(jnp.zeros_like, vparams)),
                        S.constrain(jnp.zeros_like(y_exit), mesh, mb_spec),
                    ),
                )
                loss = loss + l_e
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_head)
                dy_new = jnp.stack([dy_h, dy_h * 0.0]).astype(act_dtype)

                # [uniform] encoder / decoder embedding backwards (stage 0's
                # and stage pe's bwd, lagged), each gated on its validity
                def _embed_bwd(valid, tokens, cot):
                    def run():
                        _, evjp = jax.vjp(
                            lambda vp: embed_fwd(vp, tokens).astype(act_dtype), vparams
                        )
                        (d,) = evjp(cot)
                        return _pin_tree(d)

                    return lax.cond(
                        valid, run,
                        lambda: _pin_tree(jax.tree.map(jnp.zeros_like, vparams)),
                    )

                dvp_e = _embed_bwd(xt["emb_v"], gather_mb(enc_mb, xt["emb_mb"]), dx0)
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_e)
                dvp_d = _embed_bwd(xt["emb2_v"], gather_mb(dec_mb, xt["emb2_mb"]), dx_pe)
                vgrads = jax.tree.map(jnp.add, vgrads, dvp_d)

                return (
                    y, dx, dy_new, stash, loss, sgrads, vgrads,
                ), None

            deps = jax.tree.leaves(vparams) + jax.tree.leaves(
                (enc_mb, dec_mb, labels_mb, mask_mb, key_bias_mb, weights)
            )
            y0 = lax.optimization_barrier(
                tuple([jnp.zeros((2, mb, Sq, H), act_dtype)] + deps)
            )[0]
            carry0 = (
                y0,
                jnp.zeros((2, mb, Sq, H), act_dtype),
                jnp.zeros((2, mb, Sq, H), act_dtype),
                jnp.zeros((sched.stash, 2, mb, Sq, H), act_dtype),
                jnp.zeros((), jnp.float32),
                [jax.tree.map(jnp.zeros_like, t) for t in local],
                jax.tree.map(jnp.zeros_like, vparams),
            )
            final, _ = lax.scan(tick, carry0, xs)
            loss, sgrads, vgrads = final[4], final[5], final[6]
            return (
                loss,
                [jax.tree.map(lambda a: a[None], t) for t in sgrads],
                vgrads,
            )

        pp_specs = [jax.tree.map(lambda _: P(PP_AXIS), t) for t in stages]

        def rep_tree(t):
            return jax.tree.map(lambda _: P(), t)

        smap = jax.shard_map(
            schedule_body,
            mesh=mesh,
            in_specs=(
                pp_specs, rep_tree(vparams_stored),
                P(), P(), P(), P(), P(), P(), rep_tree(xs),
            ),
            out_specs=(P(), pp_specs, rep_tree(vparams_stored)),
            axis_names={PP_AXIS},
            check_vma=False,
        )
        from galvatron_tpu.models.t5 import t5_vocab_pipeline_specs

        vspecs_local = t5_vocab_pipeline_specs(cfg, hp, storage=False)
        vparams_local = jax.tree.map(
            lambda sp, t: S.constrain(t, mesh, sp),
            {k: vspecs_local[k] for k in vparams_stored}, vparams_stored,
            is_leaf=lambda x: isinstance(x, P),
        )
        loss, sgrads, vgrads = smap(
            stages, vparams_local, enc_mb, dec_mb, labels_mb,
            mask_mb, key_bias_mb, weights, xs,
        )

        # restore the rel-bias tie: same-type stages hold copies of one
        # table, so their gradient is the SUM over that range, broadcast back
        # (identical grads + identical init keep the copies in lockstep under
        # any elementwise optimizer)
        rel_g = sgrads[0]["rel_bias"]  # (pp, buckets, nh)
        enc_sum = jnp.sum(rel_g[:pe], axis=0, keepdims=True)
        dec_sum = jnp.sum(rel_g[pe:], axis=0, keepdims=True)
        sgrads[0]["rel_bias"] = jnp.concatenate(
            [jnp.broadcast_to(enc_sum, (pe,) + rel_g.shape[1:]),
             jnp.broadcast_to(dec_sum, (pp - pe,) + rel_g.shape[1:])], axis=0
        )

        grads = dict(vgrads)
        grads["stages"] = sgrads
        return loss, grads

    return loss_and_grad
