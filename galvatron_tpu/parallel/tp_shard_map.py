"""shard_map-native tensor-parallel layer execution with decomposed,
ppermute-overlapped collectives.

The GSPMD path (models/base.layer_forward) leaves every TP collective to the
compiler: the all-gathers/reduce-scatters implied by the column/row kernel
shardings serialize with the matmuls they feed. T3 (arXiv:2401.16677) shows
that fine-grained overlap of producer compute with those collectives is the
next step-time lever; on TPU the native idiom is DECOMPOSED collectives —
the ppermute-pipelined chunking ops/ring_attention.py already uses for
attention, generalized here to the dense TP layers:

- **column-parallel** (qkv / mlp-in kernels, ``P(..., tp)``): the megatron-sp
  seq-sharded activation is ring-all-gathered while each arriving block is
  immediately consumed by its chunk of the matmul (`_col_matmul`);
- **row-parallel** (attn-out / mlp-out kernels, ``P(tp, ...)``): the partial
  products are computed chunk-by-chunk and reduce-scattered through a
  rotating ring accumulator (`_row_matmul`), so each chunk's matmul overlaps
  the previous chunk's ppermute.

`manual_layer_forward` composes them into a full transformer block under ONE
`jax.shard_map` over the layer's dp+tp mesh axes, selected by the runtime
knob ``tp_comm_mode``:

- ``gspmd``     — the existing compiler-derived path (default);
- ``shard_map`` — manual collectives, undecomposed (`lax.all_gather` /
  `lax.psum_scatter`): the collectives become visible and schedulable (the
  prerequisite for quantized collectives, ROADMAP item 2) but still
  serialize with the matmuls;
- ``overlap``   — the decomposed ppermute rings above, with a custom_vjp so
  the backward overlaps symmetrically (dx reduce-scatter ring + dw
  accumulation share one rotation, mirroring the forward).

Numerics contract: both manual modes compute the same mathematical layer as
GSPMD (parity-tested to tolerance — reduction orders differ); configs the
manual path cannot express are REFUSED with a GLS012 diagnostic, never
silently approximated. It also sidesteps the jax 0.4.37 GSPMD
sharded-reshape miscompile class entirely: inside the manual region every
reshape is a plain local op.

Autodiff note (jax 0.4.37): the legacy shard_map the compat shim lowers to
PSUMS cotangents over unmentioned manual axes at the region boundary on its
own (verified empirically: an extra in-body psum over-counts grads by
exactly the axis-group size), so parameter leaves entering with their dp
axes dropped from the in_spec (replicated and ZeRO-3-gathered operands) get
correct batch-summed gradients with no manual psum — the parity suite
(tests/models/test_tp_comm_mode.py) pins loss AND grads against GSPMD for
every supported tp/zero3/scan combination to keep that contract honest
across jax upgrades.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from galvatron_tpu.config.strategy import (
    HybridParallelConfig,
    LayerStrategy,
    layer_runs,
)
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import LayerAxes, layer_axes, mesh_axis_size

Params = Dict[str, Any]


# ------------------------------------------------------------------ support
def manual_tp_reason(cfg, hp: HybridParallelConfig,
                     strategy: LayerStrategy) -> Optional[str]:
    """Why the manual shard_map path cannot run one layer's strategy, or None
    when it can. Pure host-side check (the strategy linter calls it with no
    tracing); layers with tp=1 have no TP collectives to make visible and are
    reported as supported — run_layers executes them through the (identical)
    GSPMD path and the linter warns the knob is inert."""
    tp = strategy.tp
    if tp <= 1:
        return None
    if strategy.sp:
        return "ulysses sequence parallelism (use_sp=1) is not expressible " \
               "in the manual TP path"
    if strategy.cp > 1:
        return "context parallelism (cp=%d) composes through " \
               "ops/ring_attention.py, not the manual TP path" % strategy.cp
    if not hp.sequence_parallel:
        return "the manual TP path requires megatron-sp activation sharding " \
               "(--sequence-parallel); --no-sequence-parallel layers keep GSPMD"
    if cfg is None:
        # linter without a model config: structural checks only
        return None
    num_heads = getattr(cfg, "num_heads", None)
    if num_heads is None:
        return "model family without a flat num_heads (t5/swin custom " \
               "trees) is not wired through the manual TP path"
    if num_heads % tp != 0:
        return "num_heads=%d not divisible by tp=%d (GSPMD pads; the " \
               "manual path refuses)" % (num_heads, tp)
    num_kv = getattr(cfg, "num_kv_heads", None) or num_heads
    if num_kv % tp != 0:
        return "num_kv_heads=%d not divisible by tp=%d" % (num_kv, tp)
    ffn = getattr(cfg, "ffn_hidden", None)
    if ffn is not None and ffn % tp != 0:
        return "ffn_hidden=%d not divisible by tp=%d" % (ffn, tp)
    seq = getattr(cfg, "max_seq_len", None)
    if seq is not None and seq % tp != 0:
        return "max_seq_len=%d not divisible by tp=%d (megatron-sp shards " \
               "the sequence over the tp axes)" % (seq, tp)
    return None


def assert_manual_tp_supported(cfg, hp: HybridParallelConfig,
                               strategy: LayerStrategy):
    """Trace-time refusal (GLS012 DiagnosticError) — the loud half of the
    never-silently-differ contract; the strategy linter reports the same
    reason pre-trace through lint_hp."""
    reason = manual_tp_reason(cfg, hp, strategy)
    if reason is not None:
        from galvatron_tpu.analysis import diagnostics as D

        raise D.DiagnosticError([D.make(
            "GLS012", "tp_comm_mode=%r: %s" % (hp.tp_comm_mode, reason),
            key="tp_comm_mode",
        )])


def wants_manual_tp(hp: Optional[HybridParallelConfig],
                    axes: Optional[LayerAxes]) -> bool:
    """Whether run_layers should route this layer through the manual path:
    the knob asks for it AND the layer actually has tp collectives (tp=1
    layers execute the identical GSPMD program — the knob is inert, which
    the linter warns about, rather than wrong)."""
    if hp is None or axes is None:
        return False
    mode = getattr(hp, "tp_comm_mode", "gspmd")
    return mode in ("shard_map", "overlap") and len(axes.tp) > 0


# ------------------------------------------------------------- ring helpers
def _ring_perm(n: int) -> List[Tuple[int, int]]:
    return [(j, (j + 1) % n) for j in range(n)]


def _flat_axis_index(axis_names: Tuple[str, ...], sizes: Tuple[int, ...]):
    """Flattened (row-major, major->minor — the order ppermute/all_gather
    flatten a tuple of axis names) index of this device along `axis_names`.
    jax 0.4.x `lax.axis_index` takes one name at a time."""
    idx = jnp.int32(0)
    for name, size in zip(axis_names, sizes):
        idx = idx * size + jax.lax.axis_index(name)
    return idx


# ------------------------------------------------------- quantized payloads
def _q_encode(x, quant):
    """Wire-encode a ring payload: (payload, scales) under a quantized
    tp_comm_quant, a bf16 cast for 'bf16', the array itself for None/'none'.
    Encoded ONCE before a rotation — the payload stays encoded through every
    hop and each consumer dequantizes only the block it multiplies
    (EQuARX-style: the wire carries int8, the MXU sees fp)."""
    from galvatron_tpu.parallel import quant_collectives as QC

    if quant is None or quant[0] == "none":
        return x
    dtype, block = quant
    if dtype == "bf16":
        return x.astype(jnp.bfloat16)
    return QC.quantize_blockwise(x, dtype, block) + (x.shape, x.dtype)


def _q_decode(enc, quant):
    from galvatron_tpu.parallel import quant_collectives as QC

    if quant is None or quant[0] == "none":
        return enc
    if quant[0] == "bf16":
        return enc  # bf16 feeds the matmul directly
    payload, scales, shape, dt = enc
    return QC.dequantize_blockwise(payload, scales, shape, dt)


def _q_permute(enc, quant, tp_axes, perm):
    if quant is None or quant[0] in ("none", "bf16"):
        return jax.lax.ppermute(enc, tp_axes, perm)
    payload, scales, shape, dt = enc
    return (jax.lax.ppermute(payload, tp_axes, perm),
            jax.lax.ppermute(scales, tp_axes, perm), shape, dt)


# --------------------------------------------------- column-parallel matmul
def _col_matmul_chunks(x, w, *, tp_axes, n, sizes, quant=None):
    """Decomposed all-gather + matmul: x (B, s, H) is this device's
    megatron-sp seq shard; w (H, ...) its column shard. Each ring step
    matmuls the block currently held and places it at the block's global
    seq offset, then rotates — the python-unrolled loop lets XLA overlap
    each step's ppermute with the previous block's matmul, exactly as the
    ring-attention forward does. Under ``quant`` the rotating activation is
    wire-encoded once (int8/fp8 blockwise or bf16) and every hop moves the
    encoded payload; each step dequantizes only the block it consumes.
    Returns (B, n*s, ...)."""
    b, s = x.shape[0], x.shape[1]
    tail = w.shape[1:]
    idx = _flat_axis_index(tp_axes, sizes)
    out = jnp.zeros((b, n * s) + tail, x.dtype)
    perm = _ring_perm(n)
    x_cur = _q_encode(x, quant)
    for step in range(n):
        src = jnp.mod(idx - step, n)  # whose block x_cur originally was
        blk = jnp.einsum("bsh,h...->bs...", _q_decode(x_cur, quant), w)
        out = jax.lax.dynamic_update_slice(
            out, blk.astype(x.dtype),
            (jnp.int32(0), src * s) + (jnp.int32(0),) * len(tail))
        if step < n - 1:
            x_cur = _q_permute(x_cur, quant, tp_axes, perm)
    return out


def _col_matmul_dense(x, w, *, tp_axes, n, sizes, quant=None):
    """Undecomposed manual form (mode='shard_map'): one all-gather, one
    matmul — visible collectives, no overlap. Under ``quant`` the activation
    is wire-encoded before the gather (the all-gather moves payload+scales)
    and dequantized once on arrival."""
    if quant is not None and quant[0] not in ("none",):
        from galvatron_tpu.parallel import quant_collectives as QC

        dtype, block = quant
        if dtype == "bf16":
            x_full = jax.lax.all_gather(
                x.astype(jnp.bfloat16), tp_axes, axis=1, tiled=True)
            return jnp.einsum("bsh,h...->bs...", x_full, w)
        payload, scales = QC.quantize_blockwise(x, dtype, block)
        pg = jax.lax.all_gather(payload, tp_axes)   # (n, nblk, block)
        sg = jax.lax.all_gather(scales, tp_axes)    # (n, nblk)
        parts = QC.dequantize_blockwise(
            pg.reshape(-1, pg.shape[-1]), sg.reshape(-1),
            (n,) + x.shape, x.dtype)
        x_full = jnp.moveaxis(parts, 0, 1).reshape(
            x.shape[0], n * x.shape[1], x.shape[2])
        return jnp.einsum("bsh,h...->bs...", x_full, w)
    del n, sizes
    x_full = jax.lax.all_gather(x, tp_axes, axis=1, tiled=True)
    return jnp.einsum("bsh,h...->bs...", x_full, w)


def _col_bwd_chunks(x, w, g, *, tp_axes, n, sizes):
    """Hand-scheduled column backward: ONE rotation serves both grads —
    x rotates as in the forward so each step contributes its chunk of
    dw = gathered(x)^T @ g, while the dx reduce-scatter accumulator rides
    the same ring home (dest arithmetic as in `_row_matmul_chunks`)."""
    s = x.shape[1]
    idx = _flat_axis_index(tp_axes, sizes)
    perm = _ring_perm(n)
    dw = jnp.zeros_like(w)
    dx = None
    x_cur = x
    for step in range(n):
        src = jnp.mod(idx - step, n)
        g_src = jax.lax.dynamic_slice_in_dim(g, src * s, s, 1)
        dw = dw + jnp.einsum("bsh,bs...->h...", x_cur, g_src)
        dest = jnp.mod(idx - 1 - step, n)
        g_dest = jax.lax.dynamic_slice_in_dim(g, dest * s, s, 1)
        part = jnp.einsum("bs...,h...->bsh", g_dest, w)
        dx = part if dx is None else jax.lax.ppermute(dx, tp_axes, perm) + part
        if step < n - 1:
            x_cur = jax.lax.ppermute(x_cur, tp_axes, perm)
    return dx, dw


# ------------------------------------------------------ row-parallel matmul
def _row_matmul_chunks(x, w, *, tp_axes, n, sizes, quant=None):
    """Decomposed matmul + reduce-scatter: x (B, S, f) full-seq with f the
    row shard, w (f, H). A ring accumulator destined for device d starts at
    d+1 and hops +1 each step picking up that device's partial for block d;
    after n-1 hops it lands home fully reduced. Each step's chunk matmul
    overlaps the accumulator's ppermute. Under ``quant`` each accumulator
    hop is wire-encoded (re-quantized per hop — the partial sums change) and
    the running sum stays in the compute dtype, the ZeRO++ reduce-scatter
    discipline. Returns the megatron-sp shard (B, S/n, H)."""
    from galvatron_tpu.parallel.quant_collectives import _wire_hop

    s = x.shape[1] // n
    idx = _flat_axis_index(tp_axes, sizes)
    perm = _ring_perm(n)
    acc = None
    for step in range(n):
        dest = jnp.mod(idx - 1 - step, n)
        x_blk = jax.lax.dynamic_slice_in_dim(x, dest * s, s, 1)
        part = jnp.einsum("bsf,fh->bsh", x_blk, w)
        if acc is None:
            acc = part
        elif quant is None or quant[0] == "none":
            acc = jax.lax.ppermute(acc, tp_axes, perm) + part
        else:
            acc = _wire_hop(acc, tp_axes, perm, quant[0], quant[1]).astype(
                part.dtype) + part
    return acc


def _row_matmul_dense(x, w, *, tp_axes, n, sizes, quant=None):
    # psum_scatter reduces inside the collective — there is no payload seam
    # to quantize, so the 'shard_map' mode's row matmul stays full-precision
    # (the linter documents this asymmetry; 'overlap' quantizes both rings)
    del n, sizes, quant
    part = jnp.einsum("bsf,fh->bsh", x, w)
    return jax.lax.psum_scatter(part, tp_axes, scatter_dimension=1, tiled=True)


def _row_bwd_chunks(x, w, g, *, tp_axes, n, sizes):
    """Row backward = the column forward's mirror: the seq-sharded cotangent
    g (B, s, H) ring-all-gathers while each arriving block immediately
    feeds its chunk of dx = g_full @ w^T (placed at the block's seq offset)
    and of dw = x^T @ g_full."""
    b, s = g.shape[0], g.shape[1]
    f = x.shape[2]
    idx = _flat_axis_index(tp_axes, sizes)
    perm = _ring_perm(n)
    dx = jnp.zeros((b, n * s, f), x.dtype)
    dw = jnp.zeros_like(w)
    g_cur = g
    for step in range(n):
        src = jnp.mod(idx - step, n)
        part = jnp.einsum("bsh,fh->bsf", g_cur, w)
        dx = jax.lax.dynamic_update_slice(
            dx, part, (jnp.int32(0), src * s, jnp.int32(0)))
        x_src = jax.lax.dynamic_slice_in_dim(x, src * s, s, 1)
        dw = dw + jnp.einsum("bsf,bsh->fh", x_src, g_cur)
        if step < n - 1:
            g_cur = jax.lax.ppermute(g_cur, tp_axes, perm)
    return dx, dw


def make_col_matmul(tp_axes: Tuple[str, ...], n: int, sizes: Tuple[int, ...], *,
                    mode: str, use_custom_vjp: bool = True, quant=None):
    """(x_shard (B,s,H), w_shard (H,...)) -> (B,S,...). With `use_custom_vjp`
    the overlap mode attaches the hand-scheduled ring backward; the autodiff
    fallback (the tests' parity oracle, as in ring_attention) differentiates
    the unrolled forward. ``quant`` = (wire dtype, block) quantizes the
    FORWARD ring payload (tp_comm_quant); the hand-scheduled backward keeps
    full-precision cotangent rings — the straight-through convention, so
    gradients are taken as if the forward wire were exact."""
    kw = dict(tp_axes=tuple(tp_axes), n=n, sizes=tuple(sizes), quant=quant)
    bkw = dict(tp_axes=tuple(tp_axes), n=n, sizes=tuple(sizes))
    fwd_impl = _col_matmul_dense if mode == "shard_map" else _col_matmul_chunks
    if mode == "shard_map" or not use_custom_vjp:
        return partial(fwd_impl, **kw)

    @jax.custom_vjp
    def col(x, w):
        return _col_matmul_chunks(x, w, **kw)

    col.defvjp(lambda x, w: (_col_matmul_chunks(x, w, **kw), (x, w)),
               lambda res, g: _col_bwd_chunks(*res, g, **bkw))
    return col


def make_row_matmul(tp_axes: Tuple[str, ...], n: int, sizes: Tuple[int, ...], *,
                    mode: str, use_custom_vjp: bool = True, quant=None):
    """(x (B,S,f), w (f,H)) -> (B,s,H); see make_col_matmul."""
    kw = dict(tp_axes=tuple(tp_axes), n=n, sizes=tuple(sizes), quant=quant)
    bkw = dict(tp_axes=tuple(tp_axes), n=n, sizes=tuple(sizes))
    fwd_impl = _row_matmul_dense if mode == "shard_map" else _row_matmul_chunks
    if mode == "shard_map" or not use_custom_vjp:
        return partial(fwd_impl, **kw)

    @jax.custom_vjp
    def row(x, w):
        return _row_matmul_chunks(x, w, **kw)

    row.defvjp(lambda x, w: (_row_matmul_chunks(x, w, **kw), (x, w)),
               lambda res, g: _row_bwd_chunks(*res, g, **bkw))
    return row


# -------------------------------------------------------------- layer body
def manual_param_specs(cfg, axes: LayerAxes) -> Params:
    """The manual region's in_specs for one layer's params: the GSPMD specs
    (models/base.layer_param_specs) with every non-tp mesh axis dropped —
    zero3 dims enter gathered (shard_map inserts the boundary all-gather,
    exactly the ZeRO-3 gather GSPMD would emit) and the transpose
    reduce-scatters the cotangent back outside."""
    from galvatron_tpu.models.base import layer_param_specs

    tp_set = set(axes.tp)

    def keep_tp(sp: P) -> P:
        entries = []
        for e in sp:
            kept = tuple(a for a in S._entry_axes(e) if a in tp_set)
            entries.append(S._ax(kept))
        return P(*entries)

    return jax.tree.map(keep_tp, layer_param_specs(cfg, axes),
                        is_leaf=lambda t: isinstance(t, P))


def manual_layer_forward(
    p: Params,
    x: jax.Array,
    positions: jax.Array,
    cfg,
    *,
    mesh: Mesh,
    axes: LayerAxes,
    hp: Optional[HybridParallelConfig] = None,
    attn_bias: Optional[jax.Array] = None,
    mode: str = "overlap",
    use_custom_vjp: bool = True,
) -> jax.Array:
    """One transformer block with manual TP collectives, drop-in signature-
    compatible with models/base.layer_forward for run_layers' scan and
    unrolled bodies. `x` is the (B, S, H) global activation carrying the
    inter-layer act_spec sharding (batch over dp, seq over tp — megatron-sp);
    the whole block runs under one shard_map over dp+tp with qkv/mlp-in as
    overlapped column matmuls, attention local on the head shard, and
    attn-out/mlp-out as overlapped row matmuls."""
    if mode not in ("shard_map", "overlap"):
        raise ValueError("manual_layer_forward mode must be 'shard_map' or "
                         "'overlap', got %r" % mode)
    # tp_comm_quant: wire-encode the ring payloads (ROADMAP item 2 /
    # EQuARX); fp8 without runtime support refuses loudly (GLS013), the
    # never-silently-differ contract
    quant = None
    tp_quant = getattr(hp, "tp_comm_quant", "none") if hp is not None else "none"
    if tp_quant != "none":
        from galvatron_tpu.parallel import quant_collectives as QC

        if tp_quant == "fp8_e4m3" and not QC.fp8_supported():
            from galvatron_tpu.analysis import diagnostics as D

            raise D.DiagnosticError([D.make(
                "GLS013", "tp_comm_quant='fp8_e4m3' needs "
                "jax.numpy.float8_e4m3fn, which this jax does not provide",
                key="tp_comm_quant",
            )])
        quant = (tp_quant, int(getattr(hp, "comm_quant_block", 64)))
    tp_axes = tuple(axes.tp)
    n = mesh_axis_size(mesh, tp_axes)
    sizes = tuple(mesh.shape[a] for a in tp_axes)
    bd = S._ax(axes.batch_axes)
    x_spec = P(bd, S._ax(axes.seq_axes), None)
    p_specs = manual_param_specs(cfg, axes)
    has_bias = attn_bias is not None
    dtype = cfg.compute_dtype

    def body(lp, xs, pos, bias):
        col = make_col_matmul(tp_axes, n, sizes, mode=mode,
                              use_custom_vjp=use_custom_vjp, quant=quant)
        row = make_row_matmul(tp_axes, n, sizes, mode=mode,
                              use_custom_vjp=use_custom_vjp, quant=quant)

        from galvatron_tpu.models.base import _activation, _norm
        from galvatron_tpu.ops.attention import core_attention
        from galvatron_tpu.ops.rope import apply_rotary

        def col_proj(pk, y):
            out = col(y, pk["kernel"].astype(dtype))
            if "bias" in pk:
                out = out + pk["bias"].astype(dtype)
            return out

        residual = xs
        y = _norm(xs, lp["ln1"], cfg) if cfg.pre_norm else xs
        if cfg.fused_qkv:
            qkv = col_proj(lp["wqkv"], y)  # (B, S, 3, nh_loc, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            q = col_proj(lp["wq"], y)
            kv = col_proj(lp["wkv"], y)  # (B, S, 2, nkv_loc, hd)
            k, v = kv[:, :, 0], kv[:, :, 1]
        if cfg.position_type == "rope":
            q = apply_rotary(q, pos, cfg.rope_theta)
            k = apply_rotary(k, pos, cfg.rope_theta)
        # attention is LOCAL on the head shard: q/k/v are full-sequence
        attn = core_attention(q, k, v, causal=cfg.causal, bias=bias,
                              impl=cfg.attn_impl, bias_type="key_padding")
        attn = attn.reshape(attn.shape[0], attn.shape[1], -1)
        o = row(attn, lp["wo"]["kernel"].astype(dtype))
        if "bias" in lp["wo"]:
            o = o + lp["wo"]["bias"].astype(dtype)
        xs = residual + o
        if not cfg.pre_norm:
            xs = _norm(xs, lp["ln1"], cfg)

        residual = xs
        y = _norm(xs, lp["ln2"], cfg) if cfg.pre_norm else xs
        wi_out = col_proj(lp["wi"], y)
        if cfg.activation == "swiglu":
            hmid = jax.nn.silu(wi_out[:, :, 0]) * wi_out[:, :, 1]
        else:
            hmid = _activation(wi_out, cfg)
        out = row(hmid, lp["wo_mlp"]["kernel"].astype(dtype))
        if "bias" in lp["wo_mlp"]:
            out = out + lp["wo_mlp"]["bias"].astype(dtype)
        xs = residual + out
        if not cfg.pre_norm:
            xs = _norm(xs, lp["ln2"], cfg)
        return xs

    in_specs = (p_specs, x_spec, P(bd, None), P(bd, None, None, None))
    if not has_bias:
        # consistent arity (as in ring_attention): a zero operand the body
        # feeds to core_attention as bias=None would change the program, so
        # pass None through a closure instead
        body_fn = lambda lp, xs, pos: body(lp, xs, pos, None)  # noqa: E731
        in_specs = in_specs[:3]
        operands = (p, x, positions)
    else:
        body_fn = body
        operands = (p, x, positions, attn_bias)
    ctx = jax.sharding.get_abstract_mesh()
    use_mesh = ctx if (ctx is not None and not ctx.empty) else mesh
    return jax.shard_map(
        body_fn,
        mesh=use_mesh,
        in_specs=in_specs,
        out_specs=x_spec,
        axis_names=set(axes.dp) | set(axes.tp),
    )(*operands)


# ----------------------------------------------------- overlap measurement
def measure_comm_hidden(
    cfg,
    hp: HybridParallelConfig,
    mesh: Mesh,
    *,
    batch_size: Optional[int] = None,
    iters: int = 3,
    warmup: int = 1,
) -> List[Dict[str, Any]]:
    """Measured communication time hidden by the decomposed path, per TP
    LayerRun: wall-clock of ONE representative layer (fwd+bwd, scaled by
    the run's length) under ``overlap`` vs the serialized manual mode
    (``shard_map`` — same collectives, no interleaving).
    ``comm_hidden_ms = max(serial - overlap, 0)`` is the comm the chunked
    schedule moved off the critical path. One small jitted program per
    (run, mode) on synthetic activations — a profiling helper (driver
    --profile / bench), never on the training hot path."""
    import time as _time

    bsz = batch_size or hp.global_bsz
    seq = cfg.max_seq_len
    key = jax.random.PRNGKey(0)
    out: List[Dict[str, Any]] = []
    for ridx, run in enumerate(layer_runs(hp)):
        ax = layer_axes(hp, run.start)
        if len(ax.tp) == 0 or manual_tp_reason(cfg, hp, run.strategy) is not None:
            continue
        from galvatron_tpu.models.base import init_layer_params

        lp = init_layer_params(key, cfg)
        x = jax.random.normal(key, (bsz, seq, cfg.hidden_size), jnp.float32)
        x = x.astype(cfg.compute_dtype)
        positions = jnp.broadcast_to(jnp.arange(seq), (bsz, seq))

        def timed(mode):
            def loss(p_, x_):
                y = manual_layer_forward(
                    p_, x_, positions, cfg, mesh=mesh, axes=ax, hp=hp,
                    mode=mode)
                return jnp.mean(y.astype(jnp.float32) ** 2)

            f = jax.jit(jax.value_and_grad(loss))
            for _ in range(max(warmup, 1)):
                jax.block_until_ready(f(lp, x))  # galv-lint: ignore[GLC005] -- timing harness: the sync IS the measurement
            ts = []
            for _ in range(max(iters, 1)):
                t0 = _time.perf_counter()
                jax.block_until_ready(f(lp, x))  # galv-lint: ignore[GLC005] -- timing harness: the sync IS the measurement
                ts.append(_time.perf_counter() - t0)
            return min(ts) * 1e3

        overlap_ms = timed("overlap")
        serial_ms = timed("shard_map")
        out.append({
            "run": ridx,
            "start": run.start,
            "stop": run.stop,
            "overlap_ms": round(overlap_ms * run.length, 4),
            "serial_ms": round(serial_ms * run.length, 4),
            "comm_hidden_ms": round(max(serial_ms - overlap_ms, 0.0) * run.length, 4),
        })
    return out
