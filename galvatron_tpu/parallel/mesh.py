"""Device mesh construction and per-layer axis assignment.

TPU-native replacement for the reference's NCCL communication-group builder
(reference: galvatron/core/runtime/comm_groups.py:416-569). Where the reference
materialises one `torch.distributed` group per (layer, role) — TP consecutive
(comm_groups.py:71), CP strided (:94), DP strided (:121), SP (:146), PP (:180),
embedding (:199), plus explicit redistribution groups (:315) — we build ONE
`jax.sharding.Mesh` whose per-stage device block is factored into binary
sub-axes ``m0 .. m{k-1}`` (major -> minor), and express every layer's strategy
as an *assignment of sub-axes to roles*:

    minor sub-axes -> tp (or ulysses-sp), next -> cp, major remainder -> dp

matching the reference's rank order DP(outer) -> CP -> TP(inner, consecutive)
(comm_groups.py:94-145). ``tp_consec=0`` flips the assignment so tp occupies
the *major* sub-axes — the TPU analogue of non-consecutive (cross-node) TP
groups: on a real slice the minor mesh dims ride contiguous ICI rings while
major dims may span DCN.

All collectives (grad all-reduce over dp, TP all-reduce/all-gather, Ulysses
all-to-all, ring ppermute, inter-layer redistribution) are then *derived by
XLA* from `PartitionSpec`s over these axes — there is no group bookkeeping to
keep in sync.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from galvatron_tpu.config.strategy import HybridParallelConfig, LayerStrategy

PP_AXIS = "pp"


def subaxis_sizes(per_stage: int) -> Tuple[int, ...]:
    """Factor the per-pipeline-stage device count into binary sub-axes
    (major -> minor), with any odd remainder as a single leading axis.

    Powers of two cover every degree in the reference search space
    (search_engine.py:783-914 enumerates pow2 tp/cp/pp)."""
    sizes = []
    n = per_stage
    while n % 2 == 0 and n > 1:
        sizes.append(2)
        n //= 2
    if n > 1:
        sizes.insert(0, n)
    return tuple(sizes)


def subaxis_names(per_stage: int) -> Tuple[str, ...]:
    return tuple("m%d" % i for i in range(len(subaxis_sizes(per_stage))))


def build_mesh(
    config: HybridParallelConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh with axes ``("pp", "m0", ..., "m{k-1}")``.

    On real hardware, prefer `mesh_utils.create_device_mesh` so minor axes map
    to contiguous ICI; on CPU/test backends fall back to a plain reshape."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < config.world_size:
        raise ValueError(
            "need %d devices for this config, have %d" % (config.world_size, len(devices))
        )
    devices = list(devices)[: config.world_size]
    shape = (config.pp,) + subaxis_sizes(config.per_stage_devices)
    names = (PP_AXIS,) + subaxis_names(config.per_stage_devices)
    # multi-host: hybrid ICI/DCN placement (pp + major-dp span hosts, tp/cp
    # stay on intra-host ICI — runtime/distributed.py)
    from galvatron_tpu.runtime.distributed import dcn_granule_count, device_mesh_for

    try:
        dev_array = device_mesh_for(shape, devices)
    except Exception:
        if dcn_granule_count(devices) > 1:
            # never silently downgrade a multi-host run to a locality-blind
            # reshape: tp/cp would span DCN and cripple every collective
            raise
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, names)


@dataclass(frozen=True)
class LayerAxes:
    """The mesh-axis assignment realising one layer's strategy.

    ``dp``/``cp``/``tp`` are tuples of mesh-axis names (major -> minor).
    When ``ulysses`` is set the ``tp`` axes carry Ulysses sequence parallelism
    (attention-head scatter / sequence gather all-to-all) instead of Megatron
    tensor parallelism. ``megatron_sp`` marks Megatron-SP activation sharding
    (activations sharded over the tp axes outside attention/mlp)."""

    dp: Tuple[str, ...]
    cp: Tuple[str, ...]
    tp: Tuple[str, ...]
    ulysses: bool = False
    megatron_sp: bool = False
    zero3: bool = False
    zero_opt: bool = False  # optimizer state sharded over dp (zero1/2/3)

    @property
    def seq_axes(self) -> Tuple[str, ...]:
        """Axes sharding the sequence dim of activations *between* layers:
        cp always; plus tp when this layer does ulysses or megatron-sp."""
        ax = tuple(self.cp)
        if self.ulysses or self.megatron_sp:
            ax += tuple(self.tp)
        return ax

    @property
    def batch_axes(self) -> Tuple[str, ...]:
        return self.dp


def _assign(
    names: Tuple[str, ...],
    sizes: Tuple[int, ...],
    tp: int,
    cp: int,
    tp_consec: bool,
) -> Tuple[Tuple[str, ...], Tuple[str, ...], Tuple[str, ...]]:
    """Split sub-axes into (dp, cp, tp) groups by degree products."""

    def take_minor(names_left, sizes_left, degree, what):
        taken = []
        prod = 1
        while prod < degree:
            if not names_left:
                raise ValueError("cannot realise %s degree %d from sub-axes %s" % (what, degree, sizes))
            taken.insert(0, names_left[-1])
            prod *= sizes_left[-1]
            names_left, sizes_left = names_left[:-1], sizes_left[:-1]
        if prod != degree:
            raise ValueError("%s degree %d not a product of minor sub-axes %s" % (what, degree, sizes))
        return names_left, sizes_left, tuple(taken)

    if not tp_consec and tp > 1:
        # tp on the MAJOR axes: reverse, assign, un-reverse.
        rn, rs = tuple(reversed(names)), tuple(reversed(sizes))
        rn_left, rs_left, tp_ax = take_minor(rn, rs, tp, "tp")
        rn_left, rs_left, cp_ax = take_minor(rn_left, rs_left, cp, "cp")
        dp_ax = tuple(reversed(rn_left))
        return dp_ax, tuple(reversed(cp_ax)), tuple(reversed(tp_ax))
    names_left, sizes_left, tp_ax = take_minor(names, sizes, tp, "tp")
    names_left, sizes_left, cp_ax = take_minor(names_left, sizes_left, cp, "cp")
    return tuple(names_left), cp_ax, tp_ax


def layer_axes(config: HybridParallelConfig, layer_idx: int) -> LayerAxes:
    s = config.layers[layer_idx]
    return _axes_from_strategy(config, s.tp, s.cp, bool(s.sp), bool(s.tp_consec), bool(s.fsdp))


def vocab_axes(config: HybridParallelConfig) -> LayerAxes:
    """Axes for embedding / lm-head / loss layers (vocab_tp/vocab_sp/vocab_cp,
    reference hybrid_parallel_config.py:90,105 and dp_core.cpp:78-117)."""
    return _axes_from_strategy(
        config,
        config.vocab_tp,
        config.vocab_cp,
        bool(config.vocab_sp),
        True,
        bool(config.embed_sdp),
    )


def _axes_from_strategy(
    config: HybridParallelConfig,
    tp: int,
    cp: int,
    ulysses: bool,
    tp_consec: bool,
    fsdp: bool,
) -> LayerAxes:
    names = subaxis_names(config.per_stage_devices)
    sizes = subaxis_sizes(config.per_stage_devices)
    dp_ax, cp_ax, tp_ax = _assign(names, sizes, tp, cp, tp_consec)
    dp_type = "zero3" if fsdp else config.default_dp_type
    return LayerAxes(
        dp=dp_ax,
        cp=cp_ax,
        tp=tp_ax,
        ulysses=ulysses and tp > 1,
        megatron_sp=config.sequence_parallel and tp > 1 and not ulysses,
        zero3=dp_type == "zero3",
        zero_opt=dp_type in ("zero2", "zero3"),
    )


def mesh_axis_size(mesh: Mesh, axes: Sequence[str]) -> int:
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n
