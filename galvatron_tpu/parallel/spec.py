"""PartitionSpec builders: per-layer parameter / activation shardings.

This module replaces three reference subsystems at once:

- per-layer FSDP wrapping with ShardingStrategy {NO_SHARD, SHARD_GRAD_OP,
  FULL_SHARD} (reference: galvatron/core/runtime/parallel.py:92-199) — here,
  ZeRO-3 is a parameter sharding over the layer's dp sub-axes and ZeRO-1/2 is
  an optimizer-state/grad-accumulator sharding (see runtime/optimizer.py);
- Megatron Column/RowParallelLinear weight partitioning with per-layer groups
  (reference: site_package/megatron/core/tensor_parallel/layers.py:126-228) —
  here, a column kernel is `P(..., tp)` and a row kernel `P(tp, ...)`;
- activation redistribution between layers with different strategies
  (reference: galvatron/core/runtime/redistribute.py, parallel.py:279-313) —
  here, `jax.lax.with_sharding_constraint` on the layer boundary makes XLA
  insert exactly the split/all-gather/all-to-all collectives the reference
  hand-writes.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.parallel.mesh import LayerAxes

Axes = Union[None, str, Tuple[str, ...]]


def _ax(axes: Sequence[str]) -> Axes:
    """Collapse an axis-name tuple for use inside a PartitionSpec."""
    axes = tuple(axes)
    if not axes:
        return None
    if len(axes) == 1:
        return axes[0]
    return axes


def _merge(*groups: Sequence[str]) -> Axes:
    out: Tuple[str, ...] = ()
    for g in groups:
        out += tuple(g)
    return _ax(out)


# ----------------------------------------------------------------- activations
def act_spec(ax: LayerAxes, *, seq_dim: int = 1, ndim: int = 3) -> P:
    """Sharding of a (batch, seq, hidden) activation *between* layers.

    Batch is sharded over dp; sequence over cp (+ tp when the layer runs
    ulysses or megatron-sp). The hidden dim stays unsharded between layers —
    inside a TP layer XLA re-partitions as the matmuls require."""
    entries = [None] * ndim
    entries[0] = _ax(ax.batch_axes)
    entries[seq_dim] = _ax(ax.seq_axes)
    return P(*entries)


def logits_spec(ax: LayerAxes) -> P:
    """(batch, seq, vocab) logits. vocab_sp=0: vocab sharded over tp
    (vocab-parallel lm head + loss). vocab_sp=1 (ulysses/vocab-SP): sequence
    stays tp-sharded and vocab is dense (reference
    vocab_sequence_parallel_cross_entropy, site_package/megatron/core/
    tensor_parallel/cross_entropy.py:174-219)."""
    if ax.ulysses:
        return P(_ax(ax.batch_axes), _ax(ax.seq_axes), None)
    return P(_ax(ax.batch_axes), _ax(ax.cp), _ax(ax.tp))


# ------------------------------------------------------------------ parameters
def _zero3_axes(ax: LayerAxes) -> Tuple[str, ...]:
    return tuple(ax.dp) if ax.zero3 else ()


def col_kernel_spec(ax: LayerAxes) -> P:
    """Column-parallel kernel (in_dim, out_dim): out over tp; ZeRO-3 shards the
    in dim over dp. With ulysses the tp axes hold sequence, so the kernel is
    *not* tp-sharded (reference transformer.py:2065-2177 keeps dense weights)."""
    tp = () if ax.ulysses else ax.tp
    return P(_ax(_zero3_axes(ax) or ()), _ax(tp))


def row_kernel_spec(ax: LayerAxes) -> P:
    """Row-parallel kernel (in_dim, out_dim): in over tp; ZeRO-3 shards out."""
    tp = () if ax.ulysses else ax.tp
    return P(_ax(tp), _ax(_zero3_axes(ax) or ()))


def col_bias_spec(ax: LayerAxes) -> P:
    tp = () if ax.ulysses else ax.tp
    return P(_ax(tp))


def replicated_1d_spec(ax: LayerAxes) -> P:
    """LayerNorm scales / row-parallel biases: replicated over tp; ZeRO-3
    shards over dp (the FSDP flat-param analogue)."""
    return P(_ax(_zero3_axes(ax) or ()))


def vocab_embed_spec(ax: LayerAxes) -> P:
    """(vocab, hidden) embedding table, vocab-parallel over tp
    (reference: VocabParallelEmbedding, models/gpt_hf/GPTModel_tensor_parallel.py:84-132).
    Under vocab-SP (ulysses) the tp axes carry sequence, so the table stays
    vocab-dense (matching logits_spec) and ZeRO-3 shards the vocab dim."""
    if ax.ulysses:
        return P(_ax(_zero3_axes(ax) or ()), None)
    return P(_ax(ax.tp), _ax(_zero3_axes(ax) or ()))


# ------------------------------------------------------------------- utilities
def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def constrain(x, mesh: Mesh, spec: P):
    """Reshard an activation to `spec` — the XLA-native Module_with_relocation
    (reference parallel.py:279-313): collectives are inserted by the compiler."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _entry_axes(e: Axes) -> Tuple[str, ...]:
    if e is None:
        return ()
    if isinstance(e, str):
        return (e,)
    return tuple(e)


def meet_spec(a: P, b: P, ndim: int) -> P:
    """Per-dim longest common prefix of two PartitionSpecs.

    Resharding a -> meet -> b is *axis-monotone*: every step only drops or
    appends trailing mesh axes on each dim, so XLA lowers it with group-scoped
    collectives (all-gather / slice) and never an axis-reassigning
    collective-permute. That property is what makes heterogeneous per-layer
    reshards safe inside the 1F1B schedule's stage-divergent branches, where a
    collective-permute (whose XLA rendezvous spans ALL devices) would deadlock
    across stages running different branches."""
    ea = list(a) + [None] * (ndim - len(a))
    eb = list(b) + [None] * (ndim - len(b))
    out = []
    for xa, xb in zip(ea, eb):
        ta, tb = _entry_axes(xa), _entry_axes(xb)
        common = []
        for i in range(min(len(ta), len(tb))):
            if ta[i] != tb[i]:
                break
            common.append(ta[i])
        out.append(_ax(common))
    return P(*out)


def monotone_constrain(x, mesh: Mesh, from_spec: P, to_spec: P):
    """Constrain `x` (currently sharded as `from_spec`) to `to_spec`, routing
    through the per-dim meet when the direct transition would reassign a dim
    between different mesh axes. Trace-time decision: when the transition is
    already nested (meet equals one endpoint) no extra constraint is emitted."""
    meet = meet_spec(from_spec, to_spec, x.ndim)
    norm = lambda s: tuple(list(s) + [None] * (x.ndim - len(s)))
    if norm(meet) not in (norm(from_spec), norm(to_spec)):
        x = constrain(x, mesh, meet)
    return constrain(x, mesh, to_spec)


def replicated_spec(ndim: int) -> P:
    return P(*([None] * ndim))
