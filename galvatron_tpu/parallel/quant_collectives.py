"""Quantized collectives: blockwise int8/fp8 payloads for the DP/ZeRO
gradient sync, the ZeRO-3 parameter all-gather, and the decomposed TP rings.

On bandwidth-bound dp/zero3 configs the step time is dominated by two
collectives: the gradient sync (all-reduce under ddp, reduce-scatter under
ZeRO) and the ZeRO-3 weight all-gather. EQuARX (arXiv:2506.17615) shows a
quantized AllReduce inside XLA for exactly this stack; ZeRO++
(arXiv:2306.10209) shows blockwise-int8 gradient sync and quantized ZeRO-3
weight gather at production scale. This module is the jax-userland
equivalent, built on the same machinery PR 8 established for the TP rings
(`lax.ppermute` rings under `jax.shard_map`):

- **blockwise symmetric quantization** (`quantize_blockwise` /
  `dequantize_blockwise`): per-block absmax scales (block size a knob,
  ``comm_quant_block``), int8 or fp8-e4m3 wire payloads, deterministic
  round-half-even. ``bf16``/``fp32`` are passthrough payloads (a precision
  cast on the wire, no scales).
- **quantized rings**: `ring_all_reduce` = reduce-scatter with quantized
  wire hops and fp32 dequant-accumulate, then a quantized all-gather of the
  reduced chunk (the ZeRO++ gradient-sync schedule); `ring_all_gather` /
  `ring_reduce_scatter` along an arbitrary dim serve the ZeRO-3 parameter
  gather and its cotangent reduce-scatter (`make_qgather`, one custom_vjp:
  quantized weight gather forward, quantized grad reduce-scatter backward).
- **the explicit grad-sync train path** (`make_quant_loss_and_grads`): for
  pure data-parallel layouts (pp=1, tp=1, cp=1, no ulysses — the ZeRO++
  domain) the whole loss+grad computation runs under ONE `jax.shard_map`
  over the dp axes. Inside the manual region each device computes grads on
  its local batch shard through the constraint-free local loss path
  (models/base loss_fns with hp=None), so the cross-device gradient
  reduction becomes OUR ring instead of a GSPMD-inserted collective — the
  seam GSPMD never exposes. Per-layer ``grad_comm_dtype`` /
  ``param_comm_dtype`` (serialized strategy fields) choose each leaf's wire
  precision; ``none`` leaves ride exact `lax.psum` / native gathers.

Numerics contract (mirroring tp_shard_map's): layouts the quantized path
cannot express are REFUSED with a GLS013 diagnostic — at lint time
(strategy_lint) and again at trace time — never silently approximated.
``bf16`` payloads of a bf16-computed gradient are bitwise the cast chain;
quantized payloads carry a bounded relative error per block (<= 1/(2*qmax)
of the block absmax per wire hop), pinned by
tests/parallel/test_quant_collectives.py.

jax 0.4.37 notes (inherited from PR 8, pinned in memory + tests): the
shard_map here is manual over the dp axes with the size-1 'pp' axis auto
(compiles fine; true partial-manual does not); custom_vjp bodies compute
`lax.axis_index` inside the traced function, never close over it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# COMM_DTYPES lives with the schema (config/strategy.py) — the serialized
# per-layer fields validate against it; re-exported here for callers of the
# kernel API. "none" keeps the exact full-precision collective (GSPMD /
# lax.psum); "bf16" is a passthrough cast (half the bytes, no scales);
# int8 / fp8_e4m3 are blockwise-quantized.
from galvatron_tpu.config.strategy import COMM_DTYPES, HybridParallelConfig

QUANTIZED_DTYPES = ("int8", "fp8_e4m3")

_QMAX = {"int8": 127.0, "fp8_e4m3": 448.0}
# wire bytes per element, scales included at the given block size
def wire_bytes_per_element(dtype: str, block: int, full_bytes: float = 4.0) -> float:
    """Bytes on the wire per gradient element for one collective pass:
    payload + fp32 per-block scale amortised over the block. The cost
    models' comm-precision axis prices volume through this same function."""
    if dtype == "none":
        return full_bytes
    if dtype == "bf16":
        return 2.0
    return 1.0 + 4.0 / max(int(block), 1)


def fp8_supported() -> bool:
    """Whether the installed jax/ml_dtypes ships float8_e4m3fn."""
    return hasattr(jnp, "float8_e4m3fn")


def _payload_jnp_dtype(dtype: str):
    if dtype == "int8":
        return jnp.int8
    if dtype == "fp8_e4m3":
        if not fp8_supported():
            raise TypeError("installed jax has no float8_e4m3fn")
        return jnp.float8_e4m3fn
    raise ValueError("not a quantized wire dtype: %r" % dtype)


# ============================================================ quant kernels
def quantize_blockwise(x: jax.Array, dtype: str, block: int):
    """Flatten ``x`` and quantize in blocks of ``block`` elements.

    Returns ``(payload, scales)``: payload ``(nblk, block)`` in the wire
    dtype, scales ``(nblk,)`` fp32 (absmax / qmax; all-zero blocks get
    scale 1 so the payload is exactly zero). The tail is zero-padded to a
    block multiple — callers slice back with the original shape.
    Deterministic: jnp.round (half-to-even), no RNG."""
    qmax = _QMAX[dtype]
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(amax > 0.0, amax / qmax, 1.0).astype(jnp.float32)
    scaled = blocks / scales[:, None]
    if dtype == "int8":
        payload = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(jnp.int8)
    else:
        payload = jnp.clip(scaled, -qmax, qmax).astype(_payload_jnp_dtype(dtype))
    return payload, scales


def dequantize_blockwise(payload: jax.Array, scales: jax.Array, shape,
                         out_dtype=jnp.float32) -> jax.Array:
    """Inverse of quantize_blockwise: drop the pad, restore ``shape``."""
    flat = payload.astype(jnp.float32) * scales[:, None]
    n = int(np.prod(shape)) if shape else 1
    return flat.reshape(-1)[:n].reshape(shape).astype(out_dtype)


# --------------------------------------------------------- wire transports
def _ring_perm(n: int) -> List[Tuple[int, int]]:
    return [(j, (j + 1) % n) for j in range(n)]


def _flat_axis_index(axis_names: Tuple[str, ...], sizes: Tuple[int, ...]):
    idx = jnp.int32(0)
    for name, size in zip(axis_names, sizes):
        idx = idx * size + jax.lax.axis_index(name)
    return idx


def _wire_hop(x: jax.Array, axes, perm, dtype: str, block: int) -> jax.Array:
    """One ppermute hop of ``x`` at the requested wire precision: quantize
    for the wire, permute payload+scales, dequantize on arrival (fp32).
    This is the only place values leave the device at reduced precision —
    accumulation stays fp32 (the ZeRO++ discipline)."""
    if dtype == "none":
        return jax.lax.ppermute(x, axes, perm)
    if dtype == "bf16":
        sent = jax.lax.ppermute(x.astype(jnp.bfloat16), axes, perm)
        return sent.astype(x.dtype)
    payload, scales = quantize_blockwise(x, dtype, block)
    payload = jax.lax.ppermute(payload, axes, perm)
    scales = jax.lax.ppermute(scales, axes, perm)
    return dequantize_blockwise(payload, scales, x.shape, x.dtype)


# ============================================================== collectives
# All of these run INSIDE a shard_map body manual over ``axes`` (tuples of
# mesh axis names, major->minor, with ``sizes`` their mesh sizes).

def ring_all_gather(x: jax.Array, axes: Tuple[str, ...], sizes: Tuple[int, ...],
                    *, axis: int = 0, dtype: str = "none",
                    block: int = 64) -> jax.Array:
    """All-gather the local shard along ``axis`` with the shard quantized
    ONCE and the (payload, scales) pair riding the ring; each arriving
    block dequantizes into its source's slot (same index arithmetic as the
    PR-8 column ring). ``dtype='none'`` uses the native tiled all_gather."""
    n = int(np.prod(sizes))
    if n == 1:
        return x
    if dtype == "none":
        return jax.lax.all_gather(x, axes, axis=axis, tiled=True)
    xm = jnp.moveaxis(x, axis, 0)
    s = xm.shape[0]
    idx = _flat_axis_index(axes, sizes)
    perm = _ring_perm(n)
    out = jnp.zeros((n * s,) + xm.shape[1:], jnp.float32)
    if dtype == "bf16":
        cur: Any = xm.astype(jnp.bfloat16)
        decode = lambda c: c.astype(jnp.float32)  # noqa: E731
        hop = lambda c: jax.lax.ppermute(c, axes, perm)  # noqa: E731
    else:
        cur = quantize_blockwise(xm, dtype, block)
        decode = lambda c: dequantize_blockwise(c[0], c[1], xm.shape)  # noqa: E731
        hop = lambda c: (jax.lax.ppermute(c[0], axes, perm),  # noqa: E731
                         jax.lax.ppermute(c[1], axes, perm))
    for step in range(n):
        src = jnp.mod(idx - step, n)
        out = jax.lax.dynamic_update_slice_in_dim(out, decode(cur), src * s, 0)
        if step < n - 1:
            cur = hop(cur)
    return jnp.moveaxis(out, 0, axis).astype(x.dtype)


def ring_reduce_scatter(x: jax.Array, axes: Tuple[str, ...],
                        sizes: Tuple[int, ...], *, axis: int = 0,
                        dtype: str = "none", block: int = 64) -> jax.Array:
    """Reduce-scatter ``x`` (each device holds a full partial sum) along
    ``axis``: a rotating accumulator picks up each device's block for its
    destination, quantized on every wire hop, accumulated in fp32
    (ZeRO++-style int8 gradient sync). Returns this device's reduced
    1/n-slice. ``dtype='none'`` uses the native psum_scatter."""
    n = int(np.prod(sizes))
    if n == 1:
        return x
    if dtype == "none":
        return jax.lax.psum_scatter(x, axes, scatter_dimension=axis, tiled=True)
    xm = jnp.moveaxis(x, axis, 0).astype(jnp.float32)
    s = xm.shape[0] // n
    idx = _flat_axis_index(axes, sizes)
    perm = _ring_perm(n)
    acc = None
    for step in range(n):
        dest = jnp.mod(idx - 1 - step, n)
        part = jax.lax.dynamic_slice_in_dim(xm, dest * s, s, 0)
        if acc is None:
            acc = part
        else:
            acc = _wire_hop(acc, axes, perm, dtype, block) + part
    return jnp.moveaxis(acc, 0, axis).astype(x.dtype)


def ring_all_reduce(x: jax.Array, axes: Tuple[str, ...], sizes: Tuple[int, ...],
                    *, dtype: str = "none", block: int = 64) -> jax.Array:
    """Sum-all-reduce with quantized wire traffic: flat reduce-scatter
    (quantized hops, fp32 accumulate) then a quantized all-gather of the
    reduced chunk — 2x(n-1)/n quantized volume, the ZeRO++ schedule.
    ``dtype='none'`` is an exact lax.psum."""
    n = int(np.prod(sizes))
    if n == 1:
        return x
    if dtype == "none":
        return jax.lax.psum(x, axes)
    shape, dt = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    ln = flat.shape[0]
    pad = (-ln) % (n * block)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(n, -1)
    reduced = ring_reduce_scatter(chunks, axes, sizes, axis=0,
                                  dtype=dtype, block=block)  # (1, c)
    gathered = ring_all_gather(reduced, axes, sizes, axis=0,
                               dtype=dtype, block=block)  # (n, c)
    return gathered.reshape(-1)[:ln].reshape(shape).astype(dt)


def make_qgather(axes: Tuple[str, ...], sizes: Tuple[int, ...], dim: int,
                 param_dtype: str, grad_dtype: str, block: int) -> Callable:
    """The ZeRO-3 leaf transport as ONE custom_vjp: forward = quantized ring
    all-gather of the parameter shard along ``dim`` (``param_comm_dtype``),
    backward = quantized ring reduce-scatter of the cotangent
    (``grad_comm_dtype``) — exactly the two collectives ZeRO++ quantizes.
    ``none`` on either side keeps the native exact collective for that
    direction."""

    def _fwd_impl(shard):
        return ring_all_gather(shard, axes, sizes, axis=dim,
                               dtype=param_dtype, block=block)

    @jax.custom_vjp
    def qg(shard):
        return _fwd_impl(shard)

    def fwd(shard):
        return _fwd_impl(shard), None

    def bwd(_res, g):
        # the cotangent arrives in the primal's (float) dtype, so the
        # reduce-scattered shard is already shaped and typed like the input
        return (ring_reduce_scatter(g, axes, sizes, axis=dim,
                                    dtype=grad_dtype, block=block),)

    qg.defvjp(fwd, bwd)
    return qg


# =========================================================== support checks
def wants_quant_comm(hp: Optional[HybridParallelConfig]) -> bool:
    """Whether the strategy asks for the explicit quantized grad-sync path:
    any layer's grad/param comm dtype is not 'none' AND there is a dp group
    to communicate over (dp=1 layouts have no grad sync — the knob is
    inert, which the linter warns about, rather than wrong)."""
    if hp is None:
        return False
    asks = any(
        getattr(s, "grad_comm_dtype", "none") != "none"
        or getattr(s, "param_comm_dtype", "none") != "none"
        for s in hp.layers
    )
    if not asks:
        return False
    try:
        return any(hp.dp(i) > 1 for i in range(hp.num_layers))
    except Exception:
        return False


def quant_comm_reason(model_cfg: Any, hp: HybridParallelConfig, *,
                      anomaly_guard: Optional[bool] = None) -> Optional[str]:
    """Why the quantized comm path cannot run this config, or None when it
    can. Pure host-side (the strategy linter calls it with no tracing);
    shared verbatim by the GLS013 lint diagnostics and the trace-time
    refusal so the two can never disagree."""
    if hp.pp > 1:
        return "quantized grad sync requires pp=1 (the pipeline engines own " \
               "their grad schedule)"
    for i, s in enumerate(hp.layers):
        if s.tp > 1 or s.cp > 1 or s.sp:
            return "layer %d: quantized grad sync requires a pure " \
                   "data-parallel layout (tp=1, cp=1, no ulysses); got " \
                   "tp=%d cp=%d sp=%d" % (i, s.tp, s.cp, s.sp)
    if hp.vocab_tp > 1 or hp.vocab_cp > 1 or hp.vocab_sp:
        return "vocab parallelism (vtp=%d vcp=%d vsp=%d) is not expressible " \
               "in the manual dp grad ring" % (hp.vocab_tp, hp.vocab_cp, hp.vocab_sp)
    if hp.default_dp_type == "zero2":
        return "default_dp_type='zero2' shards the grad accumulator without " \
               "sharding params; the quantized ring covers ddp and per-layer " \
               "zero3 (fsdp=1) only"
    needs_fp8 = any(
        "fp8_e4m3" in (s.grad_comm_dtype, s.param_comm_dtype) for s in hp.layers
    ) or hp.tp_comm_quant == "fp8_e4m3"
    if needs_fp8 and not fp8_supported():
        return "fp8_e4m3 wire payloads need jax.numpy.float8_e4m3fn, which " \
               "this jax does not provide"
    if anomaly_guard:
        return "the anomaly guard's spike/rollback contract expects the " \
               "bitwise GSPMD loss; disable it (--anomaly_guard 0) to train " \
               "with quantized grad sync"
    return None


def assert_quant_comm_supported(model_cfg: Any, hp: HybridParallelConfig, *,
                                anomaly_guard: Optional[bool] = None) -> None:
    """Trace-time refusal (GLS013 DiagnosticError) — the loud half of the
    never-silently-differ contract; strategy_lint reports the same reason
    pre-trace."""
    reason = quant_comm_reason(model_cfg, hp, anomaly_guard=anomaly_guard)
    if reason is not None:
        from galvatron_tpu.analysis import diagnostics as D

        raise D.DiagnosticError([D.make(
            "GLS013", "quantized collectives: %s" % reason,
            key="grad_comm_dtype",
        )])


# ===================================================== grad-sync train path
def _spec_dp_dim(spec: P, dp_axes: Tuple[str, ...]) -> Optional[int]:
    """Dim index carrying any of the dp axes in ``spec`` (the ZeRO-3 shard
    dim), or None for replicated leaves."""
    dp = set(dp_axes)
    for i, e in enumerate(spec):
        names = (e,) if isinstance(e, str) else tuple(e or ())
        if any(a in dp for a in names):
            return i
    return None


def _leaf_wire_dtypes(model) -> Dict[str, Any]:
    """Per-leaf (grad_dtype, param_dtype) trees matching model.param_specs:
    layer leaves inherit their layer's serialized comm dtypes; embed/head
    (vocab) leaves stay 'none' — their sync is exact (small, and the loss
    head is the numerically touchiest part of the model)."""
    hp = model.hp
    layer_lists = ("layers", "stages", "enc_layers", "dec_layers", "blocks")
    out = {}
    offset = 0
    for key, sub in model.param_specs.items():
        if key in layer_lists:
            per = []
            for i in range(len(sub)):
                s = hp.layers[offset + i]
                per.append(jax.tree.map(
                    lambda _: (s.grad_comm_dtype, s.param_comm_dtype), sub[i],
                    is_leaf=lambda t: isinstance(t, P)))
            out[key] = per
            offset += len(sub)
        else:
            out[key] = jax.tree.map(lambda _: ("none", "none"), sub,
                                    is_leaf=lambda t: isinstance(t, P))
    return out


def make_quant_loss_and_grads(model) -> Callable:
    """(params, batch) -> (loss, grads) with the DP gradient sync as an
    explicit (quantizable) ring.

    One `jax.shard_map` manual over the dp mesh axes wraps the whole
    loss+grad computation: params enter through their own PartitionSpecs
    (replicated leaves whole, ZeRO-3 leaves as shards that a `make_qgather`
    custom_vjp gathers — quantized forward, quantized cotangent
    reduce-scatter), the batch enters dp-sharded, and the body runs the
    family's constraint-free local loss (models/base with hp=None) under
    ``value_and_grad``. Microbatches (hp.chunks) are weighted by their
    share of the GLOBAL valid-token count (one cheap scalar psum), so the
    objective is identical to the GSPMD step's; replicated-leaf grads are
    summed by `ring_all_reduce` at each leaf's ``grad_comm_dtype``
    ('none' leaves ride exact lax.psum). Grads come out in the exact
    shardings ``grad_accum_specs`` expects, so the optimizer update stays
    the ordinary GSPMD program."""
    hp, mesh, cfg = model.hp, model.mesh, model.cfg
    local_loss = getattr(model, "local_loss_fn", None)
    if local_loss is None:
        from galvatron_tpu.analysis import diagnostics as D

        raise D.DiagnosticError([D.make(
            "GLS013", "quantized collectives: this model family has no "
            "constraint-free local loss path (custom param trees / custom "
            "loss_fn); quantized grad sync supports the base transformer "
            "families", key="grad_comm_dtype",
        )])
    assert_quant_comm_supported(cfg, hp)
    from galvatron_tpu.parallel.mesh import layer_axes

    dp_axes = tuple(layer_axes(hp, 0).dp)
    sizes = tuple(mesh.shape[a] for a in dp_axes)
    n = int(np.prod(sizes))
    block = int(hp.comm_quant_block)
    chunks = max(int(hp.chunks), 1)

    p_specs = model.param_specs
    wires = _leaf_wire_dtypes(model)
    is_spec = lambda t: isinstance(t, P)  # noqa: E731

    # per-leaf transport plan, precomputed outside the traced body. A plain
    # tuple (not a dict: the param tree's interior nodes are dicts, so an
    # is_leaf=dict test would swallow the whole tree as one leaf); wrapped
    # as a static leaf via a 1-tuple-free flatten over the SPEC tree, whose
    # leaf order matches jax.tree.flatten of the params.
    def leaf_plan(spec, wire):
        gdt, pdt = wire
        return (_spec_dp_dim(spec, dp_axes), gdt, pdt)

    spec_leaves = jax.tree.leaves(p_specs, is_leaf=is_spec)
    wire_leaves = jax.tree.leaves(wires, is_leaf=lambda t: isinstance(t, tuple))
    plan_leaves = [leaf_plan(s, w) for s, w in zip(spec_leaves, wire_leaves)]

    def body(params_loc, batch_loc):
        # gather zero3 leaves through the custom_vjp transport; the same
        # function is reapplied per microbatch inside value_and_grad so the
        # backward reduce-scatter fires exactly where ZeRO flushes grads
        def gather_tree(p):
            leaves, treedef = jax.tree.flatten(p)
            out = []
            for leaf, (dim, gdt, pdt) in zip(leaves, plan_leaves, strict=True):
                if dim is None:
                    out.append(leaf)
                else:
                    out.append(make_qgather(dp_axes, sizes, dim, pdt, gdt,
                                            block)(leaf))
            return jax.tree.unflatten(treedef, out)

        # microbatch weights: each (shard, microbatch) loss is a mean over
        # its own valid tokens; weighting by its share of the GLOBAL valid
        # count keeps the objective identical to the GSPMD chunks loop
        def split(x):
            return x.reshape((chunks, x.shape[0] // chunks) + x.shape[1:])

        mbs = jax.tree.map(split, batch_loc)
        if "loss_mask" in batch_loc:
            counts = jnp.sum(
                mbs["loss_mask"].astype(jnp.float32),
                axis=tuple(range(1, batch_loc["loss_mask"].ndim + 1)))
        else:
            some = jax.tree.leaves(batch_loc)[0]
            counts = jnp.full((chunks,), some.shape[0] / chunks, jnp.float32)
        total = jax.lax.psum(jnp.sum(counts), dp_axes)
        weights = counts / jnp.maximum(total, 1.0)

        grads = None
        loss = jnp.float32(0.0)
        for c in range(chunks):
            mb = jax.tree.map(lambda x: x[c], mbs)
            w = weights[c]

            def weighted(p, _mb=mb, _w=w):
                return (_w * local_loss(gather_tree(p), _mb)).astype(jnp.float32)

            l, g = jax.value_and_grad(weighted)(params_loc)
            grads = g if grads is None else jax.tree.map(jnp.add, grads, g)
            loss = loss + l
        loss = jax.lax.psum(loss, dp_axes)

        # replicated-leaf sync: the explicit quantized ring (zero3 leaves
        # were reduce-scattered by the qgather transpose already)
        g_leaves, treedef = jax.tree.flatten(grads)
        out = []
        for leaf, (dim, gdt, _pdt) in zip(g_leaves, plan_leaves, strict=True):
            if dim is not None:
                out.append(leaf)  # reduce-scattered by the qgather transpose
            elif gdt == "none" or n == 1:
                out.append(jax.lax.psum(leaf, dp_axes) if n > 1 else leaf)
            else:
                out.append(ring_all_reduce(leaf, dp_axes, sizes,
                                           dtype=gdt, block=block))
        return loss, jax.tree.unflatten(treedef, out)

    def loss_and_grads(params, batch):
        batch_specs = model.batch_specs(batch)
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(p_specs, batch_specs),
            out_specs=(P(), p_specs),
            axis_names=set(dp_axes),
        )(params, batch)

    return loss_and_grads


# ============================================================= measurement
def bytes_on_wire_mb(hp: HybridParallelConfig, param_mb_per_layer: float) -> Dict[str, float]:
    """Estimated per-step gradient-sync traffic in MB (sum over layers of
    ring volume x wire bytes), fp32-grads baseline vs the strategy's comm
    dtypes — the bench's bytes-on-wire estimate and the README's worked
    numbers come from here."""
    out = {"fp32": 0.0, "configured": 0.0}
    for i, s in enumerate(hp.layers):
        d = hp.dp(i)
        if d <= 1:
            continue
        ring = 2.0 * (d - 1) / d
        out["fp32"] += ring * param_mb_per_layer
        out["configured"] += ring * param_mb_per_layer * (
            wire_bytes_per_element(s.grad_comm_dtype, hp.comm_quant_block) / 4.0)
    return {k: round(v, 3) for k, v in out.items()}


def measure_quant_overhead_ms(shape=(1 << 18,), dtype: str = "int8",
                              block: int = 64, iters: int = 5) -> float:
    """Wall-clock of one jitted quantize+dequantize round trip over a
    ``shape`` fp32 buffer — the per-pass overhead coefficient the
    TimeCostModel's comm-precision axis charges (ms; profiling helper for
    the hardware profiler and the quant_comm telemetry event, never on the
    training hot path)."""
    import time as _time

    x = jnp.arange(int(np.prod(shape)), dtype=jnp.float32).reshape(shape) * 1e-3

    @jax.jit
    def roundtrip(v):
        p, sc = quantize_blockwise(v, dtype, block)
        return dequantize_blockwise(p, sc, v.shape)

    jax.block_until_ready(roundtrip(x))  # galv-lint: ignore[GLC005] -- timing harness: the sync IS the measurement
    ts = []
    for _ in range(max(iters, 1)):
        t0 = _time.perf_counter()
        jax.block_until_ready(roundtrip(x))  # galv-lint: ignore[GLC005] -- timing harness: the sync IS the measurement
        ts.append(_time.perf_counter() - t0)
    return min(ts) * 1e3
