"""SPMD pipeline parallelism: scan over microbatch ticks + ppermute stage shift.

TPU-native replacement for the reference's hand-rolled pipeline engine
(galvatron/core/runtime/pipeline/pipeline.py: GPipe :718-883, 1F1B :375-701,
batched P2P :1080-1257). Instead of per-rank send/recv of activations, the
whole pipeline is ONE jitted SPMD program:

- layer parameters are *stacked across stages* with a leading ``pp`` dim
  sharded over the ``pp`` mesh axis, so stage s's weights live only on its
  devices;
- activations live in a ``(pp, mb, S, H)`` rolling buffer, also ``pp``-sharded;
- each scan tick vmaps the stage body over the pp dim (GSPMD partitions it so
  every stage group computes only its own slice — MPMD from vmap+sharding),
  then ``jnp.roll`` shifts outputs to the next stage: XLA lowers the roll of a
  pp-sharded buffer to a single collective-permute over ICI, the analogue of
  the reference's `batch_isend_irecv` p2p (pipeline.py:1095-1127);
- microbatch t enters stage 0 at tick t and exits stage pp-1 at tick t+pp-1;
  total ticks = num_microbatches + pp - 1 (the GPipe bubble).

The backward pass is jax autodiff through the scan — including the reversed
collective-permutes — which also makes tied-embedding gradients (used by both
stage 0 and the last stage) correct with no embedding-group all-reduce
(reference grad_reduce.py:68-124).

This module is the GPipe schedule; `pipeline_type="pipedream_flush"` runs the
true 1F1B engine in parallel/pipeline_1f1b.py (bounded activation stash,
hand-written backward, heterogeneous per-stage strategies).

GPipe-scan restrictions (asserted): equal layers per stage; within-stage layer
strategies — including checkpoint flags — uniform across stages (the vmapped
body is one program; heterogeneous configs must use 1F1B); no ring-attention
CP inside pp>1.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import PP_AXIS, layer_axes, vocab_axes

Params = Dict[str, Any]


def validate_pipeline_config(hp: HybridParallelConfig):
    if hp.pp <= 1:
        return
    div = hp.pp_division
    if len(set(div)) != 1:
        raise ValueError(
            "pipelined execution requires equal layers per stage, got pp_division=%s "
            "(pad the model or use pp_division of equal parts)" % (div,)
        )
    lps = div[0]
    for j in range(lps):
        strategies = {hp.layers[s * lps + j] for s in range(hp.pp)}
        if len(strategies) != 1:
            raise ValueError(
                "within-stage layer %d must use the same strategy on every stage "
                "for the gpipe scan pipeline (use pipeline_type='pipedream_flush' "
                "for per-stage heterogeneous strategies); got %s" % (j, strategies)
            )
    for s in hp.layers:
        if s.cp > 1:
            raise ValueError(
                "cp>1 with pp>1 runs through the 1F1B engine "
                "(pipeline_type='pipedream_flush'), not the scan pipeline: "
                "the vmapped body here computes attention without the ring "
                "shard_map, which is wrong for zigzag-permuted cp layouts"
            )
    if hp.global_bsz % hp.chunks != 0:
        raise ValueError("global_bsz must divide into chunks")


def layers_per_stage(hp: HybridParallelConfig) -> int:
    """Slot count of the stacked layout: max layers on any stage. Equal
    divisions (the gpipe contract) make every slot live on every stage;
    the 1F1B engine also accepts UNEVEN divisions (reference slices
    arbitrary model_ranks, pipeline.py:110-112) — stages with fewer layers
    hold zero-filled padding in the trailing slots, statically skipped by
    their stage body and receiving exactly-zero gradients."""
    return max(hp.pp_division)


def stage_layer_offsets(hp: HybridParallelConfig) -> List[int]:
    """Global index of each stage's first layer."""
    out, acc = [], 0
    for n in hp.pp_division:
        out.append(acc)
        acc += n
    return out


# ------------------------------------------------------- stacked param layout
def stack_layer_specs(cfg, hp: HybridParallelConfig):
    """Param specs for the stacked layout: for each within-stage layer index j,
    the per-layer spec prefixed with the pp axis."""
    from galvatron_tpu.models.base import layer_param_specs

    lps = layers_per_stage(hp)
    out = []
    for j in range(lps):
        # storage-layout hint only: slot j is keyed to GLOBAL layer j's axes
        # (always valid: max(div) <= total layers); the within-stage layout
        # is resolved by GSPMD inside the manual-over-pp shard_map, and the
        # stage bodies reshard per layer
        ax = layer_axes(hp, j)
        spec_j = layer_param_specs(cfg, ax)
        out.append(jax.tree.map(lambda sp: P(PP_AXIS, *sp), spec_j, is_leaf=lambda x: isinstance(x, P)))
    return out


def stack_params(layer_params: List[Params], hp: HybridParallelConfig) -> List[Params]:
    """[n_layers trees] -> [layers_per_stage trees with leading pp dim].
    Uneven divisions pad the short stages' trailing slots with zeros (all
    layers of a family share one tree shape)."""
    lps = layers_per_stage(hp)
    offs = stage_layer_offsets(hp)
    zero = jax.tree.map(jnp.zeros_like, layer_params[0])
    stacked = []
    for j in range(lps):
        per_stage = [
            layer_params[offs[s] + j] if j < hp.pp_division[s] else zero
            for s in range(hp.pp)
        ]
        stacked.append(jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage))
    return stacked


def unstack_params(stacked: List[Params], hp: HybridParallelConfig) -> List[Params]:
    offs = stage_layer_offsets(hp)
    layers: List[Params] = [None] * len(hp.layers)  # type: ignore
    for j, tree in enumerate(stacked):
        for s in range(hp.pp):
            if j < hp.pp_division[s]:
                layers[offs[s] + j] = jax.tree.map(lambda x: x[s], tree)
    return layers


# ----------------------------------------------------------------- the engine
def pipeline_apply(
    stacked_layers: List[Params],
    x_mb: jax.Array,  # (num_mb, mb, S, H) embedded microbatches
    positions_mb: jax.Array,  # (num_mb, mb, S)
    cfg,
    hp: HybridParallelConfig,
    mesh: Mesh,
    attn_bias_mb: Optional[jax.Array] = None,  # (num_mb, mb, 1, 1, S)
) -> jax.Array:
    """Run the scan pipeline; returns (num_mb, mb, S, H) last-stage outputs."""
    from galvatron_tpu.models.base import layer_forward

    pp, num_mb = hp.pp, hp.chunks
    lps = layers_per_stage(hp)

    # the mask is threaded through the scan only when present — a None here is
    # a trace-time constant, so maskless runs keep `bias is None` inside
    # layer_forward and the flash-attention dispatch stays eligible
    use_bias = attn_bias_mb is not None

    def stage_body(stage_layers: List[Params], x, pos, bias=None):
        for j in range(lps):
            fwd = partial(layer_forward, cfg=cfg, mesh=None, axes=None, attn_bias=bias)
            if hp.layers[j].checkpoint:
                fwd = jax.checkpoint(fwd)
            x = fwd(stage_layers[j], x, pos)
        return x

    vstage = jax.vmap(stage_body, in_axes=(0, 0, 0, 0) if use_bias else (0, 0, 0))

    ax0 = layer_axes(hp, 0)
    buf_spec = P(PP_AXIS, S._ax(ax0.batch_axes), S._ax(ax0.seq_axes), None)
    pos_buf_spec = P(PP_AXIS, S._ax(ax0.batch_axes), S._ax(ax0.seq_axes))

    mb_shape = x_mb.shape[1:]
    total = num_mb + pp - 1
    pad = total - num_mb

    def padded(t):
        return jnp.concatenate([t, jnp.zeros((pad,) + t.shape[1:], t.dtype)], 0)

    carry0 = [jnp.zeros((pp,) + mb_shape, x_mb.dtype),
              jnp.zeros((pp,) + positions_mb.shape[1:], positions_mb.dtype)]
    xs = [padded(x_mb), padded(positions_mb)]
    if use_bias:
        carry0.append(jnp.zeros((pp,) + attn_bias_mb.shape[1:], attn_bias_mb.dtype))
        xs.append(padded(attn_bias_mb))

    def tick(carry, xt):
        # shift previous outputs to the next stage; microbatch enters stage 0.
        shifted = [jnp.roll(c, 1, axis=0).at[0].set(inp) for c, inp in zip(carry, xt)]
        shifted[0] = S.constrain(shifted[0], mesh, buf_spec)
        shifted[1] = S.constrain(shifted[1], mesh, pos_buf_spec)
        out = vstage(stacked_layers, *shifted)
        out = S.constrain(out, mesh, buf_spec)
        return [out] + shifted[1:], out[-1]

    _, ys = jax.lax.scan(tick, carry0, tuple(xs))
    return ys[pp - 1 :]


def make_pipelined_loss(cfg, hp: HybridParallelConfig, mesh: Mesh):
    """Loss over the pipelined model; batch is split into `chunks` microbatches
    INSIDE this function, so the train step's grad-accumulation loop must not
    split again (model_api handles this). Serves every head type of the
    generic tree (lm / mlm / classification — the reference's per-model `Cls_`
    stages, GPTModel_sequential.py:201-215)."""
    from galvatron_tpu.models import base as M

    validate_pipeline_config(hp)
    vax = vocab_axes(hp)

    def loss_fn(params, batch):
        num_mb = hp.chunks
        if cfg.input_type == "patches":
            inputs = batch["pixels"]
            x = M.embed_patches(params["embed"], inputs, cfg)
            positions = jnp.zeros(x.shape[:2], jnp.int32)
        else:
            inputs = batch["tokens"]
            positions = batch["positions"]
            x = M.embed_tokens(params["embed"], inputs, positions, cfg, mesh, vax,
                               token_type_ids=batch.get("token_type_ids"))
        B = x.shape[0]
        mb = B // num_mb

        # jax 0.4.37 GSPMD hazard (sibling of the stack_layer_run finding in
        # models/base.py): reshaping a dp-SHARDED batch dim into
        # (num_mb, mb, ...) and feeding the result straight into the tick
        # scan MISCOMPILES — silently wrong values, no error, and only when
        # the incoming batch is sharded (an unsharded batch computes the
        # pp=1 loss exactly; measured 4e-4 loss drift in float64, the
        # test_pipeline_matches_dp failures). Pinning the microbatch layout
        # explicitly (microbatch dim unsharded, per-microbatch batch dim on
        # the dp axes) right after the reshape makes the result
        # layout-independent again; tests pin this parity.
        def split(t, seq_dim=2):
            r = t.reshape((num_mb, mb) + t.shape[1:])
            entries = [None, S._ax(vax.batch_axes)] + [None] * (r.ndim - 2)
            if seq_dim is not None and r.ndim > seq_dim:
                entries[seq_dim] = S._ax(vax.seq_axes)
            return S.constrain(r, mesh, P(*entries))

        bias_mb = None
        if batch.get("attn_mask") is not None:
            # the bias' trailing dim is key positions, not the activation
            # sequence layout — keep it (and the singleton dims) unsharded
            bias_mb = split(M.padding_attn_bias(batch["attn_mask"]), seq_dim=None)
        # embed all microbatches up-front (replicated across pp groups; the
        # vocab layers' own parallelism comes from vocab_tp/vocab_sp axes)
        outs = pipeline_apply(params["stages"], split(x), split(positions), cfg, hp, mesh,
                              attn_bias_mb=bias_mb)
        h = outs.reshape((B,) + x.shape[1:])
        h = S.constrain(h, mesh, S.act_spec(vax))
        logits = M.model_head(params, h, cfg)
        if cfg.head_type == "classification":
            return M.softmax_nll(logits, batch["labels"])
        logits = S.constrain(logits, mesh, S.logits_spec(vax))
        return M.vocab_parallel_cross_entropy(logits, batch["labels"], batch.get("loss_mask"))

    return loss_fn
