"""Fault tolerance for long-running hybrid-parallel training.

Galvatron's value proposition is multi-day hybrid-parallel runs, and the
failures those runs actually see are not exotic: TPU preemption (SIGTERM with
a grace window), a NaN/Inf loss from a poisoned batch or a flaky chip, and
transient filesystem/tensorstore errors during checkpoint I/O. The reference
runtime assumes save/resume just works; the Galvatron-2 execution engine
calls out fault recovery as first-class. This module supplies the pieces the
driver (cli/train.py) wires together:

- :class:`PreemptionHandler` — converts SIGTERM/SIGINT into a flag the train
  loop polls at step boundaries, so an emergency ``save_checkpoint`` happens
  on a *consistent* params/opt_state snapshot and the process exits cleanly
  (exit code 0) instead of dying mid-collective.
- :class:`AnomalyGuard` — host-side accounting for the in-step anomaly gate
  (``make_train_step(guard_anomalies=True)`` keeps old params/opt_state when
  the loss or grad norm is non-finite or the loss exceeds a spike cap; the
  step functions donate their inputs, so the keep-old select MUST live inside
  the jitted step). The guard tracks an EMA of accepted losses to arm the
  spike cap, counts consecutive strikes, and signals rollback after N.
- :func:`with_retry` — exponential backoff around checkpoint save/restore and
  dataloader I/O for transient ``OSError``-family failures.
- :class:`ResilienceCounters` — anomalies/rollbacks/retries/emergency-saves
  counters surfaced in the profiler summary dict.
- :class:`FaultHooks` — the deterministic fault-injection seam used by
  tests/runtime/fault_injection.py (wrap the data iterator, wrap the step
  function, observe step boundaries). Production runs leave it unset.

Checkpoint integrity (the atomic manifest that detects torn saves) lives in
runtime/checkpoint.py; this module only decides *when* to save, retry, and
roll back.
"""

from __future__ import annotations

import dataclasses
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np


class TrainingAnomalyError(RuntimeError):
    """Raised when anomalies persist beyond what rollback can repair
    (no checkpoint to roll back to, or the rollback budget is exhausted)."""


# ------------------------------------------------------------------ counters
@dataclass
class ResilienceCounters:
    """Resilience event counts, merged into the profiler summary dict."""

    anomalies_skipped: int = 0
    rollbacks: int = 0
    retries: int = 0
    retries_succeeded: int = 0  # operations that failed, backed off, then made it
    retries_exhausted: int = 0  # operations that gave up (budget or elapsed cap)
    emergency_saves: int = 0
    torn_checkpoints_skipped: int = 0
    # silent-corruption sentinel (runtime/sdc.py)
    sdc_checks: int = 0  # digest observations emitted to telemetry
    sdc_mismatches: int = 0  # drain-time replica-vote disagreements
    sdc_reexecutions: int = 0  # repair-from-replica + re-execute recoveries
    sdc_quarantines: int = 0  # devices convicted by the strike ladder

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------- retry
@dataclass
class RetryPolicy:
    """Exponential backoff for transient I/O failures (filesystem flakes,
    tensorstore timeouts). `retries` is the number of RE-attempts after the
    first failure; delays are base * multiplier**attempt, capped per-sleep
    by `max_delay_s` and in TOTAL by `max_elapsed_s`.

    `jitter` applies full jitter (delay drawn uniformly from [0, backoff])
    — with many workers retrying the same flaky filesystem, synchronized
    exponential backoff re-creates the thundering herd every 2^k seconds;
    full jitter decorrelates them. `max_elapsed_s` bounds the whole retry
    episode (sleeps + attempts measured on `clock`) so a restore-side retry
    chain cannot outlive a preemption grace window: when the budget is
    spent, the last error propagates immediately instead of sleeping into
    the SIGKILL."""

    retries: int = 2
    base_delay_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 8.0
    max_elapsed_s: Optional[float] = None
    jitter: bool = True
    retryable: Tuple[type, ...] = (OSError,)


def with_retry(
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    counters: Optional[ResilienceCounters] = None,
    description: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    log_fn: Callable[[str], None] = print,
    rng: Callable[[], float] = random.random,
    clock: Callable[[], float] = time.monotonic,
):
    """Run `fn()`; on a retryable exception, back off (full jitter unless
    the policy disables it) and retry up to `policy.retries` times within
    `policy.max_elapsed_s` total. Non-retryable exceptions propagate
    immediately; the last retryable one propagates after the budget. Each
    backoff is logged through `log_fn` and recorded as a ``retry`` telemetry
    event when a sink is active; `counters` distinguishes episodes that
    eventually succeeded (`retries_succeeded`) from those that gave up
    (`retries_exhausted`)."""
    from galvatron_tpu.obs import telemetry

    policy = policy or RetryPolicy()
    attempt = 0
    t_start = clock()
    while True:
        try:
            out = fn()
            if attempt > 0 and counters is not None:
                counters.retries_succeeded += 1
            return out
        except policy.retryable as e:
            if attempt >= policy.retries:
                if counters is not None:
                    counters.retries_exhausted += 1
                raise
            delay = min(policy.base_delay_s * policy.multiplier**attempt, policy.max_delay_s)
            if policy.jitter and delay > 0:
                delay = rng() * delay
            if policy.max_elapsed_s is not None and (
                clock() - t_start + delay > policy.max_elapsed_s
            ):
                # sleeping would overrun the grace window — give up NOW with
                # the real error, leaving the caller time to act on it
                if counters is not None:
                    counters.retries_exhausted += 1
                log_fn(
                    "resilience: %s failed (%s: %s); retry budget elapsed "
                    "(%.2fs of %.2fs) — giving up"
                    % (description, type(e).__name__, e, clock() - t_start,
                       policy.max_elapsed_s)
                )
                raise
            if counters is not None:
                counters.retries += 1
            log_fn(
                "resilience: %s failed (%s: %s); retry %d/%d in %.2fs"
                % (description, type(e).__name__, e, attempt + 1, policy.retries, delay)
            )
            telemetry.emit(
                "retry", description=description, attempt=attempt + 1,
                error="%s: %s" % (type(e).__name__, e), delay_s=delay,
            )
            sleep(delay)
            attempt += 1


# ---------------------------------------------------------------- preemption
class PreemptionHandler:
    """SIGTERM/SIGINT -> a flag polled at step boundaries.

    TPU preemption delivers SIGTERM with a grace window; a first Ctrl-C asks
    for a graceful stop the same way. The handler only records the signal —
    the train loop finishes the in-flight step, writes an emergency
    checkpoint, and returns normally (clean exit code). A second SIGINT
    raises KeyboardInterrupt so a stuck save can still be aborted."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._signum: Optional[int] = None
        self._prev: Dict[int, object] = {}
        self._installed = False

    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            return self  # signal handlers only work on the main thread
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def _handle(self, signum, frame):
        if self._signum is not None and signum == signal.SIGINT:
            raise KeyboardInterrupt
        self._signum = signum

    @property
    def triggered(self) -> bool:
        return self._signum is not None

    @property
    def signal_name(self) -> Optional[str]:
        return signal.Signals(self._signum).name if self._signum is not None else None


# ------------------------------------------------------------- anomaly guard
@dataclass
class AnomalyGuardConfig:
    spike_factor: float = 0.0  # anomaly when loss > spike_factor * EMA; 0 = off
    ema_beta: float = 0.9
    min_history: int = 5  # accepted losses before the spike cap arms
    max_strikes: int = 3  # consecutive anomalies before rollback
    max_rollbacks: int = 3  # rollbacks before giving up (TrainingAnomalyError)


class AnomalyGuard:
    """Host-side half of the anomaly gate.

    The jitted step already refused to apply a non-finite / spiking update
    (make_train_step(guard_anomalies=True)); this object reads the step's
    loss, maintains the accepted-loss EMA that feeds the next step's spike
    cap, and counts consecutive strikes to decide when skipping is no longer
    enough and the loop must roll back to the last checkpoint."""

    def __init__(self, cfg: Optional[AnomalyGuardConfig] = None):
        self.cfg = cfg or AnomalyGuardConfig()
        self.ema: Optional[float] = None
        self.accepted = 0
        self.strikes = 0

    def spike_cap(self) -> float:
        """The loss ceiling the NEXT step's update must stay under; +inf
        until spike detection is configured and armed."""
        if self.cfg.spike_factor and self.accepted >= self.cfg.min_history and self.ema:
            return float(self.cfg.spike_factor * abs(self.ema))
        return float("inf")

    def observe(self, loss: float) -> str:
        """Classify one step's loss: "ok" | "nan" | "spike"."""
        if not np.isfinite(loss):
            self.strikes += 1
            return "nan"
        if loss > self.spike_cap():
            self.strikes += 1
            return "spike"
        self.strikes = 0
        self.accepted += 1
        self.ema = (
            loss
            if self.ema is None
            else self.cfg.ema_beta * self.ema + (1.0 - self.cfg.ema_beta) * loss
        )
        return "ok"

    @property
    def should_roll_back(self) -> bool:
        return self.strikes >= max(self.cfg.max_strikes, 1)

    def reset_after_rollback(self) -> None:
        """Restart accounting from the restored state: the EMA belongs to the
        discarded trajectory, and stale history must not arm a stale cap."""
        self.ema = None
        self.accepted = 0
        self.strikes = 0


# ----------------------------------------------------------- fault injection
@dataclass
class FaultHooks:
    """Deterministic fault-injection seam (tests/runtime/fault_injection.py).

    The driver consults `args.fault_hooks` (absent in production): the data
    iterator and step function are wrapped once per (re)build — including
    after a rollback — and `on_step(it)` fires at each step boundary before
    the batch is fetched (where the harness sends itself SIGTERM or arms a
    mid-save kill)."""

    wrap_data_iter: Optional[Callable[[Iterator, int], Iterator]] = None  # (iter, start_step)
    wrap_step_fn: Optional[Callable[[Callable], Callable]] = None
    on_step: Optional[Callable[[int], None]] = None
