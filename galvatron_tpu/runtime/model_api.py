"""Hybrid-parallel model construction and the jitted train step.

TPU-native equivalent of the reference's 6-step model assembly
(galvatron/core/runtime/hybrid_parallel_model.py:165-326: comm groups -> TP
rewrite -> sequential split -> relocation -> PipelineParallel -> FSDP -> ckpt)
and of `GalvatronModel.forward_backward` (:42-70). Here the assembly is:

  1. build one named Mesh (parallel/mesh.py — replaces gen_comm_groups);
  2. build per-layer param/activation PartitionSpecs (replaces the TP rewrite,
     FSDP wrapping, and Module_with_relocation);
  3. jit one train-step function whose gradient accumulation loop over
     microbatches replaces the GPipe/1F1B/no-pp schedule dispatch (pp>1 runs
     the scan/ppermute pipeline from parallel/pipeline.py);
  4. ZeRO grad/optimizer-state semantics are sharding constraints on the
     accumulator and the adam moments (replaces grad_reduce.py's no_sync +
     manual FSDP flush).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.models import base as M
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import build_mesh, layer_axes, vocab_axes
from galvatron_tpu.runtime.optimizer import opt_state_specs

Params = Dict[str, Any]


def _is_spec(x):
    return isinstance(x, P)


@dataclass
class HybridParallelModel:
    cfg: M.TransformerConfig
    hp: HybridParallelConfig
    mesh: Mesh
    param_specs: Params
    loss_fn: Callable  # (params, batch) -> loss
    forward_fn: Callable  # (params, batch) -> logits
    init_fn: Optional[Callable] = None  # (rng) -> params; families with their
    # own param tree (t5/swin) supply this instead of base.init_model_params
    grad_fn: Optional[Callable] = None  # (params, batch) -> (loss, grads);
    # set by the 1f1b pipeline, whose hand-written schedule produces gradients
    # directly instead of going through jax.value_and_grad
    eval_loss_fn: Optional[Callable] = None  # forward-only (params, batch) ->
    # loss for evaluation: under the 1f1b engines, loss_fn is the grad-bearing
    # schedule (loss and grads come out of one scan, so XLA cannot DCE the
    # backward); this is the cheap path (reference evaluation is forward-only)
    local_loss_fn: Optional[Callable] = None  # the CONSTRAINT-FREE local
    # loss (models/base loss_fns with hp=None/mesh=None): the body of the
    # quantized grad-sync shard_map (parallel/quant_collectives.py), where
    # each dp shard computes grads on its local batch with no
    # with_sharding_constraint in scope. Base families only; None refuses
    # the quantized path with GLS013.
    # memoized NamedSharding trees per batch signature (key set + ranks), so
    # the per-step shard_batch is ONE device_put of the whole tree with no
    # per-key NamedSharding construction on the hot path
    _batch_shardings: Dict[Tuple, Dict[str, NamedSharding]] = field(
        default_factory=dict, repr=False)

    @property
    def eval_loss(self) -> Callable:
        """The loss to use for evaluation: forward-only when available."""
        return self.eval_loss_fn or self.loss_fn

    # ------------------------------------------------------------------ params
    def shardings(self, specs=None):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs if specs is not None else self.param_specs,
            is_leaf=_is_spec,
        )

    def _init_fn(self, rng) -> Params:
        if self.init_fn is not None:
            return self.init_fn(rng)
        params = M.init_model_params(rng, self.cfg)
        if self.hp.pp > 1:
            from galvatron_tpu.parallel.pipeline import stack_params

            params["stages"] = stack_params(params.pop("layers"), self.hp)
        return params

    def abstract_params(self) -> Params:
        """Abstract (ShapeDtypeStruct) params tree for this model — the
        shared currency of cross-layout checkpoint restore and live
        in-memory migration (structure + shapes, no device work)."""
        return jax.eval_shape(self._init_fn, jax.random.PRNGKey(0))

    def init_params(self, rng) -> Params:
        """Sharded init: jit with out_shardings so each device materialises
        only its shard (the analogue of meta-device init + shard streaming,
        reference runtime/initialize.py:8-112)."""
        if self.init_fn is None and self.hp.pp > 1:
            # jax 0.4.37 GSPMD hazard: fusing the per-layer init with the
            # jnp.stack into `stages` in ONE jitted program whose
            # out_shardings put the pp axis on the new stacked dim produces
            # silently wrong values in some stacked entries (eager init is
            # correct; measured 0.2-0.3 absolute error on layer kernels,
            # the test_pipelined_bert_mlm parity failure). Init the
            # canonical per-layer tree jitted, stack it op-by-op OUTSIDE
            # the jitted program, then place onto the stacked shardings —
            # the same path the parity-test fixtures use.
            from galvatron_tpu.parallel.pipeline import stack_params

            params = jax.jit(lambda r: M.init_model_params(r, self.cfg))(rng)
            params["stages"] = stack_params(params.pop("layers"), self.hp)
            return jax.device_put(params, self.shardings())
        init = jax.jit(self._init_fn, out_shardings=self.shardings())
        return init(rng)

    def _batch_spec_for(self, x) -> P:
        """(B, S) token-shaped entries shard over (dp, seq); rank-1 labels over
        dp; higher-rank entries (pixels) shard batch only."""
        vax = vocab_axes(self.hp)
        ndim = getattr(x, "ndim", None) or len(getattr(x, "shape", ()))
        if ndim == 2:
            return P(S._ax(vax.batch_axes), S._ax(vax.seq_axes))
        if ndim == 1:
            return P(S._ax(vax.batch_axes))
        return P(*([S._ax(vax.batch_axes)] + [None] * (ndim - 1)))

    def batch_specs(self, batch_example: Dict[str, Any]):
        return {k: self._batch_spec_for(v) for k, v in batch_example.items()}

    def shard_batch(self, batch):
        """One sharded transfer for the whole batch: the sharding tree is
        precomputed per batch signature and the entire dict goes through a
        single ``jax.device_put`` — no per-key Python round trips, and the
        runtime can overlap the per-leaf copies (the prefetch thread issues
        this ahead of the step that consumes it)."""
        sig = tuple(sorted(
            (k, getattr(v, "ndim", None) or len(getattr(v, "shape", ())))
            for k, v in batch.items()
        ))
        shardings = self._batch_shardings.get(sig)
        if shardings is None:
            shardings = {
                k: NamedSharding(self.mesh, self._batch_spec_for(v))
                for k, v in batch.items()
            }
            self._batch_shardings[sig] = shardings
        return jax.device_put(batch, shardings)

    # -------------------------------------------------------------- train step
    def zero_axes_tree(self):
        """Per-param dp axes over which to shard adam moments (ZeRO-1/2/3)."""

        def for_axes(ax, tree):
            zax = tuple(ax.dp) if ax.zero_opt else ()
            return jax.tree.map(lambda _: zax, tree)

        ps = self.param_specs
        vax = vocab_axes(self.hp)
        layer_lists = ("layers", "stages", "enc_layers", "dec_layers", "blocks")
        out = {}
        offset = 0
        for key, sub in ps.items():
            if key in layer_lists:
                out[key] = [
                    for_axes(layer_axes(self.hp, offset + i), sub[i]) for i in range(len(sub))
                ]
                offset += len(sub)
            else:
                out[key] = for_axes(vax, sub)
        return out

    def grad_accum_specs(self):
        """Accumulated-grad shardings: dp-sharded wherever ZeRO applies, so the
        per-microbatch reduction is a reduce-scatter not an all-reduce
        (reference grad_reduce.py:47-64 no-sync + flush semantics)."""
        shapes = self.abstract_params()
        mesh_shape = dict(self.mesh.shape)
        from galvatron_tpu.runtime.optimizer import _shard_moment_spec

        return jax.tree.map(
            lambda spec, shp, zax: _shard_moment_spec(spec, shp.shape, tuple(zax), mesh_shape),
            self.param_specs,
            shapes,
            self.zero_axes_tree(),
            is_leaf=_is_spec,
        )

    def make_train_step(self, tx: optax.GradientTransformation, *,
                        guard_anomalies: bool = False, donate: bool = True,
                        sdc_check: str = "off"):
        """The jitted (params, opt_state, batch[, spike_cap]) -> (params,
        opt_state, metrics) step. With `guard_anomalies` the step takes a
        fourth `spike_cap` scalar and refuses to apply an update whose loss
        or grad norm is non-finite or whose loss exceeds the cap: params and
        opt_state pass through unchanged and metrics["anomalous"] is set.
        The select must live INSIDE the step — inputs are donated, so the
        host cannot keep the old state around to retry with.

        `donate=False` keeps params/opt_state un-donated (two resident
        copies of the model state). It exists for the dispatch-ahead loop on
        XLA:CPU, whose runtime executes a call synchronously whenever a
        donated input buffer is still being produced by the previous call —
        donation there serializes host and device no matter how far ahead
        the host dispatches. TPU runtimes handle donated futures
        asynchronously, so production keeps the default.

        `sdc_check` (runtime/sdc.py) adds silent-corruption side-outputs.
        "digest": metrics gain the layout-invariant integrity fold +
        sum-of-squares of the returned params — near-zero cost, composes
        with donation, pp scan, and every tp_comm_mode; the update program
        is untouched, so sentinel-on and sentinel-off trajectories stay
        bitwise identical. "vote" additionally digests each device's
        *input-param* replica under a shard_map manual over the dp axes
        (metrics["sdc_votes"], flat order = sdc.vote_device_ids) and
        freezes params/opt_state through the same keep-old select the
        anomaly guard uses whenever the replicas disagree
        (metrics["sdc_mismatch"]) — a lying device cannot leak into the
        psummed update, and the driver repairs + re-executes at drain time.
        Voting requires sdc.vote_supported; callers downgrade to "digest"
        on unsupported layouts (the driver logs it, strategy_lint warns
        GLS103). Note the manual shard_map region legally shifts GSPMD
        partitioning decisions for the rest of the module, so a "vote" run
        may differ from an "off" run in last-bit float rounding; it is
        deterministic, and re-execution after a repair is bitwise identical
        to a clean run *in the same mode* — which is the comparison the
        fault sims make."""
        hp, mesh = self.hp, self.mesh
        from galvatron_tpu.runtime import sdc as SDC

        if sdc_check not in SDC.SDC_MODES:
            raise ValueError("sdc_check must be one of %r, got %r"
                             % (SDC.SDC_MODES, sdc_check))
        vote_fn = None
        if sdc_check == "vote":
            reason = SDC.vote_reason(hp)
            if reason is not None:
                raise ValueError(
                    "sdc_check='vote' unsupported for this layout (%s); "
                    "callers should downgrade to 'digest'" % reason)
            vote_fn = SDC.make_vote_digest_fn(self)
        # pp>1: the scan pipeline consumes the whole batch as `chunks`
        # microbatches itself — no outer accumulation loop.
        chunks = 1 if hp.pp > 1 else hp.chunks
        accum_shardings = self.shardings(self.grad_accum_specs())

        # quantized comm-precision path (parallel/quant_collectives.py): the
        # strategy's per-layer grad/param comm dtypes route the whole
        # loss+grad computation through the explicit shard_map grad ring.
        # Unsupported configs refuse with GLS013 here (and at lint time);
        # the guard combination is part of that refusal contract.
        quant_fn = None
        from galvatron_tpu.parallel import quant_collectives as QC

        if self.grad_fn is None and QC.wants_quant_comm(hp):
            QC.assert_quant_comm_supported(self.cfg, hp,
                                           anomaly_guard=guard_anomalies)
            quant_fn = QC.make_quant_loss_and_grads(self)

        def train_step(params, opt_state, batch, spike_cap=None):
            def mb_loss(p, mb):
                return self.loss_fn(p, mb)

            if self.grad_fn is not None:
                # 1f1b pipeline: loss and grads come out of the hand-written
                # warmup/steady/cooldown schedule in one pass. The reshard to
                # accumulator shardings happens HERE, outside the schedule's
                # scan, so no ZeRO dp-sharding constraint can propagate into
                # its stage-divergent branches; the per-leaf reshards are
                # chained so independent global collectives cannot be entered
                # in different orders by stages whose executor timelines
                # diverged in the schedule (see the divergence-safety notes in
                # pipeline_1f1b.make_loss_and_grad).
                loss, grads = self.grad_fn(params, batch)
                leaves, treedef = jax.tree.flatten(grads)
                slvs = jax.tree.leaves(accum_shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
                out, prev = [], None
                for g, s in zip(leaves, slvs, strict=True):
                    if prev is not None:
                        g = jax.lax.optimization_barrier((g, prev))[0]
                    g = jax.lax.with_sharding_constraint(g, s)
                    out.append(g)
                    prev = g
                grads = jax.tree.unflatten(treedef, out)
            elif quant_fn is not None:
                # explicit quantized grad sync: microbatching and the dp
                # reduction happen inside the shard_map body; the grads come
                # out already in the accumulator shardings (the constraints
                # below are no-ops that keep the update program identical)
                loss, grads = quant_fn(params, batch)
                grads = jax.tree.map(
                    lambda g, s: jax.lax.with_sharding_constraint(g, s), grads, accum_shardings
                )
            elif chunks == 1:
                loss, grads = jax.value_and_grad(mb_loss)(params, batch)
                grads = jax.tree.map(
                    lambda g, s: jax.lax.with_sharding_constraint(g, s), grads, accum_shardings
                )
            else:
                # microbatch loop: python-unrolled so XLA can overlap each
                # microbatch's reduce-scatter with the next one's compute
                # (the reference's async_grad_reduce, runtime/arguments.py).
                def split(x):
                    return x.reshape((chunks, x.shape[0] // chunks) + x.shape[1:])

                mbs = jax.tree.map(split, batch)
                # per-microbatch weights: each microbatch loss is a mean over
                # its own valid tokens, so weight by its share of the valid
                # tokens to keep the chunked objective identical to chunks=1
                if "loss_mask" in batch:
                    mask_sums = jnp.sum(
                        mbs["loss_mask"].astype(jnp.float32), axis=tuple(range(1, batch["loss_mask"].ndim + 1))
                    )
                    weights = mask_sums / jnp.maximum(jnp.sum(mask_sums), 1.0)
                else:
                    weights = jnp.full((chunks,), 1.0 / chunks, jnp.float32)
                grads = None
                loss = 0.0
                for c in range(chunks):
                    mb = jax.tree.map(lambda x: x[c], mbs)
                    l, g = jax.value_and_grad(mb_loss)(params, mb)
                    w = weights[c]
                    g = jax.tree.map(
                        lambda gi, s: jax.lax.with_sharding_constraint(gi * w, s),
                        g,
                        accum_shardings,
                    )
                    grads = g if grads is None else jax.tree.map(jnp.add, grads, g)
                    loss = loss + l * w
            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            grad_norm = optax.global_norm(grads)
            metrics = {"loss": loss, "grad_norm": grad_norm}
            if guard_anomalies:
                bad = jnp.logical_or(
                    jnp.logical_or(~jnp.isfinite(loss), ~jnp.isfinite(grad_norm)),
                    loss > spike_cap,
                )
                keep = lambda new, old: jnp.where(bad, old, new)  # noqa: E731
                new_params = jax.tree.map(keep, new_params, params)
                # the skipped step also must not advance the optimizer (adam
                # moments AND the schedule counter stay put)
                new_opt_state = jax.tree.map(keep, new_opt_state, opt_state)
                metrics["anomalous"] = bad
            if vote_fn is not None:
                # per-replica digests of the INPUT params: the dp redundancy
                # the layout already pays for. On any disagreement the state
                # freezes through the same keep-old select the guard uses —
                # the lying replica stays localized to its device instead of
                # riding the psummed update onto every replica; the driver
                # repairs from a healthy replica and re-executes.
                votes = vote_fn(params)
                mismatch = jnp.any(votes != jnp.ravel(votes)[0])
                keep_sdc = lambda new, old: jnp.where(mismatch, old, new)  # noqa: E731
                new_params = jax.tree.map(keep_sdc, new_params, params)
                new_opt_state = jax.tree.map(keep_sdc, new_opt_state, opt_state)
                metrics["sdc_votes"] = votes
                metrics["sdc_mismatch"] = mismatch
            if sdc_check != "off":
                # layout-invariant digest of the state this step hands back
                # (post-select): pure side-outputs, so the trajectory is
                # bitwise identical to a sentinel-off run
                fold, sumsq = SDC.tree_fold_metrics(new_params)
                metrics["sdc_fold"] = fold
                metrics["sdc_sumsq"] = sumsq
            return new_params, new_opt_state, metrics

        donate_argnums = (0, 1) if donate else ()
        if not guard_anomalies:
            def plain_step(params, opt_state, batch):
                return train_step(params, opt_state, batch)

            return jax.jit(plain_step, donate_argnums=donate_argnums)
        return jax.jit(train_step, donate_argnums=donate_argnums)

    def opt_state_shardings(self, tx: optax.GradientTransformation, params: Params):
        state_shape = jax.eval_shape(tx.init, params)
        shapes = jax.tree.map(lambda x: x, jax.eval_shape(lambda p: p, params))
        specs = opt_state_specs(state_shape, self.param_specs, shapes, self.zero_axes_tree(), self.mesh)
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs, is_leaf=_is_spec)

    def init_opt_state(self, tx: optax.GradientTransformation, params: Params):
        return jax.jit(tx.init, out_shardings=self.opt_state_shardings(tx, params))(params)


def construct_hybrid_parallel_model(
    cfg: M.TransformerConfig,
    hp: HybridParallelConfig,
    devices=None,
    loss_fn=None,
) -> HybridParallelModel:
    mesh = build_mesh(hp, devices)
    specs = M.model_param_specs(cfg, hp)
    grad_fn = None
    eval_loss = None
    local_loss = None
    if hp.pp > 1 and hp.pipeline_type == "pipedream_flush":
        from galvatron_tpu.parallel import pipeline_1f1b
        from galvatron_tpu.parallel.pipeline import (
            make_pipelined_loss,
            stack_layer_specs,
        )

        specs = pipeline_1f1b.vocab_param_specs(cfg, hp)
        specs["stages"] = stack_layer_specs(cfg, hp)
        del specs["layers"]
        grad_fn = pipeline_1f1b.make_loss_and_grad(cfg, hp, mesh)
        base_loss = lambda p, b: grad_fn(p, b)[0]
        # forward-only eval: the gpipe scan computes the identical loss
        # without the 1F1B backward slots whenever the config fits its
        # contract (even divisions, stage-uniform strategies, no cp — it
        # validates on construction); otherwise eval falls back to the
        # grad-bearing schedule
        try:
            eval_loss = make_pipelined_loss(cfg, hp, mesh)
        except ValueError:
            eval_loss = None
        fwd = None
    elif hp.pp > 1:
        from galvatron_tpu.parallel.pipeline import make_pipelined_loss, stack_layer_specs

        specs["stages"] = stack_layer_specs(cfg, hp)
        del specs["layers"]
        base_loss = make_pipelined_loss(cfg, hp, mesh)
        fwd = None
    elif cfg.head_type == "classification":
        base_loss = lambda p, b: M.classification_loss_fn(p, b, cfg, hp, mesh)
        fwd = lambda p, b: M.model_forward(
            p, b.get("pixels", b.get("tokens")), b.get("positions"), cfg, hp, mesh,
            attn_mask=b.get("attn_mask"),
        )
        local_loss = lambda p, b: M.classification_loss_fn(p, b, cfg)
    else:
        base_loss = lambda p, b: M.lm_loss_fn(p, b, cfg, hp, mesh)
        fwd = lambda p, b: M.model_forward(
            p, b["tokens"], b["positions"], cfg, hp, mesh,
            token_type_ids=b.get("token_type_ids"), attn_mask=b.get("attn_mask"),
        )
        local_loss = lambda p, b: M.lm_loss_fn(p, b, cfg)
    if hp.pp > 1 or loss_fn is not None:
        # custom losses have no constraint-free local form; pp>1 never takes
        # the quantized path (GLS013)
        local_loss = None
    return HybridParallelModel(
        cfg=cfg,
        hp=hp,
        mesh=mesh,
        param_specs=specs,
        loss_fn=loss_fn or base_loss,
        forward_fn=fwd,
        grad_fn=grad_fn,
        eval_loss_fn=None if loss_fn is not None else eval_loss,
        local_loss_fn=local_loss,
    )
