"""Multi-host bootstrap and hybrid ICI/DCN mesh shapes.

TPU-native replacement for the reference's process bootstrap — one process
per GPU via ``torch.distributed.launch`` with MASTER_ADDR/PORT env:// init
(reference scripts/train_dist.sh:9-15, core/arguments.py:8-30) and MPI for
multi-node nccl-tests (hardware_profiler.py:361-369). On TPU pods the unit
is one process per HOST, each owning its local chips:

- `initialize_distributed` wires `jax.distributed.initialize` from flags or
  the standard env vars. On TPU pod slices JAX discovers the topology from
  the runtime with zero configuration, so every knob is optional; on
  CPU/GPU clusters pass coordinator/num_processes/process_id explicitly.
- `hybrid_mesh_shapes` splits a logical mesh shape into (ici, dcn) factors
  for `mesh_utils.create_hybrid_device_mesh`: cross-host (DCN) factors are
  taken from the MAJOR axes first — pp and major-dp ride DCN while tp/cp
  stay on the minor axes' contiguous ICI, the same major->minor convention
  as parallel/mesh.py's tp_consec assignment.

Launch procedure (documented for operators):

    # TPU pod slice (one process per host, auto-discovery):
    $ python -m galvatron_tpu train --model_type llama ...   # on every host

    # CPU/GPU cluster (explicit bootstrap, the env:// analogue):
    $ GALVATRON_COORDINATOR=host0:8476 GALVATRON_NUM_PROCESSES=4 \
      GALVATRON_PROCESS_ID=$RANK python -m galvatron_tpu train ...
"""

from __future__ import annotations

import os
from math import gcd
from typing import Optional, Sequence, Tuple

import numpy as np

import jax


def _distributed_is_initialized() -> bool:
    """jax.distributed.is_initialized() appeared after 0.4.37; on older jax
    the equivalent signal is whether the distributed client exists. Neither
    path touches jax.devices()/process_count(), so the backend stays
    uninitialized (the constraint documented in initialize_distributed)."""
    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bootstrap `jax.distributed` for multi-host runs. Returns True when a
    multi-process runtime is (now) active.

    Resolution order per knob: explicit argument > GALVATRON_* env var >
    JAX auto-discovery (TPU pod runtime / cluster plugins). Single-process
    runs (no coordinator resolvable, or num_processes == 1) are a no-op.
    Safe to call twice — a live distributed runtime short-circuits. The
    short-circuit must NOT touch jax.process_count()/jax.devices(): those
    initialize the local backend, after which jax.distributed.initialize
    raises — the bootstrap must run before any backend exists."""
    if _distributed_is_initialized():
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get("GALVATRON_COORDINATOR")
    env_np = os.environ.get("GALVATRON_NUM_PROCESSES")
    env_pid = os.environ.get("GALVATRON_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if num_processes is not None and num_processes <= 1:
        return False
    if coordinator_address is None and num_processes is None:
        # no explicit bootstrap requested; TPU pod runtimes self-initialize
        # via jax.distributed only when the operator opts in
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def hybrid_mesh_shapes(
    shape: Sequence[int], num_hosts: int
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Split a logical mesh shape into (ici_shape, dcn_shape) with
    prod(dcn) == num_hosts, taking DCN factors from the MAJOR (leading)
    axes first so pp / major-dp span hosts while minor axes (tp/cp) stay on
    intra-host ICI. Raises when the host count does not factor into the
    leading axes (e.g. 3 hosts over a pow2 mesh)."""
    rem = num_hosts
    dcn = []
    for s in shape:
        g = gcd(s, rem)
        dcn.append(g)
        rem //= g
    if rem != 1:
        raise ValueError(
            "cannot factor %d hosts into mesh shape %s (leading-axis split)"
            % (num_hosts, tuple(shape))
        )
    ici = tuple(s // d for s, d in zip(shape, dcn))
    # DCN factors must form a contiguous LEADING block: every axis before the
    # last DCN-carrying axis must be fully DCN. Otherwise a minor (tp/cp)
    # axis silently absorbs host factors — e.g. shape (3, 4) on 2 hosts would
    # put tp across DCN — the exact silent-cripple build_mesh refuses.
    last_dcn = max((i for i, d in enumerate(dcn) if d > 1), default=-1)
    if any(ici[i] > 1 for i in range(last_dcn)):
        raise ValueError(
            "host count %d does not factor into the LEADING axes of mesh "
            "shape %s (dcn=%s would put a minor axis across DCN)"
            % (num_hosts, tuple(shape), tuple(dcn))
        )
    return ici, tuple(dcn)


def dcn_granule_count(devices: Sequence[jax.Device]) -> int:
    """Number of DCN-separated device groups (slices on TPU, processes
    elsewhere); 1 means every device pair rides ICI."""
    if hasattr(devices[0], "slice_index"):
        return len({d.slice_index for d in devices})
    return len({getattr(d, "process_index", 0) for d in devices})


def device_mesh_for(
    shape: Sequence[int], devices: Sequence[jax.Device]
) -> np.ndarray:
    """Device array for a logical mesh shape: hybrid ICI/DCN placement when
    the devices span multiple DCN granules, plain ICI-aware placement
    otherwise (reference analogue: hostfile + MPI rank layout).

    The DCN granule is a TPU *slice* when the runtime reports `slice_index`
    (a multi-host pod slice is fully ICI-connected — only multislice crosses
    DCN); otherwise a *process* (CPU/GPU clusters, mocked tests)."""
    from jax.experimental import mesh_utils

    process_is_granule = not hasattr(devices[0], "slice_index")
    n_granules = dcn_granule_count(devices)
    if n_granules > 1:
        ici, dcn = hybrid_mesh_shapes(shape, n_granules)
        return mesh_utils.create_hybrid_device_mesh(
            ici, dcn, devices=list(devices), process_is_granule=process_is_granule
        )
    return mesh_utils.create_device_mesh(tuple(shape), devices=list(devices))
