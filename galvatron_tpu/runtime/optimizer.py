"""Optimizer + LR schedule.

Replaces apex FusedAdam + Megatron's OptimizerParamScheduler (reference:
galvatron/core/runtime/utils.py:137-167). On TPU, optax adamw is XLA-fused;
ZeRO-1/2 optimizer-state sharding is a *sharding of the adam moments over the
per-layer dp sub-axes* (see zero_opt_specs) rather than a different optimizer
wrapper — GSPMD inserts the gather/scatter around the elementwise update."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P


@dataclass
class OptimizerArgs:
    lr: float = 1e-4
    min_lr: float = 1e-5
    weight_decay: float = 0.01
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    clip_grad: float = 1.0
    warmup_steps: int = 100
    total_steps: int = 10000
    lr_decay_style: str = "cosine"  # cosine | linear | constant


def make_schedule(a: OptimizerArgs):
    if a.lr_decay_style == "constant":
        warm = optax.linear_schedule(0.0, a.lr, max(a.warmup_steps, 1))
        return optax.join_schedules([warm, optax.constant_schedule(a.lr)], [a.warmup_steps])
    if a.lr_decay_style == "linear":
        warm = optax.linear_schedule(0.0, a.lr, max(a.warmup_steps, 1))
        decay = optax.linear_schedule(a.lr, a.min_lr, max(a.total_steps - a.warmup_steps, 1))
        return optax.join_schedules([warm, decay], [a.warmup_steps])
    return optax.warmup_cosine_decay_schedule(
        0.0, a.lr, max(a.warmup_steps, 1), max(a.total_steps, 2), end_value=a.min_lr
    )


def _no_weight_decay(path, _leaf) -> bool:
    """Megatron convention: no decay for biases and norm scales."""
    keys = {getattr(k, "key", getattr(k, "idx", None)) for k in path}
    return not ({"bias", "scale"} & {k for k in keys if isinstance(k, str)})


def get_optimizer_and_scheduler(args: Optional[OptimizerArgs] = None):
    a = args or OptimizerArgs()
    schedule = make_schedule(a)
    tx = optax.chain(
        optax.clip_by_global_norm(a.clip_grad) if a.clip_grad and a.clip_grad > 0 else optax.identity(),
        optax.scale_by_adam(b1=a.adam_beta1, b2=a.adam_beta2, eps=a.adam_eps),
        optax.add_decayed_weights(
            a.weight_decay,
            mask=lambda params: jax.tree_util.tree_map_with_path(_no_weight_decay, params),
        )
        if a.weight_decay
        else optax.identity(),
        optax.scale_by_learning_rate(schedule),
    )
    return tx, schedule


# ------------------------------------------------------------- state sharding
def _shard_moment_spec(param_spec: P, shape, dp_axes, mesh_shape) -> P:
    """ZeRO-1/2: place the dp sub-axes on the first dim of the moment that is
    unsharded and divisible — the flat-param shard analogue of FSDP
    SHARD_GRAD_OP (reference parallel.py:107-111, cost_model.py:99-110)."""
    if not dp_axes:
        return param_spec
    entries = list(param_spec) + [None] * (len(shape) - len(param_spec))
    dp_size = 1
    for a in dp_axes:
        dp_size *= mesh_shape[a]
    used = set()
    for e in entries:
        if e is None:
            continue
        for x in (e if isinstance(e, tuple) else (e,)):
            used.add(x)
    if any(a in used for a in dp_axes):
        return param_spec  # already dp-sharded (zero3 param)
    for i, e in enumerate(entries):
        if e is None and shape[i] % dp_size == 0:
            entries[i] = dp_axes if len(dp_axes) > 1 else dp_axes[0]
            return P(*entries)
    return param_spec


def opt_state_specs(tx_state, param_specs, param_shapes, zero_axes_tree, mesh):
    """Build a sharding-spec pytree for an optax state.

    `zero_axes_tree`: per-param tuple of dp axes to shard moments over (empty
    tuple => keep the param's own sharding, i.e. pure DP)."""

    def moment_spec(ps, shape, zax):
        shp = shape.shape if hasattr(shape, "shape") else shape
        return _shard_moment_spec(ps, shp, tuple(zax), dict(mesh.shape))

    def map_state(state):
        if isinstance(state, optax.ScaleByAdamState):
            mu = jax.tree.map(moment_spec, param_specs, param_shapes, zero_axes_tree,
                              is_leaf=lambda x: isinstance(x, P))
            nu = jax.tree.map(moment_spec, param_specs, param_shapes, zero_axes_tree,
                              is_leaf=lambda x: isinstance(x, P))
            return optax.ScaleByAdamState(count=P(), mu=mu, nu=nu)
        if isinstance(state, tuple) and type(state) is not tuple:
            # other NamedTuple states: replicate scalars, param-like trees get param specs
            return jax.tree.map(lambda _: P(), state)
        if isinstance(state, tuple):
            return tuple(map_state(s) for s in state)
        return P()

    return map_state(tx_state)
