from galvatron_tpu.runtime.model_api import HybridParallelModel, construct_hybrid_parallel_model
from galvatron_tpu.runtime.optimizer import get_optimizer_and_scheduler
from galvatron_tpu.runtime.resilience import (
    AnomalyGuard,
    AnomalyGuardConfig,
    FaultHooks,
    PreemptionHandler,
    ResilienceCounters,
    RetryPolicy,
    TrainingAnomalyError,
    with_retry,
)

__all__ = [
    "HybridParallelModel",
    "construct_hybrid_parallel_model",
    "get_optimizer_and_scheduler",
    "AnomalyGuard",
    "AnomalyGuardConfig",
    "FaultHooks",
    "PreemptionHandler",
    "ResilienceCounters",
    "RetryPolicy",
    "TrainingAnomalyError",
    "with_retry",
]
