from galvatron_tpu.runtime.health import (
    WATCHDOG_EXIT_CODE,
    MeshHealthMonitor,
    Watchdog,
    WatchdogConfig,
    classify_world,
)
from galvatron_tpu.runtime.model_api import HybridParallelModel, construct_hybrid_parallel_model
from galvatron_tpu.runtime.optimizer import get_optimizer_and_scheduler
from galvatron_tpu.runtime.prefetch import PrefetchIterator, PrefetchStalledError
from galvatron_tpu.runtime.resilience import (
    AnomalyGuard,
    AnomalyGuardConfig,
    FaultHooks,
    PreemptionHandler,
    ResilienceCounters,
    RetryPolicy,
    TrainingAnomalyError,
    with_retry,
)

__all__ = [
    "HybridParallelModel",
    "construct_hybrid_parallel_model",
    "get_optimizer_and_scheduler",
    "WATCHDOG_EXIT_CODE",
    "MeshHealthMonitor",
    "Watchdog",
    "WatchdogConfig",
    "classify_world",
    "PrefetchIterator",
    "PrefetchStalledError",
    "AnomalyGuard",
    "AnomalyGuardConfig",
    "FaultHooks",
    "PreemptionHandler",
    "ResilienceCounters",
    "RetryPolicy",
    "TrainingAnomalyError",
    "with_retry",
]
