"""Distributed checkpoint save / resume with torn-write detection.

TPU-native counterpart of the reference's distributed checkpoint system
(models/llama_hf/LlamaModel_checkpoint.py:148-220: per-FSDP-module
FULL_STATE_DICT save, one file per tp-rank per layer under ``iter_N/`` plus
per-rank optimizer state and scheduler JSON). Here sharded arrays are written
through orbax/tensorstore — each host writes exactly its addressable shards,
and restore re-shards to the current mesh layout.

The reference *asserts the parallel strategy is unchanged on resume* (no
cross-strategy re-sharding, hybrid_parallel_config.py:112-124). We keep the
same guard by default (`strict_strategy=True`) but — because restore targets
are (spec, mesh)-typed abstract arrays and tensorstore reads any slice —
resume under a *different* searched strategy also works when the guard is
relaxed, which the reference cannot do.

Layout under ``<dir>/``:
    hybrid_parallel_config.json      strategy fingerprint (assert-equal on resume)
    meta.json                        model family/size, world size
    <iteration>/                     orbax composite: params, opt_state, train_meta
    manifests/<iteration>.json       post-save integrity manifest (below)

Integrity manifest
------------------
A preempted or killed process can leave a torn ``<iteration>/`` directory
that poisons the next resume. The manifest is the commit record: it is
written atomically (tmp file + ``os.replace``) *after* the orbax save
completes, so a step directory without a matching manifest is by definition
torn. Each manifest records, per item (``params`` / ``opt_state`` /
``train_meta``):

    ``digest``       sha256 over every leaf's (path, dtype, shape, bytes),
                     in deterministic flatten order (None when some shards
                     are not host-addressable, i.e. multi-host meshes)
    ``spec_digest``  sha256 over (path, dtype, shape) only
    ``num_leaves``   leaf count

plus the step metadata (iteration, save unix time). ``load_checkpoint``
verifies the manifest: a missing manifest or a value-digest mismatch marks
the step torn, and — when no explicit iteration was requested — restore
falls back to the latest *intact* step instead of crashing. A
``spec_digest`` mismatch (caller restores under different dtypes/shapes,
e.g. a precision change) skips value verification with a warning rather
than failing. Checkpoint directories written before this discipline (no
``manifests/`` dir) are accepted as-is for back-compat.

Retention: `keep_latest_k` on save (the driver's ``--keep_latest_k``)
garbage-collects the oldest step dirs and their manifests.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.utils.jsonio import read_json_config, write_json_config

MANIFEST_DIRNAME = "manifests"

# test-only seam (tests/runtime/fault_injection.py): called after the orbax
# write completes but before the manifest commit — the torn-save window a
# preemption kill actually hits
_before_manifest_write = None


def _manager(ckpt_dir: str, create: bool = False) -> ocp.CheckpointManager:
    options = ocp.CheckpointManagerOptions(create=create, enable_async_checkpointing=False)
    return ocp.CheckpointManager(os.path.abspath(ckpt_dir), options=options)


# ----------------------------------------------------------------- manifests
def _manifest_path(ckpt_dir: str, iteration: int) -> str:
    return os.path.join(ckpt_dir, MANIFEST_DIRNAME, "%d.json" % iteration)


def _tree_digests(tree: Any) -> Dict[str, Any]:
    """Per-item integrity record: value digest (None when shards are not
    addressable), structure-only digest, leaf count."""
    value = hashlib.sha256()
    spec = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    addressable = True
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path).encode()
        try:
            arr = np.asarray(jax.device_get(leaf))
        except Exception:
            addressable = False
            arr = None
        if arr is not None:
            spec.update(key + str(arr.dtype).encode() + str(arr.shape).encode())
            value.update(key + str(arr.dtype).encode() + str(arr.shape).encode())
            value.update(arr.tobytes())
        else:
            spec.update(key)
            addressable = False
    return {
        "digest": value.hexdigest() if addressable else None,
        "spec_digest": spec.hexdigest(),
        "num_leaves": len(leaves),
    }


def _meta_digest(meta: Dict[str, Any]) -> Dict[str, Any]:
    blob = json.dumps(meta, sort_keys=True).encode()
    d = hashlib.sha256(blob).hexdigest()
    return {"digest": d, "spec_digest": d, "num_leaves": 1}


def _write_manifest(ckpt_dir: str, iteration: int, items: Dict[str, Dict[str, Any]]) -> None:
    path = _manifest_path(ckpt_dir, iteration)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {"format": 1, "iteration": iteration, "saved_at": time.time(), "items": items}
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic commit: manifest exists => save completed


def read_manifest(ckpt_dir: str, iteration: int) -> Optional[Dict[str, Any]]:
    path = _manifest_path(ckpt_dir, iteration)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # a torn manifest marks the step torn too


def _has_manifest_discipline(ckpt_dir: str) -> bool:
    """False for checkpoint dirs written before the manifest era — those are
    accepted as-is (back-compat); once any manifest exists, a manifest-less
    step means a torn save."""
    return os.path.isdir(os.path.join(ckpt_dir, MANIFEST_DIRNAME))


# ---------------------------------------------------------------------- save
def save_checkpoint(
    ckpt_dir: str,
    iteration: int,
    params: Any,
    opt_state: Any = None,
    hp: Optional[HybridParallelConfig] = None,
    train_meta: Optional[Dict[str, Any]] = None,
    keep_latest_k: Optional[int] = None,
) -> None:
    """Write params (+ optimizer state + scalar train metadata) at `iteration`,
    commit the integrity manifest, then GC to the newest `keep_latest_k`."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if hp is not None:
        write_json_config(hp.to_json_dict(), os.path.join(ckpt_dir, "hybrid_parallel_config.json"))
    items = {"params": ocp.args.StandardSave(params)}
    digests = {"params": _tree_digests(params)}
    if opt_state is not None:
        items["opt_state"] = ocp.args.StandardSave(opt_state)
        digests["opt_state"] = _tree_digests(opt_state)
    if train_meta:
        items["train_meta"] = ocp.args.JsonSave(train_meta)
        digests["train_meta"] = _meta_digest(train_meta)
    with _manager(ckpt_dir, create=True) as mgr:
        if iteration in set(mgr.all_steps()):
            # re-save of an existing step (e.g. retraining over a torn step
            # after a rollback): replace it wholesale — its manifest, if any,
            # is invalidated by the overwrite either way
            mgr.delete(iteration)
            try:
                os.remove(_manifest_path(ckpt_dir, iteration))
            except OSError:
                pass
        mgr.save(iteration, args=ocp.args.Composite(**items))
        mgr.wait_until_finished()
    if _before_manifest_write is not None:
        _before_manifest_write(iteration)
    if jax.process_index() == 0:
        _write_manifest(ckpt_dir, iteration, digests)
    if keep_latest_k:
        gc_checkpoints(ckpt_dir, keep_latest_k)


def gc_checkpoints(ckpt_dir: str, keep_latest_k: int) -> List[int]:
    """Delete all but the newest `keep_latest_k` steps (and their manifests).
    Returns the deleted iterations."""
    if keep_latest_k <= 0 or jax.process_index() != 0:
        return []
    with _manager(ckpt_dir) as mgr:
        steps = sorted(mgr.all_steps())
        doomed = steps[:-keep_latest_k] if keep_latest_k < len(steps) else []
        for step in doomed:
            mgr.delete(step)
    for step in doomed:
        try:
            os.remove(_manifest_path(ckpt_dir, step))
        except OSError:
            pass
    return doomed


# ------------------------------------------------------------------- listing
def latest_iteration(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    with _manager(ckpt_dir) as mgr:
        return mgr.latest_step()


def intact_iterations(ckpt_dir: str) -> List[int]:
    """Saved steps whose manifest committed (all steps for pre-manifest
    dirs), ascending. Steps present on disk but missing from this list are
    torn."""
    if not os.path.isdir(ckpt_dir):
        return []
    with _manager(ckpt_dir) as mgr:
        steps = sorted(mgr.all_steps())
    if not _has_manifest_discipline(ckpt_dir):
        return steps
    return [s for s in steps if read_manifest(ckpt_dir, s) is not None]


def _abstract_like(tree, shardings):
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        tree,
        shardings,
    )


# ---------------------------------------------------------------------- load
def _verify_items(manifest: Dict[str, Any], restored: Dict[str, Any]) -> Optional[str]:
    """None when every restored item matches its manifest record; otherwise a
    reason string. A spec mismatch (different dtypes/shapes requested by the
    restore target) downgrades to a warning — the bytes legitimately differ."""
    for name, rec in manifest.get("items", {}).items():
        if name not in restored:
            continue  # caller did not request this item
        got = (
            _meta_digest(restored[name])
            if name == "train_meta"
            else _tree_digests(restored[name])
        )
        if rec.get("num_leaves") != got["num_leaves"]:
            return "item %r: leaf count %s != manifest %s" % (
                name, got["num_leaves"], rec.get("num_leaves"))
        if rec.get("spec_digest") != got["spec_digest"]:
            print(
                "checkpoint: item %r restored under a different dtype/shape "
                "spec; skipping value verification" % name
            )
            continue
        if rec.get("digest") is None or got["digest"] is None:
            continue  # shards not fully addressable at save or restore time
        if rec["digest"] != got["digest"]:
            return "item %r: content digest mismatch" % name
    return None


def load_checkpoint(
    ckpt_dir: str,
    iteration: Optional[int] = None,
    *,
    params_target: Any,
    params_shardings: Any = None,
    opt_state_target: Any = None,
    opt_state_shardings: Any = None,
    hp: Optional[HybridParallelConfig] = None,
    strict_strategy: bool = True,
    verify_integrity: bool = True,
):
    """Restore (params, opt_state, train_meta) re-sharded to the current mesh.

    `*_target` are example pytrees (real or ShapeDtypeStruct) giving
    shapes/dtypes; `*_shardings` optional matching NamedShardings. With
    `strict_strategy` the saved strategy must equal `hp` (reference
    hybrid_parallel_config.py:112-124 resume assert).

    With `verify_integrity` (default), each candidate step must have a
    committed manifest whose digests match the restored bytes. When
    `iteration` is None the newest step is tried first and torn steps are
    skipped (the skipped steps are reported under
    ``meta["torn_iterations"]``); an explicitly requested `iteration` that
    fails verification raises instead — the caller asked for that exact
    state."""
    if hp is not None:
        cfg_path = os.path.join(ckpt_dir, "hybrid_parallel_config.json")
        if os.path.exists(cfg_path):
            saved = HybridParallelConfig.from_json(cfg_path, world_size=hp.world_size)
            if strict_strategy:
                hp.assert_equal(saved)

    def abstract(tree, sh):
        if sh is None:
            return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
        return _abstract_like(tree, sh)

    with _manager(ckpt_dir) as mgr:
        explicit = iteration is not None
        if explicit:
            candidates = [iteration]
        else:
            candidates = sorted(mgr.all_steps(), reverse=True)
            if not candidates:
                raise FileNotFoundError("no checkpoint found under %s" % ckpt_dir)
        check = verify_integrity and _has_manifest_discipline(ckpt_dir)
        torn: Dict[int, str] = {}
        out = None
        for step in candidates:
            manifest = read_manifest(ckpt_dir, step) if check else None
            if check and manifest is None:
                reason = "missing/unreadable manifest (torn save)"
                if explicit:
                    raise RuntimeError(
                        "checkpoint %s step %d: %s" % (ckpt_dir, step, reason))
                torn[step] = reason
                continue
            # only request items actually present: an h2g-converted checkpoint
            # is params-only (tools/convert_checkpoint.py) — the optimizer then
            # starts fresh, matching the reference's HF-init path
            # (parallel.py:79-89)
            try:
                present = set(dict(mgr.item_metadata(step).items()))
            except Exception:
                present = {"params", "opt_state", "train_meta"}
            items = {"params": ocp.args.StandardRestore(abstract(params_target, params_shardings))}
            if opt_state_target is not None and "opt_state" in present:
                items["opt_state"] = ocp.args.StandardRestore(
                    abstract(opt_state_target, opt_state_shardings)
                )
            if "train_meta" in present:
                items["train_meta"] = ocp.args.JsonRestore()
            try:
                out = mgr.restore(step, args=ocp.args.Composite(**items))
            except Exception as e:
                if explicit:
                    raise
                torn[step] = "restore failed: %s: %s" % (type(e).__name__, e)
                continue
            reason = _verify_items(manifest, dict(out.items())) if manifest else None
            if reason is not None:
                if explicit:
                    raise RuntimeError(
                        "checkpoint %s step %d failed integrity verification: %s"
                        % (ckpt_dir, step, reason)
                    )
                torn[step] = reason
                out = None
                continue
            iteration = step
            break
        if out is None:
            raise FileNotFoundError(
                "no intact checkpoint under %s (torn steps skipped: %s)"
                % (ckpt_dir, {k: v for k, v in sorted(torn.items())})
            )
    if torn:
        print(
            "checkpoint: fell back to intact step %d; skipped torn steps %s"
            % (iteration, sorted(torn))
        )
    params = out["params"]
    opt_state = out.get("opt_state")
    meta = out.get("train_meta") or {}
    meta.setdefault("iteration", iteration)
    if torn:
        meta["torn_iterations"] = sorted(torn)
    return params, opt_state, meta
