"""Distributed checkpoint save / resume with torn-write detection.

TPU-native counterpart of the reference's distributed checkpoint system
(models/llama_hf/LlamaModel_checkpoint.py:148-220: per-FSDP-module
FULL_STATE_DICT save, one file per tp-rank per layer under ``iter_N/`` plus
per-rank optimizer state and scheduler JSON). Here sharded arrays are written
through orbax/tensorstore — each host writes exactly its addressable shards,
and restore re-shards to the current mesh layout.

The reference *asserts the parallel strategy is unchanged on resume* (no
cross-strategy re-sharding, hybrid_parallel_config.py:112-124). We keep the
same guard by default (`strict_strategy=True`) but — because restore targets
are (spec, mesh)-typed abstract arrays and tensorstore reads any slice —
resume under a *different* searched strategy also works when the guard is
relaxed, which the reference cannot do.

Layout under ``<dir>/``:
    hybrid_parallel_config.json      strategy fingerprint (assert-equal on resume)
    meta.json                        model family/size, world size
    <iteration>/                     orbax composite: params, opt_state, train_meta
    manifests/<iteration>.json       post-save integrity manifest (below)

Integrity manifest
------------------
A preempted or killed process can leave a torn ``<iteration>/`` directory
that poisons the next resume. The manifest is the commit record: it is
written atomically (tmp file + ``os.replace``) *after* the orbax save
completes, so a step directory without a matching manifest is by definition
torn. Each manifest records, per item (``params`` / ``opt_state`` /
``train_meta``):

    ``digest``       sha256 over every leaf's (path, dtype, shape, bytes),
                     in deterministic flatten order (None when some shards
                     are not host-addressable, i.e. multi-host meshes)
    ``spec_digest``  sha256 over (path, dtype, shape) only
    ``num_leaves``   leaf count

plus the step metadata (iteration, save unix time). ``load_checkpoint``
verifies the manifest: a missing manifest or a value-digest mismatch marks
the step torn, and — when no explicit iteration was requested — restore
falls back to the latest *intact* step instead of crashing. A
``spec_digest`` mismatch (caller restores under different dtypes/shapes,
e.g. a precision change) skips value verification with a warning rather
than failing. Checkpoint directories written before this discipline (no
``manifests/`` dir) are accepted as-is for back-compat.

Provenance
----------
The manifest additionally carries a ``provenance`` block (built by
runtime/elastic.build_provenance): the serialized strategy JSON the
checkpoint was written under, mesh shape / device count, a model-config
digest, the optimizer identity/hyperparam digest, and chunks/global_bsz.
Provenance is what makes a checkpoint *strategy-portable*: on resume the
driver can detect that the live mesh no longer matches the saved one and
re-plan (runtime/elastic.py) instead of failing the strategy assert, and
``load_checkpoint(..., target=)`` can restore the on-disk global arrays
directly into a DIFFERENT ``HybridParallelModel``'s shardings — including
across pipeline-layout changes (the stacked ``stages`` tree is re-laid-out
leaf-exactly through pipeline.stack/unstack). Incompatibilities refuse with
structured GLS2xx diagnostics (analysis/diagnostics.py) rather than
garbling state.

Retention: `keep_latest_k` on save (the driver's ``--keep_latest_k``)
garbage-collects the oldest step dirs and their manifests. GC never deletes
a step another thread is currently restoring (``_RESTORING``), nor the
newest intact step (the only guaranteed-resumable state), and tolerates
stray non-step directories.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.obs import telemetry
from galvatron_tpu.utils.jsonio import read_json_config, write_json_config

MANIFEST_DIRNAME = "manifests"

# test-only seam (tests/runtime/fault_injection.py): called after the orbax
# write completes but before the manifest commit — the torn-save window a
# preemption kill actually hits
_before_manifest_write = None

# steps currently being restored (load_checkpoint registers them for the
# duration of the orbax read): gc_checkpoints must never delete one out from
# under an in-flight restore, e.g. a background save's GC racing the
# rollback path's fallback to an older intact step
_RESTORING: set = set()


def _manager(ckpt_dir: str, create: bool = False) -> ocp.CheckpointManager:
    options = ocp.CheckpointManagerOptions(create=create, enable_async_checkpointing=False)
    return ocp.CheckpointManager(os.path.abspath(ckpt_dir), options=options)


# ----------------------------------------------------------------- manifests
def _manifest_path(ckpt_dir: str, iteration: int) -> str:
    return os.path.join(ckpt_dir, MANIFEST_DIRNAME, "%d.json" % iteration)


def _tree_digests(tree: Any) -> Dict[str, Any]:
    """Per-item integrity record: value digest (None when shards are not
    addressable), structure-only digest, leaf count, and the
    sharding-layout-invariant integrity fold (runtime/sdc.py). The sha256
    covers the exact host bytes in tree order — torn/partial writes; the
    fold survives any relayout, so `cli lint --ckpt --deep` (GLS214) and a
    cross-strategy resume can both check the VALUES independently of how
    the restoring run shards them."""
    from galvatron_tpu.runtime import sdc

    value = hashlib.sha256()
    spec = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    addressable = True
    fold = 0
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path).encode()
        try:
            arr = np.asarray(jax.device_get(leaf))
        except Exception:
            addressable = False
            arr = None
        if arr is not None:
            spec.update(key + str(arr.dtype).encode() + str(arr.shape).encode())
            value.update(key + str(arr.dtype).encode() + str(arr.shape).encode())
            value.update(arr.tobytes())
            fold = (fold + sdc.host_tree_fold(arr)) & 0xFFFFFFFF
        else:
            spec.update(key)
            addressable = False
    return {
        "digest": value.hexdigest() if addressable else None,
        "spec_digest": spec.hexdigest(),
        "num_leaves": len(leaves),
        "fold": fold if addressable else None,
    }


def _meta_digest(meta: Dict[str, Any]) -> Dict[str, Any]:
    blob = json.dumps(meta, sort_keys=True).encode()
    d = hashlib.sha256(blob).hexdigest()
    return {"digest": d, "spec_digest": d, "num_leaves": 1}


def _write_manifest(ckpt_dir: str, iteration: int, items: Dict[str, Dict[str, Any]],
                    provenance: Optional[Dict[str, Any]] = None) -> None:
    path = _manifest_path(ckpt_dir, iteration)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {"format": 1, "iteration": iteration, "saved_at": time.time(), "items": items}
    if provenance is not None:
        payload["provenance"] = provenance
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic commit: manifest exists => save completed


def _read_manifest_raising(ckpt_dir: str, iteration: int) -> Optional[Dict[str, Any]]:
    """Like read_manifest, but lets transient OSErrors propagate so a caller
    can put a retry policy around the read (resilience.with_retry); only a
    missing file returns None here."""
    path = _manifest_path(ckpt_dir, iteration)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def read_manifest(ckpt_dir: str, iteration: int) -> Optional[Dict[str, Any]]:
    try:
        return _read_manifest_raising(ckpt_dir, iteration)
    except (OSError, ValueError):
        return None  # a torn manifest marks the step torn too


def read_provenance(ckpt_dir: str, iteration: Optional[int] = None):
    """(iteration, provenance dict) from the requested (or newest intact)
    step's manifest; (None, None) when no manifest carries provenance —
    a pre-elastic checkpoint, or no checkpoint at all."""
    if iteration is not None:
        m = read_manifest(ckpt_dir, iteration)
        prov = (m or {}).get("provenance")
        return (iteration, prov) if prov else (None, None)
    for step in reversed(intact_iterations(ckpt_dir)):
        m = read_manifest(ckpt_dir, step)
        if m and m.get("provenance"):
            return step, m["provenance"]
    return None, None


def _has_manifest_discipline(ckpt_dir: str) -> bool:
    """False for checkpoint dirs written before the manifest era — those are
    accepted as-is (back-compat); once any manifest exists, a manifest-less
    step means a torn save."""
    return os.path.isdir(os.path.join(ckpt_dir, MANIFEST_DIRNAME))


# ---------------------------------------------------------------------- save
def save_checkpoint(
    ckpt_dir: str,
    iteration: int,
    params: Any,
    opt_state: Any = None,
    hp: Optional[HybridParallelConfig] = None,
    train_meta: Optional[Dict[str, Any]] = None,
    keep_latest_k: Optional[int] = None,
    provenance: Optional[Dict[str, Any]] = None,
) -> None:
    """Write params (+ optimizer state + scalar train metadata) at `iteration`,
    commit the integrity manifest (carrying `provenance` when given — see
    runtime/elastic.build_provenance), then GC to the newest `keep_latest_k`."""
    t0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)
    if hp is not None:
        write_json_config(hp.to_json_dict(), os.path.join(ckpt_dir, "hybrid_parallel_config.json"))
    items = {"params": ocp.args.StandardSave(params)}
    digests = {"params": _tree_digests(params)}
    if opt_state is not None:
        items["opt_state"] = ocp.args.StandardSave(opt_state)
        digests["opt_state"] = _tree_digests(opt_state)
    if train_meta:
        items["train_meta"] = ocp.args.JsonSave(train_meta)
        digests["train_meta"] = _meta_digest(train_meta)
    with _manager(ckpt_dir, create=True) as mgr:
        if iteration in set(mgr.all_steps()):
            # re-save of an existing step (e.g. retraining over a torn step
            # after a rollback): replace it wholesale — its manifest, if any,
            # is invalidated by the overwrite either way
            mgr.delete(iteration)
            try:
                os.remove(_manifest_path(ckpt_dir, iteration))
            except OSError:
                pass
        mgr.save(iteration, args=ocp.args.Composite(**items))
        mgr.wait_until_finished()
    if _before_manifest_write is not None:
        _before_manifest_write(iteration)
    if jax.process_index() == 0:
        _write_manifest(ckpt_dir, iteration, digests, provenance=provenance)
    telemetry.emit(
        "checkpoint_save", iteration=iteration, path=ckpt_dir,
        duration_ms=(time.perf_counter() - t0) * 1e3,
        emergency=True if (train_meta and train_meta.get("emergency")) else None,
    )
    if keep_latest_k:
        gc_checkpoints(ckpt_dir, keep_latest_k)


def gc_checkpoints(ckpt_dir: str, keep_latest_k: int,
                   protect: Any = ()) -> List[int]:
    """Delete all but the newest `keep_latest_k` steps (and their manifests).
    Returns the deleted iterations.

    Safety rules (the GC/resume race): a step currently being restored
    (`_RESTORING`, registered by load_checkpoint) or listed in `protect` is
    never deleted, and neither is the newest INTACT step — with torn newer
    steps on disk, blindly keeping the newest K by number could delete the
    only state a fallback restore can still use. Stray non-step directories
    and already-missing steps are tolerated, not raised on."""
    if keep_latest_k <= 0 or jax.process_index() != 0:
        return []
    keep = set(protect) | set(_RESTORING)
    intact = intact_iterations(ckpt_dir)
    if intact:
        keep.add(max(intact))
    deleted = []
    with _manager(ckpt_dir) as mgr:
        steps = sorted(mgr.all_steps())
        doomed = steps[:-keep_latest_k] if keep_latest_k < len(steps) else []
        for step in doomed:
            if step in keep:
                continue
            try:
                mgr.delete(step)
            except (OSError, ValueError) as e:
                # a concurrently-removed or stray step is not worth failing
                # a SAVE over; leave it for the next GC pass
                telemetry.runtime_log(
                    "checkpoint gc: could not delete step %d: %s" % (step, e))
                continue
            deleted.append(step)
    for step in deleted:
        try:
            os.remove(_manifest_path(ckpt_dir, step))
        except OSError:
            pass
    if deleted:
        telemetry.emit("checkpoint_gc", deleted=deleted, path=ckpt_dir)
    return deleted


# ------------------------------------------------------------------- listing
def latest_iteration(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    with _manager(ckpt_dir) as mgr:
        return mgr.latest_step()


def intact_iterations(ckpt_dir: str) -> List[int]:
    """Saved steps whose manifest committed (all steps for pre-manifest
    dirs), ascending. Steps present on disk but missing from this list are
    torn."""
    if not os.path.isdir(ckpt_dir):
        return []
    with _manager(ckpt_dir) as mgr:
        steps = sorted(mgr.all_steps())
    if not _has_manifest_discipline(ckpt_dir):
        return steps
    return [s for s in steps if read_manifest(ckpt_dir, s) is not None]


def _abstract_like(tree, shardings):
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        tree,
        shardings,
    )


# --------------------------------------------- cross-strategy param layouts
def _same_param_layout(a: HybridParallelConfig, b: HybridParallelConfig) -> bool:
    """True when both strategies produce the same params TREE (sharding may
    still differ — that is just a device_put): the tree only depends on
    whether layers are stacked into pipeline stages and how."""
    if (a.pp > 1) != (b.pp > 1):
        return False
    return a.pp <= 1 or (a.pp == b.pp and list(a.pp_division) == list(b.pp_division))


def _abstract_canonical_params(cfg):
    """Abstract canonical (un-stacked, per-layer) param tree for the generic
    transformer family."""
    from galvatron_tpu.models import base as M

    rng = jax.random.PRNGKey(0)
    return jax.eval_shape(lambda: M.init_model_params(rng, cfg))


def _abstract_saved_params(cfg, saved_hp: HybridParallelConfig):
    """Abstract params tree AS SAVED under `saved_hp`: canonical for pp=1,
    stacked `stages` (leading pp dim per slot) for pp>1. Every layer of the
    generic tree shares one shape, so the stacked slots are derivable
    without building the saved model (whose mesh may need devices that no
    longer exist — the whole point of elastic resume)."""
    canonical = _abstract_canonical_params(cfg)
    if saved_hp.pp <= 1:
        return canonical
    from galvatron_tpu.parallel.pipeline import layers_per_stage

    out = dict(canonical)
    layers = out.pop("layers")
    slot = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((saved_hp.pp,) + l.shape, l.dtype), layers[0]
    )
    out["stages"] = [slot for _ in range(layers_per_stage(saved_hp))]
    return out


def _relayout_tree(tree, saved_hp: HybridParallelConfig, target_hp: HybridParallelConfig):
    """Re-layout any pytree holding a params-shaped subtree (params itself,
    adam mu/nu, ...) from `saved_hp`'s pipeline layout to `target_hp`'s:
    stacked ``stages`` unstack to the canonical layer list and restack for
    the target division. Pure data movement — leaf values are bit-exact."""
    from galvatron_tpu.parallel.pipeline import stack_params, unstack_params

    def walk(t):
        if isinstance(t, dict) and ("stages" in t or "layers" in t):
            t = dict(t)
            if "stages" in t:
                layers = unstack_params(t.pop("stages"), saved_hp)
            else:
                layers = list(t.pop("layers"))
            if target_hp.pp > 1:
                t["stages"] = stack_params(layers, target_hp)
            else:
                t["layers"] = layers
            return t
        if isinstance(t, dict):
            return {k: walk(v) for k, v in t.items()}
        if isinstance(t, tuple) and hasattr(t, "_fields"):
            return type(t)(*(walk(x) for x in t))
        if isinstance(t, (list, tuple)):
            return type(t)(walk(x) for x in t)
        return t

    return walk(tree)


def _read_saved_strategy(ckpt_dir: str, iteration: Optional[int],
                         fallback_world: int) -> Optional[HybridParallelConfig]:
    """The strategy the checkpoint was written under: provenance first (it
    records the true world size), the legacy hybrid_parallel_config.json
    otherwise."""
    _, prov = read_provenance(ckpt_dir, iteration)
    if prov and prov.get("strategy"):
        return HybridParallelConfig.from_json(
            dict(prov["strategy"]), world_size=int(prov.get("world_size", fallback_world))
        )
    cfg_path = os.path.join(ckpt_dir, "hybrid_parallel_config.json")
    if os.path.exists(cfg_path):
        return HybridParallelConfig.from_json(cfg_path, world_size=fallback_world)
    return None


# ---------------------------------------------------------------------- load
def _verify_items(manifest: Dict[str, Any], restored: Dict[str, Any]) -> Optional[str]:
    """None when every restored item matches its manifest record; otherwise a
    reason string. A spec mismatch (different dtypes/shapes requested by the
    restore target) downgrades to a warning — the bytes legitimately differ."""
    for name, rec in manifest.get("items", {}).items():
        if name not in restored:
            continue  # caller did not request this item
        got = (
            _meta_digest(restored[name])
            if name == "train_meta"
            else _tree_digests(restored[name])
        )
        if rec.get("num_leaves") != got["num_leaves"]:
            return "item %r: leaf count %s != manifest %s" % (
                name, got["num_leaves"], rec.get("num_leaves"))
        if rec.get("spec_digest") != got["spec_digest"]:
            telemetry.runtime_log(
                "checkpoint: item %r restored under a different dtype/shape "
                "spec; skipping value verification" % name
            )
            continue
        if rec.get("digest") is None or got["digest"] is None:
            continue  # shards not fully addressable at save or restore time
        if rec["digest"] != got["digest"]:
            return "item %r: content digest mismatch" % name
    return None


def load_checkpoint(
    ckpt_dir: str,
    iteration: Optional[int] = None,
    *,
    params_target: Any = None,
    params_shardings: Any = None,
    opt_state_target: Any = None,
    opt_state_shardings: Any = None,
    hp: Optional[HybridParallelConfig] = None,
    strict_strategy: bool = True,
    verify_integrity: bool = True,
    target: Any = None,
    tx: Any = None,
    saved_strategy: Optional[HybridParallelConfig] = None,
    retry_policy: Any = None,
    counters: Any = None,
    sdc_check: bool = False,
):
    """Restore (params, opt_state, train_meta) re-sharded to the current mesh.

    `*_target` are example pytrees (real or ShapeDtypeStruct) giving
    shapes/dtypes; `*_shardings` optional matching NamedShardings. With
    `strict_strategy` the saved strategy must equal `hp` (reference
    hybrid_parallel_config.py:112-124 resume assert).

    `target` (a runtime.model_api.HybridParallelModel, duck-typed) selects
    the STRATEGY-PORTABLE path: the on-disk global arrays are restored
    directly into `target`'s shardings, even when the checkpoint was written
    under a different strategy (`saved_strategy`; read from the manifest
    provenance / legacy strategy JSON when omitted). A pipeline-layout
    change (pp on/off, different division) restores the saved tree
    structure host-side, re-lays it out leaf-exactly, and places it onto
    the target mesh. `tx` (the optax transformation) supplies the optimizer
    tree to restore opt_state into; a structurally incompatible saved
    opt_state refuses with a GLS202 DiagnosticError instead of garbling
    state. Families with custom param trees (t5/swin) support same-layout
    `target` restores only (GLS206 otherwise).

    `retry_policy`/`counters` (resilience.RetryPolicy/ResilienceCounters)
    put exponential backoff around the manifest reads and the orbax
    restore, mirroring the retries saves have always had.

    With `verify_integrity` (default), each candidate step must have a
    committed manifest whose digests match the restored bytes. When
    `iteration` is None the newest step is tried first and torn steps are
    skipped (the skipped steps are reported under
    ``meta["torn_iterations"]``); an explicitly requested `iteration` that
    fails verification raises instead — the caller asked for that exact
    state."""
    from galvatron_tpu.analysis import diagnostics as D

    t0 = time.perf_counter()

    if hp is not None:
        cfg_path = os.path.join(ckpt_dir, "hybrid_parallel_config.json")
        if os.path.exists(cfg_path):
            saved = HybridParallelConfig.from_json(cfg_path, world_size=hp.world_size)
            if strict_strategy:
                hp.assert_equal(saved)

    # ------------------------------------------ strategy-portable target path
    cross = False
    target_abs_params = None
    if target is not None:
        target_hp = target.hp
        if saved_strategy is None:
            saved_strategy = _read_saved_strategy(ckpt_dir, iteration, target_hp.world_size)
        cross = saved_strategy is not None and not _same_param_layout(saved_strategy, target_hp)
        target_abs_params = target.abstract_params()
        if cross and target.init_fn is not None:
            raise D.DiagnosticError([D.make(
                "GLS206", "cross-pipeline-layout restore (pp %s -> pp %s) is "
                "only supported for the generic transformer tree; this "
                "family builds its own params" % (saved_strategy.pp, target_hp.pp),
            )])
        if cross:
            # restore the SAVED tree structure host-side (unsharded); the
            # re-layout + device_put onto the target mesh happens below
            params_target = _abstract_saved_params(target.cfg, saved_strategy)
            params_shardings = None
            opt_state_target = jax.eval_shape(tx.init, params_target) if tx is not None else None
            opt_state_shardings = None
        else:
            params_target = target_abs_params
            params_shardings = target.shardings()
            opt_state_target = jax.eval_shape(tx.init, params_target) if tx is not None else None
            opt_state_shardings = (
                target.opt_state_shardings(tx, params_target) if tx is not None else None
            )
    if params_target is None:
        raise TypeError("load_checkpoint needs params_target or target=")

    def abstract(tree, sh):
        if sh is None:
            return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
        return _abstract_like(tree, sh)

    def read_manifest_retrying(step):
        def fn():
            return _read_manifest_raising(ckpt_dir, step)

        try:
            if retry_policy is not None:
                from galvatron_tpu.runtime import resilience as rsl

                return rsl.with_retry(fn, retry_policy, counters,
                                      description="manifest read")
            return fn()
        except (OSError, ValueError):
            return None

    with _manager(ckpt_dir) as mgr:
        explicit = iteration is not None
        if explicit:
            candidates = [iteration]
        else:
            candidates = sorted(mgr.all_steps(), reverse=True)
            if not candidates:
                raise FileNotFoundError("no checkpoint found under %s" % ckpt_dir)
        check = verify_integrity and _has_manifest_discipline(ckpt_dir)
        torn: Dict[int, str] = {}
        out = None
        for step in candidates:
            manifest = read_manifest_retrying(step) if check else None
            if check and manifest is None:
                reason = "missing/unreadable manifest (torn save)"
                if explicit:
                    raise RuntimeError(
                        "checkpoint %s step %d: %s" % (ckpt_dir, step, reason))
                torn[step] = reason
                continue
            # refuse an optimizer-tree mismatch BEFORE the orbax restore can
            # garble state: the manifest records the saved leaf count
            if manifest and opt_state_target is not None:
                rec = manifest.get("items", {}).get("opt_state")
                want = len(jax.tree.leaves(opt_state_target))
                if rec and rec.get("num_leaves") is not None and rec["num_leaves"] != want:
                    raise D.DiagnosticError([D.make(
                        "GLS202", "saved opt_state has %s leaves but the "
                        "requested optimizer expects %d — resume with the "
                        "optimizer the checkpoint was written with, or "
                        "restore params-only (opt_state_target=None)"
                        % (rec["num_leaves"], want),
                    )])
            # only request items actually present: an h2g-converted checkpoint
            # is params-only (tools/convert_checkpoint.py) — the optimizer then
            # starts fresh, matching the reference's HF-init path
            # (parallel.py:79-89)
            try:
                present = set(dict(mgr.item_metadata(step).items()))
            except Exception:
                present = {"params", "opt_state", "train_meta"}
            items = {"params": ocp.args.StandardRestore(abstract(params_target, params_shardings))}
            if opt_state_target is not None and "opt_state" in present:
                items["opt_state"] = ocp.args.StandardRestore(
                    abstract(opt_state_target, opt_state_shardings)
                )
            if "train_meta" in present:
                items["train_meta"] = ocp.args.JsonRestore()

            def do_restore(step=step, items=items):
                return mgr.restore(step, args=ocp.args.Composite(**items))

            _RESTORING.add(step)
            try:
                if retry_policy is not None:
                    from galvatron_tpu.runtime import resilience as rsl

                    out = rsl.with_retry(do_restore, retry_policy, counters,
                                         description="orbax restore")
                else:
                    out = do_restore()
            except D.DiagnosticError:
                raise
            except (ValueError, TypeError, KeyError) as e:
                if target is not None:
                    # a tree-structure mismatch against a known-intact step is
                    # an optimizer/model incompatibility, not a torn save
                    raise D.DiagnosticError([D.make(
                        "GLS202", "restore into the target tree failed "
                        "structurally (%s: %s) — the checkpoint's optimizer "
                        "or model tree differs from the target's"
                        % (type(e).__name__, e),
                    )])
                if explicit:
                    raise
                torn[step] = "restore failed: %s: %s" % (type(e).__name__, e)
                continue
            except Exception as e:
                if explicit:
                    raise
                torn[step] = "restore failed: %s: %s" % (type(e).__name__, e)
                continue
            finally:
                _RESTORING.discard(step)
            reason = _verify_items(manifest, dict(out.items())) if manifest else None
            if reason is not None:
                if explicit:
                    raise RuntimeError(
                        "checkpoint %s step %d failed integrity verification: %s"
                        % (ckpt_dir, step, reason)
                    )
                torn[step] = reason
                out = None
                continue
            iteration = step
            break
        if out is None:
            raise FileNotFoundError(
                "no intact checkpoint under %s (torn steps skipped: %s)"
                % (ckpt_dir, {k: v for k, v in sorted(torn.items())})
            )
    if torn:
        telemetry.runtime_log(
            "checkpoint: fell back to intact step %d; skipped torn steps %s"
            % (iteration, sorted(torn))
        )
    params = out["params"]
    opt_state = out.get("opt_state")
    params_fold = opt_fold = None
    if sdc_check and target is not None and cross:
        # the layout-invariant fold of the AS-RESTORED state, asserted
        # unchanged across the relayout + placement below (GLS016): the
        # manifest sha256 cannot make this check — it is bound to the saved
        # strategy's exact byte layout
        from galvatron_tpu.runtime import sdc

        params_fold = sdc.host_tree_fold(params)
        if opt_state is not None and tx is not None:
            opt_fold = sdc.host_tree_fold(opt_state)
    if target is not None and cross:
        # integrity was verified on the AS-SAVED tree above; now re-lay-out
        # (leaf-exact host-side data movement) and place onto the target mesh
        params = _relayout_tree(params, saved_strategy, target.hp)
        params = jax.device_put(params, target.shardings())
        if opt_state is not None and tx is not None:
            opt_state = _relayout_tree(opt_state, saved_strategy, target.hp)
            target_abs_opt = jax.eval_shape(tx.init, target_abs_params)
            got = [(jax.tree_util.keystr(p), tuple(l.shape)) for p, l in
                   jax.tree_util.tree_flatten_with_path(opt_state)[0]]
            want = [(jax.tree_util.keystr(p), tuple(l.shape)) for p, l in
                    jax.tree_util.tree_flatten_with_path(target_abs_opt)[0]]
            if got != want:
                diffs = [(g, w) for g, w in zip(got, want) if g != w][:3]
                raise D.DiagnosticError([D.make(
                    "GLS202", "re-laid-out opt_state does not match the "
                    "target optimizer tree (%d vs %d leaves; first diffs: "
                    "%s)" % (len(got), len(want), diffs),
                )])
            opt_state = jax.device_put(
                opt_state, target.opt_state_shardings(tx, target_abs_params))
        if params_fold is not None:
            from galvatron_tpu.runtime import sdc

            sdc.assert_digest_continuity(
                params_fold, params, "load_checkpoint(cross, params)",
                iteration=iteration)
            if opt_fold is not None and opt_state is not None:
                sdc.assert_digest_continuity(
                    opt_fold, opt_state, "load_checkpoint(cross, opt_state)",
                    iteration=iteration)
    meta = out.get("train_meta") or {}
    meta.setdefault("iteration", iteration)
    if torn:
        meta["torn_iterations"] = sorted(torn)
    telemetry.emit(
        "checkpoint_restore", iteration=int(meta["iteration"]), path=ckpt_dir,
        duration_ms=(time.perf_counter() - t0) * 1e3,
        torn_skipped=len(torn) or None,
        cross_strategy=True if (target is not None and cross) else None,
    )
    return params, opt_state, meta
