"""Distributed checkpoint save / resume.

TPU-native counterpart of the reference's distributed checkpoint system
(models/llama_hf/LlamaModel_checkpoint.py:148-220: per-FSDP-module
FULL_STATE_DICT save, one file per tp-rank per layer under ``iter_N/`` plus
per-rank optimizer state and scheduler JSON). Here sharded arrays are written
through orbax/tensorstore — each host writes exactly its addressable shards,
and restore re-shards to the current mesh layout.

The reference *asserts the parallel strategy is unchanged on resume* (no
cross-strategy re-sharding, hybrid_parallel_config.py:112-124). We keep the
same guard by default (`strict_strategy=True`) but — because restore targets
are (spec, mesh)-typed abstract arrays and tensorstore reads any slice —
resume under a *different* searched strategy also works when the guard is
relaxed, which the reference cannot do.

Layout under ``<dir>/``:
    hybrid_parallel_config.json      strategy fingerprint (assert-equal on resume)
    meta.json                        model family/size, world size
    <iteration>/                     orbax composite: params, opt_state, train_meta
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.utils.jsonio import read_json_config, write_json_config


def _manager(ckpt_dir: str, create: bool = False) -> ocp.CheckpointManager:
    options = ocp.CheckpointManagerOptions(create=create, enable_async_checkpointing=False)
    return ocp.CheckpointManager(os.path.abspath(ckpt_dir), options=options)


def save_checkpoint(
    ckpt_dir: str,
    iteration: int,
    params: Any,
    opt_state: Any = None,
    hp: Optional[HybridParallelConfig] = None,
    train_meta: Optional[Dict[str, Any]] = None,
) -> None:
    """Write params (+ optimizer state + scalar train metadata) at `iteration`."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if hp is not None:
        write_json_config(hp.to_json_dict(), os.path.join(ckpt_dir, "hybrid_parallel_config.json"))
    items = {"params": ocp.args.StandardSave(params)}
    if opt_state is not None:
        items["opt_state"] = ocp.args.StandardSave(opt_state)
    if train_meta:
        items["train_meta"] = ocp.args.JsonSave(train_meta)
    with _manager(ckpt_dir, create=True) as mgr:
        mgr.save(iteration, args=ocp.args.Composite(**items))
        mgr.wait_until_finished()


def latest_iteration(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    with _manager(ckpt_dir) as mgr:
        return mgr.latest_step()


def _abstract_like(tree, shardings):
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        tree,
        shardings,
    )


def load_checkpoint(
    ckpt_dir: str,
    iteration: Optional[int] = None,
    *,
    params_target: Any,
    params_shardings: Any = None,
    opt_state_target: Any = None,
    opt_state_shardings: Any = None,
    hp: Optional[HybridParallelConfig] = None,
    strict_strategy: bool = True,
):
    """Restore (params, opt_state, train_meta) re-sharded to the current mesh.

    `*_target` are example pytrees (real or ShapeDtypeStruct) giving
    shapes/dtypes; `*_shardings` optional matching NamedShardings. With
    `strict_strategy` the saved strategy must equal `hp` (reference
    hybrid_parallel_config.py:112-124 resume assert)."""
    if hp is not None:
        cfg_path = os.path.join(ckpt_dir, "hybrid_parallel_config.json")
        if os.path.exists(cfg_path):
            saved = HybridParallelConfig.from_json(cfg_path, world_size=hp.world_size)
            if strict_strategy:
                hp.assert_equal(saved)
    with _manager(ckpt_dir) as mgr:
        if iteration is None:
            iteration = mgr.latest_step()
            if iteration is None:
                raise FileNotFoundError("no checkpoint found under %s" % ckpt_dir)

        def abstract(tree, sh):
            if sh is None:
                return jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
            return _abstract_like(tree, sh)

        # only request items actually present: an h2g-converted checkpoint is
        # params-only (tools/convert_checkpoint.py) — the optimizer then starts
        # fresh, matching the reference's HF-init path (parallel.py:79-89)
        try:
            present = set(dict(mgr.item_metadata(iteration).items()))
        except Exception:
            present = {"params", "opt_state", "train_meta"}
        items = {"params": ocp.args.StandardRestore(abstract(params_target, params_shardings))}
        if opt_state_target is not None and "opt_state" in present:
            items["opt_state"] = ocp.args.StandardRestore(
                abstract(opt_state_target, opt_state_shardings)
            )
        if "train_meta" in present:
            items["train_meta"] = ocp.args.JsonRestore()
        out = mgr.restore(iteration, args=ocp.args.Composite(**items))
    params = out["params"]
    opt_state = out.get("opt_state")
    meta = out.get("train_meta") or {}
    meta.setdefault("iteration", iteration)
    return params, opt_state, meta
