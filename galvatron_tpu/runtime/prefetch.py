"""Background-thread input prefetcher: dispatch-ahead data pipeline.

The steady-state train loop is only as fast as its slowest serial segment.
Before this module the loop was host-serialized: numpy batch prep (zigzag
permutation, label rolling) ran on the critical path, then a blocking
``device_put``, then the step — the device idled during data prep and the
host idled during the step. :class:`PrefetchIterator` moves the host work
off the critical path: a daemon thread pulls from the underlying iterator,
applies ``place_fn`` (the driver passes ``model.shard_batch``, a single
sharded ``jax.device_put`` of the whole batch tree), and parks up to
``depth`` already-placed batches in a bounded queue so the transfer of batch
N+1..N+depth overlaps the compute of batch N.

Contract:

- **Ordering**: batches come out in exactly the order the source yields
  them (single worker, FIFO queue) — required for bitwise loss parity with
  the synchronous loop and for step-indexed fault injection.
- **Bounded**: at most ``depth`` placed batches are buffered (plus the one
  the worker is currently preparing); a slow consumer back-pressures the
  producer instead of ballooning host/device memory.
- **Exceptions propagate**: an exception in the source iterator or in
  ``place_fn`` is re-raised from :meth:`__next__` in the training thread —
  a poisoned corpus or exhausted I/O retry budget fails the run, it does
  not silently starve it.
- **Clean shutdown**: :meth:`close` (also via context manager and the train
  driver's ``finally``) unblocks and joins the worker, so preemption /
  rollback / interpreter exit never leak a thread mid-``device_put``.

jax note: issuing ``device_put`` from a non-main thread is supported; the
backends must already be initialised (they are — the driver builds the mesh
long before the first batch), and signal handlers stay on the main thread
(:class:`~galvatron_tpu.runtime.resilience.PreemptionHandler` already
guards against non-main installation).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional

__all__ = ["PrefetchIterator"]

_ITEM, _DONE, _ERROR = "item", "done", "error"


class PrefetchIterator:
    """Wrap ``source`` so host batch prep + device placement run ahead of
    the consumer on a background thread. Iterator protocol + context
    manager; ``close()`` is idempotent."""

    def __init__(
        self,
        source: Iterator,
        depth: int = 2,
        place_fn: Optional[Callable] = None,
        name: str = "galvatron-prefetch",
    ):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1, got %d" % depth)
        self._source = source
        self._place_fn = place_fn
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exhausted = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._worker, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _put(self, entry) -> bool:
        """Blocking put that stays responsive to close(); False if closing."""
        while not self._stop.is_set():
            try:
                self._queue.put(entry, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            while not self._stop.is_set():
                try:
                    item = next(self._source)
                except StopIteration:
                    self._put((_DONE, None))
                    return
                if self._place_fn is not None:
                    item = self._place_fn(item)
                if not self._put((_ITEM, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            self._put((_ERROR, e))

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise RuntimeError("PrefetchIterator used after close()")
        if self._error is not None:
            raise self._error
        if self._exhausted:
            raise StopIteration
        while True:
            try:
                tag, payload = self._queue.get(timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # worker died without posting a marker (should not
                    # happen; defensive against a killed interpreter)
                    self._exhausted = True
                    raise StopIteration
                continue
            if tag == _ITEM:
                return payload
            if tag == _DONE:
                self._exhausted = True
                raise StopIteration
            self._error = payload
            raise payload

    # ------------------------------------------------------------- shutdown
    def close(self, timeout: float = 5.0):
        """Stop the worker and join it. Buffered batches are dropped (the
        rollback path rebuilds the stream at a different step anyway)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a worker blocked in put() sees the stop event promptly
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False

    def __del__(self):  # pragma: no cover — best-effort
        try:
            self.close(timeout=0.1)
        except Exception:
            pass
