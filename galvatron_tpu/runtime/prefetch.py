"""Background-thread input prefetcher: dispatch-ahead data pipeline.

The steady-state train loop is only as fast as its slowest serial segment.
Before this module the loop was host-serialized: numpy batch prep (zigzag
permutation, label rolling) ran on the critical path, then a blocking
``device_put``, then the step — the device idled during data prep and the
host idled during the step. :class:`PrefetchIterator` moves the host work
off the critical path: a daemon thread pulls from the underlying iterator,
applies ``place_fn`` (the driver passes ``model.shard_batch``, a single
sharded ``jax.device_put`` of the whole batch tree), and parks up to
``depth`` already-placed batches in a bounded queue so the transfer of batch
N+1..N+depth overlaps the compute of batch N.

Contract:

- **Ordering**: batches come out in exactly the order the source yields
  them (single worker, FIFO queue) — required for bitwise loss parity with
  the synchronous loop and for step-indexed fault injection.
- **Bounded**: at most ``depth`` placed batches are buffered (plus the one
  the worker is currently preparing); a slow consumer back-pressures the
  producer instead of ballooning host/device memory.
- **Exceptions propagate**: an exception in the source iterator or in
  ``place_fn`` is re-raised from :meth:`__next__` in the training thread —
  a poisoned corpus or exhausted I/O retry budget fails the run, it does
  not silently starve it.
- **Clean shutdown**: :meth:`close` (also via context manager and the train
  driver's ``finally``) unblocks and joins the worker, so preemption /
  rollback / interpreter exit never leak a thread mid-``device_put``.

jax note: issuing ``device_put`` from a non-main thread is supported; the
backends must already be initialised (they are — the driver builds the mesh
long before the first batch), and signal handlers stay on the main thread
(:class:`~galvatron_tpu.runtime.resilience.PreemptionHandler` already
guards against non-main installation).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, Optional

__all__ = ["PrefetchIterator", "PrefetchStalledError"]

_ITEM, _DONE, _ERROR = "item", "done", "error"


class PrefetchStalledError(RuntimeError):
    """The producer thread is alive but produced nothing within the stall
    timeout — a wedged ``place_fn`` (a device_put stuck on a sick
    interconnect) or a hung source iterator. Carries the diagnostics the
    watchdog event wants; raising (instead of blocking forever) is what
    lets the driver surface the stall instead of silently hanging."""

    def __init__(self, message: str, diagnostics: Optional[Dict] = None):
        super().__init__(message)
        self.diagnostics = dict(diagnostics or {})


class PrefetchIterator:
    """Wrap ``source`` so host batch prep + device placement run ahead of
    the consumer on a background thread. Iterator protocol + context
    manager; ``close()`` is idempotent.

    `stall_timeout` (seconds) bounds how long :meth:`get`/``__next__`` will
    wait on a live-but-unproductive worker before raising
    :class:`PrefetchStalledError` (None = wait forever, the pre-watchdog
    behavior)."""

    def __init__(
        self,
        source: Iterator,
        depth: int = 2,
        place_fn: Optional[Callable] = None,
        name: str = "galvatron-prefetch",
        stall_timeout: Optional[float] = None,
    ):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1, got %d" % depth)
        self._source = source
        self._place_fn = place_fn
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exhausted = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self._stall_timeout = stall_timeout
        self._produced = 0  # items the worker finished placing
        self._consumed = 0  # items handed to the consumer
        self._busy_since: Optional[float] = None  # worker inside next()/place_fn
        self._thread = threading.Thread(target=self._worker, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _put(self, entry) -> bool:
        """Blocking put that stays responsive to close(); False if closing."""
        while not self._stop.is_set():
            try:
                self._queue.put(entry, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            while not self._stop.is_set():
                self._busy_since = time.monotonic()
                try:
                    item = next(self._source)
                except StopIteration:
                    self._busy_since = None
                    self._put((_DONE, None))
                    return
                if self._place_fn is not None:
                    item = self._place_fn(item)
                self._busy_since = None
                self._produced += 1
                if not self._put((_ITEM, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            self._busy_since = None
            self._put((_ERROR, e))

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def diagnostics(self) -> Dict:
        """Producer-side state for the watchdog's stall report."""
        busy = self._busy_since
        return {
            "worker_alive": self._thread.is_alive(),
            "produced": self._produced,
            "consumed": self._consumed,
            "buffered": self._queue.qsize(),
            "busy_for_s": (time.monotonic() - busy) if busy is not None else None,
            "stall_timeout_s": self._stall_timeout,
        }

    def get(self, timeout: Optional[float] = None):
        """Next placed batch, waiting at most `timeout` seconds (default:
        the constructor's `stall_timeout`). A live worker that produces
        nothing within the budget raises :class:`PrefetchStalledError`
        with diagnostics instead of hanging the training thread."""
        if self._closed:
            raise RuntimeError("PrefetchIterator used after close()")
        if self._error is not None:
            raise self._error
        if self._exhausted:
            raise StopIteration
        timeout = self._stall_timeout if timeout is None else timeout
        deadline = (time.monotonic() + timeout) if timeout is not None else None
        while True:
            try:
                tag, payload = self._queue.get(timeout=0.1)
            except queue.Empty:
                if not self._thread.is_alive() and self._queue.empty():
                    # worker died without posting a marker (should not
                    # happen; defensive against a killed interpreter)
                    self._exhausted = True
                    raise StopIteration
                if deadline is not None and time.monotonic() > deadline:
                    diag = self.diagnostics()
                    raise PrefetchStalledError(
                        "prefetch producer yielded nothing for %.1fs "
                        "(worker alive, %d produced / %d buffered%s)"
                        % (timeout, diag["produced"], diag["buffered"],
                           ", busy in source/place_fn for %.1fs"
                           % diag["busy_for_s"] if diag["busy_for_s"] else ""),
                        diagnostics=diag,
                    )
                continue
            if tag == _ITEM:
                self._consumed += 1
                return payload
            if tag == _DONE:
                self._exhausted = True
                raise StopIteration
            self._error = payload
            raise payload

    def __next__(self):
        return self.get()

    # ------------------------------------------------------------- shutdown
    def close(self, timeout: float = 5.0):
        """Stop the worker and join it (bounded). Buffered batches are
        dropped (the rollback path rebuilds the stream at a different step
        anyway). A worker wedged inside ``place_fn`` cannot be joined — the
        bounded join returns anyway (daemon thread, cannot block exit) and
        the leak is reported as a warning event rather than a deadlock."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a worker blocked in put() sees the stop event promptly
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            from galvatron_tpu.obs import telemetry

            telemetry.runtime_log(
                "prefetch close: worker did not exit within %.1fs (wedged "
                "in source/place_fn?); leaking the daemon thread" % timeout
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
        return False

    def __del__(self):  # pragma: no cover — best-effort
        try:
            self.close(timeout=0.1)
        except Exception:
            pass
