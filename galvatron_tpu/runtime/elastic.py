"""Elastic degraded-mesh resume: re-plan the strategy for the surviving mesh.

Galvatron's premise is that the optimal layer-wise strategy is a function of
the hardware (PAPER.md) — so when the hardware changes mid-run (TPU
preemption shrinking a slice, an ICI link flap dropping a host, a chip
failure), the right response is not "refuse to resume" but "re-optimize for
what survived". This module is the resume-side half of that story; the
save-side half is the provenance block runtime/checkpoint.py embeds in every
integrity manifest (:func:`build_provenance`).

On resume with ``--elastic {resume,search}`` the driver calls
:func:`resolve_resume_strategy`, which

1. reads the newest intact manifest's provenance (strategy JSON, mesh/device
   count, model-config digest, optimizer digest, chunks);
2. refuses with structured GLS2xx diagnostics (exit code 2 at the CLI) when
   the checkpoint cannot be resumed safely: different model-config digest
   (GLS201), no provenance at all (GLS204), a changed mesh with no way to
   pick a new strategy (GLS205), or no strategy that fits the memory budget
   on the surviving devices (GLS203);
3. on a world-size match returns the SAVED strategy — same-strategy resume
   stays bitwise identical to the non-elastic path;
4. on a mismatch either loads the user-supplied ``--elastic_strategy`` JSON
   or re-runs :class:`GalvatronSearchEngine` for the surviving world size
   under the same memory budget — with profiled cost tables when the config
   dir has them, and an analytic Megatron-style fallback (the same tables
   the strategy linter's GLS101 estimate uses) when it does not.

The actual cross-strategy restore (different shardings, different pipeline
layout, opt_state re-sharded leaf-wise with structural checks) is
``load_checkpoint(..., target=)`` in runtime/checkpoint.py.

Live in-memory migration
------------------------
:func:`migrate` is the no-disk sibling of the cross-strategy restore: it
moves the LIVE params + optimizer state from the running model onto a new
strategy's model entirely on-device — the same ``_relayout_tree`` family
re-lays out pipeline-layout changes, a plain sharded ``device_put`` handles
everything else — so a degraded or re-planned run swaps strategies mid-
process and continues from the same step, bitwise-identical to a
checkpoint round-trip under the target strategy (pinned by
tests/cli/test_migration.py). :func:`resolve_migration_strategy` picks the
target (operator-supplied JSON or a fresh search for the surviving world)
and refuses infeasible migrations with GLS207; the driver wires both to
the watchdog / mesh-health probe (runtime/health.py) and to a SIGUSR1
manual trigger.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from galvatron_tpu.analysis import diagnostics as D
from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.obs import telemetry

DEFAULT_MEMORY_GB = 16.0  # matches the search CLI's --memory_constraint default

# model-config fields excluded from the digest: precision knobs are runtime
# choices (the manifest's spec_digest machinery already handles a dtype
# change), not model identity
_DIGEST_EXCLUDE = ("compute_dtype", "param_dtype", "attn_impl")


def _stable_json(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, default=str)


def model_config_digest(model_cfg: Any) -> str:
    """sha256 over the model's architectural identity. Restoring a checkpoint
    into a model with a different digest is refused (GLS201): same-shaped
    trees with different semantics (e.g. swapped activation) would restore
    cleanly and train garbage."""
    if dataclasses.is_dataclass(model_cfg):
        fields = dataclasses.asdict(model_cfg)
    else:  # duck-typed configs (tests)
        fields = {k: v for k, v in vars(model_cfg).items() if not k.startswith("_")}
    fields = {k: str(v) for k, v in fields.items() if k not in _DIGEST_EXCLUDE}
    return hashlib.sha256(_stable_json(fields).encode()).hexdigest()


def optimizer_digest(opt_args: Any) -> str:
    """sha256 over the optimizer identity + hyperparams (runtime.optimizer
    .OptimizerArgs). A mismatch on resume is a warning, not a refusal — lr
    schedules legitimately change mid-run; the *structural* guard against a
    different optimizer lives in load_checkpoint (GLS202)."""
    fields = dataclasses.asdict(opt_args) if dataclasses.is_dataclass(opt_args) else dict(opt_args)
    return hashlib.sha256(_stable_json({k: str(v) for k, v in fields.items()}).encode()).hexdigest()


def build_provenance(
    hp: HybridParallelConfig,
    model_cfg: Any,
    opt_args: Any = None,
    mesh: Any = None,
    memory_budget_gb: Optional[float] = None,
) -> Dict[str, Any]:
    """The manifest provenance block: everything a future process on
    DIFFERENT hardware needs to decide how (or whether) to resume."""
    prov: Dict[str, Any] = {
        "format": 1,
        "strategy": hp.to_json_dict(),
        "world_size": hp.world_size,
        "chunks": hp.chunks,
        "global_bsz": hp.global_bsz,
        "mixed_precision": hp.mixed_precision,
        "model_digest": model_config_digest(model_cfg),
    }
    if mesh is not None:
        prov["mesh_shape"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        prov["device_count"] = int(mesh.devices.size)
    else:
        prov["device_count"] = hp.world_size
    if opt_args is not None:
        prov["optimizer"] = {
            "kind": type(opt_args).__name__,
            "digest": optimizer_digest(opt_args),
        }
    if memory_budget_gb:
        prov["memory_budget_gb"] = float(memory_budget_gb)
    return prov


# ------------------------------------------------------ analytic cost tables
def analytic_model_profiles(model_cfg: Any, max_tp: int) -> Optional[Tuple[dict, dict]]:
    """(time_config, memory_config) for GalvatronSearchEngine synthesized
    from the model config alone — the no-profiles fallback, built on the
    same analytic parameter/activation tables the strategy linter's GLS101
    estimate uses, so the elastic re-search and the linter agree on what
    fits. Timing is a flops-proportional constant: with no profiled tables
    every strategy's compute scales identically, so relative comparisons
    (what the DP needs) remain meaningful."""
    from galvatron_tpu.analysis.strategy_lint import (
        _analytic_activation_dict,
        _analytic_parameter_mb,
    )

    param_mb = _analytic_parameter_mb(model_cfg)
    act = _analytic_activation_dict(model_cfg, max_tp)
    if param_mb is None or not act:
        return None
    h = getattr(model_cfg, "hidden_size", 1024)
    s = getattr(model_cfg, "max_seq_len", 2048)
    # ~12*s*h^2 flops/token forward; an arbitrary-but-fixed throughput turns
    # it into ms/layer/sample (only ratios matter without profiles)
    fwd_ms = 12.0 * s * h * h / 1e12 * 1e3
    time_config = {"layertype_0": max(fwd_ms, 1e-3), "other_time": max(fwd_ms, 1e-3)}
    states = {}
    t = 1
    while t <= max_tp:
        # embed/head model states (params + grads + adam moments ~ 16 bytes/
        # param fp32-master) sharded over vocab tp
        vocab = getattr(model_cfg, "vocab_size", 0) or 0
        states[t] = vocab * h * 16.0 / 2**20 / t
        t *= 2
    act_other = {k: v for k, v in act.items() if k != "checkpoint"}
    memory_config = {
        "layertype_0": {
            "parameter_size": param_mb,
            "tp_activation_per_bsz_dict": dict(act),
        },
        "other_memory_pp_off": {"model_states": dict(states), "activation": dict(act_other)},
        "other_memory_pp_on": {
            "first_stage": {"model_states": {k: v / 2 for k, v in states.items()},
                            "activation": {k: v / 2 for k, v in act_other.items()}},
            "last_stage": {"model_states": {k: v / 2 for k, v in states.items()},
                           "activation": {k: v / 2 for k, v in act_other.items()}},
        },
    }
    return time_config, memory_config


def analytic_hardware_profiles(world: int) -> Tuple[dict, dict, dict]:
    """(allreduce, p2p, overlap) coefficient JSONs for the no-profiles
    fallback: flat plausible ICI bandwidths — without measurements every
    collective is priced identically per byte, which still ranks strategies
    by communication VOLUME (the dominant analytic signal)."""
    allreduce = {}
    size = 2
    while size <= world:
        allreduce["allreduce_size_%d_consec_1" % size] = 100.0
        allreduce["allreduce_size_%d_consec_0" % size] = 80.0
        size *= 2
    p2p = {}
    size = 2
    while size <= world:
        p2p["pp_size_%d" % size] = 120.0
        size *= 2
    return allreduce, p2p, {"overlap_coe": 1.1}


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def search_surviving_strategy(
    model_cfg: Any,
    live_world: int,
    global_bsz: int,
    memory_budget_gb: float,
    model_type: str = "model",
    config_dir: Optional[str] = None,
    default_dp_type: str = "ddp",
    logger=None,
    time_config: Optional[dict] = None,
    memory_config: Optional[dict] = None,
    remat_search: bool = False,
) -> Optional[HybridParallelConfig]:
    """Re-run the strategy search for the surviving world size under the
    same global batch and memory budget. Profiled tables are used when
    `config_dir` has them for this model; otherwise the analytic fallback.
    Explicit `time_config`/`memory_config` (profiler JSON schema) override
    both — the online autotuner re-searches on MEASURED tables through this
    exact recipe, so settle_bsz stays pinned to the live global batch.
    Returns None when nothing fits (the caller turns that into GLS203)."""
    from galvatron_tpu.search.engine import GalvatronSearchEngine, SearchArgs

    heads = getattr(model_cfg, "num_heads", None) or 1
    num_layers = getattr(model_cfg, "num_layers", 1)
    seq_len = getattr(model_cfg, "max_seq_len", 2048)
    hidden = getattr(model_cfg, "hidden_size", 1024)
    # cap tp at the largest power of two dividing the head count so every
    # emitted strategy passes the model-aware GLS007 check
    max_tp = 1
    while max_tp * 2 <= min(heads, live_world) and heads % (max_tp * 2) == 0:
        max_tp *= 2
    args = SearchArgs(
        memory_constraint=memory_budget_gb,
        settle_bsz=global_bsz,  # the batch is part of the training trajectory
        settle_chunk=None,
        max_tp_deg=max_tp,
        max_pp_deg=min(_pow2_floor(num_layers), live_world),
        default_dp_type=default_dp_type,
        sp_space="tp",
        # remat axis: the re-plan may mix per-layer policies (and, with
        # settle_chunk=None, change chunks) when the budget rewards it
        remat_search=remat_search,
    )
    engine = GalvatronSearchEngine(
        args, live_world,
        [{"hidden_size": hidden, "seq_len": seq_len, "layer_num": num_layers}],
        config_dir=config_dir or "configs", model_name=model_type, logger=logger,
    )
    profiles = None
    if config_dir:
        profiles = _load_profiled_tables(model_cfg, model_type, config_dir, live_world)
    if profiles is None:
        synth = analytic_model_profiles(model_cfg, max_tp=live_world)
        if synth is None:
            return None
        time_cfg, mem_cfg = synth
        allreduce, p2p, overlap = analytic_hardware_profiles(live_world)
    else:
        time_cfg, mem_cfg, allreduce, p2p, overlap = profiles
    if time_config is not None and memory_config is not None:
        time_cfg, mem_cfg = time_config, memory_config  # measured tables win
    engine.set_model_profiles(time_cfg, mem_cfg)
    engine.set_hardware_profiles(allreduce, p2p, overlap)
    engine.initialize_search_engine()
    result = engine.parallelism_optimization()
    if result is None:
        return None
    return engine.result_to_config(result)


def _load_profiled_tables(model_cfg, model_type, config_dir, world):
    """The profiled-table path of the elastic re-search: the same files the
    search CLI reads (cli/search.py). None when any required table is
    missing or unreadable — the analytic fallback takes over."""
    try:
        from galvatron_tpu.profiler.model import ModelProfileArgs, ModelProfiler
        from galvatron_tpu.utils.jsonio import read_json_config

        prof = ModelProfiler(model_cfg, model_name=model_type,
                             args=ModelProfileArgs(config_dir=config_dir))
        mp = prof.config_paths()
        time_cfg = read_json_config(mp["computation"])
        mem_cfg = read_json_config(mp["memory"])
        tag = "%dchips" % world
        allreduce = read_json_config(
            os.path.join(config_dir, "allreduce_bandwidth_%s.json" % tag))
        p2p_path = os.path.join(config_dir, "p2p_bandwidth_%s.json" % tag)
        p2p = read_json_config(p2p_path) if os.path.exists(p2p_path) else None
        ov_path = os.path.join(config_dir, "overlap_coefficient.json")
        overlap = read_json_config(ov_path) if os.path.exists(ov_path) else None
        return time_cfg, mem_cfg, allreduce, p2p, overlap
    except (OSError, ValueError, KeyError, TypeError):
        return None


# ------------------------------------------------------------- resume planning
@dataclass
class ElasticPlan:
    """What resolve_resume_strategy decided: run `hp` now; the checkpoint
    was written under `saved_hp` (load_checkpoint's cross-strategy restore
    needs it)."""

    action: str  # "match" | "strategy_file" | "search"
    hp: HybridParallelConfig
    saved_hp: HybridParallelConfig
    provenance: Dict[str, Any]
    ckpt_iteration: Optional[int] = None

    @property
    def cross_strategy(self) -> bool:
        return self.action != "match"


def _budget_refusal(hp, model_cfg, budget_gb) -> Optional[D.Diagnostic]:
    """GLS203 when the strategy's estimated memory exceeds the budget on the
    surviving mesh — the linter only warns (GLS101); a refusal is right here
    because proceeding would OOM minutes into the resumed run."""
    from galvatron_tpu.analysis.strategy_lint import estimate_stage_memory_mb

    stage_mb = estimate_stage_memory_mb(hp, model_cfg)
    if stage_mb is None or not budget_gb:
        return None
    worst = max(stage_mb)
    if worst > budget_gb * 1024.0:
        return D.make(
            "GLS203", "stage memory estimated at %.2f GB exceeds the %.1f GB "
            "budget on the surviving %d-device mesh; lower the batch/enable "
            "checkpointing via --elastic_strategy, or raise "
            "--elastic_memory_gb" % (worst / 1024.0, budget_gb, hp.world_size),
        )
    return None


def resolve_resume_strategy(
    args: Any,
    model_cfg: Any,
    live_world: int,
    opt_args: Any = None,
) -> ElasticPlan:
    """Decide the strategy for an elastic resume (--elastic resume|search).

    Raises DiagnosticError (GLS2xx) whenever resuming would corrupt or
    silently degrade training; the train CLI maps that to exit code 2."""
    from galvatron_tpu.runtime import checkpoint as ckpt

    mode = getattr(args, "elastic", "off")
    it, prov = ckpt.read_provenance(args.load)
    if prov is None:
        raise D.DiagnosticError([D.make(
            "GLS204", "checkpoint %s has no provenance manifest — it predates "
            "elastic resume; resume it on the original mesh with --elastic "
            "off (one save there upgrades it)" % args.load,
        )])
    live_digest = model_config_digest(model_cfg)
    if prov.get("model_digest") and prov["model_digest"] != live_digest:
        raise D.DiagnosticError([D.make(
            "GLS201", "checkpoint %s was written for a different model "
            "config (digest %s.. != %s..): elastic resume re-plans the "
            "PARALLELISM, never the model" % (
                args.load, prov["model_digest"][:12], live_digest[:12]),
        )])
    if opt_args is not None and prov.get("optimizer", {}).get("digest"):
        if prov["optimizer"]["digest"] != optimizer_digest(opt_args):
            telemetry.runtime_log(
                "elastic: optimizer hyperparams differ from the checkpoint's "
                "(%s); continuing — the structural guard still applies"
                % prov["optimizer"].get("kind", "?")
            )
    saved_world = int(prov.get("world_size", live_world))
    exec_kw = dict(
        scan_layers=getattr(args, "scan_layers", True),
        remat_policy=getattr(args, "remat_policy", "full"),
        tp_comm_mode=getattr(args, "tp_comm_mode", "gspmd"),
        tp_comm_quant=getattr(args, "tp_comm_quant", "none"),
        mixed_precision=getattr(args, "mixed_precision", "bf16"),
    )
    # NB grad/param comm dtypes + comm_quant_block are serialized per-layer
    # strategy fields, so they ride prov["strategy"] through resume,
    # re-search fallback excepted (a re-searched strategy starts at 'none')
    saved_hp = HybridParallelConfig.from_json(
        dict(prov["strategy"]), world_size=saved_world, **exec_kw)
    budget = getattr(args, "elastic_memory_gb", None) or prov.get(
        "memory_budget_gb") or DEFAULT_MEMORY_GB

    strategy_file = getattr(args, "elastic_strategy", None)
    if saved_world == live_world and not strategy_file:
        # nothing changed: resume under the saved strategy, bitwise identical
        # to a plain --load (the checkpoint's strategy wins over GLOBAL flags
        # so a stale launch script cannot silently fork the trajectory). An
        # EXPLICIT --elastic_strategy is different from stale flags: the
        # operator deliberately re-plans (e.g. validating a live-migration
        # target offline), so it is honored below even on a matching world.
        telemetry.emit(
            "elastic", action="match", saved_world=saved_world,
            live_world=live_world)
        return ElasticPlan("match", saved_hp, saved_hp, prov, it)

    if strategy_file:
        hp = HybridParallelConfig.from_json(
            strategy_file, world_size=live_world, **exec_kw)
        if saved_world == live_world and hp.to_json_dict() == saved_hp.to_json_dict():
            # the supplied file IS the saved strategy: the cheaper bitwise
            # same-strategy restore applies
            telemetry.emit(
                "elastic", action="match", saved_world=saved_world,
                live_world=live_world)
            return ElasticPlan("match", saved_hp, saved_hp, prov, it)
        if hp.global_bsz != saved_hp.global_bsz:
            telemetry.runtime_log(
                "elastic: --elastic_strategy changes global_bsz %d -> %d; "
                "the loss trajectory will not be comparable to the original "
                "run" % (saved_hp.global_bsz, hp.global_bsz)
            )
        action = "strategy_file"
    elif mode == "search":
        hp = search_surviving_strategy(
            model_cfg, live_world, saved_hp.global_bsz, budget,
            model_type=getattr(args, "model_type", "model"),
            config_dir=getattr(args, "config_dir", None),
            default_dp_type=saved_hp.default_dp_type,
        )
        if hp is None:
            raise D.DiagnosticError([D.make(
                "GLS203", "no strategy for %d surviving devices fits "
                "global_bsz=%d under the %.1f GB budget; shrink the batch "
                "with --elastic_strategy or raise --elastic_memory_gb"
                % (live_world, saved_hp.global_bsz, budget),
            )])
        for k, v in exec_kw.items():
            setattr(hp, k, v)
        action = "search"
    else:
        raise D.DiagnosticError([D.make(
            "GLS205", "world size changed %d -> %d: pass a replacement "
            "strategy via --elastic_strategy, or let the search engine "
            "re-plan with --elastic search" % (saved_world, live_world),
        )])

    from galvatron_tpu.analysis import strategy_lint as _slint

    report = _slint.lint_hp(hp, model_cfg=model_cfg)
    if not report.ok:
        raise D.DiagnosticError(report.errors)
    if action == "strategy_file":
        # the search engine enforced the budget itself (possibly against
        # profiled tables); a hand-supplied strategy gets the analytic check
        refusal = _budget_refusal(hp, model_cfg, budget)
        if refusal is not None:
            raise D.DiagnosticError([refusal])
    telemetry.emit(
        "elastic", action=action, saved_world=saved_world, live_world=live_world)
    return ElasticPlan(action, hp, saved_hp, prov, it)


# ------------------------------------------------------- in-memory migration
@dataclass
class MigrationResult:
    """What :func:`migrate` produced: run `model` with `params`/`opt_state`
    from here on. `same_layout` records whether the swap was a pure
    on-device reshard (no host round trip, no tree rewrite)."""

    model: Any
    params: Any
    opt_state: Any
    same_layout: bool
    from_hp: HybridParallelConfig
    to_hp: HybridParallelConfig


def resolve_migration_strategy(
    args: Any,
    model_cfg: Any,
    live_world: int,
    current_hp: HybridParallelConfig,
) -> Tuple[HybridParallelConfig, str]:
    """Pick the target strategy for a LIVE migration: the operator-supplied
    ``--elastic_strategy`` JSON when given, otherwise a fresh search for
    `live_world` under the memory budget. Returns (hp, action).

    Raises DiagnosticError: GLS203 when nothing fits the budget, GLS207
    when the candidate would fork the training trajectory (a different
    global batch makes "continue from the same step" meaningless — unlike
    a disk resume, a live migration exists only to preserve the run)."""
    exec_kw = dict(
        scan_layers=current_hp.scan_layers,
        remat_policy=current_hp.remat_policy,
        tp_comm_mode=current_hp.tp_comm_mode,
        tp_comm_quant=current_hp.tp_comm_quant,
        mixed_precision=current_hp.mixed_precision,
    )
    budget = getattr(args, "elastic_memory_gb", None) or DEFAULT_MEMORY_GB
    strategy_file = getattr(args, "elastic_strategy", None)
    if strategy_file:
        hp = HybridParallelConfig.from_json(
            strategy_file, world_size=live_world, **exec_kw)
        action = "strategy_file"
    else:
        hp = search_surviving_strategy(
            model_cfg, live_world, current_hp.global_bsz, budget,
            model_type=getattr(args, "model_type", "model"),
            config_dir=getattr(args, "config_dir", None),
            default_dp_type=current_hp.default_dp_type,
        )
        if hp is None:
            raise D.DiagnosticError([D.make(
                "GLS203", "no strategy for %d surviving devices fits "
                "global_bsz=%d under the %.1f GB budget; supply one with "
                "--elastic_strategy or raise --elastic_memory_gb"
                % (live_world, current_hp.global_bsz, budget),
            )])
        for k, v in exec_kw.items():
            setattr(hp, k, v)
        action = "search"
    if hp.global_bsz != current_hp.global_bsz:
        raise D.DiagnosticError([D.make(
            "GLS207", "live migration cannot change global_bsz (%d -> %d): "
            "the run would fork its own trajectory; stop and resume from a "
            "checkpoint instead" % (current_hp.global_bsz, hp.global_bsz),
        )])
    from galvatron_tpu.analysis import strategy_lint as _slint

    report = _slint.lint_hp(hp, model_cfg=model_cfg)
    if not report.ok:
        raise D.DiagnosticError(report.errors)
    if action == "strategy_file":
        refusal = _budget_refusal(hp, model_cfg, budget)
        if refusal is not None:
            raise D.DiagnosticError([refusal])
    return hp, action


def migrate(
    model: Any,
    params: Any,
    opt_state: Any,
    tx: Any,
    target_hp: HybridParallelConfig,
    devices: Any = None,
    build_model: Any = None,
    reason: str = "manual",
    iteration: Optional[int] = None,
    sdc_check: bool = False,
) -> MigrationResult:
    """Hot-swap the LIVE training state onto `target_hp` without a
    checkpoint round-trip.

    - Same pipeline layout (the common case — dp<->tp<->zero reshards,
      world shrink/grow with unchanged stacking): the params/opt_state
      TREES are already right, so the move is one sharded ``device_put``
      per tree onto the new model's shardings — pure on-device data
      movement, bit-exact.
    - Pipeline-layout change (pp on/off, different division): the stacked
      ``stages`` tree is re-laid-out leaf-exactly through the same
      ``_relayout_tree`` family the cross-layout checkpoint restore uses,
      then placed. Adam moments travel with their params.
    - Refusals (GLS207): custom-param-tree families (t5/swin) across
      layouts — ``_relayout_tree`` only knows the generic transformer tree
      — and an opt_state whose re-laid-out structure does not match the
      target optimizer's (corrupting moments silently would be worse than
      stopping).

    `build_model` overrides model construction for families with their own
    build hook; `devices` selects the surviving device subset on a shrink.
    With `sdc_check` the layout-invariant integrity digest (runtime/sdc.py)
    is recorded before the move and asserted unchanged after relayout +
    placement — GLS016 refusal instead of silently garbling state. The swap
    is logged as an ``elastic`` telemetry event carrying the full
    before/after strategy JSON."""
    import jax

    from galvatron_tpu.runtime import checkpoint as ckpt
    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    sdc = None
    params_fold = opt_fold = None
    if sdc_check:
        from galvatron_tpu.runtime import sdc

        params_fold = sdc.host_tree_fold(params)
        if opt_state is not None:
            opt_fold = sdc.host_tree_fold(opt_state)

    old_hp: HybridParallelConfig = model.hp
    same_layout = ckpt._same_param_layout(old_hp, target_hp)
    if not same_layout and model.init_fn is not None:
        raise D.DiagnosticError([D.make(
            "GLS207", "live migration across pipeline layouts (pp %s -> pp "
            "%s) is only supported for the generic transformer tree; this "
            "family builds its own params" % (old_hp.pp, target_hp.pp),
        )])
    if target_hp.global_bsz != old_hp.global_bsz:
        raise D.DiagnosticError([D.make(
            "GLS207", "live migration cannot change global_bsz (%d -> %d)"
            % (old_hp.global_bsz, target_hp.global_bsz),
        )])
    t0 = time.perf_counter()
    if build_model is not None:
        new_model = build_model(model.cfg, target_hp, devices)
    else:
        new_model = construct_hybrid_parallel_model(model.cfg, target_hp, devices)

    if same_layout:
        new_params = jax.device_put(params, new_model.shardings())
    else:
        new_params = jax.device_put(
            ckpt._relayout_tree(params, old_hp, target_hp), new_model.shardings())

    new_opt = opt_state
    if opt_state is not None and tx is not None:
        relaid = opt_state if same_layout else ckpt._relayout_tree(
            opt_state, old_hp, target_hp)
        target_abs_params = new_model.abstract_params()
        target_abs_opt = jax.eval_shape(tx.init, target_abs_params)
        got = [(jax.tree_util.keystr(p), tuple(l.shape)) for p, l in
               jax.tree_util.tree_flatten_with_path(relaid)[0]]
        want = [(jax.tree_util.keystr(p), tuple(l.shape)) for p, l in
                jax.tree_util.tree_flatten_with_path(target_abs_opt)[0]]
        if got != want:
            diffs = [(g, w) for g, w in zip(got, want) if g != w][:3]
            raise D.DiagnosticError([D.make(
                "GLS207", "re-laid-out opt_state does not match the target "
                "optimizer tree (%d vs %d leaves; first diffs: %s)"
                % (len(got), len(want), diffs),
            )])
        new_opt = jax.device_put(
            relaid, new_model.opt_state_shardings(tx, target_abs_params))

    if sdc_check:
        # the whole move — stage restack + sharded device_put — is
        # value-preserving by contract; the layout-invariant fold proves it
        sdc.assert_digest_continuity(
            params_fold, new_params, "migrate(params)", iteration=iteration)
        if opt_fold is not None and new_opt is not None:
            sdc.assert_digest_continuity(
                opt_fold, new_opt, "migrate(opt_state)", iteration=iteration)

    telemetry.emit(
        "elastic", action="migrate", reason=reason, iter=iteration,
        saved_world=old_hp.world_size, live_world=target_hp.world_size,
        from_strategy=old_hp.to_json_dict(), to_strategy=target_hp.to_json_dict(),
        duration_ms=(time.perf_counter() - t0) * 1e3,
        same_layout=same_layout,
    )
    return MigrationResult(
        model=new_model, params=new_params, opt_state=new_opt,
        same_layout=same_layout, from_hp=old_hp, to_hp=target_hp,
    )


# ------------------------------------------------- degraded-mesh serve path
def search_surviving_serve_strategy(
    model_cfg: Any,
    live_world: int,
    memory_budget_gb: float,
    serve_max_concurrency: int,
    serve_page_size: int,
    p99_ttft_ms: float = 0.0,
    p99_tpot_ms: float = 0.0,
    model_type: str = "model",
    config_dir: Optional[str] = None,
    default_dp_type: str = "ddp",
    logger=None,
) -> HybridParallelConfig:
    """Re-run ``search --objective serve`` for the surviving world: the same
    decode-compatible enumeration + ServeTimeCostModel pricing the offline
    serve search uses, fed profiled tables when available and the analytic
    fallback otherwise. Concurrency and page size are pinned to the RUNNING
    engine's values so in-flight journals stay replayable into the new
    cache. Raises GLS015 when no strategy is feasible on what survived."""
    from galvatron_tpu.search.engine import GalvatronSearchEngine, SearchArgs

    heads = getattr(model_cfg, "num_heads", None) or 1
    nkv = getattr(model_cfg, "num_kv_heads", None) or heads
    num_layers = getattr(model_cfg, "num_layers", 1)
    seq_len = getattr(model_cfg, "max_seq_len", 2048)
    hidden = getattr(model_cfg, "hidden_size", 1024)
    max_tp = 1
    while max_tp * 2 <= min(heads, live_world) and heads % (max_tp * 2) == 0:
        max_tp *= 2
    args = SearchArgs(
        memory_constraint=memory_budget_gb,
        max_tp_deg=max_tp,
        max_pp_deg=1,  # serve layouts are pp=1 by contract (GLS014)
        default_dp_type=default_dp_type,
        sp_space="tp",
        objective="serve",
        p99_ttft_ms=p99_ttft_ms,
        p99_tpot_ms=p99_tpot_ms,
        serve_max_concurrency=serve_max_concurrency,
        serve_page_size=serve_page_size,
        serve_kv_frac=nkv / heads,
    )
    engine = GalvatronSearchEngine(
        args, live_world,
        [{"hidden_size": hidden, "seq_len": seq_len, "layer_num": num_layers}],
        config_dir=config_dir or "configs", model_name=model_type, logger=logger,
    )
    profiles = None
    if config_dir:
        profiles = _load_profiled_tables(model_cfg, model_type, config_dir, live_world)
    if profiles is None:
        synth = analytic_model_profiles(model_cfg, max_tp=live_world)
        if synth is None:
            raise D.DiagnosticError([D.make(
                "GLS015", "cannot synthesize analytic cost tables for this "
                "model config — no way to re-plan serving for the %d "
                "surviving devices" % live_world,
            )])
        time_cfg, mem_cfg = synth
        allreduce, p2p, overlap = analytic_hardware_profiles(live_world)
    else:
        time_cfg, mem_cfg, allreduce, p2p, overlap = profiles
    engine.set_model_profiles(time_cfg, mem_cfg)
    engine.set_hardware_profiles(allreduce, p2p, overlap)
    engine.initialize_search_engine()
    try:
        result = engine.serve_optimization()
    except D.DiagnosticError as e:
        # the offline objective refuses with GLS014 ("this config cannot
        # serve"); mid-flight the refusal is about the DEGRADED WORLD
        raise D.DiagnosticError([D.make(
            "GLS015", "serve world infeasible after degradation: no serving "
            "strategy for the %d surviving devices (%s); drain and redeploy "
            "on a healthy slice" % (
                live_world,
                "; ".join(d.message for d in e.diagnostics)[:400]),
        )]) from e
    return engine.result_to_config(result)


def resolve_serve_migration_strategy(
    args: Any,
    model_cfg: Any,
    live_world: int,
    current_hp: HybridParallelConfig,
    kv_cfg: Any = None,
) -> Tuple[HybridParallelConfig, str]:
    """Pick the target strategy for a LIVE degraded-mesh serve migration:
    the operator-supplied ``--elastic_strategy`` JSON when given, otherwise
    a fresh ``--objective serve`` search for `live_world`. Returns
    (hp, action). Raises DiagnosticError (GLS015) when the surviving world
    cannot serve; the serve CLI maps that to exit code 2."""
    exec_kw = dict(
        scan_layers=current_hp.scan_layers,
        remat_policy=current_hp.remat_policy,
        tp_comm_mode=current_hp.tp_comm_mode,
        tp_comm_quant=current_hp.tp_comm_quant,
        mixed_precision=current_hp.mixed_precision,
    )
    budget = getattr(args, "elastic_memory_gb", None) or DEFAULT_MEMORY_GB
    concurrency = (getattr(kv_cfg, "max_slots", 0)
                   or current_hp.serve_max_concurrency or 8)
    page = (getattr(kv_cfg, "page_size", 0)
            or current_hp.serve_page_size or 16)
    strategy_file = getattr(args, "elastic_strategy", None)
    if strategy_file:
        hp = HybridParallelConfig.from_json(
            strategy_file, world_size=live_world, **exec_kw)
        action = "strategy_file"
    else:
        hp = search_surviving_serve_strategy(
            model_cfg, live_world, budget,
            serve_max_concurrency=concurrency, serve_page_size=page,
            p99_ttft_ms=getattr(args, "p99_ttft_ms", 0.0) or 0.0,
            p99_tpot_ms=getattr(args, "p99_tpot_ms", 0.0) or 0.0,
            model_type=getattr(args, "model_type", "model"),
            config_dir=getattr(args, "config_dir", None),
            default_dp_type=current_hp.default_dp_type,
        )
        for k, v in exec_kw.items():
            setattr(hp, k, v)
        action = "search"
    from galvatron_tpu.analysis import strategy_lint as _slint

    report = _slint.lint_hp(hp, model_cfg=model_cfg, mode="serve")
    if not report.ok:
        raise D.DiagnosticError([D.make(
            "GLS015", "serve world infeasible after degradation: the %s "
            "strategy for %d devices fails the serve lint (%s)" % (
                action, live_world,
                "; ".join("%s: %s" % (d.code, d.message)
                          for d in report.errors)[:400]),
        )])
    return hp, action


def migrate_serve_params(
    model: Any,
    params: Any,
    target_hp: HybridParallelConfig,
    devices: Any = None,
    build_model: Any = None,
    sdc_check: bool = False,
) -> Tuple[Any, Any, bool]:
    """Params-only live relayout for a serve migration: the inference twin
    of :func:`migrate` with no optimizer state and no trajectory checks
    (serving has no training trajectory to fork — global_bsz is inert).
    With `sdc_check` the layout-invariant digest is asserted unchanged
    across the move (GLS016 on mismatch), like :func:`migrate`.
    Returns (new_model, new_params, same_layout); the caller rebuilds the
    ServeEngine (fresh KV cache in the new layout) and journal-replays the
    in-flight requests (serve/engine.ContinuousBatcher.migrate_to)."""
    import jax

    from galvatron_tpu.runtime import checkpoint as ckpt
    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    params_fold = None
    if sdc_check:
        from galvatron_tpu.runtime import sdc

        params_fold = sdc.host_tree_fold(params)

    old_hp: HybridParallelConfig = model.hp
    same_layout = ckpt._same_param_layout(old_hp, target_hp)
    if not same_layout and model.init_fn is not None:
        raise D.DiagnosticError([D.make(
            "GLS015", "serve migration across pipeline layouts (pp %s -> pp "
            "%s) is only supported for the generic transformer tree; this "
            "family builds its own params" % (old_hp.pp, target_hp.pp),
        )])
    if build_model is not None:
        new_model = build_model(model.cfg, target_hp, devices)
    else:
        new_model = construct_hybrid_parallel_model(model.cfg, target_hp, devices)
    if same_layout:
        new_params = jax.device_put(params, new_model.shardings())
    else:
        new_params = jax.device_put(
            ckpt._relayout_tree(params, old_hp, target_hp), new_model.shardings())
    if params_fold is not None:
        from galvatron_tpu.runtime import sdc

        sdc.assert_digest_continuity(
            params_fold, new_params, "migrate_serve_params")
    return new_model, new_params, same_layout
