"""Elastic degraded-mesh resume: re-plan the strategy for the surviving mesh.

Galvatron's premise is that the optimal layer-wise strategy is a function of
the hardware (PAPER.md) — so when the hardware changes mid-run (TPU
preemption shrinking a slice, an ICI link flap dropping a host, a chip
failure), the right response is not "refuse to resume" but "re-optimize for
what survived". This module is the resume-side half of that story; the
save-side half is the provenance block runtime/checkpoint.py embeds in every
integrity manifest (:func:`build_provenance`).

On resume with ``--elastic {resume,search}`` the driver calls
:func:`resolve_resume_strategy`, which

1. reads the newest intact manifest's provenance (strategy JSON, mesh/device
   count, model-config digest, optimizer digest, chunks);
2. refuses with structured GLS2xx diagnostics (exit code 2 at the CLI) when
   the checkpoint cannot be resumed safely: different model-config digest
   (GLS201), no provenance at all (GLS204), a changed mesh with no way to
   pick a new strategy (GLS205), or no strategy that fits the memory budget
   on the surviving devices (GLS203);
3. on a world-size match returns the SAVED strategy — same-strategy resume
   stays bitwise identical to the non-elastic path;
4. on a mismatch either loads the user-supplied ``--elastic_strategy`` JSON
   or re-runs :class:`GalvatronSearchEngine` for the surviving world size
   under the same memory budget — with profiled cost tables when the config
   dir has them, and an analytic Megatron-style fallback (the same tables
   the strategy linter's GLS101 estimate uses) when it does not.

The actual cross-strategy restore (different shardings, different pipeline
layout, opt_state re-sharded leaf-wise with structural checks) is
``load_checkpoint(..., target=)`` in runtime/checkpoint.py.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from galvatron_tpu.analysis import diagnostics as D
from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.obs import telemetry

DEFAULT_MEMORY_GB = 16.0  # matches the search CLI's --memory_constraint default

# model-config fields excluded from the digest: precision knobs are runtime
# choices (the manifest's spec_digest machinery already handles a dtype
# change), not model identity
_DIGEST_EXCLUDE = ("compute_dtype", "param_dtype", "attn_impl")


def _stable_json(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, default=str)


def model_config_digest(model_cfg: Any) -> str:
    """sha256 over the model's architectural identity. Restoring a checkpoint
    into a model with a different digest is refused (GLS201): same-shaped
    trees with different semantics (e.g. swapped activation) would restore
    cleanly and train garbage."""
    if dataclasses.is_dataclass(model_cfg):
        fields = dataclasses.asdict(model_cfg)
    else:  # duck-typed configs (tests)
        fields = {k: v for k, v in vars(model_cfg).items() if not k.startswith("_")}
    fields = {k: str(v) for k, v in fields.items() if k not in _DIGEST_EXCLUDE}
    return hashlib.sha256(_stable_json(fields).encode()).hexdigest()


def optimizer_digest(opt_args: Any) -> str:
    """sha256 over the optimizer identity + hyperparams (runtime.optimizer
    .OptimizerArgs). A mismatch on resume is a warning, not a refusal — lr
    schedules legitimately change mid-run; the *structural* guard against a
    different optimizer lives in load_checkpoint (GLS202)."""
    fields = dataclasses.asdict(opt_args) if dataclasses.is_dataclass(opt_args) else dict(opt_args)
    return hashlib.sha256(_stable_json({k: str(v) for k, v in fields.items()}).encode()).hexdigest()


def build_provenance(
    hp: HybridParallelConfig,
    model_cfg: Any,
    opt_args: Any = None,
    mesh: Any = None,
    memory_budget_gb: Optional[float] = None,
) -> Dict[str, Any]:
    """The manifest provenance block: everything a future process on
    DIFFERENT hardware needs to decide how (or whether) to resume."""
    prov: Dict[str, Any] = {
        "format": 1,
        "strategy": hp.to_json_dict(),
        "world_size": hp.world_size,
        "chunks": hp.chunks,
        "global_bsz": hp.global_bsz,
        "mixed_precision": hp.mixed_precision,
        "model_digest": model_config_digest(model_cfg),
    }
    if mesh is not None:
        prov["mesh_shape"] = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        prov["device_count"] = int(mesh.devices.size)
    else:
        prov["device_count"] = hp.world_size
    if opt_args is not None:
        prov["optimizer"] = {
            "kind": type(opt_args).__name__,
            "digest": optimizer_digest(opt_args),
        }
    if memory_budget_gb:
        prov["memory_budget_gb"] = float(memory_budget_gb)
    return prov


# ------------------------------------------------------ analytic cost tables
def analytic_model_profiles(model_cfg: Any, max_tp: int) -> Optional[Tuple[dict, dict]]:
    """(time_config, memory_config) for GalvatronSearchEngine synthesized
    from the model config alone — the no-profiles fallback, built on the
    same analytic parameter/activation tables the strategy linter's GLS101
    estimate uses, so the elastic re-search and the linter agree on what
    fits. Timing is a flops-proportional constant: with no profiled tables
    every strategy's compute scales identically, so relative comparisons
    (what the DP needs) remain meaningful."""
    from galvatron_tpu.analysis.strategy_lint import (
        _analytic_activation_dict,
        _analytic_parameter_mb,
    )

    param_mb = _analytic_parameter_mb(model_cfg)
    act = _analytic_activation_dict(model_cfg, max_tp)
    if param_mb is None or not act:
        return None
    h = getattr(model_cfg, "hidden_size", 1024)
    s = getattr(model_cfg, "max_seq_len", 2048)
    # ~12*s*h^2 flops/token forward; an arbitrary-but-fixed throughput turns
    # it into ms/layer/sample (only ratios matter without profiles)
    fwd_ms = 12.0 * s * h * h / 1e12 * 1e3
    time_config = {"layertype_0": max(fwd_ms, 1e-3), "other_time": max(fwd_ms, 1e-3)}
    states = {}
    t = 1
    while t <= max_tp:
        # embed/head model states (params + grads + adam moments ~ 16 bytes/
        # param fp32-master) sharded over vocab tp
        vocab = getattr(model_cfg, "vocab_size", 0) or 0
        states[t] = vocab * h * 16.0 / 2**20 / t
        t *= 2
    act_other = {k: v for k, v in act.items() if k != "checkpoint"}
    memory_config = {
        "layertype_0": {
            "parameter_size": param_mb,
            "tp_activation_per_bsz_dict": dict(act),
        },
        "other_memory_pp_off": {"model_states": dict(states), "activation": dict(act_other)},
        "other_memory_pp_on": {
            "first_stage": {"model_states": {k: v / 2 for k, v in states.items()},
                            "activation": {k: v / 2 for k, v in act_other.items()}},
            "last_stage": {"model_states": {k: v / 2 for k, v in states.items()},
                           "activation": {k: v / 2 for k, v in act_other.items()}},
        },
    }
    return time_config, memory_config


def analytic_hardware_profiles(world: int) -> Tuple[dict, dict, dict]:
    """(allreduce, p2p, overlap) coefficient JSONs for the no-profiles
    fallback: flat plausible ICI bandwidths — without measurements every
    collective is priced identically per byte, which still ranks strategies
    by communication VOLUME (the dominant analytic signal)."""
    allreduce = {}
    size = 2
    while size <= world:
        allreduce["allreduce_size_%d_consec_1" % size] = 100.0
        allreduce["allreduce_size_%d_consec_0" % size] = 80.0
        size *= 2
    p2p = {}
    size = 2
    while size <= world:
        p2p["pp_size_%d" % size] = 120.0
        size *= 2
    return allreduce, p2p, {"overlap_coe": 1.1}


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def search_surviving_strategy(
    model_cfg: Any,
    live_world: int,
    global_bsz: int,
    memory_budget_gb: float,
    model_type: str = "model",
    config_dir: Optional[str] = None,
    default_dp_type: str = "ddp",
    logger=None,
) -> Optional[HybridParallelConfig]:
    """Re-run the strategy search for the surviving world size under the
    same global batch and memory budget. Profiled tables are used when
    `config_dir` has them for this model; otherwise the analytic fallback.
    Returns None when nothing fits (the caller turns that into GLS203)."""
    from galvatron_tpu.search.engine import GalvatronSearchEngine, SearchArgs

    heads = getattr(model_cfg, "num_heads", None) or 1
    num_layers = getattr(model_cfg, "num_layers", 1)
    seq_len = getattr(model_cfg, "max_seq_len", 2048)
    hidden = getattr(model_cfg, "hidden_size", 1024)
    # cap tp at the largest power of two dividing the head count so every
    # emitted strategy passes the model-aware GLS007 check
    max_tp = 1
    while max_tp * 2 <= min(heads, live_world) and heads % (max_tp * 2) == 0:
        max_tp *= 2
    args = SearchArgs(
        memory_constraint=memory_budget_gb,
        settle_bsz=global_bsz,  # the batch is part of the training trajectory
        settle_chunk=None,
        max_tp_deg=max_tp,
        max_pp_deg=min(_pow2_floor(num_layers), live_world),
        default_dp_type=default_dp_type,
        sp_space="tp",
    )
    engine = GalvatronSearchEngine(
        args, live_world,
        [{"hidden_size": hidden, "seq_len": seq_len, "layer_num": num_layers}],
        config_dir=config_dir or "configs", model_name=model_type, logger=logger,
    )
    profiles = None
    if config_dir:
        profiles = _load_profiled_tables(model_cfg, model_type, config_dir, live_world)
    if profiles is None:
        synth = analytic_model_profiles(model_cfg, max_tp=live_world)
        if synth is None:
            return None
        time_cfg, mem_cfg = synth
        allreduce, p2p, overlap = analytic_hardware_profiles(live_world)
    else:
        time_cfg, mem_cfg, allreduce, p2p, overlap = profiles
    engine.set_model_profiles(time_cfg, mem_cfg)
    engine.set_hardware_profiles(allreduce, p2p, overlap)
    engine.initialize_search_engine()
    result = engine.parallelism_optimization()
    if result is None:
        return None
    return engine.result_to_config(result)


def _load_profiled_tables(model_cfg, model_type, config_dir, world):
    """The profiled-table path of the elastic re-search: the same files the
    search CLI reads (cli/search.py). None when any required table is
    missing or unreadable — the analytic fallback takes over."""
    try:
        from galvatron_tpu.profiler.model import ModelProfileArgs, ModelProfiler
        from galvatron_tpu.utils.jsonio import read_json_config

        prof = ModelProfiler(model_cfg, model_name=model_type,
                             args=ModelProfileArgs(config_dir=config_dir))
        mp = prof.config_paths()
        time_cfg = read_json_config(mp["computation"])
        mem_cfg = read_json_config(mp["memory"])
        tag = "%dchips" % world
        allreduce = read_json_config(
            os.path.join(config_dir, "allreduce_bandwidth_%s.json" % tag))
        p2p_path = os.path.join(config_dir, "p2p_bandwidth_%s.json" % tag)
        p2p = read_json_config(p2p_path) if os.path.exists(p2p_path) else None
        ov_path = os.path.join(config_dir, "overlap_coefficient.json")
        overlap = read_json_config(ov_path) if os.path.exists(ov_path) else None
        return time_cfg, mem_cfg, allreduce, p2p, overlap
    except (OSError, ValueError, KeyError, TypeError):
        return None


# ------------------------------------------------------------- resume planning
@dataclass
class ElasticPlan:
    """What resolve_resume_strategy decided: run `hp` now; the checkpoint
    was written under `saved_hp` (load_checkpoint's cross-strategy restore
    needs it)."""

    action: str  # "match" | "strategy_file" | "search"
    hp: HybridParallelConfig
    saved_hp: HybridParallelConfig
    provenance: Dict[str, Any]
    ckpt_iteration: Optional[int] = None

    @property
    def cross_strategy(self) -> bool:
        return self.action != "match"


def _budget_refusal(hp, model_cfg, budget_gb) -> Optional[D.Diagnostic]:
    """GLS203 when the strategy's estimated memory exceeds the budget on the
    surviving mesh — the linter only warns (GLS101); a refusal is right here
    because proceeding would OOM minutes into the resumed run."""
    from galvatron_tpu.analysis.strategy_lint import estimate_stage_memory_mb

    stage_mb = estimate_stage_memory_mb(hp, model_cfg)
    if stage_mb is None or not budget_gb:
        return None
    worst = max(stage_mb)
    if worst > budget_gb * 1024.0:
        return D.make(
            "GLS203", "stage memory estimated at %.2f GB exceeds the %.1f GB "
            "budget on the surviving %d-device mesh; lower the batch/enable "
            "checkpointing via --elastic_strategy, or raise "
            "--elastic_memory_gb" % (worst / 1024.0, budget_gb, hp.world_size),
        )
    return None


def resolve_resume_strategy(
    args: Any,
    model_cfg: Any,
    live_world: int,
    opt_args: Any = None,
) -> ElasticPlan:
    """Decide the strategy for an elastic resume (--elastic resume|search).

    Raises DiagnosticError (GLS2xx) whenever resuming would corrupt or
    silently degrade training; the train CLI maps that to exit code 2."""
    from galvatron_tpu.runtime import checkpoint as ckpt

    mode = getattr(args, "elastic", "off")
    it, prov = ckpt.read_provenance(args.load)
    if prov is None:
        raise D.DiagnosticError([D.make(
            "GLS204", "checkpoint %s has no provenance manifest — it predates "
            "elastic resume; resume it on the original mesh with --elastic "
            "off (one save there upgrades it)" % args.load,
        )])
    live_digest = model_config_digest(model_cfg)
    if prov.get("model_digest") and prov["model_digest"] != live_digest:
        raise D.DiagnosticError([D.make(
            "GLS201", "checkpoint %s was written for a different model "
            "config (digest %s.. != %s..): elastic resume re-plans the "
            "PARALLELISM, never the model" % (
                args.load, prov["model_digest"][:12], live_digest[:12]),
        )])
    if opt_args is not None and prov.get("optimizer", {}).get("digest"):
        if prov["optimizer"]["digest"] != optimizer_digest(opt_args):
            telemetry.runtime_log(
                "elastic: optimizer hyperparams differ from the checkpoint's "
                "(%s); continuing — the structural guard still applies"
                % prov["optimizer"].get("kind", "?")
            )
    saved_world = int(prov.get("world_size", live_world))
    exec_kw = dict(
        scan_layers=getattr(args, "scan_layers", True),
        remat_policy=getattr(args, "remat_policy", "full"),
        mixed_precision=getattr(args, "mixed_precision", "bf16"),
    )
    saved_hp = HybridParallelConfig.from_json(
        dict(prov["strategy"]), world_size=saved_world, **exec_kw)
    budget = getattr(args, "elastic_memory_gb", None) or prov.get(
        "memory_budget_gb") or DEFAULT_MEMORY_GB

    if saved_world == live_world:
        # nothing changed: resume under the saved strategy, bitwise identical
        # to a plain --load (the checkpoint's strategy wins over GLOBAL flags
        # so a stale launch script cannot silently fork the trajectory)
        telemetry.emit(
            "elastic", action="match", saved_world=saved_world,
            live_world=live_world)
        return ElasticPlan("match", saved_hp, saved_hp, prov, it)

    strategy_file = getattr(args, "elastic_strategy", None)
    if strategy_file:
        hp = HybridParallelConfig.from_json(
            strategy_file, world_size=live_world, **exec_kw)
        if hp.global_bsz != saved_hp.global_bsz:
            telemetry.runtime_log(
                "elastic: --elastic_strategy changes global_bsz %d -> %d; "
                "the loss trajectory will not be comparable to the original "
                "run" % (saved_hp.global_bsz, hp.global_bsz)
            )
        action = "strategy_file"
    elif mode == "search":
        hp = search_surviving_strategy(
            model_cfg, live_world, saved_hp.global_bsz, budget,
            model_type=getattr(args, "model_type", "model"),
            config_dir=getattr(args, "config_dir", None),
            default_dp_type=saved_hp.default_dp_type,
        )
        if hp is None:
            raise D.DiagnosticError([D.make(
                "GLS203", "no strategy for %d surviving devices fits "
                "global_bsz=%d under the %.1f GB budget; shrink the batch "
                "with --elastic_strategy or raise --elastic_memory_gb"
                % (live_world, saved_hp.global_bsz, budget),
            )])
        for k, v in exec_kw.items():
            setattr(hp, k, v)
        action = "search"
    else:
        raise D.DiagnosticError([D.make(
            "GLS205", "world size changed %d -> %d: pass a replacement "
            "strategy via --elastic_strategy, or let the search engine "
            "re-plan with --elastic search" % (saved_world, live_world),
        )])

    from galvatron_tpu.analysis import strategy_lint as _slint

    report = _slint.lint_hp(hp, model_cfg=model_cfg)
    if not report.ok:
        raise D.DiagnosticError(report.errors)
    if action == "strategy_file":
        # the search engine enforced the budget itself (possibly against
        # profiled tables); a hand-supplied strategy gets the analytic check
        refusal = _budget_refusal(hp, model_cfg, budget)
        if refusal is not None:
            raise D.DiagnosticError([refusal])
    telemetry.emit(
        "elastic", action=action, saved_world=saved_world, live_world=live_world)
    return ElasticPlan(action, hp, saved_hp, prov, it)
