"""Silent-data-corruption sentinel: digests, replica voting, quarantine.

Every failure the runtime survives today is *loud*: AnomalyGuard catches
non-finite losses, the Watchdog catches hangs, MeshHealthMonitor catches
enumeration/collective failures. A marginal accelerator that returns
finite-but-wrong values passes all three, poisons the optimizer state, and
gets sha256-sealed into "intact" checkpoints. This module is the sentinel
the driver (cli/train.py) wires in under ``--sdc_check``, in three legs:

1. **In-jit integrity digests** (:func:`tree_fold_metrics`): a cheap,
   deterministic, *sharding-layout-invariant* tree digest — every leaf
   bitcast to uint32 words and folded with wraparound addition mod 2^32
   (commutative + associative, so the fold is bitwise identical no matter
   how the elements are sharded, restacked across pipeline stages, or
   reduced), plus an fp32 sum-of-squares for telemetry trend lines (floats
   do NOT sum order-invariantly; only the integer fold is compared
   exactly). Inside an auto-GSPMD jit the sums are global (the partitioner
   inserts the exact all-reduce); inside a ``shard_map`` manual region they
   are per-shard, which is exactly what the voting leg wants.
   :func:`host_tree_fold` is the numpy twin — the same mod-2^32 fold
   computed host-side, bitwise equal to the device fold.

2. **Cross-replica voting** (:func:`make_vote_digest_fn` +
   :class:`VoteLadder`): pure-dp layouts hold a full parameter replica per
   device — redundancy the runtime gets for free. A ``shard_map`` manual
   over the dp axes digests each device's *input-param* replica
   independently; a device whose memory or ALU lies shows a divergent
   digest and is *localized*, not just detected. The step freezes
   params/opt_state in-jit on any disagreement (the AnomalyGuard keep-old
   select machinery), so a lying replica cannot leak into the psummed
   update; the driver repairs from a healthy replica
   (:func:`repair_from_replica`), re-executes the step, and escalates a
   persistently-striking device through :class:`VoteLadder` into a
   quarantine verdict that ``MeshHealthMonitor`` turns into the existing
   ``--migrate_on_degrade`` path — re-search + in-memory relayout, no
   checkpoint round-trip.

3. **Digest continuity across state motion**
   (:func:`assert_digest_continuity`): ``elastic.migrate``, cross-layout
   ``load_checkpoint(target=)``, and serve param migration are all
   value-preserving by contract; because the fold is layout-invariant it
   can be asserted unchanged end-to-end across any relayout, refusing with
   GLS016 instead of silently garbling state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

SDC_MODES = ("off", "digest", "vote")

_MASK32 = (1 << 32) - 1


# ------------------------------------------------------------ device digests
def _leaf_bits_u32(x) -> jnp.ndarray:
    """`x` reinterpreted as uint32 words (8-byte dtypes become two words per
    element via a trailing dim; sub-32-bit dtypes zero-extend)."""
    x = jnp.asarray(x)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    nbits = x.dtype.itemsize * 8
    if nbits > 32:
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    bits = jax.lax.bitcast_convert_type(x, jnp.dtype("uint%d" % nbits))
    return bits.astype(jnp.uint32)


def tree_fold_metrics(tree) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(fold, sumsq) integrity digest of a pytree, traceable inside jit.

    ``fold`` (uint32) is the wraparound sum of every leaf's uint32 bit
    words — exact, deterministic, and invariant to element order, sharding
    layout, and layers<->stages restacking, so the same state yields the
    same fold under any strategy. ``sumsq`` (float32) is the sum of squares
    of the float leaves — a cheap magnitude trend for telemetry, NOT
    order-exact; comparisons use ``fold`` only.
    """
    fold = jnp.uint32(0)
    sumsq = jnp.float32(0.0)
    for leaf in jax.tree.leaves(tree):
        arr = jnp.asarray(leaf)
        if not arr.size:
            continue
        fold = fold + jnp.sum(_leaf_bits_u32(arr), dtype=jnp.uint32)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            sumsq = sumsq + jnp.sum(jnp.square(arr.astype(jnp.float32)))
    return fold, sumsq


def host_tree_fold(tree) -> int:
    """Numpy twin of :func:`tree_fold_metrics`'s fold: the same mod-2^32
    word sum computed host-side (pulls device arrays to host — gate usage
    behind ``--sdc_check``). Bitwise equal to the in-jit fold because
    addition mod 2^32 is exact in any order; overflowing the uint64
    accumulator is harmless since 2^32 divides 2^64."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        a = np.asarray(leaf)
        if a.dtype == np.bool_:
            a = a.astype(np.uint8)
        if not a.size:
            continue
        width = min(a.dtype.itemsize * 8, 32)
        words = np.ascontiguousarray(a).reshape(-1).view(np.dtype("uint%d" % width))
        total = (total + int(words.sum(dtype=np.uint64))) & _MASK32
    return total


# ----------------------------------------------------------- replica voting
def vote_reason(hp) -> Optional[str]:
    """None when per-replica voting is expressible for this strategy, else
    the reason it is not. Voting digests each device's full parameter
    replica under a shard_map manual over the dp axes — the same platform
    envelope as the quantized-collectives path: every non-dp form of
    parallelism must be off (a sharded replica is not a replica), and the
    optimizer state must be dp-replicated too so a lying device can be
    repaired from any healthy peer. strategy_lint mirrors this as a GLS103
    downgrade warning; the train driver falls back to digest mode."""
    if hp.pp > 1:
        return ("pp=%d: pipeline stages hold disjoint layer shards, not "
                "full replicas" % hp.pp)
    for i, s in enumerate(hp.layers):
        if s.tp > 1 or s.cp > 1 or s.sp:
            return ("layer %d: tp=%d cp=%d sp=%d shard the parameters; "
                    "voting needs a full per-device replica (pure-dp "
                    "layout)" % (i, s.tp, s.cp, int(s.sp)))
        if s.fsdp:
            return ("layer %d: fsdp=1 (ZeRO-3) shards parameters over dp; "
                    "there is no per-device replica to vote on" % i)
    if hp.vocab_tp > 1 or hp.vocab_cp > 1 or getattr(hp, "embed_sdp", 0):
        return ("embed/head sharding (vtp=%d vcp=%d embed_sdp=%d) leaves "
                "no full per-device replica"
                % (hp.vocab_tp, hp.vocab_cp, int(getattr(hp, "embed_sdp", 0))))
    if getattr(hp, "default_dp_type", "ddp") != "ddp":
        return ("default_dp_type=%r shards optimizer state over dp; replica "
                "repair needs dp-replicated state" % hp.default_dp_type)
    if hp.dp(0) < 2:
        return "dp=1: voting needs at least two data-parallel replicas"
    return None


def vote_supported(model) -> Tuple[bool, Optional[str]]:
    """(ok, reason) for an already-built HybridParallelModel."""
    reason = vote_reason(model.hp)
    return reason is None, reason


def dp_axes_of(model) -> Tuple[str, ...]:
    from galvatron_tpu.parallel.mesh import layer_axes

    return tuple(layer_axes(model.hp, 0).dp)


def make_vote_digest_fn(model):
    """``params -> uint32[dp_sizes...]``: each device's digest of its own
    parameter replica, computed under a ``shard_map`` manual over the dp
    mesh axes (every other axis has size 1 under :func:`vote_reason`'s
    envelope — the quant_collectives partial-manual pattern, which legacy
    shard_map compiles). The output's flat order matches
    :func:`vote_device_ids`."""
    dp_axes = dp_axes_of(model)
    mesh, p_specs = model.mesh, model.param_specs

    def body(params_loc):
        fold, _ = tree_fold_metrics(params_loc)
        return fold.reshape((1,) * len(dp_axes))

    def vote(params):
        return jax.shard_map(
            body, mesh=mesh, in_specs=(p_specs,),
            out_specs=P(*dp_axes), axis_names=set(dp_axes),
        )(params)

    return vote


def vote_device_ids(mesh, dp_axes: Sequence[str]) -> List[int]:
    """Device id behind each flat vote index: the mesh device grid
    transposed so the dp axes come first (in ``dp_axes`` order), then
    flattened C-order — the same order ``out_specs=P(*dp_axes)``
    concatenates per-device outputs in."""
    names = list(mesh.axis_names)
    order = [names.index(a) for a in dp_axes] + [
        i for i, a in enumerate(names) if a not in dp_axes
    ]
    grid = np.transpose(mesh.devices, order)
    n = int(np.prod([mesh.shape[a] for a in dp_axes]))
    return [int(d.id) for d in grid.reshape(n, -1)[:, 0]]


@dataclass
class VoteLadder:
    """Host-side strike ladder over per-replica digest votes.

    One :meth:`observe` per drained vote round. A unanimous round resets
    the ladder. A round with a strict-majority digest localizes the
    dissenting device(s); each consecutive localization strikes them, and
    ``strikes`` consecutive strikes escalate to a ``quarantine`` action. A
    tied round (e.g. dp=2 disagreeing 1-1) is a detection without a
    culprit: re-execute, never quarantine."""

    strikes: int = 2
    _consecutive: Dict[int, int] = field(default_factory=dict, repr=False)

    def observe(self, folds: Sequence[int], device_ids: Sequence[int]) -> Dict[str, Any]:
        folds = [int(f) for f in folds]
        ids = [int(i) for i in device_ids]
        counts: Dict[int, int] = {}
        for f in folds:
            counts[f] = counts.get(f, 0) + 1
        majority_fold, majority_n = max(counts.items(), key=lambda kv: kv[1])
        if len(counts) == 1:
            self._consecutive.clear()
            return {"ok": True, "action": "none", "suspects": [],
                    "quarantine": [], "strikes": {}}
        if majority_n * 2 <= len(folds):
            # no strict majority: detected, not localizable
            return {"ok": False, "action": "reexecute", "suspects": [],
                    "quarantine": [], "strikes": dict(self._consecutive)}
        suspects = [i for i, f in zip(ids, folds) if f != majority_fold]
        for d in list(self._consecutive):
            if d not in suspects:
                del self._consecutive[d]
        for d in suspects:
            self._consecutive[d] = self._consecutive.get(d, 0) + 1
        quarantine = [d for d in suspects if self._consecutive[d] >= self.strikes]
        return {
            "ok": False,
            "action": "quarantine" if quarantine else "reexecute",
            "suspects": suspects,
            "quarantine": quarantine,
            "strikes": dict(self._consecutive),
            "majority_fold": majority_fold,
        }

    def reset(self) -> None:
        self._consecutive.clear()


def repair_from_replica(tree, bad_device_ids: Sequence[int]):
    """Rebuild every leaf of a dp-replicated tree from a replica held by a
    device NOT in ``bad_device_ids``. Under :func:`vote_reason`'s envelope
    every addressable shard is the full global value, so one healthy
    shard's bytes re-placed under the leaf's own sharding restores
    agreement across all replicas — including the lying device's."""
    bad = {int(i) for i in bad_device_ids}

    def fix(leaf):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            return leaf
        healthy = [s for s in shards if int(s.device.id) not in bad]
        src = healthy[0] if healthy else shards[0]
        return jax.device_put(np.asarray(src.data), leaf.sharding)

    return jax.tree.map(fix, tree)


# ------------------------------------------------------- digest continuity
def assert_digest_continuity(before_fold: int, tree, where: str,
                             iteration: Optional[int] = None) -> int:
    """Assert `tree`'s layout-invariant fold still equals ``before_fold``
    after a supposedly value-preserving state motion (relayout, migrate,
    cross-layout restore). Raises a GLS016 DiagnosticError on mismatch —
    refusing garbled state beats training on it. Returns the fold and emits
    an ``sdc_check mode="continuity"`` event on success."""
    after = host_tree_fold(tree)
    if int(after) != int(before_fold) & _MASK32:
        from galvatron_tpu.analysis import diagnostics as D

        raise D.DiagnosticError([D.make(
            "GLS016",
            "%s: layout-invariant digest changed 0x%08x -> 0x%08x; the "
            "state motion was not value-preserving — refusing to continue "
            "on garbled state" % (where, int(before_fold) & _MASK32, after),
        )])
    from galvatron_tpu.obs import telemetry

    telemetry.emit("sdc_check", mode="continuity", where=where,
                   iter=iteration, fold=int(after))
    return after
