"""Input pipeline: batch preparation, zigzag CP layout, synthetic data.

Counterpart of the reference's dataloader glue + per-model `get_batch`
(galvatron/core/runtime/dataloader.py:4-20, models/gpt_hf/dataloader.py:137,
random-data fallback in the same file). The Megatron-style indexed dataset
(C++ sample-index builder, site_package/megatron/core/datasets/helpers.cpp)
lands in galvatron_tpu/data/.

`prepare_batch` is where the zigzag context-parallel layout is applied: the
model is permutation-equivariant given per-token positions (see
ops/ring_attention.py), so the reference's runtime linear<->zigzag activation
transforms (redistribute.py:8-44) reduce to permuting tokens/labels/positions
once per batch here, when `hp.cp_mode == "zigzag"` and any layer has cp>1."""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

import jax.numpy as jnp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.ops.ring_attention import zigzag_permutation


def prepare_batch(
    hp: Optional[HybridParallelConfig],
    tokens: np.ndarray,
    labels: Optional[np.ndarray] = None,
    loss_mask: Optional[np.ndarray] = None,
    attn_mask: Optional[np.ndarray] = None,
) -> Dict[str, jnp.ndarray]:
    """tokens (B, S) -> model batch dict with positions/labels, zigzag-permuted
    along the sequence when the strategy uses zigzag context parallelism.
    `attn_mask` (B, S) key-padding masks MUST come through here under zigzag
    cp: the key bias is sharded over cp and rotated with K/V, so its sequence
    order has to match the permuted tokens."""
    tokens = np.asarray(tokens)
    B, S = tokens.shape
    if labels is None:
        labels = np.roll(tokens, -1, axis=1)
        if loss_mask is None:
            loss_mask = np.ones((B, S), np.float32)
            loss_mask[:, -1] = 0.0  # rolled last token has no target
    positions = np.broadcast_to(np.arange(S), (B, S))
    batch = {
        "tokens": tokens,
        "positions": positions,
        "labels": labels,
    }
    if loss_mask is not None:
        batch["loss_mask"] = loss_mask
    if attn_mask is not None:
        batch["attn_mask"] = np.asarray(attn_mask)
    if hp is not None and hp.cp_mode == "zigzag" and hp.max_cp > 1:
        idx = zigzag_permutation(S, hp.max_cp)
        batch = {k: v[:, idx] for k, v in batch.items()}
    return {k: jnp.asarray(v) for k, v in batch.items()}


class RandomTextDataset:
    """Deterministic synthetic token stream (the reference models' random-data
    fallback path, models/gpt_hf/dataloader.py)."""

    def __init__(self, vocab_size: int, seq_len: int, size: int = 1024, seed: int = 1234):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.size = size
        self.seed = seed

    def batch(self, step: int, batch_size: int) -> np.ndarray:
        rng = np.random.RandomState(self.seed + step % max(self.size, 1))
        return rng.randint(0, self.vocab_size, (batch_size, self.seq_len))

    def iterator(self, hp: HybridParallelConfig, start_step: int = 0) -> Iterator[Dict[str, jnp.ndarray]]:
        step = start_step
        while True:
            yield prepare_batch(hp, self.batch(step, hp.global_bsz))
            step += 1


def get_train_iterator(
    hp: HybridParallelConfig, vocab_size: int, seq_len: int, seed: int = 1234,
    start_step: int = 0,
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Every stream here is a pure function of the step index, so checkpoint
    resume passes `start_step` and skips in O(1) (the reference keeps Megatron
    dataset cursors in its checkpoint instead)."""
    return RandomTextDataset(vocab_size, seq_len, seed=seed).iterator(hp, start_step)


def get_seq2seq_train_iterator(
    hp: HybridParallelConfig, vocab_size: int, enc_seq_len: int, dec_seq_len: int,
    seed: int = 1234, start_step: int = 0,
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Synthetic encoder-decoder stream (t5: tokens/dec_tokens/labels)."""
    step = start_step
    while True:
        rng = np.random.RandomState(seed + step)
        dec = rng.randint(0, vocab_size, (hp.global_bsz, dec_seq_len))
        loss_mask = np.ones((hp.global_bsz, dec_seq_len), np.float32)
        loss_mask[:, -1] = 0.0  # rolled last position has no real target
        yield {
            "tokens": jnp.asarray(rng.randint(0, vocab_size, (hp.global_bsz, enc_seq_len))),
            "dec_tokens": jnp.asarray(dec),
            "labels": jnp.asarray(np.roll(dec, -1, axis=1)),
            "loss_mask": jnp.asarray(loss_mask),
        }
        step += 1


def get_vision_train_iterator(
    hp: HybridParallelConfig, image_size: int, num_channels: int, num_classes: int,
    seed: int = 1234, start_step: int = 0,
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Synthetic image-classification stream (vit/swin: pixels/labels)."""
    step = start_step
    while True:
        rng = np.random.RandomState(seed + step)
        yield {
            "pixels": jnp.asarray(
                rng.randn(hp.global_bsz, image_size, image_size, num_channels).astype(np.float32)
            ),
            "labels": jnp.asarray(rng.randint(0, num_classes, (hp.global_bsz,))),
        }
        step += 1
