"""Online autotuner: measured-cost re-search with in-memory strategy
hot-swap mid-run.

The analytic cost tables the search engine starts from are a model of the
hardware; the run itself is the ground truth. Once the steady-state
detector (obs/steady.py) declares the step time converged, this module

1. **calibrates** — folds the measured steady step time, the per-LayerRun
   FLOPs-share split, the overlap-hidden comm time, and the compiled-step
   memory back into the profiler's JSON table schema
   (`measured_model_profiles`), so the search engine re-runs on *measured*
   tables with zero new search-engine code paths;
2. **re-plans** — re-searches under the original memory budget with
   settle_bsz pinned to the live global batch (trajectory continuity),
   then compares the incumbent's predicted step time against the new
   winner's with a hysteresis margin plus an amortization check: the
   predicted saving over the remaining steps must exceed the measured
   relayout+recompile cost, learned from prior swaps (`OnlineAutotuner`);
3. **applies** — the driver performs the swap through the existing
   `do_migrate` path; this module only decides and keeps the books
   (swap-cost learning, realized-saving telemetry).

`--autotune observe` runs 1–2 and logs the counterfactual; `apply` also
performs 3. The same calibrator doubles as the offline
`cli report --emit_profiles` path (`emit_profiles`), which writes the
measured tables to disk in the profiler's file layout so a later
`search --time_profile_path/--memory_profile_path` run consumes them.

Module-level imports stay jax-free (the report CLI imports this); the
cost-model machinery is imported lazily inside the functions that price
candidates.
"""

from __future__ import annotations

import copy
import statistics
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from galvatron_tpu.obs import steady as S
from galvatron_tpu.obs import telemetry as T

__all__ = [
    "AutotuneConfig",
    "AutotuneDecision",
    "OnlineAutotuner",
    "emit_profiles",
    "measured_model_profiles",
    "predicted_step_ms",
]

# Floor on the compute share of the measured step attributed to the body
# layers: even a wildly mis-calibrated comm_hidden estimate can't drive
# the measured table negative.
_MIN_BODY_FRACTION = 0.1

# The memory ratio is clamped: compiled-memory accounting on small debug
# models can be off by more than the cost model's activation split, and an
# unbounded ratio would swing the search's memory feasibility wildly.
_MEM_RATIO_MIN, _MEM_RATIO_MAX = 0.2, 5.0


# ----------------------------------------------------------------- calibrator

def _scale_time_entry(entry: Any, ratio: float) -> Any:
    """Scale a computation-table entry; entries are either a scalar ms or
    an [m, c] pair (per-microbatch linear model) — scale both terms."""
    if isinstance(entry, (list, tuple)):
        return [float(v) * ratio for v in entry]
    return float(entry) * ratio


def _scale_activations(mem_cfg: Dict[str, Any], ratio: float) -> None:
    """Scale activation entries in-place; parameter/model-state sizes are
    exact analytic byte counts and stay untouched."""
    for key, val in mem_cfg.items():
        if key.startswith("layertype_"):
            act = val.get("tp_activation_per_bsz_dict")
            if isinstance(act, dict):
                for k in act:
                    act[k] = float(act[k]) * ratio
        elif key in ("other_memory_pp_off",):
            act = val.get("activation")
            if isinstance(act, dict):
                for k in act:
                    act[k] = float(act[k]) * ratio
        elif key in ("other_memory_pp_on",):
            for stage in val.values():
                act = stage.get("activation") if isinstance(stage, dict) else None
                if isinstance(act, dict):
                    for k in act:
                        act[k] = float(act[k]) * ratio


def measured_model_profiles(
    base_time: Dict[str, Any],
    base_memory: Dict[str, Any],
    layer_run_rows: List[Dict[str, Any]],
    steady_step_ms: Optional[float],
    comm_hidden_ms: float = 0.0,
    compiled_memory_mb: Optional[float] = None,
    pred_comm_ms: float = 0.0,
) -> Optional[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """Fold a measured steady step into the profiler's table schema.

    ``base_time``/``base_memory`` are the tables the incumbent's
    predictions were priced on (analytic or profiled); ``layer_run_rows``
    are the per-LayerRun prediction rows (``predict_layer_runs`` output or
    the equivalent ``layer_run`` telemetry events) carrying
    ``predicted_ms`` and ``flops_share``. The measured step is split by
    FLOPs share; overlap-hidden comm and the modeled communication price
    ``pred_comm_ms`` (the hardware-table part of the prediction — see
    ``calibrate_from_run`` for how it is derived) are subtracted, because
    the computation table must absorb only the COMPUTE miss: the search
    keeps pricing collectives from the hardware tables, so the calibrated
    ratio solves ``compute * r + comm = measured`` rather than uniformly
    inflating a comm-dominated prediction. Memory entries rescale by
    compiled/predicted when the compiled-step memory is known.

    Returns (time_config, memory_config) in the exact schema
    ``search_surviving_strategy`` / ``predict_layer_runs`` consume, or
    None when the inputs cannot support a calibration (no steady step, no
    usable rows)."""
    if steady_step_ms is None or steady_step_ms <= 0 or not layer_run_rows:
        return None

    body = [r for r in layer_run_rows
            if r.get("run", -1) >= 0 and r.get("predicted_ms") is not None]
    head = [r for r in layer_run_rows if r.get("run", -1) < 0]
    if not body:
        return None

    share_body = sum(float(r.get("flops_share") or 0.0) for r in body)
    pred_body = sum(float(r["predicted_ms"]) for r in body)
    if share_body <= 0 or pred_body <= 0:
        return None

    compute_pred = pred_body - float(pred_comm_ms or 0.0)
    if compute_pred <= 0:
        # the base prediction says this step is all communication; there is
        # no compute entry a measured-compute ratio could land on
        return None
    measured_body = max(
        steady_step_ms * share_body
        - float(comm_hidden_ms or 0.0) - float(pred_comm_ms or 0.0),
        _MIN_BODY_FRACTION * steady_step_ms * share_body,
    )
    ratio_body = measured_body / compute_pred

    # The embed/head row carries FLOPs share but (analytically) no priced
    # time; when it is priced, calibrate other_time on its own ratio, else
    # inherit the body ratio — same silicon, same scale error.
    ratio_head = ratio_body
    if head:
        share_head = sum(float(r.get("flops_share") or 0.0) for r in head)
        pred_head = sum(float(r["predicted_ms"]) for r in head
                        if r.get("predicted_ms") is not None)
        if share_head > 0 and pred_head > 0:
            ratio_head = steady_step_ms * share_head / pred_head

    time_cfg: Dict[str, Any] = {}
    for key, entry in base_time.items():
        if key.startswith("layertype_"):
            time_cfg[key] = _scale_time_entry(entry, ratio_body)
        elif key == "other_time":
            time_cfg[key] = _scale_time_entry(entry, ratio_head)
        else:
            time_cfg[key] = copy.deepcopy(entry)

    mem_cfg = copy.deepcopy(base_memory)
    if compiled_memory_mb and compiled_memory_mb > 0:
        pred_mem = sum(float(r.get("predicted_memory_mb") or 0.0) for r in body)
        if pred_mem > 0:
            ratio_mem = compiled_memory_mb / pred_mem
            ratio_mem = min(max(ratio_mem, _MEM_RATIO_MIN), _MEM_RATIO_MAX)
            _scale_activations(mem_cfg, ratio_mem)
    return time_cfg, mem_cfg


def calibrate_from_run(
    cfg: Any,
    hp: Any,
    base_time: Dict[str, Any],
    base_memory: Dict[str, Any],
    layer_run_rows: List[Dict[str, Any]],
    steady_step_ms: Optional[float],
    comm_hidden_ms: float = 0.0,
    compiled_memory_mb: Optional[float] = None,
) -> Optional[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """The full calibration recipe: price the incumbent's communication on
    the base tables (a zeroed-compute pricing pass — what the cost model
    charges when every computation entry is 0 is exactly the hardware-table
    part), then fold the measured steady step into the tables with that
    comm price separated out (see ``measured_model_profiles``)."""
    if steady_step_ms is None:
        return None
    zero_time = {
        k: _scale_time_entry(v, 0.0)
        if (k.startswith("layertype_") or k == "other_time")
        else copy.deepcopy(v)
        for k, v in base_time.items()
    }
    try:
        pred_comm = predicted_step_ms(cfg, hp, zero_time, base_memory) or 0.0
    except Exception:
        pred_comm = 0.0
    return measured_model_profiles(
        base_time, base_memory, layer_run_rows, steady_step_ms,
        comm_hidden_ms=comm_hidden_ms, compiled_memory_mb=compiled_memory_mb,
        pred_comm_ms=pred_comm,
    )


def predicted_step_ms(
    cfg: Any,
    hp: Any,
    time_config: Optional[dict] = None,
    memory_config: Optional[dict] = None,
) -> Optional[float]:
    """Price a candidate strategy on the given tables: the summed
    per-LayerRun predicted time. Both the incumbent and the searched
    winner are priced through this one function so the hysteresis
    comparison is apples-to-apples."""
    from galvatron_tpu.obs.attribution import predict_layer_runs

    rows = predict_layer_runs(
        cfg, hp, time_config=time_config, memory_config=memory_config)
    if not rows:
        return None
    total = sum(float(r["predicted_ms"]) for r in rows
                if r.get("predicted_ms") is not None)
    return total if total > 0 else None


# ------------------------------------------------------------------ decisions

@dataclass
class AutotuneConfig:
    """Knobs for the online decision loop.

    ``swap_cost_ms`` starts at 0 — an optimistic prior, so the first
    justified swap is never blocked by an unmeasured cost; every
    performed swap replaces it with the measured relayout wall time plus
    the first-step recompile spike (see ``OnlineAutotuner.observe_step``).
    """

    mode: str = "off"  # off | observe | apply
    margin: float = 0.05
    window: int = 5
    rel_std: float = 0.15
    swap_cost_ms: float = 0.0


@dataclass
class AutotuneDecision:
    """Outcome of one planning epoch; ``reason`` is one of ``swap``,
    ``hysteresis``, ``amortization``, ``identical``, ``infeasible``."""

    reason: str
    swap: bool
    incumbent_ms: Optional[float] = None
    winner_ms: Optional[float] = None
    predicted_saving_ms: Optional[float] = None
    remaining_steps: Optional[int] = None
    swap_cost_ms: Optional[float] = None
    target_hp: Any = None


class OnlineAutotuner:
    """Decision bookkeeping for the driver's drain loop.

    The driver pushes each drained step's wall time via ``observe_step``;
    when the detector settles, ``plan_pending`` goes True (once per
    measurement epoch). The driver then builds measured tables, searches,
    prices, and calls ``decide``; if it performs the swap it calls
    ``mark_swapped`` with the relayout wall time, which starts a new
    epoch. When the post-swap epoch re-settles, the tuner emits the
    ``action="realized"`` telemetry row comparing before/after steady
    step times against the predicted saving."""

    def __init__(self, config: AutotuneConfig):
        self.config = config
        self.detector = S.SteadyStateDetector(
            window=config.window, rel_std=config.rel_std)
        self.swaps = 0
        self.plans = 0
        self._planned_epoch = False
        # swap-in-flight bookkeeping
        self._await_first_step = False
        self._relayout_wall_ms = 0.0
        self._pre_swap_steady_ms: Optional[float] = None
        self._pre_swap_predicted_saving: Optional[float] = None
        self._swap_iteration: Optional[int] = None
        self._realized_emitted = True  # nothing pending until a swap happens

    # -- driver-facing surface --------------------------------------------

    @property
    def plan_pending(self) -> bool:
        return self.detector.settled and not self._planned_epoch

    def observe_step(self, iter_ms: Optional[float], iteration: Optional[int] = None) -> None:
        """Feed one drained step. The first step after a swap is the
        recompile spike: it funds the swap-cost estimate and is excluded
        from the new epoch's series."""
        if iter_ms is None:
            return
        if self._await_first_step:
            self._await_first_step = False
            spike = 0.0
            if self._pre_swap_steady_ms is not None:
                spike = max(float(iter_ms) - self._pre_swap_steady_ms, 0.0)
            self.config.swap_cost_ms = self._relayout_wall_ms + spike
            return
        settled_before = self.detector.settled
        self.detector.push(iter_ms)
        if (not settled_before and self.detector.settled
                and not self._realized_emitted):
            self._emit_realized(iteration)

    def steady_step_ms(self) -> Optional[float]:
        return self.detector.steady_step_ms()

    def decide(
        self,
        incumbent_ms: Optional[float],
        winner_ms: Optional[float],
        remaining_steps: int,
        identical: bool,
        target_hp: Any = None,
    ) -> AutotuneDecision:
        """Hysteresis + amortization gate. Marks this epoch planned —
        one decision per settle."""
        self._planned_epoch = True
        self.plans += 1
        common = dict(
            incumbent_ms=incumbent_ms, winner_ms=winner_ms,
            remaining_steps=remaining_steps,
            swap_cost_ms=self.config.swap_cost_ms, target_hp=target_hp,
        )
        if incumbent_ms is None or winner_ms is None:
            return AutotuneDecision(reason="infeasible", swap=False, **common)
        saving = incumbent_ms - winner_ms
        common["predicted_saving_ms"] = saving
        if identical:
            return AutotuneDecision(reason="identical", swap=False, **common)
        if saving <= self.config.margin * incumbent_ms:
            return AutotuneDecision(reason="hysteresis", swap=False, **common)
        if saving * max(remaining_steps, 0) <= self.config.swap_cost_ms:
            return AutotuneDecision(reason="amortization", swap=False, **common)
        return AutotuneDecision(reason="swap", swap=True, **common)

    def mark_swapped(
        self,
        iteration: int,
        relayout_wall_ms: float,
        predicted_saving_ms: Optional[float] = None,
    ) -> None:
        """The driver performed the swap: start a fresh measurement epoch
        and arm the realized-saving comparison."""
        self.swaps += 1
        self._relayout_wall_ms = float(relayout_wall_ms)
        self._pre_swap_steady_ms = self.detector.steady_step_ms()
        self._pre_swap_predicted_saving = predicted_saving_ms
        self._swap_iteration = iteration
        self._await_first_step = True
        self._realized_emitted = False
        self.detector.reset()
        self._planned_epoch = False

    # -- internals ---------------------------------------------------------

    def _emit_realized(self, iteration: Optional[int]) -> None:
        self._realized_emitted = True
        after = self.detector.steady_step_ms()
        before = self._pre_swap_steady_ms
        realized = None
        if before is not None and after is not None:
            realized = before - after
        T.emit(
            "autotune",
            action="realized",
            iter=iteration if iteration is not None else self._swap_iteration,
            mode=self.config.mode,
            step_ms_before=before,
            step_ms_after=after,
            realized_saving_ms=realized,
            predicted_saving_ms=self._pre_swap_predicted_saving,
        )


# --------------------------------------------------------- offline calibrator

def _duck_model_config(rs: Dict[str, Any]) -> Any:
    """Rebuild the minimum model-shape object the analytic tables need
    from a run_start event's calibration fields."""
    from types import SimpleNamespace

    hidden = int(rs["hidden_size"])
    heads = int(rs["num_heads"])
    return SimpleNamespace(
        hidden_size=hidden,
        num_heads=heads,
        num_kv_heads=int(rs.get("num_kv_heads") or heads),
        ffn_hidden=int(rs.get("ffn_hidden") or 4 * hidden),
        vocab_size=int(rs["vocab_size"]),
        max_seq_len=int(rs["seq_len"]),
        num_layers=int(rs["num_layers"]),
        activation=rs.get("activation") or "gelu",
    )


def emit_profiles(
    events: List[Dict[str, Any]],
    out_dir: str,
    window: int = 5,
    rel_std: float = 0.15,
) -> Dict[str, str]:
    """Offline calibrator: turn a telemetry JSONL stream into measured
    per-layer time/memory tables on disk, in the profiler's exact file
    layout, so ``search --time_profile_path/--memory_profile_path``
    consumes them directly.

    Raises ValueError when the stream cannot support calibration (no
    run_start with model-shape fields — telemetry predating this version —
    or no usable step series)."""
    import os

    from galvatron_tpu.runtime import elastic as els
    from galvatron_tpu.utils.jsonio import write_json_config

    by_type: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        by_type.setdefault(ev.get("type", ""), []).append(ev)

    starts = by_type.get("run_start", [])
    if not starts:
        raise ValueError("no run_start event; cannot identify the model")
    rs = starts[-1]
    if not all(rs.get(k) is not None
               for k in ("hidden_size", "num_heads", "vocab_size",
                         "seq_len", "num_layers")):
        raise ValueError(
            "run_start lacks model-shape calibration fields; the telemetry "
            "predates them — re-run train with this version to calibrate")
    cfg = _duck_model_config(rs)
    world = int(rs.get("world_size") or 1)

    st = S.detect(
        [ev.get("iter_ms") for ev in by_type.get("step", [])],
        window=window, rel_std=rel_std)
    if st.start_index is None:
        raise ValueError("no step events with iter_ms; nothing to calibrate on")
    tail = [float(ev["iter_ms"]) for ev in by_type.get("step", [])
            if ev.get("iter_ms") is not None][st.start_index:]
    steady_ms = float(statistics.median(tail))

    rows = [ev for ev in by_type.get("layer_run", [])]
    comm_hidden = sum(float(ev.get("comm_hidden_ms") or 0.0)
                      for ev in by_type.get("tp_overlap", []))
    compiled_mb = None
    for ev in by_type.get("compile", []):
        if ev.get("compiled_memory_mb") is not None:
            compiled_mb = float(ev["compiled_memory_mb"])

    base = els.analytic_model_profiles(cfg, max_tp=world)
    if base is None:
        raise ValueError("model family outside the analytic tables; cannot "
                         "build a calibration baseline")
    hp = None
    if rs.get("strategy"):
        try:
            from galvatron_tpu.config.strategy import HybridParallelConfig

            hp = HybridParallelConfig.from_json(
                dict(rs["strategy"]), world_size=world)
        except Exception:
            hp = None  # comm price falls back to 0 (pure-compute scaling)
    if hp is not None:
        tables = calibrate_from_run(
            cfg, hp, base[0], base[1], rows, steady_ms,
            comm_hidden_ms=comm_hidden, compiled_memory_mb=compiled_mb)
    else:
        tables = measured_model_profiles(
            base[0], base[1], rows, steady_ms,
            comm_hidden_ms=comm_hidden, compiled_memory_mb=compiled_mb)
    if tables is None:
        raise ValueError("no layer_run prediction rows in the telemetry; "
                         "run train with --telemetry to record them")
    time_cfg, mem_cfg = tables

    model_type = rs.get("model_type") or "model"
    mixed_precision = rs.get("mixed_precision") or "fp32"
    tag = "%s_hidden%d_head%d_seqlen%d" % (
        mixed_precision, cfg.hidden_size, cfg.num_heads, cfg.max_seq_len)
    os.makedirs(out_dir, exist_ok=True)
    time_path = os.path.join(
        out_dir, "computation_profiling_%s_%s.json" % (tag, model_type))
    mem_path = os.path.join(
        out_dir, "memory_profiling_%s_%s.json" % (tag, model_type))
    write_json_config(time_cfg, time_path)
    write_json_config(mem_cfg, mem_path)
    return {"computation": time_path, "memory": mem_path}
