"""Training watchdog + mesh-health probing: detect a wedged or degraded run.

PR 5 made runs *resumable* after hardware trouble (elastic degraded-mesh
resume), but the detection side was still an operator staring at a stalled
log: a hung collective, a wedged input pipeline, or a quietly shrunken
device set all present as "the process stopped printing". This module is
the runtime's own failure detector, the missing half of the self-healing
story (ROADMAP item 5; the recovery half is in-memory migration in
runtime/elastic.py):

- :class:`Watchdog` — a monitor thread armed around every dispatched step.
  The deadline is *learned* from the run itself: ``factor * median(steady
  step time) + floor`` once enough post-warmup steps have drained, a
  generous startup deadline before that (first-step compiles legitimately
  take minutes). A missed deadline escalates in two stages: **fire**
  (emit a ``watchdog`` telemetry event with a full diagnostic dump —
  in-flight window depth, last drained step, per-thread stacks via
  :mod:`faulthandler` — and request a drain-and-retry from the driver),
  then **escalate** (request an emergency save + clean exit with
  :data:`WATCHDOG_EXIT_CODE`) when a further deadline passes with no
  progress. All decision logic lives in the pure :meth:`Watchdog.check`
  so tests drive it with a fake clock; the thread is just a pump.
- :func:`classify_world` / :class:`MeshHealthMonitor` — a cheap periodic
  mesh-health probe: a device-enumeration diff against the strategy's
  provenance plus a tiny jitted collective run under a bounded timeout,
  classifying the live world as healthy / degraded / grown / wedged. The
  driver's ``--migrate_on_degrade`` turns a degraded verdict into an
  in-memory strategy migration instead of a crash-and-resume round trip.

The watchdog cannot *unwedge* a hard-stuck XLA call — nothing in-process
can — but it turns "silent hang" into a structured, machine-readable event
stream entry with thread stacks, and turns transient stalls (a long GC
pause, a flaky interconnect retry, an injected sleeping callback in the
fault sim) into a drained-and-retried step or a clean, resumable exit.
"""

from __future__ import annotations

import faulthandler
import statistics
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from galvatron_tpu.obs import telemetry

__all__ = [
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "WatchdogConfig",
    "classify_world",
    "probe_collective",
    "MeshHealthMonitor",
    "thread_stack_dump",
]

# The driver's exit code when the watchdog escalated and forced the
# emergency-save path: distinct from 0 (clean), 1 (ordinary failure), and 2
# (the GLS2xx elastic-refusal contract), so a supervisor can tell "the run
# wedged and self-evacuated" from "needs operator input".
WATCHDOG_EXIT_CODE = 3


def thread_stack_dump(max_chars: int = 8000) -> str:
    """Every thread's current Python stack, via faulthandler (which can dump
    even threads blocked in C calls — exactly the ones a hang diagnostic
    cares about). Truncated to keep the telemetry event bounded."""
    try:
        with tempfile.TemporaryFile(mode="w+") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.seek(0)
            text = fh.read()
    except Exception as e:  # faulthandler needs a real fd; degrade gracefully
        return "<stack dump unavailable: %s>" % e
    if len(text) > max_chars:
        text = text[:max_chars] + "\n<truncated>"
    return text


# ------------------------------------------------------------------ watchdog
@dataclass
class WatchdogConfig:
    """Deadline learning + escalation knobs (driver flags ``--watchdog`` /
    ``--watchdog_factor`` map onto floor_s / factor)."""

    floor_s: float = 30.0  # additive floor under the learned deadline
    factor: float = 4.0  # k in k * median(step time) + floor
    min_history: int = 3  # drained steps before the deadline arms
    startup_deadline_s: float = 600.0  # pre-history deadline (covers compile)
    escalation_grace: float = 1.0  # extra deadlines after fire before escalate
    poll_interval_s: float = 0.25  # monitor-thread cadence
    history: int = 64  # step-time samples kept for the median


class Watchdog:
    """Per-step liveness monitor with a two-stage escalation ladder.

    The driver arms the watchdog at the top of each loop body (covering
    batch fetch + dispatch + the in-flight window) and reports progress at
    every drain; `disarm()` brackets legitimately slow sections (eval,
    checkpoint saves). The monitor thread periodically calls :meth:`check`;
    tests call it directly with a fake clock.

    Escalation contract (the driver polls the request flags at the loop
    top, where params/opt_state are consistent):

    - ``fire``  -> `retry_requested`: drain the in-flight window and keep
      going (a transient stall should not kill a multi-day run).
    - ``escalate`` -> `abort_requested`: emergency-save + clean exit with
      :data:`WATCHDOG_EXIT_CODE`.
    """

    def __init__(
        self,
        cfg: Optional[WatchdogConfig] = None,
        time_fn: Callable[[], float] = time.monotonic,
        on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
        on_escalate: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self.cfg = cfg or WatchdogConfig()
        self._time = time_fn
        self._on_fire = on_fire
        self._on_escalate = on_escalate
        self._lock = threading.Lock()
        self._step_times_ms: deque = deque(maxlen=max(self.cfg.history, 1))
        # armed interval state
        self._armed = False
        self._armed_at: Optional[float] = None
        self._phase = ""
        self._iteration: Optional[int] = None
        self._inflight_depth = 0
        self._last_drained: Optional[int] = None
        # escalation state
        self._fired_at: Optional[float] = None
        self.fires = 0
        self.escalated = False
        self.retry_requested = False
        self.abort_requested = False
        self.events: List[Dict[str, Any]] = []  # local record (summary dict)
        # monitor thread
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- learning
    def observe_step_time(self, ms: float) -> None:
        with self._lock:
            self._step_times_ms.append(float(ms))

    def deadline_s(self) -> float:
        """The current no-progress budget: learned once `min_history` steps
        have drained, the generous startup deadline before that."""
        with self._lock:
            times = list(self._step_times_ms)
        if len(times) < max(self.cfg.min_history, 1):
            return float(self.cfg.startup_deadline_s)
        med_s = statistics.median(times) / 1e3
        return self.cfg.factor * med_s + self.cfg.floor_s

    # ------------------------------------------------------------ arm/disarm
    def arm(self, iteration: int, phase: str = "step", inflight: int = 0) -> None:
        """Start (or refresh) the armed interval: the deadline clock runs
        from now. Called at the top of each loop body and after dispatch."""
        now = self._time()
        with self._lock:
            self._armed = True
            self._armed_at = now
            self._phase = phase
            self._iteration = int(iteration)
            self._inflight_depth = int(inflight)
            self._fired_at = None  # new interval: the ladder restarts

    def progress(self, drained_iteration: Optional[int] = None,
                 inflight: Optional[int] = None) -> None:
        """Report liveness without restarting the escalation ladder's armed
        flag semantics: refreshes the deadline clock and clears a pending
        fire (the run recovered on its own)."""
        now = self._time()
        with self._lock:
            if drained_iteration is not None:
                self._last_drained = int(drained_iteration)
            if inflight is not None:
                self._inflight_depth = int(inflight)
            if self._armed:
                self._armed_at = now
                self._fired_at = None

    def disarm(self) -> None:
        """Suspend monitoring (eval passes, checkpoint saves, migration —
        long-running by design, with their own containment)."""
        with self._lock:
            self._armed = False
            self._armed_at = None
            self._fired_at = None

    # -------------------------------------------------------------- decision
    def check(self, now: Optional[float] = None) -> Optional[str]:
        """The pure escalation decision: None | "fire" | "escalate".

        fire     — armed, no progress for a full deadline, not yet fired in
                   this interval.
        escalate — fired, and a further `escalation_grace` deadlines passed
                   with still no progress.
        """
        now = self._time() if now is None else now
        deadline = self.deadline_s()
        with self._lock:
            if not self._armed or self._armed_at is None or self.escalated:
                return None
            if self._fired_at is None:
                if now - self._armed_at <= deadline:
                    return None
                self._fired_at = now
                self.fires += 1
                self.retry_requested = True
                action = "fire"
            else:
                if now - self._fired_at <= deadline * max(self.cfg.escalation_grace, 0.0):
                    return None
                self.escalated = True
                self.abort_requested = True
                action = "escalate"
            elapsed = now - self._armed_at
        self._report(action, elapsed, deadline)
        return action

    def take_retry_request(self) -> bool:
        """Consume a pending drain-and-retry request (driver loop top)."""
        with self._lock:
            req, self.retry_requested = self.retry_requested, False
            return req

    # ------------------------------------------------------------ diagnostics
    def diagnostics(self, include_stacks: bool = True) -> Dict[str, Any]:
        with self._lock:
            times = list(self._step_times_ms)
            diag: Dict[str, Any] = {
                "iter": self._iteration,
                "phase": self._phase,
                "inflight_depth": self._inflight_depth,
                "last_drained": self._last_drained,
                "fires": self.fires,
                "steps_observed": len(times),
            }
        if times:
            diag["median_step_ms"] = float(statistics.median(times))
        if include_stacks:
            diag["stacks"] = thread_stack_dump()
        return diag

    def _report(self, action: str, elapsed: float, deadline: float) -> None:
        diag = self.diagnostics()
        diag.update(action=action, elapsed_s=elapsed, deadline_s=deadline)
        self.events.append({k: v for k, v in diag.items() if k != "stacks"})
        telemetry.emit(
            "watchdog", action=action, iter=diag.get("iter"),
            phase=diag.get("phase"), elapsed_s=elapsed, deadline_s=deadline,
            inflight_depth=diag.get("inflight_depth"),
            last_drained=diag.get("last_drained"), fires=diag.get("fires"),
            stacks=diag.get("stacks"),
        )
        telemetry.runtime_log(
            "watchdog %s: no progress for %.1fs (deadline %.1fs) at iter %s "
            "phase %r, %s step(s) in flight, last drained %s"
            % (action, elapsed, deadline, diag.get("iter"), diag.get("phase"),
               diag.get("inflight_depth"), diag.get("last_drained"))
        )
        cb = self._on_fire if action == "fire" else self._on_escalate
        if cb is not None:
            cb(diag)

    def summary(self) -> Dict[str, Any]:
        return {
            "fires": self.fires,
            "escalated": self.escalated,
            "deadline_s": self.deadline_s(),
            "events": list(self.events),
        }

    # ---------------------------------------------------------------- thread
    def start(self) -> "Watchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="galvatron-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(self.cfg.poll_interval_s * 4, 1.0))
            self._thread = None

    def _monitor(self) -> None:
        while not self._stop.wait(self.cfg.poll_interval_s):
            try:
                self.check()
            except Exception as e:  # the monitor must never kill the run
                telemetry.runtime_log("watchdog monitor error: %s" % e)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc_info):
        self.stop()
        return False


# ------------------------------------------------------------- mesh health
def classify_world(expected_ids: Sequence[int], live_devices: Sequence[Any]) -> Dict[str, Any]:
    """Device-enumeration diff: the live platform's device ids against the
    ids the running strategy was planned for (its mesh / the checkpoint
    provenance's device_count). Pure bookkeeping — no device work."""
    expected = sorted(int(i) for i in expected_ids)
    live = sorted(int(getattr(d, "id", d)) for d in live_devices)
    missing = sorted(set(expected) - set(live))
    added = sorted(set(live) - set(expected))
    if missing:
        status = "degraded"
    elif added:
        status = "grown"
    else:
        status = "healthy"
    return {
        "status": status,
        "expected": len(expected),
        "live": len(live),
        "missing_ids": missing,
        "added_ids": added,
    }


def probe_collective(mesh, timeout_s: float = 5.0) -> Dict[str, Any]:
    """A tiny jitted collective across every device of `mesh`, run under a
    bounded timeout: one float per device, sharded over all mesh axes,
    summed to a replicated scalar (an all-reduce on any multi-device mesh).
    A healthy mesh answers in milliseconds; a wedged interconnect leaves
    the worker blocked and the probe reports ``ok=False`` with
    ``timed_out=True`` instead of hanging the driver."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    result: Dict[str, Any] = {"ok": False, "timed_out": False, "elapsed_s": None}
    n = int(mesh.devices.size)
    axes = tuple(mesh.shape.keys())

    def run():
        try:
            t0 = time.perf_counter()
            x = jax.device_put(
                np.ones((n,), np.float32), NamedSharding(mesh, PartitionSpec(axes)))
            total = jax.jit(
                jnp.sum, out_shardings=NamedSharding(mesh, PartitionSpec()))(x)
            value = float(jax.device_get(total))
            result["elapsed_s"] = time.perf_counter() - t0
            result["ok"] = value == float(n)
            if not result["ok"]:
                result["error"] = "collective returned %r, expected %d" % (value, n)
        except Exception as e:  # noqa: BLE001 — reported, not raised
            result["error"] = "%s: %s" % (type(e).__name__, e)

    worker = threading.Thread(target=run, name="galvatron-mesh-probe", daemon=True)
    worker.start()
    worker.join(timeout=max(timeout_s, 0.0))
    if worker.is_alive():
        result["timed_out"] = True
        result["error"] = "collective did not complete within %.1fs" % timeout_s
    return result


@dataclass
class MeshHealthMonitor:
    """Periodic mesh-health probe driven from the train loop's step
    boundaries (no extra thread: a probe only runs when the loop is live,
    which is exactly when its verdict can be acted on).

    `expected_ids` come from the running strategy's mesh; `devices_fn` is
    injectable so tests can simulate device loss without killing real
    devices.

    `quarantined_ids` holds devices other subsystems have convicted (the
    silent-corruption voter in runtime/sdc.py): a quarantined device is
    treated as missing even though enumeration still lists it — the lie is
    in its arithmetic, not its liveness — so every later probe keeps
    reporting the world degraded until the run migrates off it."""

    mesh: Any
    interval_s: float = 60.0
    timeout_s: float = 5.0
    devices_fn: Callable[[], Sequence[Any]] = None  # default: jax.devices
    time_fn: Callable[[], float] = time.monotonic
    collective: bool = True  # enumeration diff only when False (cheaper)
    _next_due: Optional[float] = field(default=None, repr=False)
    expected_ids: Sequence[int] = ()
    quarantined_ids: set = field(default_factory=set)

    def __post_init__(self):
        if self.devices_fn is None:
            import jax

            self.devices_fn = jax.devices
        if not self.expected_ids:
            self.expected_ids = [int(d.id) for d in self.mesh.devices.flat]

    def maybe_probe(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Run the probe when due (every `interval_s`); None otherwise."""
        now = self.time_fn() if now is None else now
        if self._next_due is None:
            self._next_due = now + self.interval_s
            return None
        if now < self._next_due:
            return None
        self._next_due = now + self.interval_s
        return self.probe()

    def quarantine(self, device_ids: Sequence[int]) -> Dict[str, Any]:
        """Convict `device_ids` and return the immediate (degraded) verdict
        the caller can feed straight into its migrate-on-degrade handler —
        no need to wait for the next scheduled probe."""
        self.quarantined_ids.update(int(i) for i in device_ids)
        return self.probe()

    def probe(self) -> Dict[str, Any]:
        live = [d for d in self.devices_fn()
                if int(getattr(d, "id", d)) not in self.quarantined_ids]
        verdict = classify_world(self.expected_ids, live)
        if self.quarantined_ids:
            verdict["quarantined_ids"] = sorted(self.quarantined_ids)
        if self.collective and verdict["status"] == "healthy":
            coll = probe_collective(self.mesh, timeout_s=self.timeout_s)
            verdict["collective_ok"] = coll["ok"]
            if coll.get("elapsed_s") is not None:
                verdict["collective_elapsed_s"] = coll["elapsed_s"]
            if not coll["ok"]:
                verdict["status"] = "wedged"
                verdict["error"] = coll.get("error")
        return verdict
