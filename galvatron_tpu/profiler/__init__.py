from galvatron_tpu.profiler.hardware import HardwareProfiler
from galvatron_tpu.profiler.model import ModelProfiler
from galvatron_tpu.profiler.runtime import RuntimeProfiler

__all__ = ["HardwareProfiler", "ModelProfiler", "RuntimeProfiler"]
