"""Runtime (in-training) profiler: iteration timing, throughput, memory.

TPU-native counterpart of the reference RuntimeProfiler
(galvatron/core/profiler/runtime_profiler.py:10-339): CUDA-event timing with a
warmup window (:189-300) becomes `block_until_ready` walltime around the
jitted train step (one step = one XLA program, so walltime IS device time
after the first dispatch); stage-tagged peak-memory snapshots via
`torch.cuda.max_memory_allocated` (:99-126) become `device.memory_stats()`
(live TPU HBM: bytes_in_use / peak_bytes_in_use) plus the compiler-reported
working set of the compiled step, which is the number the search engine's
memory constraint is checked against.

Results persist into the same JSON files the search engine reads
(reference profiler/utils.py save_profiled_time:57 / save_profiled_memory:22).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax

from galvatron_tpu.obs import flops as obs_flops
from galvatron_tpu.obs import telemetry
from galvatron_tpu.utils.jsonio import read_json_config, write_json_config


def device_memory_stats(device=None) -> Dict[str, float]:
    """Current/peak HBM bytes for one device; zeros when the backend does not
    report (CPU test meshes)."""
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    return {
        "bytes_in_use": float(stats.get("bytes_in_use", 0.0)),
        "peak_bytes_in_use": float(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0.0))),
        "bytes_limit": float(stats.get("bytes_limit", 0.0)),
    }


def compiled_step_memory_mb(compiled) -> float:
    """HBM working set of a compiled train step (args + temps + outputs),
    the quantity MemoryCostModel predicts."""
    stats = compiled.memory_analysis()
    if stats is None:
        return 0.0
    total = (
        stats.temp_size_in_bytes
        + stats.argument_size_in_bytes
        + stats.output_size_in_bytes
        - getattr(stats, "alias_size_in_bytes", 0)
    )
    return float(total) / 2**20


@dataclass
class RuntimeProfiler:
    """Wrap a train loop: `start(it)` / `end(it, n_samples)` around each step.

    Iterations inside the warmup window are timed but excluded from the
    summary (reference profile_time_start/end warmup handling,
    runtime_profiler.py:189-300)."""

    warmup: int = 2
    rank: int = 0
    save_path: Optional[str] = None
    model_name: str = "model"
    log_dir: Optional[str] = None  # tee iteration stats to
    # <log_dir>/train_<model_name>.log (the search engine's per-task log
    # discipline applied to training; reference logs rank-0 prints only)
    _t0: float = 0.0
    # per-iteration start stamps keyed by iteration: the dispatch-ahead loop
    # keeps a window of steps in flight, so start(N+2) can precede end(N)
    _t0s: Dict[int, float] = field(default_factory=dict)
    _wall_t0: Optional[float] = None  # first post-warmup start (loop_fence)
    _started: int = 0  # post-warmup dispatches (rollback replays count)
    iter_times_ms: List[float] = field(default_factory=list)
    all_times_ms: List[float] = field(default_factory=list)
    samples: List[int] = field(default_factory=list)
    dispatch_ms: List[float] = field(default_factory=list)  # start -> step
    # call returned (host enqueue cost; the device may still be running)
    host_blocked_ms: List[float] = field(default_factory=list)  # time the
    # host spent blocked on the device inside end()'s block_until_ready —
    # the number the dispatch-ahead loop exists to drive to ~zero
    loop_wall_ms: Optional[float] = None  # fence-to-fence post-warmup wall
    memory_snapshots: Dict[str, Dict[str, float]] = field(default_factory=dict)
    resilience_counters: Optional[Dict[str, int]] = None  # set by the train
    # driver (runtime/resilience.py ResilienceCounters.as_dict()): anomalies
    # skipped, rollbacks, I/O retries, emergency saves, torn checkpoints
    trace_ms: Optional[float] = None  # step-fn trace (lower) walltime
    compile_ms: Optional[float] = None  # XLA compile walltime of the step
    # MFU accounting (obs/flops.py): the driver sets the per-step model
    # FLOPs and the chip's peak so the summary can report MFU and
    # model-FLOPs/s next to every timing number
    model_flops: Optional[float] = None  # model FLOPs per optimizer step
    peak_flops: Optional[float] = None  # device peak FLOP/s (registry)
    compiled_memory_mb: Optional[float] = None  # compiled-step working set
    # decomposed-TP overlap accounting (parallel/tp_shard_map): per-LayerRun
    # measured comm hidden behind the chunked matmul schedule; the summary
    # reports the per-step total next to host_blocked_ms — one is the comm
    # the overlap path hid on-device, the other the host-side stall the
    # dispatch-ahead loop hides
    comm_hidden_ms: Dict[int, float] = field(default_factory=dict)
    _iter: int = 0
    _log_fh = None  # one appending handle for the whole run (close() closes)

    # ------------------------------------------------------------------ timing
    def start(self, iteration: int):
        self._iter = iteration
        self._t0 = time.perf_counter()
        self._t0s[iteration] = self._t0
        if iteration >= self.warmup:
            if self._wall_t0 is None:
                self._wall_t0 = self._t0
            self._started += 1

    def dispatched(self, iteration: int):
        """Call right after the (async) step call returns: records the host
        dispatch cost of this iteration — how long the host held the critical
        path before handing the program to the device."""
        t0 = self._t0s.get(iteration, self._t0)
        dt = (time.perf_counter() - t0) * 1e3
        if iteration >= self.warmup:
            self.dispatch_ms.append(dt)
        return dt

    def end(self, iteration: int, n_samples: int = 0, outputs=None):
        """Call with the step outputs so the timer blocks until the device
        finishes (outputs=None times dispatch only). Under the dispatch-ahead
        loop this runs at drain time, possibly several iterations after
        start(); the blocked interval inside block_until_ready is recorded
        separately as host_blocked_ms."""
        tb = time.perf_counter()
        if outputs is not None:
            jax.block_until_ready(outputs)
        now = time.perf_counter()
        dt = (now - self._t0s.pop(iteration, self._t0)) * 1e3
        self.all_times_ms.append(dt)
        if iteration >= self.warmup:
            self.iter_times_ms.append(dt)
            self.samples.append(n_samples)
            self.host_blocked_ms.append((now - tb) * 1e3)
        return dt

    def loop_fence(self, outputs=None):
        """End-of-run fence: block until the device has fully drained, then
        record the post-warmup loop wall time. Without this fence the
        dispatch-ahead loop's steady-state numbers would credit work the
        device has not finished."""
        if outputs is not None:
            jax.block_until_ready(outputs)
        if self._wall_t0 is not None and self._started > 0:
            self.loop_wall_ms = (time.perf_counter() - self._wall_t0) * 1e3

    def record_comm_hidden(self, run: int, hidden_ms: float):
        """Record the measured communication time (ms per step) the
        decomposed TP path hid behind chunked compute for one LayerRun
        (tp_shard_map.measure_comm_hidden; driver --profile under
        tp_comm_mode=overlap)."""
        self.comm_hidden_ms[int(run)] = float(hidden_ms)

    def record_compile(self, trace_ms: Optional[float] = None,
                       compile_ms: Optional[float] = None):
        """Record the one-off trace/compile cost of the jitted train step
        (cli/train.py AOT-lowers and compiles the step explicitly), so the
        summary separates program-build cost from steady-state step time —
        under scan-over-layer-runs the former is depth-constant and this is
        where the win shows up."""
        if trace_ms is not None:
            self.trace_ms = float(trace_ms)
        if compile_ms is not None:
            self.compile_ms = float(compile_ms)

    # ------------------------------------------------------------------ memory
    def profile_memory(self, iteration: int, stage: str = ""):
        """Stage-tagged snapshot (reference profile_memory/post_profile_memory,
        runtime_profiler.py:99-128)."""
        key = "iter_%d_%s" % (iteration, stage or "snap")
        self.memory_snapshots[key] = device_memory_stats()
        return self.memory_snapshots[key]

    # ----------------------------------------------------------------- summary
    def summary(self) -> Dict[str, float]:
        if not self.iter_times_ms:
            out = {"avg_iter_ms": 0.0, "samples_per_s": 0.0, "iters": 0}
        else:
            avg = float(np.mean(self.iter_times_ms))
            tput = (
                float(np.sum(self.samples)) / (float(np.sum(self.iter_times_ms)) / 1e3)
                if np.sum(self.iter_times_ms) > 0
                else 0.0
            )
            peak = max((m["peak_bytes_in_use"] for m in self.memory_snapshots.values()), default=0.0)
            out = {
                "avg_iter_ms": avg,
                "p50_iter_ms": float(np.percentile(self.iter_times_ms, 50)),
                # alias: the steady-state step time, to read alongside the
                # one-off trace_ms/compile_ms program-build costs
                "steady_step_ms": float(np.percentile(self.iter_times_ms, 50)),
                "samples_per_s": tput,
                "peak_hbm_mb": peak / 2**20,
                "iters": len(self.iter_times_ms),
            }
        if self.dispatch_ms:
            out["dispatch_ms"] = float(np.mean(self.dispatch_ms))
        if self.host_blocked_ms:
            out["host_blocked_ms"] = float(np.mean(self.host_blocked_ms))
            out["host_blocked_ms_total"] = float(np.sum(self.host_blocked_ms))
        if self.loop_wall_ms is not None and self._started > 0:
            # the honest steady-state throughput: post-warmup dispatches over
            # fenced wall time (iter_times_ms measures dispatch->drain
            # latency, which overlaps across iterations under dispatch-ahead)
            out["loop_wall_ms"] = self.loop_wall_ms
            out["wall_ms_per_iter"] = self.loop_wall_ms / self._started
            if self.loop_wall_ms > 0:
                out["steps_per_s"] = self._started / (self.loop_wall_ms / 1e3)
        if self.comm_hidden_ms:
            out["comm_hidden_ms"] = float(sum(self.comm_hidden_ms.values()))
        if self.trace_ms is not None:
            out["trace_ms"] = self.trace_ms
        if self.compile_ms is not None:
            out["compile_ms"] = self.compile_ms
        if self.compiled_memory_mb is not None:
            out["compiled_step_memory_mb"] = self.compiled_memory_mb
        if self.model_flops:
            # MFU from the honest steady-state rate: fenced wall time per
            # post-warmup dispatch when available (iter_ms latencies overlap
            # under the dispatch-ahead loop), else the mean iteration time
            out["model_flops_per_step"] = self.model_flops
            step_ms = out.get("wall_ms_per_iter") or out.get("avg_iter_ms")
            fps = obs_flops.flops_per_s(self.model_flops, step_ms)
            if fps is not None:
                out["model_flops_per_s"] = fps
            util = obs_flops.mfu(self.model_flops, step_ms, self.peak_flops)
            if util is not None:
                out["mfu"] = util
        if self.resilience_counters is not None:
            out["resilience"] = dict(self.resilience_counters)
        return out

    def log_iteration(self, iteration: int, metrics: Optional[dict] = None, print_fn=print):
        """reference _log_iteration_stats (runtime_profiler.py:303). The
        per-task log file is opened ONCE (appending) and held until
        :meth:`close` — the old open-per-iteration cost a filesystem round
        trip on the logging path every step — and the same line is mirrored
        into the telemetry stream when a sink is active."""
        if self.rank != 0 or not self.all_times_ms:
            return
        extra = ""
        if metrics:
            extra = " " + " ".join(
                "%s=%.4g" % (k, float(v)) for k, v in metrics.items() if np.isscalar(v) or getattr(v, "ndim", 1) == 0
            )
        line = "iter %4d | %8.2f ms%s" % (iteration, self.all_times_ms[-1], extra)
        print_fn(line)
        telemetry.emit("log", message=line)
        if self.log_dir:
            if self._log_fh is None:
                os.makedirs(self.log_dir, exist_ok=True)
                path = os.path.join(self.log_dir, "train_%s.log" % self.model_name)
                self._log_fh = open(path, "a")  # galv-lint: ignore[GLC006] -- the one sanctioned open, held for the run
            self._log_fh.write(line + "\n")

    def close(self):
        """Release the iteration-log handle (the train driver calls this in
        its ``finally``); safe to call repeatedly, flushes on close."""
        if self._log_fh is not None:
            try:
                self._log_fh.close()
            finally:
                self._log_fh = None

    # -------------------------------------------------------------------- save
    def save(self, path: Optional[str] = None):
        """Merge this run's summary into a profiling JSON keyed by model
        (reference profiler/utils.py:22-90 merges into shared config files)."""
        path = path or self.save_path
        if not path:
            return
        existing = read_json_config(path) if os.path.exists(path) else {}
        existing[self.model_name] = self.summary()
        write_json_config(existing, path)
