"""Runtime (in-training) profiler: iteration timing, throughput, memory.

TPU-native counterpart of the reference RuntimeProfiler
(galvatron/core/profiler/runtime_profiler.py:10-339): CUDA-event timing with a
warmup window (:189-300) becomes `block_until_ready` walltime around the
jitted train step (one step = one XLA program, so walltime IS device time
after the first dispatch); stage-tagged peak-memory snapshots via
`torch.cuda.max_memory_allocated` (:99-126) become `device.memory_stats()`
(live TPU HBM: bytes_in_use / peak_bytes_in_use) plus the compiler-reported
working set of the compiled step, which is the number the search engine's
memory constraint is checked against.

Results persist into the same JSON files the search engine reads
(reference profiler/utils.py save_profiled_time:57 / save_profiled_memory:22).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax

from galvatron_tpu.utils.jsonio import read_json_config, write_json_config


def device_memory_stats(device=None) -> Dict[str, float]:
    """Current/peak HBM bytes for one device; zeros when the backend does not
    report (CPU test meshes)."""
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    return {
        "bytes_in_use": float(stats.get("bytes_in_use", 0.0)),
        "peak_bytes_in_use": float(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0.0))),
        "bytes_limit": float(stats.get("bytes_limit", 0.0)),
    }


def compiled_step_memory_mb(compiled) -> float:
    """HBM working set of a compiled train step (args + temps + outputs),
    the quantity MemoryCostModel predicts."""
    stats = compiled.memory_analysis()
    if stats is None:
        return 0.0
    total = (
        stats.temp_size_in_bytes
        + stats.argument_size_in_bytes
        + stats.output_size_in_bytes
        - getattr(stats, "alias_size_in_bytes", 0)
    )
    return float(total) / 2**20


@dataclass
class RuntimeProfiler:
    """Wrap a train loop: `start(it)` / `end(it, n_samples)` around each step.

    Iterations inside the warmup window are timed but excluded from the
    summary (reference profile_time_start/end warmup handling,
    runtime_profiler.py:189-300)."""

    warmup: int = 2
    rank: int = 0
    save_path: Optional[str] = None
    model_name: str = "model"
    log_dir: Optional[str] = None  # tee iteration stats to
    # <log_dir>/train_<model_name>.log (the search engine's per-task log
    # discipline applied to training; reference logs rank-0 prints only)
    _t0: float = 0.0
    iter_times_ms: List[float] = field(default_factory=list)
    all_times_ms: List[float] = field(default_factory=list)
    samples: List[int] = field(default_factory=list)
    memory_snapshots: Dict[str, Dict[str, float]] = field(default_factory=dict)
    resilience_counters: Optional[Dict[str, int]] = None  # set by the train
    # driver (runtime/resilience.py ResilienceCounters.as_dict()): anomalies
    # skipped, rollbacks, I/O retries, emergency saves, torn checkpoints
    trace_ms: Optional[float] = None  # step-fn trace (lower) walltime
    compile_ms: Optional[float] = None  # XLA compile walltime of the step
    _iter: int = 0

    # ------------------------------------------------------------------ timing
    def start(self, iteration: int):
        self._iter = iteration
        self._t0 = time.perf_counter()

    def end(self, iteration: int, n_samples: int = 0, outputs=None):
        """Call with the step outputs so the timer blocks until the device
        finishes (outputs=None times dispatch only)."""
        if outputs is not None:
            jax.block_until_ready(outputs)
        dt = (time.perf_counter() - self._t0) * 1e3
        self.all_times_ms.append(dt)
        if iteration >= self.warmup:
            self.iter_times_ms.append(dt)
            self.samples.append(n_samples)
        return dt

    def record_compile(self, trace_ms: Optional[float] = None,
                       compile_ms: Optional[float] = None):
        """Record the one-off trace/compile cost of the jitted train step
        (cli/train.py AOT-lowers and compiles the step explicitly), so the
        summary separates program-build cost from steady-state step time —
        under scan-over-layer-runs the former is depth-constant and this is
        where the win shows up."""
        if trace_ms is not None:
            self.trace_ms = float(trace_ms)
        if compile_ms is not None:
            self.compile_ms = float(compile_ms)

    # ------------------------------------------------------------------ memory
    def profile_memory(self, iteration: int, stage: str = ""):
        """Stage-tagged snapshot (reference profile_memory/post_profile_memory,
        runtime_profiler.py:99-128)."""
        key = "iter_%d_%s" % (iteration, stage or "snap")
        self.memory_snapshots[key] = device_memory_stats()
        return self.memory_snapshots[key]

    # ----------------------------------------------------------------- summary
    def summary(self) -> Dict[str, float]:
        if not self.iter_times_ms:
            out = {"avg_iter_ms": 0.0, "samples_per_s": 0.0, "iters": 0}
        else:
            avg = float(np.mean(self.iter_times_ms))
            tput = (
                float(np.sum(self.samples)) / (float(np.sum(self.iter_times_ms)) / 1e3)
                if np.sum(self.iter_times_ms) > 0
                else 0.0
            )
            peak = max((m["peak_bytes_in_use"] for m in self.memory_snapshots.values()), default=0.0)
            out = {
                "avg_iter_ms": avg,
                "p50_iter_ms": float(np.percentile(self.iter_times_ms, 50)),
                # alias: the steady-state step time, to read alongside the
                # one-off trace_ms/compile_ms program-build costs
                "steady_step_ms": float(np.percentile(self.iter_times_ms, 50)),
                "samples_per_s": tput,
                "peak_hbm_mb": peak / 2**20,
                "iters": len(self.iter_times_ms),
            }
        if self.trace_ms is not None:
            out["trace_ms"] = self.trace_ms
        if self.compile_ms is not None:
            out["compile_ms"] = self.compile_ms
        if self.resilience_counters is not None:
            out["resilience"] = dict(self.resilience_counters)
        return out

    def log_iteration(self, iteration: int, metrics: Optional[dict] = None, print_fn=print):
        """reference _log_iteration_stats (runtime_profiler.py:303)."""
        if self.rank != 0 or not self.all_times_ms:
            return
        extra = ""
        if metrics:
            extra = " " + " ".join(
                "%s=%.4g" % (k, float(v)) for k, v in metrics.items() if np.isscalar(v) or getattr(v, "ndim", 1) == 0
            )
        line = "iter %4d | %8.2f ms%s" % (iteration, self.all_times_ms[-1], extra)
        print_fn(line)
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(self.log_dir, "train_%s.log" % self.model_name)
            with open(path, "a") as f:
                f.write(line + "\n")

    # -------------------------------------------------------------------- save
    def save(self, path: Optional[str] = None):
        """Merge this run's summary into a profiling JSON keyed by model
        (reference profiler/utils.py:22-90 merges into shared config files)."""
        path = path or self.save_path
        if not path:
            return
        existing = read_json_config(path) if os.path.exists(path) else {}
        existing[self.model_name] = self.summary()
        write_json_config(existing, path)
