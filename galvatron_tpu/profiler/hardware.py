"""Hardware profiler: timed JAX collectives over device meshes.

TPU-native replacement for the reference's nccl-tests-driven HardwareProfiler
(galvatron/core/profiler/hardware_profiler.py:11-500 and the vendored
site_package/nccl-tests CUDA binaries). Instead of spawning `mpirun
all_reduce_perf` per group topology and parsing "Avg bus bandwidth" from logs
(hardware_profiler.py:422-487), each collective is a jitted `shard_map`
program over a mesh factored into (outer, inner) axes, timed in-process with
`block_until_ready`. All groups of a given size run the collective
simultaneously — the steady-state pattern of hybrid-parallel training, and
what the cost model's coefficients describe.

Group topology mapping (reference generate_allreduce_groups,
hardware_profiler.py:380-420): a "consecutive" group of size g is the MINOR
mesh axis (contiguous ICI neighbours on a real slice); "non-consecutive" is
the MAJOR axis (strided ranks — DCN-crossing on multi-host). This mirrors
parallel/mesh.py's tp_consec axis assignment.

Outputs (same JSON schemas the search engine reads,
search/engine.py:set_hardware_profiles):
- allreduce_bandwidth_*.json  {"allreduce_size_%d_consec_%d": GB/s busbw}
- p2p_bandwidth_*.json        {"pp_size_%d": GB/s}
- sp_time_*.json              {"allreduce"|"all2all": {deg: {"popt": [ms/MB, ms]}}}
- overlap_coefficient.json    {"overlap_coe": slowdown when comm overlaps compute}

Bus-bandwidth conventions follow nccl-tests (so numbers are comparable to the
reference's): allreduce busbw = 2(g-1)/g * bytes/t; all2all (g-1)/g * bytes/t;
p2p ring sendrecv bytes/t.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from galvatron_tpu.utils.jsonio import write_json_config


@dataclass
class HardwareProfileArgs:
    """Reference galvatron_profile_hardware_args (core/profiler/arguments.py:88-180),
    minus the mpi/hostfile/nccl-test knobs that have no TPU counterpart."""

    start_mb: float = 1.0
    end_mb: float = 64.0
    scale: int = 2  # multiplicative step between message sizes
    warmup: int = 2
    iters: int = 5
    avg_or_min_or_first: str = "avg"
    max_pp_deg: int = 8
    max_tp_deg: int = 8
    overlap_time_multiply: int = 4
    config_dir: str = "configs"


def _aggregate(ts: Sequence[float], mode: str) -> float:
    if mode == "min":
        return float(np.min(ts))
    if mode == "first":
        return float(ts[0])
    return float(np.mean(ts))


def _time_fn(fn: Callable, args: tuple, warmup: int, iters: int, mode: str) -> float:
    """Wall-time one jitted program (seconds)."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))  # galv-lint: ignore[GLC005] -- profilers measure BY syncing
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))  # galv-lint: ignore[GLC005] -- profilers measure BY syncing
        ts.append(time.perf_counter() - t0)
    return _aggregate(ts, mode)


class HardwareProfiler:
    """Measures ICI/DCN collective performance on the available devices."""

    def __init__(self, args: Optional[HardwareProfileArgs] = None, devices=None):
        self.args = args or HardwareProfileArgs()
        self.devices = list(devices) if devices is not None else jax.devices()
        self.ndev = len(self.devices)

    # ------------------------------------------------------------------ meshes
    def _group_mesh(self, group_size: int, consec: bool) -> Tuple[Mesh, str]:
        """Mesh of all devices where `group_size`-rank groups are one axis.
        consec=True puts the group on the minor axis (contiguous devices)."""
        outer = self.ndev // group_size
        if consec:
            shape, names, group_axis = (outer, group_size), ("outer", "inner"), "inner"
        else:
            shape, names, group_axis = (group_size, outer), ("inner", "outer"), "inner"
        try:
            # hybrid-aware placement: on multi-host runs the MAJOR axis spans
            # DCN, so non-consec groups measure cross-host bandwidth
            from galvatron_tpu.runtime.distributed import device_mesh_for

            dev_array = device_mesh_for(shape, self.devices)
        except Exception:
            dev_array = np.array(self.devices).reshape(shape)
        return Mesh(dev_array, names), group_axis

    def _message(self, mesh: Mesh, mb: float, dtype=jnp.float32) -> jax.Array:
        """Per-device buffer of `mb` MB, distinct data per device so constant
        folding cannot elide the collective. Global shape (ndev, nelem),
        sharded one row per device."""
        nelem = max(int(mb * 2**20) // np.dtype(np.float32).itemsize, 8)
        axes = mesh.axis_names
        x = jnp.arange(self.ndev * nelem, dtype=dtype).reshape(self.ndev, nelem) * 1e-9
        spec = P(axes) if len(axes) == 1 else P(tuple(axes))
        # flatten mesh axes onto dim 0: one row per device
        return jax.device_put(x, NamedSharding(mesh, P(tuple(mesh.axis_names))))

    # ------------------------------------------------------------- collectives
    def _collective_time_ms(
        self, kind: str, group_size: int, consec: bool, mb: float,
        mesh_gax: Optional[Tuple[Mesh, str]] = None,
    ) -> float:
        """Time one collective over all size-`group_size` groups at once; the
        per-rank message is `mb` MB. `mesh_gax` overrides the mesh/group-axis
        placement (the DCN profile pins groups to whole hosts)."""
        if group_size > self.ndev:
            raise ValueError("group size %d > %d devices" % (group_size, self.ndev))
        mesh, gax = mesh_gax if mesh_gax is not None else self._group_mesh(group_size, consec)
        x = self._message(mesh, mb)
        all_axes = tuple(mesh.axis_names)

        def body(local):
            # local: (1, nelem) — this device's message
            if kind == "allreduce":
                return jax.lax.psum(local, gax)
            if kind == "allgather":
                return jax.lax.all_gather(local, gax, axis=0, tiled=True)
            if kind == "reducescatter":
                return jax.lax.psum_scatter(local, gax, scatter_dimension=1, tiled=True)
            if kind == "all2all":
                g = group_size
                nelem = local.shape[1]
                blk = local.reshape(g, nelem // g)
                return jax.lax.all_to_all(blk, gax, split_axis=0, concat_axis=0, tiled=False)
            if kind == "sendrecv":
                n = group_size
                perm = [(j, (j + 1) % n) for j in range(n)]
                return jax.lax.ppermute(local, gax, perm)
            raise ValueError(kind)

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=P(all_axes), out_specs=P(all_axes)
            )
        )
        a = self.args
        return _time_fn(fn, (x,), a.warmup, a.iters, a.avg_or_min_or_first) * 1e3

    @staticmethod
    def busbw_gbps(kind: str, group_size: int, mb: float, ms: float) -> float:
        """nccl-tests bus-bandwidth conventions (so results are directly
        comparable with the reference's hardware_configs JSONs)."""
        g = group_size
        factor = {
            "allreduce": 2.0 * (g - 1) / g,
            "allgather": (g - 1) / g,
            "reducescatter": (g - 1) / g,
            "all2all": (g - 1) / g,
            "sendrecv": 1.0,
        }[kind]
        gb = mb / 1024.0
        return factor * gb / (ms / 1e3) if ms > 0 else float("inf")

    # ---------------------------------------------------------------- profiles
    def _group_sizes(self, limit: int) -> List[int]:
        out, g = [], 2
        while g <= min(limit, self.ndev):
            out.append(g)
            g *= 2
        return out

    def _sweep_mbs(self) -> List[float]:
        a, out = self.args, []
        mb = a.start_mb
        while mb <= a.end_mb:
            out.append(mb)
            mb *= a.scale
        return out

    def profile_allreduce_bandwidth(self) -> Dict[str, float]:
        """Bus bandwidth per (group size, consec) at the largest message size
        (reference parses the avg over its sweep; the large-message busbw is
        the stable regime both use for the cost-model coefficient)."""
        mb = self.args.end_mb
        out: Dict[str, float] = {}
        for g in self._group_sizes(self.args.max_tp_deg * self.args.max_pp_deg):
            placements = [True] if g == self.ndev else [True, False]
            for consec in placements:
                ms = self._collective_time_ms("allreduce", g, consec, mb)
                out["allreduce_size_%d_consec_%d" % (g, int(consec))] = round(
                    self.busbw_gbps("allreduce", g, mb, ms), 3
                )
        return out

    def profile_p2p_bandwidth(self) -> Dict[str, float]:
        """Ring send/recv bandwidth per pipeline degree (reference
        sendrecv_perf per pp split, hardware_profiler.py:218-249)."""
        mb = self.args.end_mb
        out: Dict[str, float] = {}
        for g in self._group_sizes(self.args.max_pp_deg):
            # pipeline stages are the MAJOR axis (dp/tp groups inside a stage)
            ms = self._collective_time_ms("sendrecv", g, False, mb)
            out["pp_size_%d" % g] = round(self.busbw_gbps("sendrecv", g, mb, ms), 3)
        return out

    def profile_sp_time(self) -> Dict[str, Dict]:
        """Per-degree linear fits time(ms) = m * message_MB + c for allreduce
        and all2all — the tables the SP/Ulysses cost paths interpolate
        (reference profile_sp_bandwidth, hardware_profiler.py:251-316;
        consumed by cost_model._table_time)."""
        fits: Dict[str, Dict] = {"allreduce": {}, "all2all": {}}
        mbs = self._sweep_mbs()
        for kind in ("allreduce", "all2all"):
            for g in self._group_sizes(self.args.max_tp_deg):
                times = [self._collective_time_ms(kind, g, True, mb) for mb in mbs]
                if len(mbs) < 2:
                    m, c = times[0] / mbs[0], 0.0
                else:
                    m, c = np.polyfit(np.asarray(mbs, np.float64), np.asarray(times, np.float64), 1)
                fits[kind][g] = {"popt": [float(max(m, 0.0)), float(max(c, 0.0))]}
        return fits

    def profile_dcn_bandwidth(self) -> Dict[str, float]:
        """Cross-host (DCN) allreduce bandwidth per host-group size — the
        TPU-native row for the reference's multi-node path (hostfile + mpirun
        nccl-tests, hardware_profiler.py:344-370). Groups span g hosts with
        every local device participating; single-host runs return {} (no
        DCN to measure)."""
        from galvatron_tpu.runtime.distributed import dcn_granule_count

        n_proc = dcn_granule_count(self.devices)
        if n_proc <= 1:
            return {}
        per_host = self.ndev // n_proc
        mb = self.args.end_mb

        def _granule(d):
            if hasattr(d, "slice_index"):
                return d.slice_index
            return getattr(d, "process_index", 0)

        # explicit placement: hosts sorted, group i = hosts [i*g, (i+1)*g) —
        # each allreduce group spans EXACTLY g whole hosts (a generic
        # hybrid-mesh factoring would spread every group over all hosts)
        devs = sorted(self.devices, key=lambda d: (_granule(d), d.id))
        out: Dict[str, float] = {}
        g = 2
        while g <= n_proc:
            if n_proc % g:
                g *= 2
                continue
            gs = g * per_host
            arr = np.array(devs).reshape(n_proc // g, gs)
            mesh = Mesh(arr, ("outer", "inner"))
            ms = self._collective_time_ms(
                "allreduce", gs, False, mb, mesh_gax=(mesh, "inner")
            )
            out["dcn_allreduce_%dhosts" % g] = round(
                self.busbw_gbps("allreduce", gs, mb, ms), 3
            )
            g *= 2
        return out

    def profile_overlap(self) -> Dict[str, float]:
        """Compute/communication overlap slowdown coefficient (reference
        profile_overlap.py: concurrent compute & allreduce streams ->
        overlap_coe=1.1256 on the authors' cluster). Here: time a matmul
        chain, an allreduce chain, and one program containing both; XLA/TPU
        overlaps async collectives with compute, so
        coe = t_both / max(t_compute, t_comm), clamped to >= 1."""
        if self.ndev < 2:
            return {"overlap_coe": 1.0}
        mesh, gax = self._group_mesh(self.ndev, True)
        n = 1024
        k = self.args.overlap_time_multiply
        w = jnp.eye(n, dtype=jnp.bfloat16) * 1.0001
        x = self._message(mesh, self.args.end_mb)
        all_axes = tuple(mesh.axis_names)

        def compute(w):
            y = w
            for _ in range(8 * k):
                y = (y @ w)
            return y

        def comm_body(local):
            y = local
            for _ in range(k):
                y = jax.lax.psum(y, gax)
            return y

        comm = jax.jit(jax.shard_map(comm_body, mesh=mesh, in_specs=P(all_axes), out_specs=P(all_axes)))

        def both_body(w, local):
            return compute(w), comm_body(local)

        both = jax.jit(
            jax.shard_map(
                both_body, mesh=mesh, in_specs=(P(None, None), P(all_axes)),
                out_specs=(P(None, None), P(all_axes)),
            )
        )
        a = self.args
        t_comp = _time_fn(jax.jit(compute), (w,), a.warmup, a.iters, a.avg_or_min_or_first)
        t_comm = _time_fn(comm, (x,), a.warmup, a.iters, a.avg_or_min_or_first)
        t_both = _time_fn(both, (w, x), a.warmup, a.iters, a.avg_or_min_or_first)
        coe = t_both / max(max(t_comp, t_comm), 1e-9)
        return {"overlap_coe": round(float(np.clip(coe, 1.0, 2.0)), 4)}

    def profile_quant_overhead(self) -> Dict[str, float]:
        """Quantize+dequantize toll per fp32-MB per collective pass (ms/MB)
        — the comm-precision axis's compute coefficient
        (TimeCostModel.quant_overhead_ms; parallel/quant_collectives.py
        blockwise kernels). Measured at end_mb so the fixed jit-dispatch
        cost amortises; written into the overlap config, whose parser
        (cost_model_args.parse_hardware_profiles) carries it into the
        search engine."""
        from galvatron_tpu.parallel.quant_collectives import (
            measure_quant_overhead_ms,
        )

        mb = max(self.args.end_mb, 1.0)
        n_elems = int(mb * 1024 * 1024 / 4)
        ms = measure_quant_overhead_ms((n_elems,), dtype="int8",
                                       iters=self.args.iters)
        return {"quant_overhead_coe": round(ms / mb, 5)}

    # ------------------------------------------------------------------- files
    def config_paths(self) -> Dict[str, str]:
        d = self.args.config_dir
        tag = "%dchips" % self.ndev
        return {
            "allreduce": os.path.join(d, "allreduce_bandwidth_%s.json" % tag),
            "p2p": os.path.join(d, "p2p_bandwidth_%s.json" % tag),
            "sp": os.path.join(d, "sp_time_%s.json" % tag),
            "overlap": os.path.join(d, "overlap_coefficient.json"),
            "dcn": os.path.join(d, "dcn_bandwidth_%s.json" % tag),
        }

    def profile_all(self, write: bool = True) -> Dict[str, Dict]:
        """The reference profile_hardware.py:5-16 pipeline: bandwidth ->
        sp tables -> overlap."""
        results = {
            "allreduce": self.profile_allreduce_bandwidth(),
            "p2p": self.profile_p2p_bandwidth(),
            "sp": self.profile_sp_time(),
            "overlap": self.profile_overlap(),
            "dcn": self.profile_dcn_bandwidth(),
        }
        # the quant toll rides the overlap config file (both are scalar
        # coefficient dicts the same parser consumes)
        results["overlap"].update(self.profile_quant_overhead())
        if write:
            paths = self.config_paths()
            os.makedirs(self.args.config_dir, exist_ok=True)
            for key, data in results.items():
                if data:
                    write_json_config(data, paths[key])
                elif os.path.exists(paths[key]):
                    # an empty profile (e.g. no DCN on this host set) must not
                    # leave a stale file from a previous topology behind
                    os.remove(paths[key])
        return results
