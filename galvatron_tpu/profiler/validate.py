"""Cost-model validation: predicted vs compiler-measured peak HBM.

The project's second north-star metric (BASELINE.json: "peak HBM vs
cost-model prediction") and the reference's implicit accuracy contract — its
search is only as good as MemoryCostModel (cost_model.py:10-219). This module
closes the loop the reference never automates: for a (model config, hybrid
strategy) pair it

  1. profiles the model's per-layer tables (ModelProfiler, layer differencing),
  2. predicts per-chip memory with the SAME MemoryCostModel the search uses,
  3. measures the jitted train step's actual per-chip footprint from XLA's
     compiled memory_analysis (argument + temp bytes — exact, no execution
     needed),

and reports the ratio. `validate_time` does the analogue for TimeCostModel
with walltimed steps (requires a real device to be meaningful).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.search.cost_model import MemoryCostModel
from galvatron_tpu.search.cost_model_args import (
    ModelArgs,
    ParallelArgs,
    ProfileModelArgs,
    TrainArgs,
)

MB = 2.0**20


@dataclass
class MemoryValidation:
    predicted_mb: float
    measured_mb: float
    predicted_layers_mb: float
    predicted_other_mb: float

    @property
    def ratio(self) -> float:
        return self.measured_mb / max(self.predicted_mb, 1e-9)


def _strategy_vector(hp: HybridParallelConfig, i: int):
    s = hp.layers[i]
    info = {"sp": s.sp, "cp": s.cp, "fsdp": s.fsdp, "cpt": s.checkpoint, "tp": s.tp_consec}
    return [hp.pp, s.tp, hp.dp(i), info]


def predict_memory_mb(
    hp: HybridParallelConfig,
    memory_config: Dict[str, Any],
    seq_len: int,
    hidden: int,
    *,
    mixed_precision: bool = True,
    layer_type_of=None,
) -> Dict[str, float]:
    """Per-chip memory prediction (MB) for stage 0 of `hp` using the search
    engine's MemoryCostModel on profiled tables."""
    n_layers = len(hp.layers)
    layer_type_of = layer_type_of or ([0] * n_layers)
    per_layer = []
    other = 0.0
    for i in range(n_layers):
        t = layer_type_of[i]
        ma = ModelArgs(
            parameter_size=memory_config["layertype_%d" % t]["parameter_size"],
            seq_length=seq_len, hidden_size=hidden, layer_num=n_layers,
        )
        pma = ProfileModelArgs(
            tp_activation_per_bsz_dict=memory_config["layertype_%d" % t][
                "tp_activation_per_bsz_dict"
            ],
            other_memory_pp_off=memory_config.get("other_memory_pp_off", {}),
            other_memory_pp_on=memory_config.get("other_memory_pp_on", {}),
        )
        m = MemoryCostModel(
            _strategy_vector(hp, i),
            global_batch_size=hp.global_bsz,
            mbsz=max(1, hp.global_bsz // max(hp.dp(i), 1)),
            min_tp=1,
            max_tp=max(s.tp for s in hp.layers),
            model_args=ma,
            train_args=TrainArgs(mixed_precision=mixed_precision,
                                 runtime_context_mem=0.0),
            parallel_args=ParallelArgs(chunks=hp.chunks, pipeline_type=hp.pipeline_type),
            profile_model_args=pma,
        )
        cost = m.get_memory_cost()
        per_layer.append(cost["enc_total"])
        if i == 0:
            vtp = hp.vocab_tp
            other_tbl = cost["other"]  # {vtp: [per-stage MB]}
            key = vtp if vtp in other_tbl else min(other_tbl)
            other = float(other_tbl[key][0])
    stage_of = hp.stage_of_layer
    stage0_layers = [per_layer[i] for i in range(n_layers) if stage_of[i] == 0]
    layers_mb = float(np.sum(stage0_layers))
    return {
        "layers_mb": layers_mb,
        "other_mb": other,
        "total_mb": layers_mb + other,
    }


def measure_train_step_mb(model, tx) -> float:
    """Per-chip footprint of the compiled train step: (sharded) argument
    bytes + XLA temp bytes, divided by the device count — the quantity
    MemoryCostModel predicts per chip."""
    params_shapes = jax.eval_shape(model._init_fn, jax.random.PRNGKey(0))
    params_abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        params_shapes, model.shardings(),
    )
    opt_shapes = jax.eval_shape(tx.init, params_abstract)
    opt_abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        opt_shapes, model.opt_state_shardings(tx, params_abstract),
    )
    # an example batch with the model's own sharding
    hp = model.hp
    cfg = model.cfg
    if getattr(cfg, "input_type", "tokens") == "patches":
        batch = {
            "pixels": jnp.zeros((hp.global_bsz, cfg.image_size, cfg.image_size, cfg.num_channels), jnp.float32),
            "labels": jnp.zeros((hp.global_bsz,), jnp.int32),
        }
    else:
        shape = (hp.global_bsz, cfg.max_seq_len)
        batch = {
            "tokens": jnp.zeros(shape, jnp.int32),
            "positions": jnp.zeros(shape, jnp.int32),
            "labels": jnp.zeros(shape, jnp.int32),
        }
    batch_shardings = model.shardings(model.batch_specs(batch))
    batch_abstract = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=batch_shardings[k])
        for k, v in batch.items()
    }
    step = model.make_train_step(tx)
    compiled = step.lower(params_abstract, opt_abstract, batch_abstract).compile()
    stats = compiled.memory_analysis()
    if stats is None:
        raise RuntimeError("backend reports no memory analysis")
    # SPMD-compiled sizes are PER DEVICE (each argument is its local shard)
    total = stats.argument_size_in_bytes + stats.temp_size_in_bytes
    return float(total) / MB


def validate_memory(cfg, hp: HybridParallelConfig, memory_config: Dict[str, Any], tx=None,
                    layer_type_of=None) -> MemoryValidation:
    """Predicted-vs-measured per-chip memory for one (config, strategy)."""
    import optax

    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    tx = tx or optax.adam(1e-3)
    model = construct_hybrid_parallel_model(cfg, hp)
    pred = predict_memory_mb(
        hp, memory_config, cfg.max_seq_len, cfg.hidden_size,
        mixed_precision=(cfg.compute_dtype == jnp.bfloat16),
        layer_type_of=layer_type_of,
    )
    measured = measure_train_step_mb(model, tx)
    return MemoryValidation(
        predicted_mb=pred["total_mb"],
        measured_mb=measured,
        predicted_layers_mb=pred["layers_mb"],
        predicted_other_mb=pred["other_mb"],
    )
