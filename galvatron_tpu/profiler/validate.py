"""Cost-model validation: predicted vs compiler-measured peak HBM.

The project's second north-star metric (BASELINE.json: "peak HBM vs
cost-model prediction") and the reference's implicit accuracy contract — its
search is only as good as MemoryCostModel (cost_model.py:10-219). This module
closes the loop the reference never automates: for a (model config, hybrid
strategy) pair it

  1. profiles the model's per-layer tables (ModelProfiler, layer differencing),
  2. predicts per-chip memory with the SAME MemoryCostModel the search uses,
  3. measures the jitted train step's actual per-chip footprint from XLA's
     compiled memory_analysis (argument + temp bytes — exact, no execution
     needed),

and reports the ratio. `validate_time` does the analogue for TimeCostModel
with walltimed steps (requires a real device to be meaningful).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.search.cost_model import MemoryCostModel
from galvatron_tpu.search.cost_model_args import (
    ModelArgs,
    ParallelArgs,
    ProfileModelArgs,
    TrainArgs,
)

MB = 2.0**20


@dataclass
class MemoryValidation:
    predicted_mb: float
    measured_mb: float
    predicted_layers_mb: float
    predicted_other_mb: float

    @property
    def ratio(self) -> float:
        return self.measured_mb / max(self.predicted_mb, 1e-9)


def _strategy_vector(hp: HybridParallelConfig, i: int):
    s = hp.layers[i]
    info = {"sp": s.sp, "cp": s.cp, "fsdp": s.fsdp, "cpt": s.checkpoint, "tp": s.tp_consec}
    return [hp.pp, s.tp, hp.dp(i), info]


def predict_memory_mb(
    hp: HybridParallelConfig,
    memory_config: Dict[str, Any],
    seq_len: int,
    hidden: int,
    *,
    mixed_precision: bool = True,
    layer_type_of=None,
) -> Dict[str, float]:
    """Per-chip memory prediction (MB) for stage 0 of `hp` using the search
    engine's MemoryCostModel on profiled tables."""
    n_layers = len(hp.layers)
    layer_type_of = layer_type_of or ([0] * n_layers)
    per_layer = []
    other = 0.0
    for i in range(n_layers):
        t = layer_type_of[i]
        ma = ModelArgs(
            parameter_size=memory_config["layertype_%d" % t]["parameter_size"],
            seq_length=seq_len, hidden_size=hidden, layer_num=n_layers,
        )
        pma = ProfileModelArgs(
            tp_activation_per_bsz_dict=memory_config["layertype_%d" % t][
                "tp_activation_per_bsz_dict"
            ],
            other_memory_pp_off=memory_config.get("other_memory_pp_off", {}),
            other_memory_pp_on=memory_config.get("other_memory_pp_on", {}),
        )
        m = MemoryCostModel(
            _strategy_vector(hp, i),
            global_batch_size=hp.global_bsz,
            mbsz=max(1, hp.global_bsz // max(hp.dp(i), 1)),
            min_tp=1,
            max_tp=max(s.tp for s in hp.layers),
            model_args=ma,
            train_args=TrainArgs(mixed_precision=mixed_precision,
                                 runtime_context_mem=0.0),
            parallel_args=ParallelArgs(chunks=hp.chunks, pipeline_type=hp.pipeline_type),
            profile_model_args=pma,
        )
        cost = m.get_memory_cost()
        per_layer.append(cost["enc_total"])
        if i == 0:
            vtp = hp.vocab_tp
            other_tbl = cost["other"]  # {vtp: [per-stage MB]}
            key = vtp if vtp in other_tbl else min(other_tbl)
            other = float(other_tbl[key][0])
    stage_of = hp.stage_of_layer
    stage0_layers = [per_layer[i] for i in range(n_layers) if stage_of[i] == 0]
    layers_mb = float(np.sum(stage0_layers))
    return {
        "layers_mb": layers_mb,
        "other_mb": other,
        "total_mb": layers_mb + other,
    }


def measure_train_step_mb(model, tx) -> float:
    """Per-chip footprint of the compiled train step: (sharded) argument
    bytes + XLA temp bytes, divided by the device count — the quantity
    MemoryCostModel predicts per chip."""
    params_shapes = jax.eval_shape(model._init_fn, jax.random.PRNGKey(0))
    params_abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        params_shapes, model.shardings(),
    )
    opt_shapes = jax.eval_shape(tx.init, params_abstract)
    opt_abstract = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        opt_shapes, model.opt_state_shardings(tx, params_abstract),
    )
    # an example batch with the model's own sharding
    hp = model.hp
    cfg = model.cfg
    if getattr(cfg, "input_type", "tokens") == "patches":
        batch = {
            "pixels": jnp.zeros((hp.global_bsz, cfg.image_size, cfg.image_size, cfg.num_channels), jnp.float32),
            "labels": jnp.zeros((hp.global_bsz,), jnp.int32),
        }
    else:
        shape = (hp.global_bsz, cfg.max_seq_len)
        batch = {
            "tokens": jnp.zeros(shape, jnp.int32),
            "positions": jnp.zeros(shape, jnp.int32),
            "labels": jnp.zeros(shape, jnp.int32),
        }
    batch_shardings = model.shardings(model.batch_specs(batch))
    batch_abstract = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=batch_shardings[k])
        for k, v in batch.items()
    }
    step = model.make_train_step(tx)
    compiled = step.lower(params_abstract, opt_abstract, batch_abstract).compile()
    stats = compiled.memory_analysis()
    if stats is None:
        raise RuntimeError("backend reports no memory analysis")
    # SPMD-compiled sizes are PER DEVICE (each argument is its local shard)
    total = stats.argument_size_in_bytes + stats.temp_size_in_bytes
    return float(total) / MB


@dataclass
class TimeValidation:
    predicted_ms: float
    measured_ms: float

    @property
    def ratio(self) -> float:
        return self.measured_ms / max(self.predicted_ms, 1e-9)


def _hw_dicts(hw: Dict[str, Dict]) -> Dict[str, Any]:
    """HardwareProfiler.profile_all output -> the full coefficient bundle
    (comm_coe_dict, p2p_coe_dict, overlap_coe, allreduce_dict, all2all_dict),
    via the SAME parser the search engine uses
    (cost_model_args.parse_hardware_profiles)."""
    from galvatron_tpu.search.cost_model_args import parse_hardware_profiles

    return parse_hardware_profiles(
        hw.get("allreduce"), hw.get("p2p"), hw.get("overlap"), hw.get("sp"),
    )


def predict_step_time_ms(
    hp: HybridParallelConfig,
    time_config: Dict[str, Any],
    memory_config: Dict[str, Any],
    hw: Dict[str, Dict],
    seq_len: int,
    hidden: int,
    *,
    mixed_precision: bool = True,
) -> float:
    """Per-iteration time prediction (ms) for `hp` with the SAME
    TimeCostModel + pipeline pricing the search uses (single layer type)."""
    from galvatron_tpu.search.cost_model import (
        OtherTimeCostModel,
        TimeCostModel,
        pipeline_costmodel,
    )

    n_layers = len(hp.layers)
    hwp = _hw_dicts(hw)
    ma = ModelArgs(
        parameter_size=memory_config["layertype_0"]["parameter_size"],
        seq_length=seq_len, hidden_size=hidden, layer_num=n_layers,
    )
    ta = TrainArgs(mixed_precision=mixed_precision)
    pa = ParallelArgs(chunks=hp.chunks, pipeline_type=hp.pipeline_type)
    pma = ProfileModelArgs(
        forward_computation_time=time_config["layertype_0"],
        tp_activation_per_bsz_dict=memory_config["layertype_0"]["tp_activation_per_bsz_dict"],
        other_memory_pp_off=memory_config.get("other_memory_pp_off", {}),
        other_memory_pp_on=memory_config.get("other_memory_pp_on", {}),
        other_time_profiled=time_config.get("other_time", 1.0),
    )
    from galvatron_tpu.search.cost_model_args import ProfileHardwareArgs

    pha = ProfileHardwareArgs(
        comm_coe_dict=hwp["comm_coe_dict"], dp_overlap_coe=hwp["overlap_coe"],
        bct_overlap_coe=hwp["overlap_coe"], p2p_comm_coe_dict=hwp["p2p_coe_dict"],
        allreduce_dict=hwp["allreduce_dict"], all2all_dict=hwp["all2all_dict"],
    )
    max_tp = max(s.tp for s in hp.layers)
    otc = OtherTimeCostModel(
        # the search's own mbsz for this model (engine.py search_for_bsz_chunk:
        # bsz*min_tp//world_size at min_tp=1), so the validated prediction is
        # the number the search actually scored
        mbsz=max(1, hp.global_bsz // hp.world_size),
        pp_deg=hp.pp, world_size=hp.world_size, vsp=hp.vocab_sp,
        embed_sdp=bool(getattr(hp, "embed_sdp", 0)),
        min_tp=1, max_tp=max(max_tp, hp.vocab_tp),
        sequence_length_list=[seq_len], model_args=ma, train_args=ta,
        parallel_args=pa, profile_model_args=pma, profile_hardware_args=pha,
    ).gen_result()
    key = hp.vocab_tp if hp.vocab_tp in otc else min(otc)
    other = otc[key]
    strategies = [_strategy_vector(hp, i) for i in range(n_layers)]
    return float(pipeline_costmodel(
        TimeCostModel,
        [n_layers], [ma], [ta], [pa], [pma], [pha],
        strategies, list(hp.pp_division), hp.chunks, hp.global_bsz,
        min_tp=1, other_time_cost=other,
    ))


def measure_step_time_ms(model, tx, iters: int = 3) -> float:
    """Walltime of the jitted train step (min over iters after a compile
    warmup). NB on the virtual CPU mesh all shards execute on one host, so
    absolute walltime is the SERIALISED work — on real hardware this is the
    true per-iteration time the prediction targets."""
    import time

    params = model.init_params(jax.random.PRNGKey(0))
    opt_state = model.init_opt_state(tx, params)
    hp, cfg = model.hp, model.cfg
    rng = np.random.RandomState(0)
    if getattr(cfg, "input_type", "tokens") == "patches":
        batch = {
            "pixels": jnp.asarray(rng.randn(
                hp.global_bsz, cfg.image_size, cfg.image_size, cfg.num_channels
            ).astype(np.float32)),
            "labels": jnp.asarray(rng.randint(0, 10, (hp.global_bsz,))),
        }
    else:
        tokens = rng.randint(0, cfg.vocab_size, (hp.global_bsz, cfg.max_seq_len))
        batch = {
            "tokens": jnp.asarray(tokens),
            "positions": jnp.broadcast_to(jnp.arange(cfg.max_seq_len),
                                          (hp.global_bsz, cfg.max_seq_len)),
            "labels": jnp.asarray(np.roll(tokens, -1, 1)),
        }
    batch = model.shard_batch(batch)
    step = model.make_train_step(tx)
    params, opt_state, m = step(params, opt_state, batch)  # compile + warmup
    float(m["loss"])
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        params, opt_state, m = step(params, opt_state, batch)
        float(m["loss"])
        times.append(time.perf_counter() - t0)
    return float(np.min(times)) * 1e3


def validate_time(cfg, hp: HybridParallelConfig, time_config: Dict[str, Any],
                  memory_config: Dict[str, Any], hw: Dict[str, Dict],
                  tx=None) -> TimeValidation:
    """Predicted-vs-measured per-iteration time for one (config, strategy) —
    the TimeCostModel analogue of validate_memory (VERDICT r4 item 8)."""
    import optax

    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    tx = tx or optax.adam(1e-3)
    model = construct_hybrid_parallel_model(cfg, hp)
    predicted = predict_step_time_ms(
        hp, time_config, memory_config, hw, cfg.max_seq_len, cfg.hidden_size,
        mixed_precision=(cfg.compute_dtype == jnp.bfloat16),
    )
    measured = measure_step_time_ms(model, tx)
    return TimeValidation(predicted_ms=predicted, measured_ms=measured)


def validate_memory(cfg, hp: HybridParallelConfig, memory_config: Dict[str, Any], tx=None,
                    layer_type_of=None) -> MemoryValidation:
    """Predicted-vs-measured per-chip memory for one (config, strategy)."""
    import optax

    from galvatron_tpu.runtime.model_api import construct_hybrid_parallel_model

    tx = tx or optax.adam(1e-3)
    model = construct_hybrid_parallel_model(cfg, hp)
    pred = predict_memory_mb(
        hp, memory_config, cfg.max_seq_len, cfg.hidden_size,
        mixed_precision=(cfg.compute_dtype == jnp.bfloat16),
        layer_type_of=layer_type_of,
    )
    measured = measure_train_step_mb(model, tx)
    return MemoryValidation(
        predicted_mb=pred["total_mb"],
        measured_mb=measured,
        predicted_layers_mb=pred["layers_mb"],
        predicted_other_mb=pred["other_mb"],
    )
