"""Model profiler: per-layer time/memory via layer differencing.

TPU-native replacement for the reference ModelProfiler
(galvatron/core/profiler/model_profiler.py:14-1051). The reference launches
the model's own train_dist as subprocesses with varied layer counts via
`os.system` (:181-299) and post-processes the JSONs those runs write; here the
same layer-differencing methodology (:328-372) runs IN-PROCESS:

    per-layer quantity = (Q(layernum_max) - Q(layernum_min))
                         / (layernum_max - layernum_min) / batch_size

- time: jitted forward over an n-layer stack, walltimed with
  `block_until_ready` (the CUDA-event timing of runtime_profiler.py:189-300
  has no TPU analogue; dispatch overhead cancels in the difference);
- memory: XLA's compiled `memory_analysis()` (argument/output/temp bytes) of
  the forward+backward program — exact compiler-reported HBM, not a runtime
  sample, so it needs no accelerator to be present.

Per-tp activation entries: the tp=1 (and remat) numbers are MEASURED; tp=k
entries are act/k because under Megatron-SP every saved activation is
seq-sharded across the tp group (a measured identity on TPU, where no
unsharded LayerNorm copies exist — the reason the reference must measure
per-tp is its partially-replicated SP activations). The vocab ("other")
tables divide by vtp the same way.

Multi-layer-type models plug in by subclassing: `T5ModelProfiler` overrides
the stack builders so encoder (layertype_0) and decoder (layertype_1) are
differenced separately (reference profiles swin/t5 per layer list,
model_profiler.py:71-75); every profile_mode works for every subclass.

Outputs match search/engine.py:set_model_profiles:
  computation_profiling_*.json {"layertype_%d": ms|[m,c], "other_time": ms}
  memory_profiling_*.json      {"layertype_%d": {"parameter_size": MB,
     "tp_activation_per_bsz_dict": {tp: MB, "checkpoint": MB}},
     "other_memory_pp_off"/"other_memory_pp_on": {...}}
"""

from __future__ import annotations

import dataclasses
import os
import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from galvatron_tpu.models import base as M
from galvatron_tpu.utils.jsonio import write_json_config

MB = 2.0**20


@dataclass
class ModelProfileArgs:
    """Reference galvatron_profile_args (core/profiler/arguments.py:1-86)."""

    profile_type: str = "computation"  # computation | memory
    profile_mode: str = "static"  # static | batch | sequence
    profile_batch_size: int = 8
    profile_min_batch_size: int = 1
    profile_max_batch_size: int = 8
    batch_size_step: int = 1
    profile_seq_length: Optional[int] = None  # default: cfg.max_seq_len
    profile_min_seq_length: int = 512
    profile_max_seq_length: int = 2048
    seq_length_step: int = 512
    layernum_min: int = 1
    layernum_max: int = 3
    warmup: int = 2
    iters: int = 5
    max_tp_deg: int = 8
    mixed_precision: str = "bf16"
    config_dir: str = "configs"
    # measure the per-remat-policy backward recompute fraction (strategy
    # field remat_policy; TimeCostModel.remat_frac) — 4 extra grad-program
    # compiles per layer type, so opt-in for quick profile runs
    profile_remat: bool = False


def _tree_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def _walltime(fn, args, warmup, iters) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))  # galv-lint: ignore[GLC005] -- profilers measure BY syncing
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))  # galv-lint: ignore[GLC005] -- profilers measure BY syncing
        ts.append(time.perf_counter() - t0)
    return float(np.mean(ts))


def _compiled_peak_bytes(fn, args) -> float:
    """Compiler-reported working set of one jitted call: temps + outputs
    (+ arguments are counted by the caller where relevant)."""
    stats = jax.jit(fn).lower(*args).compile().memory_analysis()
    if stats is None:
        return 0.0
    return float(stats.temp_size_in_bytes + stats.output_size_in_bytes)


class ModelProfiler:
    """Profiles one model family. One instance covers every layer type of the
    family (`layer_types`); subclasses override the `_stack_t` /
    `_layer_param_bytes` / `_full_model` hooks."""

    layer_types = 1

    def __init__(self, cfg, model_name: str = "model",
                 args: Optional[ModelProfileArgs] = None):
        self._check_config(cfg)
        self.cfg = cfg
        self.model_name = model_name
        self.args = args or ModelProfileArgs()

    def _check_config(self, cfg):
        if not isinstance(cfg, M.TransformerConfig):
            raise TypeError(
                "ModelProfiler profiles TransformerConfig families; t5 uses "
                "T5ModelProfiler (two layer types, reference "
                "model_profiler.py:71-75)"
            )

    @property
    def _dtype(self):
        return jnp.bfloat16 if self.args.mixed_precision == "bf16" else jnp.float32

    @property
    def _target_seq(self) -> int:
        return self.args.profile_seq_length or self.cfg.max_seq_len

    def _file_tag(self) -> str:
        c = self.cfg
        return "%s_hidden%d_head%d_seqlen%d" % (
            self.args.mixed_precision, c.hidden_size, c.num_heads, self._target_seq
        )

    # ------------------------------------------------- overridable primitives
    def _stack_t(self, t: int, n: int, bsz: int, seq: int, remat: bool = False):
        """Jitted forward over an n-layer stack of layer type `t` (no
        embed/head): returns (fwd, layers, extra_args_tuple)."""
        cfg = dataclasses.replace(self.cfg, num_layers=max(n, 1))
        keys = jax.random.split(jax.random.PRNGKey(0), max(n, 1))
        layers = [M.init_layer_params(k, cfg) for k in keys[:n]]
        x = jax.random.normal(jax.random.PRNGKey(1), (bsz, seq, cfg.hidden_size), self._dtype)
        positions = jnp.broadcast_to(jnp.arange(seq), (bsz, seq))

        def fwd(layers, x):
            body = partial(M.layer_forward, cfg=cfg)
            for lp in layers:
                f = jax.checkpoint(body) if remat else body
                x = f(lp, x, positions)
            return jnp.sum(x.astype(jnp.float32))

        return fwd, layers, (x,)

    def _layer_param_bytes(self, t: int) -> int:
        return _tree_bytes(M.init_layer_params(jax.random.PRNGKey(0), self.cfg))

    def _full_model(self, n_layers: int, bsz: int, seq: int):
        """(loss_fn, params, batch) for the whole tiny model — used for the
        'other' (embed/head/loss) time and memory tables."""
        cfg = dataclasses.replace(
            self.cfg, num_layers=max(n_layers, 1), max_seq_len=max(seq, self.cfg.max_seq_len)
        )
        params = M.init_model_params(jax.random.PRNGKey(0), cfg)
        params["layers"] = params["layers"][:n_layers]
        if cfg.input_type == "patches":
            batch = {
                "pixels": jax.random.normal(
                    jax.random.PRNGKey(1), (bsz, cfg.image_size, cfg.image_size, cfg.num_channels)
                ),
                "labels": jax.random.randint(jax.random.PRNGKey(2), (bsz,), 0, max(cfg.num_classes, 1)),
            }
            loss = lambda p, b: M.classification_loss_fn(p, b, cfg)
        else:
            tokens = jax.random.randint(jax.random.PRNGKey(1), (bsz, seq), 0, cfg.vocab_size)
            batch = {
                "tokens": tokens,
                "positions": jnp.broadcast_to(jnp.arange(seq), (bsz, seq)),
                "labels": jnp.roll(tokens, -1, 1),
            }
            loss = lambda p, b: M.lm_loss_fn(p, b, cfg)
        return loss, params, batch

    def _other_model_state_tables(self, bsz: int, seq: int, tps: Sequence[int]):
        """(embed_mb, head_mb, rest_mb, act_total_mb) for the 'other' tables."""
        loss, params, batch = self._full_model(0, bsz, seq)
        embed_mb = _tree_bytes(params["embed"]) / MB
        if getattr(self.cfg, "head_type", "lm") in ("lm", "mlm") and self.cfg.tie_embeddings:
            head_mb = embed_mb + _tree_bytes(params.get("head", {})) / MB
        else:
            head_mb = (_tree_bytes(params.get("lm_head", {})) + _tree_bytes(params.get("head", {}))) / MB
        rest_mb = _tree_bytes(params.get("final_norm", {})) / MB
        act_total = _compiled_peak_bytes(lambda p, b: jax.grad(loss)(p, b), (params, batch))
        act_total = max(act_total - 2 * _tree_bytes(params), 1024.0) / MB
        return embed_mb, head_mb, rest_mb, act_total

    # ----------------------------------------------------- shared differencing
    def _fwd_ms(self, t: int, bsz: int, seq: int) -> float:
        a = self.args
        lo, hi = a.layernum_min, a.layernum_max
        f_lo, l_lo, xs = self._stack_t(t, lo, bsz, seq)
        t_lo = _walltime(jax.jit(f_lo), (l_lo,) + xs, a.warmup, a.iters)
        f_hi, l_hi, xs = self._stack_t(t, hi, bsz, seq)
        t_hi = _walltime(jax.jit(f_hi), (l_hi,) + xs, a.warmup, a.iters)
        return max((t_hi - t_lo) / (hi - lo) / bsz * 1e3, 1e-6)

    def _act_bytes(self, t: int, bsz: int, seq: int, remat: bool) -> float:
        """Layer-differenced fwd+bwd working set per layer per sample."""
        a = self.args
        lo, hi = a.layernum_min, a.layernum_max

        def grad_prog(n):
            fwd, layers, xs = self._stack_t(t, n, bsz, seq, remat=remat)
            return (lambda layers, *xs: jax.grad(fwd)(layers, *xs)), (layers,) + xs

        g_lo, args_lo = grad_prog(lo)
        g_hi, args_hi = grad_prog(hi)
        b_lo = _compiled_peak_bytes(g_lo, args_lo)
        b_hi = _compiled_peak_bytes(g_hi, args_hi)
        # subtract the grad outputs (they equal the extra layers' param bytes
        # and are model-state, not activation, memory)
        extra_params = _tree_bytes(args_hi[0]) - _tree_bytes(args_lo[0])
        per_layer = (b_hi - b_lo - 2 * extra_params) / (hi - lo)
        return max(per_layer / bsz, 1024.0)

    def _act_bytes_tp(self, t: int, bsz: int, seq: int, k: int,
                      kind: str = "tp") -> Optional[float]:
        """MEASURED per-device activation bytes per layer per sample at
        degree k of one strategy `kind` — "tp" (megatron-sp), "ulysses", or
        "cp" (zigzag ring): compile the layer-stack gradient over a k-device
        mesh with the runtime's own shardings and difference the compiled
        per-device peaks. Replaces the act(1)/k derivation — attention under
        megatron-sp gathers full-sequence tensors whose footprint does NOT
        divide by k, ulysses' all-to-all and the ring's blockwise state have
        their own footprints (the reference measures per-strategy for the
        same reason, model_profiler.py:374-559). Returns None when fewer
        than k local devices exist (single-chip profiling falls back to the
        derivation)."""
        if k <= 1 or len(jax.devices()) < k:
            return None

        from galvatron_tpu.config.strategy import HybridParallelConfig
        from galvatron_tpu.parallel.mesh import build_mesh

        a = self.args
        lo, hi = a.layernum_min, a.layernum_max

        degrees = {"tp": dict(tp=k), "ulysses": dict(tp=k, sp=1), "cp": dict(cp=k)}[kind]

        def grad_prog(n):
            hp = HybridParallelConfig.uniform(k, max(n, 1), global_bsz=bsz, **degrees)
            mesh = build_mesh(hp, jax.devices()[:k])
            built = self._sharded_stack_t(t, n, bsz, seq, hp, mesh, kind)
            if built is None:
                return None
            fwd, layers, xs = built
            # per-device bytes of the grad outputs, from the actual shardings
            shard_bytes = sum(
                leaf.nbytes // max(len(leaf.sharding.device_set), 1)
                for lp in layers for leaf in jax.tree.leaves(lp)
            )
            return (lambda ls, *xx: jax.grad(fwd)(ls, *xx)), (layers,) + tuple(xs), shard_bytes

        try:
            built_lo, built_hi = grad_prog(lo), grad_prog(hi)
            if built_lo is None or built_hi is None:
                return None
            g_lo, args_lo, p_lo = built_lo
            g_hi, args_hi, p_hi = built_hi
            b_lo = _compiled_peak_bytes(g_lo, args_lo)
            b_hi = _compiled_peak_bytes(g_hi, args_hi)
        except Exception:
            # strategy not measurable on this model/mesh (e.g. heads not
            # divisible by the ulysses degree): fall back to the derivation
            return None
        per_layer = (b_hi - b_lo - 2 * (p_hi - p_lo)) / (hi - lo)
        return max(per_layer / bsz, 1024.0)

    def _sharded_stack_t(self, t: int, n: int, bsz: int, seq: int, hp, mesh,
                         kind: str):
        """Family hook for the per-strategy measurement: an n-layer stack of
        layer type `t` with params device_put in the runtime's own shardings
        under hp's per-layer axes, and a forward applying the same activation
        constraints. Returns (fwd, layers, xs) or None when this family
        cannot realise the strategy."""
        from jax.sharding import PartitionSpec as P

        from galvatron_tpu.models.base import layer_param_specs
        from galvatron_tpu.parallel import spec as S
        from galvatron_tpu.parallel.mesh import layer_axes

        if not isinstance(self.cfg, M.TransformerConfig):
            return None
        cfg = dataclasses.replace(self.cfg, num_layers=max(n, 1))
        keys = jax.random.split(jax.random.PRNGKey(0), max(n, 1))
        layers = [M.init_layer_params(kk, cfg) for kk in keys[:n]]
        axes = [layer_axes(hp, j) for j in range(n)]
        layers = [
            jax.device_put(lp, jax.tree.map(
                lambda sp: S.named(mesh, sp), layer_param_specs(cfg, ax),
                is_leaf=lambda v: isinstance(v, P),
            ))
            for lp, ax in zip(layers, axes)
        ]
        x = jax.random.normal(jax.random.PRNGKey(1), (bsz, seq, cfg.hidden_size), self._dtype)
        positions = jnp.broadcast_to(jnp.arange(seq), (bsz, seq))

        def fwd(layers, x):
            for j, lp in enumerate(layers):
                ax = axes[j]
                x = S.constrain(x, mesh, S.act_spec(ax))
                x = M.layer_forward(lp, x, positions, cfg, mesh=mesh, axes=ax)
            return jnp.sum(x.astype(jnp.float32))

        return fwd, layers, (x,)

    def _grad_ms(self, t: int, bsz: int, seq: int, policy: Optional[str]) -> float:
        """Per-layer fwd+bwd walltime (layer-differenced), with the stack
        wrapped in jax.checkpoint under `policy` when given. Whole-stack
        wrapping yields the same per-layer recompute toll as per-layer
        wrapping — every layer's forward replays exactly once either way —
        and reuses the family's _stack_t hook unchanged."""
        from galvatron_tpu.models.base import _remat

        a = self.args
        lo, hi = a.layernum_min, a.layernum_max

        def grad_prog(n):
            fwd, layers, xs = self._stack_t(t, n, bsz, seq)
            f = _remat(fwd, policy) if policy and policy != "none" else fwd
            return (lambda ls, *xx: jax.grad(f)(ls, *xx)), (layers,) + tuple(xs)

        g_lo, args_lo = grad_prog(lo)
        g_hi, args_hi = grad_prog(hi)
        t_lo = _walltime(jax.jit(g_lo), args_lo, a.warmup, a.iters)
        t_hi = _walltime(jax.jit(g_hi), args_hi, a.warmup, a.iters)
        return max((t_hi - t_lo) / (hi - lo) * 1e3, 1e-9)

    def profile_remat(self, t: int = 0) -> Dict[str, float]:
        """Measured backward recompute toll per remat policy, as a fraction
        of the forward (TimeCostModel.remat_frac's profiled override):
        frac(policy) = (grad_ms(policy) - grad_ms(no-remat)) / fwd_ms,
        layer-differenced like every other table. Clamped to [0, 1.5] so
        timer noise can never feed the search a negative (or absurd)
        recompute price."""
        a = self.args
        seq = self._target_seq
        bsz = a.profile_batch_size
        fwd_ms = self._fwd_ms(t, bsz, seq) * bsz  # un-normalise to per-layer ms
        base = self._grad_ms(t, bsz, seq, None)
        out: Dict[str, float] = {"none": 0.0}
        for pol in ("full", "nothing_saveable", "dots_saveable"):
            frac = (self._grad_ms(t, bsz, seq, pol) - base) / max(fwd_ms, 1e-9)
            out[pol] = round(float(min(max(frac, 0.0), 1.5)), 4)
        # a policy that pins MORE tensors can never owe more recompute than
        # full remat; enforce against timer noise on tiny profile models
        out["dots_saveable"] = min(out["dots_saveable"], out["full"])
        return out

    def _other_ms_per_sample(self, bsz: int, seq: int, per_layer_ms_sum: float) -> float:
        """Embedding + head + loss time: full tiny model minus its layers'
        share (reference separates this as 'other_time')."""
        a = self.args
        loss, params, batch = self._full_model(a.layernum_min, bsz, seq)
        t = _walltime(jax.jit(loss), (params, batch), a.warmup, a.iters)
        return max(t / bsz * 1e3 - a.layernum_min * per_layer_ms_sum, 1e-6)

    # ------------------------------------------------------------ computation
    def profile_computation(self) -> Dict:
        """time_config for the search engine, every layer type. profile_mode:
        - static: one scalar at (profile_batch_size, seq);
        - batch: linear fit [m, c] of per-layer total ms vs batch size
          (reference fits with scipy at search time, search_engine.py:119-163
          — here the fit happens at profile time, same curve);
        - sequence: quadratic sweep over seq; stored under "seqlen%d" keys plus
          the fit evaluated at the target seq as the headline scalar."""
        a = self.args
        seq = self._target_seq
        out: Dict = {}
        headline = []  # per-type scalar at the target point, for other_time
        for t in range(self.layer_types):
            key = "layertype_%d" % t
            if a.profile_mode == "batch":
                bszs = list(range(a.profile_min_batch_size, a.profile_max_batch_size + 1, a.batch_size_step))
                totals = [self._fwd_ms(t, b, seq) * b for b in bszs]
                m, c = np.polyfit(np.asarray(bszs, np.float64), np.asarray(totals, np.float64), 1)
                # time is monotone in batch; clamp fit noise so a noisy sweep
                # can never feed the search a negative marginal cost
                out[key] = [float(max(m, 0.0)), float(max(c, 0.0))]
                headline.append(totals[-1] / bszs[-1])
            elif a.profile_mode == "sequence":
                seqs = list(range(a.profile_min_seq_length, a.profile_max_seq_length + 1, a.seq_length_step))
                per_seq = {s: self._fwd_ms(t, a.profile_batch_size, s) for s in seqs}
                for s, v in per_seq.items():
                    out["%s_seqlen%d" % (key, s)] = v
                coef = np.polyfit(np.asarray(seqs, np.float64), np.asarray(list(per_seq.values())), 2)
                out["%s_seq_popt" % key] = [float(v) for v in coef]
                out[key] = float(np.polyval(coef, seq))
                headline.append(out[key])
            else:
                out[key] = self._fwd_ms(t, a.profile_batch_size, seq)
                headline.append(out[key])
        bsz_for_other = a.profile_max_batch_size if a.profile_mode == "batch" else a.profile_batch_size
        out["other_time"] = self._other_ms_per_sample(bsz_for_other, seq, sum(headline))
        if a.profile_remat:
            # per-policy backward recompute fractions, consumed by
            # TimeCostModel via ProfileModelArgs.remat_recompute_frac
            out["remat_recompute_frac"] = self.profile_remat()
        return out

    # ----------------------------------------------------------------- memory
    def profile_memory(self) -> Dict:
        a = self.args
        seq = self._target_seq
        bsz = a.profile_batch_size
        tps = []
        t = 1
        while t <= a.max_tp_deg:
            tps.append(t)
            t *= 2
        out: Dict = {}
        for lt in range(self.layer_types):
            param_mb = self._layer_param_bytes(lt) / MB
            act1 = self._act_bytes(lt, bsz, seq, remat=False) / MB
            act_ckpt = self._act_bytes(lt, bsz, seq, remat=True) / MB
            # tp>1 entries are MEASURED on a k-device mesh when the machine
            # has one (tests, multi-chip); a single-chip profile falls back to
            # the act(1)/k derivation
            tp_act = {}
            for k in tps:
                measured = self._act_bytes_tp(lt, bsz, seq, k) if k > 1 else None
                tp_act[k] = round(measured / MB if measured else act1 / k, 3)
                if k > 1:
                    # per-strategy rows (ulysses all-to-all / ring blockwise
                    # footprints differ from act/k); written only when
                    # measured — the cost model falls back to the derivation
                    m_u = self._act_bytes_tp(lt, bsz, seq, k, kind="ulysses")
                    if m_u:
                        tp_act["ulysses_%d" % k] = round(m_u / MB, 3)
                    m_c = self._act_bytes_tp(lt, bsz, seq, k, kind="cp")
                    if m_c:
                        tp_act["cp_%d" % k] = round(m_c / MB, 3)
            tp_act["checkpoint"] = round(min(act_ckpt, act1), 3)
            out["layertype_%d" % lt] = {
                "parameter_size": round(param_mb, 3),
                "tp_activation_per_bsz_dict": tp_act,
            }
        embed_mb, head_mb, rest_mb, act_total = self._other_model_state_tables(bsz, seq, tps)

        def per_tp(x):
            return {k: round(x / k, 3) for k in tps}

        # model_states = 4x params (param+grad+adam moments, fp32 master), the
        # same convention MemoryCostModel applies to layer parameter_size
        out["other_memory_pp_off"] = {
            "model_states": per_tp(4 * (embed_mb + head_mb + rest_mb)),
            "activation": {k: round(act_total / bsz / k, 3) for k in tps},
        }
        out["other_memory_pp_on"] = {
            "first_stage": {
                "model_states": per_tp(4 * embed_mb),
                "activation": {k: round(0.5 * act_total / bsz / k, 3) for k in tps},
            },
            "last_stage": {
                "model_states": per_tp(4 * (head_mb + rest_mb)),
                "activation": {k: round(0.5 * act_total / bsz / k, 3) for k in tps},
            },
        }
        return out

    # ------------------------------------------------------------------- files
    def config_paths(self) -> Dict[str, str]:
        tag = self._file_tag()
        return {
            "computation": os.path.join(
                self.args.config_dir, "computation_profiling_%s_%s.json" % (tag, self.model_name)
            ),
            "memory": os.path.join(
                self.args.config_dir, "memory_profiling_%s_%s.json" % (tag, self.model_name)
            ),
        }

    def profile_all(self, write: bool = True) -> Dict[str, Dict]:
        results = {
            "computation": self.profile_computation(),
            "memory": self.profile_memory(),
        }
        if write:
            os.makedirs(self.args.config_dir, exist_ok=True)
            paths = self.config_paths()
            for k, v in results.items():
                write_json_config(v, paths[k])
        return results


class T5ModelProfiler(ModelProfiler):
    """Two-layer-type profiler for T5 (layertype_0 = encoder, layertype_1 =
    decoder; search consumes them via the multi-layer-type DP,
    dynamic_programming.py:170-189). The decoder stack is differenced against
    a FIXED encoder output so the cross-attention cost lands in the decoder
    layer type. Every profile_mode of the base class works here."""

    layer_types = 2

    def _check_config(self, cfg):
        from galvatron_tpu.models.t5 import T5Config

        if not isinstance(cfg, T5Config):
            raise TypeError("T5ModelProfiler needs a T5Config")

    def _stack_t(self, t: int, n: int, bsz: int, seq: int, remat: bool = False):
        from galvatron_tpu.models import t5 as T

        cfg = dataclasses.replace(self.cfg, compute_dtype=self._dtype)
        keys = jax.random.split(jax.random.PRNGKey(0), max(n, 1))
        x = jax.random.normal(jax.random.PRNGKey(1), (bsz, seq, cfg.hidden_size), self._dtype)
        table = jax.random.normal(
            jax.random.PRNGKey(2), (cfg.rel_buckets, cfg.num_heads), jnp.float32
        ) * 0.02
        if t == 0:
            layers = [T.init_enc_layer(k, cfg) for k in keys[:n]]
            bias = T.rel_bias(table, seq, seq, cfg, bidirectional=True)
            body = lambda lp, x: T.enc_layer_forward(lp, x, cfg, bias)
            extra = (x,)

            def fwd(layers, x):
                for lp in layers:
                    f = jax.checkpoint(body) if remat else body
                    x = f(lp, x)
                return jnp.sum(x.astype(jnp.float32))

            return fwd, layers, extra
        layers = [T.init_dec_layer(k, cfg) for k in keys[:n]]
        bias = T.rel_bias(table, seq, seq, cfg, bidirectional=False)
        enc_out = jax.random.normal(jax.random.PRNGKey(3), (bsz, seq, cfg.hidden_size), self._dtype)
        body = lambda lp, x: T.dec_layer_forward(lp, x, enc_out, cfg, bias)

        def fwd(layers, x):
            for lp in layers:
                f = jax.checkpoint(body) if remat else body
                x = f(lp, x)
            return jnp.sum(x.astype(jnp.float32))

        return fwd, layers, (x,)

    def _sharded_stack_t(self, t: int, n: int, bsz: int, seq: int, hp, mesh,
                         kind: str):
        """Per-strategy measurement for the enc/dec layer types (the
        decoder's fixed encoder memory replicates across the mesh). Ring cp
        needs a zigzag-permuted bias layout the profiler does not model;
        fall back to the derivation for it."""
        if kind == "cp":
            return None
        from jax.sharding import PartitionSpec as P

        from galvatron_tpu.models import t5 as T
        from galvatron_tpu.parallel import spec as S
        from galvatron_tpu.parallel.mesh import layer_axes

        cfg = dataclasses.replace(self.cfg, compute_dtype=self._dtype)
        keys = jax.random.split(jax.random.PRNGKey(0), max(n, 1))
        x = jax.random.normal(jax.random.PRNGKey(1), (bsz, seq, cfg.hidden_size), self._dtype)
        table = jax.random.normal(
            jax.random.PRNGKey(2), (cfg.rel_buckets, cfg.num_heads), jnp.float32
        ) * 0.02
        axes = [layer_axes(hp, j) for j in range(n)]
        init = T.init_enc_layer if t == 0 else T.init_dec_layer
        specs = T.enc_layer_specs if t == 0 else T.dec_layer_specs
        layers = [
            jax.device_put(init(kk, cfg), jax.tree.map(
                lambda sp: S.named(mesh, sp), specs(cfg, ax),
                is_leaf=lambda v: isinstance(v, P),
            ))
            for kk, ax in zip(keys[:n], axes)
        ]
        bias = T.rel_bias(table, seq, seq, cfg, bidirectional=(t == 0))
        if t == 0:
            def fwd(layers, x):
                for j, lp in enumerate(layers):
                    ax = axes[j]
                    x = S.constrain(x, mesh, S.act_spec(ax))
                    x = T.enc_layer_forward(lp, x, cfg, bias, mesh=mesh, axes=ax)
                return jnp.sum(x.astype(jnp.float32))

            return fwd, layers, (x,)
        enc_out = jax.random.normal(
            jax.random.PRNGKey(3), (bsz, seq, cfg.hidden_size), self._dtype
        )

        def fwd(layers, x):
            for j, lp in enumerate(layers):
                ax = axes[j]
                x = S.constrain(x, mesh, S.act_spec(ax))
                x = T.dec_layer_forward(lp, x, enc_out, cfg, bias, mesh=mesh, axes=ax)
            return jnp.sum(x.astype(jnp.float32))

        return fwd, layers, (x,)

    def _layer_param_bytes(self, t: int) -> int:
        from galvatron_tpu.models import t5 as T

        init = T.init_enc_layer if t == 0 else T.init_dec_layer
        return _tree_bytes(init(jax.random.PRNGKey(0), self.cfg))

    def _full_model(self, n_layers: int, bsz: int, seq: int):
        from galvatron_tpu.models import t5 as T

        cfg = dataclasses.replace(
            self.cfg, num_enc_layers=n_layers, num_dec_layers=n_layers,
            compute_dtype=self._dtype,
        )
        params = T.init_t5_params(jax.random.PRNGKey(0), cfg)
        enc = jax.random.randint(jax.random.PRNGKey(1), (bsz, seq), 0, cfg.vocab_size)
        dec = jax.random.randint(jax.random.PRNGKey(2), (bsz, seq), 0, cfg.vocab_size)
        batch = {"tokens": enc, "dec_tokens": dec, "labels": dec}
        return (lambda p, b: T.t5_loss_fn(p, b, cfg)), params, batch

    def _other_model_state_tables(self, bsz: int, seq: int, tps: Sequence[int]):
        loss, params, batch = self._full_model(0, bsz, seq)
        embed_mb = _tree_bytes(params["embed"]) / MB
        rest_mb = (_tree_bytes(params) - _tree_bytes(params["embed"])) / MB
        head_mb = embed_mb if self.cfg.tie_embeddings else _tree_bytes(params.get("lm_head", {})) / MB
        act_total = _compiled_peak_bytes(lambda p, b: jax.grad(loss)(p, b), (params, batch))
        act_total = max(act_total - 2 * _tree_bytes(params), 1024.0) / MB
        return embed_mb, head_mb, rest_mb, act_total


class SwinModelProfiler(ModelProfiler):
    """Per-stage layer types for swin (reference `layernum_listed` profiling,
    model_profiler.py:71-75, with per-stage seqlens :96-100): layertype_s is
    stage s's block at its own resolution/width. Block differencing runs on
    (B, res, res, C) activations; shifted blocks alternate as in the model."""

    def _check_config(self, cfg):
        from galvatron_tpu.models.swin import SwinConfig

        if not isinstance(cfg, SwinConfig):
            raise TypeError("SwinModelProfiler needs a SwinConfig")

    @property
    def _target_seq(self) -> int:
        # each stage has its own resolution; the headline seq is the stage-0
        # patch-grid token count
        return self.args.profile_seq_length or self.cfg.stage_resolution(0) ** 2

    def _file_tag(self) -> str:
        c = self.cfg
        return "%s_hidden%d_head%d_seqlen%d" % (
            self.args.mixed_precision, c.embed_dim, c.num_heads[0], self._target_seq
        )

    @property
    def layer_types(self):  # type: ignore[override]
        return self.cfg.num_stages

    def _sharded_stack_t(self, t: int, n: int, bsz: int, seq: int, hp, mesh,
                         kind: str):
        """Per-strategy measurement for swin blocks. Only tp applies (window
        attention has no sequence dim to shard: cp/ulysses fall back)."""
        if kind != "tp":
            return None
        from jax.sharding import PartitionSpec as P

        from galvatron_tpu.models import swin as W
        from galvatron_tpu.parallel import spec as S
        from galvatron_tpu.parallel.mesh import layer_axes

        cfg = dataclasses.replace(self.cfg, compute_dtype=self._dtype)
        if cfg.num_heads[t] % max(hp.layers[0].tp, 1) != 0:
            return None
        res = cfg.stage_resolution(t)
        keys = jax.random.split(jax.random.PRNGKey(0), max(n, 1))
        axes = [layer_axes(hp, j) for j in range(n)]
        layers = [
            jax.device_put(W.init_block_params(kk, cfg, t), jax.tree.map(
                lambda sp: S.named(mesh, sp), W.block_param_specs(cfg, t, ax),
                is_leaf=lambda v: isinstance(v, P),
            ))
            for kk, ax in zip(keys[:n], axes)
        ]
        x = jax.random.normal(
            jax.random.PRNGKey(1), (bsz, res, res, cfg.stage_dim(t)), self._dtype
        )

        def fwd(layers, x):
            for j, lp in enumerate(layers):
                x = W.block_forward(
                    lp, x, cfg=cfg, stage=t, shift=(j % 2 == 1),
                    mesh=mesh, axes=axes[j],
                )
            return jnp.sum(x.astype(jnp.float32))

        return fwd, layers, (x,)

    def _stack_t(self, t: int, n: int, bsz: int, seq: int, remat: bool = False):
        # `seq` is ignored: each stage has a fixed resolution from the config
        from galvatron_tpu.models import swin as W

        cfg = dataclasses.replace(self.cfg, compute_dtype=self._dtype)
        res = cfg.stage_resolution(t)
        keys = jax.random.split(jax.random.PRNGKey(0), max(n, 1))
        layers = [W.init_block_params(k, cfg, t) for k in keys[:n]]
        x = jax.random.normal(
            jax.random.PRNGKey(1), (bsz, res, res, cfg.stage_dim(t)), self._dtype
        )

        def fwd(layers, x):
            for j, lp in enumerate(layers):
                body = partial(W.block_forward, cfg=cfg, stage=t, shift=(j % 2 == 1))
                f = jax.checkpoint(body) if remat else body
                x = f(lp, x)
            return jnp.sum(x.astype(jnp.float32))

        return fwd, layers, (x,)

    def _layer_param_bytes(self, t: int) -> int:
        from galvatron_tpu.models import swin as W

        return _tree_bytes(W.init_block_params(jax.random.PRNGKey(0), self.cfg, t))

    def _full_model(self, n_layers: int, bsz: int, seq: int):
        from galvatron_tpu.models import swin as W

        cfg = dataclasses.replace(
            self.cfg,
            depths=tuple(max(n_layers, 1) for _ in self.cfg.depths),
            compute_dtype=self._dtype,
        )
        params = W.init_swin_params(jax.random.PRNGKey(0), cfg)
        if n_layers == 0:
            params["blocks"] = []
            cfg = dataclasses.replace(cfg, depths=tuple(0 for _ in self.cfg.depths))
        batch = {
            "pixels": jax.random.normal(
                jax.random.PRNGKey(1), (bsz, cfg.image_size, cfg.image_size, cfg.num_channels)
            ),
            "labels": jax.random.randint(jax.random.PRNGKey(2), (bsz,), 0, max(cfg.num_classes, 1)),
        }
        return (lambda p, b: W.swin_loss_fn(p, b, cfg)), params, batch

    def _other_model_state_tables(self, bsz: int, seq: int, tps: Sequence[int]):
        loss, params, batch = self._full_model(0, bsz, seq)
        embed_mb = _tree_bytes(params["embed"]) / MB
        head_mb = _tree_bytes(params["head"]) / MB
        rest_mb = (_tree_bytes(params["merges"]) + _tree_bytes(params["final_norm"])) / MB
        act_total = _compiled_peak_bytes(lambda p, b: jax.grad(loss)(p, b), (params, batch))
        act_total = max(act_total - 2 * _tree_bytes(params), 1024.0) / MB
        return embed_mb, head_mb, rest_mb, act_total

