"""Prefill/decode inference engine with continuous batching.

Execution model
---------------
- **Prefill** (one request, compute-bound): the prompt runs through the SAME
  `models/base.run_layers` scan path the trainer uses, with `collect_kv=True`
  turning each layer's post-rope (k, v) into scan side outputs; the block is
  written into the request's cache slot and the first token is sampled from
  the last valid position. TTFT is dominated by this step.
- **Decode** (all active slots, bandwidth-bound): one jitted step embeds the
  last sampled token per slot at position `lengths`, runs
  `models/base.decode_layer_forward` per layer against the cached K/V
  (causality + slot-length masking folded into one additive
  `kv_cache.length_bias`), appends the new k/v in place, and samples.
- **Buckets**: context lengths are quantised to `page_size` pages; each
  (kind, page-count) pair gets ONE executable, AOT-compiled through an
  in-process memo with the persistent compile cache BYPASSED — executing a
  DESERIALIZED XLA:CPU executable through the AOT fast path corrupts the
  allocator heap on jaxlib 0.4.37 (see cli/train.py `_compile_uncached` and
  tests/conftest.py), so serve reuses live executable objects only.
- **Continuous batching**: slot-based admission in strict arrival (FIFO)
  order; a slot frees the moment its request hits `max_new_tokens`, and the
  next pending request is admitted at the following scheduler tick, so batch
  occupancy refills without draining.
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from galvatron_tpu.config.strategy import HybridParallelConfig
from galvatron_tpu.models import base as M
from galvatron_tpu.obs import telemetry as T
from galvatron_tpu.serve.kv_cache import (
    KVCacheConfig,
    bucket_pages,
    init_kv_cache,
    kv_cache_specs,
    length_bias,
    request_fits,
    write_prompt_kv,
)
from galvatron_tpu.parallel import spec as S
from galvatron_tpu.parallel.mesh import layer_axes, vocab_axes


def _cache_constrainer(cfg, hp, mesh, max_slots=None):
    """Pin the returned cache pytree to its canonical strategy-derived
    layout. Without this, GSPMD propagates whatever sharding the last update
    op preferred into the jit output, and the SECOND call of the memoized
    AOT executable rejects its own previous output ("input sharding does not
    match the sharding the computation was compiled with")."""
    if hp is None or mesh is None:
        return lambda c: c
    specs = kv_cache_specs(hp, mesh, cfg, max_slots)

    def constrain(c):
        return {
            "k": [S.constrain(x, mesh, sp) for x, sp in zip(c["k"], specs["k"])],
            "v": [S.constrain(x, mesh, sp) for x, sp in zip(c["v"], specs["v"])],
            "lengths": S.constrain(c["lengths"], mesh, specs["lengths"]),
        }

    return constrain

# ------------------------------------------------------------- AOT executables
# In-process memo of live compiled executables, keyed on (mesh device ids,
# HLO digest) — the cli/train.py `_STEP_EXECUTABLES` discipline. Entries are
# never serialized; `_compile_uncached` additionally keeps the compile itself
# out of the persistent cache so no deserialized executable can ever reach
# the AOT fast path (the jaxlib 0.4.37 heap-corruption hazard).
_SERVE_EXECUTABLES: "OrderedDict[Tuple, Any]" = OrderedDict()
_SERVE_EXECUTABLES_MAX = 32


def _compile_uncached(lowered):
    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        return lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def _exec_key(mesh: Optional[Mesh], lowered) -> Optional[Tuple]:
    try:
        dev_ids = (
            tuple(int(d.id) for d in mesh.devices.flat)
            if mesh is not None else ("nomesh",)
        )
        return (dev_ids, hashlib.sha256(lowered.as_text().encode()).hexdigest())
    except Exception:
        return None


def _aot_executable(jitted, mesh, *args):
    """AOT-compile `jitted` for these args through the memo; returns a
    callable — `jitted` itself (plain-jit fallback) when lowering or AOT
    compilation is unsupported. Lower/compile happens at most once per
    (mesh, HLO) — callers hold on to the result and reuse it every tick."""
    try:
        lowered = jitted.lower(*args)
        key = _exec_key(mesh, lowered)
    except Exception:
        return jitted
    if key is not None and key in _SERVE_EXECUTABLES:
        _SERVE_EXECUTABLES.move_to_end(key)
        return _SERVE_EXECUTABLES[key]
    try:
        compiled = _compile_uncached(lowered)
    except ValueError:
        return jitted
    if key is not None:
        _SERVE_EXECUTABLES[key] = compiled
        while len(_SERVE_EXECUTABLES) > _SERVE_EXECUTABLES_MAX:
            _SERVE_EXECUTABLES.popitem(last=False)
    return compiled


# ------------------------------------------------------------------- sampling
def sample_token(logits: jax.Array, rng: jax.Array, temperature: float) -> jax.Array:
    """Greedy (temperature <= 0) or temperature sampling over (..., V)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(jnp.int32)


# ------------------------------------------------------------ step factories
def make_prefill_step(
    cfg: M.TransformerConfig,
    hp: Optional[HybridParallelConfig],
    mesh: Optional[Mesh],
    kv_cfg: KVCacheConfig,
    pages: int,
    temperature: float = 0.0,
) -> Callable:
    """Build the prefill function for one `pages` bucket:
    (params, cache, tokens (1, ctx_b), prompt_len, slot, rng)
      -> (cache', first_token (1,), last_logits (1, V)).
    Padding past prompt_len is masked in attention and in the sampled
    position; its garbage K/V lands in the cache but stays behind the
    length mask until decode overwrites it."""
    ctx_b = pages * kv_cfg.page_size
    use_hp = hp is not None and mesh is not None
    vax = vocab_axes(hp) if use_hp else None
    constrain_cache = _cache_constrainer(cfg, hp, mesh, kv_cfg.max_slots)

    def prefill_bucket(params, cache, tokens, prompt_len, slot, rng):
        positions = jnp.broadcast_to(jnp.arange(ctx_b), (1, ctx_b))
        valid = (jnp.arange(ctx_b) < prompt_len)[None, :]
        bias = M.padding_attn_bias(valid)
        x = M.embed_tokens(params["embed"], tokens, positions, cfg, mesh, vax)
        x, kvs = M.run_layers(
            params, x, positions, cfg,
            hp if use_hp else None, mesh if use_hp else None,
            attn_bias=bias, collect_kv=True,
        )
        h_last = jax.lax.dynamic_slice(
            x, (0, prompt_len - 1, 0), (1, 1, x.shape[-1])
        )
        logits = M.lm_logits(params, h_last, cfg)[:, 0]
        token = sample_token(logits, rng, temperature)
        cache = constrain_cache(write_prompt_kv(cache, kvs, slot, prompt_len))
        return cache, token, logits

    return jax.jit(prefill_bucket, donate_argnums=(1,))


def make_decode_step(
    cfg: M.TransformerConfig,
    hp: Optional[HybridParallelConfig],
    mesh: Optional[Mesh],
    kv_cfg: KVCacheConfig,
    pages: int,
    temperature: float = 0.0,
) -> Callable:
    """Build the single-token decode function for one `pages` bucket:
    (params, cache, tokens (slots,), active (slots,) bool, rng)
      -> (cache', next_tokens (slots,), logits (slots, V)).
    All slots step together; inactive slots compute (and write masked
    garbage k/v at their frozen length) but neither advance `lengths` nor
    change their token — their columns are overwritten at re-admission."""
    ctx_b = pages * kv_cfg.page_size
    use_hp = hp is not None and mesh is not None
    vax = vocab_axes(hp) if use_hp else None
    constrain_cache = _cache_constrainer(cfg, hp, mesh, kv_cfg.max_slots)

    def decode(params, cache, tokens, active, rng):
        lengths = cache["lengths"]
        positions = lengths[:, None]
        x = M.embed_tokens(params["embed"], tokens[:, None], positions, cfg, mesh, vax)
        bias = length_bias(lengths, ctx_b)
        k_list, v_list = list(cache["k"]), list(cache["v"])
        for li in range(cfg.num_layers):
            axes = layer_axes(hp, li) if use_hp else None
            k_c = jax.lax.slice_in_dim(k_list[li], 0, ctx_b, axis=1)
            v_c = jax.lax.slice_in_dim(v_list[li], 0, ctx_b, axis=1)
            x, k_c, v_c = M.decode_layer_forward(
                params["layers"][li], x, positions, cfg,
                k_cache=k_c, v_cache=v_c, write_index=lengths,
                mesh=mesh if use_hp else None, axes=axes, attn_bias=bias,
            )
            k_list[li] = jax.lax.dynamic_update_slice(k_list[li], k_c, (0, 0, 0, 0))
            v_list[li] = jax.lax.dynamic_update_slice(v_list[li], v_c, (0, 0, 0, 0))
        logits = M.lm_logits(params, x, cfg)[:, 0]
        next_tok = sample_token(logits, rng, temperature)
        next_tok = jnp.where(active, next_tok, tokens)
        lengths = lengths + active.astype(jnp.int32)
        return (
            constrain_cache({"k": k_list, "v": v_list, "lengths": lengths}),
            next_tok,
            logits,
        )

    return jax.jit(decode, donate_argnums=(1,))


# -------------------------------------------------------------------- engine
class ServeEngine:
    """Owns the cache + per-bucket executables; host-level prefill/decode API
    returning numpy. The scheduler (ContinuousBatcher) drives it."""

    def __init__(
        self,
        cfg: M.TransformerConfig,
        params: Any,
        kv_cfg: KVCacheConfig,
        hp: Optional[HybridParallelConfig] = None,
        mesh: Optional[Mesh] = None,
        temperature: float = 0.0,
        rng_seed: int = 0,
    ):
        if cfg.head_type != "lm":
            raise ValueError("serving requires a causal LM head, got head_type=%r" % cfg.head_type)
        self.cfg, self.params, self.kv_cfg = cfg, params, kv_cfg
        self.hp, self.mesh = hp, mesh
        self.temperature = temperature
        self.cache = init_kv_cache(cfg, kv_cfg, hp, mesh)
        self._rng = jax.random.PRNGKey(rng_seed)
        self._prefill_fns: Dict[int, Callable] = {}
        self._decode_fns: Dict[int, Callable] = {}
        self._execs: Dict[Tuple[str, int], Callable] = {}

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _prefill_fn(self, pages: int) -> Callable:
        if pages not in self._prefill_fns:
            self._prefill_fns[pages] = make_prefill_step(
                self.cfg, self.hp, self.mesh, self.kv_cfg, pages, self.temperature
            )
        return self._prefill_fns[pages]

    def _decode_fn(self, pages: int) -> Callable:
        if pages not in self._decode_fns:
            self._decode_fns[pages] = make_decode_step(
                self.cfg, self.hp, self.mesh, self.kv_cfg, pages, self.temperature
            )
        return self._decode_fns[pages]

    def _call(self, kind: str, pages: int, jitted: Callable, *args):
        ekey = (kind, pages)
        fn = self._execs.get(ekey)
        if fn is None:
            fn = _aot_executable(jitted, self.mesh, *args)
            self._execs[ekey] = fn
        return fn(*args)

    def prefill(self, prompt: Sequence[int], slot: int) -> Tuple[int, np.ndarray]:
        """Run one prompt into cache row `slot`; returns (first_token, logits)."""
        plen = len(prompt)
        pages = bucket_pages(plen, self.kv_cfg.page_size, self.kv_cfg.max_pages)
        ctx_b = pages * self.kv_cfg.page_size
        tokens = np.zeros((1, ctx_b), np.int32)
        tokens[0, :plen] = np.asarray(prompt, np.int32)
        self.cache, tok, logits = self._call(
            "prefill", pages, self._prefill_fn(pages),
            self.params, self.cache, jnp.asarray(tokens),
            jnp.int32(plen), jnp.int32(slot), self._next_rng(),
        )
        return int(jax.device_get(tok)[0]), np.asarray(jax.device_get(logits))[0]

    def decode_step(
        self, tokens: np.ndarray, active: np.ndarray, pages: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One decode tick over every slot; returns (next_tokens, logits)."""
        self.cache, next_tok, logits = self._call(
            "decode", pages, self._decode_fn(pages),
            self.params, self.cache,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(active, bool),
            self._next_rng(),
        )
        return np.asarray(jax.device_get(next_tok)), np.asarray(jax.device_get(logits))


# ---------------------------------------------------------------- load model
@dataclasses.dataclass
class Request:
    rid: int
    arrival_s: float
    prompt: List[int]
    max_new_tokens: int
    deadline_s: Optional[float] = None  # absolute TTFT deadline (batcher clock)
    # runtime bookkeeping (filled by the batcher)
    slot: Optional[int] = None
    output: List[int] = dataclasses.field(default_factory=list)
    prefill_start_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    # terminal disposition: "pending" while live, then exactly one of
    # "completed" | "shed" (retryable, never started or abandoned mid-decode)
    # | "failed" (non-retryable, e.g. oversize for the cache geometry).
    status: str = "pending"
    finish_reason: Optional[str] = None
    retryable: bool = False

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def journal(self) -> List[int]:
        """The request's full token history — prompt plus every sampled
        token. Pure token sequences are replayable by construction: the
        exact cache state of an in-flight request is reproduced by greedy
        re-prefill of ``journal[:-1]`` (see ContinuousBatcher.migrate_to)."""
        return list(self.prompt) + list(self.output)

    def ttft_ms(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.arrival_s) * 1000.0

    def tpot_ms(self) -> Optional[float]:
        if self.done_t is None or self.first_token_t is None or len(self.output) < 2:
            return None
        return (self.done_t - self.first_token_t) * 1000.0 / (len(self.output) - 1)


def synthetic_requests(
    n: int,
    *,
    vocab_size: int,
    seed: int = 0,
    rate_rps: float = 0.0,
    prompt_len_range: Tuple[int, int] = (4, 16),
    max_new_tokens: int = 8,
) -> List[Request]:
    """Poisson arrivals (`rate_rps` > 0; 0 = a t=0 backlog) with uniform
    prompt lengths — the synthetic open-loop load for cli/serve and bench."""
    rnd = random.Random(seed)
    t = 0.0
    out = []
    for rid in range(n):
        if rate_rps > 0:
            t += rnd.expovariate(rate_rps)
        plen = rnd.randint(*prompt_len_range)
        prompt = [rnd.randrange(vocab_size) for _ in range(plen)]
        out.append(Request(rid=rid, arrival_s=t, prompt=prompt, max_new_tokens=max_new_tokens))
    return out


def replay_requests(path: str, *, vocab_size: int, seed: int = 0) -> List[Request]:
    """Replay a trace: JSONL of {"arrival_s", "prompt_len", "max_new_tokens"}
    (prompt token ids synthesised deterministically from `seed`)."""
    import json

    rnd = random.Random(seed)
    out = []
    with open(path) as f:
        for rid, line in enumerate(ln for ln in f if ln.strip()):
            rec = json.loads(line)
            plen = int(rec["prompt_len"])
            out.append(Request(
                rid=rid,
                arrival_s=float(rec.get("arrival_s", 0.0)),
                prompt=[rnd.randrange(vocab_size) for _ in range(plen)],
                max_new_tokens=int(rec.get("max_new_tokens", 8)),
            ))
    return out


# ----------------------------------------------------------------- scheduler
class ContinuousBatcher:
    """Slot-based continuous batching over a ServeEngine (or any object with
    the same prefill/decode_step surface — scheduler tests use a fake).

    Invariants (tests/serve/test_scheduler.py):
    - admission is strict FIFO in arrival order — a later request never
      occupies a slot while an earlier arrived one waits;
    - no slot leak: every admitted request frees its slot at completion, and
      a slot is never doubly occupied — including under exceptions in
      prefill or decode;
    - bucket routing: each decode tick runs in the smallest page bucket
      covering every active slot's next write position;
    - no request ever raises out of the batcher: oversize prompts, blown
      deadlines, and predicted-TTFT overload are structured rejections
      (`Request.status`/`finish_reason`/`retryable`) collected in
      ``self.shed``, not exceptions.

    Admission control: ``p99_ttft_ms`` arms a cheap predicted-TTFT model —
    time already waited plus queue position times the learned median prefill
    and decode-tick costs — that sheds (retryable) any pending request which
    cannot meet the bound. ``max_pending`` bounds the arrived-but-unadmitted
    queue; overflow sheds from the tail (newest arrivals). Both engage only
    after ``min_shed_samples`` prefills AND ticks have been observed, so
    compile warmup never sheds.

    Resilience: an optional ``watchdog`` (runtime/health.Watchdog) is armed
    around every prefill and decode tick with learned deadlines; an optional
    ``control`` callback is polled once per scheduler iteration and may
    return a drain-reason string (e.g. ``"SIGTERM"``, ``"watchdog"``) to
    stop admission and wind down, or trigger a live migration itself via
    ``migrate_to`` and return None (the cli/serve resilience hook).
    """

    def __init__(
        self,
        engine,
        kv_cfg: KVCacheConfig,
        clock: Optional[Callable[[], float]] = None,
        p99_ttft_ms: float = 0.0,
        max_pending: int = 0,
        request_timeout_s: float = 0.0,
        min_shed_samples: int = 3,
        watchdog=None,
        control: Optional[Callable[["ContinuousBatcher"], Optional[str]]] = None,
    ):
        self.engine = engine
        self.kv_cfg = kv_cfg
        self._clock = clock if clock is not None else time.monotonic
        self._t0: Optional[float] = None
        self.p99_ttft_ms = float(p99_ttft_ms)
        self.max_pending = int(max_pending)
        self.request_timeout_s = float(request_timeout_s)
        self.min_shed_samples = int(min_shed_samples)
        self.watchdog = watchdog
        self.control = control
        # host-side per-slot state (device lengths are never read back)
        self.slot_req: List[Optional[Request]] = [None] * kv_cfg.max_slots
        self.slot_len = np.zeros((kv_cfg.max_slots,), np.int64)
        self.slot_tok = np.zeros((kv_cfg.max_slots,), np.int32)
        self.decode_steps = 0
        self.completed: List[Request] = []
        self.shed: List[Request] = []
        self.migrations = 0
        self.drain_reason: Optional[str] = None
        # learned cost medians feeding the predicted-TTFT shed model
        self._prefill_ms: deque = deque(maxlen=64)
        self._tick_ms: deque = deque(maxlen=64)

    def now(self) -> float:
        if self._t0 is None:
            self._t0 = self._clock()
        return self._clock() - self._t0

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self.slot_req):
            if r is None:
                return i
        return None

    def occupancy(self) -> int:
        return sum(1 for r in self.slot_req if r is not None)

    # ------------------------------------------------- rejection + shedding
    def _reject(self, req: Request, reason: str, retryable: bool,
                **extra) -> None:
        """Terminal structured rejection: mark the request, collect it, and
        emit a `serve_shed` event. Never touches slot state — callers free
        any slot the request held BEFORE rejecting."""
        req.status = "shed" if retryable else "failed"
        req.finish_reason = reason
        req.retryable = retryable
        req.done_t = self.now()
        req.slot = None
        self.shed.append(req)
        T.emit(
            "serve_shed", id=req.rid, reason=reason,
            retryable=int(retryable), prompt_len=req.prompt_len,
            output_len=len(req.output) or None,
            waited_ms=max(0.0, (self.now() - req.arrival_s) * 1000.0),
            **extra,
        )

    @staticmethod
    def _median(xs) -> float:
        if not xs:
            return 0.0
        s = sorted(xs)
        return float(s[len(s) // 2])

    def predicted_ttft_ms(self, req: Request, queue_pos: int) -> float:
        """Cheap TTFT forecast: time already waited + one prefill for this
        request + (queue depth ahead) × (median prefill + median tick) —
        every request ahead costs its own prefill and roughly one decode
        tick before a slot frees."""
        waited = max(0.0, (self.now() - req.arrival_s) * 1000.0)
        mp = self._median(self._prefill_ms)
        mt = self._median(self._tick_ms)
        return waited + mp + queue_pos * (mp + mt)

    def _shed_scan(self, pending: deque) -> None:
        """Drop pending requests that cannot be served: blown per-request
        deadlines, predicted-TTFT overload, and pending-queue overflow.
        Rebuilds the deque preserving FIFO order of the survivors."""
        if not pending:
            return
        now = self.now()
        learned = (len(self._prefill_ms) >= self.min_shed_samples
                   and len(self._tick_ms) >= self.min_shed_samples)
        keep: List[Request] = []
        arrived_kept = 0
        for req in pending:
            if req.arrival_s > now:
                keep.append(req)
                continue
            deadline = req.deadline_s
            if deadline is None and self.request_timeout_s > 0:
                deadline = req.arrival_s + self.request_timeout_s
            if deadline is not None and now > deadline:
                self._reject(req, "deadline", retryable=True)
                continue
            if self.p99_ttft_ms > 0 and learned:
                pred = self.predicted_ttft_ms(req, arrived_kept)
                if pred > self.p99_ttft_ms:
                    self._reject(req, "predicted_ttft", retryable=True,
                                 predicted_ttft_ms=pred,
                                 queue_depth=arrived_kept)
                    continue
            if self.max_pending > 0 and arrived_kept >= self.max_pending:
                self._reject(req, "queue_full", retryable=True,
                             queue_depth=arrived_kept)
                continue
            arrived_kept += 1
            keep.append(req)
        if len(keep) != len(pending):
            pending.clear()
            pending.extend(keep)

    def _admit(self, pending: deque) -> None:
        while pending:
            req = pending[0]
            if req.arrival_s > self.now():
                break
            slot = self._free_slot()
            if slot is None:
                break
            pending.popleft()
            if not request_fits(self.kv_cfg, req.prompt_len, req.max_new_tokens):
                # structured per-request refusal: the slot was never
                # occupied, the loop continues with the next arrival
                self._reject(req, "oversize", retryable=False)
                continue
            req.slot = slot
            req.prefill_start_t = self.now()
            if self.watchdog is not None:
                self.watchdog.arm(self.decode_steps, phase="prefill",
                                  inflight=self.occupancy())
            try:
                tok, _ = self.engine.prefill(req.prompt, slot)
            except Exception as e:
                # slot never assigned (slot_req[slot] still None): contain
                # the failure to this request and keep serving
                if self.watchdog is not None:
                    self.watchdog.progress()
                self._reject(req, "prefill_error", retryable=True,
                             error=repr(e)[:200])
                continue
            prefill_ms = (self.now() - req.prefill_start_t) * 1000.0
            self._prefill_ms.append(prefill_ms)
            if self.watchdog is not None:
                self.watchdog.observe_step_time(prefill_ms)
                self.watchdog.progress()
            req.first_token_t = self.now()
            req.output.append(tok)
            self.slot_req[slot] = req
            self.slot_len[slot] = req.prompt_len
            self.slot_tok[slot] = tok
            self._maybe_finish(slot)

    def _maybe_finish(self, slot: int) -> None:
        req = self.slot_req[slot]
        if req is not None and len(req.output) >= req.max_new_tokens:
            req.done_t = self.now()
            req.status = "completed"
            req.finish_reason = "completed"
            self.completed.append(req)
            self.slot_req[slot] = None
            T.emit(
                "serve_request", id=req.rid, arrival_t=req.arrival_s,
                prefill_start_t=req.prefill_start_t,
                first_token_t=req.first_token_t, done_t=req.done_t,
                prompt_len=req.prompt_len, output_len=len(req.output),
                ttft_ms=req.ttft_ms(), tpot_ms=req.tpot_ms(),
            )

    def decode_pages(self) -> int:
        """Smallest bucket whose context covers every active slot's write
        position (= its current length)."""
        active_lens = [int(self.slot_len[i]) for i, r in enumerate(self.slot_req) if r is not None]
        return bucket_pages(max(active_lens), self.kv_cfg.page_size, self.kv_cfg.max_pages)

    def _abandon_active(self, reason: str) -> int:
        """Free every occupied slot, rejecting its request as retryable —
        the containment path for engine-wide decode failures and hard
        drains. Returns how many were abandoned."""
        n = 0
        for slot, req in enumerate(self.slot_req):
            if req is None:
                continue
            self.slot_req[slot] = None
            self.slot_len[slot] = 0
            self.slot_tok[slot] = 0
            self._reject(req, reason, retryable=True)
            n += 1
        return n

    def _decode_tick(self) -> None:
        active = np.array([r is not None for r in self.slot_req], bool)
        pages = self.decode_pages()
        t_start = self.now()
        if self.watchdog is not None:
            self.watchdog.arm(self.decode_steps, phase="decode",
                              inflight=int(active.sum()))
        try:
            next_tok, _ = self.engine.decode_step(self.slot_tok, active, pages)
        except Exception:
            # an engine-wide failure, not a per-request one: free every
            # slot (no leak), park the requests as retryable, and let the
            # driver decide (migrate / exit) on the re-raised error
            if self.watchdog is not None:
                self.watchdog.progress()
            self._abandon_active("decode_error")
            raise
        step_ms = (self.now() - t_start) * 1000.0
        self._tick_ms.append(step_ms)
        if self.watchdog is not None:
            self.watchdog.observe_step_time(step_ms)
            self.watchdog.progress()
        self.decode_steps += 1
        n_active = int(active.sum())
        tokens = 0
        for slot, req in enumerate(self.slot_req):
            if req is None:
                continue
            tok = int(next_tok[slot])
            req.output.append(tok)
            self.slot_tok[slot] = tok
            self.slot_len[slot] += 1
            tokens += 1
            self._maybe_finish(slot)
        T.emit(
            "decode_batch", step=self.decode_steps,
            occupancy=n_active / self.kv_cfg.max_slots,
            slots=self.kv_cfg.max_slots, step_ms=step_ms, bucket_pages=pages,
            tokens=tokens,
        )

    # --------------------------------------------------------------- drain
    def drain(self, reason: str, pending: Optional[deque] = None,
              finish_active: bool = True) -> Dict[str, int]:
        """Graceful wind-down: stop admitting (every pending request sheds
        retryable), complete in-flight decodes where possible (bounded by
        the tokens they still owe), mark anything left retryable, and emit
        one `serve_drain` event. Idempotent per run()."""
        if self.watchdog is not None:
            self.watchdog.disarm()
        pending_shed = 0
        if pending:
            while pending:
                self._reject(pending.popleft(), "drain", retryable=True)
                pending_shed += 1
        active_before = self.occupancy()
        completed_before = len(self.completed)
        if finish_active and active_before:
            budget = sum(
                r.max_new_tokens - len(r.output)
                for r in self.slot_req if r is not None
            ) + active_before
            try:
                while self.occupancy() and budget > 0:
                    self._decode_tick()
                    budget -= 1
            except Exception:
                pass  # _decode_tick already freed slots + parked retryable
        active_shed = self._abandon_active("drain")
        self.drain_reason = reason
        T.emit(
            "serve_drain", reason=reason,
            completed=len(self.completed),
            active_completed=len(self.completed) - completed_before,
            active_shed=active_shed, pending_shed=pending_shed,
            shed=len(self.shed),
        )
        return {
            "reason": reason, "pending_shed": pending_shed,
            "active_shed": active_shed,
            "active_completed": len(self.completed) - completed_before,
        }

    # ----------------------------------------------------------- migration
    def migrate_to(self, engine, kv_cfg: Optional[KVCacheConfig] = None) -> Dict[str, int]:
        """Swap in a new engine (typically rebuilt on a degraded mesh with a
        re-searched strategy) and re-prefill every in-flight request from
        its token journal into the new KV cache.

        Replay math: after k sampled tokens the old cache holds the K/V of
        ``prompt + output[:-1]`` (the last sampled token has not been
        embedded yet — it is the pending `slot_tok`). Greedy prefill of that
        prefix therefore reproduces the exact cache state AND re-samples
        ``output[-1]``; the re-sampled token is discarded and `slot_tok` is
        restored, so the greedy continuation is identical to an
        uninterrupted run. Requests that no longer fit the new cache
        geometry shed retryable instead of raising."""
        if self.watchdog is not None:
            self.watchdog.disarm()
        old_slots = [(r, int(self.slot_len[i]), int(self.slot_tok[i]))
                     for i, r in enumerate(self.slot_req) if r is not None]
        self.engine = engine
        if kv_cfg is not None:
            self.kv_cfg = kv_cfg
        self.slot_req = [None] * self.kv_cfg.max_slots
        self.slot_len = np.zeros((self.kv_cfg.max_slots,), np.int64)
        self.slot_tok = np.zeros((self.kv_cfg.max_slots,), np.int32)
        replayed = shed = 0
        for req, _, last_tok in old_slots:
            replay = req.journal[:-1]
            slot = self._free_slot()
            remaining = req.max_new_tokens - len(req.output) + 1
            if slot is None or not request_fits(self.kv_cfg, len(replay), remaining):
                self._reject(req, "migrate_infeasible", retryable=True)
                shed += 1
                continue
            try:
                self.engine.prefill(replay, slot)  # re-sampled token == last_tok (greedy); discarded
            except Exception as e:
                self._reject(req, "migrate_prefill_error", retryable=True,
                             error=repr(e)[:200])
                shed += 1
                continue
            req.slot = slot
            self.slot_req[slot] = req
            self.slot_len[slot] = len(replay)
            self.slot_tok[slot] = last_tok
            replayed += 1
        self.migrations += 1
        return {"replayed": replayed, "shed": shed}

    def run(self, requests: Sequence[Request]) -> List[Request]:
        """Drive the load to completion; returns the completed requests in
        completion order. Shed/failed requests land in ``self.shed``."""
        pending = deque(sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        self.now()  # start the clock
        try:
            while pending or any(r is not None for r in self.slot_req):
                if self.control is not None:
                    verdict = self.control(self)
                    if verdict:
                        self.drain(str(verdict), pending)
                        break
                self._shed_scan(pending)
                self._admit(pending)
                if any(r is not None for r in self.slot_req):
                    self._decode_tick()
                elif pending:
                    # idle: wait out the arrival gap (real clock) / spin (fake)
                    gap = pending[0].arrival_s - self.now()
                    if gap > 0 and self._clock is time.monotonic:
                        time.sleep(min(gap, 0.05))
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()
        return self.completed


# -------------------------------------------------------------------- report
def percentile(values: Sequence[float], q: float) -> float:
    if not values:
        return float("nan")
    xs = sorted(values)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def summarize(
    completed: Sequence[Request], wall_s: float, world_size: int = 1,
    shed: Sequence[Request] = (),
) -> Dict[str, Any]:
    """TTFT/TPOT percentiles + throughput for a finished load, plus the shed
    ledger (count, retryable count, per-reason breakdown) when given."""
    ttfts = [r.ttft_ms() for r in completed if r.ttft_ms() is not None]
    tpots = [r.tpot_ms() for r in completed if r.tpot_ms() is not None]
    out_tokens = sum(len(r.output) for r in completed)
    by_reason: Dict[str, int] = {}
    for r in shed:
        by_reason[r.finish_reason or "unknown"] = by_reason.get(r.finish_reason or "unknown", 0) + 1
    return {
        "shed": len(shed),
        "shed_retryable": sum(1 for r in shed if r.retryable),
        "shed_by_reason": by_reason,
        "requests": len(completed),
        "output_tokens": out_tokens,
        "wall_s": wall_s,
        "tokens_per_s": out_tokens / wall_s if wall_s > 0 else float("nan"),
        "tokens_per_s_per_chip": (
            out_tokens / wall_s / world_size if wall_s > 0 else float("nan")
        ),
        "ttft_ms": {
            "p50": percentile(ttfts, 50), "p90": percentile(ttfts, 90),
            "p99": percentile(ttfts, 99),
        },
        "tpot_ms": {
            "p50": percentile(tpots, 50), "p90": percentile(tpots, 90),
            "p99": percentile(tpots, 99),
        },
    }
