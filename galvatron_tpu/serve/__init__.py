"""Serving subsystem: searched-strategy inference (ROADMAP item 4).

The training side of this repo searches per-layer hybrid strategies and
executes them via GSPMD; serving reuses the same strategy JSONs, the same
model functions, and the same relayout machinery, with the objective flipped
from MFU to tokens/s/chip under a latency bound:

- kv_cache.py: preallocated slot-based KV cache whose per-layer sharding is
  derived from that layer's searched strategy.
- engine.py: prefill/decode split, bucketed AOT executables, continuous
  batching, greedy/temperature sampling.

Driver: ``python -m galvatron_tpu.cli serve`` (cli/serve.py); search-side
objective: ``search --objective serve`` (search/engine.py).
"""

from galvatron_tpu.serve.kv_cache import (  # noqa: F401
    KVCacheConfig,
    bucket_pages,
    init_kv_cache,
    kv_bytes_per_slot,
    kv_cache_specs,
    layer_kv_spec,
    length_bias,
    request_fits,
)
